"""lena-simple-epc: LTE radio + EPC core + remote host traffic.

The full BASELINE config #4 shape; upstream analog:
src/lte/examples/lena-simple-epc.cc — a remote host behind a
point-to-point backhaul to the PGW sends downlink UDP to every UE, and
every UE sends uplink UDP back, all through the EPC bearers.

Run: python examples/lena-simple-epc.py --nEnbs=2 --uesPerCell=3 --simTime=0.5

With --speed > 0 the UEs drive toward the last cell and hand over
mid-run (A3-RSRP + X2-lite):

    python examples/lena-simple-epc.py --nEnbs=2 --uesPerCell=2 \
        --simTime=2 --speed=50 --rlcMode=am
"""

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.helper.applications import UdpClientHelper, UdpServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.internet.ipv4 import Ipv4L3Protocol, Ipv4StaticRouting
from tpudes.models.lte import LteHelper
from tpudes.models.lte.epc import EpcHelper
from tpudes.models.mobility import (
    ConstantVelocityMobilityModel,
    ListPositionAllocator,
    MobilityHelper,
    Vector,
)
from tpudes.network.address import Ipv4Address, Ipv4Mask


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nEnbs", "eNBs on a line", 2)
    cmd.AddValue("uesPerCell", "UEs per cell", 3)
    cmd.AddValue("simTime", "simulated seconds", 0.5)
    cmd.AddValue("interSite", "inter-site distance (m)", 500.0)
    cmd.AddValue("speed", "UE speed toward the last cell (m/s)", 0.0)
    cmd.AddValue("rlcMode", "um | am", "um")
    cmd.AddValue("s1uDelay", "S1-U link one-way delay", "0ms")
    cmd.AddValue("s1uRate", "S1-U link capacity", "1Gbps")
    cmd.Parse(argv)
    n_enbs = int(cmd.nEnbs)
    per_cell = int(cmd.uesPerCell)
    sim_time = float(cmd.simTime)
    speed = float(cmd.speed)

    lte = LteHelper()
    epc = EpcHelper(s1u_rate=str(cmd.s1uRate), s1u_delay=str(cmd.s1uDelay))

    # remote host behind a 100 Gbps / 10 ms backhaul to the PGW
    remote = NodeContainer()
    remote.Create(1)
    InternetStackHelper().Install(remote)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "100Gbps")
    p2p.SetChannelAttribute("Delay", "10ms")
    backhaul = p2p.Install(remote.Get(0), epc.GetPgwNode())
    addr = Ipv4AddressHelper("1.0.0.0", "255.0.0.0")
    internet_ifc = addr.Assign(backhaul)
    # route the UE network through the PGW
    remote_routing = remote.Get(0).GetObject(Ipv4L3Protocol).GetRoutingProtocol()
    assert isinstance(remote_routing, Ipv4StaticRouting)
    remote_routing.AddNetworkRouteTo(
        Ipv4Address(EpcHelper.UE_NETWORK), Ipv4Mask(EpcHelper.UE_MASK),
        remote.Get(0).GetObject(Ipv4L3Protocol).GetInterfaceForDevice(
            backhaul.Get(0)
        ),
        gateway=internet_ifc.GetAddress(1),
    )

    enb_nodes = NodeContainer()
    enb_nodes.Create(n_enbs)
    ue_nodes = NodeContainer()
    ue_nodes.Create(n_enbs * per_cell)
    ea = ListPositionAllocator()
    for i in range(n_enbs):
        ea.Add(Vector(i * float(cmd.interSite), 0.0, 30.0))
    me = MobilityHelper()
    me.SetPositionAllocator(ea)
    me.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    me.Install(enb_nodes)
    ua = ListPositionAllocator()
    for c in range(n_enbs):
        for k in range(per_cell):
            a = 2 * math.pi * k / max(per_cell, 1)
            ua.Add(Vector(
                c * float(cmd.interSite) + 80.0 * math.cos(a),
                80.0 * math.sin(a), 1.5,
            ))
    mu = MobilityHelper()
    mu.SetPositionAllocator(ua)
    mu.SetMobilityModel(
        "tpudes::ConstantVelocityMobilityModel"
        if speed > 0
        else "tpudes::ConstantPositionMobilityModel"
    )
    mu.Install(ue_nodes)
    if speed > 0:
        for i in range(ue_nodes.GetN()):
            ue_nodes.Get(i).GetObject(ConstantVelocityMobilityModel).SetVelocity(
                Vector(speed, 0.0, 0.0)
            )
        lte.SetHandoverAlgorithmType("tpudes::A3RsrpHandoverAlgorithm")
        lte.SetHandoverAlgorithmAttribute("TimeToTrigger", 160)
        lte.AddX2Interface(enb_nodes)

    lte.InstallEnbDevice(enb_nodes)
    ue_devs = lte.InstallUeDevice(ue_nodes)
    InternetStackHelper().Install(ue_nodes)
    ue_list = [ue_devs.Get(i) for i in range(ue_devs.GetN())]
    lte.Attach(ue_list)
    lte.ActivateDataRadioBearer(ue_list, mode=str(cmd.rlcMode))
    ue_addrs = epc.AssignUeIpv4Address(ue_list)
    epc.wire_enbs([lte.controller.enbs[i] for i in range(n_enbs)])

    # downlink: remote host → each UE; uplink: each UE → remote host
    dl_rx = [0] * len(ue_list)
    dl_delay = []  # per-packet one-way DL delay

    class _TsTag:  # send timestamp rides the packet (loss-proof pairing)
        def __init__(self, t):
            self.t = t
    ul_server = UdpServerHelper(2000)
    ul_apps = ul_server.Install(remote.Get(0))
    ul_apps.Start(Seconds(0.0))
    for i, ue_addr in enumerate(ue_addrs):
        server = UdpServerHelper(1000 + i)
        sapps = server.Install(ue_nodes.Get(i))
        sapps.Start(Seconds(0.0))
        def on_dl(pkt, *a, i=i):
            dl_rx[i] += 1
            tag = pkt.PeekPacketTag(_TsTag)
            if tag is not None:
                dl_delay.append(Simulator.Now().GetSeconds() - tag.t)

        sapps.Get(0).TraceConnectWithoutContext("Rx", on_dl)
        dl = UdpClientHelper(ue_addr, 1000 + i)
        dl.SetAttribute("MaxPackets", 0)
        dl.SetAttribute("Interval", Seconds(0.02))
        dl.SetAttribute("PacketSize", 400)
        dapps = dl.Install(remote.Get(0))
        dapps.Get(0).TraceConnectWithoutContext(
            "Tx",
            lambda p: p.AddPacketTag(_TsTag(Simulator.Now().GetSeconds())),
        )
        dapps.Start(Seconds(0.05))
        dapps.Stop(Seconds(sim_time))
        ul = UdpClientHelper(internet_ifc.GetAddress(0), 2000)
        ul.SetAttribute("MaxPackets", 0)
        ul.SetAttribute("Interval", Seconds(0.04))
        ul.SetAttribute("PacketSize", 200)
        uapps = ul.Install(ue_nodes.Get(i))
        uapps.Start(Seconds(0.06))
        uapps.Stop(Seconds(sim_time))

    wall0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - wall0

    ul_rx = ul_apps.Get(0).received
    c = lte.controller
    print(
        f"enbs={n_enbs} ues={len(ue_list)} rlc={cmd.rlcMode} "
        f"dl_rx={sum(dl_rx)} (per-UE min={min(dl_rx)}) ul_rx={ul_rx} "
        f"handovers={c.stats['handovers']} "
        f"ttis={c.stats['ttis']} "
        f"dl_delay_mean={sum(dl_delay) / max(len(dl_delay), 1) * 1e3:.2f}ms "
        f"wall={wall:.1f}s"
    )
    if c.handover_log:
        for tti, imsi, src, dst in c.handover_log:
            print(f"  t={tti / 1000.0:.3f}s imsi={imsi} cell {src} -> {dst}")
    ok = sum(dl_rx) > 0 and ul_rx > 0 and min(dl_rx) > 0
    Simulator.Destroy()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
