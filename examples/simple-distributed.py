"""simple-distributed: space-parallel PDES over local ranks.

Upstream analog: src/mpi/examples/simple-distributed.cc — a topology
partitioned by node ``systemId``, run under DistributedSimulatorImpl
with cross-partition links as remote channels.  Where upstream launches
via ``mpirun -np 2``, this build's transport is N local processes
joined by pipes (tpudes/parallel/mpi.py — the MpiInterface seam an
actual MPI backend would plug into).

Run:  python examples/simple-distributed.py --ranks=2 --nPairs=8

Each rank owns one side of ``nPairs`` echo client/server pairs that
talk across the partition boundary; the script prints each rank's
event count and granted windows, then cross-checks delivery against
the sequential engine.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rank_main(rank: int, size: int, n_pairs: int, sim_time: float,
              engine: str = "tpudes::DistributedSimulatorImpl"):
    from tpudes.core import Seconds, Simulator
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.world import reset_world
    from tpudes.helper.applications import (
        UdpEchoClientHelper,
        UdpEchoServerHelper,
    )
    from tpudes.helper.containers import NodeContainer
    from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
    from tpudes.helper.point_to_point import PointToPointHelper
    from tpudes.parallel.mpi import MpiInterface

    reset_world()
    distributed = MpiInterface.IsEnabled() and size > 1
    if distributed:
        GlobalValue.Bind("SimulatorImplementationType", engine)
    me = MpiInterface.GetSystemId() if distributed else 0

    left = NodeContainer()
    left.Create(n_pairs, system_id=0)
    right = NodeContainer()
    right.Create(n_pairs, system_id=1 if distributed else 0)

    stack = InternetStackHelper()
    stack.Install(left)
    stack.Install(right)
    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "10Mbps")
    p2p.SetChannelAttribute("Delay", "3ms")
    addr = Ipv4AddressHelper("10.7.0.0", "255.255.255.0")

    rx_total = [0]
    for i in range(n_pairs):
        devs = p2p.Install(left.Get(i), right.Get(i))
        ifc = addr.Assign(devs)
        addr.NewNetwork()
        if right.Get(i).GetSystemId() == me or not distributed:
            server = UdpEchoServerHelper(9)
            sapps = server.Install(right.Get(i))
            sapps.Start(Seconds(0.0))
            sapps.Get(0).TraceConnectWithoutContext(
                "Rx", lambda *a: rx_total.__setitem__(0, rx_total[0] + 1)
            )
        if left.Get(i).GetSystemId() == me or not distributed:
            client = UdpEchoClientHelper(ifc.GetAddress(1), 9)
            client.SetAttribute("MaxPackets", 10)
            client.SetAttribute("Interval", Seconds(0.05))
            client.SetAttribute("PacketSize", 256)
            client.Install(left.Get(i)).Start(Seconds(0.1 + 0.003 * i))

    t0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - t0
    out = dict(
        rank=me,
        events=Simulator.GetEventCount(),
        windows=getattr(Simulator.GetImpl(), "windows_run", 0),
        nulls=getattr(Simulator.GetImpl(), "null_messages_sent", 0),
        server_rx=rx_total[0],
        wall=wall,
    )
    Simulator.Destroy()
    return out


def main(argv=None):
    from tpudes.core import CommandLine
    from tpudes.parallel.mpi import LaunchDistributed

    cmd = CommandLine()
    cmd.AddValue("ranks", "number of local ranks (processes)", 2)
    cmd.AddValue("nPairs", "echo pairs across the boundary", 8)
    cmd.AddValue("simTime", "simulated seconds", 1.0)
    cmd.AddValue("nullMessage", "use the CMB null-message engine", False)
    cmd.Parse(argv)
    ranks, n_pairs, sim_time = int(cmd.ranks), int(cmd.nPairs), float(cmd.simTime)
    engine = (
        "tpudes::NullMessageSimulatorImpl"
        if cmd.GetValue("nullMessage")
        else "tpudes::DistributedSimulatorImpl"
    )

    seq = rank_main(0, 1, n_pairs, sim_time)
    print(
        f"sequential: events={seq['events']} server_rx={seq['server_rx']} "
        f"wall={seq['wall']:.2f}s"
    )
    results = LaunchDistributed(
        rank_main, ranks, args=(n_pairs, sim_time, engine)
    )
    dist_rx = sum(r["server_rx"] for r in results)
    for r in results:
        meter = (
            f"nulls={r['nulls']}" if cmd.GetValue("nullMessage")
            else f"windows={r['windows']}"
        )
        print(
            f"rank {r['rank']}: events={r['events']} {meter} "
            f"server_rx={r['server_rx']} wall={r['wall']:.2f}s"
        )
    ok = dist_rx == seq["server_rx"]
    print(f"delivery parity: {dist_rx} == {seq['server_rx']} -> {ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
