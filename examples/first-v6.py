"""first.cc, IPv6 edition: two nodes, a point-to-point link, one UDP
echo exchange over 2001:db8::/64 (upstream examples/tutorial/first.cc
with Ipv6AddressHelper, the ns-3 dual-stack idiom).

Run: python examples/first-v6.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv6AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nPackets", "echo packets", 1)
    cmd.Parse(argv)

    nodes = NodeContainer()
    nodes.Create(2)

    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)

    stack = InternetStackHelper()
    stack.Install(nodes)

    address = Ipv6AddressHelper()
    address.SetBase("2001:db8::", 64)
    interfaces = address.Assign(devices)

    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(1))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))

    client = UdpEchoClientHelper(interfaces.GetAddress(1, 1), 9)
    client.SetAttribute("MaxPackets", int(cmd.nPackets))
    client.SetAttribute("Interval", Seconds(1.0))
    client.SetAttribute("PacketSize", 1024)
    client_apps = client.Install(nodes.Get(0))
    client_apps.Start(Seconds(2.0))
    client_apps.Stop(Seconds(10.0))

    cl, srv = client_apps.Get(0), server_apps.Get(0)
    cl.TraceConnectWithoutContext(
        "Tx", lambda p: print(f"At time {Simulator.Now().GetSeconds()}s client sent {p.GetSize()} bytes to {interfaces.GetAddress(1, 1)} port 9")
    )
    srv.TraceConnectWithoutContext(
        "RxWithAddresses", lambda p, f, l: print(
            f"At time {Simulator.Now().GetSeconds()}s server received {p.GetSize()} bytes from {f}"
        )
    )
    cl.TraceConnectWithoutContext(
        "Rx", lambda p: print(f"At time {Simulator.Now().GetSeconds()}s client received {p.GetSize()} bytes from {interfaces.GetAddress(1, 1)} port 9")
    )

    Simulator.Run()
    Simulator.Destroy()
    ok = cl.received >= int(cmd.nPackets)
    print(f"client echoes received: {cl.received}/{cl.sent}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
