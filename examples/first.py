"""first.py — the minimum end-to-end slice (BASELINE config #1).

Reference parity: examples/tutorial/first.cc — two nodes on a 5 Mbps /
2 ms point-to-point link; a UDP echo client sends one 1024-byte packet to
an echo server which reflects it back.

Run:  python examples/first.py [--packets=N] [--RngRun=R]
      [--SimulatorImplementationType=tpudes::JaxSimulatorImpl]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator, Time
from tpudes.helper import (
    InternetStackHelper,
    Ipv4AddressHelper,
    NodeContainer,
    PointToPointHelper,
    UdpEchoClientHelper,
    UdpEchoServerHelper,
)


def main(argv=None):
    cmd = CommandLine("first.py: 2-node point-to-point UDP echo")
    cmd.AddValue("packets", "number of echo packets", 1)
    cmd.AddValue("pcap", "write first-<node>-<dev>.pcap traces", True)
    cmd.AddValue("ascii", "write first.tr ascii trace", False)
    cmd.Parse(argv)

    Time.SetResolution(Time.NS)

    nodes = NodeContainer()
    nodes.Create(2)

    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    devices = p2p.Install(nodes)

    stack = InternetStackHelper()
    stack.Install(nodes)

    address = Ipv4AddressHelper()
    address.SetBase("10.1.1.0", "255.255.255.0")
    interfaces = address.Assign(devices)

    if cmd.GetValue("pcap"):
        p2p.EnablePcapAll("first")
    if cmd.GetValue("ascii"):
        p2p.EnableAsciiAll("first.tr")

    echo_server = UdpEchoServerHelper(9)
    server_apps = echo_server.Install(nodes.Get(1))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))

    echo_client = UdpEchoClientHelper(interfaces.GetAddress(1), 9)
    echo_client.SetAttribute("MaxPackets", cmd.GetValue("packets"))
    echo_client.SetAttribute("Interval", Seconds(1.0))
    echo_client.SetAttribute("PacketSize", 1024)
    client_apps = echo_client.Install(nodes.Get(0))
    client_apps.Start(Seconds(2.0))
    client_apps.Stop(Seconds(10.0))

    client = client_apps.Get(0)
    server = server_apps.Get(0)
    client.TraceConnectWithoutContext(
        "Tx",
        lambda p: print(f"At time {Simulator.Now().GetSeconds():g}s client sent {p.GetSize()} bytes to {interfaces.GetAddress(1)} port 9"),
    )
    server.TraceConnectWithoutContext(
        "RxWithAddresses",
        lambda p, src, local: print(
            f"At time {Simulator.Now().GetSeconds():g}s server received {p.GetSize()} bytes from {src.GetIpv4()} port {src.GetPort()}"
        ),
    )
    client.TraceConnectWithoutContext(
        "Rx",
        lambda p: print(f"At time {Simulator.Now().GetSeconds():g}s client received {p.GetSize()} bytes from {interfaces.GetAddress(1)} port 9"),
    )

    Simulator.Run()
    ok = client.sent == cmd.GetValue("packets") and server.received == client.sent and client.received == client.sent
    print(f"sent={client.sent} server_rx={server.received} client_rx={client.received} -> {'OK' if ok else 'MISMATCH'}")
    Simulator.Destroy()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
