"""tcp-variants: N TCP bulk flows across a dumbbell bottleneck.

The TCP workload shape from BASELINE.json config #2; upstream analog:
examples/tcp/tcp-variants-comparison.cc over the
point-to-point-layout dumbbell.

Run (scalar DES, one variant):
    python examples/tcp-variants.py --nFlows=4 --variant=TcpCubic --simTime=5

Sweep all seventeen variants sequentially:
    python examples/tcp-variants.py --nFlows=4 --variant=all --simTime=5

The TPU engine is one GlobalValue flip away — 256 Monte-Carlo replicas
of the whole dumbbell at once, per variant:

    python examples/tcp-variants.py --nFlows=8 --variant=all --simTime=10 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=256

JaxSimulatorImpl lowers the SAME constructed object graph to the
packet-slot program (tpudes/parallel/tcp_dumbbell.py): every slot of
the bottleneck, every flow's cwnd evolution, and all drops/recoveries
run as one lax.scan on the accelerator, vmapped over replicas.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.core.global_value import GlobalValue
from tpudes.core.world import reset_world
from tpudes.models.internet.tcp_congestion import TCP_VARIANTS
from tpudes.scenarios import build_dumbbell


def jain(xs):
    s = sum(xs)
    q = sum(x * x for x in xs)
    return (s * s) / (len(xs) * q) if q else 1.0


def run_one(variant, n_flows, sim_time, bottleneck_rate, queue, engine):
    reset_world()  # one world per variant; restore the engine choice
    for name, value in engine.items():
        GlobalValue.Bind(name, value)
    db, sinks = build_dumbbell(
        n_flows, sim_time, variant=variant,
        bottleneck_rate=bottleneck_rate, queue=queue,
    )
    from tpudes.models.flow_monitor import FlowMonitorHelper

    fmh = FlowMonitorHelper()
    monitor = fmh.InstallAll()
    wall0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - wall0

    res = getattr(Simulator.GetImpl(), "replicated_result", None)
    if res is not None:
        import numpy as np

        out = res["out"]
        g = np.asarray(out["goodput_mbps"])          # (R, F)
        agg = g.sum(axis=1)
        fair = [jain(list(row)) for row in g]
        print(
            f"{variant:14s} replicas={res['replicas']} "
            f"agg={agg.mean():.2f}±{agg.std():.2f} Mbps "
            f"jain={float(np.mean(fair)):.3f} "
            f"drops={float(np.asarray(out['drops']).sum(1).mean()):.0f} "
            f"queue={float(np.asarray(out['mean_queue']).mean()):.1f}p "
            f"wall={wall:.2f}s "
            f"sim-s/wall-s={res['replicas'] * sim_time / wall:,.0f}"
        )
        ok = agg.mean() > 0
    else:
        tput = [
            s.GetTotalRx() * 8.0 / max(sim_time - 0.1, 1e-9) / 1e6
            for s in sinks
        ]
        monitor.CheckForLostPackets()
        # data flows only: sink ports are 5000..5000+n (the reverse ACK
        # flows land on ephemeral destination ports >= 49152)
        fwd = [
            s for fid, s in monitor.GetFlowStats().items()
            if 5000
            <= fmh.GetClassifier().FindFlow(fid).destination_port
            < 5000 + n_flows
        ]
        print(
            f"{variant:14s} goodput/flow "
            f"[{', '.join(f'{t:.2f}' for t in tput)}] Mbps "
            f"agg={sum(tput):.2f} jain={jain(tput):.3f} "
            f"lost={sum(s.lost_packets for s in fwd)} "
            f"mean_delay={sum(s.mean_delay_s for s in fwd) / max(len(fwd), 1) * 1e3:.1f}ms "
            f"events={Simulator.GetEventCount()} wall={wall:.2f}s"
        )
        ok = sum(tput) > 0
    Simulator.Destroy()
    return ok


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nFlows", "flows per side", 4)
    cmd.AddValue("variant", "TcpX | all", "TcpNewReno")
    cmd.AddValue("simTime", "simulated seconds", 5.0)
    cmd.AddValue("bottleneckRate", "bottleneck data rate", "10Mbps")
    cmd.AddValue("queue", "bottleneck queue (packets)", "100p")
    cmd.Parse(argv)

    variants = (
        list(TCP_VARIANTS) if cmd.variant == "all" else [str(cmd.variant)]
    )
    engine = {
        name: GlobalValue.GetValue(name)
        for name in ("SimulatorImplementationType", "JaxReplicas", "RngRun")
    }
    ok = True
    for v in variants:
        ok = run_one(
            v, int(cmd.nFlows), float(cmd.simTime),
            str(cmd.bottleneckRate), str(cmd.queue), engine,
        ) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
