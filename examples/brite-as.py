"""brite-as: synthetic AS-scale topology with sparse CBR traffic.

The Internet-scale workload shape from BASELINE.json config #5;
upstream analog: examples using BriteTopologyHelper (src/brite) +
Ipv4GlobalRoutingHelper over a 10k-node BRITE AS graph.

Run (scalar DES, small graph):
    python examples/brite-as.py --nNodes=200 --nFlows=16 --simTime=2

Full-scale on the TPU engine — the north-star config, 10k nodes,
1024 Monte-Carlo replicas of the whole traffic study at once:

    python examples/brite-as.py --nNodes=10000 --nFlows=128 --simTime=10 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=1024

JaxSimulatorImpl lowers the constructed graph to the flow-level device
engine (tpudes/parallel/as_flows.py): Bellman–Ford SPF by edge-parallel
scatter-min, bounded-hop path walks, per-replica load accumulation —
all on the accelerator.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.scenarios import build_as_network


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nNodes", "topology size", 200)
    cmd.AddValue("nFlows", "concurrent CBR flows", 16)
    cmd.AddValue("simTime", "simulated seconds", 2.0)
    cmd.AddValue("model", "BA | Waxman", "BA")
    cmd.AddValue("flowKbps", "per-flow offered rate", 400.0)
    cmd.AddValue("progress", "print a ShowProgress line each sim-second", False)
    cmd.Parse(argv)
    n, f, sim_time = int(cmd.nNodes), int(cmd.nFlows), float(cmd.simTime)

    t0 = time.monotonic()
    topo, servers = build_as_network(
        n, f, sim_time, model=str(cmd.model), flow_kbps=float(cmd.flowKbps)
    )
    build_wall = time.monotonic() - t0
    print(
        f"topology: {topo.GetNNodesTopology()} nodes, "
        f"{topo.GetNEdgesTopology()} links, built+routed in {build_wall:.1f}s"
    )

    if cmd.GetValue("progress"):
        from tpudes.core.show_progress import ShowProgress

        ShowProgress(Seconds(1.0))

    wall0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - wall0

    res = getattr(Simulator.GetImpl(), "replicated_result", None)
    if res is not None:
        import numpy as np

        out = res["out"]
        g = np.asarray(out["goodput_bps"]) / 1e3
        print(
            f"replicas={res['replicas']} flows={f} "
            f"goodput/flow={g.mean():.1f}±{g.std():.1f} kbps "
            f"delivered={float(np.asarray(out['delivered_frac']).mean()):.3f} "
            f"mean_delay={float(np.asarray(out['delay_s']).mean() * 1e3):.2f}ms "
            f"max_hops={int(np.asarray(out['hops']).max())} "
            f"unreachable={int(np.asarray(out['unreachable']).sum())} "
            f"wall={wall:.2f}s "
            f"sim-s/wall-s={res['replicas'] * sim_time / wall:,.0f}"
        )
        ok = float(np.asarray(out["delivered_frac"]).mean()) > 0.5
    else:
        rx = [s.received for s in servers]
        print(
            f"flows={f} received={sum(rx)} pkts "
            f"(per-flow min={min(rx)} max={max(rx)}) "
            f"events={Simulator.GetEventCount()} wall={wall:.2f}s"
        )
        ok = sum(rx) > 0
    Simulator.Destroy()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
