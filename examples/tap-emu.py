"""tap-emu: a real kernel socket talks to a simulated host (dnemu).

Upstream analog: src/tap-bridge/examples/tap-csma.cc + the
fd-emu-udp-echo family — the emulation axis the fork's name points at.

Creates a kernel tap interface (needs /dev/net/tun + CAP_NET_ADMIN),
gives the host side 10.6.0.1/24, runs a simulated UDP echo host at
10.6.0.2 behind the tap under RealtimeSimulatorImpl, then sends real
kernel UDP datagrams at it and prints the round-trip times.

Run:  python examples/tap-emu.py [--count=5] [--simTime=3]
"""

import os
import socket
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.core.global_value import GlobalValue
from tpudes.helper.applications import UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper
from tpudes.models.fd_net_device import FdNetDeviceHelper, create_tap
from tpudes.models.internet.ipv4 import (
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
)
from tpudes.network.address import Ipv4Address, Ipv4Mask


def main(argv=None):
    cmd = CommandLine("tap-emu: kernel <-> simulation over a tap")
    cmd.AddValue("count", "datagrams to bounce", 5)
    cmd.AddValue("simTime", "realtime run window (s)", 3.0)
    cmd.Parse(argv)
    count = int(cmd.count)

    try:
        fd, name = create_tap("tpudes-emu0")
        subprocess.run(
            ["ip", "addr", "add", "10.6.0.1/24", "dev", name],
            check=True, capture_output=True,
        )
        subprocess.run(
            ["ip", "link", "set", name, "up"], check=True,
            capture_output=True,
        )
    except (OSError, subprocess.SubprocessError) as e:
        print(f"tap unavailable ({e}); this example needs /dev/net/tun")
        return 77

    GlobalValue.Bind(
        "SimulatorImplementationType", "tpudes::RealtimeSimulatorImpl"
    )
    nodes = NodeContainer()
    nodes.Create(1)
    InternetStackHelper().Install(nodes)
    dev = FdNetDeviceHelper().Install(nodes.Get(0), fd)
    ipv4 = nodes.Get(0).GetObject(Ipv4L3Protocol)
    if_index = ipv4.AddInterface(dev)
    ipv4.AddAddress(
        if_index,
        Ipv4InterfaceAddress(Ipv4Address("10.6.0.2"), Ipv4Mask("255.255.255.0")),
    )
    ipv4.GetRoutingProtocol().AddNetworkRouteTo(
        Ipv4Address("10.6.0.0"), Ipv4Mask("255.255.255.0"), if_index
    )
    dev.Start()
    server = UdpEchoServerHelper(9)
    server.Install(nodes.Get(0)).Start(Seconds(0.0))

    rtts = []

    def world():
        time.sleep(0.2)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.bind(("10.6.0.1", 0))
        s.settimeout(1.0)
        for i in range(count):
            t0 = time.monotonic()
            s.sendto(f"probe-{i}".encode(), ("10.6.0.2", 9))
            try:
                s.recvfrom(4096)
                rtts.append((time.monotonic() - t0) * 1e3)
            except TimeoutError:
                pass
            time.sleep(0.05)
        s.close()

    t = threading.Thread(target=world)
    t.start()
    Simulator.Stop(Seconds(float(cmd.simTime)))
    Simulator.Run()
    t.join(timeout=5)
    dev.Stop()
    os.close(fd)
    ok = len(rtts) == count
    print(
        f"tap={name} echoed {len(rtts)}/{count} kernel datagrams"
        + (f", rtt min/mean {min(rtts):.2f}/{sum(rtts) / len(rtts):.2f} ms"
           if rtts else "")
        + (" -> OK" if ok else " -> MISMATCH")
    )
    Simulator.Destroy()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
