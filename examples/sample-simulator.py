"""Core-runtime demo: event scheduling, random streams, CommandLine.

Reference parity: src/core/examples/sample-simulator.cc — a model object
schedules its own next event off an exponential random variable until the
simulator is stopped.

Run:  python examples/sample-simulator.py [--events=N] [--RngRun=R]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import (
    CommandLine,
    ExponentialRandomVariable,
    MilliSeconds,
    Seconds,
    Simulator,
)


class MyModel:
    def __init__(self, limit):
        self.count = 0
        self.limit = limit
        self.delay = ExponentialRandomVariable(Mean=0.5)

    def start(self):
        Simulator.Schedule(MilliSeconds(10), self.deal_with_event, 42.0)

    def deal_with_event(self, value):
        self.count += 1
        print(f"at {Simulator.Now().GetSeconds():.6f}s: event #{self.count} value={value}")
        if self.count < self.limit:
            Simulator.Schedule(Seconds(self.delay.GetValue()), self.deal_with_event, value)


def random_function(model):
    print(f"at {Simulator.Now().GetSeconds():.6f}s: random function fired")
    model.start()


def cancelled_event():
    print("this event should never run")


def main(argv=None):
    cmd = CommandLine("sample-simulator [--events=N]")
    cmd.AddValue("events", "number of model events to run", 6)
    cmd.Parse(argv)

    model = MyModel(cmd.GetValue("events"))
    Simulator.Schedule(Seconds(10), random_function, model)
    doomed = Simulator.Schedule(Seconds(30), cancelled_event)
    doomed.Cancel()
    Simulator.Stop(Seconds(100))
    Simulator.Run()
    print(f"done at {Simulator.Now().GetSeconds():.6f}s after {Simulator.GetEventCount()} events")
    Simulator.Destroy()


if __name__ == "__main__":
    main()
