"""second.py — p2p + CSMA LAN (the tutorial's second.cc).

Reference parity: examples/tutorial/second.cc — node n0 reaches a
CSMA LAN (n2..n2+nCsma) across a point-to-point link to n1, which
bridges both networks via global routing; UDP echo to the last LAN
host; optional pcap on the bus.

Run:  python examples/second.py [--nCsma=3] [--pcap=1] [--ping=1]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.models.csma import CsmaHelper
from tpudes.models.internet.global_routing import Ipv4GlobalRoutingHelper


def main(argv=None):
    cmd = CommandLine("second.py: p2p + CSMA LAN")
    cmd.AddValue("nCsma", "LAN hosts beyond the router", 3)
    cmd.AddValue("pcap", "write second-*.pcap on the bus", False)
    cmd.AddValue("ping", "also ping the far host", False)
    cmd.Parse(argv)
    n_csma = int(cmd.nCsma)

    p2p_nodes = NodeContainer()
    p2p_nodes.Create(2)
    csma_nodes = NodeContainer()
    csma_nodes.Add(p2p_nodes.Get(1))
    csma_nodes.Create(n_csma)

    p2p = PointToPointHelper()
    p2p.SetDeviceAttribute("DataRate", "5Mbps")
    p2p.SetChannelAttribute("Delay", "2ms")
    p2p_devices = p2p.Install(p2p_nodes)

    csma = CsmaHelper()
    csma.SetChannelAttribute("DataRate", "100Mbps")
    csma.SetChannelAttribute("Delay", Seconds(6.56e-6))
    csma_devices = csma.Install(csma_nodes)

    stack = InternetStackHelper()
    stack.SetRoutingHelper(Ipv4GlobalRoutingHelper())
    stack.Install(p2p_nodes.Get(0))
    stack.Install(csma_nodes)

    address = Ipv4AddressHelper()
    address.SetBase("10.1.1.0", "255.255.255.0")
    address.Assign(p2p_devices)
    address.SetBase("10.1.2.0", "255.255.255.0")
    csma_interfaces = address.Assign(csma_devices)
    Ipv4GlobalRoutingHelper.PopulateRoutingTables()

    echo_server = UdpEchoServerHelper(9)
    server_apps = echo_server.Install(csma_nodes.Get(n_csma))
    server_apps.Start(Seconds(1.0))
    server_apps.Stop(Seconds(10.0))
    rx = [0]
    server_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: rx.__setitem__(0, rx[0] + 1)
    )

    echo_client = UdpEchoClientHelper(csma_interfaces.GetAddress(n_csma), 9)
    echo_client.SetAttribute("MaxPackets", 1)
    echo_client.SetAttribute("Interval", Seconds(1.0))
    echo_client.SetAttribute("PacketSize", 1024)
    client_apps = echo_client.Install(p2p_nodes.Get(0))
    client_apps.Start(Seconds(2.0))
    client_apps.Stop(Seconds(10.0))
    cli_rx = [0]
    client_apps.Get(0).TraceConnectWithoutContext(
        "Rx", lambda *a: cli_rx.__setitem__(0, cli_rx[0] + 1)
    )

    ping = None
    if cmd.GetValue("ping"):
        from tpudes.models.internet.icmp import V4Ping

        ping = V4Ping(
            Remote=str(csma_interfaces.GetAddress(n_csma)),
            Interval=Seconds(1.0), Count=3,
        )
        p2p_nodes.Get(0).AddApplication(ping)
        ping.SetStartTime(Seconds(2.5))

    if cmd.GetValue("pcap"):
        csma.EnablePcap("second", csma_devices.Get(1), promiscuous=True)

    Simulator.Stop(Seconds(10.0))
    Simulator.Run()
    line = f"server_rx={rx[0]} client_rx={cli_rx[0]}"
    if ping is not None:
        line += (
            f" ping {ping.received}/{ping.sent}"
            f" rtt={ping.rtts[0] * 1e3:.2f}ms" if ping.rtts else " ping 0/3"
        )
    ok = rx[0] == 1 and cli_rx[0] == 1 and (
        ping is None or ping.received == ping.sent
    )
    print(line + (" -> OK" if ok else " -> MISMATCH"))
    Simulator.Destroy()
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
