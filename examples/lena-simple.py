"""lena-simple: hex-grid macro cells, full-buffer downlink, RLC SM.

The LTE workload shape from BASELINE.json config #4 (7 eNB × 210 UE hex
grid); upstream analog: src/lte/examples/lena-simple.cc + the lena
throughput studies.  No EPC — RLC saturation mode generates full-buffer
traffic, the classic scheduler-comparison setup.

Run: python examples/lena-simple.py --nEnbs=7 --uesPerCell=30 --simTime=0.5

The TPU engine is one GlobalValue flip away: with

    python examples/lena-simple.py --nEnbs=7 --uesPerCell=30 --simTime=10 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=64

JaxSimulatorImpl lowers the SAME constructed object graph to the
device-resident full-buffer engine (tpudes/parallel/lte_sm.py): the
whole multi-TTI simulation — scheduling, HARQ-IR, decode draws — runs
as one lax.scan on the accelerator, vmapped over Monte-Carlo replicas.
"""

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.helper.containers import NodeContainer
from tpudes.models.lte import LteHelper
from tpudes.models.mobility import ListPositionAllocator, MobilityHelper, Vector


def hex_grid(n: int, spacing: float):
    """First n positions of a hexagonal ring layout (cell 0 centered)."""
    pos = [(0.0, 0.0)]
    ring = 1
    while len(pos) < n:
        for k in range(6 * ring):
            a = 2 * math.pi * k / (6 * ring)
            pos.append((ring * spacing * math.cos(a), ring * spacing * math.sin(a)))
            if len(pos) >= n:
                break
        ring += 1
    return pos[:n]


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nEnbs", "number of eNBs (hex grid)", 7)
    cmd.AddValue("uesPerCell", "UEs dropped per cell", 30)
    cmd.AddValue("simTime", "simulated seconds", 0.5)
    cmd.AddValue("scheduler", "pf | rr | tdmt | fdmt | tta | tdbet | fdbet | cqa | pss", "pf")
    cmd.AddValue("interSite", "inter-site distance (m)", 500.0)
    cmd.AddValue("ffr", "hard frequency reuse-3 (lena-dual-stripe idiom)", False)
    cmd.Parse(argv)
    n_enbs = int(cmd.nEnbs)
    ues_per_cell = int(cmd.uesPerCell)
    sim_time = float(cmd.simTime)

    lte = LteHelper()
    from tpudes.models.lte.scheduler import resolve_scheduler

    lte.SetSchedulerType(resolve_scheduler(str(cmd.scheduler)))
    if cmd.GetValue("ffr"):
        lte.SetFfrAlgorithmType("tpudes::LteFrHardAlgorithm")

    enb_nodes = NodeContainer()
    enb_nodes.Create(n_enbs)
    ue_nodes = NodeContainer()
    ue_nodes.Create(n_enbs * ues_per_cell)

    sites = hex_grid(n_enbs, float(cmd.interSite))
    enb_alloc = ListPositionAllocator()
    for x, y in sites:
        enb_alloc.Add(Vector(x, y, 30.0))
    mob = MobilityHelper()
    mob.SetPositionAllocator(enb_alloc)
    mob.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob.Install(enb_nodes)

    # UEs dropped uniformly in a disc around their site
    import random

    rng = random.Random(7)
    ue_alloc = ListPositionAllocator()
    for c in range(n_enbs):
        cx, cy = sites[c]
        for _ in range(ues_per_cell):
            r = float(cmd.interSite) * 0.45 * math.sqrt(rng.random())
            a = 2 * math.pi * rng.random()
            ue_alloc.Add(Vector(cx + r * math.cos(a), cy + r * math.sin(a), 1.5))
    mob_ue = MobilityHelper()
    mob_ue.SetPositionAllocator(ue_alloc)
    mob_ue.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
    mob_ue.Install(ue_nodes)

    enb_devs = lte.InstallEnbDevice(enb_nodes)
    ue_devs = lte.InstallUeDevice(ue_nodes)
    lte.Attach([ue_devs.Get(i) for i in range(ue_devs.GetN())])  # strongest cell
    lte.ActivateDataRadioBearer([ue_devs.Get(i) for i in range(ue_devs.GetN())])

    wall0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - wall0

    res = getattr(Simulator.GetImpl(), "replicated_result", None)
    if res is not None:
        # JaxSimulatorImpl lifted the graph onto the device SM engine
        import numpy as np

        out = res["out"]
        replicas = res["replicas"]
        agg = out["rx_bits"].sum(axis=-1) / sim_time / 1e6  # (R,) Mbps
        agg = np.atleast_1d(agg)
        print(
            f"replicas={replicas} enbs={n_enbs} ues={ue_nodes.GetN()} "
            f"scheduler={cmd.scheduler} agg_dl mean={agg.mean():.1f}Mbps "
            f"std={agg.std():.2f} min={agg.min():.1f} max={agg.max():.1f} "
            f"tbs={int(np.sum(out['new_tbs']) + np.sum(out['retx']))} "
            f"drops={int(np.sum(out['drops']))} wall_incl_compile={wall:.2f}s "
            f"sim-s/wall-s={replicas * sim_time / wall:,.1f} "
            f"(one-shot incl. jit compile; bench.py reports steady state)"
        )
        Simulator.Destroy()
        return 0 if float(agg.mean()) > 0 else 1

    stats = lte.GetRlcStats()
    total_dl = sum(s["dl_rx_bytes"] for s in stats)
    per_cell = {}
    for s in stats:
        per_cell[s["cell_id"]] = per_cell.get(s["cell_id"], 0) + s["dl_rx_bytes"]
    ctrl = lte.controller
    agg_mbps = total_dl * 8 / sim_time / 1e6
    print(
        f"enbs={n_enbs} ues={ue_nodes.GetN()} scheduler={cmd.scheduler} "
        f"ttis={ctrl.stats['ttis']} dl_tbs={ctrl.stats['dl_tbs']} "
        f"dl_ok={ctrl.stats['dl_ok']} harq_retx={ctrl.stats['dl_harq_retx']} "
        f"drops={ctrl.stats['dl_drops']} agg_dl={agg_mbps:.1f}Mbps "
        f"per_cell_min={min(per_cell.values()) * 8 / sim_time / 1e6:.1f}Mbps "
        f"wall={wall:.2f}s sim-s/wall-s={sim_time / max(wall, 1e-9):.2f}"
    )
    Simulator.Destroy()
    return 0 if ctrl.stats["dl_ok"] > 0 and total_dl > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
