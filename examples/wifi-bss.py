"""Infrastructure BSS: AP + N STAs, UDP echo upstream traffic.

The WiFi workload shape from BASELINE.json config #3 (64-STA YansWifiPhy
BSS); upstream analog: examples/wireless/wifi-simple-infra.cc + the
third.cc tutorial topology.

Run: python examples/wifi-bss.py --nStas=8 --simTime=2

The TPU engine is one GlobalValue flip away (the north-star execution
mode, BASELINE.json: 512 replicas of config #3):

    python examples/wifi-bss.py --nStas=64 --simTime=2 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=512

JaxSimulatorImpl then lowers the SAME constructed object graph onto the
replica axis (tpudes/parallel/lift.py) and runs all replicas on the
accelerator at once; graphs the lowering cannot faithfully represent
fall back to the windowed scalar engine with a warning.

Moving stations lift too (the ISSUE-10 device geometry pipeline):

    python examples/wifi-bss.py --nStas=8 --simTime=2 \
        --mobility=const_velocity --speed=1.0 --JaxGeomStride=8 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=64

And so do realistic workloads (the ISSUE-14 device traffic stage):
``--JaxTrafficModel=onoff`` (or mmpp / trace / cbr) swaps the STA
arrivals onto the traffic subsystem at the echo apps' mean rate —
bursts, modulated rates, or exact trace replay, one executable for
the whole model family:

    python examples/wifi-bss.py --nStas=8 --simTime=2 \
        --JaxTrafficModel=onoff --JaxTrafficSeed=7 \
        --SimulatorImplementationType=tpudes::JaxSimulatorImpl \
        --JaxReplicas=64
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpudes.core import CommandLine, Seconds, Simulator
from tpudes.helper.applications import UdpEchoClientHelper, UdpEchoServerHelper
from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.mobility import MobilityHelper
from tpudes.models.wifi import (
    WifiHelper,
    WifiMacHelper,
    YansWifiChannelHelper,
    YansWifiPhyHelper,
)


def main(argv=None):
    cmd = CommandLine()
    cmd.AddValue("nStas", "number of stations", 8)
    cmd.AddValue("simTime", "simulated seconds", 2.0)
    cmd.AddValue("packetSize", "UDP payload bytes", 512)
    cmd.AddValue("interval", "client send interval (s)", 0.1)
    cmd.AddValue("standard", "80211a (legacy) or 80211n (HT: QoS + A-MPDU)", "80211a")
    cmd.AddValue("dataMode", "ConstantRate data mode ('' = per-standard default)", "")
    cmd.AddValue(
        "mobility", "STA motion: static | const_velocity | random_walk",
        "static",
    )
    cmd.AddValue("speed", "STA speed (m/s) when mobility != static", 1.0)
    cmd.Parse(argv)
    n_stas = int(cmd.nStas)
    sim_time = float(cmd.simTime)
    from tpudes.models.wifi.helper import HT_STANDARDS, normalize_standard

    standard = normalize_standard(str(cmd.standard))
    data_mode = str(cmd.dataMode) or (
        "HtMcs7" if standard in HT_STANDARDS else "OfdmRate54Mbps"
    )

    nodes = NodeContainer()
    nodes.Create(n_stas + 1)  # node 0 = AP

    # AP pinned at the disc center; STA motion selected by --mobility
    # (moving graphs lift through the device geometry pipeline —
    # tpudes/ops/mobility.py — instead of refusing)
    mob_kind = str(cmd.mobility)
    speed = float(cmd.speed)
    from tpudes.models.mobility import Vector

    ap_mob = MobilityHelper()
    ap_mob.SetPositionAllocator("tpudes::ListPositionAllocator").Add(
        Vector(0.0, 0.0, 0.0)
    )
    ap_mob.Install(nodes.Get(0))
    sta_nodes = [nodes.Get(i) for i in range(1, n_stas + 1)]
    mobility = MobilityHelper()
    mobility.SetPositionAllocator(
        "tpudes::RandomDiscPositionAllocator", X=0.0, Y=0.0, Rho=25.0
    )
    if mob_kind == "random_walk":
        mobility.SetMobilityModel(
            "tpudes::RandomWalk2dMobilityModel",
            Bounds=(-30.0, 30.0, -30.0, 30.0),
            MinSpeed=speed / 2.0, MaxSpeed=speed,
        )
        mobility.Install(sta_nodes)
    elif mob_kind == "const_velocity":
        import math as _math

        from tpudes.models.mobility import ConstantVelocityMobilityModel

        mobility.SetMobilityModel("tpudes::ConstantVelocityMobilityModel")
        mobility.Install(sta_nodes)
        for node in sta_nodes:
            m = node.GetObject(ConstantVelocityMobilityModel)
            p = m.GetPosition()
            a = _math.atan2(p.y, p.x)
            # tangential drift keeps STAs near their radius
            m.SetVelocity(
                Vector(-speed * _math.sin(a), speed * _math.cos(a), 0.0)
            )
    else:
        mobility.SetMobilityModel("tpudes::ConstantPositionMobilityModel")
        mobility.Install(sta_nodes)

    channel = YansWifiChannelHelper.Default().Create()
    phy = YansWifiPhyHelper()
    phy.SetChannel(channel)
    wifi = WifiHelper()
    wifi.SetStandard(standard)
    wifi.SetRemoteStationManager("tpudes::ConstantRateWifiManager", DataMode=data_mode)

    ap_mac = WifiMacHelper()
    ap_mac.SetType("tpudes::ApWifiMac")
    ap_devices = wifi.Install(phy, ap_mac, [nodes.Get(0)])

    sta_mac = WifiMacHelper()
    sta_mac.SetType("tpudes::StaWifiMac")
    sta_devices = wifi.Install(phy, sta_mac, [nodes.Get(i) for i in range(1, n_stas + 1)])

    stack = InternetStackHelper()
    stack.Install(nodes)
    address = Ipv4AddressHelper()
    address.SetBase("10.1.3.0", "255.255.255.0")
    devices = NetDeviceContainer()
    devices.Add(ap_devices.Get(0))
    for i in range(n_stas):
        devices.Add(sta_devices.Get(i))
    interfaces = address.Assign(devices)

    server = UdpEchoServerHelper(9)
    server_apps = server.Install(nodes.Get(0))
    server_apps.Start(Seconds(0.5))
    server_apps.Stop(Seconds(sim_time))
    rx_count = [0]
    server_apps.Get(0).TraceConnectWithoutContext("Rx", lambda pkt, *a: rx_count.__setitem__(0, rx_count[0] + 1))

    clients = []
    for i in range(n_stas):
        client = UdpEchoClientHelper(interfaces.GetAddress(0), 9)
        client.SetAttribute("MaxPackets", 1_000_000)
        client.SetAttribute("Interval", Seconds(float(cmd.interval)))
        client.SetAttribute("PacketSize", int(cmd.packetSize))
        apps = client.Install(nodes.Get(1 + i))
        apps.Start(Seconds(1.0 + 0.001 * i))  # staggered join
        apps.Stop(Seconds(sim_time))
        clients.append(apps.Get(0))

    # per-flow KPIs on the scalar path (FlowMonitor rides the IP traces)
    from tpudes.models.flow_monitor import FlowMonitorHelper

    fmh = FlowMonitorHelper()
    monitor = fmh.InstallAll()

    wall0 = time.monotonic()
    Simulator.Stop(Seconds(sim_time))
    Simulator.Run()
    wall = time.monotonic() - wall0

    res = getattr(Simulator.GetImpl(), "replicated_result", None)
    if res is not None:
        # JaxSimulatorImpl lifted the graph onto the replica axis
        import numpy as np

        out = res["out"]
        replicas = res["replicas"]
        srv = np.asarray(out["srv_rx"])
        print(
            f"replicas={replicas} stas={n_stas} server_rx mean={srv.mean():.2f} "
            f"std={srv.std():.2f} min={srv.min()} max={srv.max()} "
            f"steps={out['steps']} all_done={out['all_done']} "
            f"wall_incl_compile={wall:.2f}s "
            f"sim-s/wall-s={replicas * sim_time / wall:,.0f} "
            f"(one-shot incl. jit compile; bench.py reports steady state)"
        )
        Simulator.Destroy()
        return 0 if out["all_done"] and srv.mean() > 0 else 1

    events = Simulator.GetEventCount()
    n_assoc = sum(
        1 for i in range(n_stas) if sta_devices.Get(i).GetMac().IsAssociated()
    )
    print(f"stas={n_stas} associated={n_assoc} server_rx={rx_count[0]} "
          f"events={events} wall={wall:.2f}s events/s={events / max(wall, 1e-9):,.0f}")
    monitor.CheckForLostPackets()
    stats = monitor.GetFlowStats()
    up = [s for fid, s in stats.items()
          if fmh.GetClassifier().FindFlow(fid).destination_port == 9]
    if up:
        print(
            f"flows={len(stats)} (uplink {len(up)}): "
            f"rx={sum(s.rx_packets for s in up)}/{sum(s.tx_packets for s in up)} pkts "
            f"lost={sum(s.lost_packets for s in up)} "
            f"mean_delay={sum(s.mean_delay_s for s in up) / len(up) * 1e3:.2f}ms "
            f"mean_jitter={sum(s.mean_jitter_s for s in up) / len(up) * 1e3:.2f}ms"
        )
    Simulator.Destroy()
    return 0 if n_assoc == n_stas and rx_count[0] > 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
