/* tpudes native event core: binary-heap scheduler + C dispatch loop.
 *
 * Reference parity: src/core/model/heap-scheduler.{h,cc} and the
 * event-dispatch inner loop of default-simulator-impl.cc (upstream
 * paths; mount empty at survey - SURVEY.md section 0, 2.1).  Upstream's
 * engine is C++ end to end; this extension moves the two hot pieces of
 * the Python engine - the (ts, uid) priority queue and the
 * pop/advance/invoke loop - into C, leaving model callbacks in Python.
 *
 * The heap stores (ts, uid, Event*) with strict (ts, uid) ordering,
 * identical to Scheduler::EventKey.  Cancellation stays lazy: the loop
 * checks ev->cancelled at the head, as the Python schedulers do.
 *
 * Built by tpudes/core/native.py on first use (plain cc -shared; no
 * pybind11 dependency - CPython C API only).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    long long ts;
    long long uid;
    PyObject *ev; /* owned reference */
} HeapEntry;

typedef struct {
    PyObject_HEAD
    HeapEntry *a;
    Py_ssize_t size;
    Py_ssize_t cap;
} CHeapObject;

/* interned attribute names, created at module init */
static PyObject *s_cancelled, *s_fn, *s_args, *s_context, *s_current_ts,
    *s_current_context, *s_current_uid, *s_event_count, *s_stop,
    *s_injected;

static inline int entry_lt(const HeapEntry *x, const HeapEntry *y)
{
    if (x->ts != y->ts)
        return x->ts < y->ts;
    return x->uid < y->uid;
}

static int cheap_grow(CHeapObject *self)
{
    Py_ssize_t ncap = self->cap ? self->cap * 2 : 256;
    HeapEntry *na = (HeapEntry *)realloc(self->a, ncap * sizeof(HeapEntry));
    if (!na) {
        PyErr_NoMemory();
        return -1;
    }
    self->a = na;
    self->cap = ncap;
    return 0;
}

static void sift_up(HeapEntry *a, Py_ssize_t i)
{
    HeapEntry v = a[i];
    while (i > 0) {
        Py_ssize_t parent = (i - 1) / 2;
        if (!entry_lt(&v, &a[parent]))
            break;
        a[i] = a[parent];
        i = parent;
    }
    a[i] = v;
}

static void sift_down(HeapEntry *a, Py_ssize_t n, Py_ssize_t i)
{
    HeapEntry v = a[i];
    for (;;) {
        Py_ssize_t child = 2 * i + 1;
        if (child >= n)
            break;
        if (child + 1 < n && entry_lt(&a[child + 1], &a[child]))
            child++;
        if (!entry_lt(&a[child], &v))
            break;
        a[i] = a[child];
        i = child;
    }
    a[i] = v;
}

/* pop the minimum entry; caller takes ownership of the reference */
static HeapEntry cheap_pop_entry(CHeapObject *self)
{
    HeapEntry top = self->a[0];
    self->size--;
    if (self->size > 0) {
        self->a[0] = self->a[self->size];
        sift_down(self->a, self->size, 0);
    }
    return top;
}

/* drop cancelled heads; returns 0 ok, -1 on python error */
static int cheap_purge(CHeapObject *self)
{
    while (self->size > 0) {
        PyObject *c = PyObject_GetAttr(self->a[0].ev, s_cancelled);
        if (!c)
            return -1;
        int truth = PyObject_IsTrue(c);
        Py_DECREF(c);
        if (truth < 0)
            return -1;
        if (!truth)
            return 0;
        HeapEntry e = cheap_pop_entry(self);
        Py_DECREF(e.ev);
    }
    return 0;
}

static PyObject *cheap_insert(CHeapObject *self, PyObject *args)
{
    long long ts, uid;
    PyObject *ev;
    if (!PyArg_ParseTuple(args, "LLO", &ts, &uid, &ev))
        return NULL;
    if (self->size == self->cap && cheap_grow(self) < 0)
        return NULL;
    Py_INCREF(ev);
    self->a[self->size].ts = ts;
    self->a[self->size].uid = uid;
    self->a[self->size].ev = ev;
    sift_up(self->a, self->size);
    self->size++;
    Py_RETURN_NONE;
}

static PyObject *cheap_is_empty(CHeapObject *self, PyObject *noarg)
{
    if (cheap_purge(self) < 0)
        return NULL;
    return PyBool_FromLong(self->size == 0);
}

static PyObject *cheap_peek(CHeapObject *self, PyObject *noarg)
{
    if (cheap_purge(self) < 0)
        return NULL;
    if (self->size == 0) {
        PyErr_SetString(PyExc_IndexError, "peek on empty heap");
        return NULL;
    }
    Py_INCREF(self->a[0].ev);
    return self->a[0].ev;
}

static PyObject *cheap_pop(CHeapObject *self, PyObject *noarg)
{
    if (cheap_purge(self) < 0)
        return NULL;
    if (self->size == 0) {
        PyErr_SetString(PyExc_IndexError, "pop on empty heap");
        return NULL;
    }
    HeapEntry e = cheap_pop_entry(self);
    return e.ev; /* ownership transferred */
}

static PyObject *cheap_size(CHeapObject *self, PyObject *noarg)
{
    return PyLong_FromSsize_t(self->size);
}

/* run(impl): the engine inner loop.  Pops and invokes events until the
 * queue drains, impl._stop goes true, or impl._injected is non-empty
 * (the Python wrapper drains cross-thread injections and re-enters).
 * Returns the number of events invoked. */
static PyObject *cheap_run(CHeapObject *self, PyObject *impl)
{
    long long invoked = 0;
    long long base_count;
    {
        PyObject *cnt = PyObject_GetAttr(impl, s_event_count);
        if (!cnt)
            return NULL;
        base_count = PyLong_AsLongLong(cnt);
        Py_DECREF(cnt);
        if (base_count == -1 && PyErr_Occurred())
            return NULL;
    }
    for (;;) {
        /* stop flag (callbacks may call Simulator.Stop()) */
        PyObject *stop = PyObject_GetAttr(impl, s_stop);
        if (!stop)
            return NULL;
        int stopped = PyObject_IsTrue(stop);
        Py_DECREF(stop);
        if (stopped < 0)
            return NULL;
        if (stopped)
            break;
        /* cross-thread injections pending? -> let Python drain them */
        PyObject *inj = PyObject_GetAttr(impl, s_injected);
        if (!inj)
            return NULL;
        Py_ssize_t n_inj = PyObject_Length(inj);
        Py_DECREF(inj);
        if (n_inj < 0)
            return NULL;
        if (n_inj > 0)
            break;
        if (cheap_purge(self) < 0)
            return NULL;
        if (self->size == 0)
            break;
        HeapEntry e = cheap_pop_entry(self);

        /* advance engine clock/context/uid and the live event counter
         * (Simulator.Now / GetEventCount read these from callbacks) */
        PyObject *ts_o = PyLong_FromLongLong(e.ts);
        PyObject *uid_o = PyLong_FromLongLong(e.uid);
        PyObject *cnt_o = PyLong_FromLongLong(base_count + invoked + 1);
        PyObject *ctx =
            ts_o && uid_o && cnt_o ? PyObject_GetAttr(e.ev, s_context) : NULL;
        if (!ctx || PyObject_SetAttr(impl, s_current_ts, ts_o) < 0 ||
            PyObject_SetAttr(impl, s_current_context, ctx) < 0 ||
            PyObject_SetAttr(impl, s_current_uid, uid_o) < 0 ||
            PyObject_SetAttr(impl, s_event_count, cnt_o) < 0) {
            Py_XDECREF(ts_o);
            Py_XDECREF(uid_o);
            Py_XDECREF(cnt_o);
            Py_XDECREF(ctx);
            Py_DECREF(e.ev);
            return NULL;
        }
        Py_DECREF(ts_o);
        Py_DECREF(uid_o);
        Py_DECREF(cnt_o);
        Py_DECREF(ctx);

        PyObject *fn = PyObject_GetAttr(e.ev, s_fn);
        PyObject *fargs = fn ? PyObject_GetAttr(e.ev, s_args) : NULL;
        Py_DECREF(e.ev);
        if (!fargs) {
            Py_XDECREF(fn);
            return NULL;
        }
        PyObject *res = PyObject_CallObject(fn, fargs);
        Py_DECREF(fn);
        Py_DECREF(fargs);
        if (!res)
            return NULL; /* callback raised */
        Py_DECREF(res);
        invoked++;
    }
    return PyLong_FromLongLong(invoked);
}

/* cyclic-GC support: events commonly close over the engine that owns
 * this heap (impl -> scheduler -> heap -> event.fn -> impl), so the
 * collector must be able to see through the C array */
static int cheap_traverse(CHeapObject *self, visitproc visit, void *arg)
{
    for (Py_ssize_t i = 0; i < self->size; i++)
        Py_VISIT(self->a[i].ev);
    return 0;
}

static int cheap_clear(CHeapObject *self)
{
    Py_ssize_t n = self->size;
    self->size = 0;
    for (Py_ssize_t i = 0; i < n; i++)
        Py_CLEAR(self->a[i].ev);
    return 0;
}

static PyObject *cheap_live_count(CHeapObject *self, PyObject *noarg)
{
    /* read-only scan; no mutation (len() must not purge) */
    Py_ssize_t live = 0;
    for (Py_ssize_t i = 0; i < self->size; i++) {
        PyObject *c = PyObject_GetAttr(self->a[i].ev, s_cancelled);
        if (!c)
            return NULL;
        int truth = PyObject_IsTrue(c);
        Py_DECREF(c);
        if (truth < 0)
            return NULL;
        if (!truth)
            live++;
    }
    return PyLong_FromSsize_t(live);
}

/* ---- MRG32k3a (L'Ecuyer 1999): the simulator's RandU01 hot path.
 * Exact int64 arithmetic mirroring tpudes/core/rng.py bit for bit
 * (python's %% is always nonnegative; C truncates, hence the fixups),
 * so native and pure-Python streams are interchangeable mid-run. */

#define MRG_M1 4294967087LL
#define MRG_M2 4294944443LL
#define MRG_A12 1403580LL
#define MRG_A13N 810728LL
#define MRG_A21 527612LL
#define MRG_A23N 1370589LL

typedef struct {
    PyObject_HEAD
    long long s1[3];
    long long s2[3];
} MrgObject;

static PyObject *mrg_new(PyTypeObject *type, PyObject *args, PyObject *kw)
{
    MrgObject *self = (MrgObject *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    if (!PyArg_ParseTuple(
            args, "LLLLLL", &self->s1[0], &self->s1[1], &self->s1[2],
            &self->s2[0], &self->s2[1], &self->s2[2])) {
        Py_DECREF(self);
        return NULL;
    }
    return (PyObject *)self;
}

static PyObject *mrg_rand_u01(MrgObject *self, PyObject *noarg)
{
    long long p1 = (MRG_A12 * self->s1[1] - MRG_A13N * self->s1[0]) % MRG_M1;
    if (p1 < 0)
        p1 += MRG_M1;
    self->s1[0] = self->s1[1];
    self->s1[1] = self->s1[2];
    self->s1[2] = p1;
    long long p2 = (MRG_A21 * self->s2[2] - MRG_A23N * self->s2[0]) % MRG_M2;
    if (p2 < 0)
        p2 += MRG_M2;
    self->s2[0] = self->s2[1];
    self->s2[1] = self->s2[2];
    self->s2[2] = p2;
    long long d = p1 - p2;
    if (d <= 0)
        d += MRG_M1;
    return PyFloat_FromDouble((double)d * (1.0 / (MRG_M1 + 1.0)));
}

static PyObject *mrg_get_state(MrgObject *self, PyObject *noarg)
{
    return Py_BuildValue(
        "(LLLLLL)", self->s1[0], self->s1[1], self->s1[2],
        self->s2[0], self->s2[1], self->s2[2]);
}

static PyMethodDef mrg_methods[] = {
    {"rand_u01", (PyCFunction)mrg_rand_u01, METH_NOARGS, "next U(0,1)"},
    {"get_state", (PyCFunction)mrg_get_state, METH_NOARGS,
     "(s1_0..s2_2) current state"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject MrgType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "tpudes_event_core.Mrg32k3a",
    .tp_basicsize = sizeof(MrgObject),
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_doc = "MRG32k3a stream (exact match of the Python reference)",
    .tp_new = mrg_new,
    .tp_methods = mrg_methods,
};

static void cheap_dealloc(CHeapObject *self)
{
    PyObject_GC_UnTrack(self);
    cheap_clear(self);
    free(self->a);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *cheap_new(PyTypeObject *type, PyObject *args, PyObject *kw)
{
    CHeapObject *self = (CHeapObject *)type->tp_alloc(type, 0);
    if (self) {
        self->a = NULL;
        self->size = 0;
        self->cap = 0;
    }
    return (PyObject *)self;
}

static PyMethodDef cheap_methods[] = {
    {"insert", (PyCFunction)cheap_insert, METH_VARARGS, "insert(ts, uid, ev)"},
    {"is_empty", (PyCFunction)cheap_is_empty, METH_NOARGS, "live queue empty?"},
    {"peek", (PyCFunction)cheap_peek, METH_NOARGS, "next live event"},
    {"pop", (PyCFunction)cheap_pop, METH_NOARGS, "pop next live event"},
    {"size", (PyCFunction)cheap_size, METH_NOARGS, "raw entry count"},
    {"live_count", (PyCFunction)cheap_live_count, METH_NOARGS,
     "non-cancelled entry count (read-only scan)"},
    {"run", (PyCFunction)cheap_run, METH_O,
     "run(impl) -> events invoked; returns on stop/injection/empty"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject CHeapType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "tpudes_event_core.CHeap",
    .tp_basicsize = sizeof(CHeapObject),
    .tp_flags = Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC,
    .tp_doc = "binary heap of (ts, uid, event) with a C dispatch loop",
    .tp_new = cheap_new,
    .tp_dealloc = (destructor)cheap_dealloc,
    .tp_traverse = (traverseproc)cheap_traverse,
    .tp_clear = (inquiry)cheap_clear,
    .tp_methods = cheap_methods,
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "tpudes_event_core",
    "native event heap + dispatch loop", -1, NULL,
};

PyMODINIT_FUNC PyInit_tpudes_event_core(void)
{
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    if (PyType_Ready(&CHeapType) < 0)
        return NULL;
    Py_INCREF(&CHeapType);
    PyModule_AddObject(m, "CHeap", (PyObject *)&CHeapType);
    if (PyType_Ready(&MrgType) < 0)
        return NULL;
    Py_INCREF(&MrgType);
    PyModule_AddObject(m, "Mrg32k3a", (PyObject *)&MrgType);
#define INTERN(var, name)                                                     \
    if (!(var = PyUnicode_InternFromString(name)))                            \
        return NULL;
    INTERN(s_cancelled, "cancelled")
    INTERN(s_fn, "fn")
    INTERN(s_args, "args")
    INTERN(s_context, "context")
    INTERN(s_current_ts, "current_ts")
    INTERN(s_current_context, "current_context")
    INTERN(s_current_uid, "current_uid")
    INTERN(s_event_count, "_event_count")
    INTERN(s_stop, "_stop")
    INTERN(s_injected, "_injected")
#undef INTERN
    return m;
}
