"""Benchmark: BASELINE.md config #3 — YansWifiPhy BSS PHY evaluations,
64 STAs × 512 Monte-Carlo replicas.

Numerator: the fused window kernel (tpudes.parallel.kernels) running
multi-window lax.scan on the accelerator — the TPU execution path of
SURVEY.md §3.2's hot loop.

Denominator (vs_baseline): the identical logical work — per-(tx, rx)
log-distance rx power + NIST chunk PER + coin flip — through the host
scalar path used by DefaultSimulatorImpl (float64 oracle math).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = 65          # AP + 64 STAs
N_REPLICAS = 512
N_WINDOWS = 256
TX_PER_WINDOW = 8     # expected concurrent transmitters per window


def tpu_rate() -> tuple[float, dict]:
    import jax
    import jax.numpy as jnp

    from tpudes.parallel.kernels import wifi_phy_window

    key = jax.random.PRNGKey(42)
    k_pos, k_run = jax.random.split(key)
    positions = jax.random.uniform(k_pos, (N_NODES, 3), minval=0.0, maxval=60.0)
    positions = positions.at[:, 2].set(0.0)
    mode_idx = jnp.full((N_NODES,), 7, dtype=jnp.int32)     # 54 Mbps
    frame_bytes = jnp.full((N_NODES,), 1000.0, dtype=jnp.float32)
    tx_prob = TX_PER_WINDOW / N_NODES

    def window(carry, k):
        delivered = carry
        k_tx, k_phy = jax.random.split(k)
        # per-replica tx draws: (R, N)
        tx = jax.random.uniform(k_tx, (N_REPLICAS, N_NODES)) < tx_prob
        keys = jax.random.split(k_phy, N_REPLICAS)
        ok, _, _ = jax.vmap(
            lambda t, kk: wifi_phy_window(positions, t, mode_idx, frame_bytes, kk)
        )(tx, keys)
        return delivered + jnp.sum(ok, dtype=jnp.int32), jnp.sum(tx, dtype=jnp.int32)

    @jax.jit
    def run(k):
        keys = jax.random.split(k, N_WINDOWS)
        delivered, tx_counts = jax.lax.scan(window, jnp.int32(0), keys)
        return delivered, jnp.sum(tx_counts)

    # compile
    d, ntx = run(k_run)
    d.block_until_ready()
    # timed
    t0 = time.monotonic()
    d, ntx = run(jax.random.PRNGKey(43))
    d.block_until_ready()
    wall = time.monotonic() - t0

    evals = int(ntx) * (N_NODES - 1)  # logical (tx → rx) frame evaluations
    # aggregate simulated time: windows are 1 ms, all replicas advance together
    sim_s_aggregate = N_WINDOWS * 1e-3 * N_REPLICAS
    extras = {
        "delivered": int(d),
        "wall_s": wall,
        "sim_s_per_wall_s_per_chip": sim_s_aggregate / wall / max(len(jax.devices()), 1),
        "devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
    return evals / wall, extras


def cpu_rate() -> float:
    """Identical logical work through the sequential engine's float64
    scalar path (the DefaultSimulatorImpl denominator)."""
    import random

    from tpudes.ops.wifi_error import ALL_MODES, chunk_success_rate_py

    mode = ALL_MODES[7]
    rng = random.Random(1)
    noise_w = 10 ** (7 / 10) * 1.380649e-23 * 290 * 20e6
    # pre-draw geometry like the scalar channel would see it
    pos = [(rng.uniform(0, 60), rng.uniform(0, 60)) for _ in range(N_NODES)]
    n_pairs = 0
    t0 = time.monotonic()
    target_pairs = 60_000
    delivered = 0
    while n_pairs < target_pairs:
        tx_set = [i for i in range(N_NODES) if rng.random() < TX_PER_WINDOW / N_NODES]
        for t in tx_set:
            for r in range(N_NODES):
                if r == t:
                    continue
                # log-distance rx power (float64 scalar, as CalcRxPower)
                dx, dy = pos[t][0] - pos[r][0], pos[t][1] - pos[r][1]
                d = max(math.sqrt(dx * dx + dy * dy), 1.0)
                rx_dbm = 16.0206 - (46.6777 + 30.0 * math.log10(d))
                rx_w = 10 ** ((rx_dbm - 30) / 10)
                # interference from other concurrent tx
                i_w = 0.0
                for o in tx_set:
                    if o in (t, r):
                        continue
                    ox, oy = pos[o][0] - pos[r][0], pos[o][1] - pos[r][1]
                    od = max(math.sqrt(ox * ox + oy * oy), 1.0)
                    i_w += 10 ** ((16.0206 - (46.6777 + 30.0 * math.log10(od)) - 30) / 10)
                sinr = rx_w / (noise_w + i_w)
                psr = chunk_success_rate_py(sinr, 8000.0, mode.constellation, mode.rate_class)
                if rng.random() < psr:
                    delivered += 1
                n_pairs += 1
    wall = time.monotonic() - t0
    return n_pairs / wall


def main():
    cpu = cpu_rate()
    tpu, extras = tpu_rate()
    out = {
        "metric": "wifi-bss phy frame evaluations (64 STA x 512 replicas)",
        "value": round(tpu, 1),
        "unit": "evals/s",
        "vs_baseline": round(tpu / cpu, 2),
        "baseline_evals_s": round(cpu, 1),
        **{k: (round(v, 3) if isinstance(v, float) else v) for k, v in extras.items()},
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
