"""Benchmark: engine vs engine on the BASELINE scenarios.

Numerator: the SAME constructed object graph lowered onto the replica
axis (tpudes/parallel/lift.py) and run on the accelerator —
``JaxSimulatorImpl``'s lifted path.  Denominator: ``DefaultSimulatorImpl``
executing the identical scenario's scalar event loop on the host.
Both sides are *scenario-level* sim-seconds per wall-second; the ratio
is the engine speedup the north star asks for (BASELINE.json: "one
GlobalValue flag flips a stock scenario onto the TPU").

Four scenarios:
  - BSS (BASELINE config #3): 64-STA infrastructure WiFi, UDP echo,
    512 Monte-Carlo replicas at once (the headline metric).
  - LTE (BASELINE config #4): 7 eNB x 210 UE full-buffer hex grid,
    64 replicas of 10 simulated seconds on the device SM engine vs the
    host per-TTI controller loop.
  - TCP dumbbell (BASELINE config #2): 8 bulk flows over a 10 Mbps
    bottleneck, 256 replicas of 20 simulated seconds on the packet-slot
    engine vs the host socket stack.
  - AS topology (BASELINE config #5): BRITE-style BA graph, 10k nodes,
    128 sparse CBR flows, 1024 replicas on the flow engine vs one host
    packet-level run of the same scenario.  The flow engine computes the
    converged steady-state outcome directly (its cost does not scale
    with simulated seconds), so this line reports **studies/s** — one
    study = one replica's complete traffic outcome — not sim-s/wall-s;
    the host side's study is its AS_HOST_S packet-level integration.

Two sweep rows ride on top of the LTE and TCP scenarios (r6):
  - lte_sched_sweep: the SAME lowered hex grid through all NINE FF-MAC
    schedulers.  The scheduler id is a traced operand of the compiled
    program, so the sweep pays ONE compile; the row reports the whole-
    family wall time and asserts the single-executable property.
  - tcp_variant_sweep: one dumbbell with 17 flows, one per
    TcpCongestionOps variant — the full family in one fused program.

Timing protocol: the device side compiles once, then runs N_TIMED=5
timed repetitions with distinct PRNG keys; the reported value is the
MEDIAN with min/max spread (rounds 1-3 reported single-shot numbers,
whose ±20% drift was indistinguishable from real regressions — the
spread now makes the noise visible).  The host side runs once (its
wall time is deterministic within a few percent) after a warm-up
segment so JIT compilation of the TTI kernel is excluded on both sides.

Strong-scaling rows (PR 4): ``bench_mesh()`` runs each engine's SAME
program on a 1-device mesh vs the full mesh and reports rate, wall
medians, speedup and per-configuration compile counts.  The section
rides the default output whenever more than one device is visible, and
``--mesh [--smoke]`` emits it standalone (the CI virtual-device job and
the MULTICHIP harness both use that path).  The timed repetitions ride
``RUNTIME.submit`` — the async in-flight window — so the rows measure
pipelined steady-state throughput, not launch+sync round trips.

ISSUE-6 rows:
  - the `lte` row now carries `ttis_per_wall_s` + the pallas/precision
    flags, and `lte_kernel_profile` reports per-stage device timings of
    the fused TTI kernel chain (coin PRNG, retx admission, scheduler
    dispatch, SINR/CQI/HARQ decode, fused step) with the dominating
    stage named — the measurement behind the Pallas fusion tentpole.

ISSUE-5 rows:
  - sweep_vectorized: the 8-point LTE scheduler sweep and 8-point TCP
    variant sweep as ONE config-axis (C, R, …) launch vs 8 per-point
    launches of the same executable — the one-launch rate must be >=
    the per-point rate on every platform, and the row carries the
    launch/compile counters that pin the single-launch property.
  - pipeline_overlap: a heterogeneous 6-horizon LTE sweep dispatched
    blocking vs through RUNTIME.submit; reports both walls and the
    max_in_flight telemetry.
  - mesh_config_sweep (with --mesh): a 2-point scheduler sweep on the
    full mesh — megabatching composed with replica sharding.

ISSUE-7 row:
  - serving_closed_loop: a closed-loop multi-tenant client pool driving
    the StudyServer (tpudes/serving) vs serialized RUNTIME.submit of
    the same study stream — requests/s at bounded p99 study latency,
    the first metric that models many concurrent users rather than one
    batch job.  Coalesced serving must be >= 2x serialized throughput
    at equal (bit-pinned) results.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_STAS = 64
WIFI_REPLICAS = 512
WIFI_SIM_S = 2.0
WIFI_HT_SIM_S = 2.0
WIFI_HT_INTERVAL_S = 0.01
LTE_ENBS = 7
LTE_UES_PER_CELL = 30
LTE_REPLICAS = 64
LTE_SIM_S = 10.0
LTE_HOST_WARM_S = 0.01
LTE_HOST_MEAS_S = 0.04
TCP_FLOWS = 8
TCP_REPLICAS = 256
TCP_SIM_S = 20.0
TCP_HOST_S = 5.0
AS_NODES = 10_000
AS_FLOWS = 128
AS_REPLICAS = 1024
AS_SIM_S = 10.0
AS_HOST_S = 2.0
N_TIMED = 5


def _bench_bss(sim_s, **build_kwargs):
    """Shared BSS harness: scalar denominator + replica-engine numerator
    on the SAME object graph, so the legacy and HT WiFi lines are
    measured identically."""
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.parallel.replicated import lower_bss, run_replicated_bss
    from tpudes.scenarios import build_bss

    reset_world()
    sta_devices, ap_device, clients, _ = build_bss(N_STAS, sim_s, **build_kwargs)
    n = sta_devices.GetN()
    prog = lower_bss(
        [sta_devices.Get(i) for i in range(n)], ap_device, clients, sim_s
    )

    # --- denominator: DefaultSimulatorImpl on the same graph ------------
    t0 = time.monotonic()
    Simulator.Stop(Seconds(sim_s))
    Simulator.Run()
    scalar_wall = time.monotonic() - t0
    scalar_events = Simulator.GetEventCount()
    reset_world()
    scalar_rate = sim_s / scalar_wall

    # --- numerator: replica engine, median of N_TIMED ---------------------
    run_replicated_bss(prog, WIFI_REPLICAS, jax.random.PRNGKey(0))  # compile
    walls, delivered = [], 0
    for i in range(N_TIMED):
        t0 = time.monotonic()
        out = run_replicated_bss(prog, WIFI_REPLICAS, jax.random.PRNGKey(1 + i))
        walls.append(time.monotonic() - t0)
        delivered += int(out["srv_rx"].sum())
        assert out["all_done"]
    med = statistics.median(walls)
    rate = WIFI_REPLICAS * sim_s / med
    return prog, dict(
        sim_s_per_wall_s=rate,
        vs_scalar=rate / scalar_rate,
        wall_median_s=med,
        wall_min_s=min(walls),
        wall_max_s=max(walls),
        scalar_sim_s_per_wall_s=scalar_rate,
        scalar_events_per_s=scalar_events / scalar_wall,
        srv_rx_mean=delivered / (N_TIMED * WIFI_REPLICAS),
    )


def bench_wifi():
    _, out = _bench_bss(WIFI_SIM_S)
    return out


def bench_wifi_ht():
    """The 802.11n line: same BSS shape, HT rates + QoS + A-MPDU under
    BlockAck, at an offered load (512 B / 10 ms per STA, doubled by
    echoes) that saturates single-MPDU exchanges so aggregation is
    actually exercised on both engines."""
    prog, out = _bench_bss(
        WIFI_HT_SIM_S, interval_s=WIFI_HT_INTERVAL_S,
        data_mode="HtMcs7", standard="80211n",
    )
    assert prog.max_mpdus > 1, "HT bench must exercise aggregation"
    out["max_mpdus"] = prog.max_mpdus
    return out


def bench_lte():
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.parallel.lte_sm import lower_lte_sm, run_lte_sm
    from tpudes.scenarios import build_lena

    reset_world()
    lte, _ = build_lena(LTE_ENBS, LTE_UES_PER_CELL)
    prog = lower_lte_sm(lte, LTE_SIM_S)

    # --- denominator: the host per-TTI controller loop -------------------
    # warm-up segment first so the TTI kernel's jit compile is excluded,
    # then a measured segment (the host path is linear in TTIs)
    Simulator.Stop(Seconds(LTE_HOST_WARM_S))
    Simulator.Run()
    t0 = time.monotonic()
    Simulator.Stop(Seconds(LTE_HOST_MEAS_S))
    Simulator.Run()
    host_wall = time.monotonic() - t0
    reset_world()
    host_rate = LTE_HOST_MEAS_S / host_wall

    # --- numerator: device SM engine, median of N_TIMED -------------------
    run_lte_sm(prog, jax.random.PRNGKey(0), replicas=LTE_REPLICAS)  # compile
    walls, bits = [], 0
    for i in range(N_TIMED):
        t0 = time.monotonic()
        out = run_lte_sm(
            prog, jax.random.PRNGKey(1 + i), replicas=LTE_REPLICAS
        )
        walls.append(time.monotonic() - t0)
        bits += int(out["rx_bits"].sum())
    med = statistics.median(walls)
    rate = LTE_REPLICAS * LTE_SIM_S / med
    ues = LTE_REPLICAS * LTE_ENBS * LTE_UES_PER_CELL
    from tpudes.parallel.kernels_pallas import pallas_enabled

    return dict(
        sim_s_per_wall_s=rate,
        vs_scalar=rate / host_rate,
        wall_median_s=med,
        wall_min_s=min(walls),
        wall_max_s=max(walls),
        scalar_sim_s_per_wall_s=host_rate,
        # ISSUE-6: where the TTI budget goes, not just that it is spent
        ttis_per_wall_s=LTE_REPLICAS * prog.n_ttis / med,
        pallas=pallas_enabled(),
        precision=prog.precision,
        agg_dl_mbps=bits / N_TIMED / LTE_REPLICAS / LTE_SIM_S / 1e6,
        # tpudes.obs device accumulators (last timed run, per-UE means)
        obs_grants_per_ue=float(out["new_tbs"].sum()) / ues,
        obs_harq_retx_per_ue=float(out["retx"].sum()) / ues,
        obs_harq_drops_per_ue=float(out["drops"].sum()) / ues,
    )


def bench_mobile_bss(smoke: bool = False):
    """ISSUE-10 row: a MOVING BSS topology on the device engine.

    Three measurements on the same scenario shape:
    - ``host``: the scalar host DES on the mobile graph — the rate any
      mobile topology ran at while the engines refused mobility (the
      host-geometry-refresh baseline);
    - ``static``: the device engine on the frozen (t=0) geometry — the
      ceiling the mobile engine is compared against;
    - ``mobile``: the device engine with the geometry stage in the scan
      carry at ``geom_stride``.

    Acceptance: mobile >= 5x the host baseline at <= 1.5x the static
    wall (CPU reference shape); the row carries the geometry-refresh
    counters so the artifact PROVES which regime ran."""
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.obs.geometry import GeomTelemetry
    from tpudes.parallel.replicated import lower_bss, run_replicated_bss
    from tpudes.scenarios import build_bss

    n_stas = 8 if smoke else N_STAS
    sim_s = 1.4 if smoke else WIFI_SIM_S
    replicas = 32 if smoke else WIFI_REPLICAS
    stride = 8
    speed = 1.0

    def _lowered(mobility):
        reset_world()
        stas, ap, clients, _ = build_bss(
            n_stas, sim_s, mobility=mobility, speed=speed
        )
        prog = lower_bss(
            [stas.Get(i) for i in range(n_stas)], ap, clients, sim_s,
            geom_stride=stride,
        )
        return prog

    # --- host baseline: the mobile graph on the scalar DES ---------------
    prog_m = _lowered("const_velocity")
    t0 = time.monotonic()
    Simulator.Stop(Seconds(sim_s))
    Simulator.Run()
    host_rate = sim_s / (time.monotonic() - t0)
    prog_s = _lowered("static")
    reset_world()

    def _timed(prog):
        run_replicated_bss(prog, replicas, jax.random.PRNGKey(0))  # compile
        walls = []
        for i in range(N_TIMED):
            t0 = time.monotonic()
            out = run_replicated_bss(prog, replicas, jax.random.PRNGKey(1 + i))
            walls.append(time.monotonic() - t0)
            assert out["all_done"]
        return statistics.median(walls), out

    GeomTelemetry.reset()
    static_wall, _ = _timed(prog_s)
    mobile_wall, mout = _timed(prog_m)
    mobile_rate = replicas * sim_s / mobile_wall
    return dict(
        sim_s_per_wall_s=mobile_rate,
        static_sim_s_per_wall_s=replicas * sim_s / static_wall,
        host_sim_s_per_wall_s=host_rate,
        # the two acceptance ratios
        vs_host_refresh=mobile_rate / host_rate,
        wall_vs_static=mobile_wall / static_wall,
        wall_median_s=mobile_wall,
        geom_stride=stride,
        mob_model=prog_m.mobility.model,
        speed_mps=speed,
        # per-run geometry accounting (last timed mobile run) + the
        # process-cumulative telemetry the obs schema gate validates
        geom_refreshes=mout["geom_refreshes"],
        steps=mout["steps"],
        geom_telemetry=GeomTelemetry.engine("bss"),
        replicas=replicas,
        n_stas=n_stas,
    )


def bench_lte_mobility(smoke: bool = False):
    """ISSUE-10 row, LTE side: moving UEs through the SM engine's
    device geometry stage vs (a) the host TTI controller on the same
    mobile graph — whose every TTI pays the host geometry refresh that
    used to be the ONLY way to run mobile LTE — and (b) the device
    engine on the frozen drop (the static-geometry ceiling)."""
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.obs.geometry import GeomTelemetry
    from tpudes.parallel.lte_sm import lower_lte_sm, run_lte_sm
    from tpudes.scenarios import build_lena

    n_enbs, upc = (2, 4) if smoke else (LTE_ENBS, LTE_UES_PER_CELL)
    sim_s = 0.3 if smoke else LTE_SIM_S
    replicas = 8 if smoke else LTE_REPLICAS
    stride = 8
    speed = 10.0

    reset_world()
    lte, _ = build_lena(
        n_enbs, upc, mobility="const_velocity", speed=speed
    )
    prog_m = lower_lte_sm(lte, sim_s, geom_stride=stride)
    # host baseline: the controller's TTI loop on the SAME mobile graph
    # (per-TTI host geometry refresh); warm segment excludes the jit
    Simulator.Stop(Seconds(LTE_HOST_WARM_S))
    Simulator.Run()
    t0 = time.monotonic()
    Simulator.Stop(Seconds(LTE_HOST_WARM_S + LTE_HOST_MEAS_S))
    Simulator.Run()
    host_rate = LTE_HOST_MEAS_S / (time.monotonic() - t0)
    reset_world()
    lte, _ = build_lena(n_enbs, upc)  # same drop, frozen
    prog_s = lower_lte_sm(lte, sim_s)
    reset_world()

    def _timed(prog):
        run_lte_sm(prog, jax.random.PRNGKey(0), replicas=replicas)
        walls = []
        for i in range(N_TIMED):
            t0 = time.monotonic()
            out = run_lte_sm(
                prog, jax.random.PRNGKey(1 + i), replicas=replicas
            )
            walls.append(time.monotonic() - t0)
        return statistics.median(walls), out

    GeomTelemetry.reset()
    static_wall, _ = _timed(prog_s)
    mobile_wall, mout = _timed(prog_m)
    mobile_rate = replicas * sim_s / mobile_wall
    return dict(
        sim_s_per_wall_s=mobile_rate,
        static_sim_s_per_wall_s=replicas * sim_s / static_wall,
        host_sim_s_per_wall_s=host_rate,
        vs_host_refresh=mobile_rate / host_rate,
        wall_vs_static=mobile_wall / static_wall,
        wall_median_s=mobile_wall,
        ttis_per_wall_s=replicas * prog_m.n_ttis / mobile_wall,
        geom_stride=stride,
        mob_model=prog_m.mobility.model,
        speed_mps=speed,
        geom_refreshes=mout["geom_refreshes"],
        geom_telemetry=GeomTelemetry.engine("lte_sm"),
        replicas=replicas,
        n_enbs=n_enbs,
        ues_per_cell=upc,
    )


def bench_traffic_burst(smoke: bool = False):
    """ISSUE-14 row: the device-resident traffic stage as a metric.

    Three measurements on one BSS program:

    - ``stage_overhead``: the neutral cbr WORKLOAD program (identical
      arrivals through the traffic stage's traced dispatch) vs
      ``traffic=None`` (the legacy advance) — the pure cost of
      compiling the model-family dispatch in;
    - ``burst_overhead``: a bursty ON-OFF workload vs the cbr
      workload at MATCHED mean load, normalized per retired event
      step — the acceptance bar is <= 1.5x.  (Clustered arrivals
      legitimately serialize more steps — same-instant contention —
      so the raw ``burst_wall_ratio`` rides the row unguarded and
      the gate bounds what the stage costs per step.);
    - the one-launch WORKLOAD sweep: 8 mixed cbr/mmpp/onoff/trace
      points (shape-unified, `toy_traffic_points`) as ONE (C, R, …)
      launch — launches must be 1, fresh compiles during the timed
      call 0, and the demux bit-equal to per-point launches.

    The row embeds the :class:`TrafficTelemetry` snapshot so the
    artifact PROVES which models ran.
    """
    import dataclasses

    import jax
    import numpy as np

    from tpudes.obs.device import CompileTelemetry
    from tpudes.obs.traffic import TrafficTelemetry
    from tpudes.parallel.programs import toy_bss_program, toy_traffic_points
    from tpudes.parallel.replicated import run_replicated_bss
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.traffic.host import offered_packets

    # smoke shapes stay big enough that the wall ratio measures the
    # engine, not dispatch jitter (the CI gate pins ratio <= 1.5)
    n_stas = 4 if smoke else 8
    sim_s = 1.2 if smoke else 1.5
    replicas = 32 if smoke else 64
    reps = N_TIMED
    from tpudes.traffic import TrafficProgram, bounded_pareto_mean

    prog = toy_bss_program(n_sta=n_stas, sim_end_us=int(sim_s * 1e6))
    pts = toy_traffic_points(
        prog.n, prog.sim_end_us, start_us=prog.start_us,
        beacon=(int(prog.interval_us[0]), int(prog.start_us[0])),
    )
    cbr_prog = dataclasses.replace(prog, traffic=pts[0])
    # the burst program offers the SAME mean load as the cbr one
    # (peak = rate / duty), so the wall ratio measures burstiness —
    # gap dispatch + arrival clustering — not extra workload volume
    on, off_s = (1.5, 0.05, 0.3), 0.1
    duty = bounded_pareto_mean(*on) / (bounded_pareto_mean(*on) + off_s)
    sta_rate = 1e6 / float(prog.interval_us[1])
    burst_tp = TrafficProgram.onoff(
        prog.n, sta_rate / duty, horizon_us=prog.sim_end_us, on=on,
        off_mean_s=off_s, start_us=prog.start_us, tr_seed=1,
    ).with_cbr_rows(
        np.arange(prog.n) == 0, int(prog.interval_us[0]),
        int(prog.start_us[0]),
    )
    burst_prog = dataclasses.replace(prog, traffic=burst_tp)

    def timed(fn):
        # MIN of the repetitions, not the bench's usual median: this
        # row's deliverable is a RATIO gated in CI, and at CPU-smoke
        # walls (tens of ms) one scheduler hiccup on the numerator or
        # denominator alone flakes the gate — the minimum is the
        # noise-floor estimator for a single-process ratio
        fn(jax.random.PRNGKey(0))  # compile + warm
        walls = []
        for i in range(reps):
            t0 = time.monotonic()
            fn(jax.random.PRNGKey(1 + i))
            walls.append(time.monotonic() - t0)
        return min(walls)

    outs = {}

    def runner(name, p):
        def fn(k):
            outs[name] = run_replicated_bss(p, replicas, k)

        return fn

    wall_none = timed(runner("none", prog))
    wall_cbr = timed(runner("cbr", cbr_prog))
    wall_burst = timed(runner("burst", burst_prog))
    # clustered arrivals legitimately serialize MORE event steps at the
    # same mean load (same-instant contention → extra backoff/retry
    # events) — that is workload physics, not stage cost.  The gated
    # overhead is therefore PER RETIRED STEP: wall ratio divided by
    # step-count ratio, the cost the traffic stage adds to each event
    # the vector loop executes.  The raw wall ratio rides the row too.
    step_ratio = max(
        int(outs["burst"]["steps"]) / max(int(outs["cbr"]["steps"]), 1),
        1e-9,
    )

    # --- one-launch workload sweep (the acceptance criterion) ------------
    key = jax.random.PRNGKey(99)
    per = [
        run_replicated_bss(
            dataclasses.replace(prog, traffic=tp), replicas, key
        )
        for tp in pts
    ]
    run_replicated_bss(cbr_prog, replicas, key, traffic_sweep=pts)  # warm
    l0 = RUNTIME.launches("bss")
    c0 = CompileTelemetry.compiles("bss")
    t0 = time.monotonic()
    swept = run_replicated_bss(
        cbr_prog, replicas, key, traffic_sweep=pts
    )
    sweep_wall = time.monotonic() - t0
    demux_equal = all(
        np.array_equal(np.asarray(a[f]), np.asarray(b[f]))
        for a, b in zip(per, swept)
        for f in ("srv_rx", "cli_rx", "tx_data", "drops")
    )

    # workload telemetry: offered from the host mirror of the device
    # cum kernel, delivered from the burst run's outcome counters
    res = outs["burst"]
    offered = float(
        np.floor(
            offered_packets(burst_prog.traffic, prog.sim_end_us)[1:]
        ).sum()
    ) * replicas
    TrafficTelemetry.record(
        "bss", "onoff",
        offered=offered,
        delivered=float(np.asarray(res["srv_rx"], np.int64).sum()),
        unit="packets",
        duty=float(
            np.clip(
                burst_prog.traffic.rate_pps[1:].sum()
                / max(float(burst_prog.traffic.peak_pps[1:].sum()), 1e-9),
                0.0, 1.0,
            )
        ),
    )

    return dict(
        replicas=replicas,
        sim_s=sim_s,
        wall_none_s=round(wall_none, 4),
        wall_cbr_s=round(wall_cbr, 4),
        wall_burst_s=round(wall_burst, 4),
        stage_overhead=round(wall_cbr / wall_none, 3),
        burst_steps=int(outs["burst"]["steps"]),
        cbr_steps=int(outs["cbr"]["steps"]),
        burst_wall_ratio=round(wall_burst / wall_cbr, 3),
        # the CI-gated bound (<= 1.5): per-step wall overhead of the
        # bursty workload vs cbr at matched mean load
        burst_overhead=round(wall_burst / wall_cbr / step_ratio, 3),
        sweep_points=len(pts),
        sweep_wall_s=round(sweep_wall, 4),
        sweep_launches=RUNTIME.launches("bss") - l0,       # must be 1
        sweep_compiles_timed=CompileTelemetry.compiles("bss") - c0,  # 0
        sweep_demux_bit_equal=bool(demux_equal),
        smoke=smoke,
        traffic_telemetry=TrafficTelemetry.snapshot()["engines"].get(
            "bss", {}
        ),
    )


def bench_grad_calibration(smoke: bool = False):
    """ISSUE-15 row: optimization-as-a-service as a metric.

    Two measurements:

    - the LTE calibration demo — plant a propagation exponent,
      observe per-UE CQIs through the differentiable expected-KPI
      chain, recover it by L-BFGS-lite descent.  The WHOLE descent is
      one compiled ``lax.scan``: ``descent_launches`` must be 1 and
      ``descent_compiles_timed`` 0 on the timed (warm) run; the row
      carries the loss-vs-iteration curve (subsampled) and the
      recovered-parameter relative error (acceptance <= 2 %);
    - a C-point grad-of-sweep batch on the AS engine (vmap-of-grad
      over the offered-load axis) — ``grad_sweep_launches`` must be 1
      with 0 timed compiles (the one-executable contract).

    The row embeds the :class:`GradTelemetry` snapshot so the
    artifact PROVES the descent ran (step counts, grad-norm rings,
    the non-finite canary at zero).
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpudes.diff import Surrogacy, calibrate_lte, grad_as_flows
    from tpudes.diff.lte_grad import build_lte_diff, lte_default_params
    from tpudes.obs.device import CompileTelemetry
    from tpudes.obs.grad import GradTelemetry
    from tpudes.parallel.lte_sm import LteSmProgram
    from tpudes.parallel.programs import toy_as_program
    from tpudes.parallel.runtime import RUNTIME

    key = jax.random.PRNGKey(15)
    n_ue = 6 if smoke else 12
    E = 2 if smoke else 3
    steps = 60 if smoke else 120
    serving = (np.arange(n_ue) % E).astype(np.int32)
    rng = np.random.default_rng(3)
    enb_pos = np.asarray(
        [[600.0 * i, 0.0, 30.0] for i in range(E)], np.float32
    )
    ue_pos = (
        enb_pos[serving]
        + np.c_[rng.uniform(-220, 220, n_ue),
                rng.uniform(-220, 220, n_ue),
                np.full(n_ue, -28.5)]
    ).astype(np.float32)
    prog = LteSmProgram(
        gain=np.full((E, n_ue), 1e-12),
        serving=serving,
        tx_power_dbm=np.full((E,), 43.0),
        noise_psd=10.0**0.9 * 1.380649e-23 * 290.0,
        n_rb=25,
        n_ttis=400,
        scheduler="pf",
        enb_pos=enb_pos,
        pathloss=("log_distance", 3.0, 1.0, 46.67),
    )
    planted = 3.45
    kpi = jax.jit(build_lte_diff(prog, Surrogacy()))
    p = lte_default_params(prog, {"ue_pos": ue_pos})
    p["ploss"] = jnp.asarray([planted, 1.0, 46.67], jnp.float32)
    observed = np.asarray(kpi(p)["cqi"])

    def run_calibration():
        return calibrate_lte(
            prog, key, observed, wrt=("ploss",), at={"ue_pos": ue_pos},
            steps=steps, lr=0.5, loss="cqi_mse", opt="lbfgs",
        )

    run_calibration()  # compile + warm the descent program
    l0 = RUNTIME.launches("diff_lte")
    c0 = CompileTelemetry.compiles("diff_lte")
    t0 = time.monotonic()
    res = run_calibration()
    wall = time.monotonic() - t0
    descent_launches = RUNTIME.launches("diff_lte") - l0
    descent_compiles = CompileTelemetry.compiles("diff_lte") - c0
    rel_err = abs(float(res.params["ploss"][0]) - planted) / planted

    # C-point grad-of-sweep on the AS engine: one launch, one grad per
    # sweep point
    as_prog = dataclasses.replace(
        toy_as_program(n_nodes=24 if smoke else 48, n_flows=3),
        surrogate=Surrogacy(),
    )
    scales = [0.5, 1.0, 2.0, 4.0]
    grad_as_flows(
        as_prog, key, 8, loss="neg_goodput", rate_scale=scales
    )  # warm
    l0 = RUNTIME.launches("diff_as")
    c0 = CompileTelemetry.compiles("diff_as")
    sweep = grad_as_flows(
        as_prog, key, 8, loss="neg_goodput", rate_scale=scales
    )
    sweep_launches = RUNTIME.launches("diff_as") - l0
    sweep_compiles = CompileTelemetry.compiles("diff_as") - c0

    curve = res.loss[:: max(1, steps // 12)].tolist() + [
        float(res.loss[-1])
    ]
    return {
        "engine": "diff_lte",
        "opt": res.opt,
        "steps": res.steps,
        "wall_s": round(wall, 4),
        "steps_per_s": round(res.steps / wall, 1),
        "loss_first": float(res.loss[0]),
        "loss_final": float(res.loss[-1]),
        "loss_curve": [round(v, 8) for v in curve],
        "planted_exponent": planted,
        "recovered_exponent": round(float(res.params["ploss"][0]), 5),
        "recovered_rel_err": round(rel_err, 6),
        "descent_launches": descent_launches,       # must be 1
        "descent_compiles_timed": descent_compiles, # must be 0 warm
        "grad_sweep_points": len(scales),
        "grad_sweep_launches": sweep_launches,      # must be 1
        "grad_sweep_compiles_timed": sweep_compiles,
        "grad_sweep_losses": [round(float(v), 6) for v in sweep["loss"]],
        "grad_telemetry": GradTelemetry.snapshot(),
    }


def bench_lte_kernel_profile():
    """ISSUE-6 tentpole row: per-stage device timing of the fused LTE
    TTI kernel chain at the bench scenario's scale, so the dominating
    stage is measured, not asserted.  Each stage is the MARGINAL cost
    of adding it to the compiled chain (delta between consecutive
    prefix programs, clamped at 0 — see profile_sm_stages); the
    fused_step row is the ground-truth per-TTI total, and the implied
    TTI throughput is the ceiling the scan overhead eats into."""
    import jax

    from tpudes.core.world import reset_world
    from tpudes.obs.device import KernelProfile
    from tpudes.parallel.kernels_pallas import profile_sm_stages
    from tpudes.parallel.lte_sm import lower_lte_sm
    from tpudes.scenarios import build_lena

    reset_world()
    lte, _ = build_lena(LTE_ENBS, LTE_UES_PER_CELL)
    prog = lower_lte_sm(lte, LTE_SIM_S)
    reset_world()

    stages = profile_sm_stages(
        prog, replicas=LTE_REPLICAS, iters=30, key=jax.random.PRNGKey(0)
    )
    walls = {k: v for k, v in stages.items() if isinstance(v, float)}
    fused = walls["fused_step"]
    dominating = max(
        (k for k in walls if k != "fused_step"), key=lambda k: walls[k]
    )
    return dict(
        stage_us={k: round(v * 1e6, 1) for k, v in walls.items()},
        dominating_stage=dominating,
        # per-launch ceiling: R replicas advance one TTI per fused call
        ttis_per_wall_s_fused=round(LTE_REPLICAS / fused, 1),
        pallas=stages["pallas"],
        precision=stages["precision"],
        obs_kernel_profile=KernelProfile.snapshot().get("lte_sm", {}),
    )


def bench_lte_sched_sweep():
    """All nine FF-MAC schedulers over the SAME lowered scenario: the
    whole family rides one XLA executable (the traced scheduler-id
    dispatch), so a 9-point scheduler study costs one compile plus nine
    device runs — the row the r6 tentpole adds must not regress the
    plain `lte` row above."""
    import dataclasses

    import jax

    from tpudes.core.world import reset_world
    from tpudes.parallel.lte_sm import SM_SCHED_IDS, lower_lte_sm, run_lte_sm
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.scenarios import build_lena

    reset_world()
    lte, _ = build_lena(LTE_ENBS, LTE_UES_PER_CELL)
    prog = lower_lte_sm(lte, LTE_SIM_S)
    reset_world()

    from tpudes.obs.device import CompileTelemetry

    RUNTIME.clear("lte_sm")
    compiles_before = CompileTelemetry.compiles("lte_sm")
    run_lte_sm(prog, jax.random.PRNGKey(0), replicas=LTE_REPLICAS)  # compile
    t0 = time.monotonic()
    per_sched = {}
    for i, sched in enumerate(SM_SCHED_IDS):
        out = run_lte_sm(
            dataclasses.replace(prog, scheduler=sched),
            jax.random.PRNGKey(1 + i), replicas=LTE_REPLICAS,
        )
        per_sched[sched] = round(
            float(out["rx_bits"].sum() / LTE_REPLICAS / LTE_SIM_S / 1e6), 3
        )
    wall = time.monotonic() - t0
    n_compiled = RUNTIME.size("lte_sm")
    rate = len(SM_SCHED_IDS) * LTE_REPLICAS * LTE_SIM_S / wall
    return dict(
        sim_s_per_wall_s=rate,
        wall_sweep_s=wall,
        schedulers=len(SM_SCHED_IDS),
        compiled_programs=n_compiled,   # must stay 1
        # same single-executable property from the obs telemetry side
        obs_compiles=CompileTelemetry.compiles("lte_sm") - compiles_before,
        agg_dl_mbps=per_sched,
    )


def bench_tcp_variant_sweep():
    """The 17-variant comparison itself: one dumbbell, one flow per
    TcpCongestionOps variant, every variant's cwnd rule evaluated as a
    masked vector lane of the same fused step."""
    import jax

    from tpudes.core.world import reset_world
    from tpudes.parallel.tcp_dumbbell import (
        VARIANTS,
        lower_dumbbell,
        run_tcp_dumbbell,
    )
    from tpudes.scenarios import build_dumbbell

    reset_world()
    build_dumbbell(
        len(VARIANTS), TCP_SIM_S, variants=list(VARIANTS),
        bottleneck_rate="13Mbps",
    )
    prog = lower_dumbbell(TCP_SIM_S)
    reset_world()

    run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=TCP_REPLICAS)
    walls = []
    goodput = None
    for i in range(N_TIMED):
        t0 = time.monotonic()
        out = run_tcp_dumbbell(
            prog, jax.random.PRNGKey(1 + i), replicas=TCP_REPLICAS
        )
        walls.append(time.monotonic() - t0)
        import numpy as np

        g = np.asarray(out["goodput_mbps"]).mean(0)
        goodput = g if goodput is None else goodput + g
    med = statistics.median(walls)
    rate = TCP_REPLICAS * TCP_SIM_S / med
    return dict(
        sim_s_per_wall_s=rate,
        wall_median_s=med,
        wall_min_s=min(walls),
        wall_max_s=max(walls),
        variants=len(VARIANTS),
        per_variant_mbps={
            v: round(float(goodput[i] / N_TIMED), 3)
            for i, v in enumerate(VARIANTS)
        },
    )


def bench_sweep_vectorized():
    """The ISSUE-5 tentpole as a metric: the SAME 8-point scheduler /
    variant sweeps executed one-point-per-launch (the PR-4 shape —
    already one executable, but serialized dispatch + D2H per point)
    vs ONE config-axis launch of a (C, R, …) program.  Reports both
    walls, the one-launch speedup, and the launch/compile counters
    that pin the single-launch property."""
    import dataclasses

    import jax

    from tpudes.core.world import reset_world
    from tpudes.obs.device import CompileTelemetry
    from tpudes.parallel.lte_sm import SM_SCHED_IDS, lower_lte_sm, run_lte_sm
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.tcp_dumbbell import (
        VARIANTS,
        _variant_ecn,
        _variant_point,
        lower_dumbbell,
        run_tcp_dumbbell,
    )
    from tpudes.scenarios import build_dumbbell, build_lena

    reset_world()
    lte, _ = build_lena(LTE_ENBS, LTE_UES_PER_CELL)
    lte_prog = lower_lte_sm(lte, LTE_SIM_S)
    reset_world()
    build_dumbbell(TCP_FLOWS, TCP_SIM_S, variant="TcpCubic")
    tcp_prog = lower_dumbbell(TCP_SIM_S)
    reset_world()

    rows = {}
    scheds = list(SM_SCHED_IDS)[:8]
    points = [[v] * TCP_FLOWS for v in VARIANTS[:8]]

    def lte_per_point(key):
        for i, s in enumerate(scheds):
            run_lte_sm(
                dataclasses.replace(lte_prog, scheduler=s),
                jax.random.fold_in(key, i), replicas=LTE_REPLICAS,
            )

    def lte_one_launch(key):
        run_lte_sm(lte_prog, key, replicas=LTE_REPLICAS, schedulers=scheds)

    def tcp_per_point(key):
        for i, p in enumerate(points):
            ids = _variant_point(p)
            run_tcp_dumbbell(
                dataclasses.replace(
                    tcp_prog, variant_idx=ids, ecn=_variant_ecn(ids)
                ),
                jax.random.fold_in(key, i), replicas=TCP_REPLICAS,
            )

    def tcp_one_launch(key):
        run_tcp_dumbbell(
            tcp_prog, key, replicas=TCP_REPLICAS, variants=points
        )

    for name, per_point, one_launch, sim_s, replicas in (
        ("lte_sm", lte_per_point, lte_one_launch, LTE_SIM_S, LTE_REPLICAS),
        ("dumbbell", tcp_per_point, tcp_one_launch, TCP_SIM_S, TCP_REPLICAS),
    ):
        RUNTIME.clear(name)
        per_point(jax.random.PRNGKey(0))   # warm (compile both modes)
        l0 = RUNTIME.launches(name)
        one_launch(jax.random.PRNGKey(0))
        launches_one = RUNTIME.launches(name) - l0  # the 1-launch pin
        c0 = CompileTelemetry.compiles(name)
        pp_walls, ol_walls = [], []
        for i in range(N_TIMED):
            t0 = time.monotonic()
            per_point(jax.random.PRNGKey(1 + i))
            pp_walls.append(time.monotonic() - t0)
            t0 = time.monotonic()
            one_launch(jax.random.PRNGKey(1 + i))
            ol_walls.append(time.monotonic() - t0)
        pp, ol = statistics.median(pp_walls), statistics.median(ol_walls)
        sweep_sim = 8 * replicas * sim_s
        rows[name] = dict(
            points=8,
            wall_per_point_s=round(pp, 4),
            wall_one_launch_s=round(ol, 4),
            rate_per_point=round(sweep_sim / pp, 1),
            rate_one_launch=round(sweep_sim / ol, 1),
            one_launch_speedup=round(pp / ol, 3),
            launches_one_launch=launches_one,                     # must be 1
            compiles_timed=CompileTelemetry.compiles(name) - c0,  # must be 0
        )
    return rows


def bench_pipeline_overlap():
    """Async submission vs blocking per-point dispatch on a
    heterogeneous sweep (distinct horizons of the lowered LTE grid —
    one executable, the traced-horizon property, but N serialized
    launch+sync round trips when blocking).  Reports both walls and
    the in-flight telemetry that pins >= 2 runs overlapped."""
    import dataclasses

    import jax

    from tpudes.core.world import reset_world
    from tpudes.parallel.lte_sm import lower_lte_sm, run_lte_sm
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.scenarios import build_lena

    reset_world()
    lte, _ = build_lena(LTE_ENBS, LTE_UES_PER_CELL)
    prog = lower_lte_sm(lte, LTE_SIM_S)
    reset_world()

    horizons = [int(LTE_SIM_S * 1000 * f) for f in
                (0.6, 0.8, 1.0, 1.2, 0.7, 0.9)]
    progs = [dataclasses.replace(prog, n_ttis=h) for h in horizons]
    run_lte_sm(progs[0], jax.random.PRNGKey(0), replicas=LTE_REPLICAS)  # warm

    block_walls, submit_walls = [], []
    for i in range(N_TIMED):
        key = jax.random.PRNGKey(1 + i)
        t0 = time.monotonic()
        for j, p in enumerate(progs):
            run_lte_sm(p, jax.random.fold_in(key, j), replicas=LTE_REPLICAS)
        block_walls.append(time.monotonic() - t0)
        t0 = time.monotonic()
        futs = [
            RUNTIME.submit(
                run_lte_sm, p, jax.random.fold_in(key, j),
                replicas=LTE_REPLICAS,
            )
            for j, p in enumerate(progs)
        ]
        for f in futs:
            f.result()
        submit_walls.append(time.monotonic() - t0)
    blk = statistics.median(block_walls)
    sub = statistics.median(submit_walls)
    stats = RUNTIME.stats()
    return dict(
        points=len(horizons),
        wall_blocking_s=round(blk, 4),
        wall_submitted_s=round(sub, 4),
        overlap_speedup=round(blk / sub, 3),
        max_in_flight=stats["max_in_flight"],
        submitted=stats["submitted"],
    )


SERVING_CLIENTS = 16
SERVING_STUDIES_PER_CLIENT = 6
SERVING_SLOTS = 50
SERVING_REPLICAS = 1
SERVING_MAX_WAIT_S = 0.004
SERVING_MAX_BATCH = 8


def bench_serving_closed_loop(smoke: bool = False):
    """ISSUE-7 tentpole row: simulation-as-a-service under closed-loop
    multi-tenant load.  A pool of client threads drives a StudyServer —
    each client submits a study (one dumbbell program per TCP variant,
    same static program / key / replica count, so every study is
    coalescible), waits for its demuxed result, and submits the next.
    The baseline is the SAME study stream through serialized
    ``RUNTIME.submit`` — the best a caller could do before the serving
    layer (pipelined async dispatch, but one device launch per study).

    The row reports requests/s on both paths and the serving p50/p99
    study latency: the acceptance bar is coalesced >= 2x serialized at
    equal results (equality is pinned by tests/test_serving.py, which
    compares coalesced results bit-for-bit against solo launches).
    The study shape is deliberately SMALL: this row measures the
    serving layer's per-launch amortization, not engine compute — on
    accelerators the fixed launch+transfer overhead it amortizes is
    larger still.

    ISSUE-13 columns: a THIRD phase re-runs the closed loop under a
    seed-keyed chaos schedule (launch-shaped errors recovered by the
    requeue/retry path) with clients split across SLO classes (gold /
    standard).  ``degraded_speedup`` is that run against the same
    serialized baseline — the acceptance target is >= 1.5x (the fleet
    absorbs injected failures without falling back to serialized
    throughput) with bounded gold p99; the failure counters and
    per-class SLO attainment ride the row."""
    import dataclasses
    import threading

    import jax

    import tpudes.chaos as chaos
    from tpudes.obs.serving import ServingTelemetry
    from tpudes.parallel.programs import toy_dumbbell_program
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.tcp_dumbbell import (
        VARIANTS,
        _variant_ecn,
        _variant_point,
        run_tcp_dumbbell,
    )
    from tpudes.serving import StudyServer

    n_clients = 8 if smoke else SERVING_CLIENTS
    per_client = 4 if smoke else SERVING_STUDIES_PER_CLIENT
    prog = toy_dumbbell_program(n_flows=3, n_slots=SERVING_SLOTS)
    key = jax.random.PRNGKey(0)

    def study_prog(i):
        ids = _variant_point([VARIANTS[i % len(VARIANTS)]] * prog.n_flows)
        return dataclasses.replace(
            prog, variant_idx=ids, ecn=_variant_ecn(ids)
        )

    total = n_clients * per_client
    stream = [study_prog(i) for i in range(total)]
    RUNTIME.clear("dumbbell")
    run_tcp_dumbbell(stream[0], key, replicas=SERVING_REPLICAS)  # warm

    # --- baseline: serialized (but async-pipelined) submission -----------
    t0 = time.monotonic()
    futs = [
        RUNTIME.submit(run_tcp_dumbbell, p, key, SERVING_REPLICAS)
        for p in stream
    ]
    for f in futs:
        f.result()
    wall_serial = time.monotonic() - t0

    def closed_loop(slo_of=None):
        """One closed-loop pool run; returns (wall_s, metrics)."""
        ServingTelemetry.reset()
        server = StudyServer(
            max_wait_s=SERVING_MAX_WAIT_S,
            max_batch=SERVING_MAX_BATCH,
            retry_backoff_s=0.002,
            warm=[dict(engine="dumbbell", prog=stream[0], key=key,
                       replicas=SERVING_REPLICAS)],
        )

        def client(c):
            for j in range(per_client):
                h = server.submit_study(
                    "dumbbell", stream[c * per_client + j], key,
                    SERVING_REPLICAS, tenant=f"tenant{c}",
                    slo=slo_of(c) if slo_of else "standard",
                )
                h.result(timeout=300)

        t0 = time.monotonic()
        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        metrics = server.metrics()
        server.close()
        return wall, metrics

    # --- coalesced serving: closed-loop client pool ----------------------
    wall_served, metrics = closed_loop()

    # --- degraded: same pool under injected failures + SLO classes -------
    # (ISSUE-13) a seed-keyed schedule plants launch-shaped errors on
    # early dispatches; every affected batch recovers via requeue/retry
    chaos.arm(chaos.ChaosSchedule([
        chaos.ChaosEvent("launch_error", "local_launch", nth=n)
        for n in (2, 5, 9)
    ]))
    try:
        wall_degraded, m_deg = closed_loop(
            slo_of=lambda c: "gold" if c < max(1, n_clients // 4)
            else "standard"
        )
    finally:
        chaos.disarm()
    fail = m_deg["failures"]
    slo = m_deg["slo"]

    eng = metrics["engines"]["dumbbell"]
    return dict(
        requests=total,
        clients=n_clients,
        smoke=smoke,
        rps_serialized=round(total / wall_serial, 1),
        rps_coalesced=round(total / wall_served, 1),
        coalesced_speedup=round(wall_serial / wall_served, 3),  # >= 2 target
        launches=eng["launches"],
        coalesced_launches=eng["coalesced_launches"],
        coalesce_rate=metrics["coalesce_rate"],
        batch_occupancy=eng["batch_occupancy"],
        latency_p50_ms=round(eng["study_latency_s"]["p50"] * 1e3, 2),
        latency_p99_ms=round(eng["study_latency_s"]["p99"] * 1e3, 2),
        launch_p99_ms=round(eng["launch_wall_s"]["p99"] * 1e3, 2),
        # --- ISSUE-13: failure-injection + SLO-attainment columns -------
        injected_failures=fail["injected_failures"],
        requeued_studies=fail["requeued_studies"],
        retry_budget_exhausted=fail["retry_budget_exhausted"],
        rps_degraded=round(total / wall_degraded, 1),
        degraded_speedup=round(wall_serial / wall_degraded, 3),  # >= 1.5
        slo_attainment={
            name: s["attainment"] for name, s in slo.items()
        },
        gold_p99_ms=round(
            slo.get("gold", {}).get("latency_s", {}).get("p99", 0.0)
            * 1e3, 2,
        ),
    )


def bench_fuzz_throughput(smoke: bool = False):
    """ISSUE-8 row: differential-fuzz harness cost.  Runs a small
    fixed-seed campaign (2 scenarios per engine, every cross-mode
    oracle pair, the host-DES pair on the first scenario of each
    engine; --smoke halves it and skips the host pair) and reports
    scenarios/s per engine from the FuzzTelemetry snapshot — so the
    safety net's price is tracked alongside the engine rates it
    protects.  A non-zero divergence count here is a red flag worth
    more than any rate."""
    from tpudes.fuzz.harness import run_campaign
    from tpudes.obs.fuzz import FuzzTelemetry

    per_engine = 1 if smoke else 2
    host_every = 0 if smoke else 2
    from tpudes.fuzz.engines import ENGINE_FUZZERS

    result = run_campaign(
        budget=len(ENGINE_FUZZERS) * per_engine,
        host_every=host_every,
        artifacts_dir="fuzz_artifacts",
    )
    snap = FuzzTelemetry.snapshot()
    return dict(
        scenarios=result.scenarios,
        host_every=host_every,
        smoke=smoke,
        wall_s=round(result.wall_s, 3),
        scenarios_per_s={
            eng: e["scenarios_per_s"] for eng, e in snap["engines"].items()
        },
        pair_runs=snap["counters"]["pair_runs"],
        divergences=snap["counters"]["divergences"],
    )


def bench_hybrid_weak_scaling(max_ranks: int = 2, smoke: bool = False):
    """ISSUE-9 row: hybrid PDES weak scaling — fixed work per rank.

    Every rank count runs the SAME engine (the space-lane hybrid,
    ``transport="batched"``) on a structurally identical per-rank block
    (:func:`tpudes.parallel.wired.wired_weak_chain`) under the SAME
    bounded window cadence (``window_slots`` = the boundary lookahead),
    so the rows isolate what adding rank lanes costs from what the
    window protocol costs.  Aggregate throughput = ranks x horizon
    sim-s / wall-s; the acceptance bar is the 2-rank aggregate >= 1.6x
    the 1-rank row on the CPU reference shape (lanes amortize the
    per-window dispatch + D2H + demux that dominate at sparse shapes).

    Measurement is PAIRED: the rank counts are interleaved round-robin
    and each pair contributes one ratio, so hypervisor throttling
    phases hit all rows alike; the row reports the MEDIAN ratio with
    min/max spread (this box's unpaired walls drift ±40%)."""
    import statistics

    import jax

    from tpudes.obs.distributed import DistributedTelemetry
    from tpudes.parallel.hybrid import run_hybrid
    from tpudes.parallel.wired import wired_weak_chain

    n_slots = 18_000 if smoke else 108_000
    period = 601 if smoke else 3573
    # the window cadence (= the boundary lookahead) picks the regime:
    # finer windows raise the K-shared protocol share of the wall, so
    # rank lanes amortize more — the reference shape runs 180 windows
    boundary = 1200 if smoke else 600
    cross = 1481 if smoke else 8793
    pairs = 3 if smoke else 9
    rank_counts = [k for k in (1, 2, 4) if k <= max(2, int(max_ranks))]
    key = jax.random.key(7)

    progs = {
        k: wired_weak_chain(
            k, links_per_rank=2, period=period, n_slots=n_slots,
            boundary_delay=boundary, cross_period=cross,
        )
        for k in rank_counts
    }

    def once(k):
        t0 = time.monotonic()
        out = run_hybrid(
            progs[k], key, replicas=1, transport="batched",
            window_slots=boundary,
        )
        return time.monotonic() - t0, out

    DistributedTelemetry.reset()
    windows = {k: once(k)[1]["windows"] for k in rank_counts}  # warm
    walls: dict[int, list] = {k: [] for k in rank_counts}
    for _ in range(pairs):
        for k in rank_counts:
            walls[k].append(once(k)[0])

    med = {k: statistics.median(walls[k]) for k in rank_counts}
    rows = {}
    for k in rank_counts:
        ratios = [
            (k * w1) / wk for w1, wk in zip(walls[1], walls[k])
        ]
        rows[str(k)] = dict(
            wall_med_s=round(med[k], 4),
            windows=windows[k],
            agg_sim_s_per_wall_s=round(
                k * n_slots * progs[k].slot_s / med[k], 1
            ),
            ratio_vs_1rank=round(statistics.median(ratios), 3),
            ratio_min=round(min(ratios), 3),
            ratio_max=round(max(ratios), 3),
        )
    return dict(
        transport="batched",
        window_slots=boundary,
        lookahead_slots=boundary,
        per_rank=dict(
            links=2, flows=3, n_slots=n_slots, period=period,
        ),
        pairs=pairs,
        smoke=smoke,
        telemetry=DistributedTelemetry.snapshot()["counters"],
        ranks=rows,
    )


def _distributed_mesh_worker(pmesh, n_replicas, n_slots):
    """One member process of the ``distributed_mesh`` row: run this
    process's contiguous replica block with the GLOBAL offset — the
    ``fold_in(key, r)`` purity contract makes the block bit-identical
    to the same rows of one big launch (module-level so the spawn
    start method can pickle it by reference)."""
    import jax

    from tpudes.parallel.wired import run_wired, wired_chain

    lo, hi = pmesh.slice_bounds(n_replicas)
    prog = wired_chain(n_links=4, n_flows=2, n_slots=n_slots,
                       jitter_slots=3)
    key = jax.random.key(11)
    run_wired(prog, key, replicas=hi - lo, replica_offset=lo)  # warm
    t0 = time.monotonic()
    out = run_wired(prog, key, replicas=hi - lo, replica_offset=lo)
    wall = time.monotonic() - t0
    return dict(
        lo=lo,
        hi=hi,
        wall_s=wall,
        global_devices=jax.device_count(),
        local_devices=jax.local_device_count(),
        deliver=out["deliver_slot"],
    )


def bench_distributed_mesh(n_procs: int = 2, smoke: bool = False):
    """ISSUE-9 row: the replica axis over N ``jax.distributed``
    processes (the multi-process mesh path of
    :mod:`tpudes.parallel.procmesh`).  CPU CI exercises the
    process-sliced contract — each member runs its contiguous replica
    block at the global offset and the stitched result must be
    BIT-equal to the single-process launch (asserted here, not just
    reported); on TPU/GPU the same worker takes the global-mesh path.
    The row reports per-process walls, the stitched aggregate
    replicas/s, and the global/local device counts the procmesh smoke
    pins (global = members x local)."""
    import jax
    import numpy as np

    from tpudes.parallel.procmesh import launch_process_mesh
    from tpudes.parallel.wired import run_wired, wired_chain

    n_replicas = 4 if smoke else 8
    n_slots = 300 if smoke else 1200
    outs = launch_process_mesh(
        _distributed_mesh_worker, n_procs, args=(n_replicas, n_slots),
        timeout_s=300.0,
    )
    stitched = np.concatenate([o["deliver"] for o in outs], axis=0)
    prog = wired_chain(n_links=4, n_flows=2, n_slots=n_slots,
                       jitter_slots=3)
    ref = run_wired(prog, jax.random.key(11), replicas=n_replicas)
    bit_equal = bool((stitched == ref["deliver_slot"]).all())
    if not bit_equal:
        raise AssertionError(
            "distributed_mesh: stitched member blocks diverged from the "
            "single-process launch — the replica_offset purity contract "
            "is broken"
        )
    wall = max(o["wall_s"] for o in outs)
    return dict(
        processes=n_procs,
        replicas=n_replicas,
        slices=[[o["lo"], o["hi"]] for o in outs],
        global_devices=outs[0]["global_devices"],
        local_devices=outs[0]["local_devices"],
        wall_max_s=round(wall, 4),
        replicas_per_s=round(n_replicas / wall, 2),
        bit_equal=bit_equal,
        smoke=smoke,
    )


def bench_tcp():
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.parallel.tcp_dumbbell import lower_dumbbell, run_tcp_dumbbell
    from tpudes.scenarios import build_dumbbell

    reset_world()
    _, sinks = build_dumbbell(TCP_FLOWS, TCP_HOST_S, variant="TcpCubic")
    # --- denominator: real TcpSocketBase over the scalar engine ----------
    t0 = time.monotonic()
    Simulator.Stop(Seconds(TCP_HOST_S))
    Simulator.Run()
    host_wall = time.monotonic() - t0
    host_rx = sum(s.GetTotalRx() for s in sinks)
    reset_world()
    host_rate = TCP_HOST_S / host_wall

    # --- numerator: packet-slot engine, median of N_TIMED -----------------
    build_dumbbell(TCP_FLOWS, TCP_SIM_S, variant="TcpCubic")
    prog = lower_dumbbell(TCP_SIM_S)
    run_tcp_dumbbell(prog, jax.random.PRNGKey(0), replicas=TCP_REPLICAS)
    walls, mbps = [], 0.0
    for i in range(N_TIMED):
        t0 = time.monotonic()
        out = run_tcp_dumbbell(
            prog, jax.random.PRNGKey(1 + i), replicas=TCP_REPLICAS
        )
        walls.append(time.monotonic() - t0)
        mbps += float(out["goodput_mbps"].sum(1).mean())
    med = statistics.median(walls)
    rate = TCP_REPLICAS * TCP_SIM_S / med
    import numpy as np

    return dict(
        sim_s_per_wall_s=rate,
        vs_scalar=rate / host_rate,
        wall_median_s=med,
        wall_min_s=min(walls),
        wall_max_s=max(walls),
        scalar_sim_s_per_wall_s=host_rate,
        scalar_goodput_mbps=host_rx * 8 / TCP_HOST_S / 1e6,
        agg_goodput_mbps=mbps / N_TIMED,
        # tpudes.obs device accumulators (last timed run, per-replica)
        obs_drops_per_replica=float(
            np.asarray(out["drops"]).sum(axis=1).mean()
        ),
        obs_mean_queue_pkts=float(np.asarray(out["mean_queue"]).mean()),
    )


def bench_as():
    import jax

    from tpudes.core import Seconds, Simulator
    from tpudes.core.world import reset_world
    from tpudes.parallel.as_flows import lower_as_flows, run_as_flows
    from tpudes.scenarios import build_as_network

    reset_world()
    _, servers = build_as_network(AS_NODES, AS_FLOWS, AS_HOST_S, seed=3)
    prog = lower_as_flows(AS_SIM_S)
    # --- denominator: one host packet-level run of the same graph --------
    t0 = time.monotonic()
    Simulator.Stop(Seconds(AS_HOST_S))
    Simulator.Run()
    host_wall = time.monotonic() - t0
    host_rx = sum(s.received for s in servers)
    reset_world()
    host_studies_per_s = 1.0 / host_wall

    # --- numerator: flow engine, median of N_TIMED ------------------------
    run_as_flows(prog, jax.random.PRNGKey(0), replicas=AS_REPLICAS)
    walls, frac = [], 0.0
    for i in range(N_TIMED):
        t0 = time.monotonic()
        out = run_as_flows(
            prog, jax.random.PRNGKey(1 + i), replicas=AS_REPLICAS
        )
        walls.append(time.monotonic() - t0)
        frac += float(out["delivered_frac"].mean())
    med = statistics.median(walls)
    rate = AS_REPLICAS / med
    return dict(
        studies_per_s=rate,
        vs_scalar=rate / host_studies_per_s,
        wall_median_s=med,
        wall_min_s=min(walls),
        wall_max_s=max(walls),
        scalar_studies_per_s=host_studies_per_s,
        scalar_rx_pkts=host_rx,
        delivered_frac=frac / N_TIMED,
    )


# --- per-engine mesh strong scaling (the MULTICHIP rows) ----------------

MESH_TIMED = 3


def _mesh_programs(smoke: bool):
    """Per-engine device programs for the strong-scaling rows — the
    shared synthetic builders (tpudes/parallel/programs.py, also the
    test_runtime fixtures), no host object graph, so the multichip
    driver can emit the rows cheaply on any backend.  ``smoke`` shrinks
    every shape for the CI virtual-device job."""
    from tpudes.parallel.programs import (
        toy_as_program,
        toy_bss_program,
        toy_dumbbell_program,
        toy_lte_program,
    )

    bss = toy_bss_program(
        n_sta=8 if smoke else 32,
        sim_end_us=100_000 if smoke else 1_000_000,
    )
    lte = toy_lte_program(
        *((2, 8) if smoke else (7, 70)),
        n_ttis=200 if smoke else 2000,
    )
    tcp = toy_dumbbell_program(
        n_flows=4 if smoke else 8, n_slots=400 if smoke else 10_000
    )
    asp = toy_as_program(
        n_nodes=128 if smoke else 2000,
        n_flows=8 if smoke else 64,
        spf_rounds=16 if smoke else 32,
    )
    return bss, lte, tcp, asp


def bench_mesh(smoke: bool = False, n_devices: int | None = None):
    """Per-engine strong scaling: the SAME device program at the same
    replica count on a 1-device mesh vs the full mesh.  Emits, per
    engine, sim-s/wall-s (studies/s for the AS flow engine) on both
    configurations, the speedup, and the XLA compile count each
    configuration paid (CompileTelemetry delta) — the rows the
    MULTICHIP harness records."""
    import jax

    from tpudes.obs.device import CompileTelemetry
    from tpudes.parallel.as_flows import run_as_flows
    from tpudes.parallel.lte_sm import run_lte_sm
    from tpudes.parallel.mesh import replica_mesh
    from tpudes.parallel.replicated import run_replicated_bss
    from tpudes.parallel.runtime import RUNTIME
    from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

    n_dev = len(jax.devices()) if n_devices is None else n_devices
    bss, lte, tcp, asp = _mesh_programs(smoke)
    r_scale = 2 * n_dev if smoke else None  # full: the BENCH replica counts

    engines = [
        (
            "bss",
            lambda key, mesh, r, **kw: run_replicated_bss(
                bss, r, key, mesh=mesh, **kw
            ),
            r_scale or WIFI_REPLICAS,
            (bss.sim_end_us / 1e6, "sim-s/wall-s"),
        ),
        (
            "lte_sm",
            lambda key, mesh, r, **kw: run_lte_sm(
                lte, key, replicas=r, mesh=mesh, **kw
            ),
            r_scale or LTE_REPLICAS,
            (lte.n_ttis / 1000.0, "sim-s/wall-s"),
        ),
        (
            "dumbbell",
            lambda key, mesh, r, **kw: run_tcp_dumbbell(
                tcp, key, replicas=r, mesh=mesh, **kw
            ),
            r_scale or TCP_REPLICAS,
            (tcp.n_slots * tcp.slot_s, "sim-s/wall-s"),
        ),
        (
            "as_flows",
            lambda key, mesh, r, **kw: run_as_flows(
                asp, key, replicas=r, mesh=mesh, **kw
            ),
            r_scale or AS_REPLICAS,
            (1.0, "studies/s"),  # one study = one replica outcome
        ),
    ]

    rows = {}
    for name, runner, replicas, (per_replica, unit) in engines:
        row = {"replicas": replicas, "unit": unit}
        for label, mesh in (("1dev", replica_mesh(1)), ("ndev", replica_mesh(n_dev))):
            # each mesh configuration pays (and records) its own
            # compiles: jit re-specializes per input sharding even on a
            # runner-cache hit, so the honest count needs a cold cache
            RUNTIME.clear(name)
            c0 = CompileTelemetry.compiles(name)
            runner(jax.random.PRNGKey(0), mesh, replicas)  # compile + warm
            # the timed repetitions ride the async submission window:
            # launch i+1 is dispatched while i's D2H/unpack drains, so
            # the row measures pipelined steady-state throughput (the
            # wall below is the per-run mean of the pipelined batch)
            t0 = time.monotonic()
            futs = [
                RUNTIME.submit(runner, jax.random.PRNGKey(1 + i), mesh,
                               replicas)
                for i in range(MESH_TIMED)
            ]
            for f in futs:
                f.result()
            # renamed from wall_median_s_*: this is the per-run MEAN of
            # a pipelined batch, not a median of blocking walls — the
            # new key keeps old MULTICHIP rows from being compared
            # against it as like-for-like
            mean = (time.monotonic() - t0) / MESH_TIMED
            row[f"wall_mean_s_{label}"] = round(mean, 4)
            row[f"rate_{label}"] = round(replicas * per_replica / mean, 3)
            row[f"compiles_{label}"] = CompileTelemetry.compiles(name) - c0
        row["speedup"] = round(row["rate_ndev"] / row["rate_1dev"], 3)
        row["pipelined"] = True
        rows[name] = row
    return {"n_devices": n_dev, "smoke": smoke, "rows": rows}


def bench_mesh_sweep(smoke: bool = True, n_devices: int | None = None):
    """CI row: a 2-point config-axis scheduler sweep executed as ONE
    launch on the full virtual mesh — the megabatch and the replica
    sharding composed (the `--mesh --smoke` job asserts this emits)."""
    import jax

    from tpudes.parallel.lte_sm import run_lte_sm
    from tpudes.parallel.mesh import replica_mesh
    from tpudes.parallel.runtime import RUNTIME

    n_dev = len(jax.devices()) if n_devices is None else n_devices
    _, lte, _, _ = _mesh_programs(smoke)
    mesh = replica_mesh(n_dev)
    replicas = 2 * n_dev if smoke else LTE_REPLICAS
    scheds = ["pf", "rr"]
    RUNTIME.clear("lte_sm")
    l0 = RUNTIME.launches("lte_sm")
    run_lte_sm(lte, jax.random.PRNGKey(0), replicas=replicas, mesh=mesh,
               schedulers=scheds)  # compile + warm
    t0 = time.monotonic()
    out = run_lte_sm(lte, jax.random.PRNGKey(1), replicas=replicas,
                     mesh=mesh, schedulers=scheds)
    wall = time.monotonic() - t0
    return dict(
        points=len(scheds),
        replicas=replicas,
        n_devices=n_dev,
        launches=RUNTIME.launches("lte_sm") - l0,  # 2 (warm + timed)
        wall_s=round(wall, 4),
        rate=round(len(scheds) * replicas * lte.n_ttis / 1000.0 / wall, 3),
        agg_rx_bits=[int(p["rx_bits"].sum()) for p in out],
    )


def main():
    import jax

    wifi = bench_wifi()
    wifi_ht = bench_wifi_ht()
    mobile_bss = bench_mobile_bss()
    traffic_burst = bench_traffic_burst()
    lte = bench_lte()
    lte_mobility = bench_lte_mobility()
    lte_profile = bench_lte_kernel_profile()
    lte_sweep = bench_lte_sched_sweep()
    tcp = bench_tcp()
    tcp_sweep = bench_tcp_variant_sweep()
    asn = bench_as()
    sweep_vec = bench_sweep_vectorized()
    pipeline = bench_pipeline_overlap()
    serving = bench_serving_closed_loop()
    fuzz = bench_fuzz_throughput()
    grad_cal = bench_grad_calibration()
    # honest-metric caveat (VERDICT r4 weak #6): the AS ratio compares a
    # host packet-level integration to a converged fluid fixed point —
    # different study definitions; the comparable number is studies/s
    asn["metric_note"] = (
        "studies/s; host study = packet-level integration of "
        f"{AS_HOST_S} sim-s, device study = converged fluid fixed point "
        "— vs_scalar compares different study definitions"
    )
    r3 = lambda d: {  # noqa: E731
        k: (round(v, 3) if isinstance(v, float) else v) for k, v in d.items()
    }
    from tpudes.obs.device import CompileTelemetry

    out = {
        "metric": (
            "scenario sim-seconds per wall-second, replica engine "
            f"(BSS {N_STAS} STA x {WIFI_REPLICAS} replicas)"
        ),
        "value": round(wifi["sim_s_per_wall_s"], 1),
        "unit": "sim-s/wall-s",
        # engine-vs-engine: same scenario through DefaultSimulatorImpl
        "vs_baseline": round(wifi["vs_scalar"], 1),
        "wifi": r3(wifi),
        "wifi_ht": r3(wifi_ht),
        "lte": r3(lte),
        # ISSUE-10 rows: moving topologies on the device engines —
        # mobile rate vs the host-geometry-refresh baseline (>= 5x)
        # and vs the static-geometry wall (<= 1.5x), with the
        # geometry-refresh counters that prove which regime ran
        "mobile_bss": r3(mobile_bss),
        "lte_mobility": r3(lte_mobility),
        # ISSUE-14 row: the device-resident traffic stage — bursty vs
        # CBR wall overhead (<= 1.5x), the one-launch 8-point mixed
        # workload sweep with its launch/compile/demux pins, and the
        # workload telemetry naming which models ran
        "traffic_burst": r3(traffic_burst),
        # ISSUE-6: per-stage timing of the fused TTI kernel chain — the
        # row that says WHERE the LTE budget goes (dominating stage,
        # fusion ratio, per-launch TTI ceiling)
        "lte_kernel_profile": lte_profile,
        "lte_sched_sweep": r3(lte_sweep),
        "tcp": r3(tcp),
        "tcp_variant_sweep": r3(tcp_sweep),
        "as": r3(asn),
        # ISSUE-5 rows: one-launch (C,R,…) megabatch vs per-point
        # dispatch, and async-submission overlap on a heterogeneous
        # sweep (one-launch must be >= per-point on every platform)
        "sweep_vectorized": sweep_vec,
        "pipeline_overlap": pipeline,
        # ISSUE-7 row: closed-loop multi-tenant serving — requests/s at
        # bounded p99, coalesced StudyServer vs serialized submission
        # of the same study stream (>= 2x is the acceptance bar)
        "serving_closed_loop": serving,
        # ISSUE-8 row: scenarios/s per engine through the differential
        # fuzz harness (every oracle pair) — the cost of the safety net
        "fuzz_throughput": fuzz,
        # ISSUE-15 row: gradient-based calibration — loss-vs-iteration
        # of the one-compile descent loop (planted propagation
        # exponent recovered by L-BFGS-lite) plus the one-launch
        # grad-of-sweep pin and the GradTelemetry snapshot
        "grad_calibration": grad_cal,
        # ISSUE-9 rows: hybrid space-parallel weak scaling (fixed work
        # per PDES rank, paired measurement) and the replica axis over
        # N jax.distributed processes (bit-equal process slicing)
        "hybrid_weak_scaling": bench_hybrid_weak_scaling(max_ranks=4),
        "distributed_mesh": bench_distributed_mesh(),
        # tpudes.obs compile telemetry: per-engine XLA compile count +
        # wall time over the whole bench process (sweeps must not add
        # compiles — the single-executable property as a metric)
        "obs_compile": CompileTelemetry.snapshot(),
        "devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
    # strong-scaling rows whenever more than one device is visible (the
    # single-device rows above are measured first, so this section
    # cannot perturb them)
    if len(jax.devices()) > 1:
        out["mesh_scaling"] = bench_mesh()
    print(json.dumps(out))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="emit ONLY the per-engine 1-vs-N-device strong-scaling rows",
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="tiny shapes for the CI virtual-device job (with --mesh)",
    )
    ap.add_argument(
        "--ranks",
        type=int,
        default=0,
        help=(
            "emit ONLY the hybrid weak-scaling row up to N PDES ranks "
            "plus the N-process distributed mesh row (ISSUE-9)"
        ),
    )
    args = ap.parse_args()
    if args.ranks:
        print(json.dumps({
            "hybrid_weak_scaling": bench_hybrid_weak_scaling(
                max_ranks=args.ranks, smoke=args.smoke
            ),
            "distributed_mesh": bench_distributed_mesh(
                n_procs=max(2, min(args.ranks, 4)), smoke=args.smoke
            ),
        }))
    elif args.mesh:
        print(json.dumps({
            "mesh_scaling": bench_mesh(smoke=args.smoke),
            "mesh_config_sweep": bench_mesh_sweep(smoke=args.smoke),
            # the serving row rides the CI artifact too, so the
            # closed-loop metric is asserted present on every run
            "serving_closed_loop": bench_serving_closed_loop(
                smoke=args.smoke
            ),
            # ISSUE-8: harness cost rides the CI artifact (and any
            # divergence found by even this tiny budget fails loudly
            # in the asserted row)
            "fuzz_throughput": bench_fuzz_throughput(smoke=args.smoke),
            # ISSUE-9: the hybrid weak-scaling row rides the mesh
            # artifact so rank-lane scaling is asserted on every run
            "hybrid_weak_scaling": bench_hybrid_weak_scaling(
                max_ranks=2, smoke=args.smoke
            ),
            # ISSUE-10: the mobile-BSS row (with geometry counters)
            # rides the CI artifact so device-resident mobility is
            # asserted on every run
            "mobile_bss": bench_mobile_bss(smoke=args.smoke),
            # ISSUE-14: the traffic-stage row (burst overhead, the
            # one-launch workload sweep, workload telemetry) rides the
            # CI artifact so the traffic subsystem is asserted on
            # every run
            "traffic_burst": bench_traffic_burst(smoke=args.smoke),
            # ISSUE-15: the calibration row (one-compile descent,
            # planted-parameter recovery, one-launch grad sweep) rides
            # the CI artifact so differentiable simulation is asserted
            # on every run
            "grad_calibration": bench_grad_calibration(smoke=args.smoke),
        }))
    else:
        main()
