"""Fuzz envelopes and the seeded scenario generator.

The generative half of :mod:`tpudes.fuzz` (ROADMAP item 5): every
device engine front-end declares a :class:`FuzzEnvelope` — the
parameter region inside which its lowering is *documented* to be
faithful (topology/geometry bounds, traffic shapes, scheduler/variant
ids, horizons, replica counts) — and :class:`ScenarioGen` turns ONE
integer seed into an in-envelope configuration dict by deriving every
draw from a ``fold_in``-keyed PRNG stream (the QuickCheck "corpus entry
is a seed" property: a scenario is reproduced from its integer alone,
no state files).

This module is deliberately standalone (no engine imports): the engine
front-ends import :class:`FuzzEnvelope` from here to declare their
``FUZZ_ENVELOPE``, and the rest of :mod:`tpudes.fuzz` imports the
engines — keeping the dependency arrow one-directional.

Axis kinds:

- ``("int", lo, hi)``      — inclusive integer range
- ``("float", lo, hi)``    — half-open float range
- ``("choice", (a, b, …))`` — finite set (ids, categorical knobs)

``floors`` names the shrink floors of the axes the auto-shrinker may
reduce (replicas, horizon, population sizes); an axis absent from
``floors`` is never shrunk below its envelope minimum.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

__all__ = ["FuzzEnvelope", "ScenarioGen", "FUZZ_ROOT_SEED"]

#: root of every fuzz PRNG stream: scenario ``seed`` is folded into
#: PRNGKey(FUZZ_ROOT_SEED), and each subsequent draw folds in a draw
#: counter — so a corpus entry is the single integer ``seed``
FUZZ_ROOT_SEED = 0x7D0DE5


class ScenarioGen:
    """Deterministic draw stream for one scenario seed.

    Draw ``i`` is a pure function of ``(FUZZ_ROOT_SEED, seed, i)`` via
    two ``fold_in`` hops — the same keying discipline the engines use
    for replica/step randomness, so the generator inherits their
    reproducibility story (and RNG001's single-use-key rule: every draw
    consumes a fresh fold)."""

    def __init__(self, seed: int):
        import jax

        self.seed = int(seed)
        self._key = jax.random.fold_in(
            jax.random.PRNGKey(FUZZ_ROOT_SEED), self.seed
        )
        self._draws = 0

    def _next_key(self):
        import jax

        k = jax.random.fold_in(self._key, self._draws)
        self._draws += 1
        return k

    def randint(self, lo: int, hi: int) -> int:
        """Uniform integer in [lo, hi] (inclusive both ends)."""
        import jax

        return int(
            jax.random.randint(self._next_key(), (), int(lo), int(hi) + 1)
        )

    def uniform(self, lo: float = 0.0, hi: float = 1.0) -> float:
        import jax

        return float(
            jax.random.uniform(
                self._next_key(), (), minval=float(lo), maxval=float(hi)
            )
        )

    def choice(self, seq):
        seq = tuple(seq)
        return seq[self.randint(0, len(seq) - 1)]


@dataclass(frozen=True)
class FuzzEnvelope:
    """One engine's documented-faithful parameter region.

    ``axes`` maps configuration keys to axis specs (module docstring);
    :meth:`draw` samples a config dict from a :class:`ScenarioGen`,
    :meth:`contains` checks a (possibly shrunk or hand-edited) config
    against the region — shrunk configs may fall below envelope minima
    down to ``floors``, which :meth:`contains` honors."""

    engine: str
    axes: Mapping[str, tuple]
    floors: Mapping[str, int] = field(default_factory=dict)
    doc: str = ""

    def draw(self, gen: ScenarioGen) -> dict:
        """Sample every axis in declaration order (order is part of the
        seed→config contract: reordering axes changes every corpus
        entry, so axes are append-only within a corpus generation)."""
        cfg: dict = {}
        for name, spec in self.axes.items():
            kind = spec[0]
            if kind == "int":
                cfg[name] = gen.randint(spec[1], spec[2])
            elif kind == "float":
                cfg[name] = round(gen.uniform(spec[1], spec[2]), 6)
            elif kind == "choice":
                cfg[name] = gen.choice(spec[1])
            else:  # pragma: no cover - envelope author error
                raise ValueError(f"unknown axis kind {kind!r} for {name!r}")
        return cfg

    def contains(self, cfg: Mapping) -> list[str]:
        """Axis names at which ``cfg`` leaves the (floor-extended)
        envelope; empty means in-envelope."""
        out: list[str] = []
        for name, spec in self.axes.items():
            if name not in cfg:
                out.append(name)
                continue
            v = cfg[name]
            kind = spec[0]
            if kind == "int":
                lo = min(spec[1], self.floors.get(name, spec[1]))
                if not (isinstance(v, int) and lo <= v <= spec[2]):
                    out.append(name)
            elif kind == "float":
                lo = min(spec[1], self.floors.get(name, spec[1]))
                if not (
                    isinstance(v, (int, float)) and lo <= v <= spec[2]
                ):
                    out.append(name)
            elif kind == "choice" and v not in spec[1]:
                out.append(name)
        return out
