"""The fuzz campaign driver: pair orchestration, auto-shrinking, replay.

One *scenario* is an in-envelope config drawn from its engine's
``FUZZ_ENVELOPE`` by the seed alone; :func:`run_scenario` lowers it
once, runs the canonical scalar launch, and checks every oracle pair
against that canonical result:

===================  =========================================  ========
pair                 contract                                   strength
===================  =========================================  ========
chunked_vs_single    donated-carry segment handoff              exact
swept_vs_point       config-axis megabatch point 0              exact
bucketing_off        pow2 replica padding                       exact
mesh_vs_single       virtual-mesh replica sharding              exact¹
serving_vs_solo      StudyServer coalescing demux               exact
pallas_vs_xla        LTE fused-kernel lowerings (LTE only)      exact
bf16_budget          LTE mixed-precision budget (LTE only)      budget
device_geom_off      carried vs precomputed geometry (LTE)      exact
host_vs_device       host DES vs device engine                  fuzz band
===================  =========================================  ========

¹ the AS fluid float chain uses the documented GSPMD ULP tolerance.

On divergence the scenario is greedily shrunk (fewer replicas / UEs /
flows / nodes, shorter horizon, simpler topology) while the SAME pair
still reproduces, then a self-contained repro artifact lands under
``fuzz_artifacts/`` (see :mod:`tpudes.fuzz.artifact`).  All effort is
recorded in :class:`tpudes.obs.fuzz.FuzzTelemetry`.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from pathlib import Path

from tpudes.fuzz.artifact import (
    _CAPTURED_ENV,
    artifact_doc,
    load_artifact,
    write_artifact,
)
from tpudes.fuzz.engines import (
    ENGINE_FUZZERS,
    Divergence,
    EngineFuzzer,
    _env,
    _mesh_or_none,
    first_diff,
)
from tpudes.fuzz.envelope import ScenarioGen
from tpudes.obs.fuzz import FuzzTelemetry

__all__ = [
    "CROSS_MODE_PAIRS",
    "CampaignResult",
    "PAIR_HOST",
    "replay",
    "run_campaign",
    "run_scenario",
    "scenario_config",
    "shrink_divergence",
]

PAIR_HOST = "host_vs_device"
#: the exact device-side pairs every scenario runs (plus the engine's
#: extra_pairs); the host pair rides the ``host_every`` stride
CROSS_MODE_PAIRS = (
    "chunked_vs_single",
    "swept_vs_point",
    "bucketing_off",
    "mesh_vs_single",
    "serving_vs_solo",
)

#: sentinel for a pair that could not run in this environment (e.g.
#: the mesh pair on a single-device host) — not counted as coverage
_SKIPPED = object()


def scenario_config(engine: str, seed: int) -> dict:
    """The seed→config map: one integer reproduces the whole scenario."""
    return ENGINE_FUZZERS[engine].envelope.draw(ScenarioGen(seed))


def _serving_pair(fz: EngineFuzzer, prog, cfg, canonical):
    from tpudes.fuzz.engines import scenario_key
    from tpudes.serving import StudyServer

    engine, studies = fz.serving_studies(prog, cfg)
    # start=False: the deterministic single-thread mode — submit both
    # studies, then pump once so the scheduler sees them together and
    # coalesces onto one megabatched launch
    server = StudyServer(start=False, max_wait_s=0.0, max_batch=2)
    try:
        key = scenario_key(cfg)
        handles = [
            server.submit_study(engine, p, key, int(cfg["replicas"]), **kw)
            for p, kw in studies
        ]
        server.pump(force=True)
        res0 = handles[0].result(timeout=600)
    finally:
        server.close()
    return first_diff(canonical, res0, fields=fz.outcome_fields)


def _run_named_pair(fz: EngineFuzzer, name: str, prog, cfg, canonical,
                    mesh_devices: int = 2):
    """One oracle pair against the canonical scalar result; returns a
    first_diff dict, None (agreement), or ``_SKIPPED``."""
    if name == "chunked_vs_single":
        return first_diff(canonical, fz.run_chunked(prog, cfg, canonical))
    if name == "swept_vs_point":
        return first_diff(
            canonical, fz.run_sweep0(prog, cfg), fields=fz.outcome_fields
        )
    if name == "bucketing_off":
        with _env("TPUDES_BUCKETING", "0"):
            res = fz.run_scalar(prog, cfg)
        # outcome fields only: the padded replicas are real independent
        # sims, so the unpadded run's shared loop counter (BSS "steps")
        # may legitimately stop earlier — same caveat as the sweep's
        # shared step budget
        return first_diff(canonical, res, fields=fz.outcome_fields)
    if name == "mesh_vs_single":
        mesh = _mesh_or_none(mesh_devices)
        if mesh is None:
            return _SKIPPED
        res = fz.run_scalar(prog, cfg, mesh=mesh)
        return first_diff(
            canonical, res, fields=fz.outcome_fields,
            rtol=getattr(fz, "mesh_rtol", 0.0),
        )
    if name == "serving_vs_solo":
        return _serving_pair(fz, prog, cfg, canonical)
    if name == PAIR_HOST:
        host = fz.host_run(cfg)
        diff = fz.host_compare(host, canonical, cfg)
        if diff is not None and host.get("_flight_recorder"):
            diff = dict(diff, flight_recorder=host["_flight_recorder"])
        return diff
    for extra_name, fn in fz.extra_pairs():
        if extra_name == name:
            return fn(prog, cfg, canonical)
    raise ValueError(f"unknown oracle pair {name!r}")


def _pair_names(fz: EngineFuzzer, host: bool) -> list[str]:
    # an engine that does not implement every execution mode restricts
    # its exact-pair set (e.g. the wired engine has no config-sweep
    # axis, so swept/serving pairs cannot run there)
    names = list(
        fz.cross_mode_pairs
        if fz.cross_mode_pairs is not None
        else CROSS_MODE_PAIRS
    )
    names += [n for n, _ in fz.extra_pairs()]
    if host:
        names.append(PAIR_HOST)
    return names


def run_scenario(
    engine: str | EngineFuzzer,
    cfg: dict,
    *,
    host: bool = False,
    mesh_devices: int = 2,
    pairs=None,
    record: bool = True,
) -> list[Divergence]:
    """Build + lower the scenario once, then run every oracle pair;
    returns the divergences (empty list = clean scenario)."""
    fz = ENGINE_FUZZERS[engine] if isinstance(engine, str) else engine
    names = list(pairs) if pairs is not None else _pair_names(fz, host)
    prog = fz.build(cfg)
    canonical = fz.run_scalar(prog, cfg)
    out: list[Divergence] = []
    for name in names:
        diff = _run_named_pair(fz, name, prog, cfg, canonical,
                               mesh_devices=mesh_devices)
        if diff is _SKIPPED:
            continue
        if record:
            FuzzTelemetry.record_pair(fz.name, name, diff is not None)
        if diff is not None:
            out.append(Divergence(fz.name, name, diff, config=dict(cfg)))
    return out


def _replay_pair(fz: EngineFuzzer, pair: str, cfg: dict,
                 mesh_devices: int = 2):
    """Re-run exactly one pair on a (possibly shrunk/edited) config;
    returns a first_diff dict, None (agreement), or ``_SKIPPED`` when
    the pair cannot run in this environment."""
    prog = fz.build(cfg)
    canonical = fz.run_scalar(prog, cfg)
    return _run_named_pair(fz, pair, prog, cfg, canonical,
                           mesh_devices=mesh_devices)


def shrink_divergence(
    fz: EngineFuzzer,
    div: Divergence,
    *,
    max_iters: int = 48,
    mesh_devices: int = 2,
):
    """Greedy auto-shrink: try each of the engine's shrink moves in
    order; keep any strictly-smaller config on which the SAME oracle
    pair still diverges, restart the scan from it, stop when no move
    reproduces (or the iteration budget runs out).  Returns
    ``(shrunk_config, shrunk_diff, iterations)``."""
    cfg, diff = dict(div.config), div.diff
    iters = 0
    progressed = True
    while progressed and iters < max_iters:
        progressed = False
        for _label, cand in fz.shrink_moves(cfg):
            if iters >= max_iters:
                break
            iters += 1
            try:
                d = _replay_pair(fz, div.pair, cand,
                                 mesh_devices=mesh_devices)
            except Exception:
                # a shrink that breaks the build/lowering is not a
                # smaller reproduction — discard the candidate
                d = None
            if d is _SKIPPED:  # pair ran at detection, so can't occur
                d = None       # mid-shrink — but never misread a skip
            if d is not None:
                cfg, diff = dict(cand), d
                progressed = True
                break
    return cfg, diff, iters


@dataclass
class CampaignResult:
    """What one :func:`run_campaign` did."""

    scenarios: int = 0
    divergences: list = field(default_factory=list)   # artifact docs
    artifact_paths: list = field(default_factory=list)
    wall_s: float = 0.0

    @property
    def clean(self) -> bool:
        return not self.divergences


def run_campaign(
    engines=None,
    *,
    budget: int | None = None,
    seconds: float | None = None,
    base_seed: int = 0,
    host_every: int = 3,
    artifacts_dir: str | Path = "fuzz_artifacts",
    mesh_devices: int = 2,
    shrink: bool = True,
    log=None,
) -> CampaignResult:
    """Round-robin the engines over seeds ``base_seed, base_seed+1, …``
    until ``budget`` scenarios ran (or ``seconds`` elapsed).  Every
    scenario runs the full cross-mode pair set; the host-DES pair runs
    on every ``host_every``-th scenario of each engine (0 disables it).
    Divergences are shrunk and written as artifacts; telemetry is reset
    at entry so :meth:`FuzzTelemetry.snapshot` describes this campaign.
    """
    FuzzTelemetry.reset()
    names = list(engines) if engines else list(ENGINE_FUZZERS)
    for n in names:
        if n not in ENGINE_FUZZERS:
            raise ValueError(
                f"unknown engine {n!r} (have {sorted(ENGINE_FUZZERS)})"
            )
    if budget is None and seconds is None:
        budget = 12
    result = CampaignResult()
    t0 = time.monotonic()
    per_engine: dict[str, int] = {}
    i = 0
    while True:
        if budget is not None and i >= budget:
            break
        if seconds is not None and time.monotonic() - t0 >= seconds:
            break
        fz = ENGINE_FUZZERS[names[i % len(names)]]
        seed = base_seed + i
        cfg = fz.envelope.draw(ScenarioGen(seed))
        k = per_engine.get(fz.name, 0)
        per_engine[fz.name] = k + 1
        host = host_every > 0 and (k % host_every == 0)
        t1 = time.monotonic()
        divs = run_scenario(fz, cfg, host=host, mesh_devices=mesh_devices)
        FuzzTelemetry.record_scenario(fz.name, time.monotonic() - t1)
        for div in divs:
            if shrink:
                scfg, sdiff, iters = shrink_divergence(
                    fz, div, mesh_devices=mesh_devices
                )
                FuzzTelemetry.record_shrink(fz.name, iters)
            else:
                scfg, sdiff, iters = dict(div.config), div.diff, 0
            flight = None
            if isinstance(sdiff, dict) and "flight_recorder" in sdiff:
                sdiff = dict(sdiff)
                flight = sdiff.pop("flight_recorder")
            doc = artifact_doc(
                fz.name, seed, div.pair, scfg, sdiff,
                original_config=dict(cfg), shrink_iterations=iters,
                flight_recorder=flight,
            )
            path = write_artifact(artifacts_dir, doc)
            result.divergences.append(doc)
            result.artifact_paths.append(path)
            if log:
                log(f"DIVERGENCE {fz.name}/{div.pair} seed={seed} -> {path}")
        if log:
            log(
                f"[{i + 1}] {fz.name} seed={seed} "
                f"pairs={'clean' if not divs else len(divs)} "
                f"({time.monotonic() - t1:.1f}s)"
            )
        result.scenarios += 1
        i += 1
    result.wall_s = time.monotonic() - t0
    return result


@contextlib.contextmanager
def _envs(env: dict):
    with contextlib.ExitStack() as stack:
        for k, v in env.items():
            stack.enter_context(_env(k, v))
        yield


def replay(
    source,
    engine: str | None = None,
    *,
    mesh_devices: int = 2,
    host: bool = False,
) -> list[Divergence]:
    """Replay an artifact (path or loaded dict) or a bare integer seed.

    - **repro artifact** (has ``pair``): re-run exactly the recorded
      pair on the recorded config under the recorded env knobs; the
      returned divergence (if any) carries the fresh first_diff so the
      caller can check bit-identical reproduction against the artifact.
    - **corpus entry / seed**: run the full cross-mode pair set (plus
      the host pair when ``host``) and expect it clean; a corpus entry
      may restrict itself to the pairs its seed was chosen to exercise
      via a ``pairs`` list.

    Returns the divergences found (empty = clean / not reproduced).
    """
    if isinstance(source, (str, Path)) and not str(source).isdigit():
        doc = load_artifact(source)
    elif isinstance(source, dict):
        doc = source
    else:
        if engine is None:
            raise ValueError("--replay <seed> needs an engine")
        doc = {"engine": engine, "seed": int(source)}
    if doc["engine"] not in ENGINE_FUZZERS:
        raise ValueError(
            f"unknown engine {doc['engine']!r} "
            f"(have {sorted(ENGINE_FUZZERS)})"
        )
    fz = ENGINE_FUZZERS[doc["engine"]]
    cfg = doc.get("config")
    if cfg is None:
        cfg = fz.envelope.draw(ScenarioGen(int(doc["seed"])))
    bad = fz.envelope.contains(cfg)
    if bad:
        raise ValueError(
            f"artifact config leaves the {fz.name} envelope at {bad}"
        )
    # apply the artifact's env knobs AND unset every captured knob the
    # artifact does NOT record — an ambient TPUDES_PALLAS=0 (or a
    # leftover planted-bug export) must not corrupt the "bit-identical
    # reproduction" verdict of an artifact found without it
    env: dict = {k: None for k in _CAPTURED_ENV}
    env.update(doc.get("env", {}))
    with _envs(env):
        if doc.get("pair"):
            diff = _replay_pair(fz, doc["pair"], cfg,
                                mesh_devices=mesh_devices)
            if diff is _SKIPPED:
                raise ValueError(
                    f"oracle pair {doc['pair']!r} cannot run in this "
                    f"environment (the mesh pair needs >= {mesh_devices} "
                    "visible devices) — replay where the artifact was "
                    "recorded"
                )
            if diff is None:
                return []
            return [Divergence(fz.name, doc["pair"], diff, config=cfg)]
        return run_scenario(
            fz, cfg, host=host or bool(doc.get("host")),
            mesh_devices=mesh_devices, record=False,
            pairs=doc.get("pairs"),
        )
