"""Differential fuzzing CLI.

Usage::

    python -m tpudes.fuzz [--engine E ...] [--budget N | --seconds S]
                          [--seed BASE] [--host-every K]
                          [--artifacts DIR] [--metrics PATH]
                          [--mesh-devices N] [--no-shrink] [--quiet]
    python -m tpudes.fuzz --replay <artifact.json | SEED> [--engine E]

Exit codes: 0 = every oracle pair agreed (or the replayed repro
artifact reproduced, which is that mode's success); 1 = a fresh
divergence was found (artifacts written) or a repro artifact did NOT
reproduce; 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudes.fuzz",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--engine", action="append", default=None,
        help="restrict to one or more engines "
             "(bss / lte_sm / dumbbell / as_flows; repeatable)",
    )
    ap.add_argument("--budget", type=int, default=None,
                    help="number of scenarios to run (default 12)")
    ap.add_argument("--seconds", type=float, default=None,
                    help="run scenarios until this much wall time elapsed")
    ap.add_argument("--seed", type=int, default=0,
                    help="base scenario seed (scenario i uses seed+i)")
    ap.add_argument("--host-every", type=int, default=3,
                    help="host-DES oracle stride per engine (0 disables)")
    ap.add_argument("--artifacts", default="fuzz_artifacts",
                    help="divergence artifact directory")
    ap.add_argument("--metrics", default=None,
                    help="write the FuzzTelemetry snapshot JSON here")
    ap.add_argument("--mesh-devices", type=int, default=2,
                    help="devices for the mesh oracle pair (skipped when "
                         "fewer are visible)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="emit artifacts without auto-shrinking")
    ap.add_argument("--quiet", action="store_true")
    ap.add_argument("--replay", default=None, metavar="ARTIFACT|SEED",
                    help="replay one artifact (or corpus entry, or bare "
                         "seed with --engine) instead of fuzzing")
    args = ap.parse_args(argv)

    if args.budget is not None and args.seconds is not None:
        ap.print_usage(sys.stderr)
        print("--budget and --seconds are exclusive", file=sys.stderr)
        return 2

    log = (lambda *a: None) if args.quiet else print

    if args.replay is not None:
        return _replay(args, log)

    from tpudes.fuzz.harness import run_campaign
    from tpudes.obs.fuzz import FuzzTelemetry

    try:
        result = run_campaign(
            args.engine,
            budget=args.budget,
            seconds=args.seconds,
            base_seed=args.seed,
            host_every=args.host_every,
            artifacts_dir=args.artifacts,
            mesh_devices=args.mesh_devices,
            shrink=not args.no_shrink,
            log=log,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    snap = FuzzTelemetry.snapshot()
    if args.metrics:
        with open(args.metrics, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
    c = snap["counters"]
    log(
        f"fuzz: {c['scenarios']} scenarios, {c['pair_runs']} oracle-pair "
        f"runs, {c['divergences']} divergences in {result.wall_s:.1f}s"
    )
    if result.divergences:
        for p in result.artifact_paths:
            print(f"divergence artifact: {p}", file=sys.stderr)
        return 1
    return 0


def _replay(args, log) -> int:
    from tpudes.fuzz.artifact import ARTIFACT_KIND_REPRO, load_artifact
    from tpudes.fuzz.harness import replay

    src = args.replay
    doc = None
    if not str(src).isdigit():
        try:
            doc = load_artifact(src)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"{src}: unreadable artifact ({e})", file=sys.stderr)
            return 2
    engine = (args.engine or [None])[0]
    try:
        divs = replay(
            doc if doc is not None else int(src),
            engine=engine,
            mesh_devices=args.mesh_devices,
        )
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    expects_repro = bool(doc and doc.get("kind") == ARTIFACT_KIND_REPRO
                         and doc.get("pair"))
    if expects_repro:
        if not divs:
            print("repro artifact did NOT reproduce", file=sys.stderr)
            return 1
        from tpudes.fuzz.artifact import _jsonable

        # compare through the artifact's own JSON normalization: the
        # fresh diff may hold tuples/np scalars/NaN where the loaded
        # one has lists/floats ("NaN" == "NaN" serialized, while
        # nan != nan under dict equality)
        norm = lambda d: json.dumps(_jsonable(d), sort_keys=True)  # noqa: E731
        fresh = divs[0].diff
        recorded = doc.get("first_diff")
        if norm(fresh) == norm(recorded):
            log(f"reproduced bit-identically: {divs[0].render()}")
            return 0
        print(
            "diverged, but not bit-identically to the artifact:\n"
            f"  recorded: {recorded}\n  fresh:    {fresh}",
            file=sys.stderr,
        )
        return 1
    if divs:
        for d in divs:
            print(d.render(), file=sys.stderr)
        return 1
    log("replay clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
