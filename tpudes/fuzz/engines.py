"""Per-engine fuzz hooks: config → object graph → lowered program,
oracle-pair runners, host-DES oracles, and shrink moves.

One :class:`EngineFuzzer` per device engine.  Every scenario config is
a plain JSON dict drawn from the engine's ``FUZZ_ENVELOPE`` (declared
next to the engine it describes); the fuzzer builds the *live object
graph* through the canonical :mod:`tpudes.scenarios` builders and
lowers it — so the device program under test is exactly the scenario
the host DES runs, and the lowering guards (``Unliftable*Error``)
enforce the envelope by construction.

Oracle pairs come in two strengths:

- **exact** cross-mode pairs — chunked horizon, config-axis sweep
  point, bucketing off, virtual-mesh sharding, serving coalescing,
  and (LTE) Pallas-vs-XLA: the documented bit-equality contracts of
  the runtime (tests/test_sweep.py pins them at hand-picked configs;
  the fuzzer generalizes them to the whole envelope);
- **tolerance** pairs — host DES vs device at *fuzz* tolerances
  (wider than the pinned parity tests: random in-envelope configs sit
  away from the hand-tuned regimes, and this oracle exists to catch
  gross semantic divergence, not to re-pin the documented bounds), and
  the LTE bf16 precision budget.
"""

from __future__ import annotations

import contextlib
import os
import warnings
from dataclasses import dataclass, field

import numpy as np

__all__ = [
    "Divergence",
    "ENGINE_FUZZERS",
    "EngineFuzzer",
    "first_diff",
    "scenario_key",
]


@dataclass
class Divergence:
    """One oracle-pair disagreement, ready for artifact emission."""

    engine: str
    pair: str
    #: first differing field/index: {"field", "index", "lhs", "rhs"}
    diff: dict
    message: str = ""
    config: dict = field(default_factory=dict)

    def render(self) -> str:
        d = self.diff
        at = f"[{', '.join(str(i) for i in d.get('index', ()))}]"
        return (
            f"{self.engine}/{self.pair}: {d.get('field')}{at} "
            f"{d.get('lhs')} != {d.get('rhs')}"
            + (f" ({self.message})" if self.message else "")
        )


def _as_comparable(v):
    a = np.asarray(v)
    return a if a.dtype != object else None


def first_diff(a: dict, b: dict, fields=None, rtol=0.0, atol=0.0):
    """First field (sorted order) and index at which the two result
    trees differ — ``None`` when they agree.  ``rtol/atol == 0`` is the
    bit-equality mode (integer counters compare exactly; float fields
    compare by equality including NaN position).  ``fields=None``
    compares the key UNION: a mode that silently drops (or invents) a
    result field is a divergence, not an agreement."""
    keys = sorted(fields if fields is not None else set(a) | set(b))
    for k in keys:
        if k not in a or k not in b:
            # index is ALWAYS a list (every branch): artifacts JSON
            # round-trip, and replay checks fresh == recorded equality
            return {"field": k, "index": [], "lhs": k in a, "rhs": k in b}
        x, y = _as_comparable(a[k]), _as_comparable(b[k])
        if x is None or y is None:
            continue
        if x.shape != y.shape:
            return {
                "field": k, "index": [],
                "lhs": list(x.shape), "rhs": list(y.shape),
            }
        if rtol == 0.0 and atol == 0.0:
            neq = ~(
                (x == y)
                | (np.isnan(x) & np.isnan(y))
                if np.issubdtype(x.dtype, np.floating)
                else (x == y)
            )
        else:
            xf = x.astype(np.float64)
            yf = y.astype(np.float64)
            neq = ~(
                np.isclose(xf, yf, rtol=rtol, atol=atol)
                | (np.isnan(xf) & np.isnan(yf))
            )
        neq = np.asarray(neq)
        if neq.any():
            idx = tuple(int(i) for i in np.argwhere(neq)[0])
            lhs = x[idx] if idx else x[()]
            rhs = y[idx] if idx else y[()]
            return {
                "field": k,
                "index": list(idx),
                "lhs": lhs.item() if hasattr(lhs, "item") else lhs,
                "rhs": rhs.item() if hasattr(rhs, "item") else rhs,
            }
    return None


def scenario_key(cfg: dict):
    """The scenario's device PRNG key (the ``key_seed`` axis)."""
    import jax

    return jax.random.PRNGKey(int(cfg.get("key_seed", 0)))


def _reset_world():
    from tpudes.core.world import reset_world

    reset_world()


def _recorder_entries():
    """Flight-recorder tail of the just-finished host run — present
    only under ``TpudesObs=1`` (the recorder exists only then); rides
    the host oracle summary into divergence artifacts."""
    from tpudes.core.simulator import Simulator

    rec = getattr(
        getattr(Simulator._impl, "_obs", None), "recorder", None
    )
    return rec.to_dicts() if rec is not None else None


@contextlib.contextmanager
def _env(name: str, value: str | None):
    """Temporarily set/unset one env knob (the per-call-read toggles:
    TPUDES_BUCKETING, TPUDES_PALLAS)."""
    old = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


@contextlib.contextmanager
def _quiet_lowering():
    """The fuzz envelopes intentionally include short horizons; the
    engines' compile-amortization / warm-up advisories are for humans
    picking one config, not a generator sweeping thousands."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        yield


def _mesh_or_none(n_devices: int = 2):
    """A small replica mesh when >1 device is visible (the fuzz mesh
    pair deliberately uses 2 devices: the pow2 replica bucket is then
    already a multiple of the device count, so the sharded run reuses
    the unsharded executables for the input-sharded engines)."""
    import jax

    if len(jax.devices()) < n_devices:
        return None
    from tpudes.parallel.mesh import replica_mesh

    return replica_mesh(n_devices)


def _shrink_int(cfg, name, floor):
    """Halve one integer axis toward ``floor`` (None when already
    there) — the generic shrink move."""
    v = int(cfg[name])
    if v <= floor:
        return None
    nv = max(floor, v // 2)
    out = dict(cfg)
    out[name] = nv
    return out


def _shrink_choice(cfg, name, simplest):
    if cfg[name] == simplest:
        return None
    out = dict(cfg)
    out[name] = simplest
    return out


def _fuzz_traffic(cfg, n, horizon_us, rate_pps, start_us=0):
    """The drawn workload program (ISSUE-14 axes) for ``n`` entities,
    or None for the "off" draw.  ``tr_burst`` is the burstiness knob
    (ON-OFF off-time mean / mmpp state spread), ``tr_phase`` the
    diurnal-envelope phase (amp fixed at 0.35, one period per
    horizon); the realization seed is the scenario's ``key_seed`` so
    the workload is part of the one-integer reproduction story."""
    from tpudes.traffic import TrafficProgram

    model = cfg.get("traffic", "off")
    if model == "off":
        return None
    burst = float(cfg.get("tr_burst", 0.3))
    env = (0.35, horizon_us / 1e6, float(cfg.get("tr_phase", 0.0)))
    seed = int(cfg.get("key_seed", 0))
    if model == "cbr":
        iv = max(1, int(round(1e6 / max(rate_pps, 1e-6))))
        return TrafficProgram.cbr(
            np.broadcast_to(
                np.asarray(start_us, np.int32), (n,)
            ).copy(),
            iv,
        )
    if model == "mmpp":
        return TrafficProgram.mmpp(
            n, rate_pps, horizon_us=horizon_us, epoch_s=0.05,
            mult=(1.0 - burst, 1.0 + 2.0 * burst),
            switch_p=(0.4, 0.4), start_us=start_us, envelope=env,
            tr_seed=seed,
        )
    if model == "onoff":
        duty = 1.0 / (1.0 + burst / 0.2)  # on-mean 0.2 s vs off-mean
        return TrafficProgram.onoff(
            n, rate_pps / max(duty, 0.05), horizon_us=horizon_us,
            on=(1.5, 0.05, 1.0), off_mean_s=burst, start_us=start_us,
            envelope=env, tr_seed=seed,
        )
    # trace: a deterministic synthetic "empirical" table derived from
    # the scenario draws (no host RNG — the seed IS the trace)
    k = max(4, min(64, int(rate_pps * horizon_us / 1e6)))
    phase = float(cfg.get("tr_phase", 0.0))
    grid = (
        np.linspace(0.05 + 0.4 * phase / max(k, 1), 0.95, k)[None, :]
        * (horizon_us - int(np.max(start_us)))
        + np.asarray(start_us).reshape(-1, 1)
        + np.arange(n)[:, None] * 997
    ).astype(np.int64)
    sizes = (256 + 61 * ((seed + np.arange(n * k)) % 23)).reshape(n, k)
    return TrafficProgram.trace_replay(np.sort(grid, axis=1), sizes)


class EngineFuzzer:
    """Template for one engine's fuzz surface; subclasses fill in the
    build/run/host hooks.  ``outcome_fields`` is the sweep/serving
    comparison set (fields documented identical across launch modes);
    ``None`` means "every field".  ``cross_mode_pairs`` restricts the
    default exact-pair set for engines that do not implement every
    execution mode (None = the full ``harness.CROSS_MODE_PAIRS``)."""

    name: str = ""
    outcome_fields: tuple | None = None
    cross_mode_pairs: tuple | None = None

    @property
    def envelope(self):
        raise NotImplementedError

    # --- scenario construction -------------------------------------------

    def build(self, cfg: dict):
        """Fresh world → object graph → lowered program → fresh world."""
        raise NotImplementedError

    # --- device runs ------------------------------------------------------

    def run_scalar(self, prog, cfg, mesh=None):
        raise NotImplementedError

    def run_chunked(self, prog, cfg, canonical):
        raise NotImplementedError

    def run_sweep0(self, prog, cfg):
        """2-point config-axis sweep whose point 0 is the scenario
        itself; returns point 0's result."""
        raise NotImplementedError

    def serving_studies(self, prog, cfg):
        """(engine_name, [(prog_i, engine_kwargs_i)]) — two compatible
        studies whose FIRST is the scenario itself."""
        raise NotImplementedError

    # --- host oracle ------------------------------------------------------

    def host_run(self, cfg: dict) -> dict:
        raise NotImplementedError

    def host_compare(self, host: dict, dev: dict, cfg: dict):
        """Divergence diff dict (see :func:`first_diff`) or None."""
        raise NotImplementedError

    # --- engine-specific exact pairs -------------------------------------

    #: fields the ``traffic_off`` pair compares (None = key union) —
    #: engines whose traffic runs add result fields (LTE backlog/
    #: goodput) restrict to the common outcome set
    traffic_off_fields: tuple | None = None

    def neutral_traffic(self, prog):
        """A workload program pinned BIT-EQUAL to ``traffic=None`` on
        this engine (the cbr branch / a saturating fill), or None when
        the engine has no traffic seam.  Powers the ``traffic_off``
        exact oracle pair."""
        return None

    def _traffic_off_pair(self, prog, cfg, canonical):
        """ISSUE-14 exactness anchor: the engine with its traffic
        stage COMPILED IN but fed the neutral workload must match the
        legacy (traffic=None) path bit for bit — generalized over the
        whole envelope, whatever workload the scenario drew."""
        import dataclasses

        del canonical  # both sides are fresh runs
        neutral = self.neutral_traffic(prog)
        if neutral is None:
            return None
        off = self.run_scalar(
            dataclasses.replace(prog, traffic=None), cfg
        )
        neu = self.run_scalar(
            dataclasses.replace(prog, traffic=neutral), cfg
        )
        return first_diff(off, neu, fields=self.traffic_off_fields)

    def extra_pairs(self):
        """[(pair_name, fn(prog, cfg, canonical) -> diff|None), ...]
        Every engine carries the ``traffic_off`` pair; one without a
        traffic seam (``neutral_traffic`` → None) passes it
        trivially."""
        return [("traffic_off", self._traffic_off_pair)]

    # --- shrinking --------------------------------------------------------

    def shrink_moves(self, cfg: dict):
        """Ordered candidate shrinks: [(label, smaller_cfg), ...] —
        each strictly smaller along its axis; the greedy shrinker keeps
        any candidate that still reproduces the divergence."""
        floors = self.envelope.floors
        out = []
        for name in ("replicas",):
            c = _shrink_int(cfg, name, floors.get(name, 1))
            if c:
                out.append((f"halve {name}", c))
        if "sim_ms" in cfg:
            c = _shrink_int(cfg, "sim_ms", floors.get("sim_ms", 8))
            if c:
                out.append(("halve sim_ms", c))
        if "traffic" in cfg:
            # dropping the workload model is the single biggest
            # simplification a traffic-era divergence can take
            c = _shrink_choice(cfg, "traffic", "off")
            if c:
                out.append(("traffic -> off", c))
        return out


# ---------------------------------------------------------------------------
# BSS (replicated Wi-Fi)
# ---------------------------------------------------------------------------


class BssFuzzer(EngineFuzzer):
    name = "bss"
    #: ``steps`` is documented to differ under the sweep's shared step
    #: budget — outcomes are the cross-mode contract
    outcome_fields = ("srv_rx", "cli_rx", "tx_data", "drops", "all_done")

    @property
    def envelope(self):
        from tpudes.parallel.replicated import FUZZ_ENVELOPE

        return FUZZ_ENVELOPE

    def _graph(self, cfg):
        from tpudes.scenarios import build_bss

        return build_bss(
            n_stas=int(cfg["n_stas"]),
            sim_time=cfg["sim_ms"] / 1e3,
            radii=(float(cfg["radius"]),),
            interval_s=cfg["interval_ms"] / 1e3,
            packet_bytes=int(cfg["packet_bytes"]),
            mobility=str(cfg.get("mob_model", "static")),
            speed=float(cfg.get("mob_speed", 1.0)),
        )

    def build(self, cfg):
        import dataclasses

        from tpudes.parallel.replicated import lower_bss

        _reset_world()
        try:
            stas, ap, clients, _ = self._graph(cfg)
            with _quiet_lowering():
                prog = lower_bss(
                    [stas.Get(i) for i in range(int(cfg["n_stas"]))],
                    ap, clients, cfg["sim_ms"] / 1e3,
                    geom_stride=int(cfg.get("geom_stride", 1)),
                )
            # ISSUE-14: STA arrivals ride the drawn workload (the AP
            # row stays cbr at the beacon period); mean rate pinned to
            # the envelope's CBR load so offered stays in-region
            tp = _fuzz_traffic(
                cfg, prog.n, prog.sim_end_us,
                rate_pps=1000.0 / float(cfg["interval_ms"]),
                start_us=prog.start_us,
            )
            if tp is not None:
                tp = tp.with_cbr_rows(
                    np.arange(prog.n) == 0, prog.interval_us[0],
                    prog.start_us[0],
                )
                prog = dataclasses.replace(prog, traffic=tp)
            return prog
        finally:
            _reset_world()

    def run_scalar(self, prog, cfg, mesh=None):
        from tpudes.parallel.replicated import run_replicated_bss

        return run_replicated_bss(
            prog, int(cfg["replicas"]), scenario_key(cfg), mesh=mesh
        )

    def run_chunked(self, prog, cfg, canonical):
        from tpudes.parallel.replicated import run_replicated_bss

        # the BSS horizon is event steps: derive an off-boundary chunk
        # from the steps the scalar run actually took
        chunk = max(1, int(canonical["steps"]) // int(cfg["chunk_divisor"]) - 1)
        return run_replicated_bss(
            prog, int(cfg["replicas"]), scenario_key(cfg), chunk_steps=chunk
        )

    def run_sweep0(self, prog, cfg):
        from tpudes.parallel.replicated import run_replicated_bss

        ends = [int(prog.sim_end_us), max(1_300_000, prog.sim_end_us * 3 // 4)]
        return run_replicated_bss(
            prog, int(cfg["replicas"]), scenario_key(cfg), sim_end_us=ends
        )[0]

    def serving_studies(self, prog, cfg):
        import dataclasses

        return "bss", [
            (prog, {}),
            (dataclasses.replace(
                prog, sim_end_us=max(1_300_000, prog.sim_end_us * 3 // 4)
            ), {}),
        ]

    def host_run(self, cfg):
        from tpudes.core import Seconds, Simulator
        from tpudes.core.rng import RngSeedManager

        _reset_world()
        try:
            RngSeedManager.SetRun(int(cfg["rng_run"]))
            _, _, _, rx = self._graph(cfg)
            Simulator.Stop(Seconds(cfg["sim_ms"] / 1e3))
            Simulator.Run()
            out = {"srv_rx": int(rx[0])}
            fr = _recorder_entries()
            if fr:
                out["_flight_recorder"] = fr
            return out
        finally:
            _reset_world()

    def neutral_traffic(self, prog):
        from tpudes.traffic import TrafficProgram

        return TrafficProgram.cbr(prog.start_us, prog.interval_us)

    def host_compare(self, host, dev, cfg):
        # the host graph runs CBR echo apps: with a generative device
        # workload the two sides simulate DIFFERENT arrival processes
        # — host parity for those lives in the dedicated host-mirror
        # parity tests (and the traffic_off exact pair covers the
        # seam); the band below is the cbr-workload contract
        if cfg.get("traffic", "off") not in ("off", "cbr"):
            return None
        # one host RngRun draw against the device replica spread: the
        # fuzz band is the replica min/max widened by a timing-model +
        # Monte-Carlo slack proportional to the offered load (BSS host
        # parity is *statistical* — tests/test_replicated.py pins the
        # distribution-level contract; this band catches gross drift)
        rep = np.asarray(dev["srv_rx"], dtype=np.float64)
        offered = float(_bss_offered(cfg))
        slack = max(6.0, 0.35 * offered)
        lo, hi = rep.min() - slack, rep.max() + slack
        h = float(host["srv_rx"])
        if lo <= h <= hi:
            return None
        return {
            "field": "srv_rx", "index": [],
            "lhs": h, "rhs": [float(rep.min()), float(rep.max())],
        }

    def shrink_moves(self, cfg):
        out = super().shrink_moves(cfg)
        floors = self.envelope.floors
        c = _shrink_int(cfg, "n_stas", floors.get("n_stas", 1))
        if c:
            out.append(("halve n_stas", c))
        c = _shrink_choice(cfg, "interval_ms", 150)
        if c:
            out.append(("slowest traffic", c))
        return out


def _bss_offered(cfg) -> int:
    """Echo requests offered over the horizon (from the config alone)."""
    sim_ms = int(cfg["sim_ms"])
    iv = int(cfg["interval_ms"])
    n = 0
    for i in range(int(cfg["n_stas"])):
        start_ms = 1000 + i  # scenarios.build_bss: 1.0 s + 1 ms stagger
        if sim_ms > start_ms:
            n += (sim_ms - start_ms + iv - 1) // iv
    return n


# ---------------------------------------------------------------------------
# LTE (full-buffer RLC-SM)
# ---------------------------------------------------------------------------


class LteSmFuzzer(EngineFuzzer):
    name = "lte_sm"
    outcome_fields = None  # every field is bit-exact across modes

    @property
    def envelope(self):
        from tpudes.parallel.lte_sm import FUZZ_ENVELOPE

        return FUZZ_ENVELOPE

    def _graph(self, cfg):
        from tpudes.scenarios import build_lena

        return build_lena(
            n_enbs=int(cfg["n_enbs"]),
            ues_per_cell=int(cfg["ues_per_cell"]),
            scheduler=str(cfg["scheduler"]),
            inter_site=float(cfg["inter_site"]),
            layout=str(cfg["layout"]),
            drop_seed=int(cfg["drop_seed"]),
            mobility=str(cfg.get("mob_model", "static")),
            speed=float(cfg.get("mob_speed", 5.0)),
        )

    def build(self, cfg):
        import dataclasses

        from tpudes.parallel.lte_sm import lower_lte_sm

        _reset_world()
        try:
            lte, _ = self._graph(cfg)
            with _quiet_lowering():
                prog = lower_lte_sm(
                    lte, cfg["sim_ms"] / 1e3,
                    geom_stride=int(cfg.get("geom_stride", 1)),
                )
            # ISSUE-14: finite per-UE backlogs from the drawn workload
            # — only on STATIC drops (the engine rejects traffic +
            # mobility on one program; a mobile draw keeps full buffer)
            if prog.mobility is None:
                tp = _fuzz_traffic(
                    cfg, prog.n_ue, prog.n_ttis * 1000, rate_pps=120.0
                )
                if tp is not None:
                    tp = dataclasses.replace(
                        tp,
                        size_pareto=np.asarray(
                            [1.4, 800.0, 12000.0], np.float32
                        ),
                    )
                    prog = dataclasses.replace(prog, traffic=tp)
            return prog
        finally:
            _reset_world()

    def run_scalar(self, prog, cfg, mesh=None):
        from tpudes.parallel.lte_sm import run_lte_sm

        return run_lte_sm(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]), mesh=mesh
        )

    def run_chunked(self, prog, cfg, canonical):
        from tpudes.parallel.lte_sm import run_lte_sm

        chunk = max(1, prog.n_ttis // int(cfg["chunk_divisor"]) - 1)
        return run_lte_sm(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            chunk_ttis=chunk,
        )

    def run_sweep0(self, prog, cfg):
        from tpudes.parallel.lte_sm import run_lte_sm

        other = "rr" if prog.scheduler != "rr" else "pf"
        return run_lte_sm(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            schedulers=[prog.scheduler, other],
        )[0]

    def serving_studies(self, prog, cfg):
        import dataclasses

        other = "rr" if prog.scheduler != "rr" else "pf"
        return "lte_sm", [
            (prog, {}),
            (dataclasses.replace(prog, scheduler=other), {}),
        ]

    #: the common outcome set: a traffic run legitimately ADDS
    #: backlog_bits/goodput_bits/offered_bits, which the traffic=None
    #: side does not have
    traffic_off_fields = (
        "rx_bits", "new_tbs", "retx", "drops", "ok", "cqi", "mcs",
        "sinr",
    )

    def neutral_traffic(self, prog):
        """A saturating cbr fill (1 packet/µs at jumbo sizes): every
        backlog is non-empty from TTI 0, so the dynamic-eligible
        kernel must reproduce the full-buffer program bit for bit.
        None on mobile draws — the engine rejects traffic + mobility
        on one program, so there is no seam to pin there."""
        import dataclasses

        from tpudes.traffic import TrafficProgram

        if prog.mobility is not None:
            return None

        tp = TrafficProgram.cbr(
            np.zeros(prog.n_ue, np.int32),
            np.full(prog.n_ue, 1, np.int64),
        )
        return dataclasses.replace(
            tp,
            size_pareto=np.asarray([0.0, 20000.0, 20000.0], np.float32),
        )

    def extra_pairs(self):
        return super().extra_pairs() + [
            ("pallas_vs_xla", self._pallas_pair),
            ("bf16_budget", self._bf16_pair),
            ("device_geom_off", self._device_geom_pair),
        ]

    def _device_geom_pair(self, prog, cfg, canonical):
        # ISSUE-10: the TPUDES_DEVICE_GEOM=0 fallback runs the mobile
        # scan against HOST-precomputed refresh positions (the
        # per-window fresh-operands shape of the legacy controller
        # path) — pinned bit-equal to the carried geometry.  A static
        # draw has no geometry stage; the pair degenerates to a rerun
        # and still must agree bit for bit.
        with _env("TPUDES_DEVICE_GEOM", "0"):
            off = self.run_scalar(prog, cfg)
        return first_diff(canonical, off)

    def _pallas_pair(self, prog, cfg, canonical):
        # the two lowerings of the fused TTI chain are pinned
        # bit-identical per backend (tests/test_lte_pallas.py) — the
        # fuzzer extends the pin to every in-envelope geometry
        with _env("TPUDES_PALLAS", "0"):
            xla = self.run_scalar(prog, cfg)
        return first_diff(canonical, xla)

    def _bf16_pair(self, prog, cfg, canonical):
        import dataclasses

        from tpudes.parallel.lte_sm import run_lte_sm

        out = run_lte_sm(
            dataclasses.replace(prog, precision="bf16"),
            scenario_key(cfg), replicas=int(cfg["replicas"]),
        )
        f32_bits = float(np.asarray(canonical["rx_bits"]).sum())
        b16_bits = float(np.asarray(out["rx_bits"]).sum())
        if not np.isfinite(b16_bits):
            return {"field": "rx_bits", "index": [], "lhs": f32_bits,
                    "rhs": b16_bits}
        # fuzz budget: the pinned engine-level bf16 budget (≤10% at the
        # test geometry) widened for arbitrary in-envelope geometries
        if abs(b16_bits - f32_bits) > 0.15 * max(f32_bits, b16_bits, 1.0):
            return {"field": "rx_bits", "index": [], "lhs": f32_bits,
                    "rhs": b16_bits}
        dcqi = np.abs(
            np.asarray(out["cqi"], np.int64)
            - np.asarray(canonical["cqi"], np.int64)
        )
        if dcqi.max() > 1:
            idx = tuple(int(i) for i in np.argwhere(dcqi > 1)[0])
            return {
                "field": "cqi", "index": list(idx),
                "lhs": int(np.asarray(canonical["cqi"])[idx]),
                "rhs": int(np.asarray(out["cqi"])[idx]),
            }
        return None

    def host_run(self, cfg):
        from tpudes.core import Seconds, Simulator

        _reset_world()
        try:
            lte, _ = self._graph(cfg)
            Simulator.Stop(Seconds(cfg["sim_ms"] / 1e3))
            Simulator.Run()
            bits = sum(s["dl_rx_bytes"] for s in lte.GetRlcStats()) * 8
            out = {"rx_bits": int(bits)}
            fr = _recorder_entries()
            if fr:
                out["_flight_recorder"] = fr
            return out
        finally:
            _reset_world()

    def host_compare(self, host, dev, cfg):
        # the host controller runs RLC-SM full buffer: any finite-
        # backlog device workload simulates a different offered load —
        # the traffic_off exact pair covers the seam instead
        if cfg.get("traffic", "off") != "off" and cfg.get(
            "mob_model", "static"
        ) == "static":
            return None
        h = float(host["rx_bits"])
        d = float(np.asarray(dev["rx_bits"]).sum(axis=-1).mean())
        # pinned parity is rel 0.15 at the hand-tuned geometry; random
        # drops can park UEs at CQI boundaries where the documented
        # timing-model deviations bite harder — fuzz band 0.35
        if abs(h - d) <= 0.35 * max(h, d, 1.0) + 1e5:
            return None
        return {"field": "rx_bits", "index": [], "lhs": h, "rhs": d}

    def shrink_moves(self, cfg):
        out = super().shrink_moves(cfg)
        floors = self.envelope.floors
        for name in ("ues_per_cell", "n_enbs"):
            c = _shrink_int(cfg, name, floors.get(name, 1))
            if c:
                out.append((f"halve {name}", c))
        c = _shrink_choice(cfg, "scheduler", "pf")
        if c:
            out.append(("scheduler -> pf", c))
        c = _shrink_choice(cfg, "layout", "line")
        if c:
            out.append(("layout -> line", c))
        return out


# ---------------------------------------------------------------------------
# TCP dumbbell
# ---------------------------------------------------------------------------


class DumbbellFuzzer(EngineFuzzer):
    name = "dumbbell"
    outcome_fields = None

    @property
    def envelope(self):
        from tpudes.parallel.tcp_dumbbell import FUZZ_ENVELOPE

        return FUZZ_ENVELOPE

    def _variants(self, cfg) -> list[str]:
        from tpudes.parallel.tcp_dumbbell import VARIANTS

        n = int(cfg["n_flows"])
        if cfg["variant_mix"] == "homogeneous":
            return [cfg["variant"]] * n
        i0 = VARIANTS.index(cfg["variant"])
        return [VARIANTS[(i0 + i) % len(VARIANTS)] for i in range(n)]

    def _graph(self, cfg):
        from tpudes.scenarios import build_dumbbell

        return build_dumbbell(
            n_flows=int(cfg["n_flows"]),
            sim_time=cfg["sim_ms"] / 1e3,
            variants=self._variants(cfg),
            bottleneck_rate=f"{int(cfg['bottleneck_mbps'])}Mbps",
            bottleneck_delay=f"{int(cfg['bottleneck_delay_ms'])}ms",
            queue=f"{int(cfg['queue_pkts'])}p",
            seg_bytes=int(cfg["seg_bytes"]),
        )

    def build(self, cfg):
        import dataclasses

        from tpudes.parallel.tcp_dumbbell import lower_dumbbell

        _reset_world()
        try:
            self._graph(cfg)
            with _quiet_lowering():
                prog = lower_dumbbell(cfg["sim_ms"] / 1e3)
            # ISSUE-14: app-limited flows — mean offered ~70% of the
            # bottleneck's fair share, so the workload (not just the
            # window) shapes the dynamics without starving the queue
            fair_pps = (
                float(cfg["bottleneck_mbps"]) * 1e6
                / (8.0 * float(cfg["seg_bytes"]))
                / max(int(cfg["n_flows"]), 1)
            )
            tp = _fuzz_traffic(
                cfg, prog.n_flows, int(cfg["sim_ms"]) * 1000,
                rate_pps=0.7 * fair_pps,
            )
            if tp is not None:
                prog = dataclasses.replace(prog, traffic=tp)
            return prog
        finally:
            _reset_world()

    def run_scalar(self, prog, cfg, mesh=None):
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        return run_tcp_dumbbell(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]), mesh=mesh
        )

    def run_chunked(self, prog, cfg, canonical):
        from tpudes.parallel.tcp_dumbbell import run_tcp_dumbbell

        chunk = max(1, prog.n_slots // int(cfg["chunk_divisor"]) - 1)
        return run_tcp_dumbbell(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            chunk_slots=chunk,
        )

    def run_sweep0(self, prog, cfg):
        from tpudes.parallel.tcp_dumbbell import VARIANTS, run_tcp_dumbbell

        p0 = [VARIANTS[i] for i in np.asarray(prog.variant_idx)]
        p1 = ["TcpNewReno"] * prog.n_flows
        return run_tcp_dumbbell(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            variants=[p0, p1],
        )[0]

    def serving_studies(self, prog, cfg):
        import dataclasses

        from tpudes.parallel.tcp_dumbbell import (
            _variant_ecn,
            _variant_point,
        )

        pt = _variant_point(["TcpNewReno"] * prog.n_flows)
        return "dumbbell", [
            (prog, {}),
            (dataclasses.replace(
                prog, variant_idx=pt, ecn=_variant_ecn(pt)
            ), {}),
        ]

    def host_run(self, cfg):
        from tpudes.core import Seconds, Simulator

        _reset_world()
        try:
            _, sinks = self._graph(cfg)
            sim_s = cfg["sim_ms"] / 1e3
            Simulator.Stop(Seconds(sim_s))
            Simulator.Run()
            span = max(sim_s - 0.1, 1e-3)  # bulk apps start at 0.1 s
            mbps = sum(s.GetTotalRx() * 8.0 / span / 1e6 for s in sinks)
            out = {"goodput_mbps": float(mbps)}
            fr = _recorder_entries()
            if fr:
                out["_flight_recorder"] = fr
            return out
        finally:
            _reset_world()

    def neutral_traffic(self, prog):
        from tpudes.traffic import TrafficProgram

        # 1 segment/µs offered: the app never limits the window, so
        # the app-limit gate must reproduce the bulk program bit for
        # bit
        return TrafficProgram.cbr(
            np.zeros(prog.n_flows, np.int32),
            np.full(prog.n_flows, 1, np.int64),
        )

    def host_compare(self, host, dev, cfg):
        # the host graph runs bulk senders: an app-limited device
        # workload is a different offered load — the traffic_off exact
        # pair covers the seam instead
        if cfg.get("traffic", "off") != "off":
            return None
        h = float(host["goodput_mbps"])
        d = float(np.asarray(dev["goodput_mbps"]).sum(axis=-1).mean())
        cap = float(cfg["bottleneck_mbps"])
        # The pinned rel-0.25 parity (tests/test_tcp_dumbbell.py) holds
        # at the long-horizon low-BDP reference config.  In-envelope
        # high-BDP short-horizon shapes are transient-dominated — the
        # host's loss-recovery convergence takes whole seconds while
        # the slot model fills the pipe from slot 0 (measured rel up to
        # ~0.7 for NewReno at 5 Mbps / 20 ms / 0.9 s) — so the fuzz
        # band is a gross-divergence detector: shared-capacity bound,
        # progress, and a wide relative band.
        diff = {"field": "goodput_mbps", "index": [], "lhs": h, "rhs": d}
        if h > 1.05 * cap or d > 1.05 * cap:
            return diff  # exceeding the shared bottleneck is never right
        if int(cfg["sim_ms"]) > 400 and (h <= 0.0) != (d <= 0.0):
            return diff  # one engine moves traffic, the other is dead
        if abs(h - d) <= 0.75 * max(h, d) + 0.3:
            return None
        return diff

    def shrink_moves(self, cfg):
        out = super().shrink_moves(cfg)
        floors = self.envelope.floors
        c = _shrink_int(cfg, "n_flows", floors.get("n_flows", 1))
        if c:
            out.append(("halve n_flows", c))
        c = _shrink_choice(cfg, "variant_mix", "homogeneous")
        if c:
            out.append(("homogeneous variants", c))
        c = _shrink_choice(cfg, "variant", "TcpNewReno")
        if c:
            out.append(("variant -> NewReno", c))
        return out


# ---------------------------------------------------------------------------
# AS flows (fluid)
# ---------------------------------------------------------------------------


class AsFlowsFuzzer(EngineFuzzer):
    name = "as_flows"
    outcome_fields = None
    #: the fluid outcome chain is float; GSPMD re-rounds fusions under
    #: sharding — the documented mesh tolerance (tests/test_sweep.py)
    mesh_rtol = 2e-5

    @property
    def envelope(self):
        from tpudes.parallel.as_flows import FUZZ_ENVELOPE

        return FUZZ_ENVELOPE

    def _graph(self, cfg):
        from tpudes.scenarios import build_as_network

        return build_as_network(
            n_nodes=int(cfg["n_nodes"]),
            n_flows=int(cfg["n_flows"]),
            sim_time=cfg["sim_ms"] / 1e3,
            flow_kbps=float(cfg["flow_kbps"]),
            pkt_bytes=int(cfg["pkt_bytes"]),
            seed=int(cfg["topo_seed"]),
        )

    def build(self, cfg):
        import dataclasses

        from tpudes.parallel.as_flows import lower_as_flows

        _reset_world()
        try:
            self._graph(cfg)
            with _quiet_lowering():
                prog = lower_as_flows(cfg["sim_ms"] / 1e3)
            # ISSUE-14: the fluid engine consumes the workload's
            # realized/nominal rate multiplier per flow
            tp = _fuzz_traffic(
                cfg, len(prog.src), int(cfg["sim_ms"]) * 1000,
                rate_pps=float(cfg["flow_kbps"]) * 1e3
                / (8.0 * float(cfg["pkt_bytes"])),
            )
            if tp is not None:
                prog = dataclasses.replace(prog, traffic=tp)
            # ISSUE-15: "ste" compiles the straight-through surrogate
            # program — forward pinned bit-equal to the legacy engine
            # (pre-ISSUE-15 corpus configs lack the axis: off)
            if cfg.get("surrogate", "off") == "ste":
                from tpudes.diff.surrogate import Surrogacy

                prog = dataclasses.replace(
                    prog, surrogate=Surrogacy(ste=True)
                )
            return prog
        finally:
            _reset_world()

    def run_scalar(self, prog, cfg, mesh=None):
        from tpudes.parallel.as_flows import run_as_flows

        return run_as_flows(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]), mesh=mesh
        )

    def run_chunked(self, prog, cfg, canonical):
        from tpudes.parallel.as_flows import run_as_flows

        return run_as_flows(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            chunk_rounds=int(cfg["chunk_divisor"]),
        )

    def run_sweep0(self, prog, cfg):
        from tpudes.parallel.as_flows import run_as_flows

        return run_as_flows(
            prog, scenario_key(cfg), replicas=int(cfg["replicas"]),
            rate_scale=[1.0, 0.5],
        )[0]

    def serving_studies(self, prog, cfg):
        return "as_flows", [
            (prog, {"rate_scale": 1.0}),
            (prog, {"rate_scale": 0.5}),
        ]

    def host_run(self, cfg):
        from tpudes.core import Seconds, Simulator

        _reset_world()
        try:
            _, servers = self._graph(cfg)
            sim_s = cfg["sim_ms"] / 1e3
            Simulator.Stop(Seconds(sim_s))
            Simulator.Run()
            out = {"rx": [int(s.received) for s in servers]}
            fr = _recorder_entries()
            if fr:
                out["_flight_recorder"] = fr
            return out
        finally:
            _reset_world()

    def neutral_traffic(self, prog):
        from tpudes.traffic import TrafficProgram

        # any cbr program: the fluid multiplier is exactly 1.0 for the
        # cbr branch by construction
        return TrafficProgram.cbr(
            np.zeros(len(prog.src), np.int32),
            np.full(len(prog.src), 1000, np.int64),
        )

    def host_compare(self, host, dev, cfg):
        # the host graph runs constant-rate UdpClients: a generative
        # device workload offers a different load — the traffic_off
        # exact pair covers the seam (cbr's multiplier is exactly 1,
        # so the cbr draw keeps the band meaningful)
        if cfg.get("traffic", "off") not in ("off", "cbr"):
            return None
        sim_s = cfg["sim_ms"] / 1e3
        interval_s = int(cfg["pkt_bytes"]) * 8.0 / (cfg["flow_kbps"] * 1e3)
        expected = (sim_s - 0.05) / interval_s  # clients start at 0.05 s
        frac = np.asarray(dev["delivered_frac"]).mean(axis=0)  # (F,)
        rx = np.asarray(host["rx"], dtype=np.float64)
        # sparse-regime contract: where the fluid engine says a flow
        # delivers (frac ~ 1) the packet DES must deliver most of its
        # offered packets, and vice versa (multi-hop in-flight slack)
        for f in range(len(rx)):
            host_frac = rx[f] / max(expected, 1.0)
            if frac[f] > 0.95 and host_frac < 0.7:
                return {"field": "delivered_frac", "index": [f],
                        "lhs": host_frac, "rhs": float(frac[f])}
            if frac[f] < 0.5 and host_frac > 0.9:
                return {"field": "delivered_frac", "index": [f],
                        "lhs": host_frac, "rhs": float(frac[f])}
        return None

    def _surrogate_off_pair(self, prog, cfg, canonical):
        """ISSUE-15 exactness anchor: the straight-through surrogate
        program (hard forward, soft backward) must match the legacy
        (surrogate=None) engine bit for bit — generalized over the
        whole envelope, whatever surrogate the config drew.  The
        surrogate=None side IS the canonical run when the scenario
        drew 'off' (reused, not recomputed)."""
        import dataclasses

        from tpudes.diff.surrogate import Surrogacy

        off = canonical if prog.surrogate is None else self.run_scalar(
            dataclasses.replace(prog, surrogate=None), cfg
        )
        ste = self.run_scalar(
            dataclasses.replace(prog, surrogate=Surrogacy(ste=True)),
            cfg,
        )
        return first_diff(off, ste)

    def extra_pairs(self):
        return super().extra_pairs() + [
            ("surrogate_off", self._surrogate_off_pair)
        ]

    def shrink_moves(self, cfg):
        out = super().shrink_moves(cfg)
        floors = self.envelope.floors
        for name in ("n_flows", "n_nodes"):
            c = _shrink_int(cfg, name, floors.get(name, 1))
            if c:
                out.append((f"halve {name}", c))
        if cfg.get("surrogate", "off") != "off":
            c = _shrink_choice(cfg, "surrogate", "off")
            if c:
                out.append(("surrogate -> off", c))
        return out


# ---------------------------------------------------------------------------
# Wired graph (per-link queues — the hybrid-PDES partition unit)
# ---------------------------------------------------------------------------


class WiredFuzzer(EngineFuzzer):
    """The hybrid-capable wired engine: deterministic CBR over
    per-link queues, so every oracle pair here is EXACT (bit-equal
    timestamps) — including the ``hybrid_vs_host`` pair, which runs the
    full 2-rank granted-time-window protocol (in-process fabric: the
    identical advance/operand sequence the spawned-rank transport
    issues) against both the single-engine device run and the
    sequential host DES."""

    name = "wired"
    outcome_fields = ("deliver_slot", "delivered", "served")
    # no config-sweep axis on the wired engine (yet), so the swept /
    # serving pairs cannot run; chunking, bucketing and mesh sharding
    # all apply
    cross_mode_pairs = ("chunked_vs_single", "bucketing_off",
                        "mesh_vs_single")

    @property
    def envelope(self):
        from tpudes.parallel.wired import FUZZ_ENVELOPE

        return FUZZ_ENVELOPE

    def build(self, cfg):
        from tpudes.parallel.wired import wired_chain

        L = int(cfg["n_links"])
        return wired_chain(
            n_links=L,
            n_flows=int(cfg["n_flows"]),
            service=[1 + (i % int(cfg["max_service"])) for i in range(L)],
            period=int(cfg["period"]),
            n_slots=int(cfg["n_slots"]),
            ranks=2,
            boundary_delay=int(cfg["boundary_delay"]),
            jitter_slots=int(cfg["jitter"]),
        )

    def run_scalar(self, prog, cfg, mesh=None):
        from tpudes.parallel.wired import run_wired

        return run_wired(
            prog, scenario_key(cfg), int(cfg["replicas"]), mesh=mesh
        )

    def run_chunked(self, prog, cfg, canonical):
        from tpudes.parallel.wired import run_wired

        # an off-boundary window size, mimicking a mid-stream grant cut
        window = max(1, int(cfg["n_slots"]) // 3 - 1)
        return run_wired(
            prog, scenario_key(cfg), int(cfg["replicas"]),
            window_slots=window,
        )

    def _jitter_rows(self, prog, cfg):
        from tpudes.parallel.wired import _replica_jitter

        return np.asarray(_replica_jitter(
            prog, scenario_key(cfg), int(cfg["replicas"])
        ))

    def host_run(self, cfg):
        from tpudes.parallel.wired import run_wired_host

        prog = self.build(cfg)
        jit = self._jitter_rows(prog, cfg)
        # the host DES is cheap: run EVERY replica's jitter trajectory
        rows = [
            run_wired_host(prog, jitter=jit[r])
            for r in range(int(cfg["replicas"]))
        ]
        return dict(
            deliver_slot=np.stack([r["deliver_slot"] for r in rows]),
            served=np.stack([r["served"] for r in rows]),
        )

    def host_compare(self, host, dev, cfg):
        # deterministic model: the host DES and the device engine must
        # agree on every timestamp — exact, not a fuzz band
        return first_diff(
            {k: host[k] for k in ("deliver_slot", "served")},
            {k: np.asarray(dev[k]) for k in ("deliver_slot", "served")},
        )

    def extra_pairs(self):
        def hybrid_vs_host(prog, cfg, canonical):
            from tpudes.parallel.hybrid import run_hybrid

            hybrid = run_hybrid(
                prog, scenario_key(cfg), int(cfg["replicas"]),
                ranks=2, transport="local",
            )
            diff = first_diff(
                canonical, hybrid, fields=self.outcome_fields
            )
            if diff is not None:
                return diff
            host = self.host_run(cfg)
            return first_diff(
                {k: host[k] for k in ("deliver_slot", "served")},
                {k: np.asarray(hybrid[k]) for k in ("deliver_slot", "served")},
            )

        # super() keeps the base traffic_off pair on the roster (it
        # passes trivially until the wired engine grows a traffic
        # seam, at which point the oracle arms itself)
        return super().extra_pairs() + [
            ("hybrid_vs_host", hybrid_vs_host)
        ]

    def shrink_moves(self, cfg):
        out = super().shrink_moves(cfg)
        floors = self.envelope.floors
        for name in ("n_slots", "n_flows", "n_links"):
            c = _shrink_int(cfg, name, floors.get(name, 1))
            if c:
                out.append((f"halve {name}", c))
        c = _shrink_choice(cfg, "jitter", 0)
        if c:
            out.append(("no jitter", c))
        return out


#: engine name -> fuzzer (the registry the harness and CLI iterate)
ENGINE_FUZZERS: dict[str, EngineFuzzer] = {
    f.name: f
    for f in (BssFuzzer(), LteSmFuzzer(), DumbbellFuzzer(), AsFlowsFuzzer(),
              WiredFuzzer())
}
