"""Self-contained repro artifacts for fuzz divergences.

An artifact is one JSON document that reproduces a divergence from
nothing but the repo: the integer seed, the (shrunk) scenario config,
the failing oracle-pair name, the first-divergence field diff, the env
knobs that were live at detection (the planted-bug flag, Pallas /
bucketing overrides), and — when ``TpudesObs`` was on — the host
flight-recorder tail.  ``python -m tpudes.fuzz --replay <artifact>``
re-runs exactly the recorded pair under the recorded knobs and checks
the diff reproduces bit-identically.

Corpus entries (``tests/fuzz_corpus/``) use the same format with
``kind == "tpudes-fuzz-corpus"`` and no divergence fields: replaying
one runs the full cross-mode pair set and expects it clean.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

__all__ = [
    "ARTIFACT_KIND_CORPUS",
    "ARTIFACT_KIND_REPRO",
    "artifact_doc",
    "load_artifact",
    "write_artifact",
]

ARTIFACT_KIND_REPRO = "tpudes-fuzz-repro"
ARTIFACT_KIND_CORPUS = "tpudes-fuzz-corpus"

#: env knobs that change what a replay executes — captured at detection
#: time so a later ``--replay`` reconstructs the same modes without the
#: caller having to remember to export them
_CAPTURED_ENV = (
    "TPUDES_FUZZ_PLANTED_BUG",
    "TPUDES_PALLAS",
    "TPUDES_BUCKETING",
    "TPUDES_DEVICE_GEOM",
)


def _jsonable(v):
    if hasattr(v, "item"):
        return v.item()
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return v


def artifact_doc(
    engine: str,
    seed: int,
    pair: str,
    config: dict,
    first_diff: dict,
    original_config: dict | None = None,
    shrink_iterations: int = 0,
    flight_recorder=None,
) -> dict:
    import jax

    doc = {
        "version": 1,
        "kind": ARTIFACT_KIND_REPRO,
        "engine": engine,
        "seed": int(seed),
        "pair": pair,
        "config": _jsonable(config),
        "first_diff": _jsonable(first_diff),
        "shrink_iterations": int(shrink_iterations),
        "env": {
            k: os.environ[k] for k in _CAPTURED_ENV if k in os.environ
        },
        "meta": {
            "backend": jax.default_backend(),
            "devices": len(jax.devices()),
        },
    }
    if original_config is not None and original_config != config:
        doc["original_config"] = _jsonable(original_config)
    if flight_recorder:
        doc["flight_recorder"] = _jsonable(flight_recorder)
    return doc


def write_artifact(dirpath: str | Path, doc: dict) -> str:
    d = Path(dirpath)
    d.mkdir(parents=True, exist_ok=True)
    name = f"{doc['engine']}-{doc.get('pair', 'scenario')}-seed{doc['seed']}.json"
    path = d / name
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return str(path)


def load_artifact(path: str | Path) -> dict:
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict) or "engine" not in doc:
        raise ValueError(f"{path}: not a tpudes fuzz artifact")
    return doc
