"""tpudes.fuzz — property-based differential fuzzing with
auto-shrinking oracles across every execution mode.

The correctness harness of ROADMAP item 5: the host DES (the ns-3
lineage's semantic ground truth) and the runtime's documented
bit-equality contracts (chunking, sweeping, bucketing, mesh sharding,
serving coalescing, the LTE Pallas/precision modes) are *oracles*; a
seeded generator turns integers into in-envelope scenarios for all
four device engines and checks every pair.  On divergence the scenario
auto-shrinks while the failure reproduces and a self-contained repro
artifact lands under ``fuzz_artifacts/``.  Run as::

    python -m tpudes.fuzz --budget 40            # fixed scenario budget
    python -m tpudes.fuzz --engine lte_sm --seconds 60
    python -m tpudes.fuzz --replay fuzz_artifacts/dumbbell-…-seed17.json

Every engine front-end declares its documented-faithful region as a
``FUZZ_ENVELOPE`` (:class:`FuzzEnvelope`) next to its lowering guards;
``tests/fuzz_corpus/`` pins regression seeds replayed by tier-1.

This module stays import-light (the engine front-ends import
:class:`FuzzEnvelope` from :mod:`tpudes.fuzz.envelope` at module
scope); the harness surface loads lazily on first touch.
"""

from tpudes.fuzz.envelope import FUZZ_ROOT_SEED, FuzzEnvelope, ScenarioGen

__all__ = [
    "FUZZ_ROOT_SEED",
    "CampaignResult",
    "Divergence",
    "ENGINE_FUZZERS",
    "FuzzEnvelope",
    "ScenarioGen",
    "first_diff",
    "replay",
    "run_campaign",
    "run_scenario",
    "scenario_config",
    "shrink_divergence",
]

_HARNESS = {
    "CampaignResult", "replay", "run_campaign", "run_scenario",
    "scenario_config", "shrink_divergence",
}
_ENGINES = {"Divergence", "ENGINE_FUZZERS", "first_diff"}


def __getattr__(name: str):
    if name in _HARNESS:
        from tpudes.fuzz import harness

        return getattr(harness, name)
    if name in _ENGINES:
        from tpudes.fuzz import engines

        return getattr(engines, name)
    raise AttributeError(f"module 'tpudes.fuzz' has no attribute {name!r}")
