"""tpudes — a TPU-native discrete-event network simulation framework.

A from-scratch framework with the capabilities of ``ybaddi/ns-3-dev-dnemu``
(an ns-3 fork; see SURVEY.md): a discrete-event core with pluggable engines
behind the ``SimulatorImplementationType`` seam, an ns-3-class model library
(propagation, WiFi, LTE, internet stack, applications, mobility), and a
JAX/XLA execution backend (``JaxSimulatorImpl``) that evaluates the
high-fanout PHY math — propagation loss/delay, interference SNR, NIST
error-rate, LTE RB-grid SINR/BLER — as jit-compiled, vmapped kernels over
(node x link x replica) arrays in conservative time windows, with
Monte-Carlo replicas sharded across a TPU mesh.

Layout:
  core/      Simulator, Scheduler, Time, events, Object/TypeId/attributes,
             GlobalValue, Config, CommandLine, RNG streams, logging, tracing
             (reference parity: src/core/model/)
  network/   Packet, Node, NetDevice, Channel, Socket, Queue, ErrorModel,
             addresses (reference parity: src/network/model/)
  models/    propagation, mobility, spectrum, wifi, lte, internet, apps
             (reference parity: src/{propagation,mobility,spectrum,wifi,
             lte,internet,applications}/)
  ops/       pure jittable JAX kernels (the TPU compute path)
  parallel/  mesh/replica sharding, conservative-window PDES engine,
             LBTS collectives (reference parity: src/mpi/)
  helper/    topology-wiring helpers (reference parity: src/*/helper/)
  utils/     observability: flow monitor, pcap, stats, progress
"""

__version__ = "0.1.0"
