"""tpudes.chaos — deterministic failure injection for the serving fleet.

ISSUE 13: the fault-tolerance layer (requeue-on-death, retry budgets,
checkpoint/resume, SLO preemption) is regression-tested by *planting*
failures, not waiting for them.  A :class:`~tpudes.chaos.schedule.
ChaosSchedule` — derivable from one integer seed — is armed
process-globally here; the serving/transport stack calls :func:`fire`
/ :func:`filter_frame` / :func:`maybe_fail` at its injection sites and
the schedule decides, by deterministic per-site ordinals, when a
member dies, a frame corrupts, a launch OOMs, or a checkpointed run
aborts between chunks.  Nothing is injected unless a schedule is armed
(explicitly, or via ``TPUDES_CHAOS=<seed>`` — which spawned member
processes inherit), so production paths pay one ``is None`` check.

Replay: ``python -m tpudes.chaos --replay SEED`` re-runs the canonical
serving scenario under ``canonical_schedule(SEED, members)`` and
verifies every study completed; ``--check`` runs it twice and demands
bit-identical failure/recovery counters — the chaos analog of
``python -m tpudes.fuzz --replay``.
"""

from __future__ import annotations

import os
import time

from tpudes.chaos.schedule import (
    KINDS,
    SITES,
    ChaosEvent,
    ChaosSchedule,
    canonical_schedule,
)

__all__ = [
    "KINDS",
    "SITES",
    "ChaosEvent",
    "ChaosInjected",
    "ChaosSchedule",
    "arm",
    "armed",
    "canonical_schedule",
    "disarm",
    "filter_frame",
    "fire",
    "maybe_fail",
]


class ChaosInjected(RuntimeError):
    """A planted failure fired.  The serving layer treats this as a
    *transient* fault (retry/requeue under the retry budget), mirroring
    how a real launch-time OOM or preempted member would be handled."""


#: the armed schedule; None = chaos off (the production state)
_armed: ChaosSchedule | None = None
_env_checked = False


def arm(schedule: ChaosSchedule) -> ChaosSchedule:
    """Arm ``schedule`` process-globally (replacing any armed one)."""
    global _armed, _env_checked
    _armed = schedule
    _env_checked = True
    return schedule


def disarm() -> None:
    """Disarm (and forget any ``TPUDES_CHAOS`` env arming)."""
    global _armed, _env_checked
    _armed = None
    _env_checked = True


def reset() -> None:
    """Test isolation: drop the armed schedule AND re-read the env on
    the next :func:`armed` call."""
    global _armed, _env_checked
    _armed = None
    _env_checked = False


def armed() -> ChaosSchedule | None:
    """The armed schedule, lazily arming from ``TPUDES_CHAOS=<seed>``
    (+ optional ``TPUDES_CHAOS_MEMBERS=<n>``) on first query — the
    path a spawned member process takes, since it inherits the
    launcher's environment but not its Python state."""
    global _armed, _env_checked
    if not _env_checked:
        _env_checked = True
        raw = os.environ.get("TPUDES_CHAOS")
        if raw:
            try:
                members = int(os.environ.get("TPUDES_CHAOS_MEMBERS", "0"))
                _armed = canonical_schedule(int(raw), members)
            except ValueError:
                _armed = None
    return _armed


def fire(site: str, member: int | None = None,
         tag: object = None) -> ChaosEvent | None:
    """Visit injection ``site``; returns the due event (already counted
    into the schedule's ``injected`` telemetry) or None."""
    sched = armed()
    if sched is None:
        return None
    ev = sched.fire(site, member=member, tag=tag)
    if ev is not None:
        from tpudes.obs.serving import ServingTelemetry

        ServingTelemetry.record_injected(ev.kind)
    return ev


def filter_frame(site: str, blob: bytes,
                 member: int | None = None) -> bytes:
    """Wire-layer injection: pass a framed blob through the armed
    schedule.  ``wire_truncate`` cuts the frame mid-payload and
    ``wire_corrupt`` flips the version byte — both deterministic
    :class:`~tpudes.parallel.mpi.WireFormatError` shapes at the
    receiver, never silent garbage."""
    ev = fire(site, member=member)
    if ev is None:
        return blob
    if ev.kind == "wire_truncate":
        return blob[: max(1, len(blob) // 2)]
    if ev.kind == "wire_corrupt":
        return bytes((blob[0] ^ 0x7F,)) + blob[1:]
    return blob


def maybe_fail(site: str, what: str = "launch",
               member: int | None = None, tag: object = None) -> None:
    """Control-plane injection: raise a compile/OOM-shaped
    :class:`ChaosInjected` (``launch_error`` / ``checkpoint_kill``) or
    sleep (``slow_member``) when the armed schedule says so."""
    ev = fire(site, member=member, tag=tag)
    if ev is None:
        return
    if ev.kind == "launch_error":
        raise ChaosInjected(
            f"RESOURCE_EXHAUSTED: chaos-injected {what} failure at "
            f"{site!r} (compile/OOM shape)"
        )
    if ev.kind == "checkpoint_kill":
        raise ChaosInjected(
            f"chaos-injected kill after checkpoint save at {site!r}"
        )
    if ev.kind == "slow_member":
        time.sleep(float(ev.param or 0.1))
