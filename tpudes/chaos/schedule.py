"""Seed-keyed chaos schedules: every failure scenario is one integer.

The fault-tolerance layer (ISSUE 13) is only testable if failures are
*replayable*: a member process dying "sometimes", a frame corrupting
"under load", a launch OOMing "occasionally" cannot be pinned by a
regression test.  This module makes failure injection deterministic the
same way :mod:`tpudes.fuzz` made scenario generation deterministic —
a :class:`ChaosSchedule` is a list of :class:`ChaosEvent` entries, each
"at the ``nth`` visit of injection ``site`` (optionally: by ``member``),
inject ``kind``", and :meth:`ChaosSchedule.from_seed` /
:func:`canonical_schedule` derive the whole list from one seed.  Replay
= arm the same seed again (``python -m tpudes.chaos --replay SEED``).

Injection sites (where the serving/transport stack calls
:func:`tpudes.chaos.fire`):

``local_launch``
    the StudyServer dispatching a batch through the local runtime —
    ``launch_error`` here raises a compile/OOM-shaped
    :class:`~tpudes.chaos.ChaosInjected` before the device sees work.
``member_study``
    a routed member (:func:`tpudes.serving.serve_studies`) about to
    execute a study frame — ``kill_member`` SIGKILLs the member
    process mid-batch (or raises, in thread-member test mode),
    ``slow_member`` sleeps past the router's member timeout.
``router_send`` / ``router_recv``
    a study/result frame crossing the
    :mod:`tpudes.parallel.mpi` framed wire — ``wire_truncate`` /
    ``wire_corrupt`` mangle the frame so the receiver's
    :class:`~tpudes.parallel.mpi.WireFormatError` path fires.
``checkpoint_save``
    a chunked-horizon carry checkpoint just persisted —
    ``checkpoint_kill`` aborts the run *after* the save, simulating a
    study killed between chunks (the resume path's regression hook).

Counters are per-(site, member) ordinals inside one schedule instance,
so the same schedule armed in two processes (server + spawned member)
fires each event exactly where its ordinal lands in THAT process —
which is what makes a cross-process kill scenario a pure function of
the seed.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

__all__ = [
    "KINDS",
    "SITES",
    "ChaosEvent",
    "ChaosSchedule",
    "canonical_schedule",
]

#: failure kinds a schedule may inject
KINDS = frozenset({
    "kill_member", "slow_member", "wire_truncate", "wire_corrupt",
    "launch_error", "checkpoint_kill",
})

#: site -> kinds meaningful there (validated at schedule build)
SITES = {
    "local_launch": {"launch_error"},
    "member_study": {"kill_member", "slow_member"},
    "router_send": {"wire_truncate", "wire_corrupt"},
    "router_recv": {"wire_truncate", "wire_corrupt"},
    "checkpoint_save": {"checkpoint_kill"},
}


@dataclass(frozen=True)
class ChaosEvent:
    """One planted failure: at the ``nth`` (1-based) visit of ``site``
    — counting per member when ``member`` is set, site-wide otherwise —
    inject ``kind``.  ``param`` carries kind-specific detail: the sleep
    seconds for ``slow_member``, the string ``"raise"`` to make
    ``kill_member`` raise instead of SIGKILL (thread-member test mode),
    the engine name filter for ``checkpoint_kill``."""

    kind: str
    site: str
    nth: int
    member: int | None = None
    param: object = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown chaos site {self.site!r}")
        if self.kind not in SITES[self.site]:
            raise ValueError(
                f"kind {self.kind!r} cannot fire at site {self.site!r} "
                f"(supported: {sorted(SITES[self.site])})"
            )
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")


class ChaosSchedule:
    """An ordered set of planted failures plus the ordinal counters
    that decide when each fires.  Each event fires AT MOST ONCE."""

    def __init__(self, events: list[ChaosEvent]):
        self.events = list(events)
        #: (site, member) -> visits so far; member None = site-wide
        self._counts: dict[tuple, int] = {}
        self._fired: set[int] = set()
        #: kind -> times injected (recovery-telemetry cross-check)
        self.injected: dict[str, int] = {}

    def fire(self, site: str, member: int | None = None,
             tag: object = None) -> ChaosEvent | None:
        """Record one visit of ``site`` (by ``member``, under ``tag``)
        and return the event due at this ordinal, if any.  An event
        whose ``member`` is set counts that member's visits; a
        ``checkpoint_save`` event whose ``param`` names an engine
        counts that engine's saves (``tag``); otherwise the site-wide
        ordinal decides."""
        n_site = self._counts[(site, None)] = (
            self._counts.get((site, None), 0) + 1
        )
        n_member = None
        if member is not None:
            n_member = self._counts[(site, member)] = (
                self._counts.get((site, member), 0) + 1
            )
        n_tag = None
        if tag is not None:
            tkey = (site, ("tag", tag))
            n_tag = self._counts[tkey] = self._counts.get(tkey, 0) + 1
        for i, ev in enumerate(self.events):
            if i in self._fired or ev.site != site:
                continue
            if ev.site == "checkpoint_save" and ev.param is not None:
                hit = tag == ev.param and n_tag == ev.nth
            elif ev.member is None:
                hit = n_site == ev.nth
            else:
                hit = member == ev.member and n_member == ev.nth
            if hit:
                self._fired.add(i)
                self.injected[ev.kind] = self.injected.get(ev.kind, 0) + 1
                return ev
        return None

    def remaining(self) -> int:
        """Events not yet fired (a finished scenario should usually
        have drained the schedule)."""
        return len(self.events) - len(self._fired)

    @classmethod
    def from_seed(cls, seed: int, members: int = 0,
                  n_events: int = 3) -> "ChaosSchedule":
        """Derive a schedule from one integer: every draw comes from
        ``random.Random(seed)``, so the same (seed, members, n_events)
        always yields the same planted failures."""
        # host-side schedule derivation, deliberately stdlib: chaos
        # schedules live outside the simulation's seeded-stream API
        # (member processes arm them before jax ever loads)
        rng = random.Random(int(seed))  # tpudes: ignore[RNG002]
        kinds = ["launch_error", "wire_truncate", "wire_corrupt"]
        if members > 0:
            kinds += ["kill_member", "slow_member"]
        events = []
        for _ in range(int(n_events)):
            kind = rng.choice(kinds)
            site = {
                "launch_error": "local_launch",
                "wire_truncate": rng.choice(["router_send", "router_recv"]),
                "wire_corrupt": rng.choice(["router_send", "router_recv"]),
                "kill_member": "member_study",
                "slow_member": "member_study",
            }[kind]
            member = (
                1 + rng.randrange(members)
                if site in ("member_study",) and members > 0
                else None
            )
            param = 0.05 * (1 + rng.randrange(4)) \
                if kind == "slow_member" else None
            events.append(ChaosEvent(
                kind, site, nth=1 + rng.randrange(3), member=member,
                param=param,
            ))
        return cls(events)


def canonical_schedule(seed: int, members: int) -> ChaosSchedule:
    """The fixed replay scenario's schedule (``python -m tpudes.chaos
    --replay SEED``): with members, SIGKILL one seed-chosen member on
    its FIRST routed study (mid-coalesced-batch — the other blocks are
    in flight); without members, plant two seed-placed launch-shaped
    errors (the drill dispatches one study at a time, so both are
    guaranteed to fire).  Pure in (seed, members)."""
    # same stdlib-by-design rationale as from_seed above
    rng = random.Random(int(seed))  # tpudes: ignore[RNG002]
    events = []
    if members > 0:
        victim = 1 + int(seed) % members
        events.append(ChaosEvent(
            "kill_member", "member_study", nth=1, member=victim,
        ))
        events.append(ChaosEvent(
            "launch_error", "local_launch", nth=2 + rng.randrange(2),
        ))
    else:
        events.append(ChaosEvent(
            "launch_error", "local_launch", nth=2 + rng.randrange(3),
        ))
        events.append(ChaosEvent(
            "launch_error", "local_launch", nth=5 + rng.randrange(3),
        ))
    return ChaosSchedule(events)
