"""Chaos replay CLI (the fault-tolerance analog of ``tpudes.fuzz``).

Usage::

    python -m tpudes.chaos --replay SEED [--procs N] [--studies K]
                           [--out METRICS.json] [--check] [--quiet]

``--replay SEED`` re-runs the canonical serving drill under
``canonical_schedule(SEED, members)``: with ``--procs 1`` (default) the
in-process launch-error drill, with ``--procs N`` the spawned fleet
where the schedule SIGKILLs a seed-chosen member mid-coalesced-batch.
Exit 0 requires every study to complete AND recover bit-equal to solo
launches.  ``--check`` runs the drill twice and additionally demands
bit-identical failure/recovery counters — the determinism gate
(same seed → same injected failures → same recovery telemetry).
``--out`` writes rank-0's serving-telemetry snapshot (validated by
``python -m tpudes.obs --serving``).

Exit codes: 0 = recovered (and deterministic, under --check);
1 = a study failed, diverged, or the counters drifted; 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys


def _failure_counters(report: dict) -> dict:
    """The determinism-gated subset: injected + recovery counters
    (latency distributions legitimately vary run to run)."""
    f = dict(report["telemetry"]["failures"])
    f["completed"] = report["completed"]
    return f


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudes.chaos",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--replay", type=int, metavar="SEED", required=True,
                    help="chaos schedule seed to replay")
    ap.add_argument("--procs", type=int, default=1,
                    help="1 = in-process drill; N>1 spawns N-1 routed "
                         "members and SIGKILLs a seed-chosen one")
    ap.add_argument("--studies", type=int, default=None,
                    help="studies per drill (default 6)")
    ap.add_argument("--out", default=None,
                    help="write the serving-telemetry snapshot here")
    ap.add_argument("--check", action="store_true",
                    help="run twice; fail unless the failure/recovery "
                         "counters are identical (determinism gate)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.procs < 1:
        ap.print_usage(sys.stderr)
        print("--procs must be >= 1", file=sys.stderr)
        return 2
    log = (lambda *a: None) if args.quiet else print

    from tpudes.chaos.scenario import (
        N_STUDIES,
        run_local_scenario,
        run_scenario,
    )

    n_studies = args.studies or N_STUDIES

    def drill() -> dict:
        if args.procs == 1:
            return run_local_scenario(args.replay, n_studies)
        return run_scenario(args.replay, args.procs, n_studies)[0]

    report = drill()
    ok = report["completed"] == n_studies and report["equal"]
    f = report["telemetry"]["failures"]
    log(
        f"chaos replay seed={args.replay}: {report['completed']}/"
        f"{n_studies} studies completed, bit-equal={report['equal']}, "
        f"injected={f['injected_failures']}, "
        f"requeued={f['requeued_studies']}, "
        f"members_lost={f['members_lost']}"
    )
    if args.check:
        second = drill()
        if _failure_counters(report) != _failure_counters(second):
            print(
                "chaos replay NOT deterministic:\n"
                f"  first:  {_failure_counters(report)}\n"
                f"  second: {_failure_counters(second)}",
                file=sys.stderr,
            )
            ok = False
        else:
            log("determinism check: identical failure/recovery counters")
    if args.out:
        with open(args.out, "w") as fp:
            json.dump(report["telemetry"], fp, indent=1, sort_keys=True)
    if not ok:
        print(
            f"chaos replay FAILED: completed={report['completed']}, "
            f"equal={report['equal']}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
