"""Canonical chaos scenarios: the replayable serving-fleet drills.

Two fixed scenarios, both pure functions of an integer seed (plus the
process count), so ``python -m tpudes.chaos --replay SEED`` can re-run
the exact injected failures and compare recovery telemetry:

- :func:`run_local_scenario` — in-process StudyServer (deterministic
  ``pump`` mode) under seed-planted launch-shaped errors: every study
  must complete via requeue/retry, bit-equal to solo launches.
- :func:`run_scenario` — a spawned serving fleet (rank 0 = StudyServer
  + ProcessRouter, ranks 1.. = ``serve_studies`` members) where the
  schedule SIGKILLs a seed-chosen member mid-coalesced-batch: the
  batch requeues onto the survivors (or the local engine) and every
  study still completes bit-equal.

Both return rank-0's report: ``equal`` (bit-equality vs solo runs),
``completed``, the failure/recovery counters, and the full serving
telemetry snapshot (schema-gated by ``python -m tpudes.obs
--serving``).
"""

from __future__ import annotations

__all__ = ["chaos_serving_rank", "run_local_scenario", "run_scenario"]

#: studies per scenario run (small enough for CI, large enough that a
#: kill lands mid-stream)
N_STUDIES = 6


def _bss_studies(n_studies: int):
    import jax

    from tpudes.parallel.programs import toy_bss_program

    prog = toy_bss_program(n_sta=4, sim_end_us=40_000)
    key = jax.random.PRNGKey(3)
    horizons = [40_000 + 2_000 * i for i in range(n_studies)]
    return prog, key, horizons


def _serve_and_check(server, prog, key, horizons, timeout_s: float,
                     pump_each: bool = False):
    """Submit one BSS study per horizon, pump to completion, and
    compare every result against a solo launch (computed in the same
    process, warm caches).  ``pump_each`` dispatches study-by-study
    (many launches — the local launch-error drill's shape) instead of
    one coalesced batch (the member-kill drill's shape)."""
    import dataclasses

    import numpy as np

    from tpudes.parallel.replicated import run_replicated_bss

    handles = []
    for i, h in enumerate(horizons):
        handles.append(server.submit_study(
            "bss", dataclasses.replace(prog, sim_end_us=h), key, 2,
            tenant=f"t{i}", slo="gold" if i == 0 else "standard",
        ))
        if pump_each:
            server.pump(force=True)
    server.pump(force=True)
    completed = equal = 0
    for h, handle in zip(horizons, handles):
        res = handle.result(timeout=timeout_s)
        completed += 1
        solo = run_replicated_bss(
            dataclasses.replace(prog, sim_end_us=h), 2, key
        )
        if all(
            np.array_equal(np.asarray(res[k]), np.asarray(solo[k]))
            for k in solo
        ):
            equal += 1
    return completed, equal


def run_local_scenario(seed: int, n_studies: int = N_STUDIES) -> dict:
    """In-process drill: seed-planted launch errors against a
    ``start=False`` (deterministic pump) StudyServer.  Same seed →
    same injected failures → same recovery counters."""
    import tpudes.chaos as chaos
    from tpudes.obs.serving import ServingTelemetry
    from tpudes.serving import StudyServer

    prog, key, horizons = _bss_studies(n_studies)
    ServingTelemetry.reset()
    chaos.arm(chaos.canonical_schedule(seed, members=0))
    try:
        with StudyServer(
            start=False, retry_backoff_s=0.005, retry_budget=3,
        ) as server:
            completed, equal = _serve_and_check(
                server, prog, key, horizons, timeout_s=120.0,
                pump_each=True,
            )
            snapshot = server.metrics()
    finally:
        chaos.disarm()
    return dict(
        completed=completed,
        equal=equal == n_studies,
        injected=dict(chaos=snapshot["failures"]["injected_failures"]),
        telemetry=snapshot,
    )


def chaos_serving_rank(rank: int, size: int, seed: int,
                       n_studies: int) -> dict:
    """``LaunchDistributed`` target for the member-kill drill (rank 0
    serves, the rest run :func:`tpudes.serving.serve_studies` under the
    same seed's schedule — the victim SIGKILLs itself mid-batch)."""
    import tpudes.chaos as chaos
    from tpudes.parallel.mpi import MpiInterface

    chaos.arm(chaos.canonical_schedule(seed, members=size - 1))
    if rank != 0:
        from tpudes.serving import serve_studies

        try:
            return dict(
                served=serve_studies(MpiInterface._conns[0],
                                     member_id=rank)
            )
        finally:
            chaos.disarm()
    from tpudes.obs.serving import ServingTelemetry
    from tpudes.serving import ProcessRouter, StudyServer

    prog, key, horizons = _bss_studies(n_studies)
    ServingTelemetry.reset()
    router = ProcessRouter(MpiInterface._conns, member_timeout_s=30.0)
    server = StudyServer(
        max_batch=8, router=router, start=False,
        retry_backoff_s=0.01, retry_budget=3,
    )
    try:
        completed, equal = _serve_and_check(
            server, prog, key, horizons, timeout_s=240.0
        )
        snapshot = server.metrics()
    finally:
        server.close()
        chaos.disarm()
    f = snapshot["failures"]
    return dict(
        completed=completed,
        equal=equal == n_studies,
        requeued=f["requeued_studies"],
        members_lost=f["members_lost"],
        routed_batches=router.routed_batches,
        excluded=sorted(router._dead),
        telemetry=snapshot,
    )


def run_scenario(seed: int, procs: int = 3,
                 n_studies: int = N_STUDIES) -> list:
    """Spawn the fleet drill (rank 0 + ``procs - 1`` members); member
    ranks are optional (the schedule SIGKILLs one).  Returns per-rank
    results (None for the killed member)."""
    from tpudes.parallel.mpi import LaunchDistributed

    return LaunchDistributed(
        chaos_serving_rank,
        procs,
        args=(int(seed), int(n_studies)),
        timeout_s=420.0,
        optional_ranks=set(range(1, procs)),
    )
