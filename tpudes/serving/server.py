"""StudyServer: continuous batching of independently arriving studies.

The long-lived serving layer on top of :data:`tpudes.parallel.runtime.RUNTIME`
(ROADMAP item 1): clients call :meth:`StudyServer.submit_study` and get a
:class:`StudyHandle` back immediately; a coalescing scheduler drains the
request queue and merges **compatible** studies — same engine, same
static cache key; differences only in traced operands (scheduler id,
TCP variant assignment, BSS horizon, AS load scale) — into ONE
megabatched config-axis device launch, demultiplexing per-study results
back through each handle.  This is the simulator analog of continuous
batching in LLM serving: the hardware sees dense (C, R, …) launches
even when every study arrives alone.

Correctness is inherited, not approximated: the PR-5 sweep arguments
are pinned bit-equal to per-point solo launches (tests/test_sweep.py),
and the server only ever merges studies whose coalesce keys match —
everything the executable or the PRNG streams depend on is in the key,
so a coalesced result IS the solo result (tests/test_serving.py pins
this end to end for all four engines).

Operating behavior:

- **Batching deadline** (``max_wait_s``): the head-of-queue study waits
  at most this long for batchmates; a lone study is dispatched alone at
  the deadline, never starved.
- **Admission control**: per-tenant cap on queued+in-flight studies
  (:class:`AdmissionError` on overflow) in front of the device-side
  bounded in-flight window (``TPUDES_INFLIGHT``) that
  :meth:`EngineRuntime.submit` enforces at dispatch.
- **pow2 batch buckets**: a coalesced batch pads its config axis to the
  next power of two by duplicating the tail point (results discarded),
  so the server compiles one executable per bucket, not per batch size;
  single studies ride the engines' plain entry points and share the
  common non-sweep executables.
- **Warm pool** (:meth:`warm`): pre-compiles the hot engine/bucket set
  at server start — with ``TPUDES_CACHE_DIR`` armed these become
  persistent-cache disk hits instead of fresh XLA compiles.
- **Metrics**: every decision is recorded in
  :class:`tpudes.obs.serving.ServingTelemetry` (queue depth, coalesce
  rate, batch occupancy, launch latency p50/p99); :meth:`metrics`
  snapshots it and ``python -m tpudes.obs --serving dump.json``
  validates a dump.

Threading model: ALL device work (launch, D2H, unpack) happens on the
single scheduler thread (or the caller's thread via :meth:`pump` when
constructed with ``start=False`` — the deterministic mode tests use).
Client threads only build descriptors, enqueue, and wait on events.
"""

from __future__ import annotations

import importlib
import threading
import time
from collections import deque
from dataclasses import dataclass

from tpudes.obs.serving import ServingTelemetry
from tpudes.serving.descriptor import StudyDescriptor

__all__ = ["AdmissionError", "StudyHandle", "StudyServer"]


class AdmissionError(RuntimeError):
    """The tenant's queued+in-flight study cap is exhausted; retry
    after some of its studies complete."""


#: engine name -> (module, study-descriptor extraction function); the
#: lazy import keeps tpudes.serving importable without pulling every
#: engine (and jax) in at module import
_ENGINE_STUDY = {
    "bss": ("tpudes.parallel.replicated", "bss_study"),
    "lte_sm": ("tpudes.parallel.lte_sm", "lte_sm_study"),
    "dumbbell": ("tpudes.parallel.tcp_dumbbell", "tcp_study"),
    "as_flows": ("tpudes.parallel.as_flows", "as_study"),
}


class StudyHandle:
    """Client-side future for one submitted study."""

    def __init__(self, engine: str, tenant: str):
        self.engine = engine
        self.tenant = tenant
        #: how many real studies shared this study's launch (set at
        #: completion; 1 means it was dispatched alone)
        self.batch_size: int | None = None
        self._ev = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None):
        """Block until the study completes; raises the launch error if
        its batch failed, TimeoutError past ``timeout``."""
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"study ({self.engine}, tenant={self.tenant!r}) not "
                f"complete within {timeout} s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _complete(self, result=None, error=None, batch_size=None) -> None:
        self._result = result
        self._error = error
        self.batch_size = batch_size
        self._ev.set()


@dataclass
class _Request:
    desc: StudyDescriptor
    tenant: str
    handle: StudyHandle
    t_submit: float


def _pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class StudyServer:
    """The coalescing scheduler + its request queue (module docstring
    has the big picture)."""

    def __init__(
        self,
        *,
        max_wait_s: float = 0.01,
        max_batch: int = 8,
        tenant_cap: int = 64,
        warm: list | None = None,
        start: bool = True,
        router=None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_wait_s = float(max_wait_s)
        self.max_batch = int(max_batch)
        self.tenant_cap = int(tenant_cap)
        #: optional cross-process dispatcher
        #: (:class:`tpudes.serving.distributed.ProcessRouter`): coalesced
        #: batches whose studies carry a picklable spec split across the
        #: mesh's member processes; everything else stays host-local
        self.router = router
        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        #: dispatched launches not yet demuxed: (future, batch, t0)
        self._pending: deque[tuple] = deque()
        self._tenant_load: dict[str, int] = {}
        self._running = False
        self._closed = False
        self._thread: threading.Thread | None = None
        if warm:
            self.warm(warm)
        if start:
            self.start()

    # --- client surface ---------------------------------------------------

    def submit_study(
        self,
        engine: str,
        prog,
        key,
        replicas=None,
        *,
        mesh=None,
        tenant: str = "default",
        **engine_kwargs,
    ) -> StudyHandle:
        """Queue one study; returns immediately with its handle.

        ``engine`` is one of ``bss`` / ``lte_sm`` / ``dumbbell`` /
        ``as_flows``; ``prog`` the engine's lowered Program dataclass;
        ``key``/``replicas``/``mesh`` exactly what the engine's
        ``run_*`` entry takes.  Extra ``engine_kwargs`` flow to the
        engine's study extractor (e.g. ``rate_scale=`` for the AS
        engine).  Raises :class:`AdmissionError` when ``tenant``
        already has ``tenant_cap`` studies queued or in flight."""
        mod_name, fn_name = _ENGINE_STUDY[engine]
        extract = getattr(importlib.import_module(mod_name), fn_name)
        desc = extract(prog, key, replicas, mesh=mesh, **engine_kwargs)
        return self.submit(desc, tenant=tenant)

    def submit(self, desc: StudyDescriptor, tenant: str = "default"
               ) -> StudyHandle:
        """Queue a pre-extracted :class:`StudyDescriptor`."""
        handle = StudyHandle(desc.engine, tenant)
        with self._cond:
            if self._closed:
                # a closed server never strands a handle — including
                # one a racing submit would otherwise enqueue after
                # the drain
                raise RuntimeError("StudyServer is closed")
            if self._tenant_load.get(tenant, 0) >= self.tenant_cap:
                ServingTelemetry.record_reject(tenant)
                raise AdmissionError(
                    f"tenant {tenant!r} has {self.tenant_cap} studies "
                    "queued/in flight (tenant_cap)"
                )
            self._tenant_load[tenant] = self._tenant_load.get(tenant, 0) + 1
            self._queue.append(
                _Request(desc, tenant, handle, time.monotonic())
            )
            ServingTelemetry.record_submit(desc.engine, len(self._queue))
            self._cond.notify_all()
        return handle

    def metrics(self) -> dict:
        """Snapshot of the process-global serving telemetry (see
        :func:`tpudes.obs.serving.validate_serving_metrics`)."""
        return ServingTelemetry.snapshot()

    # --- warm pool --------------------------------------------------------

    def warm(self, studies: list, buckets: tuple | None = None) -> int:
        """Pre-compile the executables the given example studies will
        need: for each distinct coalesce key, the plain single-study
        program plus each pow2 config-axis bucket up to the one
        ``max_batch`` pads into (the default ``buckets``) — so no batch
        size the server can ever dispatch pays a fresh compile on the
        serving path.  ``studies`` holds :class:`StudyDescriptor`
        objects or dicts of :meth:`submit_study` keyword arguments.
        Returns the number of warm launches performed (each a
        minimal-horizon run — a persistent-cache disk hit when
        ``TPUDES_CACHE_DIR`` is set)."""
        top = _pow2(max(1, self.max_batch))
        if buckets is None:
            buckets = tuple(1 << i for i in range(top.bit_length()))
        n = 0
        seen: set = set()
        t0 = time.monotonic()
        for study in studies:
            desc = study
            if isinstance(study, dict):
                kw = dict(study)
                mod_name, fn_name = _ENGINE_STUDY[kw.pop("engine")]
                extract = getattr(
                    importlib.import_module(mod_name), fn_name
                )
                desc = extract(
                    kw.pop("prog"), kw.pop("key"),
                    kw.pop("replicas", None), **kw,
                )
            if desc.warm is None or desc.coalesce_key in seen:
                continue
            seen.add(desc.coalesce_key)
            for b in buckets if not desc.solo else (1,):
                if b > top:
                    continue
                desc.warm(int(b))
                n += 1
        if n:
            ServingTelemetry.record_warm(
                "all", n, time.monotonic() - t0
            )
        return n

    # --- scheduler --------------------------------------------------------

    def start(self) -> None:
        """Start the background scheduler thread (idempotent)."""
        with self._cond:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tpudes-study-server", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Stop the scheduler, force-dispatching and completing every
        queued/in-flight study first (a closed server never strands a
        handle)."""
        thread = self._thread
        with self._cond:
            self._running = False
            self._closed = True
            self._cond.notify_all()
        if thread is not None:
            thread.join()
            self._thread = None
        else:
            self.pump(force=True)  # start=False server: drain inline
        if self.router is not None:
            self.router.close()  # release the member serve loops

    def pump(self, force: bool = True) -> int:
        """Synchronously dispatch what is due (everything queued when
        ``force``) and demux every completed launch — the deterministic
        single-thread mode (``start=False``); returns the number of
        studies completed.  Must not be called while the background
        thread runs."""
        done = 0
        while True:
            with self._cond:
                batch = self._take_batch(force=force)
            if batch is None:
                break
            self._dispatch(batch)
        while self._pending:
            done += self._demux_oldest()
        return done

    def _loop(self) -> None:
        from tpudes.parallel.runtime import RUNTIME

        while True:
            batch = None
            with self._cond:
                if (
                    not self._running
                    and not self._queue
                    and not self._pending
                ):
                    return
                batch = self._take_batch(force=not self._running)
                if batch is None and self._queue and self._running:
                    # head not due: sleep until its deadline or a new
                    # arrival, whichever first
                    head_age = time.monotonic() - self._queue[0].t_submit
                    self._cond.wait(
                        timeout=max(0.001, self.max_wait_s - head_age)
                    )
                    batch = self._take_batch(force=not self._running)
                elif batch is None and not self._pending and self._running:
                    self._cond.wait(timeout=0.05)
            if batch is not None:
                self._dispatch(batch)
                RUNTIME.poll()  # sweep the window, never blocks
            # demux finished launches; a blocking result() would pin
            # the scheduler to one launch wall while a fresh arrival
            # could be dispatching into the window, so while live we
            # only nap (woken early by any submit) and retire done work
            while self._pending and self._pending[0][0].done():
                self._demux_oldest()
            if batch is None and self._pending and not self._queue:
                if self._running:
                    with self._cond:
                        if self._running and not self._queue:
                            self._cond.wait(timeout=0.002)
                else:
                    self._demux_oldest()  # shutdown drain: block

    def _take_batch(self, force: bool) -> list | None:
        """Pop the head study's batch when it is due (caller holds the
        lock): due = solo study, batch full, deadline reached, or
        ``force``.  Batchmates are every queued request sharing the
        head's coalesce key, in arrival order, up to ``max_batch``."""
        if not self._queue:
            return None
        head = self._queue[0]
        if head.desc.solo:
            mates = [head]
        else:
            mates = [
                r for r in self._queue
                if r.desc.compatible(head.desc)
            ][: self.max_batch]
        due = (
            force
            or head.desc.solo
            or len(mates) >= self.max_batch
            or (time.monotonic() - head.t_submit) >= self.max_wait_s
        )
        if not due:
            return None
        for r in mates:
            self._queue.remove(r)
        ServingTelemetry.record_queue_depth(len(self._queue))
        return mates

    def _dispatch(self, batch: list) -> None:
        """Launch one (possibly coalesced) batch through the runtime's
        bounded in-flight window.  Never raises: a failed launch
        poisons the batch's handles instead of killing the scheduler."""
        from tpudes.parallel.runtime import RUNTIME

        points = [r.desc.sweep_point for r in batch]
        n_real = len(points)
        if n_real > 1:
            # pad the config axis to the pow2 bucket by duplicating the
            # tail point: one executable per bucket, not per batch size
            points = points + [points[-1]] * (_pow2(n_real) - n_real)
        t0 = time.monotonic()
        try:
            fut = None
            if self.router is not None:
                # routed dispatch: the batch's point blocks fan out to
                # member processes (None = not routable, fall through)
                fut = self.router.launch(batch, points)
            if fut is None:
                fut = RUNTIME.submit(batch[0].desc.launch, points)
        except Exception as e:  # noqa: BLE001 - poison, don't crash
            self._finish_batch(batch, error=e, n_real=n_real)
            return
        with self._cond:
            queue_depth = len(self._queue)
        ServingTelemetry.record_dispatch(
            batch[0].desc.engine, n_real, len(points), queue_depth
        )
        self._pending.append((fut, batch, t0))

    def _demux_oldest(self) -> int:
        """Retire the oldest pending launch and complete its handles."""
        fut, batch, t0 = self._pending.popleft()
        engine = batch[0].desc.engine
        try:
            res = fut.result()
        except Exception as e:  # noqa: BLE001 - poison, don't crash
            self._finish_batch(batch, error=e, n_real=len(batch))
            return len(batch)
        ServingTelemetry.record_launch_done(
            engine, time.monotonic() - t0
        )
        results = res if isinstance(res, list) else [res]
        now = time.monotonic()
        for r, out in zip(batch, results):  # pad tail dropped by zip
            r.handle._complete(result=out, batch_size=len(batch))
            ServingTelemetry.record_study_done(engine, now - r.t_submit)
            self._release(r.tenant)
        return len(batch)

    def _finish_batch(self, batch, error, n_real) -> None:
        del n_real
        for r in batch:
            r.handle._complete(error=error, batch_size=len(batch))
            self._release(r.tenant)

    def _release(self, tenant: str) -> None:
        with self._cond:
            # decrement-only (never popped): the map is bounded by the
            # distinct-tenant count, and a zero entry is a valid gauge
            self._tenant_load[tenant] = self._tenant_load.get(tenant, 1) - 1
            self._cond.notify_all()

    # --- context manager ---------------------------------------------------

    def __enter__(self) -> "StudyServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
