"""StudyServer: continuous batching of independently arriving studies.

The long-lived serving layer on top of :data:`tpudes.parallel.runtime.RUNTIME`
(ROADMAP items 1 and 6): clients call :meth:`StudyServer.submit_study`
and get a :class:`StudyHandle` back immediately; a coalescing scheduler
drains the request queue and merges **compatible** studies — same
engine, same static cache key; differences only in traced operands
(scheduler id, TCP variant assignment, BSS horizon, AS load scale) —
into ONE megabatched config-axis device launch, demultiplexing
per-study results back through each handle.  This is the simulator
analog of continuous batching in LLM serving: the hardware sees dense
(C, R, …) launches even when every study arrives alone.

Correctness is inherited, not approximated: the PR-5 sweep arguments
are pinned bit-equal to per-point solo launches (tests/test_sweep.py),
and the server only ever merges studies whose coalesce keys match —
everything the executable or the PRNG streams depend on is in the key,
so a coalesced result IS the solo result (tests/test_serving.py pins
this end to end for all four engines).

Operating behavior:

- **Batching deadline** (``max_wait_s``): the head-of-queue study waits
  at most this long for batchmates; a lone study is dispatched alone at
  the deadline, never starved.
- **SLO classes** (``slo=`` on submit; :data:`SLO_CLASSES`): the
  scheduler picks the due head by (priority, arrival) instead of pure
  FIFO, and ``gold`` studies *preempt* coalesce-pending work — a gold
  head dispatches immediately with whatever batchmates are already
  queued instead of waiting out the batching deadline behind
  lower-priority batch formation.  Per-class latency targets
  (``slo_targets``) feed the SLO-attainment telemetry.
- **Admission control**: per-tenant cap on queued+in-flight studies
  (:class:`AdmissionError` on overflow) in front of the device-side
  bounded in-flight window (``TPUDES_INFLIGHT``) that
  :meth:`EngineRuntime.submit` enforces at dispatch.
- **Fault tolerance** (ISSUE 13): a batch that loses a routed member
  (:class:`~tpudes.serving.errors.MemberLostError` — death, wire
  corruption, or timeout) or hits a transient launch fault
  (:class:`~tpudes.chaos.ChaosInjected` — the chaos harness's
  compile/OOM shape) is **requeued**, with the lost member excluded
  from future routing, under a bounded per-study ``retry_budget`` with
  exponential ``retry_backoff_s`` between attempts; past the budget the
  handle raises :class:`~tpudes.serving.errors.RetryBudgetError`.
  Requeued studies re-coalesce and relaunch through the same
  descriptors, so recovered results are bit-equal to a failure-free
  run.  An exception escaping dispatch/demux poisons only that batch's
  handles — the scheduler loop itself never dies.
- **pow2 batch buckets**: a coalesced batch pads its config axis to the
  next power of two by duplicating the tail point (results discarded),
  so the server compiles one executable per bucket, not per batch size;
  single studies ride the engines' plain entry points and share the
  common non-sweep executables.
- **Warm pool** (:meth:`warm`): pre-compiles the hot engine/bucket set
  at server start — with ``TPUDES_CACHE_DIR`` armed these become
  persistent-cache disk hits instead of fresh XLA compiles.
- **Metrics**: every decision is recorded in
  :class:`tpudes.obs.serving.ServingTelemetry` (queue depth, coalesce
  rate, batch occupancy, launch latency p50/p99, failure/recovery
  counters, per-class SLO attainment); :meth:`metrics` snapshots it and
  ``python -m tpudes.obs --serving dump.json`` validates a dump.

Threading model: ALL device work (launch, D2H, unpack) happens on the
single scheduler thread (or the caller's thread via :meth:`pump` when
constructed with ``start=False`` — the deterministic mode tests use).
Client threads only build descriptors, enqueue, and wait on events.
"""

from __future__ import annotations

import importlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from collections import deque

from tpudes.obs.serving import ServingTelemetry
from tpudes.serving.descriptor import StudyDescriptor
from tpudes.serving.errors import MemberLostError, RetryBudgetError

__all__ = [
    "SLO_CLASSES",
    "AdmissionError",
    "StudyHandle",
    "StudyServer",
]


class AdmissionError(RuntimeError):
    """The tenant's queued+in-flight study cap is exhausted; retry
    after some of its studies complete."""


#: SLO class -> scheduling priority (lower dispatches first).  ``gold``
#: additionally preempts coalesce-pending work (see module docstring).
SLO_CLASSES = {"gold": 0, "standard": 1, "batch": 2}

#: classes whose head never waits out the batching deadline
_PREEMPT = frozenset({"gold"})

#: default per-class latency targets (seconds) for SLO attainment —
#: deliberately loose; operators pass ``slo_targets=`` for real fleets
DEFAULT_SLO_TARGETS = {
    "gold": 2.0, "standard": 30.0, "batch": float("inf"),
}

_INF = float("inf")


#: engine name -> (module, study-descriptor extraction function); the
#: lazy import keeps tpudes.serving importable without pulling every
#: engine (and jax) in at module import
_ENGINE_STUDY = {
    "bss": ("tpudes.parallel.replicated", "bss_study"),
    "lte_sm": ("tpudes.parallel.lte_sm", "lte_sm_study"),
    "dumbbell": ("tpudes.parallel.tcp_dumbbell", "tcp_study"),
    "as_flows": ("tpudes.parallel.as_flows", "as_study"),
}


class StudyHandle:
    """Client-side future for one submitted study."""

    def __init__(self, engine: str, tenant: str, slo: str = "standard"):
        self.engine = engine
        self.tenant = tenant
        self.slo = slo
        #: how many real studies shared this study's launch (set at
        #: completion; 1 means it was dispatched alone)
        self.batch_size: int | None = None
        self._ev = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None):
        """Block until the study completes; raises the launch error if
        its batch failed, TimeoutError past ``timeout``."""
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"study ({self.engine}, tenant={self.tenant!r}) not "
                f"complete within {timeout} s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _complete(self, result=None, error=None, batch_size=None) -> None:
        self._result = result
        self._error = error
        self.batch_size = batch_size
        self._ev.set()


@dataclass
class _Request:
    desc: StudyDescriptor
    tenant: str
    handle: StudyHandle
    t_submit: float
    slo: str = "standard"
    priority: int = 1
    preempt: bool = False
    seq: int = 0
    #: requeue state (ISSUE 13): attempts so far + earliest redispatch
    retries: int = 0
    t_ready: float = field(default=0.0)


def _pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class StudyServer:
    """The coalescing scheduler + its request queue (module docstring
    has the big picture)."""

    def __init__(
        self,
        *,
        max_wait_s: float = 0.01,
        max_batch: int = 8,
        tenant_cap: int = 64,
        warm: list | None = None,
        start: bool = True,
        router=None,
        retry_budget: int = 3,
        retry_backoff_s: float = 0.05,
        slo_targets: dict | None = None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_wait_s = float(max_wait_s)
        self.max_batch = int(max_batch)
        self.tenant_cap = int(tenant_cap)
        #: bounded retries per study for transient faults (member loss,
        #: chaos-injected launch errors); exceeded -> RetryBudgetError
        self.retry_budget = int(retry_budget)
        #: base backoff before a requeued batch redispatches (doubles
        #: per retry); force-pump/close ignore it so drains terminate
        self.retry_backoff_s = float(retry_backoff_s)
        self.slo_targets = dict(DEFAULT_SLO_TARGETS)
        if slo_targets:
            self.slo_targets.update(slo_targets)
        #: optional cross-process dispatcher
        #: (:class:`tpudes.serving.distributed.ProcessRouter`): coalesced
        #: batches whose studies carry a picklable spec split across the
        #: mesh's member processes; everything else stays host-local
        self.router = router
        self._cond = threading.Condition()
        self._queue: deque[_Request] = deque()
        #: dispatched launches not yet demuxed: (future, batch, t0)
        self._pending: deque[tuple] = deque()
        self._tenant_load: dict[str, int] = {}
        self._seq = itertools.count()
        self._running = False
        self._closed = False
        self._thread: threading.Thread | None = None
        if warm:
            self.warm(warm)
        if start:
            self.start()

    # --- client surface ---------------------------------------------------

    def submit_study(
        self,
        engine: str,
        prog,
        key,
        replicas=None,
        *,
        mesh=None,
        tenant: str = "default",
        slo: str = "standard",
        **engine_kwargs,
    ) -> StudyHandle:
        """Queue one study; returns immediately with its handle.

        ``engine`` is one of ``bss`` / ``lte_sm`` / ``dumbbell`` /
        ``as_flows``; ``prog`` the engine's lowered Program dataclass;
        ``key``/``replicas``/``mesh`` exactly what the engine's
        ``run_*`` entry takes.  ``slo`` picks the scheduling class
        (:data:`SLO_CLASSES`).  Extra ``engine_kwargs`` flow to the
        engine's study extractor (e.g. ``rate_scale=`` for the AS
        engine).  Raises :class:`AdmissionError` when ``tenant``
        already has ``tenant_cap`` studies queued or in flight."""
        mod_name, fn_name = _ENGINE_STUDY[engine]
        extract = getattr(importlib.import_module(mod_name), fn_name)
        desc = extract(prog, key, replicas, mesh=mesh, **engine_kwargs)
        return self.submit(desc, tenant=tenant, slo=slo)

    def submit(self, desc: StudyDescriptor, tenant: str = "default",
               slo: str = "standard") -> StudyHandle:
        """Queue a pre-extracted :class:`StudyDescriptor`."""
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown SLO class {slo!r} (have {sorted(SLO_CLASSES)})"
            )
        handle = StudyHandle(desc.engine, tenant, slo)
        with self._cond:
            if self._closed:
                # a closed server never strands a handle — including
                # one a racing submit would otherwise enqueue after
                # the drain
                raise RuntimeError("StudyServer is closed")
            if self._tenant_load.get(tenant, 0) >= self.tenant_cap:
                ServingTelemetry.record_reject(tenant)
                raise AdmissionError(
                    f"tenant {tenant!r} has {self.tenant_cap} studies "
                    "queued/in flight (tenant_cap)"
                )
            self._tenant_load[tenant] = self._tenant_load.get(tenant, 0) + 1
            self._queue.append(_Request(
                desc, tenant, handle, time.monotonic(), slo=slo,
                priority=SLO_CLASSES[slo], preempt=slo in _PREEMPT,
                seq=next(self._seq),
            ))
            ServingTelemetry.record_submit(desc.engine, len(self._queue))
            self._cond.notify_all()
        return handle

    def metrics(self) -> dict:
        """Snapshot of the process-global serving telemetry (see
        :func:`tpudes.obs.serving.validate_serving_metrics`)."""
        return ServingTelemetry.snapshot()

    # --- warm pool --------------------------------------------------------

    def warm(self, studies: list, buckets: tuple | None = None) -> int:
        """Pre-compile the executables the given example studies will
        need: for each distinct coalesce key, the plain single-study
        program plus each pow2 config-axis bucket up to the one
        ``max_batch`` pads into (the default ``buckets``) — so no batch
        size the server can ever dispatch pays a fresh compile on the
        serving path.  ``studies`` holds :class:`StudyDescriptor`
        objects or dicts of :meth:`submit_study` keyword arguments.
        Returns the number of warm launches performed (each a
        minimal-horizon run — a persistent-cache disk hit when
        ``TPUDES_CACHE_DIR`` is set)."""
        top = _pow2(max(1, self.max_batch))
        if buckets is None:
            buckets = tuple(1 << i for i in range(top.bit_length()))
        n = 0
        seen: set = set()
        t0 = time.monotonic()
        for study in studies:
            desc = study
            if isinstance(study, dict):
                kw = dict(study)
                mod_name, fn_name = _ENGINE_STUDY[kw.pop("engine")]
                extract = getattr(
                    importlib.import_module(mod_name), fn_name
                )
                desc = extract(
                    kw.pop("prog"), kw.pop("key"),
                    kw.pop("replicas", None), **kw,
                )
            if desc.warm is None or desc.coalesce_key in seen:
                continue
            seen.add(desc.coalesce_key)
            for b in buckets if not desc.solo else (1,):
                if b > top:
                    continue
                desc.warm(int(b))
                n += 1
        if n:
            ServingTelemetry.record_warm(
                "all", n, time.monotonic() - t0
            )
        return n

    # --- scheduler --------------------------------------------------------

    def start(self) -> None:
        """Start the background scheduler thread (idempotent)."""
        with self._cond:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="tpudes-study-server", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        """Stop the scheduler, force-dispatching and completing every
        queued/in-flight study first (a closed server never strands a
        handle — a study mid-retry either completes or surfaces its
        RetryBudgetError)."""
        thread = self._thread
        with self._cond:
            self._running = False
            self._closed = True
            self._cond.notify_all()
        if thread is not None:
            thread.join()
            self._thread = None
        else:
            self.pump(force=True)  # start=False server: drain inline
        if self.router is not None:
            self.router.close()  # release the member serve loops

    def pump(self, force: bool = True) -> int:
        """Synchronously dispatch what is due (everything queued when
        ``force`` — including batches still backing off) and demux
        every completed launch, following requeues until the queue
        drains — the deterministic single-thread mode (``start=False``);
        returns the number of studies completed.  Must not be called
        while the background thread runs."""
        done = 0
        while True:
            with self._cond:
                batch = self._take_batch(force=force)
            if batch is not None:
                self._dispatch(batch)
                continue
            if self._pending:
                done += self._demux_oldest()
                continue
            with self._cond:
                if not (force and self._queue):
                    break
            # a racing client submit landed between the lock drops
            # (force mode always takes a batch from a settled queue) —
            # yield briefly and re-take
            time.sleep(0.001)
        return done

    def _loop(self) -> None:
        from tpudes.parallel.runtime import RUNTIME

        while True:
            batch = None
            with self._cond:
                if (
                    not self._running
                    and not self._queue
                    and not self._pending
                ):
                    return
                batch = self._take_batch(force=not self._running)
                if batch is None and self._queue and self._running:
                    # head not due: sleep until its deadline, a retry
                    # backoff expiring, or a new arrival — bounded so
                    # the loop keeps sweeping pending work
                    self._cond.wait(timeout=self._nap_s())
                    batch = self._take_batch(force=not self._running)
                elif batch is None and not self._pending and self._running:
                    self._cond.wait(timeout=0.05)
            if batch is not None:
                try:
                    self._dispatch(batch)
                except Exception as e:  # noqa: BLE001 - hardening: an
                    # escaped dispatch error fails THIS batch's handles,
                    # never the scheduler thread (ISSUE 13 satellite)
                    self._finish_batch(batch, error=e, n_real=len(batch))
                try:
                    RUNTIME.poll()  # sweep the window, never blocks
                except Exception:  # noqa: BLE001 - a poisoned window
                    # future resurfaces via its own demux
                    ServingTelemetry.record_backstop()
            # demux finished launches (and force-demux any whose member
            # deadline passed — a hung member must not pin its batch);
            # a blocking result() on live work would serialize the
            # scheduler, so while running we only retire what is ready
            try:
                while self._pending and (
                    self._pending[0][0].done()
                    or getattr(self._pending[0][0], "deadline", _INF)
                    <= time.monotonic()
                ):
                    self._demux_oldest()
            except Exception:  # noqa: BLE001 - _demux_oldest poisons
                # per-batch; this is the loop's counted backstop
                ServingTelemetry.record_backstop()
            if batch is None and self._pending and not self._queue:
                if self._running:
                    with self._cond:
                        if self._running and not self._queue:
                            self._cond.wait(timeout=0.002)
                else:
                    try:
                        self._demux_oldest()  # shutdown drain: block
                    except Exception:  # noqa: BLE001 - see above
                        ServingTelemetry.record_backstop()

    def _nap_s(self) -> float:
        """Scheduler nap (caller holds the lock): until the oldest
        head's batching deadline, capped so retry backoffs and pending
        sweeps stay responsive."""
        now = time.monotonic()
        ages = [now - r.t_submit for r in self._queue]
        rem = self.max_wait_s - (max(ages) if ages else 0.0)
        return min(0.05, max(0.001, rem))

    def _take_batch(self, force: bool) -> list | None:
        """Pop the due batch (caller holds the lock).  The head is the
        highest-priority (then oldest) request whose retry backoff has
        expired; due = solo study, batch full, deadline reached,
        preempting SLO class, or ``force`` (which also overrides
        backoff so drains terminate).  Batchmates are every eligible
        queued request sharing the head's coalesce key, in arrival
        order, up to ``max_batch``."""
        if not self._queue:
            return None
        now = time.monotonic()
        ready = (
            list(self._queue) if force
            else [r for r in self._queue if r.t_ready <= now]
        )
        if not ready:
            return None
        head = min(ready, key=lambda r: (r.priority, r.seq))
        if head.desc.solo:
            mates = [head]
        else:
            # the head rides FIRST: with more compatible requests than
            # max_batch queued, a plain arrival-order slice could cut
            # the priority-selected head out of the very batch its
            # preempt flag made due (gold would force-dispatch other
            # tenants' work while itself staying queued)
            mates = [head] + [
                r for r in ready
                if r is not head and r.desc.compatible(head.desc)
            ][: self.max_batch - 1]
        due = (
            force
            or head.desc.solo
            or head.preempt
            or len(mates) >= self.max_batch
            or (now - head.t_submit) >= self.max_wait_s
        )
        if not due:
            return None
        for r in mates:
            self._queue.remove(r)
        ServingTelemetry.record_queue_depth(len(self._queue))
        return mates

    def _dispatch(self, batch: list) -> None:
        """Launch one (possibly coalesced) batch through the runtime's
        bounded in-flight window.  Never raises: a transient fault
        (chaos-injected launch error, member loss at send) requeues the
        batch under its retry budget; anything else poisons the batch's
        handles instead of killing the scheduler."""
        from tpudes.chaos import ChaosInjected, maybe_fail
        from tpudes.parallel.runtime import RUNTIME

        points = [r.desc.sweep_point for r in batch]
        n_real = len(points)
        if n_real > 1:
            # pad the config axis to the pow2 bucket by duplicating the
            # tail point: one executable per bucket, not per batch size
            points = points + [points[-1]] * (_pow2(n_real) - n_real)
        t0 = time.monotonic()
        try:
            maybe_fail(
                "local_launch", what=f"{batch[0].desc.engine} launch"
            )
            fut = None
            if self.router is not None:
                # routed dispatch: the batch's point blocks fan out to
                # member processes (None = not routable, fall through)
                fut = self.router.launch(batch, points)
            if fut is None:
                fut = RUNTIME.submit(batch[0].desc.launch, points)
        except (ChaosInjected, MemberLostError) as e:
            if isinstance(e, MemberLostError) and self.router is not None:
                for m in e.members:
                    self.router.exclude(m)
                ServingTelemetry.record_member_lost(len(e.members))
            self._requeue(batch, e)
            return
        except Exception as e:  # noqa: BLE001 - poison, don't crash
            self._finish_batch(batch, error=e, n_real=n_real)
            return
        with self._cond:
            queue_depth = len(self._queue)
        ServingTelemetry.record_dispatch(
            batch[0].desc.engine, n_real, len(points), queue_depth
        )
        self._pending.append((fut, batch, t0))

    def _demux_oldest(self) -> int:
        """Retire the oldest pending launch and complete its handles;
        a recoverable failure requeues the batch instead.  Returns the
        number of handles COMPLETED (0 on requeue)."""
        from tpudes.chaos import ChaosInjected

        fut, batch, t0 = self._pending.popleft()
        engine = batch[0].desc.engine
        try:
            res = fut.result()
        except MemberLostError as e:
            # the member is gone (or its stream is): exclude it so the
            # requeued batch lands on survivors or the local engine
            if self.router is not None:
                for m in e.members:
                    self.router.exclude(m)
            ServingTelemetry.record_member_lost(len(e.members))
            self._requeue(batch, e)
            return 0
        except ChaosInjected as e:
            self._requeue(batch, e)
            return 0
        except Exception as e:  # noqa: BLE001 - poison, don't crash
            self._finish_batch(batch, error=e, n_real=len(batch))
            return len(batch)
        try:
            ServingTelemetry.record_launch_done(
                engine, time.monotonic() - t0
            )
            results = res if isinstance(res, list) else [res]
            now = time.monotonic()
            for r, out in zip(batch, results):  # pad tail dropped by zip
                latency = now - r.t_submit
                r.handle._complete(result=out, batch_size=len(batch))
                target = self.slo_targets.get(r.slo)
                ServingTelemetry.record_study_done(
                    engine, latency, slo=r.slo,
                    attained=target is None or latency <= target,
                )
                self._release(r.tenant)
            return len(batch)
        except Exception as e:  # noqa: BLE001 - hardening: anything
            # after a successful launch (telemetry, demux bookkeeping)
            # fails only THIS batch's still-open handles
            for r in batch:
                if not r.handle.done():
                    r.handle._complete(error=e, batch_size=len(batch))
                    self._release(r.tenant)
            return len(batch)

    def _requeue(self, batch: list, err: BaseException) -> None:
        """Put a transiently failed batch back at the queue head with
        exponential backoff; studies past their retry budget surface
        :class:`RetryBudgetError` through their handles instead."""
        now = time.monotonic()
        kept: list[_Request] = []
        dead: list[_Request] = []
        for r in batch:
            r.retries += 1
            if r.retries > self.retry_budget:
                dead.append(r)
            else:
                r.t_ready = now + self.retry_backoff_s * (
                    2 ** (r.retries - 1)
                )
                kept.append(r)
        with self._cond:
            for r in reversed(kept):
                self._queue.appendleft(r)
            self._cond.notify_all()
        if kept:
            ServingTelemetry.record_requeue(
                batch[0].desc.engine, len(kept)
            )
        for r in dead:
            ServingTelemetry.record_retry_exhausted()
            r.handle._complete(
                error=RetryBudgetError(r.retries - 1, err),
                batch_size=len(batch),
            )
            self._release(r.tenant)

    def _finish_batch(self, batch, error, n_real) -> None:
        del n_real
        for r in batch:
            r.handle._complete(error=error, batch_size=len(batch))
            self._release(r.tenant)

    def _release(self, tenant: str) -> None:
        with self._cond:
            # decrement-only (never popped): the map is bounded by the
            # distinct-tenant count, and a zero entry is a valid gauge
            self._tenant_load[tenant] = self._tenant_load.get(tenant, 1) - 1
            self._cond.notify_all()

    # --- context manager ---------------------------------------------------

    def __enter__(self) -> "StudyServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
