"""Study descriptors: what the coalescing scheduler needs to know.

A *study* is one client-requested simulation: a lowered device program
plus its PRNG key and replica count.  The serving layer's whole trick
is that two studies whose programs differ only in a **traced operand**
(scheduler id, TCP variant assignment, BSS horizon, AS load scale)
compile to the SAME executable and can ride ONE megabatched config-axis
launch — the PR-5 sweep arguments — with results demultiplexed back
per study, bit-equal to solo launches.

Each engine front-end owns a ``*_study`` extraction function (the
engine knows which of its fields are traced) returning a
:class:`StudyDescriptor`:

- ``coalesce_key`` — hashable identity of everything that must MATCH
  for two studies to share a launch: the program's static (cache-key)
  fields, the shared launch bound where the engine has one (LTE
  ``n_ttis``, dumbbell ``n_slots`` — the BSS horizon is itself the
  sweep operand, so it is absent from the BSS key), the PRNG key bytes
  (a (C, R, …) launch feeds ONE key to every point; the PR-5 equality
  guarantee is "equals the per-point launch *with the same key*"),
  the replica count, and the mesh.
- ``sweep_point`` — this study's value of the traced sweep operand.
- ``launch(points, block=False)`` — dispatch a batch: one point goes
  through the engine's PLAIN entry (so singles share the common
  non-sweep executable with every other caller); several points go
  through the config-axis sweep argument as one device launch.
- ``warm(n_points)`` — compile the executable a batch of ``n_points``
  would use, against a minimal-horizon copy of the program (horizons
  are traced operands, so the minimal-horizon compile IS the real
  one); the server's warm pool calls this at start, where
  ``TPUDES_CACHE_DIR`` turns it into a persistent-cache disk hit.
- ``solo`` — True marks a study the sweep equality guarantee cannot
  cover (e.g. a dumbbell program whose ``ecn`` disagrees with the
  variants' ``REQUIRES_ECN`` flags — sweep points derive ECN from the
  variant); the server never batches it with anything.
- ``spec`` — a **picklable** launch description
  (``{"engine", "prog", "key", "replicas"}``) for studies that can be
  routed to a member process of a multi-process mesh
  (:mod:`tpudes.serving.distributed`): the member rebuilds the
  descriptor from the spec through the same ``*_study`` extractor and
  launches its slice of the batch's points.  ``None`` (e.g. a study
  pinned to a live device mesh) keeps the study host-local.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

__all__ = ["StudyDescriptor", "mesh_fingerprint"]


def mesh_fingerprint(mesh) -> tuple | None:
    """Hashable identity of a mesh for the coalesce key (two studies
    must target the same device set to share a launch)."""
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(d.id for d in mesh.devices.flat),
    )


@dataclass(frozen=True)
class StudyDescriptor:
    """One submitted study, as the coalescing scheduler sees it."""

    engine: str
    coalesce_key: tuple
    sweep_point: Any
    launch: Callable  # (points, block=False) -> result | EngineFuture
    warm: Callable = None  # (n_points) -> None, blocking mini-compile
    solo: bool = field(default=False)
    #: picklable launch spec for cross-process routing (None = local)
    spec: dict | None = field(default=None, compare=False)

    def compatible(self, other: "StudyDescriptor") -> bool:
        """True when ``self`` and ``other`` may share one launch."""
        return (
            not self.solo
            and not other.solo
            and self.engine == other.engine
            and self.coalesce_key == other.coalesce_key
        )
