"""Cross-process study routing: the StudyServer on a process mesh.

ROADMAP item 4/6: "the serving layer routes studies to member
processes" — and survives those members dying.  A :class:`ProcessRouter`
plugs into ``StudyServer(router=...)``: when a coalesced batch's
studies carry a picklable ``spec`` (see :class:`~tpudes.serving.
descriptor.StudyDescriptor`), the router splits the batch's config
points into contiguous per-process blocks (:func:`~tpudes.parallel.
procmesh.process_slice`), keeps block 0 on the serving process (through
the descriptor's own launch, inside ``RUNTIME``'s in-flight window) and
ships the other blocks to member processes over the
:class:`~tpudes.parallel.mpi.MpiInterface` framed pipes.  Each member
rebuilds the descriptor from the spec through the SAME ``*_study``
extractor and launches its block — so every split result is covered by
the PR-5 sweep bit-equality contract, and the reassembled batch is
bit-equal to the unrouted launch (tests/test_procmesh.py pins it).

Fault model (ISSUE 13): a member is **lost** when its pipe hits EOF
(the process died — e.g. SIGKILL mid-batch), a frame fails
:class:`~tpudes.parallel.mpi.WireFormatError` validation (the stream
cannot be resynchronized), or its reply misses ``member_timeout_s`` (a
hung member's late reply would desync the next batch on that pipe).
All three surface as a typed :class:`~tpudes.serving.errors.
MemberLostError` carrying the member ids — never a raw pickle/pipe
exception — and the StudyServer requeues the whole batch onto the
survivors (or the local engine) after :meth:`ProcessRouter.exclude`
retires the member.  Requeued batches are re-coalesced and relaunched
through the same descriptors, so recovered results stay bit-equal to a
failure-free run.

Members run :func:`serve_studies` — a poll-with-timeout loop on the
pipe to the serving rank (a dead serving rank is an EOF exit, not a
hang) — until the router closes.  Chaos injection sites
(``member_study``, ``router_send``, ``router_recv`` — see
:mod:`tpudes.chaos`) make every failure mode above a replayable
integer seed.
"""

from __future__ import annotations

import importlib
import time

import numpy as np

from tpudes.serving.errors import MemberLostError

__all__ = ["MemberLostError", "ProcessRouter", "serve_studies"]


class _RoutedFuture:
    """Future over one routed batch: the local block's EngineFuture
    plus the member replies still in flight.  Duck-types the
    ``done()/result()`` surface StudyServer's demux loop uses, plus
    ``deadline`` (monotonic seconds) past which the scheduler force-
    demuxes so a hung member cannot pin the batch forever."""

    def __init__(self, local_fut, local_n, remote, local_error=None,
                 timeout_s: float = 60.0, lost_at_send=()):
        self._local_fut = local_fut
        self._local_n = local_n
        self._remote = remote          # [(member, conn, n_points), ...]
        self._local_error = local_error
        #: members whose study frame never went out (they died at send
        #: time): their blocks are simply missing, so the batch must
        #: requeue — but the SENT members' replies still get drained
        #: here first, keeping their pipes frame-synced
        self._lost_at_send = tuple(lost_at_send)
        self._timeout_s = timeout_s
        self.deadline = time.monotonic() + timeout_s
        self._result = None
        self._done = False

    def done(self) -> bool:
        if self._done:
            return True
        if self._local_fut is not None and not self._local_fut.done():
            return False
        # a dead member's pipe polls ready (EOF is readable), so a
        # killed member never wedges this sweep
        return all(conn.poll() for _, conn, _ in self._remote)

    def result(self):
        from tpudes.parallel.mpi import WireFormatError, recv_frame

        if self._done:
            if isinstance(self._result, Exception):
                raise self._result
            return self._result
        # gate the member reply budget on the LOCAL block first: it is
        # a same-sized slice of the same computation on this host, so
        # members get member_timeout_s measured from when comparable
        # work finished here — a long-horizon routed batch must not see
        # its healthy members declared lost just because the compute
        # wall exceeded the dispatch-relative deadline
        if self._local_fut is not None and self._local_error is None:
            try:
                self._local_fut.result()  # memoized; reused below
            except Exception as e:  # noqa: BLE001 - surfaced after drain
                self._local_error = e
            self.deadline = max(
                self.deadline, time.monotonic() + self._timeout_s
            )
        # drain EVERY member reply, even when something already
        # failed: a frame left on a shared pipe would be read by the
        # NEXT routed batch's future, silently desyncing every routed
        # launch after one poisoned batch.  Per-member failures are
        # collected (not raised mid-drain) for the same reason.
        replies: list = []
        lost: list = [(m, EOFError("died at send")) for m in
                      self._lost_at_send]
        for member, conn, n in self._remote:
            budget = max(0.05, self.deadline - time.monotonic())
            try:
                replies.append((member, n, recv_frame(
                    conn, timeout_s=budget,
                    chaos_site="router_recv", member=member,
                )))
            except (EOFError, OSError, TimeoutError, WireFormatError) as e:
                lost.append((member, e))
        self._done = True
        try:
            out: list = []
            if lost:
                detail = "; ".join(
                    f"member {m}: {type(e).__name__}: {e}"
                    for m, e in lost
                )
                raise MemberLostError([m for m, _ in lost], detail)
            if self._local_error is not None:
                raise self._local_error
            if self._local_fut is not None:
                res = self._local_fut.result()
                local = res if isinstance(res, list) else [res]
                if len(local) != self._local_n:
                    raise RuntimeError(
                        f"local block returned {len(local)} results for "
                        f"{self._local_n} points"
                    )
                out.extend(local)
            for member, n, (kind, payload) in replies:
                if kind == "error":
                    raise RuntimeError(
                        f"routed member {member} launch failed:\n{payload}"
                    )
                if len(payload) != n:
                    raise RuntimeError(
                        f"routed member {member} returned {len(payload)} "
                        f"results for {n} points"
                    )
                out.extend(payload)
        except Exception as e:
            self._result = e
            raise
        self._result = out
        return out


class ProcessRouter:
    """Splits coalesced batches across the member processes reachable
    over ``conns`` (peer rank -> Connection, e.g.
    ``MpiInterface._conns`` inside a :func:`launch_process_mesh`
    worker).  Members declared lost via :meth:`exclude` never receive
    another frame — their pipe state is untrusted once a batch failed
    on them."""

    def __init__(self, conns: dict, member_timeout_s: float = 60.0):
        self._members = [(m, c) for m, c in sorted(conns.items())]
        self.member_timeout_s = float(member_timeout_s)
        self.routed_batches = 0
        self.routed_points = 0
        self._dead: set = set()
        self._closed = False

    def exclude(self, member) -> None:
        """Retire a member (dead, corrupt stream, or timed out): no
        future launch routes to it and close() skips it."""
        self._dead.add(member)

    @property
    def live_members(self) -> list:
        return [m for m, _ in self._members if m not in self._dead]

    def launch(self, batch, points):
        """Dispatch one batch, split across the serving process + live
        members; returns a :class:`_RoutedFuture`, or None when the
        batch cannot be routed (single point, no live members, or a
        spec-less study) — the caller falls back to the plain local
        launch."""
        from tpudes.parallel.mpi import send_frame
        from tpudes.parallel.procmesh import process_slice
        from tpudes.parallel.runtime import RUNTIME

        live = [(m, c) for m, c in self._members if m not in self._dead]
        n_procs = len(live) + 1
        if self._closed or n_procs < 2 or len(points) < 2:
            return None
        if any(r.desc.spec is None for r in batch):
            return None
        spec = batch[0].desc.spec
        bounds = [
            process_slice(len(points), n_procs, p) for p in range(n_procs)
        ]
        remote = []
        lost_at_send = []
        for p, (member, conn) in enumerate(live, start=1):
            lo, hi = bounds[p]
            if hi <= lo:
                continue
            try:
                send_frame(conn, (
                    "study",
                    dict(
                        engine=spec["engine"],
                        prog=spec["prog"],
                        key=np.asarray(spec["key"]),
                        replicas=spec["replicas"],
                        points=list(points[lo:hi]),
                    ),
                ), chaos_site="router_send", member=member)
            except (OSError, ValueError, BrokenPipeError):
                # the member died at send time.  Do NOT re-split and
                # resend: earlier members already hold frames for THIS
                # split, and a second frame would desync their reply
                # pipes for every later batch.  Mark the block lost —
                # the future drains the sent members' replies (pipes
                # stay synced), then raises MemberLostError and the
                # whole batch requeues without the dead member.
                self.exclude(member)
                lost_at_send.append(member)
                continue
            remote.append((member, conn, hi - lo))
        lo, hi = bounds[0]
        local_fut = local_error = None
        if hi > lo:
            try:
                local_fut = RUNTIME.submit(
                    batch[0].desc.launch, list(points[lo:hi])
                )
            except Exception as e:  # noqa: BLE001 - member frames are
                # already in flight; the future must still drain their
                # replies before surfacing this, or the pipes desync
                local_error = e
        self.routed_batches += 1
        self.routed_points += sum(n for _, _, n in remote)
        return _RoutedFuture(
            local_fut, hi - lo, remote, local_error,
            timeout_s=self.member_timeout_s,
            lost_at_send=lost_at_send,
        )

    def close(self) -> None:
        """Tell every member's :func:`serve_studies` loop to exit —
        best-effort even toward excluded members (an excluded member
        may be alive with a merely-untrusted stream, and the close
        frame is the only thing that releases its loop; a truly dead
        member's pipe just raises and is ignored)."""
        from tpudes.parallel.mpi import pack_frame

        if self._closed:
            return
        self._closed = True
        for _member, conn in self._members:
            try:
                conn.send_bytes(pack_frame(("close", None)))
            except (OSError, ValueError):
                pass


def serve_studies(conn, member_id=None, poll_s: float = 1.0) -> int:
    """Member-process loop: execute routed launch specs arriving on
    ``conn`` (the pipe to the serving rank) until a close frame;
    returns the number of launches served.  The spec rebuilds the
    study through the engine's own ``*_study`` extractor, so a member
    launch takes exactly the code path a local launch takes.

    The wait is a poll-with-timeout loop (never a bare blocking recv —
    analysis rule SRV001): a dead serving rank surfaces as EOF and the
    loop returns instead of hanging forever.  A frame that fails wire
    validation ends the loop too — the stream cannot be resynchronized,
    so the member retires and the router's MemberLostError path takes
    over.  Chaos site ``member_study`` fires before each study: a
    ``kill_member`` event SIGKILLs this process (or raises, in
    thread-member test mode), ``slow_member`` sleeps through the
    router's timeout.
    """
    import traceback

    from tpudes.parallel.mpi import WireFormatError, pack_frame, recv_frame

    if member_id is None:
        from tpudes.parallel.mpi import MpiInterface

        member_id = (
            MpiInterface.GetSystemId() if MpiInterface.IsEnabled() else None
        )
    served = 0
    while True:
        if not conn.poll(poll_s):
            continue
        try:
            kind, payload = recv_frame(conn)
        except (EOFError, OSError):
            return served  # serving rank is gone: clean exit
        except WireFormatError:
            try:
                conn.close()
            except OSError:
                pass
            return served  # poisoned stream: retire this member
        if kind == "close":
            return served
        if kind != "study":
            raise RuntimeError(f"unexpected routed frame kind {kind!r}")
        _maybe_die(member_id)
        try:
            mod_name, fn_name = _engine_study(payload["engine"])
            desc = _engine_extract(mod_name, fn_name)(
                payload["prog"], payload["key"], payload["replicas"]
            )
            res = desc.launch(payload["points"])
            if hasattr(res, "result"):  # EngineFuture: resolve to host
                res = res.result()      # numpy before the wire
            results = res if isinstance(res, list) else [res]
            conn.send_bytes(pack_frame(("result", results)))
            served += 1
        except Exception:  # noqa: BLE001 - poison the batch, not the loop
            conn.send_bytes(pack_frame(("error", traceback.format_exc())))


def _maybe_die(member_id) -> None:
    """The ``member_study`` chaos site: SIGKILL (process members) or
    raise (thread members, ``param=="raise"``) when the armed schedule
    plants a death here; sleep on ``slow_member``."""
    from tpudes.chaos import ChaosInjected, fire

    ev = fire("member_study", member=member_id)
    if ev is None:
        return
    if ev.kind == "kill_member":
        if ev.param == "raise":
            raise ChaosInjected(
                f"chaos-injected member death (member {member_id})"
            )
        import os
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    elif ev.kind == "slow_member":
        time.sleep(float(ev.param or 0.1))


def _engine_study(engine: str):
    from tpudes.serving.server import _ENGINE_STUDY

    return _ENGINE_STUDY[engine]


def _engine_extract(mod_name: str, fn_name: str):
    return getattr(importlib.import_module(mod_name), fn_name)
