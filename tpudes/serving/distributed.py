"""Cross-process study routing: the StudyServer on a process mesh.

ROADMAP item 4: "the serving layer routes studies to member
processes".  A :class:`ProcessRouter` plugs into
``StudyServer(router=...)``: when a coalesced batch's studies carry a
picklable ``spec`` (see :class:`~tpudes.serving.descriptor.
StudyDescriptor`), the router splits the batch's config points into
contiguous per-process blocks (:func:`~tpudes.parallel.procmesh.
process_slice`), keeps block 0 on the serving process (through the
descriptor's own launch, inside ``RUNTIME``'s in-flight window) and
ships the other blocks to member processes over the
:class:`~tpudes.parallel.mpi.MpiInterface` control pipes (framed wire
format).  Each member rebuilds the descriptor from the spec through the
SAME ``*_study`` extractor and launches its block — so every split
result is covered by the PR-5 sweep bit-equality contract, and the
reassembled batch is bit-equal to the unrouted launch
(tests/test_procmesh.py pins it).

Members run :func:`serve_studies` — a blocking loop on the pipe to the
serving rank — until the router closes.  On multi-host TPU the same
topology applies with one serving process per pod slice; the CPU CI
exercises the full round trip on two local processes.
"""

from __future__ import annotations

import importlib

import numpy as np

__all__ = ["ProcessRouter", "serve_studies"]


class _RoutedFuture:
    """Future over one routed batch: the local block's EngineFuture
    plus the member replies still in flight.  Duck-types the
    ``done()/result()`` surface StudyServer's demux loop uses."""

    def __init__(self, local_fut, local_n, remote, local_error=None):
        self._local_fut = local_fut
        self._local_n = local_n
        self._remote = remote          # [(conn, n_points), ...] rank order
        self._local_error = local_error
        self._result = None
        self._done = False

    def done(self) -> bool:
        if self._done:
            return True
        if self._local_fut is not None and not self._local_fut.done():
            return False
        return all(conn.poll() for conn, _ in self._remote)

    def result(self):
        from tpudes.parallel.mpi import unpack_frame

        if self._done:
            if isinstance(self._result, Exception):
                raise self._result
            return self._result
        # drain EVERY member reply FIRST, even when something already
        # failed: a frame left on a shared pipe would be read by the
        # NEXT routed batch's future, silently desyncing every routed
        # launch after one poisoned batch
        replies = [
            (n, unpack_frame(conn.recv_bytes())) for conn, n in self._remote
        ]
        self._done = True
        try:
            out: list = []
            if self._local_error is not None:
                raise self._local_error
            if self._local_fut is not None:
                res = self._local_fut.result()
                local = res if isinstance(res, list) else [res]
                if len(local) != self._local_n:
                    raise RuntimeError(
                        f"local block returned {len(local)} results for "
                        f"{self._local_n} points"
                    )
                out.extend(local)
            for n, (kind, payload) in replies:
                if kind == "error":
                    raise RuntimeError(
                        f"routed member launch failed:\n{payload}"
                    )
                if len(payload) != n:
                    raise RuntimeError(
                        f"routed member returned {len(payload)} results "
                        f"for {n} points"
                    )
                out.extend(payload)
        except Exception as e:
            self._result = e
            raise
        self._result = out
        return out


class ProcessRouter:
    """Splits coalesced batches across the member processes reachable
    over ``conns`` (peer rank -> Connection, e.g.
    ``MpiInterface._conns`` inside a :func:`launch_process_mesh`
    worker)."""

    def __init__(self, conns: dict):
        self._conns = [c for _, c in sorted(conns.items())]
        self.routed_batches = 0
        self.routed_points = 0
        self._closed = False

    def launch(self, batch, points):
        """Dispatch one batch, split across processes; returns a
        :class:`_RoutedFuture`, or None when the batch cannot be routed
        (single point, no members, or a spec-less study) — the caller
        falls back to the plain local launch."""
        from tpudes.parallel.mpi import pack_frame
        from tpudes.parallel.procmesh import process_slice
        from tpudes.parallel.runtime import RUNTIME

        n_procs = len(self._conns) + 1
        if self._closed or n_procs < 2 or len(points) < 2:
            return None
        if any(r.desc.spec is None for r in batch):
            return None
        spec = batch[0].desc.spec
        bounds = [
            process_slice(len(points), n_procs, p) for p in range(n_procs)
        ]
        remote = []
        for p, conn in enumerate(self._conns, start=1):
            lo, hi = bounds[p]
            if hi <= lo:
                continue
            conn.send_bytes(pack_frame((
                "study",
                dict(
                    engine=spec["engine"],
                    prog=spec["prog"],
                    key=np.asarray(spec["key"]),
                    replicas=spec["replicas"],
                    points=list(points[lo:hi]),
                ),
            )))
            remote.append((conn, hi - lo))
        lo, hi = bounds[0]
        local_fut = local_error = None
        if hi > lo:
            try:
                local_fut = RUNTIME.submit(
                    batch[0].desc.launch, list(points[lo:hi])
                )
            except Exception as e:  # noqa: BLE001 - member frames are
                # already in flight; the future must still drain their
                # replies before surfacing this, or the pipes desync
                local_error = e
        self.routed_batches += 1
        self.routed_points += sum(n for _, n in remote)
        return _RoutedFuture(local_fut, hi - lo, remote, local_error)

    def close(self) -> None:
        """Tell every member's :func:`serve_studies` loop to exit."""
        from tpudes.parallel.mpi import pack_frame

        if self._closed:
            return
        self._closed = True
        for conn in self._conns:
            try:
                conn.send_bytes(pack_frame(("close", None)))
            except (OSError, ValueError):
                pass


def serve_studies(conn) -> int:
    """Member-process loop: execute routed launch specs arriving on
    ``conn`` (the pipe to the serving rank) until a close frame;
    returns the number of launches served.  The spec rebuilds the
    study through the engine's own ``*_study`` extractor, so a member
    launch takes exactly the code path a local launch takes."""
    import traceback

    from tpudes.parallel.mpi import pack_frame, unpack_frame
    from tpudes.serving.server import _ENGINE_STUDY

    served = 0
    while True:
        kind, payload = unpack_frame(conn.recv_bytes())
        if kind == "close":
            return served
        if kind != "study":
            raise RuntimeError(f"unexpected routed frame kind {kind!r}")
        try:
            mod_name, fn_name = _ENGINE_STUDY[payload["engine"]]
            extract = getattr(importlib.import_module(mod_name), fn_name)
            desc = extract(
                payload["prog"], payload["key"], payload["replicas"]
            )
            res = desc.launch(payload["points"])
            if hasattr(res, "result"):  # EngineFuture: resolve to host
                res = res.result()      # numpy before the wire
            results = res if isinstance(res, list) else [res]
            conn.send_bytes(pack_frame(("result", results)))
            served += 1
        except Exception:  # noqa: BLE001 - poison the batch, not the loop
            conn.send_bytes(pack_frame(("error", traceback.format_exc())))
