"""tpudes.serving — simulation-as-a-service on the engine runtime.

A long-lived :class:`StudyServer` accepts independently arriving
*studies* (one lowered device program + key + replicas each) and
coalesces compatible ones onto shared megabatched config-axis device
launches — continuous batching for simulation studies, built on the
PR-5 sweep arguments whose per-point results are pinned bit-equal to
solo launches.  See :mod:`tpudes.serving.server` for the scheduling
story (batching deadline, admission control, pow2 batch buckets, warm
pool) and :mod:`tpudes.obs.serving` for the metrics surface.

Quick start::

    from tpudes.serving import StudyServer

    server = StudyServer(max_wait_s=0.005, max_batch=8)
    handles = [
        server.submit_study("lte_sm", prog, key, replicas=64,
                            tenant=f"user{i}")
        for i, prog in enumerate(programs)      # e.g. 9 schedulers
    ]
    results = [h.result() for h in handles]     # demuxed per study
    print(server.metrics()["coalesce_rate"])
    server.close()
"""

from tpudes.parallel.checkpoint import CarryCheckpoint, CheckpointError
from tpudes.serving.descriptor import StudyDescriptor, mesh_fingerprint
from tpudes.serving.distributed import ProcessRouter, serve_studies
from tpudes.serving.errors import MemberLostError, RetryBudgetError
from tpudes.serving.server import (
    SLO_CLASSES,
    AdmissionError,
    StudyHandle,
    StudyServer,
)

__all__ = [
    "SLO_CLASSES",
    "AdmissionError",
    "CarryCheckpoint",
    "CheckpointError",
    "MemberLostError",
    "ProcessRouter",
    "RetryBudgetError",
    "StudyDescriptor",
    "StudyHandle",
    "StudyServer",
    "mesh_fingerprint",
    "serve_studies",
]
