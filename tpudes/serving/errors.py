"""Typed serving-layer failures (the requeue path's vocabulary).

The fault-tolerance contract (ISSUE 13) needs the scheduler to tell
*recoverable* transport faults apart from *deterministic* study
failures: a member process dying mid-batch is recoverable (requeue the
batch onto survivors or the local engine — results are bit-equal by
the coalesce/demux contract), while a study whose program is genuinely
broken must not burn the retry budget pretending otherwise.
"""

from __future__ import annotations

__all__ = ["MemberLostError", "RetryBudgetError"]


class MemberLostError(RuntimeError):
    """A routed member process is gone or its frame stream is no longer
    trustworthy: EOF/closed pipe (the process died), a
    :class:`~tpudes.parallel.mpi.WireFormatError` (truncated/corrupted/
    mixed-version frame — the stream cannot be resynchronized), or a
    reply timeout (a hung member is indistinguishable from a dead one
    and its late reply would desync the next batch).  Carries the
    member ids so the router can exclude them from future launches."""

    def __init__(self, members, detail: str = ""):
        self.members = tuple(members)
        msg = f"routed member(s) {list(self.members)} lost"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class RetryBudgetError(RuntimeError):
    """A study was requeued past its retry budget; ``__cause__`` chains
    the last transient failure.  Raised through the study's handle —
    the caller decides whether to resubmit."""

    def __init__(self, retries: int, last: BaseException):
        super().__init__(
            f"study failed after {retries} retries "
            f"(last: {type(last).__name__}: {last})"
        )
        self.retries = retries
        self.__cause__ = last
