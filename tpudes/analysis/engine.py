"""Analysis driver: file collection, pass execution, rule selection,
suppression filtering, baseline ratchet.

The baseline (``tools/analysis_baseline.json``) is a count-per-key
ratchet: pre-existing findings gate *new* regressions without forcing
a repo-wide cleanup.  A finding's key is ``path:CODE:message`` (no
line number), so edits that merely move a known finding do not fire.
"""

from __future__ import annotations

import json
from pathlib import Path

from tpudes.analysis.base import Finding, Pass, SourceModule

#: default roots, relative to the project root (cwd for the CLI)
DEFAULT_ROOTS = ("tpudes", "tests", "examples", "tools")
DEFAULT_BASELINE = "tools/analysis_baseline.json"

ALL_PASSES: list[Pass] = []
_builtins_loaded = False


def register_pass(pass_cls: type) -> type:
    """Add a Pass subclass to the global registry (plugin hook);
    returns the class so it can be used as a decorator."""
    ALL_PASSES.append(pass_cls())
    return pass_cls


def _ensure_builtins():
    # flag-guarded, not emptiness-guarded: a plugin registered before
    # the first analysis must not displace the builtin passes
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from tpudes.analysis.passes import BUILTIN_PASSES

    for cls in BUILTIN_PASSES:
        register_pass(cls)


def _selected(code: str, select, ignore) -> bool:
    """Prefix match, ruff-style: --select RNG keeps RNG001+RNG002."""
    if select and not any(code.startswith(s) for s in select):
        return False
    if ignore and any(code.startswith(s) for s in ignore):
        return False
    return True


def collect_modules(paths: list[Path], root: Path) -> list[SourceModule]:
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    mods = []
    seen: set[Path] = set()
    for f in files:
        resolved = f.resolve()
        if resolved in seen:
            continue  # overlapping path args must not double-count
        seen.add(resolved)
        try:
            rel = resolved.relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        mods.append(SourceModule.from_file(f, rel))
    return mods


def run_passes(
    mods: list[SourceModule],
    passes: list[Pass] | None = None,
    select: list[str] | None = None,
    ignore: list[str] | None = None,
    project_passes: bool = True,
) -> list[Finding]:
    _ensure_builtins()
    passes = ALL_PASSES if passes is None else passes
    by_path = {m.path: m for m in mods}
    findings: list[Finding] = []
    for p in passes:
        if select or ignore:
            if not any(_selected(c, select, ignore) for c in p.codes):
                continue
        if p.project_wide:
            # cross-file passes are sound only over the full module
            # set: a subtree scan cannot see references living outside
            # it and would flag live registrations as dead
            if not project_passes:
                continue
            found = p.check_project(mods)
        else:
            found = []
            for mod in mods:
                if not p.applies(mod.path):
                    continue
                if mod.tree is None and not p.handles_syntax_errors:
                    continue
                found.extend(p.check_module(mod))
        findings.extend(found)
    out = []
    for f in findings:
        if not _selected(f.code, select, ignore):
            continue
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.code):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def analyze_paths(
    paths: list[str | Path],
    root: str | Path = ".",
    select: list[str] | None = None,
    ignore: list[str] | None = None,
    project_passes: bool = True,
) -> list[Finding]:
    root = Path(root)
    mods = collect_modules([Path(p) for p in paths], root)
    return run_passes(mods, select=select, ignore=ignore,
                      project_passes=project_passes)


def analyze_source(
    source: str,
    path: str = "tpudes/snippet.py",
    select: list[str] | None = None,
    extra_modules: list[tuple[str, str]] | None = None,
) -> list[Finding]:
    """Analyze an in-memory snippet (the fixture-test entry point).
    ``path`` participates in pass scoping (e.g. ``tpudes/ops/x.py``
    lands in the device-path scope); ``extra_modules`` are
    ``(path, source)`` companions for project-wide passes."""
    mods = [SourceModule(path, source)]
    for p, src in extra_modules or ():
        mods.append(SourceModule(p, src))
    return [f for f in run_passes(mods, select=select) if f.path == path]


# --- baseline ratchet -----------------------------------------------------

def load_baseline(path: str | Path) -> dict[str, int]:
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def baseline_counts(findings: list[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def write_baseline(path: str | Path, findings: list[Finding]) -> None:
    counts = baseline_counts(findings)
    payload = {
        "version": 1,
        "comment": (
            "Known findings gated by `python -m tpudes.analysis`. Keys are "
            "path:CODE:message (line-free). Regenerate with "
            "--write-baseline after an intentional cleanup."
        ),
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n")


def new_findings(
    findings: list[Finding], baseline: dict[str, int]
) -> list[Finding]:
    """Findings beyond the baselined count for their key."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            out.append(f)
    return out
