"""Analysis driver: file collection, pass execution, rule selection,
suppression filtering, baseline ratchet.

The baseline (``tools/analysis_baseline.json``) is a count-per-key
ratchet: pre-existing findings gate *new* regressions without forcing
a repo-wide cleanup.  A finding's key is ``path:CODE:message`` (no
line number), so edits that merely move a known finding do not fire.
"""

from __future__ import annotations

import json
from pathlib import Path

from tpudes.analysis.base import Finding, Pass, SourceModule

#: default roots, relative to the project root (cwd for the CLI)
DEFAULT_ROOTS = ("tpudes", "tests", "examples", "tools")
DEFAULT_BASELINE = "tools/analysis_baseline.json"

ALL_PASSES: list[Pass] = []
_builtins_loaded = False


def register_pass(pass_cls: type) -> type:
    """Add a Pass subclass to the global registry (plugin hook);
    returns the class so it can be used as a decorator."""
    ALL_PASSES.append(pass_cls())
    return pass_cls


def _ensure_builtins():
    # flag-guarded, not emptiness-guarded: a plugin registered before
    # the first analysis must not displace the builtin passes
    global _builtins_loaded
    if _builtins_loaded:
        return
    _builtins_loaded = True
    from tpudes.analysis.passes import BUILTIN_PASSES

    for cls in BUILTIN_PASSES:
        register_pass(cls)


def _selected(code: str, select, ignore) -> bool:
    """Prefix match, ruff-style: --select RNG keeps RNG001+RNG002."""
    if select and not any(code.startswith(s) for s in select):
        return False
    if ignore and any(code.startswith(s) for s in ignore):
        return False
    return True


def collect_modules(paths: list[Path], root: Path) -> list[SourceModule]:
    files: list[Path] = []
    for p in paths:
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    mods = []
    seen: set[Path] = set()
    for f in files:
        resolved = f.resolve()
        if resolved in seen:
            continue  # overlapping path args must not double-count
        seen.add(resolved)
        try:
            rel = resolved.relative_to(root.resolve()).as_posix()
        except ValueError:
            rel = f.as_posix()
        mods.append(SourceModule.from_file(f, rel))
    return mods


def _pass_selected(p: Pass, select, ignore) -> bool:
    if not (select or ignore):
        return True
    return any(_selected(c, select, ignore) for c in p.codes)


def _suppress_filter(findings, by_path):
    out = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.suppressed(f.line, f.code):
            continue
        out.append(f)
    return out


def run_passes(
    mods: list[SourceModule],
    passes: list[Pass] | None = None,
    select: list[str] | None = None,
    ignore: list[str] | None = None,
    project_passes: bool = True,
    jaxpr: bool = False,
    cache=None,
) -> list[Finding]:
    """Run the pass pipeline.  ``cache`` (an
    :class:`~tpudes.analysis.cache.AnalysisCache`) serves per-file and
    whole-set findings by content hash; the cache is only WRITTEN by
    un-narrowed runs (no select/ignore, default pass set), so narrowed
    runs can read it but never poison it.  ``jaxpr=True`` appends the
    trace-aware JXL pass family, cached as one whole-set entry under a
    stricter key (pass-family version + every scanned ``tpudes/``
    module hash + jax version — see ``AnalysisCache.jaxpr_sha``)."""
    _ensure_builtins()
    default_set = passes is None
    passes = ALL_PASSES if passes is None else passes
    by_path = {m.path: m for m in mods}
    if not default_set:
        cache = None  # a custom pass set must not read full-run results
    if cache is not None and any(
        not type(p).__module__.startswith("tpudes.analysis")
        for p in passes
    ):
        # third-party register_pass plugins live outside the analyzer
        # tree, so the rules fingerprint cannot see their edits — a
        # cache here could serve stale plugin findings
        cache = None
    cache_writable = (
        cache is not None and not select and not ignore
    )
    findings: list[Finding] = []

    module_passes = [
        p for p in passes
        if not p.project_wide and _pass_selected(p, select, ignore)
    ]
    any_module_pass = any(not p.project_wide for p in passes)
    for mod in mods:
        if cache is not None and any_module_pass:
            cached = cache.get_file(mod.path, mod.sha)
            if cached is not None:
                findings.extend(cached)
                continue
        found: list[Finding] = []
        for p in module_passes:
            if not p.applies(mod.path):
                continue
            if mod.tree is None and not p.handles_syntax_errors:
                continue
            found.extend(p.check_module(mod))
        found = _suppress_filter(found, by_path)
        if cache_writable:
            cache.put_file(mod.path, mod.sha, found)
        findings.extend(found)
    if cache_writable:
        cache.prune(by_path)  # renamed/deleted files must not linger

    # cross-file passes are sound only over the full module set: a
    # subtree scan cannot see references living outside it and would
    # flag live registrations as dead
    proj_passes = [
        p for p in passes
        if p.project_wide and _pass_selected(p, select, ignore)
    ]
    if project_passes and any(p.project_wide for p in passes):
        psha = None
        cached = None
        if cache is not None:
            from tpudes.analysis.cache import AnalysisCache

            psha = AnalysisCache.project_sha(mods)
            cached = cache.get_project(psha)
        if cached is not None:
            findings.extend(cached)
        else:
            found = []
            for p in proj_passes:
                found.extend(p.check_project(mods))
            found = _suppress_filter(found, by_path)
            if cache_writable and psha is not None:
                cache.put_project(psha, found)
            findings.extend(found)

    if jaxpr:
        # the trace-aware family runs regardless of project_passes: it
        # lints the engine manifests, not the scanned module set
        from tpudes.analysis.jaxpr import JAXPR_PASSES

        jx_passes = [cls() for cls in JAXPR_PASSES]
        if any(_pass_selected(p, select, ignore) for p in jx_passes):
            jsha = None
            jx_cached = None
            if cache is not None:
                from tpudes.analysis.cache import AnalysisCache

                jsha = AnalysisCache.jaxpr_sha(mods)
                jx_cached = cache.get_jaxpr(jsha)
            if jx_cached is not None:
                # warm path: no jax import, no tracing — this is what
                # keeps repeat --jaxpr gate runs under a second
                findings.extend(jx_cached)
            else:
                found = []
                for p in jx_passes:
                    if _pass_selected(p, select, ignore):
                        found.extend(p.check_project(mods))
                found = _suppress_filter(found, by_path)
                # writable implies no select/ignore, so every pass in
                # the family ran and the cached set is complete
                if cache_writable and jsha is not None:
                    cache.put_jaxpr(jsha, found)
                findings.extend(found)

    out = [f for f in findings if _selected(f.code, select, ignore)]
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    return out


def analyze_paths(
    paths: list[str | Path],
    root: str | Path = ".",
    select: list[str] | None = None,
    ignore: list[str] | None = None,
    project_passes: bool = True,
    jaxpr: bool = False,
    cache=None,
) -> list[Finding]:
    root = Path(root)
    mods = collect_modules([Path(p) for p in paths], root)
    return run_passes(mods, select=select, ignore=ignore,
                      project_passes=project_passes, jaxpr=jaxpr,
                      cache=cache)


def analyze_source(
    source: str,
    path: str = "tpudes/snippet.py",
    select: list[str] | None = None,
    extra_modules: list[tuple[str, str]] | None = None,
) -> list[Finding]:
    """Analyze an in-memory snippet (the fixture-test entry point).
    ``path`` participates in pass scoping (e.g. ``tpudes/ops/x.py``
    lands in the device-path scope); ``extra_modules`` are
    ``(path, source)`` companions for project-wide passes."""
    mods = [SourceModule(path, source)]
    for p, src in extra_modules or ():
        mods.append(SourceModule(p, src))
    return [f for f in run_passes(mods, select=select) if f.path == path]


# --- baseline ratchet -----------------------------------------------------

def load_baseline(path: str | Path) -> dict[str, int]:
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    return {str(k): int(v) for k, v in data.get("counts", {}).items()}


def baseline_counts(findings: list[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def write_baseline(path: str | Path, findings: list[Finding]) -> None:
    counts = baseline_counts(findings)
    payload = {
        "version": 1,
        "comment": (
            "Known findings gated by `python -m tpudes.analysis`. Keys are "
            "path:CODE:message (line-free). Regenerate with "
            "`python -m tpudes.analysis --jaxpr --write-baseline` after an "
            "intentional cleanup (--jaxpr so the JXL trace rules stay "
            "covered)."
        ),
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n")


def new_findings(
    findings: list[Finding], baseline: dict[str, int]
) -> list[Finding]:
    """Findings beyond the baselined count for their key."""
    remaining = dict(baseline)
    out = []
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
        else:
            out.append(f)
    return out
