"""Analyzer plumbing: findings, parsed modules, suppressions, the Pass
plugin API.

Design (mirrors the dependency-free AST-gate approach tools/lint.py
proved out — SURVEY.md §2.11): every check is a ``Pass`` with a stable
rule-code namespace; passes see ``SourceModule`` objects (source + AST
+ per-line suppressions) and emit ``Finding``s.  Baseline identity is
``path:CODE:message`` — deliberately line-number-free so unrelated
edits above a pre-existing finding do not re-flag it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

#: inline suppression: ``# tpudes: ignore`` silences every rule on the
#: line, ``# tpudes: ignore[RNG001,DET002]`` the listed codes only
_SUPPRESS_RE = re.compile(
    r"#\s*tpudes:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?"
)


class Finding:
    """One diagnostic: location + rule code + message."""

    __slots__ = ("path", "line", "col", "code", "message")

    def __init__(self, path: str, line: int, col: int, code: str, message: str):
        self.path = path
        self.line = line
        self.col = col
        self.code = code
        self.message = message

    @property
    def key(self) -> str:
        """Baseline identity (line-number-free on purpose)."""
        return f"{self.path}:{self.code}:{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "code": self.code,
            "message": self.message,
        }

    def __repr__(self):
        return f"Finding({self.render()!r})"


class SourceModule:
    """One source file: source text, LAZILY parsed AST (None on syntax
    error), posix-style display path, per-line suppression table, and a
    content hash.

    Parsing is deferred to first ``tree``/``syntax_error`` access so a
    run served entirely from the per-file findings cache
    (:mod:`tpudes.analysis.cache`) never pays ``ast.parse`` at all —
    that is most of a warm run's cost across ~200 files."""

    __slots__ = ("path", "source", "_tree", "_syntax_error", "_parsed",
                 "_suppress_tbl", "_sha")

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self._parsed = False
        self._tree = None
        self._syntax_error: SyntaxError | None = None
        self._suppress_tbl: dict | None = None
        self._sha: str | None = None

    def _parse(self) -> None:
        if self._parsed:
            return
        self._parsed = True
        try:
            self._tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self._tree = None
            self._syntax_error = e

    @property
    def tree(self):
        self._parse()
        return self._tree

    @property
    def syntax_error(self) -> SyntaxError | None:
        self._parse()
        return self._syntax_error

    @property
    def sha(self) -> str:
        if self._sha is None:
            import hashlib

            self._sha = hashlib.sha256(self.source.encode()).hexdigest()
        return self._sha

    @property
    def _suppress(self) -> dict:
        if self._suppress_tbl is None:
            tbl: dict[int, set[str] | None] = {}
            for lineno, line in enumerate(
                self.source.splitlines(), start=1
            ):
                m = _SUPPRESS_RE.search(line)
                if m is None:
                    continue
                codes = m.group(1)
                if codes is None:
                    tbl[lineno] = None  # everything on this line
                else:
                    tbl[lineno] = {
                        c.strip() for c in codes.split(",") if c.strip()
                    }
            self._suppress_tbl = tbl
        return self._suppress_tbl

    @classmethod
    def from_file(cls, file_path: Path, display_path: str) -> "SourceModule":
        return cls(display_path, file_path.read_text())

    def suppressed(self, line: int, code: str) -> bool:
        codes = self._suppress.get(line, False)
        if codes is False:
            return False
        return codes is None or code in codes

    def in_package(self, *parts: str) -> bool:
        """True when the display path contains the adjacent directory
        run ``parts`` (e.g. ``in_package("tpudes", "ops")``)."""
        p = tuple(self.path.split("/"))
        n = len(parts)
        return any(p[i : i + n] == parts for i in range(len(p) - n + 1))


class Pass:
    """One analysis pass.  Subclasses declare ``name`` and ``codes``
    (rule code -> one-line description) and implement ``check_module``
    — or ``check_project`` for cross-file passes (set
    ``project_wide = True``).  Register with
    :func:`tpudes.analysis.register_pass`."""

    name: str = ""
    codes: dict[str, str] = {}
    project_wide: bool = False
    #: only passes that opt in see modules that failed to parse
    handles_syntax_errors: bool = False

    def applies(self, path: str) -> bool:
        return True

    def check_module(self, mod: SourceModule) -> list[Finding]:
        return []

    def check_project(self, mods: list[SourceModule]) -> list[Finding]:
        out = []
        for mod in mods:
            if mod.tree is not None and self.applies(mod.path):
                out.extend(self.check_module(mod))
        return out


def walk_in_order(node: ast.AST):
    """Yield descendant nodes in source order (``ast.iter_child_nodes``
    preserves it) — the linear approximation the flow-sensitive passes
    (rng-discipline) scan."""
    for child in ast.iter_child_nodes(node):
        yield child
        yield from walk_in_order(child)


def scope_walk(scope: ast.AST):
    """Walk a scope in source order WITHOUT descending into nested
    function definitions (their bodies are separate scopes, scanned on
    their own) — nested def/lambda nodes themselves are still yielded,
    since their decorators and defaults evaluate in this scope."""
    for child in ast.iter_child_nodes(scope):
        yield child
        if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
            yield from scope_walk(child)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
