"""Abstract-tracing utilities for the jaxpr passes.

Everything here is ``jax.make_jaxpr`` only — no ``jax.jit``, no
compile, no device execution — so the ``--jaxpr`` gate is CPU-safe and
costs trace time (tens of milliseconds per tiny-shape entry), not
XLA compile time.
"""

from __future__ import annotations

import hashlib


def trace_entry(entry):
    """``jax.make_jaxpr(entry.fn)(*entry.args)`` → ClosedJaxpr."""
    import jax

    return jax.make_jaxpr(entry.fn)(*entry.args)


def trace_entries_x64(build):
    """Build a variant's entries AND trace them inside an
    ``enable_x64`` context.  Rebuilding inside the context matters:
    build-time constants (``jnp.asarray`` of host f64 tables) only
    reveal an unpinned dtype when the builder itself runs under x64
    semantics — tracing pre-built f32 arrays would hide them."""
    import jax  # noqa: F401  (jax must import before the context)
    from jax.experimental import enable_x64

    out = []
    with enable_x64():
        for entry in build():
            out.append((entry, trace_entry(entry)))
    return out


def _sub_jaxprs(value):
    """Jaxprs nested anywhere in one eqn-param value (while/cond/scan
    bodies, pjit, custom_* rules, pallas_call kernels — any primitive
    that closes over sub-jaxprs, present or future)."""
    from jax import core

    out = []
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, core.ClosedJaxpr):
            out.append(v.jaxpr)
        elif isinstance(v, core.Jaxpr):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            stack.extend(v)
        elif isinstance(v, dict):
            stack.extend(v.values())
    return out


def walk_eqns(jaxpr):
    """Yield every eqn in ``jaxpr`` and all nested sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            for sub in _sub_jaxprs(p):
                yield from walk_eqns(sub)


def primitive_names(closed_jaxpr) -> set:
    return {eqn.primitive.name for eqn in walk_eqns(closed_jaxpr.jaxpr)}


def f64_primitives(closed_jaxpr) -> set:
    """Primitive names (plus the pseudo-name ``const``) producing a
    float64 value anywhere in the trace — under an x64 trace of
    explicitly-f32 operands, every one is a creation site that did not
    pin its dtype (the silent-f64-promotion contract)."""
    import numpy as np

    out = set()
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        for v in eqn.outvars:
            dtype = getattr(getattr(v, "aval", None), "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                out.add(eqn.primitive.name)
    for c in closed_jaxpr.consts:
        if getattr(np.asarray(c), "dtype", None) == np.float64:
            out.add("const")
    return out


#: reductions whose accumulator dtype IS their output dtype — a bf16
#: output means a bf16 accumulator, which the PR 6 precision policy
#: forbids (compute low, ACCUMULATE f32).  Max/min reductions are
#: exact at any width and stay exempt.
ACCUMULATING_PRIMS = frozenset(
    {"reduce_sum", "reduce_prod", "cumsum", "cumprod", "dot_general",
     "conv_general_dilated", "reduce_window_sum"}
)


def bf16_accumulators(closed_jaxpr) -> set:
    """Accumulating primitives whose output is bfloat16."""
    out = set()
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name not in ACCUMULATING_PRIMS:
            continue
        for v in eqn.outvars:
            dtype = getattr(getattr(v, "aval", None), "dtype", None)
            if dtype is not None and str(dtype) == "bfloat16":
                out.add(eqn.primitive.name)
    return out


def large_consts(closed_jaxpr, budget: int):
    """``(shape, dtype, nbytes)`` for closure constants above the byte
    budget — values the builder baked into the program instead of
    passing as runtime operands."""
    import numpy as np

    out = []
    for c in closed_jaxpr.consts:
        arr = np.asarray(c)
        if arr.nbytes > budget:
            out.append((arr.shape, str(arr.dtype), int(arr.nbytes)))
    return out


def arg_leaf_slices(args: tuple):
    """Per-argument ``(start, stop)`` ranges into the flattened invar
    list (make_jaxpr flattens pytree args in order)."""
    import jax

    slices, pos = [], 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        slices.append((pos, pos + n))
        pos += n
    return slices


def arg_leaf_paths(arg):
    """Human-readable keypath per leaf of one argument pytree."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(arg)
    return [jax.tree_util.keystr(path) for path, _ in flat]


def used_invar_ids(closed_jaxpr) -> set:
    """ids of top-level invars consumed by some eqn or returned.
    Sub-jaxprs bind their own vars, so a top-level scan is complete."""
    used = set()
    for eqn in closed_jaxpr.jaxpr.eqns:
        for v in eqn.invars:
            used.add(id(v))
    for v in closed_jaxpr.jaxpr.outvars:
        used.add(id(v))
    return used


def unused_arg_leaves(entry, closed_jaxpr, argnum: int):
    """Keypaths of ``entry.args[argnum]``'s leaves whose invar is never
    consumed (the value was dead at trace time)."""
    slices = arg_leaf_slices(entry.args)
    start, stop = slices[argnum]
    used = used_invar_ids(closed_jaxpr)
    invars = closed_jaxpr.jaxpr.invars
    paths = arg_leaf_paths(entry.args[argnum])
    return [
        paths[i - start]
        for i in range(start, stop)
        if id(invars[i]) not in used
    ]


def unaliasable_donated_leaves(entry, closed_jaxpr, argnum: int):
    """Keypaths of donated leaves with no shape/dtype-matching output
    leaf: XLA cannot alias them, so the donation frees nothing and the
    runtime warns per call on accelerators."""
    import jax

    slices = arg_leaf_slices(entry.args)
    start, stop = slices[argnum]
    paths = arg_leaf_paths(entry.args[argnum])
    outs = {}
    for v in closed_jaxpr.jaxpr.outvars:
        aval = getattr(v, "aval", None)
        sig = (getattr(aval, "shape", None), str(getattr(aval, "dtype", "")))
        outs[sig] = outs.get(sig, 0) + 1
    missing = []
    leaves = jax.tree_util.tree_leaves(entry.args[argnum])
    for i in range(start, stop):
        leaf = leaves[i - start]
        sig = (
            tuple(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", "")),
        )
        if outs.get(sig, 0) > 0:
            outs[sig] -= 1
        else:
            missing.append(paths[i - start])
    return missing


#: primitives with FLOAT outputs whose gradient is zero (or undefined)
#: almost everywhere — a gradient path running only through these is
#: structurally dead.  Comparison/argmax/int-cast severing needs no
#: listing: their outputs are not floating, so liveness never crosses
#: them (see _diff_walk).
NONDIFF_PRIMS = frozenset(
    {"stop_gradient", "round", "floor", "ceil", "sign",
     "round_nearest_even"}
)


def _is_float_var(v) -> bool:
    import numpy as np

    dtype = getattr(getattr(v, "aval", None), "dtype", None)
    if dtype is None:
        return False
    try:
        return np.issubdtype(dtype, np.inexact)
    except TypeError:  # ml_dtypes (bfloat16) — inexact by definition
        return "float" in str(dtype)


def _diff_walk(jaxpr, live: set) -> None:
    """Propagate differentiable liveness (by var id, in ``live``)
    through one jaxpr's eqns in order.  Liveness crosses an eqn when a
    live FLOAT invar feeds it and the primitive carries gradients:
    non-float outputs (comparisons, argmax, float→int casts) and
    :data:`NONDIFF_PRIMS` sever the path.  Call-like eqns whose single
    sub-jaxpr aligns 1:1 with the invars (pjit/closed_call/remat)
    recurse precisely; other sub-jaxpr carriers (scan/while/cond) are
    treated as differentiable pass-through — conservative: a hard op
    hidden inside a loop body is missed, one outside is not."""
    from jax import core

    for eqn in jaxpr.eqns:
        in_live = any(
            not isinstance(v, core.Literal)
            and id(v) in live
            and _is_float_var(v)
            for v in eqn.invars
        )
        if not in_live:
            continue
        if eqn.primitive.name in NONDIFF_PRIMS:
            continue
        subs = []
        for p in eqn.params.values():
            subs.extend(_sub_jaxprs(p))
        if len(subs) == 1 and len(subs[0].invars) == len(eqn.invars):
            sub = subs[0]
            sub_live = set(live)
            for ev, sv in zip(eqn.invars, sub.invars):
                if (
                    not isinstance(ev, core.Literal)
                    and id(ev) in live
                    and _is_float_var(ev)
                ):
                    sub_live.add(id(sv))
            # scan feeds its carry outputs back into its carry inputs:
            # iterate the body walk to a FIXED POINT, or liveness that
            # only enters the carry on iteration k>0 (the fluid
            # fixed-point relaxation's cap→util→lfrac→lg chain) is
            # missed
            if eqn.primitive.name == "scan":
                nc = int(eqn.params.get("num_consts", 0))
                ncar = int(eqn.params.get("num_carry", 0))
                while True:
                    before = len(sub_live)
                    _diff_walk(sub, sub_live)
                    for ov, iv in zip(
                        sub.outvars[:ncar], sub.invars[nc:nc + ncar]
                    ):
                        if (
                            not isinstance(ov, core.Literal)
                            and id(ov) in sub_live
                        ):
                            sub_live.add(id(iv))
                    if len(sub_live) == before:
                        break
            else:
                _diff_walk(sub, sub_live)
            for ov, sv in zip(eqn.outvars, sub.outvars):
                if (
                    not isinstance(sv, core.Literal)
                    and id(sv) in sub_live
                    and _is_float_var(ov)
                ):
                    live.add(id(ov))
            continue
        for v in eqn.outvars:
            if _is_float_var(v):
                live.add(id(v))


def grad_severed_leaves(entry, closed_jaxpr, argnum: int):
    """Keypaths of ``entry.args[argnum]``'s FLOAT leaves with no
    differentiable path to any float output of the trace — their
    ``jax.grad`` is structurally zero (a hard op severs every path),
    the JXL006 finding."""
    jaxpr = closed_jaxpr.jaxpr
    slices = arg_leaf_slices(entry.args)
    start, stop = slices[argnum]
    invars = jaxpr.invars
    paths = arg_leaf_paths(entry.args[argnum])
    out = []
    for i in range(start, stop):
        root = invars[i]
        if not _is_float_var(root):
            continue  # integer operands carry no gradient by design
        live = {id(root)}
        _diff_walk(jaxpr, live)
        if not any(
            id(v) in live and _is_float_var(v) for v in jaxpr.outvars
        ):
            out.append(paths[i - start])
    return out


def fingerprint(closed_jaxpr) -> str:
    """Canonical identity of a traced program: the pretty-printed jaxpr
    (var names are assigned deterministically in traversal order, so
    structurally identical traces print identically) plus a digest of
    every constant's bytes.  Two builds with equal fingerprints compile
    to the same executable — the JXL004 comparison."""
    import numpy as np

    h = hashlib.sha256(str(closed_jaxpr.jaxpr).encode())
    for c in closed_jaxpr.consts:
        arr = np.asarray(c)
        h.update(str((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def variant_fingerprints(entries) -> dict:
    """``{entry_name: fingerprint}`` for a built entry list."""
    return {e.name: fingerprint(trace_entry(e)) for e in entries}
