"""Scale-complexity cost model over abstract traces.

Everything here operates on ``jax.make_jaxpr`` output only — shapes
and dtypes, never values, never a compile — so re-tracing a manifest
entry at a handful of scale-axis points costs trace time (tens of
milliseconds each), and the growth-exponent fits in JXL007 and the
``--cost`` report are CPU-safe in CI.

Three metrics per trace:

- :func:`total_buffer_bytes` — every buffer the trace materialises
  (consts, inputs, all eqn outputs, nested sub-jaxprs included).
- :func:`peak_live_bytes` — a linear-scan liveness walk: inputs and
  consts live for the whole program (donation is not modelled, so this
  is an upper bound on the working set), each eqn output from its
  birth to its last use, and a call-like eqn (scan/while/pjit body)
  contributes its body's internal peak at the call site.  This is the
  abstract analogue of XLA's ``memory_analysis().temp_size_in_bytes``
  and is cross-checked against it in the test-suite.
- :func:`widest_buffer_bytes` — the single largest buffer any eqn
  materialises.  This is the sharpest scale signal: additive
  lower-order terms make a peak-live log-log fit of an O(axis^2)
  kernel converge to 2 strictly from below, while the dominant dense
  table itself grows at exactly its true exponent (and it is the
  buffer a sparse rewrite must eliminate — no rematerialization
  schedule shrinks a single table).
- :func:`flop_estimate` — FLOP-weighted op count (dot_general at
  2·M·N·K, transcendentals at 8/element, reductions at input size,
  everything else at output size; scan bodies multiplied by trip
  count, while bodies counted once — trip counts are not abstract).

The JXL007 *memory exponent* of an axis is the max of the peak-live
and widest-buffer fits.

:func:`fit_exponent` turns per-axis metric series into log-log growth
exponents, and :func:`scale_report` assembles the full ``--cost``
report with 10^5/10^6-node projections for node-like axes — the
ROADMAP-item-2 worklist generator.
"""

from __future__ import annotations

import math

#: fitted-exponent grace over the declared budget before JXL007
#: fires: log-log fits at tiny trace shapes wobble by O(0.1) from the
#: constant and lower-order terms, so a linear-by-design kernel can
#: fit at 1.1–1.2 on a budget of 1.0 without being a finding
FIT_TOLERANCE = 0.25

#: node counts the ``--cost`` report projects device bytes at — the
#: ROADMAP item-2 scale targets
PROJECTION_NODES = (10**5, 10**6)


def _dtype_itemsize(dtype) -> int:
    import numpy as np

    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:  # exotic extended dtypes — 4 is the engine norm
        return int(getattr(dtype, "itemsize", 4))


def aval_bytes(v) -> int:
    """Byte size of one var's abstract value (0 for non-array avals,
    e.g. tokens)."""
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for d in shape:
        size *= int(d)
    return size * _dtype_itemsize(dtype)


def _vars_size(vs) -> int:
    total = 0
    for v in vs:
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        if shape is None:
            continue
        sz = 1
        for d in shape:
            sz *= int(d)
        total += sz
    return total


def _const_bytes(closed_jaxpr) -> int:
    import numpy as np

    return sum(int(np.asarray(c).nbytes) for c in closed_jaxpr.consts)


def _eqn_sub_jaxprs(eqn):
    from .trace import _sub_jaxprs

    subs = []
    for p in eqn.params.values():
        subs.extend(_sub_jaxprs(p))
    return subs


def total_buffer_bytes(closed_jaxpr) -> int:
    """Sum of every buffer the trace materialises: consts, top-level
    inputs, and all eqn outputs including nested sub-jaxprs (bodies
    counted once, unweighted by trip count — this is a *shape-growth*
    metric, not a bandwidth model)."""
    from .trace import walk_eqns

    total = _const_bytes(closed_jaxpr)
    total += sum(aval_bytes(v) for v in closed_jaxpr.jaxpr.invars)
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        total += sum(aval_bytes(v) for v in eqn.outvars)
    return total


def _internal_peak(jaxpr) -> int:
    """Peak bytes of buffers BORN inside this jaxpr.  Its inputs are
    bound to buffers the caller already counts, so only eqn outputs
    (and, recursively, sub-jaxpr internals at their call eqn) enter
    the live set.  A var is live from its defining eqn to its last
    use; outputs that escape the jaxpr stay live to the end."""
    from jax import core

    n = len(jaxpr.eqns)
    if n == 0:
        return 0
    last_use = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, core.Literal):
                last_use[id(v)] = i
    escapes = set()
    for v in jaxpr.outvars:
        if not isinstance(v, core.Literal):
            escapes.add(id(v))
    live = 0
    peak = 0
    dead_at = [[] for _ in range(n)]
    for i, eqn in enumerate(jaxpr.eqns):
        born = 0
        for v in eqn.outvars:
            b = aval_bytes(v)
            born += b
            if id(v) not in escapes:
                # last use is >= the birth index, so the death list we
                # append to has not been processed yet (unused values
                # die at their own eqn)
                dead_at[last_use.get(id(v), i)].append(b)
        inner = sum(_internal_peak(s) for s in _eqn_sub_jaxprs(eqn))
        live += born
        if live + inner > peak:
            peak = live + inner
        for b in dead_at[i]:
            live -= b
    return peak


def peak_live_bytes(closed_jaxpr) -> int:
    """Linear-scan liveness peak over the whole trace, in bytes:
    consts and inputs held for the full program (no donation
    modelling — an upper bound), plus the internal peak of the eqn
    graph (:func:`_internal_peak`)."""
    base = _const_bytes(closed_jaxpr)
    base += sum(aval_bytes(v) for v in closed_jaxpr.jaxpr.invars)
    return base + _internal_peak(closed_jaxpr.jaxpr)


def widest_buffer_bytes(closed_jaxpr) -> int:
    """Byte size of the single largest buffer any eqn (nested
    included) materialises — the tile/HBM pressure metric, and the
    cleanest growth-exponent signal (see module docstring)."""
    from .trace import walk_eqns

    best = max(
        (aval_bytes(v) for v in closed_jaxpr.jaxpr.invars), default=0
    )
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        for v in eqn.outvars:
            b = aval_bytes(v)
            if b > best:
                best = b
    return best


#: transcendental/special-function primitives costed above one flop
#: per element
_EXPENSIVE_ELEMENTWISE = frozenset(
    {"exp", "exp2", "expm1", "log", "log1p", "log2", "sin", "cos",
     "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh",
     "erf", "erfc", "erf_inv", "logistic", "pow", "integer_pow",
     "sqrt", "rsqrt", "cbrt", "digamma", "lgamma"}
)


def _jaxpr_flops(jaxpr) -> float:
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        subs = _eqn_sub_jaxprs(eqn)
        if subs:
            inner = sum(_jaxpr_flops(s) for s in subs)
            if name == "scan":
                inner *= max(int(eqn.params.get("length", 1)), 1)
            # while trip counts are not abstract: body counted once
            total += inner
            continue
        if name == "dot_general":
            dn = eqn.params.get("dimension_numbers")
            k = 1
            if dn is not None:
                (lhs_contract, _), _ = dn
                lhs_shape = getattr(
                    getattr(eqn.invars[0], "aval", None), "shape", ()
                )
                for d in lhs_contract:
                    k *= int(lhs_shape[d])
            total += 2.0 * k * _vars_size(eqn.outvars)
        elif name == "conv_general_dilated":
            total += 2.0 * _vars_size(eqn.invars)
        elif (
            name.startswith("reduce_")
            or name.startswith("cum")
            or name.startswith("arg")
            or name == "sort"
        ):
            total += _vars_size(eqn.invars)
        elif name in _EXPENSIVE_ELEMENTWISE:
            total += 8.0 * _vars_size(eqn.outvars)
        else:
            total += _vars_size(eqn.outvars)
    return total


def flop_estimate(closed_jaxpr) -> float:
    """FLOP-weighted op count of the trace (see module docstring for
    the per-primitive weights)."""
    return _jaxpr_flops(closed_jaxpr.jaxpr)


def shape_signature(closed_jaxpr) -> tuple:
    """(shape, dtype) of every input, output and eqn output in
    traversal order — equal signatures across scale-axis points mean
    the axis does not actually scale the program (the JXL007 dead-axis
    finding)."""
    from .trace import walk_eqns

    sig = []
    for v in list(closed_jaxpr.jaxpr.invars) + list(
        closed_jaxpr.jaxpr.outvars
    ):
        aval = getattr(v, "aval", None)
        sig.append(
            (
                tuple(getattr(aval, "shape", ())),
                str(getattr(aval, "dtype", "")),
            )
        )
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            sig.append(
                (
                    tuple(getattr(aval, "shape", ())),
                    str(getattr(aval, "dtype", "")),
                )
            )
    return tuple(sig)


def fit_exponent(points, values) -> float:
    """Least-squares slope of log(value) against log(point) — the
    growth exponent k of value ~ point^k.  Zero values clamp to one
    byte/flop to keep the logs finite (constant series fit to 0)."""
    xs = [math.log(float(p)) for p in points]
    ys = [math.log(max(float(v), 1.0)) for v in values]
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom == 0.0:
        return 0.0
    return sum(
        (x - mx) * (y - my) for x, y in zip(xs, ys)
    ) / denom


def project_bytes(points, values, exponent, at_value) -> int:
    """Power-law projection anchored at the largest traced point:
    value(x) = value(p_max) · (x / p_max)^k."""
    p_last = float(points[-1])
    v_last = float(values[-1])
    return int(v_last * (float(at_value) / p_last) ** exponent)


def format_bytes(n) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB", "PiB"):
        if abs(n) < 1024.0 or unit == "PiB":
            if unit == "B":
                return f"{int(n)} B"
            return f"{n:.1f} {unit}"
        n /= 1024.0


def axis_metrics(axis) -> dict:
    """Trace ``axis.build`` at every declared point and fit the growth
    exponents.  Returns the per-axis row of the cost report (rounded
    exponents — finding messages built from these must be
    byte-deterministic for the baseline ratchet)."""
    from .trace import trace_entry

    pts, peaks, widests, totals, fls, sigs = [], [], [], [], [], []
    for p in axis.points:
        cj = trace_entry(axis.build(p))
        pts.append(int(p))
        peaks.append(int(peak_live_bytes(cj)))
        widests.append(int(widest_buffer_bytes(cj)))
        totals.append(int(total_buffer_bytes(cj)))
        fls.append(float(_jaxpr_flops(cj.jaxpr)))
        sigs.append(shape_signature(cj))
    dead = all(s == sigs[0] for s in sigs[1:])
    peak_exp = 0.0 if dead else round(fit_exponent(pts, peaks), 4)
    widest_exp = 0.0 if dead else round(fit_exponent(pts, widests), 4)
    mem_exp = max(peak_exp, widest_exp)
    flop_exp = 0.0 if dead else round(fit_exponent(pts, fls), 4)
    row = {
        "axis": axis.name,
        "points": pts,
        "peak_live_bytes": peaks,
        "widest_buffer_bytes": widests,
        "total_buffer_bytes": totals,
        "flops": fls,
        "mem_exponent": mem_exp,
        "peak_exponent": peak_exp,
        "widest_exponent": widest_exp,
        "flop_exponent": flop_exp,
        "mem_budget": float(axis.mem_budget),
        "dead": dead,
        "over_budget": (
            not dead and mem_exp > axis.mem_budget + FIT_TOLERANCE
        ),
    }
    if axis.nodes_per_unit and not dead:
        proj = {}
        for nodes in PROJECTION_NODES:
            x = nodes / float(axis.nodes_per_unit)
            b = project_bytes(pts, peaks, mem_exp, x)
            proj[f"1e{int(round(math.log10(nodes)))}_nodes"] = {
                "bytes": b,
                "human": format_bytes(b),
            }
        row["projected"] = proj
    return row


def scale_report(manifests=None) -> dict:
    """The ``--cost`` report: every declared scale axis of every
    manifest's base-variant entries, traced and fitted, with
    10^5/10^6-node byte projections for node-like axes and the
    over-budget ``worklist`` — the entries ROADMAP item 2 (sparse
    wired graphs) must rewrite before they meet a million-node
    topology."""
    if manifests is None:
        from .manifest import load_manifests

        manifests = load_manifests()
    rows = []
    for man, _line in manifests:
        base = man.variants()[0]
        for entry in base.build():
            for axis in entry.scale_axes:
                row = axis_metrics(axis)
                row = {
                    "engine": man.engine,
                    "path": man.path,
                    "entry": entry.name,
                    **row,
                }
                rows.append(row)
    worklist = sorted(
        f"{r['engine']}/{r['entry']}:{r['axis']}"
        for r in rows
        if r["over_budget"]
    )
    return {
        "version": 1,
        "fit_tolerance": FIT_TOLERANCE,
        "projection_nodes": list(PROJECTION_NODES),
        "entries": rows,
        "worklist": worklist,
    }
