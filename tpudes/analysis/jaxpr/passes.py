"""JXL001–JXL008: trace-aware contract passes over the device-engine
surface.

The AST passes see Python syntax; these see the *programs the engines
actually hand to XLA*.  Every registered engine front-end exports a
trace manifest (:mod:`tpudes.analysis.jaxpr.manifest`); each rule
abstractly traces the manifest's canonical tiny-shape entries with
``jax.make_jaxpr`` (no compile, CPU-safe under ``JAX_PLATFORMS=cpu``)
and lints the resulting jaxprs.  Findings ride the ordinary
``Pass``/``Finding``/baseline/suppression machinery, anchored at the
engine module's ``trace_manifest`` definition line.

Run via ``python -m tpudes.analysis --jaxpr`` (the pass family is NOT
part of the default AST-only run — tracing costs a jax import).
"""

from __future__ import annotations

from tpudes.analysis.base import Finding, Pass
from tpudes.analysis.jaxpr import cost as C
from tpudes.analysis.jaxpr import sparse_registry as SR
from tpudes.analysis.jaxpr import trace as T

#: primitives that have no business in ANY device-engine program:
#: host callbacks re-enter Python from inside the executable (a
#: dispatch-rate killer and un-Mosaic-able), infeed/outfeed bind the
#: program to a host feed loop
FORBIDDEN_EVERYWHERE = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "callback",
     "infeed", "outfeed"}
)


def _is_gatherish(prim: str) -> bool:
    return prim == "gather" or prim.startswith("scatter")


class JaxprContractPass(Pass):
    """Trace every registered engine manifest and lint the jaxprs.

    ``manifests`` may be injected (the fixture tests run synthetic
    engines through the exact production rule code); the default is
    the real registry.
    """

    name = "jaxpr-contracts"
    project_wide = True
    codes = {
        "JXL001": "forbidden primitive in a device-engine trace "
                  "(gather/scatter in no-gather kernels; host "
                  "callbacks/infeed anywhere)",
        "JXL002": "dtype discipline: unpinned float64 under ambient "
                  "x64, or a bf16-mode reduction accumulating in bf16",
        "JXL003": "large constant baked into the traced program "
                  "(should be a runtime operand)",
        "JXL004": "cache-key hygiene: dead static key component, "
                  "missing key component, or declared-traced operand "
                  "tracing as a constant",
        "JXL005": "donation audit: donated carry leaf unused or "
                  "unaliasable, or a donatable carry never donated",
        "JXL006": "grad-hygiene: a declared-differentiable operand of "
                  "a surrogate-flagged trace has a structurally-zero "
                  "gradient (round/argmax/int-cast/stop_gradient "
                  "severs every path — annotate straight-through)",
        "JXL007": "scale-growth: an entry's fitted memory growth "
                  "exponent exceeds its declared per-axis budget "
                  "(superlinear device bytes before HBM finds out), "
                  "or a declared scale axis never changes the traced "
                  "shapes (dead axis)",
        "JXL008": "sparse-site audit: a gather/scatter/dynamic-slice "
                  "has no registered SparseSite contract, or the "
                  "jaxpr contradicts the registered contract (mode, "
                  "index provenance, scatter uniqueness)",
    }

    def __init__(self, manifests=None):
        self._manifests = manifests

    def _load(self):
        if self._manifests is not None:
            return self._manifests
        from tpudes.analysis.jaxpr.manifest import load_manifests

        return load_manifests()

    def check_project(self, mods):
        findings = []
        for man, line in self._load():
            findings.extend(lint_manifest(man, line))
        return findings


def lint_manifest(man, line: int = 1) -> list:
    """All JXL findings for one manifest (the unit the fixture tests
    drive directly)."""
    out = []

    def emit(code, msg):
        out.append(Finding(man.path, line, 1, code, msg))

    variants = man.variants()
    base_fp = None
    for vi, variant in enumerate(variants):
        entries = variant.build()
        traced = [(e, T.trace_entry(e)) for e in entries]
        if vi == 0:
            # the base variant's fingerprints double as the JXL004
            # comparison side — computed from THESE traces so the base
            # entries are never traced twice
            base_fp = {e.name: T.fingerprint(cj) for e, cj in traced}

        for entry, cj in traced:
            tag = f"{man.engine}/{variant.name}/{entry.name}"
            prims = T.primitive_names(cj)

            # JXL008 — sparse-site audit: every gather/scatter/
            # dynamic-slice must match a registered SparseSite whose
            # contract (mode, index provenance, scatter uniqueness)
            # the jaxpr upholds
            records = SR.audit_entry(
                man.engine, f"{variant.name}/{entry.name}", cj
            )
            seen_msgs = set()
            for rec in records:
                if rec["ok"]:
                    continue
                if rec["site"] is None:
                    msg = (
                        f"{tag}: unaudited sparse site — '{rec['prim']}' "
                        f"(mode {rec['mode']}, index roots "
                        f"{rec['kinds']}) has no registered SparseSite; "
                        "add a machine-checked contract in "
                        "analysis/jaxpr/sparse_registry.py"
                    )
                else:
                    msg = (
                        f"{tag}: sparse-site contract contradicted — "
                        f"'{rec['prim']}' vs '{rec['site']}': "
                        + "; ".join(rec["problems"])
                    )
                if msg not in seen_msgs:
                    seen_msgs.add(msg)
                    emit("JXL008", msg)

            # JXL001 — forbidden primitives
            for p in sorted(prims & FORBIDDEN_EVERYWHERE):
                emit("JXL001", f"{tag}: host primitive '{p}' inside "
                               "the device program")
            if man.no_gather and entry.kernel:
                # the blanket ban relaxed into the audit: a gatherish
                # eqn that passes a registered SparseSite contract is
                # allowed even in a no-gather kernel (the path the
                # CSR wired rewrite lands through); everything else
                # still fires
                bad = sorted(
                    {
                        r["prim"]
                        for r in records
                        if not r["ok"] and _is_gatherish(r["prim"])
                    }
                )
                for p in bad:
                    emit(
                        "JXL001",
                        f"{tag}: '{p}' in a no-gather step kernel — "
                        "the wired contract is one-hot/masked-"
                        "reduction forms only (XLA:CPU serializes "
                        "gathers; Mosaic tiling forbids them), "
                        "unless the site carries a verified "
                        "sparse_registry contract",
                    )

            # JXL007 — scale growth: re-trace the entry along each
            # declared axis and fit the peak-live/widest-buffer
            # growth exponents against the declared budget.  Base
            # variant only: axes describe the program, not the
            # variant, and tracing is the expensive part.
            if vi == 0:
                for ax in entry.scale_axes:
                    if len(ax.points) < 2:
                        emit(
                            "JXL007",
                            f"{tag}: scale axis '{ax.name}' declares "
                            "fewer than 2 points — growth cannot be "
                            "fitted",
                        )
                        continue
                    m = C.axis_metrics(ax)
                    if m["dead"]:
                        emit(
                            "JXL007",
                            f"{tag}: scale axis '{ax.name}' never "
                            "changes the traced shapes across points "
                            f"{m['points']} — dead axis declaration "
                            "(the manifest claims a scaling the "
                            "program does not have)",
                        )
                    elif m["over_budget"]:
                        emit(
                            "JXL007",
                            f"{tag}: scale axis '{ax.name}' fitted "
                            f"memory exponent "
                            f"{m['mem_exponent']:.2f} exceeds budget "
                            f"{ax.mem_budget:g} (peak-live "
                            f"{m['peak_exponent']:.2f}, widest "
                            f"buffer {m['widest_exponent']:.2f}) — "
                            "superlinear device bytes; run --jaxpr "
                            "--cost for the 1e5/1e6-node projections",
                        )

            # JXL002 — bf16 accumulator policy
            if variant.bf16:
                for p in sorted(T.bf16_accumulators(cj)):
                    emit(
                        "JXL002",
                        f"{tag}: '{p}' accumulates in bfloat16 — the "
                        "mixed-precision policy computes low and "
                        "accumulates f32 (use preferred_element_type "
                        "or an explicit f32 cast)",
                    )

            # JXL003 — baked-in large constants
            for shape, dtype, nbytes in T.large_consts(
                cj, man.const_budget
            ):
                emit(
                    "JXL003",
                    f"{tag}: baked constant {dtype}{list(shape)} "
                    f"({nbytes} B > {man.const_budget} B budget) — "
                    "pass it as a runtime operand so value flips "
                    "don't recompile",
                )

            # JXL004 — declared-traced operand burned to a constant
            for opname, argnum in sorted(entry.traced.items()):
                dead = T.unused_arg_leaves(entry, cj, argnum)
                n_leaves = len(T.arg_leaf_paths(entry.args[argnum]))
                if dead and len(dead) == n_leaves:
                    emit(
                        "JXL004",
                        f"{tag}: declared-traced operand '{opname}' "
                        "is unused in the trace — the builder closed "
                        "over a concrete value, so runtime flips "
                        "cannot reach the program",
                    )

            # JXL005 — donation audit
            for argnum in entry.donate:
                for path in T.unused_arg_leaves(entry, cj, argnum):
                    emit(
                        "JXL005",
                        f"{tag}: donated carry leaf '{path}' is never "
                        "consumed — dead state riding the donated "
                        "buffer",
                    )
                for path in T.unaliasable_donated_leaves(
                    entry, cj, argnum
                ):
                    emit(
                        "JXL005",
                        f"{tag}: donated leaf '{path}' has no "
                        "shape/dtype-matching output — XLA cannot "
                        "alias it, the donation frees nothing",
                    )
            for argnum in entry.carry:
                if argnum not in entry.donate:
                    emit(
                        "JXL005",
                        f"{tag}: carry argnum {argnum} is never "
                        "donated — a per-call state copy on "
                        "accelerators (wrap the jit in "
                        "donate_argnums)",
                    )

            # JXL006 — grad hygiene on surrogate-flagged variants:
            # every declared-differentiable operand leaf must keep a
            # gradient path to the outputs; a round/argmax/integer
            # cast/stop_gradient severing every path makes jax.grad
            # return structural zeros — the silent way a calibration
            # "converges" by never moving
            if variant.surrogate:
                for argnum in entry.grad_wrt:
                    for path in T.grad_severed_leaves(entry, cj, argnum):
                        emit(
                            "JXL006",
                            f"{tag}: differentiable operand leaf "
                            f"'{path}' has no gradient path to the "
                            "outputs — a hard op (round/argmax/int "
                            "cast/stop_gradient) severs every route; "
                            "wrap it straight-through "
                            "(tpudes.diff.ste) or soften it behind "
                            "the Surrogacy flag",
                        )

        # JXL002 — f64 under ambient x64 (rebuild inside the context so
        # build-time asarray boundaries are exercised too).  A trace
        # that fails to TYPE under x64 is the worst version of the
        # finding: some unpinned creation/accumulation site widened a
        # loop carry until the program stopped being well-formed.
        try:
            traced64 = T.trace_entries_x64(variant.build)
        except Exception as e:  # noqa: BLE001 - any trace-time error
            emit(
                "JXL002",
                f"{man.engine}/{variant.name}: trace fails under "
                f"ambient x64 ({type(e).__name__}) — an unpinned "
                "dtype widens the program until it no longer "
                "type-checks; pin creation sites and integer "
                "reductions (.sum(dtype=jnp.int32))",
            )
            traced64 = []
        for entry, cj64 in traced64:
            tag = f"{man.engine}/{variant.name}/{entry.name}"
            for p in sorted(T.f64_primitives(cj64)):
                emit(
                    "JXL002",
                    f"{tag}: '{p}' produces float64 when ambient x64 "
                    "is enabled — an unpinned dtype at the creating "
                    "site makes results depend on global config (pin "
                    "jnp.float32)",
                )

    # JXL004 — cache-key hygiene over the declared flips
    if man.flips is not None and base_fp is not None:
        for fname, flip in sorted(man.flips().items()):
            flip_fp = T.variant_fingerprints(flip.build())
            same = flip_fp == base_fp
            if flip.key_differs and same:
                emit(
                    "JXL004",
                    f"{man.engine}: cache-key component '{fname}' is "
                    "dead — flipping it provably leaves every traced "
                    "program identical, so it only causes spurious "
                    "recompiles",
                )
            elif not flip.key_differs and not same:
                changed = sorted(
                    k for k in base_fp if flip_fp.get(k) != base_fp[k]
                )
                emit(
                    "JXL004",
                    f"{man.engine}: '{fname}' changes the traced "
                    f"program ({', '.join(changed)}) but is NOT a "
                    "cache-key component — a stale runner would serve "
                    "the wrong executable",
                )
    return out


#: the pass family ``--jaxpr`` appends to a run (kept out of
#: BUILTIN_PASSES: tracing costs a jax import + ~a second per engine)
JAXPR_PASSES = [JaxprContractPass]
