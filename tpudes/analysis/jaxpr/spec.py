"""Trace-manifest spec types — the contract each device-engine
front-end exports so the jaxpr passes can lint it.

This module is deliberately dependency-free (no jax import at module
scope): the engine front-ends in ``tpudes/parallel/`` import it to
declare their manifests, and everything that actually traces lives in
:mod:`tpudes.analysis.jaxpr.trace`.

A manifest names, for one engine:

- how to build the **canonical tiny-shape trace entries** (the exact
  functions the engine's ``run_*`` entry point would hand to
  ``jax.jit`` — unjitted — plus concrete example operands small enough
  that ``jax.make_jaxpr`` traces them in well under a second, CPU-safe,
  no compile);
- which structural contracts apply (the wired no-gather rule, the bf16
  accumulator policy);
- a set of **flips**: single-field program variations, each tagged with
  whether the engine's REAL runner-cache key distinguishes it
  (``key_differs`` is computed by the engine from its own cache-key
  helper, so the manifest cannot drift from the code it describes).
  JXL004 then checks both directions: a key-distinguished flip whose
  traces are identical is a dead key component (spurious recompiles); a
  key-identical flip whose traces differ is a missing component (stale
  executables).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class TraceEntry:
    """One traceable function of a cached runner value.

    ``fn`` is the UNJITTED callable exactly as the engine jits it;
    ``args`` are concrete example operands (pytrees).  ``donate`` names
    the argnums the engine donates on accelerators
    (``donate_argnums(...)`` intent — the CPU backend strips them at
    jit time, so the lint checks the declared intent, not the
    backend-dependent call).  ``carry`` names argnums that are
    state carries handed call-to-call (donatable by shape); ``traced``
    maps operand names to argnums that the engine documents as traced
    runtime operands (each must surface as a *live* jaxpr input — an
    operand the builder accidentally closed over traces as a constant
    and its invars go unused).  ``kernel`` marks hot-loop entries: the
    per-manifest forbidden-primitive contracts (no-gather) apply only
    to these, not to one-time init tracing.
    """

    name: str
    fn: object
    args: tuple
    donate: tuple = ()
    carry: tuple = ()
    traced: dict = field(default_factory=dict)
    kernel: bool = True
    #: argnums documented as DIFFERENTIABLE runtime operands: on a
    #: surrogate-flagged variant JXL006 checks each keeps a gradient
    #: path to the outputs (a round/argmax/int-cast/stop_gradient
    #: severing every path = structurally-zero gradient — the hard op
    #: needs a straight-through annotation, ``tpudes.diff.ste``)
    grad_wrt: tuple = ()


@dataclass(frozen=True)
class TraceVariant:
    """One named build of the engine's entries (e.g. ``base``,
    ``bf16``).  ``build`` is a zero-arg thunk returning the entry list
    — thunked so the dtype pass can rebuild the SAME variant inside an
    ``enable_x64`` context and catch unpinned build-time dtypes, not
    just unpinned traced ops.  ``bf16`` opts the variant into the
    mixed-precision accumulator check (reductions must accumulate in
    f32, per the PR 6 precision policy)."""

    name: str
    build: object
    bf16: bool = False
    #: marks a surrogate-flagged (differentiable) build: JXL006 audits
    #: the gradient hygiene of its entries' ``grad_wrt`` operands
    surrogate: bool = False


@dataclass(frozen=True)
class FlipSpec:
    """One single-field program variation for cache-key hygiene.

    ``build`` is a zero-arg thunk returning the flipped entry list (to
    compare against the ``base`` variant); ``key_differs`` is whether
    the engine's real runner-cache key separates the flipped program
    from the base one — computed by the engine from its own cache-key
    helper at manifest build time."""

    build: object
    key_differs: bool


@dataclass(frozen=True)
class TraceManifest:
    """The per-engine export: ``trace_manifest()`` in each front-end
    module returns one of these.  ``path`` is the repo-relative display
    path findings anchor to; ``variants`` is a zero-arg thunk returning
    the :class:`TraceVariant` list (first entry is the base variant);
    ``flips`` a zero-arg thunk returning ``{field_name: FlipSpec}``.
    ``no_gather`` arms the JXL001 gather/scatter ban on kernel entries
    (the wired-engine contract: XLA:CPU serializes gathers and Mosaic
    tiles hate them — the step body must stay one-hot/masked-reduction
    only).  ``const_budget`` is the JXL003 per-constant byte threshold
    at the manifest's tiny shapes."""

    engine: str
    path: str
    variants: object
    flips: object = None
    no_gather: bool = False
    const_budget: int = 4096
