"""Trace-manifest spec types — the contract each device-engine
front-end exports so the jaxpr passes can lint it.

This module is deliberately dependency-free (no jax import at module
scope): the engine front-ends in ``tpudes/parallel/`` import it to
declare their manifests, and everything that actually traces lives in
:mod:`tpudes.analysis.jaxpr.trace`.

A manifest names, for one engine:

- how to build the **canonical tiny-shape trace entries** (the exact
  functions the engine's ``run_*`` entry point would hand to
  ``jax.jit`` — unjitted — plus concrete example operands small enough
  that ``jax.make_jaxpr`` traces them in well under a second, CPU-safe,
  no compile);
- which structural contracts apply (the wired no-gather rule, the bf16
  accumulator policy);
- a set of **flips**: single-field program variations, each tagged with
  whether the engine's REAL runner-cache key distinguishes it
  (``key_differs`` is computed by the engine from its own cache-key
  helper, so the manifest cannot drift from the code it describes).
  JXL004 then checks both directions: a key-distinguished flip whose
  traces are identical is a dead key component (spurious recompiles); a
  key-identical flip whose traces differ is a missing component (stale
  executables).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ScaleAxis:
    """One scale dimension of a trace entry, for the JXL007
    scale-growth pass and the ``--cost`` report.

    ``build`` is a one-arg callable mapping an axis value to a fresh
    :class:`TraceEntry` of the SAME program shape-scaled along this
    axis only (tiny values — everything is ``jax.make_jaxpr`` traced,
    never compiled).  ``points`` are the axis values to trace (>= 2,
    strictly increasing; spread them wide — the growth-exponent fit is
    a log-log slope and close points amplify the constant-term bias).
    ``mem_budget`` is the maximum allowed fitted peak-live-bytes growth
    exponent: 1.0 declares "device memory linear in this axis", 2.0
    admits a dense quadratic table (e.g. the BSS pairwise-detect
    geometry, which is O(n_sta^2) by physical contract).  An entry
    whose fitted exponent exceeds the budget (plus the fit tolerance)
    is a JXL007 finding; an axis whose traces do not change shape at
    all across ``points`` is a dead-axis JXL007 finding (the manifest
    claims a scaling the program does not have — the same both-ways
    hygiene as the JXL004 flips).  ``nodes_per_unit`` calibrates the
    10^5/10^6-node projections in the cost report: how many topology
    NODES one unit of this axis represents (0 disables projection for
    axes that are not node-like, e.g. replicas)."""

    name: str
    build: object
    points: tuple = (2, 8)
    mem_budget: float = 1.0
    nodes_per_unit: float = 0.0
    note: str = ""


@dataclass(frozen=True)
class TraceEntry:
    """One traceable function of a cached runner value.

    ``fn`` is the UNJITTED callable exactly as the engine jits it;
    ``args`` are concrete example operands (pytrees).  ``donate`` names
    the argnums the engine donates on accelerators
    (``donate_argnums(...)`` intent — the CPU backend strips them at
    jit time, so the lint checks the declared intent, not the
    backend-dependent call).  ``carry`` names argnums that are
    state carries handed call-to-call (donatable by shape); ``traced``
    maps operand names to argnums that the engine documents as traced
    runtime operands (each must surface as a *live* jaxpr input — an
    operand the builder accidentally closed over traces as a constant
    and its invars go unused).  ``kernel`` marks hot-loop entries: the
    per-manifest forbidden-primitive contracts (no-gather) apply only
    to these, not to one-time init tracing.
    """

    name: str
    fn: object
    args: tuple
    donate: tuple = ()
    carry: tuple = ()
    traced: dict = field(default_factory=dict)
    kernel: bool = True
    #: argnums documented as DIFFERENTIABLE runtime operands: on a
    #: surrogate-flagged variant JXL006 checks each keeps a gradient
    #: path to the outputs (a round/argmax/int-cast/stop_gradient
    #: severing every path = structurally-zero gradient — the hard op
    #: needs a straight-through annotation, ``tpudes.diff.ste``)
    grad_wrt: tuple = ()
    #: declared :class:`ScaleAxis` list: how this entry's buffers are
    #: expected to grow with problem size.  JXL007 re-traces the entry
    #: at each axis's points, fits the peak-live-bytes growth exponent,
    #: and flags any axis over its ``mem_budget`` — the dense-table
    #: early-warning for ROADMAP item 2
    scale_axes: tuple = ()


@dataclass(frozen=True)
class TraceVariant:
    """One named build of the engine's entries (e.g. ``base``,
    ``bf16``).  ``build`` is a zero-arg thunk returning the entry list
    — thunked so the dtype pass can rebuild the SAME variant inside an
    ``enable_x64`` context and catch unpinned build-time dtypes, not
    just unpinned traced ops.  ``bf16`` opts the variant into the
    mixed-precision accumulator check (reductions must accumulate in
    f32, per the PR 6 precision policy)."""

    name: str
    build: object
    bf16: bool = False
    #: marks a surrogate-flagged (differentiable) build: JXL006 audits
    #: the gradient hygiene of its entries' ``grad_wrt`` operands
    surrogate: bool = False


@dataclass(frozen=True)
class FlipSpec:
    """One single-field program variation for cache-key hygiene.

    ``build`` is a zero-arg thunk returning the flipped entry list (to
    compare against the ``base`` variant); ``key_differs`` is whether
    the engine's real runner-cache key separates the flipped program
    from the base one — computed by the engine from its own cache-key
    helper at manifest build time."""

    build: object
    key_differs: bool


@dataclass(frozen=True)
class TraceManifest:
    """The per-engine export: ``trace_manifest()`` in each front-end
    module returns one of these.  ``path`` is the repo-relative display
    path findings anchor to; ``variants`` is a zero-arg thunk returning
    the :class:`TraceVariant` list (first entry is the base variant);
    ``flips`` a zero-arg thunk returning ``{field_name: FlipSpec}``.
    ``no_gather`` arms the JXL001 gather/scatter ban on kernel entries
    (the wired-engine contract: XLA:CPU serializes gathers and Mosaic
    tiles hate them — the step body must stay one-hot/masked-reduction
    only).  ``const_budget`` is the JXL003 per-constant byte threshold
    at the manifest's tiny shapes."""

    engine: str
    path: str
    variants: object
    flips: object = None
    no_gather: bool = False
    const_budget: int = 4096
