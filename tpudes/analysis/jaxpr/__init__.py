"""tpudes.analysis.jaxpr — trace-aware lint over the device-engine
surface.

Every registered engine front-end exports a canonical tiny-shape
**trace manifest** (:mod:`tpudes.analysis.jaxpr.spec`); the JXL pass
family (:mod:`tpudes.analysis.jaxpr.passes`) abstractly traces each
manifest with ``jax.make_jaxpr`` — no compile, CPU-safe — and lints
the jaxprs for the structural contracts the paper's thesis rests on:

- JXL001  forbidden primitives (no-gather wired kernels, no host
          callbacks/infeed anywhere)
- JXL002  dtype discipline (no silent f64 promotion; bf16 reductions
          accumulate f32)
- JXL003  baked-in large constants that should be runtime operands
- JXL004  cache-key hygiene (dead/missing key components; declared-
          traced operands burned to constants)
- JXL005  donation audit (dead donated carry leaves, unaliasable
          donations, undonated carries)
- JXL006  grad-hygiene on surrogate-flagged variants (no structurally
          zero gradients)
- JXL007  scale-growth (per-axis peak-live/widest-buffer growth
          exponents fitted against declared budgets; dead axes)
- JXL008  sparse-site audit (gather/scatter/dynamic-slice only at
          registered, machine-checked SparseSite contracts)

Enable with ``python -m tpudes.analysis --jaxpr``; add ``--cost`` for
the scale-complexity report with 1e5/1e6-node byte projections.
"""

from tpudes.analysis.jaxpr.passes import (
    JAXPR_PASSES,
    JaxprContractPass,
    lint_manifest,
)
from tpudes.analysis.jaxpr.spec import (
    FlipSpec,
    ScaleAxis,
    TraceEntry,
    TraceManifest,
    TraceVariant,
)

__all__ = [
    "JAXPR_PASSES",
    "JaxprContractPass",
    "FlipSpec",
    "ScaleAxis",
    "TraceEntry",
    "TraceManifest",
    "TraceVariant",
    "lint_manifest",
]
