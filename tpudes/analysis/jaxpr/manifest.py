"""Registry of the per-engine trace manifests.

Each device-engine front-end exports a module-level
``trace_manifest()`` returning a
:class:`~tpudes.analysis.jaxpr.spec.TraceManifest`.  This module just
knows where they live and imports them lazily (the AST-only analysis
path never pays a jax import).
"""

from __future__ import annotations

import importlib

#: (module, attribute) of every engine manifest the ``--jaxpr`` pass
#: family lints — the five device engines, the hybrid space-lanes
#: window kernel, and the shared traffic stage (ISSUE-14).  A new
#: engine front-end joins the gate by exporting ``trace_manifest()``
#: and adding one row here (see README "Static analysis" for the
#: howto).
ENGINE_MANIFESTS = (
    ("tpudes.parallel.replicated", "trace_manifest"),
    ("tpudes.parallel.lte_sm", "trace_manifest"),
    ("tpudes.parallel.tcp_dumbbell", "trace_manifest"),
    ("tpudes.parallel.as_flows", "trace_manifest"),
    ("tpudes.parallel.wired", "trace_manifest"),
    ("tpudes.parallel.hybrid", "trace_manifest"),
    ("tpudes.traffic.device", "trace_manifest"),
    ("tpudes.diff.as_grad", "trace_manifest"),
)


def load_manifests():
    """Import every registered front-end and collect
    ``(manifest, anchor_line)`` pairs — the anchor is the engine's
    ``trace_manifest`` definition line, so findings land on (and inline
    suppressions apply at) the manifest export itself."""
    out = []
    for mod_name, attr in ENGINE_MANIFESTS:
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr)
        line = getattr(
            getattr(fn, "__code__", None), "co_firstlineno", 1
        )
        out.append((fn(), line))
    return out
