"""The audited sparse-ops allowlist (JXL008).

JXL001's blanket gather/scatter ban protected the wired step kernel
while every engine was dense; ROADMAP item 2 (million-node sparse
wired graphs, CSR adjacency) needs gathers — but *only* gathers whose
index handling is a stated, machine-checked contract.  This module is
that contract surface: every gather / scatter / dynamic-slice site in
the traced engine programs must match a :class:`SparseSite` registered
here, and the registration is verified against the jaxpr itself, not
against comments:

- ``mode`` — the eqn's ``GatherScatterMode`` must be present and equal
  the declared one (``promise_in_bounds`` demands the index provenance
  below actually holds; ``fill_or_drop`` / ``clip`` are self-bounding
  at the cost of a mask/clamp).  ``dynamic_slice`` carries no mode
  param — XLA clamps its start indices, so those sites declare
  ``clip``.
- ``provenance`` — the index operand is walked backward through the
  jaxpr (across pjit/scan/while bodies) to its terminal roots, each
  classified (:data:`PROVENANCE_KINDS`); every root kind found must be
  declared.  A site registered as ``("operand",)`` whose index
  suddenly arrives from an unclamped arithmetic chain or a baked
  const table is a *contradicted contract*, not a pass.
- ``unique_indices`` — scatter sites declare whether the engine
  guarantees non-colliding indices; the eqn param must agree (a
  replace-scatter silently reading ``unique_indices=False`` is a
  nondeterminism hazard on TPU).

The provenance walk is a lint, not a proof: roots bound outside a
sub-jaxpr classify as ``operand`` (their in-bounds guarantee lives in
the engine's program validation — e.g. ``WiredProgram.__post_init__``
rejects ``paths >= n_links`` — and the registration ``note`` names
it), and unrecognised computations classify as ``unknown:<prim>``,
which no site should declare.
"""

from __future__ import annotations

from dataclasses import dataclass
from fnmatch import fnmatch

#: classification vocabulary for index-operand terminal roots
PROVENANCE_KINDS = (
    "operand",    # runtime operand / outer-frame binding (validated
                  # at program-build time; the note says where)
    "const",      # closure constant baked into the trace
    "iota",       # lax.iota — in-bounds by construction when sized
                  # by the indexed axis
    "clamp",      # lax.clamp — explicitly bounded
    "mod",        # lax.rem — bounded by the modulus
    "argreduce",  # argmax/argmin — bounded by the reduced axis size
)


@dataclass(frozen=True)
class SparseSite:
    """One registered sparse-access site.

    ``engine`` is the manifest engine name (exact); ``entry`` is an
    ``fnmatch`` glob over ``variant/entry`` tags; ``primitive`` an
    fnmatch glob over primitive names (``gather``, ``scatter*``,
    ``dynamic_slice``, ``dynamic_update_slice``).  ``mode`` is the
    required GatherScatterMode (lowercase enum name), ``provenance``
    the allowed root kinds, ``unique_indices`` the declared scatter
    uniqueness (None = not asserted, only valid for accumulating
    scatters where collisions are well-defined).  ``note`` names the
    in-bounds argument a human should go read."""

    site: str
    engine: str
    entry: str
    primitive: str
    mode: str
    provenance: tuple
    unique_indices: object = None
    note: str = ""


#: primitives the JXL008 audit covers
SPARSE_PRIMS = (
    "gather",
    "scatter*",
    "dynamic_slice",
    "dynamic_update_slice",
)


def is_sparse_prim(name: str) -> bool:
    return any(fnmatch(name, pat) for pat in SPARSE_PRIMS)


# --- index-provenance walk -------------------------------------------------

#: primitives classified AS a terminal root kind
_TERMINAL = {
    "iota": "iota",
    "clamp": "clamp",
    "rem": "mod",
    "argmax": "argreduce",
    "argmin": "argreduce",
}

#: value-preserving / bounds-preserving computations the walk recurses
#: through to the real roots.  max/min/add/sub/div are recursed (the
#: BOUND argument is typically a literal); anything not listed and not
#: terminal classifies as unknown and fails any contract.
_PASS_THROUGH = frozenset(
    {"add", "sub", "mul", "div", "neg", "max", "min", "abs",
     "floor", "ceil", "round", "sign",
     "broadcast_in_dim", "reshape", "squeeze", "expand_dims",
     "transpose", "rev", "slice", "concatenate", "pad",
     "convert_element_type", "stop_gradient", "copy", "device_put",
     "reduce_max", "reduce_min", "reduce_sum", "cumsum", "sort",
     "and", "or", "xor", "not", "eq", "ne", "lt", "le", "gt", "ge",
     "select_n", "gather", "dynamic_slice", "squeeze"}
)


class _Frame:
    """One jaxpr's def/use context for the provenance walk."""

    __slots__ = ("defs", "bindings", "const_ids")

    def __init__(self, jaxpr, outer_eqn=None, outer_frame=None,
                 const_ids=()):
        from jax import core

        self.defs = {}
        for eqn in jaxpr.eqns:
            for i, v in enumerate(eqn.outvars):
                self.defs[id(v)] = (eqn, i)
        self.bindings = {}
        if (
            outer_eqn is not None
            and outer_frame is not None
            and len(jaxpr.invars) == len(outer_eqn.invars)
        ):
            for sv, ov in zip(jaxpr.invars, outer_eqn.invars):
                if not isinstance(ov, core.Literal):
                    self.bindings[id(sv)] = (ov, outer_frame)
        self.const_ids = set(const_ids)


def _eqn_subs(eqn):
    from .trace import _sub_jaxprs

    subs = []
    for p in eqn.params.values():
        subs.extend(_sub_jaxprs(p))
    return subs


def classify_roots(var, frame) -> set:
    """Terminal-root kinds of the value ``var`` within ``frame``.
    Literal roots are dropped (a literal index is trivially audited by
    shape checking at trace time)."""
    from jax import core

    kinds = set()
    stack = [(var, frame)]
    seen = set()
    while stack:
        v, fr = stack.pop()
        if isinstance(v, core.Literal):
            continue
        key = (id(v), id(fr))
        if key in seen:
            continue
        seen.add(key)
        got = fr.defs.get(id(v))
        if got is None:
            bind = fr.bindings.get(id(v))
            if bind is not None:
                stack.append(bind)
            elif id(v) in fr.const_ids:
                kinds.add("const")
            else:
                kinds.add("operand")
            continue
        eqn, out_idx = got
        name = eqn.primitive.name
        if name in _TERMINAL:
            kinds.add(_TERMINAL[name])
            continue
        subs = _eqn_subs(eqn)
        if subs:
            # call-like eqn (pjit/scan/remat): the value is the
            # corresponding sub-jaxpr output; recurse inside with the
            # invars bound 1:1 when they align
            if len(subs) == 1 and len(subs[0].outvars) == len(
                eqn.outvars
            ):
                sub = subs[0]
                sfr = _Frame(sub, outer_eqn=eqn, outer_frame=fr)
                stack.append((sub.outvars[out_idx], sfr))
            else:
                kinds.add(f"unknown:{name}")
            continue
        if name == "select_n":
            # the predicate (invars[0]) does not flow into the VALUE;
            # only the branches do
            for iv in eqn.invars[1:]:
                stack.append((iv, fr))
            continue
        if name in ("gather", "dynamic_slice"):
            # an index read out of a table: the VALUES come from the
            # table operand (the inner indices are audited at their
            # own site)
            stack.append((eqn.invars[0], fr))
            continue
        if name in _PASS_THROUGH:
            for iv in eqn.invars:
                stack.append((iv, fr))
            continue
        kinds.add(f"unknown:{name}")
    return kinds


def _index_operands(eqn):
    name = eqn.primitive.name
    if name == "gather":
        return eqn.invars[1:2]
    if name.startswith("scatter"):
        return eqn.invars[1:2]
    if name == "dynamic_slice":
        return eqn.invars[1:]
    if name == "dynamic_update_slice":
        return eqn.invars[2:]
    return []


def _eqn_mode(eqn) -> str:
    name = eqn.primitive.name
    if name in ("dynamic_slice", "dynamic_update_slice"):
        return "clip"  # XLA clamps dynamic-slice start indices
    mode = eqn.params.get("mode")
    if mode is None:
        return "unspecified"
    return getattr(mode, "name", str(mode)).lower()


def _collect_sparse_eqns(closed_jaxpr):
    """Every sparse eqn in the trace, paired with the frame of the
    jaxpr that contains it (nested bodies included)."""
    out = []
    top = _Frame(
        closed_jaxpr.jaxpr,
        const_ids=[id(v) for v in closed_jaxpr.jaxpr.constvars],
    )

    def walk(jaxpr, frame):
        for eqn in jaxpr.eqns:
            if is_sparse_prim(eqn.primitive.name):
                out.append((eqn, frame))
            for sub in _eqn_subs(eqn):
                walk(sub, _Frame(sub, outer_eqn=eqn,
                                 outer_frame=frame))

    walk(closed_jaxpr.jaxpr, top)
    return out


# --- the registry ----------------------------------------------------------

#: every audited sparse-access site in the registered engine traces.
#: Adding a gather to an engine means adding (and passing) a row here
#: — see README "Static analysis".  Rows were generated by running the
#: audit against the live manifests and then reviewed: each ``note``
#: names the in-bounds argument the provenance classification leans
#: on.
SPARSE_SITES: tuple = (
    # -- bss: slot-window views over per-replica state ----------------
    SparseSite(
        site="bss.slot_window",
        engine="bss", entry="*/advance",
        primitive="dynamic_slice", mode="clip",
        provenance=("operand",),
        note="window starts are slot counters carried in the advance "
             "state; XLA clamps dynamic-slice starts, so a horizon "
             "overrun reads the last window instead of OOB",
    ),
    # -- lte_sm + shared traffic stage --------------------------------
    SparseSite(
        site="lte_sm.serving_term",
        engine="lte_sm", entry="traffic/*",
        primitive="gather", mode="fill_or_drop",
        provenance=("operand",),
        note="serving-cell table lookups keyed by UE state operands; "
             "FILL_OR_DROP masks any out-of-range id with the "
             "sentinel fill value (-2^31 / nan), which the downstream "
             "masked reductions discard",
    ),
    SparseSite(
        site="lte_sm.traffic_cursor",
        engine="lte_sm", entry="traffic/*",
        primitive="gather", mode="promise_in_bounds",
        provenance=("operand",),
        note="per-entity epoch cursors from tpudes.traffic kernels; "
             "in-bounds because the cursor is a bounded count of "
             "epoch boundaries (see TrafficProgram horizon contract)",
    ),
    SparseSite(
        site="traffic.table_lookup",
        engine="traffic", entry="base/*",
        primitive="gather", mode="fill_or_drop",
        provenance=("operand",),
        note="same kernels as lte_sm.serving_term, traced standalone",
    ),
    SparseSite(
        site="traffic.cursor",
        engine="traffic", entry="base/*",
        primitive="gather", mode="promise_in_bounds",
        provenance=("operand",),
        note="same kernels as lte_sm.traffic_cursor, traced standalone",
    ),
    # -- tcp dumbbell: per-flow ring buffers --------------------------
    SparseSite(
        site="dumbbell.ring_window",
        engine="dumbbell", entry="*/advance",
        primitive="dynamic_slice", mode="clip",
        provenance=("operand", "mod"),
        note="ring-buffer cursors reduced mod the ring length before "
             "the slice",
    ),
    SparseSite(
        site="dumbbell.ring_read",
        engine="dumbbell", entry="*/advance",
        primitive="gather", mode="promise_in_bounds",
        provenance=("operand", "mod"),
        note="ring reads at cursor mod ring-length — in-bounds by the "
             "modulus",
    ),
    SparseSite(
        site="dumbbell.ring_write",
        engine="dumbbell", entry="*/advance",
        primitive="scatter*", mode="fill_or_drop",
        provenance=("operand", "mod"),
        unique_indices=True,
        note="one write per flow per step at distinct mod-cursors; "
             "uniqueness is asserted to XLA (unique_indices=True)",
    ),
    # -- as_flows SPF tables (and the diff loss over the same program)
    SparseSite(
        site="as_flows.path_tables",
        engine="as_flows", entry="*/run",
        primitive="gather", mode="promise_in_bounds",
        provenance=("const", "operand"),
        note="edge/path id tables validated at program build (every "
             "id < 2E by construction in toy_as_program/BRITE import)",
    ),
    SparseSite(
        site="as_flows.epoch_window",
        engine="as_flows", entry="*/run",
        primitive="dynamic_slice", mode="clip",
        provenance=("const", "operand"),
        note="epoch window starts from the scan counter",
    ),
    SparseSite(
        site="as_flows.relax_scatter",
        engine="as_flows", entry="*/run",
        primitive="scatter*", mode="fill_or_drop",
        provenance=("const", "iota", "operand"),
        unique_indices=False,
        note="SPF relaxation writes: iota/edge-table rooted, "
             "collision-free by construction but NOT asserted to XLA "
             "(scatter-min/-add are order-insensitive; the replace "
             "scatter writes disjoint iota rows) — declaring "
             "unique_indices=True upstream is a known follow-up",
    ),
    SparseSite(
        site="diff.as_loss_tables",
        engine="diff", entry="*",
        primitive="gather", mode="promise_in_bounds",
        provenance=("const", "operand"),
        note="the differentiable AS loss traces the as_flows kernels; "
             "same in-bounds argument as as_flows.path_tables",
    ),
    SparseSite(
        site="diff.as_loss_window",
        engine="diff", entry="*",
        primitive="dynamic_slice", mode="clip",
        provenance=("const", "operand"),
        note="as_flows.epoch_window through the loss wrapper",
    ),
    SparseSite(
        site="diff.as_loss_scatter",
        engine="diff", entry="*",
        primitive="scatter*", mode="fill_or_drop",
        provenance=("const", "iota", "operand"),
        unique_indices=False,
        note="as_flows.relax_scatter through the loss wrapper",
    ),
    # -- device FlowMonitor packet rings (tpudes/obs/flowmon.py) ------
    # One site per engine: flow_ring_write's dynamic_update_slice at
    # ring slot ``step % FLOW_RING_CAP`` — the start index is the
    # engine's monotonic step counter reduced by lax.rem, so every
    # write is in-bounds by the modulus; XLA clamps DUS starts anyway
    # (mode clip).  LTE is the exception that proves the vmap hazard:
    # its advance is replica-vmapped with a batched carry, so the DUS
    # batching rule lowers the ring write to a scatter (still mod-
    # rooted, still clip-moded).
    SparseSite(
        site="dumbbell.flow_ring",
        engine="dumbbell", entry="obs/advance",
        primitive="dynamic_update_slice", mode="clip",
        provenance=("operand", "mod"),
        note="FlowMonitor ring write at slot t % FLOW_RING_CAP "
             "(tpudes/obs/flowmon.py flow_ring_write)",
    ),
    SparseSite(
        site="bss.flow_ring",
        engine="bss", entry="obs/advance",
        primitive="dynamic_update_slice", mode="clip",
        provenance=("operand", "mod"),
        note="FlowMonitor ring write at slot step % FLOW_RING_CAP "
             "(tpudes/obs/flowmon.py flow_ring_write)",
    ),
    SparseSite(
        site="lte_sm.flow_ring",
        engine="lte_sm", entry="obs/advance",
        primitive="scatter", mode="clip",
        provenance=("operand", "mod"),
        note="FlowMonitor ring write at slot t % FLOW_RING_CAP; the "
             "replica vmap batches the DUS start index, so the "
             "batching rule lowers it to scatter — indices stay "
             "mod-bounded",
    ),
    SparseSite(
        site="wired.flow_ring",
        engine="wired", entry="obs/advance",
        primitive="dynamic_update_slice", mode="clip",
        provenance=("operand", "mod"),
        note="FlowMonitor ring write at slot t % FLOW_RING_CAP; rides "
             "the no-gather kernel through the JXL001 contract "
             "relaxation (verified registered sites only)",
    ),
    # -- wired / hybrid: one-time init packet-table expansion ---------
    SparseSite(
        site="wired.init_paths",
        engine="wired", entry="*/init",
        primitive="gather", mode="promise_in_bounds",
        provenance=("const",),
        note="per-packet hop tables gathered from the validated paths "
             "array — WiredProgram.__post_init__ rejects any path "
             "entry >= n_links; init is one-time, outside the "
             "no-gather step-kernel contract",
    ),
    SparseSite(
        site="wired_space.init_paths",
        engine="wired_space", entry="*/init",
        primitive="gather", mode="promise_in_bounds",
        provenance=("const",),
        note="hybrid space-lane init uses the same validated "
             "packet-table expansion as wired.init_paths",
    ),
)


def sites_for(engine: str, tag: str, prim: str):
    """Registered sites matching one eqn (``tag`` is
    ``variant/entry``)."""
    return [
        s
        for s in SPARSE_SITES
        if s.engine == engine
        and fnmatch(tag, s.entry)
        and fnmatch(prim, s.primitive)
    ]


def _check_site(site, eqn, kinds, mode) -> list:
    """Contract problems of one (site, eqn) pairing — empty means the
    site audits this eqn."""
    problems = []
    if mode != site.mode:
        problems.append(
            f"mode is '{mode}' but site '{site.site}' declares "
            f"'{site.mode}'"
        )
    undeclared = sorted(kinds - set(site.provenance))
    if undeclared:
        problems.append(
            f"index provenance {undeclared} not in site "
            f"'{site.site}' contract {sorted(site.provenance)}"
        )
    if site.unique_indices is not None and eqn.primitive.name.startswith(
        "scatter"
    ):
        actual = bool(eqn.params.get("unique_indices", False))
        if actual != bool(site.unique_indices):
            problems.append(
                f"unique_indices is {actual} but site "
                f"'{site.site}' declares {bool(site.unique_indices)}"
            )
    return problems


def audit_entry(engine: str, tag: str, closed_jaxpr) -> list:
    """JXL008 audit of one traced entry: every sparse eqn must match a
    registered site whose contract the jaxpr upholds.

    Returns audit records ``{prim, mode, kinds, ok, site, problems}``
    — one per sparse eqn.  ``ok=False`` with ``site=None`` is an
    unaudited site; ``ok=False`` with a site is a contradicted
    contract."""
    records = []
    for eqn, frame in _collect_sparse_eqns(closed_jaxpr):
        prim = eqn.primitive.name
        kinds = set()
        for iv in _index_operands(eqn):
            kinds |= classify_roots(iv, frame)
        mode = _eqn_mode(eqn)
        cands = sites_for(engine, tag, prim)
        rec = {
            "prim": prim,
            "mode": mode,
            "kinds": sorted(kinds),
            "ok": False,
            "site": None,
            "problems": [],
        }
        if not cands:
            rec["problems"] = ["unregistered sparse site"]
        else:
            best = None
            for site in cands:
                problems = _check_site(site, eqn, kinds, mode)
                if not problems:
                    rec["ok"] = True
                    rec["site"] = site.site
                    break
                if best is None or len(problems) < len(best[1]):
                    best = (site, problems)
            if not rec["ok"]:
                rec["site"] = best[0].site
                rec["problems"] = best[1]
        records.append(rec)
    return records


def entry_is_audited(records) -> bool:
    return all(r["ok"] for r in records)
