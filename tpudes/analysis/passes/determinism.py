"""determinism pass: event order must not depend on interpreter state.

The simulator's reproducibility contract is (ts, uid) total order with
uids handed out in schedule-call order — so the *schedule-call order*
itself must be deterministic.  Two ways repos break it:

DET001 — ``Simulator.Schedule*`` (or ``.Insert`` on a scheduler)
invoked from a loop over a ``set``/``frozenset`` (literal, call, or a
name assigned from one in the same function): set iteration order
varies with PYTHONHASHSEED, so uids — and therefore event tie-breaks —
differ run to run.

DET002 — ``id()`` inside a sort key (``sorted``/``.sort``/``min``/
``max`` key callables, or elements of a tuple-building sort key):
CPython ids are allocation addresses, unstable across runs, so any
ordering derived from them is unreproducible.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import (
    Finding,
    Pass,
    SourceModule,
    dotted_name,
    scope_walk,
)

_SCHEDULE_NAMES = {
    "Schedule", "ScheduleNow", "ScheduleWithContext", "ScheduleAt",
    "ScheduleDestroy", "Insert",
}
_SORTERS = {"sorted", "min", "max"}


def _is_set_expr(node: ast.AST, set_names: set[str]) -> bool:
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func)
        if dn in ("set", "frozenset"):
            return True
        # set-algebra results are sets too
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "union", "intersection", "difference", "symmetric_difference",
        ):
            return _is_set_expr(node.func.value, set_names)
    if isinstance(node, ast.Name):
        return node.id in set_names
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.BitAnd, ast.BitOr, ast.Sub, ast.BitXor)
    ):
        return _is_set_expr(node.left, set_names) or _is_set_expr(
            node.right, set_names
        )
    return False


def _schedule_call(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in _SCHEDULE_NAMES:
        dn = dotted_name(f)
        return dn or f.attr
    return None


class DeterminismPass(Pass):
    name = "determinism"
    codes = {
        "DET001": "event scheduled from set iteration (hash-order-dependent)",
        "DET002": "id() used as a sort / tie-break key",
    }

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []
        for scope in ast.walk(mod.tree):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                out.extend(self._check_scope(mod, scope))
        return out

    def _check_scope(self, mod, scope) -> list[Finding]:
        out: list[Finding] = []
        # DET001: one in-source-order pass tracking which names hold a
        # set RIGHT NOW — `backlog = sorted(backlog)` un-marks the
        # name, so scheduling from the sorted rebind stays clean
        set_names: set[str] = set()
        for node in scope_walk(scope):
            if isinstance(node, ast.Assign):
                is_set = _is_set_expr(node.value, set_names)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        (set_names.add if is_set
                         else set_names.discard)(t.id)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    if _is_set_expr(node.value, set_names):
                        set_names.add(node.target.id)
                    else:
                        set_names.discard(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)) and _is_set_expr(
                node.iter, set_names
            ):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        sched = _schedule_call(sub)
                        if sched is not None:
                            out.append(Finding(
                                mod.path, sub.lineno, sub.col_offset,
                                "DET001",
                                f"'{sched}' called while iterating a set — "
                                "uid order follows PYTHONHASHSEED",
                            ))

        # DET002: id() inside sort keys
        for node in scope_walk(scope):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_sorter = (
                isinstance(f, ast.Name) and f.id in _SORTERS
            ) or (isinstance(f, ast.Attribute) and f.attr == "sort")
            if not is_sorter:
                continue
            key_exprs = [k.value for k in node.keywords if k.arg == "key"]
            for key in key_exprs:
                for sub in ast.walk(key):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "id"
                    ):
                        out.append(Finding(
                            mod.path, sub.lineno, sub.col_offset, "DET002",
                            "id() in a sort key — object addresses are "
                            "not stable across runs",
                        ))
        return out
