"""Built-in analysis passes.  Importing this package registers them;
third-party passes call :func:`tpudes.analysis.register_pass` directly.
"""

from tpudes.analysis.passes.cross_replica import CrossReplicaShapePass
from tpudes.analysis.passes.determinism import DeterminismPass
from tpudes.analysis.passes.event_hygiene import EventHygienePass
from tpudes.analysis.passes.jit_purity import JitPurityPass
from tpudes.analysis.passes.key_discipline import KeyDisciplinePass
from tpudes.analysis.passes.liveness import ServingLivenessPass
from tpudes.analysis.passes.registry_parity import RegistryParityPass
from tpudes.analysis.passes.rng_discipline import RngDisciplinePass
from tpudes.analysis.passes.style import StylePass
from tpudes.analysis.passes.time_units import TimeUnitsPass
from tpudes.analysis.passes.trace_arity import TraceArityPass

BUILTIN_PASSES = [
    StylePass,
    JitPurityPass,
    RngDisciplinePass,
    DeterminismPass,
    EventHygienePass,
    RegistryParityPass,
    TraceArityPass,
    CrossReplicaShapePass,
    TimeUnitsPass,
    KeyDisciplinePass,
    ServingLivenessPass,
]
