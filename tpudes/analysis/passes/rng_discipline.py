"""rng-discipline pass: jax.random key hygiene + seeded-stream bypass.

RNG001 — a ``jax.random`` key consumed by two calls without an
intervening ``split``/``fold_in`` produces *identical* draws; on the
replica axis that correlates every replica's noise.  The scan is a
linter-grade abstract interpretation per function: statements in
source order, ``if``/``else`` arms forked and OR-merged, rebinding
from a non-deriver source dropping the tracked state (loop bodies get
one linear pass, so per-iteration reuse is under-reported).

RNG002 — host RNG (``np.random`` / stdlib ``random``) anywhere in
``tpudes/`` outside ``tpudes/core/rng.py`` bypasses the MRG32k3a /
threefry seeded stream API, breaking the RngSeedManager reproducibility
contract (run/substream selection never reaches it).
"""

from __future__ import annotations

import ast
import itertools

from tpudes.analysis.base import (
    Finding,
    Pass,
    SourceModule,
    dotted_name,
    walk_in_order,
)

#: parameters assumed to carry a PRNG key when named like one
_KEY_PARAMS = {"key", "subkey", "rng_key", "prng_key", "rngkey"}
#: jax.random functions that *derive* keys rather than draw with them
_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "clone", "wrap_key_data"}


def _jax_random_aliases(tree: ast.Module) -> set[str]:
    """Bound names that refer to the ``jax.random`` module."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    out.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        out.add(a.asname or "random")
    return out


def _np_and_stdlib_random(tree: ast.Module):
    """(numpy module aliases, stdlib random aliases, names imported
    from stdlib random)."""
    np_alias, rand_alias, rand_funcs = set(), set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                if a.name == "numpy" or a.name.startswith("numpy."):
                    np_alias.add(bound)
                elif a.name == "random":
                    rand_alias.add(bound)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                for a in node.names:
                    rand_funcs.add(a.asname or a.name)
    return np_alias, rand_alias, rand_funcs


class RngDisciplinePass(Pass):
    name = "rng-discipline"
    codes = {
        "RNG001": "jax.random key consumed twice without split/fold_in",
        "RNG002": "RNG use that bypasses the seeded stream API",
    }

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []
        jr = _jax_random_aliases(mod.tree)
        # jax.random is reachable as jax.random.X without an alias too
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_function(mod, node, jr))
        out.extend(self._check_bypass(mod))
        return out

    # --- RNG001 -----------------------------------------------------------
    def _jax_random_callee(self, func: ast.AST, jr: set[str]) -> str | None:
        """The jax.random function name for a call target, or None."""
        dn = dotted_name(func)
        if dn is None:
            return None
        parts = dn.split(".")
        if len(parts) >= 3 and parts[-3] == "jax" and parts[-2] == "random":
            return parts[-1]
        if len(parts) == 2 and parts[0] in jr:
            return parts[1]
        return None

    def _check_function(self, mod, fn, jr) -> list[Finding]:
        """Abstract interpretation of key consumption, one function at
        a time.  Branches of an ``if``/``try`` fork the state and merge
        with OR (consumed-on-either-path counts), so mutually-exclusive
        ``split`` calls do not false-positive.  Loops and nested defs
        get a single linear pass of their own."""
        out: list[Finding] = []
        keys: dict[str, bool] = {}  # name -> consumed?
        a = fn.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            if arg.arg in _KEY_PARAMS:
                keys[arg.arg] = False

        def scan_expr(expr: ast.AST):
            """Consume keys used by jax.random calls, in source order
            (the expression node itself included).  Derivers
            (split/fold_in) flag an already-consumed key but do NOT
            consume: deriving several children from one parent key —
            ``fold_in(key, 1)`` then ``fold_in(key, 2)`` — is the
            idiomatic safe pattern."""
            for node in itertools.chain([expr], walk_in_order(expr)):
                if not isinstance(node, ast.Call):
                    continue
                callee = self._jax_random_callee(node.func, jr)
                if callee is None or callee == "PRNGKey":
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in keys:
                        if keys[arg.id]:
                            out.append(Finding(
                                mod.path, arg.lineno, arg.col_offset,
                                "RNG001",
                                f"key '{arg.id}' already consumed — reuse "
                                "without split/fold_in repeats the same "
                                "draw",
                            ))
                        if callee not in _DERIVERS:
                            keys[arg.id] = True

        def scan_stmts(stmts: list[ast.stmt]):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # own scope, scanned separately
                if isinstance(stmt, ast.If):
                    scan_expr(stmt.test)
                    before = dict(keys)
                    scan_stmts(stmt.body)
                    after_body = dict(keys)
                    keys.clear()
                    keys.update(before)
                    scan_stmts(stmt.orelse)
                    for name in set(after_body) | set(keys):
                        keys[name] = after_body.get(name, False) or keys.get(
                            name, False
                        )
                elif isinstance(stmt, ast.Try):
                    scan_stmts(stmt.body)
                    for h in stmt.handlers:
                        scan_stmts(h.body)
                    scan_stmts(stmt.orelse)
                    scan_stmts(stmt.finalbody)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter)
                    scan_stmts(stmt.body)
                    scan_stmts(stmt.orelse)
                elif isinstance(stmt, ast.While):
                    scan_expr(stmt.test)
                    scan_stmts(stmt.body)
                    scan_stmts(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        scan_expr(item.context_expr)
                    scan_stmts(stmt.body)
                elif isinstance(stmt, ast.Assign):
                    # the RHS consumes first, THEN targets rebind fresh:
                    # `key, sub = split(key)` leaves `key` fresh
                    scan_expr(stmt.value)
                    callee = (
                        self._jax_random_callee(stmt.value.func, jr)
                        if isinstance(stmt.value, ast.Call) else None
                    )
                    for t in stmt.targets:
                        for sub in ast.walk(t):
                            if not isinstance(sub, ast.Name):
                                continue
                            if callee in _DERIVERS:
                                keys[sub.id] = False
                            else:
                                # rebound from an unknown source: stop
                                # tracking rather than carry a stale
                                # consumed flag onto a fresh key
                                keys.pop(sub.id, None)
                else:
                    scan_expr(stmt)

        scan_stmts(fn.body)
        return out

    # --- RNG002 -----------------------------------------------------------
    def _check_bypass(self, mod: SourceModule) -> list[Finding]:
        if not mod.in_package("tpudes") or mod.path.endswith("core/rng.py"):
            return []
        np_alias, rand_alias, rand_funcs = _np_and_stdlib_random(mod.tree)
        if not (np_alias or rand_alias or rand_funcs):
            return []
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is not None:
                head, _, rest = dn.partition(".")
                if head in np_alias and rest.startswith("random."):
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset, "RNG002",
                        f"'{dn}()' bypasses the seeded stream API "
                        "(RngSeedManager run/substream never reaches it)",
                    ))
                elif head in rand_alias and rest:
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset, "RNG002",
                        f"'{dn}()' uses stdlib random instead of the "
                        "seeded stream API",
                    ))
            elif isinstance(node.func, ast.Name) and node.func.id in rand_funcs:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "RNG002",
                    f"'{node.func.id}()' uses stdlib random instead of "
                    "the seeded stream API",
                ))
        return out
