"""KEY001: ``fold_in`` key discipline in the device-engine packages.

The runtime's bucketing/chunking bit-exactness contract
(tpudes/parallel/runtime.py) requires every PRNG stream to be a pure
function of stable indices — ``fold_in(key, replica)``,
``fold_in(key, t)``.  Two AST shapes break it:

- ``jax.random.split(key, n)`` with a NON-LITERAL count: threefry lays
  counters out per-shape, so the rows depend on ``n`` — growing the
  replica axis (bucket padding) or the window count silently reshuffles
  every stream.  A fixed-arity split (``split(k)`` / ``split(k, 3)``)
  of an already-folded key stays pure in its inputs and is allowed.
- **raw-key reuse**: the same key name fed to two draw calls without an
  intervening rebinding — both draws see identical bits, so "independent"
  coins are correlated 1.0.

Scope: ``tpudes/parallel/``, ``tpudes/ops/`` and ``tpudes/traffic/``
(the device-engine surface — the traffic subsystem's eager table
draws and per-arrival gap streams ride the same contract); host-side
model code draws from the seeded MRG32k3a stream API instead.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import (
    Finding,
    Pass,
    SourceModule,
    dotted_name,
    scope_walk,
)

#: jax.random sampling functions that CONSUME a key (fold_in/split
#: derive new keys and are not draws; key_data etc. are conversions)
_DRAW_FNS = frozenset(
    {"uniform", "normal", "randint", "bernoulli", "choice", "bits",
     "exponential", "gamma", "beta", "poisson", "categorical",
     "truncated_normal", "permutation", "laplace", "gumbel",
     "rademacher", "cauchy", "dirichlet", "loggamma", "multivariate_normal"}
)

#: module spellings of jax.random in this codebase.  Bare ``random``
#: is deliberately absent: stdlib ``random.uniform(lo, hi)`` has no
#: key argument and would read as raw-key reuse; ``np.random`` is the
#: rng-discipline pass's territory (RNG002).
_RANDOM_MODULES = frozenset({"jax.random", "jrandom", "jr"})


def _random_member(node: ast.AST) -> str | None:
    """``'split'``/``'uniform'``/… when ``node`` is a call target of the
    form ``<jax.random spelling>.<member>``, else None."""
    name = dotted_name(node)
    if name is None or "." not in name:
        return None
    mod, member = name.rsplit(".", 1)
    # "_jax.random.split" etc.: any dotted prefix ending in the
    # canonical jax.random spelling counts
    if mod in _RANDOM_MODULES or mod.endswith("jax.random"):
        return member
    return None


class KeyDisciplinePass(Pass):
    name = "key-discipline"
    codes = {
        "KEY001": "fold_in discipline: shape-dependent random.split or "
                  "raw-key reuse in device-engine code",
    }

    def applies(self, path: str) -> bool:
        return True  # scoping is per-module via in_package

    def check_module(self, mod: SourceModule) -> list[Finding]:
        if not (
            mod.in_package("tpudes", "parallel")
            or mod.in_package("tpudes", "ops")
            or mod.in_package("tpudes", "traffic")
        ):
            return []
        out: list[Finding] = []
        scopes = [mod.tree] + [
            n for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda))
        ]
        for scope in scopes:
            out.extend(self._check_scope(mod, scope))
        return out

    def _check_scope(self, mod: SourceModule, scope: ast.AST):
        out = []
        #: key-name -> the draw call node that last consumed it since
        #: its binding (linear source-order approximation, same model
        #: as the rng-discipline pass; scope_walk never descends into
        #: nested function scopes — they are scanned on their own)
        drawn: dict[str, ast.Call] = {}
        for node in scope_walk(scope):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.NamedExpr)):
                for tgt in self._targets(node):
                    drawn.pop(tgt, None)
                continue
            if not isinstance(node, ast.Call):
                continue
            member = _random_member(node.func)
            if member == "split":
                count = (
                    node.args[1] if len(node.args) > 1
                    else next(
                        (kw.value for kw in node.keywords
                         if kw.arg == "num"),
                        None,
                    )
                )
                if count is not None and not isinstance(
                    count, ast.Constant
                ):
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset,
                        "KEY001",
                        "jax.random.split with a shape-derived "
                        "count makes streams depend on the axis "
                        "size — bucketing/chunking would reshuffle "
                        "them; derive per-index keys via fold_in "
                        "(runtime.replica_keys)",
                    ))
                continue
            if member in _DRAW_FNS and node.args:
                key_arg = node.args[0]
                key_name = (
                    key_arg.id if isinstance(key_arg, ast.Name)
                    else None
                )
                if key_name is None:
                    continue
                if key_name in drawn:
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset,
                        "KEY001",
                        f"raw key {key_name!r} consumed by a "
                        "second draw without rebinding — the "
                        "draws are bit-correlated; fold_in a "
                        "fresh subkey per draw",
                    ))
                else:
                    drawn[key_name] = node
        return out

    @staticmethod
    def _targets(node) -> list[str]:
        tgts = []
        raw = (
            node.targets if isinstance(node, ast.Assign)
            else [node.target]
        )
        for t in raw:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    tgts.append(n.id)
        return tgts
