"""time-units pass: Time-vs-ticks unit confusion at the ``Simulator``
boundary (the other still-unbuilt rule from the PR 1 plan).

``Time`` arithmetic coerces bare numbers through ``Time(other)`` —
which interprets them as raw TICKS (nanoseconds at the default
resolution).  So ``Simulator.Schedule(Seconds(1) + 5, cb)`` schedules
at 1 s + 5 *nanoseconds*, and ``Simulator.Now() > 100`` compares
against 100 ns — both type-check, trace, and run, silently off by up
to nine orders of magnitude from the author's likely intent.  Upstream
ns-3 has the same footgun (``Time::Time(int64_t)`` is tick-valued);
the unit-safe spelling is always an explicit constructor
(``Seconds``/``MilliSeconds``/…) or ``Simulator.NowTicks()`` when raw
ticks are genuinely meant.

TIM001 fires when raw numeric literals cross the PUBLIC ``Simulator``
facade boundary:

- the delay argument of ``Simulator.Schedule`` /
  ``Simulator.ScheduleWithContext`` / ``Simulator.Stop`` is a bare
  numeric literal, or an additive expression mixing a Time-constructor
  call with a bare numeric literal;
- ``Simulator.Now()`` is combined with a bare numeric literal via
  ``+``/``-`` or compared against one.

The internal ``SimulatorImpl`` layer deliberately speaks ticks
(``delay_ticks`` parameters) and is not matched: only the dotted
``Simulator.*`` facade is the unit boundary.  A literal ``0`` delay is
exempt — zero is the same instant in every unit, and schedule-at-0 is
an established idiom.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule, dotted_name

#: unit-safe Time constructors (core/nstime.py)
_TIME_CTORS = {
    "Seconds", "MilliSeconds", "MicroSeconds", "NanoSeconds",
    "PicoSeconds", "FemtoSeconds", "Minutes", "Hours", "Days", "Time",
}

#: Simulator facade method -> index of its Time delay argument
_DELAY_ARG = {"Schedule": 0, "ScheduleWithContext": 1, "Stop": 0}


def _is_number(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) and not isinstance(
            node.value, bool
        )
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd)
    ):
        return _is_number(node.operand)
    return False


def _is_zero(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp):
        return _is_zero(node.operand)
    return isinstance(node, ast.Constant) and node.value == 0


def _is_time_expr(node: ast.AST) -> bool:
    """A call of a unit-safe constructor or of ``Simulator.Now``."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return last in _TIME_CTORS or _is_now(node)


def _is_now(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and dotted_name(node.func) is not None
        and dotted_name(node.func).endswith("Simulator.Now")
    )


def _mixed_additive(node: ast.AST) -> bool:
    """An ``a + b`` / ``a - b`` mixing a Time expression with a bare
    numeric literal (either side; one level of nesting on the Time
    side so ``Seconds(1) + Seconds(2) - 5`` is caught)."""
    if not (
        isinstance(node, ast.BinOp)
        and isinstance(node.op, (ast.Add, ast.Sub))
    ):
        return False
    left, right = node.left, node.right

    def timeish(n):
        return _is_time_expr(n) or _mixed_additive(n) or (
            isinstance(n, ast.BinOp)
            and isinstance(n.op, (ast.Add, ast.Sub))
            and (timeish(n.left) or timeish(n.right))
        )

    return (timeish(left) and _is_number(right)) or (
        _is_number(left) and timeish(right)
    )


class TimeUnitsPass(Pass):
    name = "time-units"
    codes = {
        "TIM001": "raw-int arithmetic mixed with Time values crossing "
                  "the Simulator Schedule/Now boundary",
    }

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []

        def flag(node, msg):
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, "TIM001", msg
            ))

        for node in ast.walk(mod.tree):
            # --- Schedule/Stop delay argument --------------------------
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is not None and "." in name:
                    base, _, method = name.rpartition(".")
                    if (
                        base.rsplit(".", 1)[-1] == "Simulator"
                        and method in _DELAY_ARG
                        and len(node.args) > _DELAY_ARG[method]
                    ):
                        delay = node.args[_DELAY_ARG[method]]
                        # literal 0 is unit-independent ("now" in every
                        # resolution) — the established schedule-at-0
                        # idiom carries no tick confusion
                        if _is_number(delay) and not _is_zero(delay):
                            flag(
                                node,
                                f"bare number as the Simulator.{method} "
                                "delay is interpreted as raw TICKS — "
                                "wrap it in Seconds()/MilliSeconds()/…",
                            )
                        elif _mixed_additive(delay):
                            flag(
                                node,
                                f"Simulator.{method} delay adds a bare "
                                "number to a Time — the number is raw "
                                "TICKS; wrap it in a Time constructor",
                            )
            # --- Now() arithmetic / comparisons ------------------------
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Sub)
            ):
                if (_is_now(node.left) and _is_number(node.right)) or (
                    _is_number(node.left) and _is_now(node.right)
                ):
                    flag(
                        node,
                        "Simulator.Now() +/- a bare number treats it as "
                        "raw TICKS — wrap it in a Time constructor (or "
                        "use Simulator.NowTicks() for tick math)",
                    )
            if isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(_is_now(o) for o in operands) and any(
                    _is_number(o) for o in operands
                ):
                    flag(
                        node,
                        "comparing Simulator.Now() against a bare number "
                        "compares raw TICKS — compare against a Time "
                        "constructor (or use Simulator.NowTicks())",
                    )
        return out
