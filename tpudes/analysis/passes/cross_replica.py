"""cross-replica shape pass: per-replica state arrays in lowered
programs must carry the replica axis LEADING, built from the replica
operand (the still-unbuilt rule from the PR 1 plan).

Every device engine lays per-replica state out as ``(R, …)`` arrays:
the replica axis is the vmap/shard axis, ``shard_replica_axis`` only
shards a leading-or-config-adjacent axis whose size equals the padded
replica count, and the bucketing contract (pad + slice-back) slices
``[:R]`` on axis 0.  An array that smuggles the replica count into a
*trailing* position type-checks, traces, and runs — and then silently
breaks sharding (the axis never matches, so the array replicates per
device) and bucketing slice-back (the wrong axis is sliced).  That is
exactly the class of bug a shape-polymorphic tracer cannot catch.

SHP001 fires inside ``tpudes/parallel/`` scopes that bind a replica
operand (a parameter or assignment named ``replicas`` / ``R`` /
``r_pad`` / ``n_replicas``, including bindings inherited from an
enclosing function — the engines' ``build()`` closures) when an array
constructor (``jnp.zeros/ones/empty/full/broadcast_to`` and np
equivalents) takes a literal shape tuple with the replica operand at
any position other than 0.  Leading-position use, replica-free shapes,
and computed (non-literal) shapes are not flagged.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule, scope_walk

#: names a scope may bind the replica operand to (the engines' idiom)
_REPLICA_NAMES = {"replicas", "R", "r_pad", "n_replicas"}

#: constructor attr -> index of its shape argument
_SHAPE_ARG = {
    "zeros": 0,
    "ones": 0,
    "empty": 0,
    "full": 0,
    "broadcast_to": 1,
}


def _bound_names(fn: ast.AST) -> set[str]:
    """Replica-operand names bound directly in ``fn``'s scope (params
    and simple/tuple assignment targets; nested scopes collect their
    own bindings when the walker recurses into them)."""
    out: set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for p in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        ):
            if p.arg in _REPLICA_NAMES:
                out.add(p.arg)
    for node in scope_walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for el in t.elts if isinstance(t, ast.Tuple) else [t]:
                if isinstance(el, ast.Name) and el.id in _REPLICA_NAMES:
                    out.add(el.id)
    return out


def _shape_tuple(call: ast.Call) -> ast.Tuple | None:
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _SHAPE_ARG:
        return None
    idx = _SHAPE_ARG[fn.attr]
    shape = None
    if len(call.args) > idx and not any(
        isinstance(a, ast.Starred) for a in call.args[: idx + 1]
    ):
        shape = call.args[idx]
    for kw in call.keywords:
        if kw.arg == "shape":
            shape = kw.value
    return shape if isinstance(shape, ast.Tuple) else None


class CrossReplicaShapePass(Pass):
    name = "cross-replica-shape"
    codes = {
        "SHP001": "per-replica state array's replica axis is not the "
                  "leading axis built from the replica operand",
    }

    def applies(self, path: str) -> bool:
        return "tpudes/parallel/" in path or path.startswith(
            "tpudes/parallel"
        )

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []

        def visit(scope: ast.AST, inherited: set[str]) -> None:
            bound = inherited | _bound_names(scope)
            for node in scope_walk(scope):
                if isinstance(node, ast.Call) and bound:
                    shape = _shape_tuple(node)
                    if shape is not None:
                        for i, el in enumerate(shape.elts[1:], start=1):
                            if (
                                isinstance(el, ast.Name)
                                and el.id in bound
                            ):
                                out.append(Finding(
                                    mod.path, node.lineno,
                                    node.col_offset, "SHP001",
                                    f"replica operand '{el.id}' at shape "
                                    f"position {i}; per-replica state "
                                    "must lead with the replica axis "
                                    "(sharding and bucket slice-back "
                                    "operate on axis 0)",
                                ))
                                break
            # recurse into nested scopes with the bindings visible there
            for child in _direct_nested(scope):
                visit(child, bound)

        for top in _direct_nested(mod.tree):
            visit(top, set())
        return out


def _direct_nested(scope: ast.AST):
    """Function/lambda scopes whose nearest enclosing scope is
    ``scope`` (not deeper)."""
    found = []

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                found.append(child)
            else:
                walk(child)

    walk(scope)
    return found
