"""serving-liveness pass: cross-process/thread waits must be bounded.

The serving fleet's fault model (ISSUE 13) says a dead or hung peer is
a *recoverable* event — which is only true if no wait can block
forever.  A bare ``Condition.wait()`` is the classic lost-wakeup hang
(the notify raced the sleep and nobody ever wakes you), and a bare
``Queue.get()`` / ``conn.recv()`` / ``conn.recv_bytes()`` on a pipe to
a process that just got SIGKILLed parks the scheduler thread
permanently — the exact operator-babysitting failure the
fault-tolerance layer exists to remove.

SRV001 fires in the serving layer (``tpudes/serving/``) and the
process-mesh launcher (``tpudes/parallel/procmesh.py``) on calls of
the blocking-wait shapes with NO arguments and NO ``timeout=`` (a
zero-arg ``.get()`` cannot be ``dict.get`` — that needs a key — and a
zero-arg ``.wait()``/``.recv()``/``.recv_bytes()`` is precisely the
unbounded form).  Sites that are *intentionally* unbounded (a
shutdown drain that must block) carry ``# tpudes: ignore[SRV001]``
with a justification, or live behind
:func:`tpudes.parallel.mpi.recv_frame`'s explicit ``timeout_s=None``.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule

#: zero-arg attribute calls that block unboundedly
_BLOCKING_ATTRS = {"wait", "get", "recv", "recv_bytes"}


class ServingLivenessPass(Pass):
    name = "serving-liveness"
    codes = {
        "SRV001": "unbounded blocking wait (no timeout) in the serving "
                  "layer — a dead/hung peer or lost wakeup hangs the "
                  "scheduler forever",
    }

    def applies(self, path: str) -> bool:
        return (
            "tpudes/serving/" in path
            or path.endswith("tpudes/parallel/procmesh.py")
        )

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr not in _BLOCKING_ATTRS:
                continue
            if node.args or node.keywords:
                # any argument bounds it (wait(t), get(timeout=...),
                # poll-guarded recv helpers take theirs explicitly) or
                # disambiguates (dict.get(key))
                continue
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, "SRV001",
                f"bare blocking '.{fn.attr}()' without a timeout: a "
                "dead peer or lost wakeup hangs this thread forever — "
                "pass a timeout (and loop) or route through "
                "mpi.recv_frame",
            ))
        return out
