"""event-hygiene pass: leaked events and swallowed callback errors.

EVT401-class bugs produced two of this round's advisor findings (the
PIE timer mis-arm and the 6LoWPAN reassembly leak — ADVICE.md), so the
heuristics here are tuned to those shapes:

EVT001 — inside a class that defines a stop/teardown method, an
expression-statement ``Simulator.Schedule``/``ScheduleWithContext``
whose EventId is dropped: teardown cannot Cancel what it never held, so
the event outlives the object (the classic "simulation never drains"
leak).  ``ScheduleNow``/``ScheduleDestroy`` are exempt (immediate /
teardown-by-design).

EVT002 — ``except Exception: pass`` (or BaseException) inside a
function in a Simulator-importing module: an event callback that
swallows everything turns a model bug into silent event loss.

EVT003 — a keyed buffer (``self.X`` dict) whose entries are removed
only on completion (``del self.X[k]`` / ``.pop``) in a class that never
schedules any event: nothing expires a stranded entry, so one lost
packet leaks the buffer forever (the pre-fix 6LoWPAN reassembly shape;
cf. Ipv4L3Protocol._expire_fragments).
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule, dotted_name

_TEARDOWN_NAMES = {
    "StopApplication", "DoDispose", "Dispose", "Stop", "stop",
    "teardown", "Teardown", "close", "Close",
}
_LEAKY_SCHEDULES = {"Schedule", "ScheduleWithContext", "ScheduleAt"}


def _simulator_schedule(node: ast.Call) -> str | None:
    """'Simulator.Schedule*' attr name for a call target, or None."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr.startswith("Schedule"):
        dn = dotted_name(f)
        if dn is not None and "Simulator" in dn.split("."):
            return f.attr
    return None


class EventHygienePass(Pass):
    name = "event-hygiene"
    codes = {
        "EVT001": "scheduled EventId dropped in a class with a teardown method",
        "EVT002": "except Exception: pass swallows event-callback errors",
        "EVT003": "keyed buffer with completion-only cleanup and no expiry event",
    }

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []
        uses_simulator = (
            "Simulator" in mod.source or "simulator" in mod.source
        )
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(mod, node))
        if uses_simulator:
            out.extend(self._check_swallows(mod))
        return out

    # --- EVT001 + EVT003 --------------------------------------------------
    def _check_class(self, cls_mod, cls: ast.ClassDef) -> list[Finding]:
        mod = cls_mod
        out: list[Finding] = []
        method_names = {
            n.name for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        has_teardown = bool(method_names & _TEARDOWN_NAMES)

        schedules_any = False
        completion_deletes: dict[str, ast.AST] = {}  # attr -> first del site
        keyed_buffers: set[str] = set()

        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and _simulator_schedule(node):
                schedules_any = True
            # keyed accumulation: self.X[k] = ... or self.X.setdefault
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                    ):
                        keyed_buffers.add(t.value.attr)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "setdefault"
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"
            ):
                keyed_buffers.add(node.func.value.attr)
            # completion-only cleanup: del self.X[k] / self.X.pop(k)
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"
                    ):
                        completion_deletes.setdefault(t.value.attr, t)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "pop"
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"
                and node.args
            ):
                completion_deletes.setdefault(node.func.value.attr, node)

        if has_teardown:
            for stmt in ast.walk(cls):
                if not (
                    isinstance(stmt, ast.Expr)
                    and isinstance(stmt.value, ast.Call)
                ):
                    continue
                call = stmt.value
                dn = dotted_name(call.func)
                if (
                    dn is not None
                    and "Simulator" in dn.split(".")
                    and dn.rsplit(".", 1)[-1] in _LEAKY_SCHEDULES
                ):
                    out.append(Finding(
                        mod.path, stmt.lineno, stmt.col_offset, "EVT001",
                        f"'{dn}' EventId dropped — class '{cls.name}' has a "
                        "teardown method that can never Cancel it",
                    ))

        if not schedules_any:
            for attr, site in completion_deletes.items():
                if attr in keyed_buffers:
                    out.append(Finding(
                        mod.path, site.lineno, site.col_offset, "EVT003",
                        f"keyed buffer 'self.{attr}' in '{cls.name}' is "
                        "cleaned up only on completion and the class never "
                        "schedules an expiry — stranded entries leak forever",
                    ))
        return out

    # --- EVT002 -----------------------------------------------------------
    def _check_swallows(self, mod: SourceModule) -> list[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            t = node.type
            name = dotted_name(t) if t is not None else None
            if name not in ("Exception", "BaseException"):
                continue
            body_is_noop = all(
                isinstance(s, ast.Pass)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant)
                    and s.value.value is Ellipsis)
                for s in node.body
            )
            if body_is_noop:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "EVT002",
                    f"'except {name}: pass' silently swallows callback "
                    "errors (event loss with no trace)",
                ))
        return out
