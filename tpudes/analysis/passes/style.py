"""Style pass: the four generic gates ported from the original
``tools/lint.py`` (which is now a thin shim over this pass).

LNT001 syntax error · LNT002 tab character · LNT003 unused
module-level import · LNT004 duplicate import · LNT005 bare except.
Function-local lazy imports remain the repo's idiom and are exempt;
``__init__.py`` re-export imports are exempt from LNT003.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule

#: names imported for re-export or registration side effects
EXPORT_FILES = {"__init__.py"}


def _module_imports(tree):
    """Module-level imports only: yields (lineno, bound_name, identity).
    Identity distinguishes ``import importlib.util`` from
    ``import importlib.machinery`` (same bound name, distinct imports).
    """
    for node in tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                yield node.lineno, (a.asname or a.name).split(".")[0], (
                    a.asname or a.name
                )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name != "*":
                    name = a.asname or a.name
                    yield node.lineno, name, f"{node.module}.{name}"


def _used_names(tree):
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    # __all__ strings (and other short identifier-shaped constants)
    # count as usage, as in the original lint
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if len(node.value) < 80 and node.value.isidentifier():
                used.add(node.value)
    return used


class StylePass(Pass):
    name = "style"
    handles_syntax_errors = True
    codes = {
        "LNT001": "syntax error",
        "LNT002": "tab character",
        "LNT003": "unused module-level import",
        "LNT004": "duplicate module-level import",
        "LNT005": "bare except",
    }

    def check_module(self, mod: SourceModule) -> list[Finding]:
        out: list[Finding] = []
        if "\t" in mod.source:
            line = mod.source[: mod.source.index("\t")].count("\n") + 1
            out.append(Finding(mod.path, line, 0, "LNT002", "tab character"))
        if mod.tree is None:
            e = mod.syntax_error
            out.append(
                Finding(mod.path, e.lineno or 1, e.offset or 0,
                        "LNT001", f"syntax error: {e.msg}")
            )
            return out

        basename = mod.path.rsplit("/", 1)[-1]
        if basename not in EXPORT_FILES:
            used = _used_names(mod.tree)
            seen: dict[str, int] = {}
            for lineno, name, ident in _module_imports(mod.tree):
                if ident in seen and lineno != seen[ident]:
                    # no line numbers in the message: it is the
                    # baseline key, which must survive code motion
                    out.append(Finding(
                        mod.path, lineno, 0, "LNT004",
                        f"duplicate import '{ident}'",
                    ))
                seen.setdefault(ident, lineno)
                if name not in used:
                    out.append(Finding(
                        mod.path, lineno, 0, "LNT003",
                        f"unused import '{name}'",
                    ))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset,
                    "LNT005", "bare except",
                ))
        return out
