"""jit-purity pass: Python side effects inside traced code.

A function lifted onto the replica/topology axes (``jax.jit`` /
``vmap`` / ``pmap`` decorators, functions handed to those transforms or
to ``lax.scan``/``while_loop``/``cond``/``fori_loop``) executes once at
trace time — wall-clock reads, prints, host RNG draws and mutation of
Python state silently bake one trace's value into every replica.

Traced regions are found per module: decorated defs, bare-name
arguments to transform calls, lambdas inside ``lax.*`` control-flow
calls, plus everything nested inside those.  The wall-clock / print /
host-RNG rules additionally apply module-wide in ``tpudes/ops/`` and
``tpudes/parallel/`` — every line there is on or next to the device
path (ISSUE 1 tentpole scope).

JP001 wall-clock ``time.*`` · JP002 ``print`` · JP003 host RNG
(``np.random``/stdlib ``random``) · JP004 mutation of ``self`` /
globals / captured containers (traced regions only) · JP005 host-sync
calls (``block_until_ready`` / ``.item()`` / ``np.asarray``-family) in
traced regions — inside an engine step/cond function these force a
device→host round trip per loop iteration (or simply fail to trace),
exactly the serialization the async runtime exists to avoid; the hot
loop must accumulate on-device and fetch once at run end.
"""

from __future__ import annotations

import ast

from tpudes.analysis.base import Finding, Pass, SourceModule, dotted_name

_TRANSFORMS = {"jit", "vmap", "pmap"}
_LAX_HOF_TAILS = {
    "lax.scan", "lax.while_loop", "lax.cond", "lax.fori_loop",
    "lax.map", "lax.switch", "lax.associative_scan",
}
_TIME_FUNCS = {
    "time", "monotonic", "perf_counter", "process_time", "time_ns",
    "monotonic_ns", "perf_counter_ns",
}
#: numpy calls that force a traced value onto the host (JP005); jnp's
#: spellings are fine — they stay on device
_NP_HOST_FUNCS = {"asarray", "array", "ascontiguousarray"}

_MUTATORS = {
    "append", "extend", "insert", "add", "discard", "update", "pop",
    "popitem", "remove", "clear", "setdefault", "sort", "reverse",
    "appendleft", "extendleft",
}


def _alias_map(tree: ast.Module) -> dict[str, set[str]]:
    """Module-level import aliases for the modules this pass cares
    about: ``{"time": {...}, "numpy": {...}, "random": {...},
    "np_random": {...}, "time_funcs": {...}}``.  ``from jax import
    random`` deliberately does NOT land in the stdlib ``random``
    bucket."""
    out: dict[str, set[str]] = {
        "time": set(), "numpy": set(), "random": set(),
        "np_random": set(), "time_funcs": set(), "np_funcs": set(),
    }
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                if a.name == "time" or a.name.startswith("time."):
                    out["time"].add(bound)
                elif a.name == "numpy" or a.name.startswith("numpy."):
                    out["numpy"].add(bound)
                elif a.name == "random":
                    out["random"].add(bound)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                bound = a.asname or a.name
                if node.module == "time" and a.name in _TIME_FUNCS:
                    out["time_funcs"].add(bound)
                elif node.module == "numpy" and a.name == "random":
                    out["np_random"].add(bound)
                elif node.module == "numpy" and a.name in _NP_HOST_FUNCS:
                    out["np_funcs"].add(bound)
                elif node.module == "random":
                    out["random"].add(bound)  # stdlib draw functions
    return out


def _is_transform_ref(node: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` / ``jax.numpy...vmap`` style reference."""
    if isinstance(node, ast.Name):
        return node.id in _TRANSFORMS
    dn = dotted_name(node)
    return dn is not None and dn.rsplit(".", 1)[-1] in _TRANSFORMS


def _decorator_is_transform(dec: ast.AST) -> bool:
    if _is_transform_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(static_argnums=...) or @partial(jax.jit, ...)
        if _is_transform_ref(dec.func):
            return True
        return any(_is_transform_ref(a) for a in dec.args)
    return False


def _traced_regions(tree: ast.Module) -> list[ast.AST]:
    """FunctionDef / Lambda nodes whose bodies execute under trace."""
    traced_names: set[str] = set()
    regions: list[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_is_transform(d) for d in node.decorator_list):
                regions.append(node)
        elif isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            is_transform = _is_transform_ref(node.func)
            is_lax_hof = dn is not None and any(
                dn == t or dn.endswith("." + t) for t in _LAX_HOF_TAILS
            )
            if is_transform or is_lax_hof:
                for a in node.args:
                    if isinstance(a, ast.Name):
                        traced_names.add(a.id)
                    elif isinstance(a, ast.Lambda):
                        regions.append(a)
    if traced_names:
        for node in ast.walk(tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in traced_names
                and node not in regions
            ):
                regions.append(node)
    return regions


def _binding_names(target: ast.AST):
    """Names a target BINDS.  ``x = ...`` and ``x, y = ...`` bind; an
    Attribute/Subscript target (``obj.f = ...``, ``d[k] = ...``)
    mutates its receiver and binds nothing — the distinction JP004
    rides on."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            yield from _binding_names(e)
    elif isinstance(target, ast.Starred):
        yield from _binding_names(target.value)


def _bound_names(fn: ast.AST) -> set[str]:
    """Names bound inside the function (params, assignments, loop and
    comprehension targets, local imports, nested defs) — receivers NOT
    in this set are captured or global state."""
    bound: set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = fn.args
        for arg in (
            a.posonlyargs + a.args + a.kwonlyargs
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        ):
            bound.add(arg.arg)
    elif isinstance(fn, ast.Lambda):
        a = fn.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            bound.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                bound.update(_binding_names(t))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            bound.update(_binding_names(node.target))
        elif isinstance(node, ast.comprehension):
            bound.update(_binding_names(node.target))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if node is not fn:
                bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                bound.add((a.asname or a.name).split(".")[0])
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            bound.update(_binding_names(node.optional_vars))
        elif isinstance(node, ast.NamedExpr):
            bound.add(node.target.id)
    return bound


class JitPurityPass(Pass):
    name = "jit-purity"
    codes = {
        "JP001": "wall-clock time.* in traced/device-path code",
        "JP002": "print() in traced/device-path code",
        "JP003": "host RNG (np.random / stdlib random) in traced/device-path code",
        "JP004": "mutation of self/global/captured state in traced code",
        "JP005": "host-sync call (block_until_ready/.item()/np.asarray) in traced code",
    }

    def applies(self, path: str) -> bool:
        return path.split("/")[0] == "tpudes" or "/tpudes/" in path

    def check_module(self, mod: SourceModule) -> list[Finding]:
        aliases = _alias_map(mod.tree)
        regions = _traced_regions(mod.tree)
        findings: dict[tuple, Finding] = {}

        def put(node, code, message):
            k = (node.lineno, node.col_offset, code)
            if k not in findings:
                findings[k] = Finding(
                    mod.path, node.lineno, node.col_offset, code, message
                )

        def check_effect_call(node: ast.Call):
            func = node.func
            if isinstance(func, ast.Name):
                if func.id == "print":
                    put(node, "JP002", "print() executes at trace time only")
                elif func.id in aliases["time_funcs"]:
                    put(node, "JP001",
                        f"wall-clock '{func.id}()' freezes one trace-time "
                        "value into the compiled program")
                elif func.id in aliases["random"] and not func.id[:1].isupper():
                    put(node, "JP003",
                        f"stdlib random '{func.id}()' bypasses the seeded "
                        "stream API")
                return
            dn = dotted_name(func)
            if dn is None:
                return
            head, _, rest = dn.partition(".")
            if head in aliases["time"] and rest:
                put(node, "JP001",
                    f"wall-clock '{dn}()' freezes one trace-time value "
                    "into the compiled program")
            elif head in aliases["numpy"] and rest.startswith("random."):
                put(node, "JP003",
                    f"'{dn}()' draws from host numpy RNG (use the seeded "
                    "stream API / jax.random)")
            elif head in aliases["np_random"] and rest:
                put(node, "JP003",
                    f"'{dn}()' draws from host numpy RNG (use the seeded "
                    "stream API / jax.random)")
            elif head in aliases["random"] and rest:
                put(node, "JP003",
                    f"'{dn}()' draws from stdlib random (use the seeded "
                    "stream API)")

        # JP001/2/3: module-wide on the device path, else traced regions
        if mod.in_package("tpudes", "ops") or mod.in_package("tpudes", "parallel"):
            effect_scopes: list[ast.AST] = [mod.tree]
        else:
            effect_scopes = list(regions)
        for scope in effect_scopes:
            for node in ast.walk(scope):
                if isinstance(node, ast.Call):
                    check_effect_call(node)

        # JP005: host-sync calls, traced regions ONLY — the host-side
        # run_* drivers in tpudes/parallel legitimately block/fetch at
        # run end; the rule targets step/cond bodies, where a sync is a
        # per-iteration device fence (or a trace-time failure)
        for region in regions:
            for node in ast.walk(region):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if isinstance(func, ast.Attribute):
                    if func.attr == "block_until_ready":
                        put(node, "JP005",
                            "'.block_until_ready()' fences the device "
                            "inside traced code — accumulate on-device "
                            "and sync once at run end")
                        continue
                    if func.attr == "item" and not node.args and not node.keywords:
                        put(node, "JP005",
                            "'.item()' forces a device->host transfer "
                            "of a traced value (it cannot even trace "
                            "under jit)")
                        continue
                dn = dotted_name(func)
                if dn is not None:
                    head, _, rest = dn.partition(".")
                    if (head in aliases["numpy"] and rest in _NP_HOST_FUNCS) or (
                        not rest and head in aliases["np_funcs"]
                    ):
                        put(node, "JP005",
                            f"'{dn}()' materializes a traced value on "
                            "the host (use jnp, or fetch after the "
                            "loop)")

        # JP004: mutation, traced regions only.  Module aliases (jnp,
        # np, jax...) are function namespaces, not mutable receivers —
        # jnp.sort(x) is pure
        module_aliases: set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    module_aliases.add((a.asname or a.name).split(".")[0])
        for region in regions:
            bound = _bound_names(region)

            def is_impure_receiver(node: ast.AST) -> bool:
                base = node
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name):
                    if base.id == "self":
                        return True
                    if base.id in module_aliases:
                        return False
                    # a bare free Name is captured or global state; its
                    # attributes/items are host objects either way
                    return base.id not in bound
                return False

            for node in ast.walk(region):
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, (ast.Attribute, ast.Subscript)) and (
                            is_impure_receiver(t)
                        ):
                            put(node, "JP004",
                                "assignment to self/captured/global state "
                                "inside traced code")
                elif isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, (ast.Attribute, ast.Subscript)) and (
                            is_impure_receiver(t)
                        ):
                            put(node, "JP004",
                                "del on self/captured/global state inside "
                                "traced code")
                elif isinstance(node, ast.Global):
                    put(node, "JP004", "global statement inside traced code")
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr in _MUTATORS
                        and is_impure_receiver(f.value)
                        and not isinstance(f.value, ast.Call)
                    ):
                        put(node, "JP004",
                            f"'.{f.attr}()' mutates self/captured/global "
                            "state inside traced code")
        return sorted(findings.values(), key=lambda f: (f.line, f.col, f.code))
