"""trace-arity pass: TracedCallback fire arity vs connected-sink
signature (the ROADMAP open item).

A ``TracedCallback`` is fired as ``self.<field>(a, b, ...)`` inside the
class whose TypeId declared it (``AddTraceSource("Name", ...)`` binds
``field`` via the same name→field rule the runtime uses), and consumed
by sinks connected with ``TraceConnectWithoutContext("Name", sink)`` /
``TraceConnect("Name", context, sink)``.  Nothing checks the two ends
against each other at runtime until the trace actually fires — a sink
whose signature cannot accept the fired arity is a latent ``TypeError``
that only detonates on the (often rare) traced path.

TRC001 fires at a connect site when the sink's positional-parameter
window ``[required, max]`` (defaults widen it; ``*args`` disables the
check) cannot accept ANY observed fire arity for that trace name —
``TraceConnect`` sinks receive the context string prepended, so their
window shifts by one.  Fire arities are collected project-wide per
trace NAME (not per class): two classes sharing a name union their
arities, so the pass under-reports rather than cross-flags.  Sinks it
cannot resolve statically (method references, ``MakeCallback`` results,
bound names) are skipped.
"""

from __future__ import annotations

import ast

from tpudes.core.object import _default_field
from tpudes.analysis.base import Finding, Pass, SourceModule

_CONNECT_METHODS = {"TraceConnectWithoutContext": 0, "TraceConnect": 1}


def _class_trace_fields(cls: ast.ClassDef) -> dict[str, str]:
    """``field -> trace name`` for every AddTraceSource in the class
    body's TypeId declaration chain."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "AddTraceSource"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        field = None
        for kw in node.keywords:
            if kw.arg == "field" and isinstance(kw.value, ast.Constant):
                field = kw.value.value
        if field is None and len(node.args) >= 3 and isinstance(
            node.args[2], ast.Constant
        ):
            field = node.args[2].value
        if field is None:
            field = _default_field(name)
        out[field] = name
    return out


def _sink_window(sink: ast.AST, mod: SourceModule) -> tuple[int, int] | None:
    """``(required, max)`` positional-parameter window for a sink
    expression, or None when it cannot be resolved statically (or
    accepts anything via ``*args``)."""
    fn = None
    if isinstance(sink, ast.Lambda):
        fn = sink
    elif isinstance(sink, ast.Name):
        # module-level def of the same name
        for node in mod.tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == sink.id
            ):
                fn = node
                break
    if fn is None:
        return None
    a = fn.args
    if a.vararg is not None:
        return None  # accepts anything
    params = list(a.posonlyargs) + list(a.args)
    if params and params[0].arg == "self":
        # a def referenced by bare name inside a class body — treat the
        # remaining params as the callable surface
        params = params[1:]
    n_max = len(params)
    n_req = n_max - len(a.defaults)
    return (n_req, n_max)


class TraceArityPass(Pass):
    name = "trace-arity"
    codes = {
        "TRC001": "TracedCallback fire arity vs connected-sink signature mismatch",
    }
    project_wide = True

    def check_project(self, mods: list[SourceModule]) -> list[Finding]:
        # 1. fire arities per trace name, from self.<field>(...) calls
        #    inside the declaring class (tpudes/ modules only)
        fires: dict[str, set[int]] = {}
        for mod in mods:
            if mod.tree is None or not mod.in_package("tpudes"):
                continue
            for cls in ast.walk(mod.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                fields = _class_trace_fields(cls)
                if not fields:
                    continue
                for node in ast.walk(cls):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in fields
                    ):
                        continue
                    if node.keywords or any(
                        isinstance(arg, ast.Starred) for arg in node.args
                    ):
                        continue  # dynamic arity: unknowable statically
                    fires.setdefault(fields[node.func.attr], set()).add(
                        len(node.args)
                    )

        # 2. connect sites anywhere in the analyzed set
        out: list[Finding] = []
        for mod in mods:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CONNECT_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    continue
                name = node.args[0].value
                arities = fires.get(name)
                if not arities:
                    continue  # no observed fire site (e.g. TracedValue)
                shift = _CONNECT_METHODS[node.func.attr]
                sink_idx = 1 + shift  # TraceConnect(name, context, sink)
                if len(node.args) <= sink_idx:
                    continue
                window = _sink_window(node.args[sink_idx], mod)
                if window is None:
                    continue
                n_req, n_max = window
                if any(n_req <= a + shift <= n_max for a in arities):
                    continue
                fired = ", ".join(str(a) for a in sorted(arities))
                ctx_note = " (+1 context arg)" if shift else ""
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, "TRC001",
                    f"sink connected to trace '{name}' accepts "
                    f"{n_req}..{n_max} positional args but the source "
                    f"fires {fired}{ctx_note}",
                ))
        return out
