"""registry-parity pass: dead TypeId registration drift.

Every ``AddAttribute``/``AddTraceSource`` declaration carries an
upstream ns-3 name and binds a Python field; this repo's idiom is to
keep the declared surface in lockstep with what model code actually
reads (``self.<field>``) or scripts configure/connect (the name as a
string).  A declaration nothing references is drift: either the port
of the upstream behavior was dropped, or the registration outlived a
refactor.

REG001 fires when neither the declared name nor its bound field is
referenced anywhere in the analyzed project — as a whole word inside
any string constant (``SetAttribute("DataRate", ...)``, Config paths),
as an attribute access / bare name, or as a keyword argument
(``DataRate="5Mbps"`` construction).  Strings inside the declaration
calls themselves do not count (one class's declaration must not
launder another's).

This is the only project-wide pass: declarations come from ``tpudes/``
modules, references from every analyzed file (tests pin trace names).
"""

from __future__ import annotations

import ast
import re

# the canonical name->field rule — the analyzer must derive the exact
# field the runtime binds, or REG001 misreads live attributes as dead
from tpudes.core.object import _default_field
from tpudes.analysis.base import Finding, Pass, SourceModule

_DECL_METHODS = {"AddAttribute", "AddTraceSource"}
_WORD_SPLIT = re.compile(r"[^A-Za-z0-9_]+")


def _enclosing_typeid_name(call: ast.Call) -> str | None:
    """Walk the fluent chain ``TypeId("x").SetParent(...).Add...``
    down to the TypeId(...) constructor and return its name arg."""
    node: ast.AST = call
    while isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id == "TypeId":
            if node.args and isinstance(node.args[0], ast.Constant):
                return node.args[0].value
            return None
        if isinstance(f, ast.Attribute):
            node = f.value
        else:
            return None
    return None


class RegistryParityPass(Pass):
    name = "registry-parity"
    codes = {
        "REG001": "TypeId attribute/trace source declared but never referenced",
    }
    project_wide = True

    def check_project(self, mods: list[SourceModule]) -> list[Finding]:
        decls = []       # (mod, node, kind, name, field, tid_name)
        decl_calls = []  # the Call nodes, to exclude from reference text
        for mod in mods:
            if mod.tree is None or not mod.in_package("tpudes"):
                continue
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _DECL_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    continue
                name = node.args[0].value
                field = None
                for kw in node.keywords:
                    if kw.arg == "field" and isinstance(kw.value, ast.Constant):
                        field = kw.value.value
                if field is None and node.func.attr == "AddAttribute":
                    if len(node.args) >= 4 and isinstance(
                        node.args[3], ast.Constant
                    ):
                        field = node.args[3].value
                if field is None:
                    field = _default_field(name)
                kind = (
                    "attribute" if node.func.attr == "AddAttribute"
                    else "trace source"
                )
                decls.append(
                    (mod, node, kind, name, field,
                     _enclosing_typeid_name(node))
                )
                decl_calls.append(node)
        if not decls:
            return []

        # reference universe, with declaration-call subtrees excluded
        excluded_consts: set[int] = set()
        for call in decl_calls:
            for sub in ast.walk(call):
                if isinstance(sub, ast.Constant):
                    excluded_consts.add(id(sub))
        words: set[str] = set()
        idents: set[str] = set()
        for mod in mods:
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    if id(node) not in excluded_consts and len(node.value) < 400:
                        words.update(_WORD_SPLIT.split(node.value))
                elif isinstance(node, ast.Attribute):
                    idents.add(node.attr)
                elif isinstance(node, ast.Name):
                    idents.add(node.id)
                elif isinstance(node, ast.keyword) and node.arg:
                    idents.add(node.arg)

        out: list[Finding] = []
        for mod, node, kind, name, field, tid_name in decls:
            if name in words or name in idents:
                continue
            if field in idents or field in words:
                continue
            where = f" on {tid_name}" if tid_name else ""
            out.append(Finding(
                mod.path, node.args[0].lineno, node.args[0].col_offset,
                "REG001",
                f"{kind} '{name}'{where} (field '{field}') is declared "
                "but never set/get/connected/read anywhere",
            ))
        return out
