"""CLI: ``python -m tpudes.analysis [paths...]``.

Exit 0 when every finding is covered by the baseline; nonzero when new
findings exist (the tier-1 gate in tests/test_analysis_gate.py).  With
explicit paths the same rules run over just those files/dirs.

``--jaxpr`` additionally traces every registered device-engine
manifest and runs the JXL contract passes over the jaxprs (CPU-safe —
``jax.make_jaxpr`` only, no compile; run it under
``JAX_PLATFORMS=cpu`` in CI).  ``--jaxpr --cost`` swaps lint findings
for the scale-complexity report: per-axis growth exponents and
1e5/1e6-node byte projections.  ``--format sarif`` emits SARIF 2.1.0
for GitHub code scanning.  AST findings are cached per file content
hash, jaxpr findings per pass-family version + tpudes module set
(``tools/.analysis_cache.json``); ``--no-cache`` disables.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from tpudes.analysis.engine import (
    ALL_PASSES,
    DEFAULT_BASELINE,
    DEFAULT_ROOTS,
    analyze_paths,
    load_baseline,
    new_findings,
    write_baseline,
)

DEFAULT_CACHE = "tools/.analysis_cache.json"


def _csv(value: str) -> list[str]:
    return [v.strip() for v in value.split(",") if v.strip()]


def _cost_report(args) -> int:
    """``--jaxpr --cost``: the scale-complexity report.

    Always exits 0 — the report informs; the ratchet on over-budget
    growth is the JXL007 finding plus the baseline, not this mode.
    """
    from tpudes.analysis.jaxpr.cost import format_bytes, scale_report

    t0 = time.perf_counter()
    report = scale_report()
    report["elapsed_s"] = round(time.perf_counter() - t0, 3)
    if args.cost_out:
        out = Path(args.cost_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=1))
    if args.fmt != "text":
        print(json.dumps(report, indent=1))
        return 0
    for r in report["entries"]:
        flag = ""
        if r["dead"]:
            flag = "  [DEAD AXIS]"
        elif r["over_budget"]:
            flag = "  [OVER BUDGET]"
        print(
            f"{r['engine']}/{r['entry']}  axis={r['axis']}  "
            f"mem_exp={r['mem_exponent']:.2f} (budget "
            f"{r['mem_budget']:g})  peak={r['peak_exponent']:.2f}  "
            f"widest={r['widest_exponent']:.2f}  "
            f"flops={r['flop_exponent']:.2f}{flag}"
        )
        proj = r.get("projected")
        if proj:
            parts = ", ".join(
                f"{k.replace('_nodes', ' nodes')}: {v['human']}"
                for k, v in sorted(proj.items())
            )
            print(f"    projected peak-live bytes  {parts}")
    if report["worklist"]:
        print(
            "cost: over-budget worklist (ROADMAP item 2 — sparse/CSR "
            "rewrite candidates): " + ", ".join(report["worklist"])
        )
    else:
        print("cost: no axis exceeds its declared memory budget")
    print(f"cost: {len(report['entries'])} axis fit(s) in "
          f"{report['elapsed_s']:.1f}s")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudes.analysis",
        description="tpudes simulator-aware static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to analyze (default: {DEFAULT_ROOTS})")
    ap.add_argument("--select", type=_csv, default=None, metavar="CODES",
                    help="only rules with these code prefixes (e.g. RNG,DET001)")
    ap.add_argument("--ignore", type=_csv, default=None, metavar="CODES",
                    help="drop rules with these code prefixes")
    ap.add_argument("--jaxpr", action="store_true",
                    help="also trace every registered engine manifest and "
                         "run the JXL001-JXL008 jaxpr contract passes")
    ap.add_argument("--cost", action="store_true",
                    help="emit the scale-complexity cost report instead of "
                         "lint findings: per-axis growth exponents and "
                         "1e5/1e6-node byte projections (requires --jaxpr)")
    ap.add_argument("--cost-out", default=None, metavar="PATH",
                    help="with --cost, also write the JSON report to PATH "
                         "(for CI artifact upload)")
    ap.add_argument("--format", dest="fmt", default="text",
                    choices=("text", "json", "sarif"),
                    help="output format (sarif = GitHub code scanning)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="alias for --format json")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the per-file AST findings cache")
    ap.add_argument("--cache", default=None, metavar="PATH",
                    help=f"cache file (default: {DEFAULT_CACHE} for "
                         "default-root runs)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default: {DEFAULT_BASELINE} when "
                         "analyzing the default roots)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring any baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "(combine with --jaxpr to cover the JXL rules)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule code and exit")
    args = ap.parse_args(argv)
    if args.as_json and args.fmt == "text":
        args.fmt = "json"  # alias only; an explicit --format wins

    if args.list_rules:
        from tpudes.analysis.engine import _ensure_builtins

        _ensure_builtins()
        passes = list(ALL_PASSES)
        # the jaxpr family is listed unconditionally (discovery must
        # not require the jax import that --jaxpr execution pays)
        from tpudes.analysis.jaxpr.passes import JaxprContractPass

        passes.append(JaxprContractPass())
        for p in passes:
            for code in sorted(p.codes):
                print(f"{code}  [{p.name}]  {p.codes[code]}")
        return 0

    if args.cost or args.cost_out:
        if not args.jaxpr:
            print("analysis: --cost requires --jaxpr (the report is "
                  "built by re-tracing the engine manifests)",
                  file=sys.stderr)
            return 2
        return _cost_report(args)

    root = Path.cwd()
    explicit = bool(args.paths)
    if explicit:
        paths = [Path(p) for p in args.paths]
        missing = [
            p for p in paths
            if not (p.is_dir() or (p.suffix == ".py" and p.is_file()))
        ]
        if missing:
            for p in missing:
                print(f"analysis: no such file or directory: {p}",
                      file=sys.stderr)
            return 2
    else:
        paths = [root / r for r in DEFAULT_ROOTS if (root / r).is_dir()]
        if not paths:
            print(
                f"analysis: none of the default roots {DEFAULT_ROOTS} "
                f"exist under {root} — run from the repo root or pass "
                "explicit paths", file=sys.stderr,
            )
            return 2

    # the cache is keyed by root-relative display paths, so it only
    # arms for default-root runs (explicit scans of arbitrary paths —
    # the fixture-test shape — must not grow or read it)
    cache = None
    if not args.no_cache and not explicit:
        from tpudes.analysis.cache import AnalysisCache

        cache = AnalysisCache(
            args.cache if args.cache is not None
            else root / DEFAULT_CACHE
        )
    elif args.cache is not None and explicit:
        print(
            "analysis: --cache is ignored for explicit-path scans "
            "(the cache is keyed by root-relative default-root paths)",
            file=sys.stderr,
        )

    t0 = time.perf_counter()
    findings = analyze_paths(paths, root=root,
                             select=args.select, ignore=args.ignore,
                             project_passes=not explicit,
                             jaxpr=args.jaxpr, cache=cache)
    elapsed = time.perf_counter() - t0
    if cache is not None:
        cache.save()

    # the baseline keys are root-relative, so they apply to subtree
    # scans launched from the same root too
    baseline_path = (
        args.baseline if args.baseline is not None
        else root / DEFAULT_BASELINE
    )
    baseline = {}
    if not args.no_baseline:
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        if explicit or args.select or args.ignore:
            print(
                "analysis: refusing --write-baseline from a narrowed run "
                "(explicit paths / --select / --ignore would clobber the "
                "full-repo ratchet)", file=sys.stderr,
            )
            return 2
        if not args.jaxpr and any(
            k.split(":", 2)[1].startswith("JXL")
            for k in load_baseline(baseline_path)
            if k.count(":") >= 2
        ):
            print(
                "analysis: the baseline holds JXL trace findings this "
                "run did not compute — rerun with --jaxpr "
                "--write-baseline so they are preserved, not silently "
                "dropped", file=sys.stderr,
            )
            return 2
        write_baseline(baseline_path, findings)
        print(
            f"analysis: baselined {len(findings)} finding(s) -> "
            f"{baseline_path}"
        )
        return 0

    fresh = new_findings(findings, baseline)
    if args.fmt == "json":
        payload = {
            "findings": [f.to_json() for f in fresh],
            "baselined": len(findings) - len(fresh),
            "elapsed_s": elapsed,
        }
        if cache is not None:
            payload["cache"] = {
                "hits": cache.hits, "misses": cache.misses,
            }
        print(json.dumps(payload, indent=1))
    elif args.fmt == "sarif":
        from tpudes.analysis.sarif import all_rule_descriptions, to_sarif

        print(json.dumps(
            to_sarif(fresh, all_rule_descriptions(jaxpr=True)), indent=1
        ))
    else:
        for f in fresh:
            print(f.render())
        suffix = (
            f" ({len(findings) - len(fresh)} baselined)" if baseline else ""
        )
        print(f"analysis: {len(fresh)} new finding(s){suffix}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
