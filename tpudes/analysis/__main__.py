"""CLI: ``python -m tpudes.analysis [paths...]``.

Exit 0 when every finding is covered by the baseline; nonzero when new
findings exist (the tier-1 gate in tests/test_analysis_gate.py).  With
explicit paths the same rules run over just those files/dirs.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tpudes.analysis.engine import (
    ALL_PASSES,
    DEFAULT_BASELINE,
    DEFAULT_ROOTS,
    analyze_paths,
    load_baseline,
    new_findings,
    write_baseline,
)


def _csv(value: str) -> list[str]:
    return [v.strip() for v in value.split(",") if v.strip()]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpudes.analysis",
        description="tpudes simulator-aware static analysis",
    )
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to analyze (default: {DEFAULT_ROOTS})")
    ap.add_argument("--select", type=_csv, default=None, metavar="CODES",
                    help="only rules with these code prefixes (e.g. RNG,DET001)")
    ap.add_argument("--ignore", type=_csv, default=None, metavar="CODES",
                    help="drop rules with these code prefixes")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help=f"baseline file (default: {DEFAULT_BASELINE} when "
                         "analyzing the default roots)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring any baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every registered rule code and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        from tpudes.analysis.engine import _ensure_builtins

        _ensure_builtins()
        for p in ALL_PASSES:
            for code in sorted(p.codes):
                print(f"{code}  [{p.name}]  {p.codes[code]}")
        return 0

    root = Path.cwd()
    explicit = bool(args.paths)
    if explicit:
        paths = [Path(p) for p in args.paths]
        missing = [
            p for p in paths
            if not (p.is_dir() or (p.suffix == ".py" and p.is_file()))
        ]
        if missing:
            for p in missing:
                print(f"analysis: no such file or directory: {p}",
                      file=sys.stderr)
            return 2
    else:
        paths = [root / r for r in DEFAULT_ROOTS if (root / r).is_dir()]
        if not paths:
            print(
                f"analysis: none of the default roots {DEFAULT_ROOTS} "
                f"exist under {root} — run from the repo root or pass "
                "explicit paths", file=sys.stderr,
            )
            return 2

    findings = analyze_paths(paths, root=root,
                             select=args.select, ignore=args.ignore,
                             project_passes=not explicit)

    # the baseline keys are root-relative, so they apply to subtree
    # scans launched from the same root too
    baseline_path = (
        args.baseline if args.baseline is not None
        else root / DEFAULT_BASELINE
    )
    baseline = {}
    if not args.no_baseline:
        baseline = load_baseline(baseline_path)

    if args.write_baseline:
        if explicit or args.select or args.ignore:
            print(
                "analysis: refusing --write-baseline from a narrowed run "
                "(explicit paths / --select / --ignore would clobber the "
                "full-repo ratchet)", file=sys.stderr,
            )
            return 2
        write_baseline(baseline_path, findings)
        print(
            f"analysis: baselined {len(findings)} finding(s) -> "
            f"{baseline_path}"
        )
        return 0

    fresh = new_findings(findings, baseline)
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in fresh],
            "baselined": len(findings) - len(fresh),
        }, indent=1))
    else:
        for f in fresh:
            print(f.render())
        suffix = (
            f" ({len(findings) - len(fresh)} baselined)" if baseline else ""
        )
        print(f"analysis: {len(fresh)} new finding(s){suffix}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
