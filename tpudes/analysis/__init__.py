"""tpudes.analysis — simulator-aware static analysis.

A multi-pass AST analyzer for the defect classes a generic linter
cannot see: trace-impurity inside jit-lifted kernels, jax.random key
reuse, event ordering fed from unordered containers, leaked scheduled
events, and TypeId registration drift.  Run as::

    python -m tpudes.analysis            # gate against the baseline
    python -m tpudes.analysis --list-rules

The ``Pass`` plugin API, inline ``# tpudes: ignore[RULE]``
suppressions, ``--select``/``--ignore``, JSON output and the
``tools/analysis_baseline.json`` ratchet are documented in README.md
("Static analysis").
"""

from tpudes.analysis.base import Finding, Pass, SourceModule
from tpudes.analysis.engine import (
    ALL_PASSES,
    analyze_paths,
    analyze_source,
    load_baseline,
    new_findings,
    register_pass,
)

__all__ = [
    "ALL_PASSES",
    "Finding",
    "Pass",
    "SourceModule",
    "analyze_paths",
    "analyze_source",
    "load_baseline",
    "new_findings",
    "register_pass",
]
