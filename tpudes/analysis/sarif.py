"""SARIF 2.1.0 output (``--format sarif``).

The minimal profile GitHub code scanning ingests: one run, one tool
driver carrying the rule index, one result per (new) finding with a
physical location.  Dependency-free by design, like the rest of the
analyzer.
"""

from __future__ import annotations

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(findings, rules: dict[str, str]) -> dict:
    """``findings`` are the post-baseline (new) findings; ``rules``
    maps every registered rule code to its one-line description (the
    driver advertises the full rule set, not just the codes that
    fired, so code-scanning UIs can render suppress/track state)."""
    rule_ids = sorted(rules)
    index = {code: i for i, code in enumerate(rule_ids)}
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "tpudes-analysis",
                        "informationUri":
                            "https://example.invalid/tpudes#static-analysis",
                        "rules": [
                            {
                                "id": code,
                                "shortDescription": {"text": rules[code]},
                            }
                            for code in rule_ids
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": f.code,
                        "ruleIndex": index.get(f.code, -1),
                        "level": "error",
                        "message": {"text": f.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {
                                        "uri": f.path,
                                        "uriBaseId": "SRCROOT",
                                    },
                                    "region": {
                                        "startLine": max(1, int(f.line)),
                                        "startColumn": max(
                                            1, int(f.col) + 1
                                        ),
                                    },
                                }
                            }
                        ],
                    }
                    for f in findings
                ],
            }
        ],
    }


def all_rule_descriptions(jaxpr: bool = False) -> dict[str, str]:
    """Every registered rule code → description (optionally including
    the jaxpr family)."""
    from tpudes.analysis.engine import ALL_PASSES, _ensure_builtins

    _ensure_builtins()
    passes = list(ALL_PASSES)
    if jaxpr:
        from tpudes.analysis.jaxpr import JAXPR_PASSES

        passes.extend(cls() for cls in JAXPR_PASSES)
    out: dict[str, str] = {}
    for p in passes:
        out.update(p.codes)
    return out
