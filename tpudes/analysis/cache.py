"""Per-file content-hash cache for the AST passes.

The tier-1 gate reruns ``python -m tpudes.analysis`` on every test
round; between rounds almost no file changes.  The cache stores each
file's post-suppression findings keyed by the sha256 of its CONTENT,
plus one whole-set entry for the project-wide passes — a warm run with
no edits parses nothing and runs no passes at all.

Safety model: a stale result can only be served if (a) the file bytes
are identical (content hash), AND (b) the analyzer itself is identical
(``rules_fingerprint()`` — a digest of every ``tpudes/analysis``
source file, so editing any pass, or this module, invalidates
everything).  Inline suppressions live in the file content, so they
are covered by (a).  Findings are stored UNFILTERED by
``--select/--ignore`` (selection applies at read time); runs narrowed
by selection therefore read the cache but never write it.

The jaxpr pass family gets its own section with a STRICTER key: its
findings depend on the engine modules' runtime tracing, so the jaxpr
sha folds together (a) the jaxpr pass-family sources
(:func:`jaxpr_rules_fingerprint` — an edited JXL rule must never
serve a stale warm result), (b) the content hash of every scanned
``tpudes/`` module (the manifests and the kernels they trace live
there), and (c) the installed jax version (the tracer itself).  A
warm ``--jaxpr`` run with no edits serves findings without importing
jax at all — that is what keeps the gate under a second between test
rounds.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from tpudes.analysis.base import Finding

CACHE_VERSION = 1

_rules_fp: str | None = None
_jaxpr_rules_fp: str | None = None


def rules_fingerprint() -> str:
    """Digest of every analyzer source file (memoized per process)."""
    global _rules_fp
    if _rules_fp is None:
        root = Path(__file__).resolve().parent
        h = hashlib.sha256()
        for f in sorted(root.rglob("*.py")):
            h.update(f.relative_to(root).as_posix().encode())
            h.update(f.read_bytes())
        _rules_fp = h.hexdigest()
    return _rules_fp


def jaxpr_rules_fingerprint() -> str:
    """Digest of the jaxpr pass family specifically (memoized).

    ``rules_fingerprint()`` already covers these files as part of the
    whole-store key; this narrower digest is folded into the jaxpr
    section's OWN key so the pass-family version is pinned in the cache
    entry itself, not just in the store header — a defense in depth the
    invalidation regression test exercises directly.
    """
    global _jaxpr_rules_fp
    if _jaxpr_rules_fp is None:
        root = Path(__file__).resolve().parent / "jaxpr"
        h = hashlib.sha256()
        for f in sorted(root.rglob("*.py")):
            h.update(f.relative_to(root).as_posix().encode())
            h.update(f.read_bytes())
        _jaxpr_rules_fp = h.hexdigest()
    return _jaxpr_rules_fp


def _jax_version() -> str:
    # importlib.metadata, not ``import jax``: reading the version must
    # stay cheap on warm runs where jax is otherwise never loaded.
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:
        return "unknown"


def _to_dicts(findings: list[Finding]) -> list[dict]:
    return [f.to_json() for f in findings]


def _from_dicts(raw: list[dict]) -> list[Finding]:
    return [
        Finding(d["path"], d["line"], d["col"], d["code"], d["message"])
        for d in raw
    ]


class AnalysisCache:
    """Load/lookup/store; ``save()`` writes only when something
    changed.  A version or fingerprint mismatch resets the store."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.hits = 0
        self.misses = 0
        self._dirty = False
        data: dict = {}
        try:
            data = json.loads(self.path.read_text())
        except (OSError, ValueError):
            data = {}
        if (
            data.get("version") != CACHE_VERSION
            or data.get("rules") != rules_fingerprint()
        ):
            data = {}
        self._files: dict = data.get("files", {})
        self._project: dict = data.get("project", {})
        self._jaxpr: dict = data.get("jaxpr", {})

    # --- per-file module-pass findings ---------------------------------

    def get_file(self, path: str, sha: str) -> list[Finding] | None:
        entry = self._files.get(path)
        if entry is not None and entry.get("sha") == sha:
            self.hits += 1
            return _from_dicts(entry["findings"])
        self.misses += 1
        return None

    def put_file(self, path: str, sha: str, findings: list[Finding]):
        self._files[path] = {"sha": sha, "findings": _to_dicts(findings)}
        self._dirty = True

    # --- whole-set project-pass findings --------------------------------

    @staticmethod
    def project_sha(mods) -> str:
        h = hashlib.sha256()
        for m in sorted(mods, key=lambda m: m.path):
            h.update(m.path.encode())
            h.update(m.sha.encode())
        return h.hexdigest()

    def get_project(self, sha: str) -> list[Finding] | None:
        if self._project.get("sha") == sha:
            return _from_dicts(self._project["findings"])
        return None

    def put_project(self, sha: str, findings: list[Finding]):
        self._project = {"sha": sha, "findings": _to_dicts(findings)}
        self._dirty = True

    # --- whole-set jaxpr-pass findings ----------------------------------

    @staticmethod
    def jaxpr_sha(mods) -> str:
        """Key for the jaxpr findings section.

        Folds the jaxpr pass-family version, the content hash of every
        scanned ``tpudes/`` module (manifest entries trace kernels that
        live anywhere under the package), and the jax version.  Tests,
        examples and tools cannot change what tracing produces, so they
        are excluded — editing a test must not cost a 30 s retrace.
        """
        h = hashlib.sha256()
        h.update(jaxpr_rules_fingerprint().encode())
        h.update(_jax_version().encode())
        for m in sorted(mods, key=lambda m: m.path):
            if m.path.startswith("tpudes/"):
                h.update(m.path.encode())
                h.update(m.sha.encode())
        return h.hexdigest()

    def get_jaxpr(self, sha: str) -> list[Finding] | None:
        if self._jaxpr.get("sha") == sha:
            return _from_dicts(self._jaxpr["findings"])
        return None

    def put_jaxpr(self, sha: str, findings: list[Finding]):
        self._jaxpr = {"sha": sha, "findings": _to_dicts(findings)}
        self._dirty = True

    def prune(self, keep_paths) -> None:
        """Drop per-file entries for paths no longer in the scanned
        set (renames/deletes) so the store cannot grow monotonically."""
        keep = set(keep_paths)
        dead = [p for p in self._files if p not in keep]
        for p in dead:
            # not a sim-time buffer: this IS the expiry sweep (run on
            # every un-narrowed analysis), so no scheduled event applies
            del self._files[p]  # tpudes: ignore[EVT003]
        if dead:
            self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        payload = {
            "version": CACHE_VERSION,
            "rules": rules_fingerprint(),
            "files": self._files,
            "project": self._project,
            "jaxpr": self._jaxpr,
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self.path.write_text(json.dumps(payload))
        except OSError:
            pass  # an unwritable cache degrades to cold runs, never fails
        self._dirty = False
