"""Device-resident traffic programs — workload models as traced operands.

Every engine used to drive itself with a degenerate source: CBR echo
arrivals (BSS), an always-full RLC-SM buffer (LTE), an infinite bulk
backlog (dumbbell), a constant fluid rate (AS flows).  Nothing bursts,
thinks, or arrives like a population of real users.  This module makes
the workload itself a first-class device operand, exactly the way
``tpudes.ops.mobility`` made motion one: a :class:`TrafficProgram`
describes one entity batch's arrival process, every stochastic choice
is materialized EAGERLY into ``fold_in``-keyed operand tables (the
``walk_segment_velocities`` pattern), and the engines dispatch on a
TRACED model id (:data:`TRAFFIC_MODEL_IDS`) — so the whole model
family rides one compiled executable and a model/param flip is new
operand values, never a recompile.

Model family (the upstream ``src/applications`` generator surface):

- ``cbr`` — deterministic inter-arrival ``interval_us`` (UdpClient /
  UdpEchoClient semantics).  The neutral member: engines are pinned
  bit-equal between ``traffic=None`` and the matching cbr program.
- ``mmpp`` — Markov-modulated Poisson arrivals: a 2-state modulating
  chain sampled on a fixed epoch grid (the chain realization is an
  eager ``fold_in``-keyed table — pure in ``tr_seed``), per-state rate
  multipliers, exponential gaps at the epoch's modulated rate (the
  frozen-rate approximation: the rate is held over one gap draw).
- ``onoff`` — Poisson-Pareto ON-OFF bursts (OnOffApplication / PPBP
  shape): bounded-Pareto ON durations, exponential OFF durations,
  deterministic peak-rate arrivals during ON.  The cycle realization
  is an eager per-(entity, cycle) table, so the burst boundaries are
  closed-form in time — chunking/striding cannot shift them.
- ``trace`` — compressed empirical-trace replay: per-entity
  ``(time, bytes)`` tables ride as runtime operands; replay is EXACT
  (arrival times are table lookups, no draws).

A **diurnal rate envelope** ``rate(t) *= 1 + amp·sin(2π(t/period −
phase))`` applies to the generative models by being folded into the
materialized epoch/cycle rate tables — envelope flips are operand
flips, compile-free.  Heavy-tailed packet/flow sizes are bounded-
Pareto draws (:func:`bounded_pareto_icdf`); trace replay carries exact
per-arrival bytes.

Only SHAPES and table capacities (:meth:`TrafficProgram.shape_key`)
may enter an engine cache key; :meth:`TrafficProgram.param_key` is the
full-value identity serving-layer coalesce keys use.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

__all__ = [
    "TRAFFIC_MODEL_IDS",
    "TrafficProgram",
    "bounded_pareto_icdf",
    "bounded_pareto_mean",
    "traffic_tables",
    "unify_shapes",
]

#: traffic model short name → traced dispatch id (the scheduler-id /
#: mobility-id pattern: the id is a runtime operand selecting the
#: arrival branch, so the whole family rides one compiled executable)
TRAFFIC_MODEL_IDS = {
    "cbr": 0,
    "mmpp": 1,
    "onoff": 2,
    "trace": 3,
}

#: root key of every traffic table/draw stream (the _MOB_ROOT_SEED
#: pattern): table draws are fold_in(fold_in(PRNGKey(root), tr_seed), …)
_TRAFFIC_ROOT_SEED = 0x7AF1C0

#: "no more arrivals" sentinel on the µs clock — comfortably past any
#: representable horizon, comfortably below i32 overflow when an engine
#: adds a gap to it
GAP_INF = np.int32(2**30)


def bounded_pareto_icdf(u, alpha: float, lo: float, hi: float):
    """Inverse CDF of the bounded Pareto on ``[lo, hi]`` with shape
    ``alpha`` — works on numpy or jax arrays (pure arithmetic).
    ``alpha <= 0`` or ``hi <= lo`` degenerates to the constant ``lo``
    (how fixed-size workloads ride the same branch)."""
    if alpha <= 0.0 or hi <= lo:
        return u * 0.0 + lo
    r = (lo / hi) ** alpha
    return lo / (1.0 - u * (1.0 - r)) ** (1.0 / alpha)


def bounded_pareto_mean(alpha: float, lo: float, hi: float) -> float:
    """Closed-form mean of the bounded Pareto (``alpha != 1``); the
    degenerate cases mirror :func:`bounded_pareto_icdf`."""
    if alpha <= 0.0 or hi <= lo:
        return float(lo)
    if abs(alpha - 1.0) < 1e-9:
        return float(lo * hi / (hi - lo) * math.log(hi / lo))
    r = (lo / hi) ** alpha
    return float(
        (alpha * lo / (alpha - 1.0))
        * (1.0 - (lo / hi) ** (alpha - 1.0))
        / (1.0 - r)
    )


@dataclass(frozen=True)
class TrafficProgram:
    """One entity batch's arrival workload, ready to ride any device
    engine.  All array fields are RUNTIME operands of the compiled
    program; :meth:`shape_key` is the only part that belongs in an
    engine cache key.  Build via the factory classmethods."""

    model: str                    # key of TRAFFIC_MODEL_IDS
    start_us: np.ndarray          # (N,) i32 workload start per entity
    interval_us: np.ndarray       # (N,) i32 cbr inter-arrival
    rate_pps: np.ndarray          # (N,) f32 nominal mean arrival rate
    mmpp_mult: np.ndarray         # (2,) f32 state rate multipliers
    mmpp_p: np.ndarray            # (2,) f32 per-epoch switch probs
    peak_pps: np.ndarray          # (N,) f32 ON-period arrival rate
    on_pareto: np.ndarray         # (3,) f32 (alpha, on_min_s, on_max_s)
    off_mean_s: float = 1.0       # exponential OFF mean (onoff)
    arr_t: np.ndarray = None      # (N, K) i32 µs trace times, sorted
    arr_b: np.ndarray = None      # (N, K) i32 trace bytes per arrival
    size_pareto: np.ndarray = None  # (3,) f32 (alpha, min_B, max_B)
    env: np.ndarray = None        # (3,) f32 (amp, period_s, phase)
    epoch_us: int = 100_000       # mmpp epoch length (trace-time const)
    n_epoch: int = 1              # mmpp epoch-grid length (SHAPE)
    n_cycle: int = 1              # onoff cycle-table length (SHAPE)
    tr_seed: int = 0              # table stream seed (runtime operand)
    #: (N,) i32 per-entity model override (None = every entity runs
    #: ``model``).  The dispatch select is elementwise, so MIXED
    #: batches ride one executable — e.g. a BSS program keeps the AP's
    #: beacon process cbr while the STAs burst (the mobility
    #: zero-speed-band precedent).  A runtime operand like the id.
    model_id: np.ndarray = None

    @property
    def n(self) -> int:
        return int(self.start_us.shape[0])

    def shape_key(self) -> tuple:
        """The trace-time identity: everything that changes the
        compiled program's shape.  Model id and every array are
        deliberately ABSENT — they are traced operands, so a sweep
        across the model family reuses one executable."""
        return (
            self.n, int(self.n_epoch), int(self.n_cycle),
            int(self.arr_t.shape[1]), int(self.epoch_us),
        )

    def param_key(self) -> tuple:
        """Hashable identity of the FULL parameter set (serving-layer
        coalesce keys: studies with different workloads must not
        coalesce even though the params are traced)."""
        return (
            self.model, self.start_us.tobytes(),
            self.interval_us.tobytes(), self.rate_pps.tobytes(),
            self.mmpp_mult.tobytes(), self.mmpp_p.tobytes(),
            self.peak_pps.tobytes(), self.on_pareto.tobytes(),
            float(self.off_mean_s), self.arr_t.tobytes(),
            self.arr_b.tobytes(), self.size_pareto.tobytes(),
            self.env.tobytes(), int(self.epoch_us), int(self.n_epoch),
            int(self.n_cycle), int(self.tr_seed),
            None if self.model_id is None else self.model_id.tobytes(),
        )

    def model_ids(self) -> np.ndarray:
        """(N,) i32 effective per-entity model ids."""
        if self.model_id is not None:
            return np.asarray(self.model_id, np.int32)
        return np.full(
            (self.n,), TRAFFIC_MODEL_IDS[self.model], np.int32
        )

    def with_cbr_rows(self, mask, interval_us, start_us=None):
        """A copy whose ``mask``-selected entities run deterministic
        cbr at ``interval_us`` instead of ``model`` — how an engine
        keeps one entity's control-plane cadence (the AP beacon) exact
        while the rest of the batch bursts."""
        import dataclasses

        mask = np.asarray(mask, bool)
        ids = self.model_ids().copy()
        ids[mask] = TRAFFIC_MODEL_IDS["cbr"]
        iv = self.interval_us.copy()
        iv[mask] = np.minimum(
            np.asarray(interval_us, np.int64), GAP_INF
        ).astype(np.int32)
        start = self.start_us.copy()
        if start_us is not None:
            start[mask] = np.asarray(start_us, np.int32)
        return dataclasses.replace(
            self, model_id=ids, interval_us=iv, start_us=start
        )

    def operands(self) -> dict:
        """The traced-operand dict the device kernels consume — all the
        stochastic table realizations materialized eagerly (jax PRNG
        draws are spec'd identical eager vs traced), memoized on the
        immutable program so repeat launches skip the re-materialize +
        H2D; dropped on pickling (procmesh study specs cross process
        boundaries)."""
        import jax.numpy as jnp

        cached = self.__dict__.get("_operands_cache")
        if cached is None:
            t = traffic_tables(self)
            cached = dict(
                tr_id=jnp.asarray(self.model_ids(), jnp.int32),
                tr_start=jnp.asarray(self.start_us, jnp.int32),
                tr_interval=jnp.asarray(self.interval_us, jnp.int32),
                tr_rate=jnp.asarray(self.rate_pps, jnp.float32),
                tr_epoch_rate=jnp.asarray(t["epoch_rate"], jnp.float32),
                tr_epoch_cum=jnp.asarray(t["epoch_cum"], jnp.float32),
                tr_on_start=jnp.asarray(t["on_start"], jnp.int32),
                tr_on_len=jnp.asarray(t["on_len"], jnp.int32),
                tr_cum_pk=jnp.asarray(t["cum_pk"], jnp.float32),
                tr_peak=jnp.asarray(t["peak"], jnp.float32),
                tr_arr_t=jnp.asarray(self.arr_t, jnp.int32),
                tr_arr_b=jnp.asarray(self.arr_b, jnp.int32),
                tr_size=jnp.asarray(self.size_pareto, jnp.float32),
            )
            object.__setattr__(self, "_operands_cache", cached)
        return dict(cached)

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_operands_cache", None)  # device arrays stay local
        state.pop("_tables_cache", None)
        return state

    # --- factories --------------------------------------------------------

    @classmethod
    def _fill(cls, model: str, n: int, **kw) -> "TrafficProgram":
        defaults = dict(
            start_us=np.zeros((n,), np.int32),
            interval_us=np.full((n,), GAP_INF, np.int32),
            rate_pps=np.zeros((n,), np.float32),
            mmpp_mult=np.ones((2,), np.float32),
            mmpp_p=np.zeros((2,), np.float32),
            peak_pps=np.zeros((n,), np.float32),
            on_pareto=np.asarray([0.0, 1.0, 1.0], np.float32),
            arr_t=np.full((n, 2), GAP_INF, np.int32),
            arr_b=np.zeros((n, 2), np.int32),
            size_pareto=np.asarray([0.0, 512.0, 512.0], np.float32),
            env=np.zeros((3,), np.float32),
        )
        defaults.update(kw)
        return cls(model=model, **defaults)

    @classmethod
    def cbr(cls, start_us, interval_us) -> "TrafficProgram":
        """Deterministic arrivals: entity e fires at ``start + k·interval``
        — arithmetically identical to the engines' legacy CBR advance,
        which is what pins the ``traffic_off`` exact oracle pair."""
        start = np.asarray(start_us, np.int32)
        iv = np.asarray(
            np.broadcast_to(np.asarray(interval_us), start.shape), np.int64
        )
        rate = np.where(
            iv >= GAP_INF, 0.0, 1e6 / np.maximum(iv, 1)
        ).astype(np.float32)
        return cls._fill(
            "cbr", start.shape[0], start_us=start,
            interval_us=np.minimum(iv, GAP_INF).astype(np.int32),
            rate_pps=rate,
        )

    @classmethod
    def mmpp(
        cls, n: int, rate_pps, *, horizon_us: int,
        mult=(0.25, 3.0), switch_p=(0.3, 0.3), epoch_s: float = 0.1,
        start_us=0, envelope=None, tr_seed: int = 0,
    ) -> "TrafficProgram":
        """2-state Markov-modulated Poisson arrivals.  ``mult`` are the
        per-state rate multipliers, ``switch_p`` the per-epoch switch
        probabilities (the discrete sampling of the modulating CTMC on
        the ``epoch_s`` grid); ``horizon_us`` sizes the epoch grid.
        The multipliers are normalized by the chain's STATIONARY mean,
        so ``rate_pps`` is the long-run mean arrival rate (what the
        fluid view and the fuzz load budgets reason about) and
        ``mult`` only shapes the burstiness ratio."""
        epoch_us = max(1, int(round(epoch_s * 1e6)))
        n_epoch = int(horizon_us) // epoch_us + 1
        mult = np.asarray(mult, np.float64).reshape(2)
        p01, p10 = (float(v) for v in np.reshape(switch_p, 2))
        tot = max(p01 + p10, 1e-9)
        stationary_mean = (p10 * mult[0] + p01 * mult[1]) / tot
        mult = mult / max(stationary_mean, 1e-9)
        return cls._fill(
            "mmpp", n,
            start_us=np.broadcast_to(
                np.asarray(start_us, np.int32), (n,)
            ).copy(),
            rate_pps=np.broadcast_to(
                np.asarray(rate_pps, np.float32), (n,)
            ).copy(),
            mmpp_mult=mult.astype(np.float32),
            mmpp_p=np.asarray(switch_p, np.float32).reshape(2),
            env=_env_params(envelope),
            epoch_us=epoch_us, n_epoch=n_epoch, tr_seed=int(tr_seed),
        )

    @classmethod
    def onoff(
        cls, n: int, peak_pps, *, horizon_us: int,
        on=(1.5, 0.2, 5.0), off_mean_s: float = 0.5,
        start_us=0, envelope=None, tr_seed: int = 0,
    ) -> "TrafficProgram":
        """Poisson-Pareto ON-OFF bursts: ON durations bounded-Pareto
        ``on=(alpha, min_s, max_s)``, OFF durations exponential with
        mean ``off_mean_s``, deterministic ``peak_pps`` arrivals while
        ON.  The cycle realization is one eager table per entity, so
        ``horizon_us`` sizes the cycle capacity from the MINIMUM mean
        cycle length (never run out of bursts before the horizon)."""
        on = np.asarray(on, np.float32).reshape(3)
        mean_cycle = bounded_pareto_mean(
            float(on[0]), float(on[1]), float(on[2])
        ) + float(off_mean_s)
        n_cycle = max(2, int(2.0 * horizon_us / 1e6 / max(mean_cycle, 1e-6)) + 4)
        peak = np.broadcast_to(np.asarray(peak_pps, np.float32), (n,))
        duty = bounded_pareto_mean(
            float(on[0]), float(on[1]), float(on[2])
        ) / max(mean_cycle, 1e-9)
        return cls._fill(
            "onoff", n,
            start_us=np.broadcast_to(
                np.asarray(start_us, np.int32), (n,)
            ).copy(),
            rate_pps=(peak * np.float32(duty)).copy(),
            peak_pps=peak.copy(),
            on_pareto=on,
            off_mean_s=float(off_mean_s),
            env=_env_params(envelope),
            n_cycle=n_cycle, tr_seed=int(tr_seed),
        )

    @classmethod
    def trace_replay(cls, arr_t, arr_b=None) -> "TrafficProgram":
        """Empirical-trace replay: ``arr_t`` (N, K) µs arrival times
        ascending per row (pad unused tail with any value ≥
        :data:`GAP_INF`), ``arr_b`` (N, K) per-arrival bytes (defaults
        512).  Replay is EXACT — the parity contract of the host
        mirror tests."""
        arr_t = np.asarray(arr_t, np.int64)
        if arr_t.ndim != 2:
            raise ValueError("arr_t must be (N, K)")
        if arr_t.shape[1] < 2:
            arr_t = np.concatenate(
                [arr_t, np.full_like(arr_t, GAP_INF)], axis=1
            )
        live = arr_t < GAP_INF
        srt = np.where(live, arr_t, GAP_INF)
        if (np.diff(srt, axis=1) < 0).any():
            raise ValueError("trace arrival times must ascend per row")
        arr_t = np.minimum(arr_t, GAP_INF).astype(np.int32)
        n, k = arr_t.shape
        if arr_b is None:
            arr_b = np.full((n, k), 512, np.int32)
        else:
            arr_b = np.asarray(arr_b, np.int32)
            if arr_b.shape[1] < k:  # re-pad alongside arr_t
                arr_b = np.concatenate(
                    [arr_b, np.zeros((n, k - arr_b.shape[1]), np.int32)],
                    axis=1,
                )
        dur_s = max(float(srt[live].max(initial=0)) * 1e-6, 1e-6)
        rate = (live.sum(axis=1) / dur_s).astype(np.float32)
        return cls._fill(
            "trace", n,
            start_us=np.where(
                live.any(axis=1), srt.min(axis=1), GAP_INF
            ).astype(np.int32),
            rate_pps=rate, arr_t=arr_t, arr_b=arr_b,
        )


def unify_shapes(progs) -> list:
    """Pad table CAPACITIES (epoch grid, cycle table, trace width) to
    a common :meth:`TrafficProgram.shape_key` so mixed
    cbr/mmpp/onoff/trace points ride ONE workload sweep.  Padding is
    realization-preserving: the epoch chain and cycle draws are
    per-index ``fold_in`` streams (prefix-stable under capacity
    growth) and trace tables pad with the never-arriving sentinel.
    Entity counts and ``epoch_us`` must already agree (they are
    semantic, not capacity)."""
    import dataclasses

    progs = list(progs)
    if len({p.n for p in progs}) != 1:
        raise ValueError("workload sweep points must share the entity count")
    # epoch_us only means anything to points that USE the epoch grid
    # (mmpp, or any point with a real grid); those must agree — the
    # rest are aligned to it (their mmpp branch is never selected)
    used = {int(p.epoch_us) for p in progs if int(p.n_epoch) > 1}
    if len(used) > 1:
        raise ValueError(
            "workload sweep points must share epoch_us (a trace-time "
            "constant); build the mmpp points with one epoch_s"
        )
    epoch_us = used.pop() if used else int(progs[0].epoch_us)
    progs = [
        p if int(p.epoch_us) == epoch_us
        else dataclasses.replace(p, epoch_us=epoch_us)
        for p in progs
    ]
    S = max(int(p.n_epoch) for p in progs)
    C = max(int(p.n_cycle) for p in progs)
    K = max(int(p.arr_t.shape[1]) for p in progs)
    out = []
    for p in progs:
        arr_t, arr_b = p.arr_t, p.arr_b
        k0 = arr_t.shape[1]
        if k0 < K:
            n = arr_t.shape[0]
            arr_t = np.concatenate(
                [arr_t, np.full((n, K - k0), GAP_INF, np.int32)], axis=1
            )
            arr_b = np.concatenate(
                [arr_b, np.zeros((n, K - k0), np.int32)], axis=1
            )
        out.append(
            dataclasses.replace(
                p, n_epoch=S, n_cycle=C, arr_t=arr_t, arr_b=arr_b
            )
        )
    return out


def _env_params(envelope) -> np.ndarray:
    """(amp, period_s, phase) — None means flat (amp 0)."""
    if envelope is None:
        return np.zeros((3,), np.float32)
    amp, period_s, phase = envelope
    if not (0.0 <= float(amp) < 1.0):
        raise ValueError("envelope amplitude must be in [0, 1)")
    if float(period_s) <= 0.0:
        raise ValueError("envelope period must be positive")
    return np.asarray(
        [float(amp), float(period_s), float(phase)], np.float32
    )


def _env_at(env: np.ndarray, t_s: np.ndarray) -> np.ndarray:
    """Diurnal multiplier at time ``t_s`` (numpy, eager-table side)."""
    amp, period, phase = (float(v) for v in env)
    if amp == 0.0:
        return np.ones_like(np.asarray(t_s, np.float64))
    return np.maximum(
        1.0 + amp * np.sin(2.0 * math.pi * (t_s / period - phase)), 0.0
    )


def traffic_tables(prog: TrafficProgram) -> dict:
    """The eager stochastic-table realizations (numpy) — the single
    source of truth shared by :meth:`TrafficProgram.operands` (device)
    and :mod:`tpudes.traffic.host` (the parity mirrors), so the two
    sides cannot drift.  Pure in ``(tr_seed, shapes, params)`` via the
    ``fold_in`` discipline; memoized on the immutable program.

    - ``epoch_rate`` (S,) f32 — mmpp per-epoch rate MULTIPLIER (state
      multiplier × envelope at the epoch midpoint);
    - ``epoch_cum`` (S+1,) f32 — prefix integral of ``epoch_rate`` in
      multiplier-seconds (the closed-form cumulative intensity);
    - ``on_start``/``on_len`` (N, C) i32 µs — ON-burst boundaries;
    - ``peak`` (N, C) f32 — per-cycle ON rate (envelope folded in);
    - ``cum_pk`` (N, C) f32 — offered packets before cycle c starts.
    """
    cached = prog.__dict__.get("_tables_cache")
    if cached is not None:
        return cached
    import jax

    key = jax.random.fold_in(
        jax.random.PRNGKey(_TRAFFIC_ROOT_SEED), int(prog.tr_seed)
    )
    S, C, N = int(prog.n_epoch), int(prog.n_cycle), prog.n
    out: dict = {}

    # --- mmpp: modulating-chain realization on the epoch grid ------------
    k_chain = jax.random.fold_in(key, 0)
    u = np.asarray(
        jax.vmap(
            lambda s: jax.random.uniform(jax.random.fold_in(k_chain, s))
        )(np.arange(S))
    )
    p01, p10 = float(prog.mmpp_p[0]), float(prog.mmpp_p[1])
    states = np.zeros(S, np.int32)
    s = 0
    for e in range(S):  # sequential chain — eager, tiny, pure in tr_seed
        states[e] = s
        s = (1 - s) if u[e] < (p01 if s == 0 else p10) else s
    mids = (np.arange(S) + 0.5) * (prog.epoch_us * 1e-6)
    epoch_rate = (
        np.asarray(prog.mmpp_mult, np.float64)[states]
        * _env_at(prog.env, mids)
    ).astype(np.float32)
    epoch_cum = np.zeros(S + 1, np.float32)
    epoch_cum[1:] = np.cumsum(
        epoch_rate.astype(np.float64) * (prog.epoch_us * 1e-6)
    ).astype(np.float32)
    out["epoch_rate"] = epoch_rate
    out["epoch_cum"] = epoch_cum

    # --- onoff: per-(entity, cycle) burst realization.  One fold_in
    # per (entity, cycle) — NOT a (C, 2)-shaped draw — so growing the
    # cycle capacity (unify_shapes padding for a mixed workload sweep)
    # preserves the realization prefix, the same capacity-stability
    # the engines' replica bucketing relies on.
    k_cyc = jax.random.fold_in(key, 1)
    uc = np.asarray(
        jax.vmap(
            lambda e: jax.vmap(
                lambda c: jax.random.uniform(
                    jax.random.fold_in(
                        jax.random.fold_in(k_cyc, e), c
                    ),
                    (2,),
                )
            )(np.arange(C))
        )(np.arange(N))
    )                                                   # (N, C, 2)
    alpha, on_lo, on_hi = (float(v) for v in prog.on_pareto)
    on_s = bounded_pareto_icdf(uc[..., 0], alpha, on_lo, on_hi)
    off_s = -float(prog.off_mean_s) * np.log1p(
        -np.minimum(uc[..., 1], 1.0 - 1e-7)
    )
    on_us = np.maximum(np.round(on_s * 1e6), 1.0)
    off_us = np.maximum(np.round(off_s * 1e6), 1.0)
    starts = np.zeros((N, C), np.float64)
    starts[:, 1:] = np.cumsum(on_us + off_us, axis=1)[:, :-1]
    on_start = np.minimum(starts, float(GAP_INF)).astype(np.int32)
    on_len = np.minimum(on_us, float(GAP_INF)).astype(np.int32)
    cycle_t = starts * 1e-6  # cycle start on the workload clock, s
    peak = (
        prog.peak_pps.astype(np.float64)[:, None]
        * _env_at(prog.env, cycle_t)
    ).astype(np.float32)
    cum_pk = np.zeros((N, C), np.float32)
    cum_pk[:, 1:] = np.cumsum(
        peak[:, :-1].astype(np.float64) * on_len[:, :-1] * 1e-6, axis=1
    ).astype(np.float32)
    out["on_start"] = on_start
    out["on_len"] = on_len
    out["peak"] = peak
    out["cum_pk"] = cum_pk

    object.__setattr__(prog, "_tables_cache", out)
    return out
