"""Host-side mirrors of the device traffic stage.

Numpy re-implementations of the closed-form arrival math over the SAME
eager tables (:func:`tpudes.traffic.program.traffic_tables`), so the
parity tests compare two independent evaluations of one realization:

- ``offered_packets`` — the numpy twin of ``build_cum_fn`` (exact for
  every model: the stochastic content lives in the shared tables);
- ``arrival_times`` — explicit per-entity arrival lists for the
  DETERMINISTIC models (cbr / onoff / trace): the host DES
  application layer can replay them event for event, which is what
  makes trace-replay parity EXACT.  mmpp arrivals are device-drawn
  (``fold_in``-keyed exponentials), so mmpp host parity is
  distribution-band, like the PHY coin flips — the documented fuzz
  band in tests/test_traffic_host_parity.py.

The upstream ``src/applications`` mirrors themselves (OnOffApplication,
PPBPApplication) live in :mod:`tpudes.models.applications`; this module
is the bridge that turns a :class:`TrafficProgram` into something those
host apps (and the parity tests) can consume.
"""

from __future__ import annotations

import numpy as np

from tpudes.traffic.program import (
    GAP_INF,
    TRAFFIC_MODEL_IDS,
    TrafficProgram,
    traffic_tables,
)

__all__ = ["arrival_times", "offered_packets", "offered_bits_mean"]


def offered_packets(prog: TrafficProgram, t_us) -> np.ndarray:
    """(N,) float cumulative offered packets in ``[0, t_us]`` — the
    numpy twin of the device ``cum_fn`` (same tables, same closed
    form, f64 host arithmetic, per-entity model-id select)."""
    t = traffic_tables(prog)
    tv = np.broadcast_to(np.asarray(t_us, np.int64), (prog.n,))
    tau = np.maximum(tv - prog.start_us.astype(np.int64), 0)
    started = tv >= prog.start_us
    ids = prog.model_ids()

    iv = prog.interval_us.astype(np.int64)
    a_cbr = np.where(
        started & (iv < GAP_INF), tau // np.maximum(iv, 1) + 1, 0
    ).astype(np.float64)

    S = int(prog.n_epoch)
    e = np.clip(tau // int(prog.epoch_us), 0, S - 1).astype(int)
    lam = t["epoch_cum"].astype(np.float64)[e] + t["epoch_rate"].astype(
        np.float64
    )[e] * np.minimum(
        tau - e * int(prog.epoch_us), int(prog.epoch_us)
    ) * 1e-6
    a_mmpp = prog.rate_pps.astype(np.float64) * lam * started

    C = int(prog.n_cycle)
    c = np.clip(
        (t["on_start"].astype(np.int64) <= tau[:, None]).sum(1) - 1,
        0, C - 1,
    )
    rows = np.arange(prog.n)
    on_s = t["on_start"][rows, c].astype(np.float64)
    on_l = t["on_len"][rows, c].astype(np.float64)
    pk = t["peak"][rows, c].astype(np.float64)
    fill = np.clip(tau - on_s, 0.0, on_l) * 1e-6
    a_onoff = (
        t["cum_pk"][rows, c].astype(np.float64) + pk * fill
    ) * started

    live = prog.arr_t < GAP_INF
    a_trace = (
        (live & (prog.arr_t.astype(np.int64) <= tv[:, None]))
        .sum(axis=1)
        .astype(np.float64)
    )

    return np.select(
        [
            ids == TRAFFIC_MODEL_IDS["trace"],
            ids == TRAFFIC_MODEL_IDS["onoff"],
            ids == TRAFFIC_MODEL_IDS["mmpp"],
        ],
        [a_trace, a_onoff, a_mmpp],
        default=a_cbr,
    )


def arrival_times(prog: TrafficProgram, entity: int, horizon_us: int):
    """Sorted arrival times (µs, ints) of one entity over
    ``[0, horizon_us)`` for the DETERMINISTIC models; raises for mmpp
    (whose arrivals are device-drawn — compare distributions, not
    events)."""
    mid = int(prog.model_ids()[entity])
    if mid == TRAFFIC_MODEL_IDS["mmpp"]:
        raise ValueError(
            "mmpp arrivals are fold_in-drawn on device; host parity "
            "for mmpp is distribution-band (use offered_packets)"
        )
    out: list[int] = []
    if mid == TRAFFIC_MODEL_IDS["trace"]:
        row = prog.arr_t[entity]
        return [int(v) for v in row[(row < GAP_INF) & (row < horizon_us)]]
    start = int(prog.start_us[entity])
    if mid == TRAFFIC_MODEL_IDS["cbr"]:
        iv = int(prog.interval_us[entity])
        if iv >= int(GAP_INF):
            return out
        t = start
        while t < horizon_us:
            out.append(t)
            t += iv
        return out
    # onoff: deterministic peak-rate spacing inside each table burst
    t = traffic_tables(prog)
    for c in range(int(prog.n_cycle)):
        pk = float(t["peak"][entity, c])
        if pk <= 1e-9:
            continue
        p_us = max(1, int(round(1e6 / pk)))
        b0 = start + int(t["on_start"][entity, c])
        b1 = b0 + int(t["on_len"][entity, c])
        a = b0
        while a < min(b1, horizon_us):
            out.append(a)
            a += p_us
    return out


def offered_bits_mean(prog: TrafficProgram, t_us) -> np.ndarray:
    """(N,) float expected offered bits by ``t_us`` — packets × mean
    bounded-Pareto size for the generative models, exact byte sums for
    trace-replay entities.  The telemetry-side load estimate (the
    device backlog fill quantizes sizes per window; this is its
    mean)."""
    from tpudes.traffic.program import bounded_pareto_mean

    ids = prog.model_ids()
    mean_b = bounded_pareto_mean(
        float(prog.size_pareto[0]), float(prog.size_pareto[1]),
        float(prog.size_pareto[2]),
    )
    gen = np.floor(offered_packets(prog, t_us)) * mean_b * 8.0
    live = prog.arr_t < GAP_INF
    tv = np.broadcast_to(np.asarray(t_us, np.int64), (prog.n,))
    hit = live & (prog.arr_t.astype(np.int64) <= tv[:, None])
    tr = (prog.arr_b * hit).sum(axis=1).astype(np.float64) * 8.0
    return np.where(ids == TRAFFIC_MODEL_IDS["trace"], tr, gen)
