"""Trace ingestion: real measured traces → trace-replay operand tables.

The trace-replay model (``TrafficProgram.trace_replay``) has carried
synthetic tables since ISSUE-14; this module closes the loop from
MEASURED traffic (ROADMAP item 4 remainder d): read packet captures
(the classic libpcap format the repo's own ``trace_helper`` pcap
surface writes — and tcpdump/wireshark emit) or CSV exports, compress
them into per-entity ``(time, bytes)`` tables, and hand back a
:class:`~tpudes.traffic.TrafficProgram` any engine replays EXACTLY.

Everything is dependency-free stdlib parsing (``struct`` + text): no
scapy, no pandas — the same rule as the pcap writer itself.

Compression is LOSSLESS on the engines' µs clock: arrivals are
truncated to whole microseconds (the device tables' resolution — the
precision the pcap writer itself records) and same-µs arrivals
COALESCE by summing bytes, which preserves offered load and window
bits exactly (the device kernels only ever query "bytes in [t0, t1)";
tests/test_traffic_ingest.py pins the round trip against
PPBP/OnOff-generated captures).  A trace that still exceeds
``max_rows`` after coalescing refuses loudly rather than dropping
tail arrivals.
"""

from __future__ import annotations

import struct

import numpy as np

__all__ = [
    "TraceIngestError",
    "ingest_traces",
    "read_csv_trace",
    "read_pcap",
]

#: classic libpcap magics: µs timestamps (the trace_helper writer),
#: byte-swapped, and the nanosecond-resolution variant
_MAGIC_US_LE = 0xA1B2C3D4
_MAGIC_NS_LE = 0xA1B23C4D


class TraceIngestError(ValueError):
    """Unreadable or unrepresentable trace input."""


def read_pcap(path: str):
    """Parse one libpcap file → ``(times_us, bytes_)`` int64 arrays
    (arrival time in µs since capture epoch, ORIGINAL packet length —
    what the wire carried, not the snap-truncated capture).  Handles
    both endiannesses and the nanosecond magic; pcapng is rejected
    loudly (convert with ``tcpdump -r in.pcapng -w out.pcap``)."""
    with open(path, "rb") as f:
        head = f.read(24)
        if len(head) < 24:
            raise TraceIngestError(f"{path}: truncated pcap header")
        magic_le = struct.unpack("<I", head[:4])[0]
        magic_be = struct.unpack(">I", head[:4])[0]
        if magic_le == 0x0A0D0D0A or magic_be == 0x0A0D0D0A:
            raise TraceIngestError(
                f"{path}: pcapng is not supported — convert to classic "
                "pcap (tcpdump -r in.pcapng -w out.pcap)"
            )
        if magic_le in (_MAGIC_US_LE, _MAGIC_NS_LE):
            endian, magic = "<", magic_le
        elif magic_be in (_MAGIC_US_LE, _MAGIC_NS_LE):
            endian, magic = ">", magic_be
        else:
            raise TraceIngestError(
                f"{path}: not a libpcap file (magic {head[:4]!r})"
            )
        ns = magic == _MAGIC_NS_LE
        times, sizes = [], []
        while True:
            rec = f.read(16)
            if not rec:
                break
            if len(rec) < 16:
                raise TraceIngestError(
                    f"{path}: truncated record header at packet "
                    f"{len(times)}"
                )
            sec, sub, cap, orig = struct.unpack(endian + "IIII", rec)
            data = f.read(cap)
            if len(data) < cap:
                raise TraceIngestError(
                    f"{path}: truncated payload at packet {len(times)}"
                )
            us = sec * 1_000_000 + (sub // 1000 if ns else sub)
            times.append(us)
            sizes.append(orig)
    return (
        np.asarray(times, np.int64),
        np.asarray(sizes, np.int64),
    )


def read_csv_trace(
    path: str,
    *,
    time_col: int = 0,
    bytes_col: int = 1,
    time_unit: str = "s",
    delimiter: str = ",",
):
    """Parse a CSV packet log → ``(times_us, bytes_)`` int64 arrays.
    ``time_unit`` is one of s/ms/us/ns; a non-numeric first row is
    treated as a header and skipped (exporters disagree about
    headers, so sniff instead of flag)."""
    scale = {"s": 1e6, "ms": 1e3, "us": 1.0, "ns": 1e-3}.get(time_unit)
    if scale is None:
        raise TraceIngestError(
            f"time_unit must be s/ms/us/ns, not {time_unit!r}"
        )
    times, sizes = [], []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            cells = line.split(delimiter)
            try:
                t = float(cells[time_col])
                b = float(cells[bytes_col])
            except (ValueError, IndexError):
                if lineno == 1:
                    continue  # header row
                raise TraceIngestError(
                    f"{path}:{lineno}: unparseable row {line!r}"
                ) from None
            times.append(int(round(t * scale)))
            sizes.append(int(round(b)))
    if not times:
        raise TraceIngestError(f"{path}: no packet rows")
    return (
        np.asarray(times, np.int64),
        np.asarray(sizes, np.int64),
    )


def _compress(times_us, bytes_, t0_us):
    """Sort, rebase to ``t0_us``, and coalesce same-µs arrivals (sum
    bytes) — lossless on the device tables' µs window queries."""
    order = np.argsort(times_us, kind="stable")
    t = times_us[order] - int(t0_us)
    b = bytes_[order]
    if (t < 0).any():
        raise TraceIngestError("arrival before the trace epoch t0")
    # coalesce runs of equal timestamps
    keep = np.ones(len(t), bool)
    keep[1:] = t[1:] != t[:-1]
    idx = np.cumsum(keep) - 1
    out_t = t[keep]
    out_b = np.zeros(len(out_t), np.int64)
    np.add.at(out_b, idx, b)
    return out_t, out_b


def ingest_traces(
    sources,
    *,
    t0_us: int | None = None,
    max_rows: int = 4096,
    pad_to: int | None = None,
):
    """Build an exact trace-replay :class:`TrafficProgram` from one
    measured source per entity.

    ``sources`` is a list with one entry per entity, each either a
    path (``.pcap``/``.csv`` by extension), a ``(times_us, bytes_)``
    array pair, or a callable returning one.  ``t0_us`` rebases all
    entities to a common epoch (default: the earliest arrival across
    the batch, so relative timing between entities is preserved —
    capture timestamps are wall-clock, simulation starts at 0).
    ``max_rows`` bounds the per-entity table after same-µs coalescing
    (a longer trace refuses loudly — truncation would silently change
    the workload); ``pad_to`` forces the table capacity (the
    ``shape_key`` knob, so ingested workloads can join an existing
    sweep's executable)."""
    from tpudes.traffic.program import GAP_INF, TrafficProgram

    rows = []
    for i, src in enumerate(sources):
        if callable(src):
            pair = src()
        elif isinstance(src, str):
            if src.endswith(".csv"):
                pair = read_csv_trace(src)
            else:
                pair = read_pcap(src)
        else:
            pair = src
        t, b = (np.asarray(pair[0], np.int64),
                np.asarray(pair[1], np.int64))
        if t.shape != b.shape or t.ndim != 1:
            raise TraceIngestError(
                f"entity {i}: times/bytes must be matching 1-D arrays"
            )
        rows.append((t, b))
    if all(len(t) == 0 for t, _ in rows):
        raise TraceIngestError("every source is empty")
    if t0_us is None:
        t0_us = min(int(t.min()) for t, _ in rows if len(t))
    comp = [
        _compress(t, b, t0_us) if len(t) else
        (np.zeros(0, np.int64), np.zeros(0, np.int64))
        for t, b in rows
    ]
    k = max(max((len(t) for t, _ in comp), default=1), 2)
    if k > max_rows:
        raise TraceIngestError(
            f"{k} arrivals/entity after coalescing exceeds "
            f"max_rows={max_rows} — raise the cap or split the capture"
        )
    if pad_to is not None:
        if pad_to < k:
            raise TraceIngestError(
                f"pad_to={pad_to} below the {k} rows the traces need"
            )
        k = int(pad_to)
    n = len(comp)
    arr_t = np.full((n, k), int(GAP_INF), np.int64)
    arr_b = np.zeros((n, k), np.int64)
    for i, (t, b) in enumerate(comp):
        if len(t) and int(t.max()) >= int(GAP_INF):
            raise TraceIngestError(
                f"entity {i}: arrival at {int(t.max())} µs past the "
                f"representable horizon ({int(GAP_INF)} µs ≈ 17.9 min) "
                "— rebase with t0_us or split the capture"
            )
        arr_t[i, : len(t)] = t
        arr_b[i, : len(b)] = np.minimum(b, 2**30)
    return TrafficProgram.trace_replay(arr_t, arr_b)
