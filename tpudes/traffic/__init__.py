"""Device-resident traffic subsystem (ISSUE-14, ROADMAP item 4).

Workload models as traced operands, shared by every device engine:
trace replay + generative models (MMPP, Poisson-Pareto ON-OFF bursts,
diurnal envelopes, bounded-Pareto sizes) dispatched by a traced model
id under the ``fold_in(key, replica, entity, t)`` keying discipline.

- :mod:`tpudes.traffic.program` — :class:`TrafficProgram` + factories
  and the eager ``fold_in``-keyed table realizations;
- :mod:`tpudes.traffic.device` — the closed-form cum/gap/bits/avg-mult
  kernels the engines trace (and their JXL trace manifest);
- :mod:`tpudes.traffic.host` — numpy mirrors for parity tests and
  telemetry (the upstream ``src/applications`` host apps live in
  :mod:`tpudes.models.applications`);
- :mod:`tpudes.traffic.ingest` — measured-trace ingestion (pcap/CSV →
  compressed exact-replay tables, ISSUE-15).
"""

from tpudes.traffic.ingest import (
    TraceIngestError,
    ingest_traces,
    read_csv_trace,
    read_pcap,
)
from tpudes.traffic.program import (
    TRAFFIC_MODEL_IDS,
    TrafficProgram,
    bounded_pareto_icdf,
    bounded_pareto_mean,
    traffic_tables,
    unify_shapes,
)

__all__ = [
    "TRAFFIC_MODEL_IDS",
    "TraceIngestError",
    "TrafficProgram",
    "ingest_traces",
    "read_csv_trace",
    "read_pcap",
    "bounded_pareto_icdf",
    "bounded_pareto_mean",
    "traffic_tables",
    "unify_shapes",
]
