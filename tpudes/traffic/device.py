"""Closed-form device kernels over a :class:`TrafficProgram`.

Every query is a pure function of the traced operand dict
(:meth:`TrafficProgram.operands`) and a traced time — the mobility
``build_position_fn`` shape: all model branches are evaluated and the
traced ``tr_id`` selects, which is what keeps the whole workload
family on one executable.  Three query forms cover the four engines:

- :func:`build_cum_fn` — cumulative offered packets ``A(ops, t_us) →
  (N,) f32`` (slotted engines: the dumbbell's app-limit gate, the LTE
  per-TTI arrival delta, the AS fluid average).  Closed form because
  the stochastic realizations live in the eager operand tables.
- :func:`build_gap_fn` — next inter-arrival gap after an arrival at
  ``t`` (event-stepped engines: the BSS arrival advance).  Only the
  mmpp branch draws (one exponential per arrival, keyed by the
  established ``fold_in(key, replica, entity, t)`` discipline — pure
  in those indices, so bucketing/chunking/checkpointing stay
  bit-exact); cbr/onoff/trace gaps are deterministic table math.
- :func:`build_bits_fn` — offered BITS in a window (the LTE backlog
  fill): exact per-arrival bytes for trace replay, packet-count ×
  bounded-Pareto size quantum for the generative models (one size
  draw per (entity, window), keyed ``fold_in(key, entity, t)``).

:func:`avg_mult` is the fluid view (AS flows): realized/nominal rate
ratio over a traced horizon, exactly 1 for cbr by construction (the
``traffic_off`` exact-pair anchor).
"""

from __future__ import annotations

import numpy as np

from tpudes.traffic.program import (
    GAP_INF,
    TRAFFIC_MODEL_IDS,
    TrafficProgram,
)

__all__ = [
    "avg_mult",
    "build_bits_fn",
    "build_cum_fn",
    "build_gap_fn",
    "stack_traffic_operands",
    "traffic_operands",
]

#: fold tag deriving the per-run traffic key from the engine key — a
#: fixed integer, so the stream is pure in (run key, replica, entity, t)
TRAFFIC_KEY_TAG = 0x7A


def traffic_operands(prog: TrafficProgram | None) -> dict | None:
    """None-safe operand extraction (the engines' ``geom`` shape)."""
    return None if prog is None else prog.operands()


def stack_traffic_operands(progs) -> dict:
    """Stack the operand dicts of several SAME-SHAPE programs along a
    leading config axis — the (C, …) operand of a workload sweep.  All
    programs must share :meth:`TrafficProgram.shape_key` (a sweep
    rides ONE executable; mismatched capacities are a caller error)."""
    import jax.numpy as jnp

    keys = {p.shape_key() for p in progs}
    if len(keys) != 1:
        raise ValueError(
            f"workload sweep points must share one traffic shape key "
            f"(got {sorted(keys)}); pad tables to a common capacity"
        )
    ops = [p.operands() for p in progs]
    return {k: jnp.stack([o[k] for o in ops]) for k in ops[0]}


def _cum_branches(prog: TrafficProgram):
    """Shared branch math for cum/gap: returns a function computing all
    four models' cumulative offered packets at ``t_us`` plus the
    indices the gap kernel reuses."""
    import jax.numpy as jnp

    S = int(prog.n_epoch)
    C = int(prog.n_cycle)
    K = int(prog.arr_t.shape[1])
    epoch_us = float(prog.epoch_us)

    def parts(ops, t_us):
        # normalize to a per-entity time vector (callers pass a traced
        # scalar OR an (N,) per-entity arrival-time vector)
        tv = jnp.broadcast_to(
            jnp.asarray(t_us, jnp.int32), ops["tr_start"].shape
        )
        # workload clock: τ = t − start, clamped at 0 (nothing before
        # the per-entity start); trace times are absolute
        tau = jnp.maximum(tv - ops["tr_start"], 0)            # (N,)
        tau_f = tau.astype(jnp.float32)

        # cbr: arrivals at start + k·interval, k ≥ 0
        started = tv >= ops["tr_start"]
        a_cbr = jnp.where(
            started & (ops["tr_interval"] < GAP_INF),
            tau // jnp.maximum(ops["tr_interval"], 1) + 1,
            0,
        ).astype(jnp.float32)

        # mmpp: rate_pps × closed-form cumulative intensity from the
        # epoch prefix table
        e = jnp.clip((tau // jnp.int32(epoch_us)), 0, S - 1)  # (N,)
        lam = (
            ops["tr_epoch_cum"][e]
            + ops["tr_epoch_rate"][e]
            * jnp.minimum(
                tau_f - e.astype(jnp.float32) * jnp.float32(epoch_us),
                jnp.float32(epoch_us),
            )
            * jnp.float32(1e-6)
        )
        a_mmpp = ops["tr_rate"] * lam * started

        # onoff: per-cycle prefix packets + peak-rate fill of the
        # current burst (the waypoint count-index trick)
        c = jnp.clip(
            jnp.sum(ops["tr_on_start"] <= tau[:, None], axis=1) - 1,
            0, C - 1,
        )                                                     # (N,)
        on_s = jnp.take_along_axis(
            ops["tr_on_start"], c[:, None], axis=1
        )[:, 0]
        on_l = jnp.take_along_axis(
            ops["tr_on_len"], c[:, None], axis=1
        )[:, 0]
        pk = jnp.take_along_axis(ops["tr_peak"], c[:, None], axis=1)[:, 0]
        cum0 = jnp.take_along_axis(
            ops["tr_cum_pk"], c[:, None], axis=1
        )[:, 0]
        fill_s = jnp.clip(
            tau_f - on_s.astype(jnp.float32), 0.0,
            on_l.astype(jnp.float32),
        ) * jnp.float32(1e-6)
        a_onoff = (cum0 + pk * fill_s) * started

        # trace: exact count of table entries at/before t (absolute
        # clock, INF padding never counts)
        live = ops["tr_arr_t"] < GAP_INF
        hit = live & (ops["tr_arr_t"] <= tv[:, None])
        a_trace = jnp.sum(hit, axis=1, dtype=jnp.int32).astype(
            jnp.float32
        )

        return dict(
            a_cbr=a_cbr, a_mmpp=a_mmpp, a_onoff=a_onoff,
            a_trace=a_trace, tau=tau, e=e, c=c, on_s=on_s, on_l=on_l,
            pk=pk, hit=hit, K=K,
        )

    return parts


def _select(tr_id, cbr, mmpp, onoff, trace):
    import jax.numpy as jnp

    return jnp.where(
        tr_id == TRAFFIC_MODEL_IDS["trace"], trace,
        jnp.where(
            tr_id == TRAFFIC_MODEL_IDS["onoff"], onoff,
            jnp.where(tr_id == TRAFFIC_MODEL_IDS["mmpp"], mmpp, cbr),
        ),
    )


def build_cum_fn(prog: TrafficProgram):
    """``cum_fn(ops, t_us) -> (N,) f32`` cumulative offered packets in
    ``[0, t_us]`` — monotone in t, closed form (no draws)."""
    parts = _cum_branches(prog)

    def cum_fn(ops, t_us):
        p = parts(ops, t_us)
        return _select(
            ops["tr_id"], p["a_cbr"], p["a_mmpp"], p["a_onoff"],
            p["a_trace"],
        )

    return cum_fn


def build_gap_fn(prog: TrafficProgram):
    """``gap_fn(ops, key_r, t_arr) -> (N,) i32`` µs from an arrival at
    ``t_arr[e]`` (per entity) to that entity's NEXT arrival.

    ``key_r`` is the caller's per-replica key; the one stochastic
    branch (mmpp's exponential gap) folds in ``(entity, t_arr)`` on
    top, so the draw is pure in ``(key, replica, entity, t)`` — the
    bucketing/chunking bit-exactness discipline.  Entities past their
    model's last table row return :data:`GAP_INF`-scale gaps (the
    engines' stop logic masks them)."""
    import jax
    import jax.numpy as jnp

    parts = _cum_branches(prog)
    C = int(prog.n_cycle)
    K = int(prog.arr_t.shape[1])

    def gap_fn(ops, key_r, t_arr):
        p = parts(ops, t_arr)
        tau = p["tau"]

        # cbr: the legacy advance, bit for bit
        g_cbr = ops["tr_interval"]

        # mmpp: exponential at the epoch's modulated rate (frozen-rate
        # approximation), one fold_in-keyed uniform per (entity, t)
        def draw(e_idx, t_e):
            k = jax.random.fold_in(jax.random.fold_in(key_r, e_idx), t_e)
            return jax.random.uniform(k, (), jnp.float32)

        u = jax.vmap(draw)(jnp.arange(prog.n), t_arr)
        rate = ops["tr_rate"] * ops["tr_epoch_rate"][p["e"]]
        g_exp = -jnp.log1p(-jnp.minimum(u, 1.0 - 1e-7)) / jnp.maximum(
            rate, 1e-9
        )
        g_mmpp = jnp.clip(
            jnp.round(g_exp * 1e6), 1.0, float(GAP_INF)
        ).astype(jnp.int32)
        g_mmpp = jnp.where(rate > 1e-9, g_mmpp, GAP_INF)

        # onoff: deterministic peak-rate spacing inside the burst; an
        # arrival whose successor would cross the burst end jumps to
        # the next burst's start
        p_us = jnp.clip(
            jnp.round(1e6 / jnp.maximum(p["pk"], 1e-9)), 1.0,
            float(GAP_INF),
        ).astype(jnp.int32)
        in_on = (tau >= p["on_s"]) & (tau < p["on_s"] + p["on_l"])
        cand = tau + p_us
        next_c = jnp.clip(p["c"] + 1, 0, C - 1)
        next_on = jnp.take_along_axis(
            ops["tr_on_start"], next_c[:, None], axis=1
        )[:, 0]
        exhausted = next_c == p["c"]  # past the last table cycle
        jump = jnp.where(
            exhausted, GAP_INF, jnp.maximum(next_on - tau, 1)
        )
        stays = in_on & (cand < p["on_s"] + p["on_l"]) & (p["pk"] > 1e-9)
        g_onoff = jnp.where(stays, p_us, jump)

        # trace: exact next-entry lookup
        idx = jnp.sum(p["hit"], axis=1, dtype=jnp.int32)      # (N,)
        nxt = jnp.take_along_axis(
            ops["tr_arr_t"], jnp.minimum(idx, K - 1)[:, None], axis=1
        )[:, 0]
        g_trace = jnp.where(
            (idx < K) & (nxt < GAP_INF),
            jnp.maximum(nxt - t_arr, 1),
            GAP_INF,
        )

        return _select(ops["tr_id"], g_cbr, g_mmpp, g_onoff, g_trace)

    return gap_fn


def _traced_pareto_sizes(u, tr_size):
    """Traced form of :func:`tpudes.traffic.program.bounded_pareto_icdf`
    (whose eager twin branches on python floats): the size params ride
    as the ``tr_size`` OPERAND, so a size flip is new operand values —
    never a stale compiled kernel (the shapes-only cache-key
    contract)."""
    import jax.numpy as jnp

    alpha, lo, hi = tr_size[0], tr_size[1], tr_size[2]
    degen = (alpha <= 0.0) | (hi <= lo)
    a = jnp.where(degen, 1.0, alpha)
    h = jnp.maximum(hi, lo * (1.0 + 1e-6))
    r = (lo / h) ** a
    drawn = lo / (1.0 - u * (1.0 - r)) ** (1.0 / a)
    return jnp.where(degen, lo, drawn)


def build_bits_fn(prog: TrafficProgram):
    """``bits_fn(ops, key, t0_us, t1_us) -> (N,) f32`` offered bits in
    ``[t0, t1)`` — the LTE backlog fill.  Trace replay contributes the
    EXACT per-arrival bytes; the generative models contribute packet
    count × a bounded-Pareto size quantum (one draw per (entity,
    window), ``fold_in(key, entity, t0)``-keyed — shared across
    replicas like the workload realization itself).  The size params
    are TRACED (``tr_size``), like every other workload parameter."""
    import jax
    import jax.numpy as jnp

    cum_fn = build_cum_fn(prog)

    def bits_fn(ops, key, t0_us, t1_us):
        d_pkts = jnp.floor(cum_fn(ops, t1_us - 1)) - jnp.floor(
            cum_fn(ops, t0_us - 1)
        )
        d_pkts = jnp.maximum(d_pkts, 0.0)

        def draw(e_idx):
            k = jax.random.fold_in(jax.random.fold_in(key, e_idx), t0_us)
            return jax.random.uniform(k, (), jnp.float32)

        u = jax.vmap(draw)(jnp.arange(prog.n))
        size_b = _traced_pareto_sizes(u, ops["tr_size"])
        gen_bits = d_pkts * size_b * 8.0

        live = ops["tr_arr_t"] < GAP_INF
        win = live & (ops["tr_arr_t"] >= t0_us) & (ops["tr_arr_t"] < t1_us)
        tr_bits = (
            jnp.sum(
                jnp.where(win, ops["tr_arr_b"], 0), axis=1,
                dtype=jnp.int32,
            ).astype(jnp.float32)
            * 8.0
        )
        return jnp.where(
            ops["tr_id"] == TRAFFIC_MODEL_IDS["trace"], tr_bits, gen_bits
        )

    return bits_fn


def avg_mult(prog: TrafficProgram):
    """``mult_fn(ops, horizon_us) -> (N,) f32`` — the fluid view: the
    workload's realized/nominal rate ratio over the horizon, i.e. how
    an AS-flow engine scales each flow's nominal ``flow_bps``.  Exactly
    1.0 for cbr (by construction, not by arithmetic — the
    ``traffic_off`` exact-pair anchor)."""
    import jax.numpy as jnp

    cum_fn = build_cum_fn(prog)

    def mult_fn(ops, horizon_us):
        h_s = jnp.maximum(horizon_us.astype(jnp.float32), 1.0) * 1e-6
        nominal = jnp.maximum(ops["tr_rate"] * h_s, 1e-9)
        m = cum_fn(ops, horizon_us) / nominal
        return jnp.where(
            ops["tr_id"] == TRAFFIC_MODEL_IDS["cbr"],
            jnp.float32(1.0), m,
        )

    return mult_fn


# --- trace manifest (tpudes.analysis.jaxpr) --------------------------------


def _trace_prog(**over) -> TrafficProgram:
    """Canonical tiny-shape program for the abstract traces: a 3-entity
    mmpp workload (the shape class every model shares)."""
    import dataclasses

    prog = TrafficProgram.mmpp(
        3, 40.0, horizon_us=500_000, epoch_s=0.05, tr_seed=7
    )
    return dataclasses.replace(prog, **over) if over else prog


def _trace_entries(prog: TrafficProgram, scale: bool = True):
    import jax
    import jax.numpy as jnp

    from tpudes.analysis.jaxpr.spec import TraceEntry

    cum_fn = build_cum_fn(prog)
    gap_fn = build_gap_fn(prog)
    ops = prog.operands()
    key = jax.random.PRNGKey(0)
    t = jnp.full((prog.n,), 40_000, jnp.int32)
    return [
        TraceEntry(
            "cum", cum_fn, (ops, jnp.int32(40_000)),
            kernel=False, traced={"t_us": 1},
            scale_axes=_scale_axes() if scale else (),
        ),
        TraceEntry(
            "gap", gap_fn, (ops, key, t),
            kernel=False, traced={"t_arr": 2},
        ),
    ]


def _scale_axes():
    """JXL007 scale axes for the workload kernels: the operand tables
    are (n, n_epoch) — linear in the entity count and in the epoch
    count, budget 1.0 each (a cross-entity correlation table would
    fire them)."""
    import dataclasses

    from tpudes.analysis.jaxpr.spec import ScaleAxis

    def at_n(v):
        prog = TrafficProgram.mmpp(
            int(v), 40.0, horizon_us=500_000, epoch_s=0.05, tr_seed=7
        )
        return _trace_entries(prog, scale=False)[0]

    def at_epochs(v):
        prog = dataclasses.replace(_trace_prog(), n_epoch=int(v))
        return _trace_entries(prog, scale=False)[0]

    return (
        ScaleAxis(
            "n", at_n, points=(3, 12), mem_budget=1.0
        ),
        ScaleAxis(
            "n_epoch", at_epochs, points=(16, 64), mem_budget=1.0
        ),
    )


def _trace_flips():
    import dataclasses

    from tpudes.analysis.jaxpr.spec import FlipSpec

    base = _trace_prog()

    def flip(key_differs, **over):
        prog = dataclasses.replace(base, **over)
        return FlipSpec(
            build=lambda p=prog: _trace_entries(p),
            key_differs=key_differs,
        )

    return {
        # live SHAPE components: each must change some traced program
        "n_epoch": flip(True, n_epoch=64),
        "epoch_us": flip(True, epoch_us=20_000),
        # excluded-by-design: model id and every parameter are traced
        # operands, so flipping them must leave the traces identical —
        # a model/param sweep never recompiles (the tentpole contract)
        "model": flip(False, model="onoff"),
        "tr_seed": flip(False, tr_seed=99),
        "rate_pps": flip(
            False, rate_pps=np.full((3,), 80.0, np.float32)
        ),
    }


def trace_manifest():
    """Per-stage trace manifest (see :mod:`tpudes.analysis.jaxpr`) —
    the traffic kernels join the JXL lint surface like any engine."""
    from tpudes.analysis.jaxpr.spec import TraceManifest, TraceVariant

    return TraceManifest(
        engine="traffic",
        path="tpudes/traffic/device.py",
        variants=lambda: [
            TraceVariant("base", lambda: _trace_entries(_trace_prog()))
        ],
        flips=_trace_flips,
    )
