"""Topology readers: Inet / Orbis / Rocketfuel file formats.

Reference parity: src/topology-read/model/{topology-reader,
inet-topology-reader,orbis-topology-reader,rocketfuel-topology-
reader}.{h,cc} + helper/topology-reader-helper.{h,cc} (upstream paths;
mount empty at survey — SURVEY.md §0, §2.9 topology-read row).

Readers parse the on-disk formats into (node names, links); the
resulting graph feeds the same object-construction path as the BRITE
generator (BriteGraph/BuildTopology), so a measured Internet topology
drops into any scenario that takes a synthetic one.
"""

from __future__ import annotations

import re

import numpy as np


class TopologyReader:
    """Base: after Read(), ``GetNodes()`` → ordered names, ``GetLinks()``
    → (from_name, to_name, attrs) triples."""

    def __init__(self, filename: str = ""):
        self.filename = filename
        self._nodes: list[str] = []
        self._node_set: dict[str, int] = {}
        self._links: list[tuple[str, str, dict]] = []

    def SetFileName(self, filename: str) -> None:
        self.filename = filename

    def _add_node(self, name: str) -> None:
        if name not in self._node_set:
            self._node_set[name] = len(self._nodes)
            self._nodes.append(name)

    def _add_link(self, a: str, b: str, **attrs) -> None:
        self._add_node(a)
        self._add_node(b)
        self._links.append((a, b, attrs))

    def GetNodes(self) -> list[str]:
        return list(self._nodes)

    def GetLinks(self) -> list[tuple[str, str, dict]]:
        return list(self._links)

    def LinksSize(self) -> int:
        return len(self._links)

    def NodesSize(self) -> int:
        return len(self._nodes)

    def Read(self):
        raise NotImplementedError

    # --- shared materialization (the BRITE BuildTopology path) ------------
    def ToGraph(self, default_rate_bps: float = 10e6,
                default_delay_s: float = 2e-3):
        """Arrays for the device flow engine / object construction."""
        from tpudes.helper.topology import BriteGraph

        idx = self._node_set
        edges = np.asarray(
            [(idx[a], idx[b]) for a, b, _ in self._links], np.int32
        ).reshape(-1, 2)
        delays = np.asarray(
            [float(at.get("delay_s", default_delay_s))
             for _a, _b, at in self._links]
        )
        rates = np.full(len(self._links), default_rate_bps)
        pos = np.zeros((len(self._nodes), 2))
        return BriteGraph(len(self._nodes), edges, delays, rates, pos)


class InetTopologyReader(TopologyReader):
    """inet-topology-reader.cc: header "n_nodes n_links", node lines
    "id x y", link lines "from to weight"."""

    def Read(self):
        with open(self.filename) as f:
            lines = [
                s for s in (ln.strip() for ln in f)
                if s and not s.startswith("#")
            ]
        n_nodes, _n_links = (int(v) for v in lines[0].split()[:2])
        self._coords: dict[str, tuple[float, float]] = {}
        for ln in lines[1 : 1 + n_nodes]:
            parts = ln.split()
            self._add_node(parts[0])
            self._coords[parts[0]] = (float(parts[1]), float(parts[2]))
        for ln in lines[1 + n_nodes :]:
            parts = ln.split()
            self._add_link(parts[0], parts[1],
                           weight=float(parts[2]) if len(parts) > 2 else 1.0)
        return self

    def ToGraph(self, **kw):
        g = super().ToGraph(**kw)
        for name, (x, y) in self._coords.items():
            g.pos[self._node_set[name]] = (x, y)
        return g


class OrbisTopologyReader(TopologyReader):
    """orbis-topology-reader.cc: one "from to" pair per line."""

    def Read(self):
        with open(self.filename) as f:
            for ln in f:
                parts = ln.split()
                if len(parts) >= 2:
                    self._add_link(parts[0], parts[1])
        return self


class RocketfuelTopologyReader(TopologyReader):
    """rocketfuel-topology-reader.cc, the 'weights' flavor the suite
    ships: lines "node1 node2 weight" where names may contain commas
    (city,country); the maps flavor's rich syntax is out of scope."""

    _LINE = re.compile(r"^(\S+)\s+(\S+)\s+([0-9.]+)\s*$")

    def Read(self):
        with open(self.filename) as f:
            for ln in f:
                m = self._LINE.match(ln.strip())
                if m:
                    self._add_link(m.group(1), m.group(2),
                                   weight=float(m.group(3)))
        return self


class TopologyReaderHelper:
    FORMATS = {
        "Inet": InetTopologyReader,
        "Orbis": OrbisTopologyReader,
        "Rocketfuel": RocketfuelTopologyReader,
    }

    def __init__(self):
        self._filename = ""
        self._format = "Inet"

    def SetFileName(self, filename: str) -> None:
        self._filename = filename

    def SetFileType(self, fmt: str) -> None:
        if fmt not in self.FORMATS:
            raise ValueError(f"unknown topology format {fmt!r}")
        self._format = fmt

    def GetTopologyReader(self) -> TopologyReader:
        reader = self.FORMATS[self._format](self._filename)
        reader.Read()
        return reader
