"""Containers: ordered collections the helper API operates on.

Reference parity: src/network/helper/node-container.{h,cc},
net-device-container.{h,cc}, src/internet/helper/
ipv4-interface-container.{h,cc}, application-container.{h,cc}.
"""

from __future__ import annotations

from tpudes.core.nstime import Time
from tpudes.network.node import Node


class _Container:
    def __init__(self, *items):
        self._items: list = []
        for it in items:
            self.Add(it)

    def Add(self, other) -> None:
        if isinstance(other, _Container):
            self._items.extend(other._items)
        elif isinstance(other, (list, tuple)):
            self._items.extend(other)
        else:
            self._items.append(other)

    def Get(self, i: int):
        return self._items[i]

    def GetN(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]


class NodeContainer(_Container):
    def Create(self, n: int, system_id: int = 0) -> "NodeContainer":
        for _ in range(n):
            self._items.append(Node(system_id=system_id))
        return self

    @staticmethod
    def GetGlobal() -> "NodeContainer":
        from tpudes.network.node import NodeList

        c = NodeContainer()
        c.Add(NodeList.All())
        return c


class NetDeviceContainer(_Container):
    pass


class ApplicationContainer(_Container):
    def Start(self, time: Time) -> None:
        for app in self._items:
            app.SetStartTime(time)

    def Stop(self, time: Time) -> None:
        for app in self._items:
            app.SetStopTime(time)


class Ipv4InterfaceContainer(_Container):
    """Items are (Ipv4L3Protocol, interface_index) pairs."""

    def Add(self, other) -> None:
        # a 2-tuple (ipv4, if_index) is one item, not a sequence to splice
        if isinstance(other, tuple) and len(other) == 2 and isinstance(other[1], int):
            self._items.append(other)
        else:
            super().Add(other)

    def GetAddress(self, i: int, j: int = 0):
        ipv4, index = self._items[i]
        return ipv4.GetAddress(index, j).GetLocal()

    def SetMetric(self, i: int, metric: int) -> None:
        ipv4, index = self._items[i]
        ipv4.GetInterface(index).metric = metric
