"""Canned topology builders (the point-to-point-layout module).

Reference parity: src/point-to-point-layout/model/
point-to-point-dumbbell.{h,cc} and point-to-point-grid.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0, §2.9).

The dumbbell is BASELINE config #2's substrate: N left leaves feeding a
single bottleneck link toward N right leaves — the classic TCP
congestion-control arena.
"""

from __future__ import annotations

from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.helper.internet import Ipv4AddressHelper


class PointToPointDumbbellHelper:
    """left leaves — left router ==bottleneck== right router — right
    leaves, each leaf on its own access link."""

    def __init__(self, n_left: int, left_helper, n_right: int, right_helper,
                 bottleneck_helper):
        self._routers = NodeContainer()
        self._routers.Create(2)
        self._left_leaves = NodeContainer()
        self._left_leaves.Create(n_left)
        self._right_leaves = NodeContainer()
        self._right_leaves.Create(n_right)

        self._router_devices = bottleneck_helper.Install(
            self._routers.Get(0), self._routers.Get(1)
        )
        self._left_router_devices = NetDeviceContainer()
        self._left_leaf_devices = NetDeviceContainer()
        for i in range(n_left):
            c = left_helper.Install(self._routers.Get(0), self._left_leaves.Get(i))
            self._left_router_devices.Add(c.Get(0))
            self._left_leaf_devices.Add(c.Get(1))
        self._right_router_devices = NetDeviceContainer()
        self._right_leaf_devices = NetDeviceContainer()
        for i in range(n_right):
            c = right_helper.Install(self._routers.Get(1), self._right_leaves.Get(i))
            self._right_router_devices.Add(c.Get(0))
            self._right_leaf_devices.Add(c.Get(1))

        self._left_interfaces = None
        self._right_interfaces = None
        self._router_interfaces = None

    # --- accessors (upstream names) -------------------------------------
    def GetLeft(self, i: int | None = None):
        return self._routers.Get(0) if i is None else self._left_leaves.Get(i)

    def GetRight(self, i: int | None = None):
        return self._routers.Get(1) if i is None else self._right_leaves.Get(i)

    def LeftCount(self) -> int:
        return self._left_leaves.GetN()

    def RightCount(self) -> int:
        return self._right_leaves.GetN()

    def GetLeftIpv4Address(self, i: int):
        return self._left_interfaces[i]

    def GetRightIpv4Address(self, i: int):
        return self._right_interfaces[i]

    def GetBottleneckDevices(self) -> NetDeviceContainer:
        return self._router_devices

    # --- wiring ----------------------------------------------------------
    def InstallStack(self, stack) -> None:
        stack.Install(self._routers)
        stack.Install(self._left_leaves)
        stack.Install(self._right_leaves)

    def AssignIpv4Addresses(self, left_ip: Ipv4AddressHelper,
                            right_ip: Ipv4AddressHelper,
                            router_ip: Ipv4AddressHelper) -> None:
        """One subnet per access link, one for the bottleneck; leaf
        addresses are recorded for GetLeft/RightIpv4Address."""
        self._router_interfaces = router_ip.Assign(self._router_devices)
        self._left_interfaces = []
        for i in range(self.LeftCount()):
            c = NetDeviceContainer(
                self._left_router_devices.Get(i), self._left_leaf_devices.Get(i)
            )
            ifc = left_ip.Assign(c)
            self._left_interfaces.append(ifc.GetAddress(1))
            left_ip.NewNetwork()
        self._right_interfaces = []
        for i in range(self.RightCount()):
            c = NetDeviceContainer(
                self._right_router_devices.Get(i), self._right_leaf_devices.Get(i)
            )
            ifc = right_ip.Assign(c)
            self._right_interfaces.append(ifc.GetAddress(1))
            right_ip.NewNetwork()
