"""Application helpers.

Reference parity: src/applications/helper/udp-echo-helper.{h,cc},
udp-client-server-helper.{h,cc}, on-off-helper.{h,cc},
packet-sink-helper.{h,cc}, bulk-send-helper.{h,cc}.
"""

from __future__ import annotations

from tpudes.helper.containers import ApplicationContainer, NodeContainer
from tpudes.models.applications import (
    BulkSendApplication,
    OnOffApplication,
    PacketSink,
    UdpClient,
    UdpEchoClient,
    UdpEchoServer,
    UdpServer,
)


class _AppHelper:
    app_cls = None

    def __init__(self, **attrs):
        self._attrs = dict(attrs)

    def SetAttribute(self, name: str, value) -> None:
        self._attrs[name] = value

    def Install(self, nodes) -> ApplicationContainer:
        if not isinstance(nodes, (NodeContainer, list, tuple)):
            nodes = [nodes]
        apps = ApplicationContainer()
        for node in nodes:
            app = self.app_cls(**self._attrs)
            node.AddApplication(app)
            apps.Add(app)
        return apps


class UdpEchoServerHelper(_AppHelper):
    app_cls = UdpEchoServer

    def __init__(self, port: int = 9, **attrs):
        super().__init__(Port=port, **attrs)


class UdpEchoClientHelper(_AppHelper):
    app_cls = UdpEchoClient

    def __init__(self, address=None, port: int = 0, **attrs):
        super().__init__(RemoteAddress=address, RemotePort=port, **attrs)


class UdpServerHelper(_AppHelper):
    app_cls = UdpServer

    def __init__(self, port: int = 100, **attrs):
        super().__init__(Port=port, **attrs)


class UdpClientHelper(_AppHelper):
    app_cls = UdpClient

    def __init__(self, address=None, port: int = 100, **attrs):
        super().__init__(RemoteAddress=address, RemotePort=port, **attrs)


class PacketSinkHelper(_AppHelper):
    app_cls = PacketSink

    def __init__(self, protocol: str = "tpudes::UdpSocketFactory", local=None, **attrs):
        super().__init__(Protocol=protocol, Local=local, **attrs)


class OnOffHelper(_AppHelper):
    app_cls = OnOffApplication

    def __init__(self, protocol: str = "tpudes::UdpSocketFactory", remote=None, **attrs):
        super().__init__(Protocol=protocol, Remote=remote, **attrs)


class BulkSendHelper(_AppHelper):
    app_cls = BulkSendApplication

    def __init__(self, protocol: str = "tpudes::TcpSocketFactory", remote=None, **attrs):
        super().__init__(Protocol=protocol, Remote=remote, **attrs)
