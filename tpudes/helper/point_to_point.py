"""PointToPointHelper: install p2p links between node pairs.

Reference parity: src/point-to-point/helper/point-to-point-helper.{h,cc}.
"""

from __future__ import annotations

from tpudes.helper.containers import NetDeviceContainer, NodeContainer
from tpudes.models.p2p import PointToPointChannel, PointToPointNetDevice
from tpudes.network.queue import DropTailQueue
from tpudes.network.trace_helper import DLT_PPP, PcapHelperForDevice


class PointToPointHelper(PcapHelperForDevice):
    pcap_dlt = DLT_PPP

    def _pcap_device_ok(self, device) -> bool:
        return isinstance(device, PointToPointNetDevice)

    def __init__(self):
        self._device_attrs: dict = {}
        self._channel_attrs: dict = {}
        self._queue_attrs: dict = {}

    def SetDeviceAttribute(self, name: str, value) -> None:
        self._device_attrs[name] = value

    def SetChannelAttribute(self, name: str, value) -> None:
        self._channel_attrs[name] = value

    def SetQueue(self, _type: str = "tpudes::DropTailQueue", **attrs) -> None:
        self._queue_attrs = attrs

    def Install(self, a, b=None) -> NetDeviceContainer:
        if b is None:  # a is a container of exactly 2 nodes
            assert isinstance(a, NodeContainer) and a.GetN() == 2
            a, b = a.Get(0), a.Get(1)
        if isinstance(a, NodeContainer):
            a = a.Get(0)
        if isinstance(b, NodeContainer):
            b = b.Get(0)
        dev_a = PointToPointNetDevice(**self._device_attrs)
        dev_b = PointToPointNetDevice(**self._device_attrs)
        dev_a.SetQueue(DropTailQueue(**self._queue_attrs))
        dev_b.SetQueue(DropTailQueue(**self._queue_attrs))
        a.AddDevice(dev_a)
        b.AddDevice(dev_b)
        # a link spanning two partitions becomes a remote channel (the
        # upstream helper does the same systemId check under MPI)
        from tpudes.parallel.mpi import MpiInterface

        if (
            MpiInterface.IsEnabled()
            and a.GetSystemId() != b.GetSystemId()
        ):
            from tpudes.models.p2p import PointToPointRemoteChannel

            channel = PointToPointRemoteChannel(**self._channel_attrs)
        else:
            channel = PointToPointChannel(**self._channel_attrs)
        dev_a.Attach(channel)
        dev_b.Attach(channel)
        return NetDeviceContainer(dev_a, dev_b)
