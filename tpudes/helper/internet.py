"""InternetStackHelper + Ipv4AddressHelper.

Reference parity: src/internet/helper/internet-stack-helper.{h,cc},
ipv4-address-helper.{h,cc}. Address assignment auto-installs the
connected-subnet route on the interface, as upstream's
Ipv4StaticRouting does on NotifyAddAddress.
"""

from __future__ import annotations

from tpudes.helper.containers import Ipv4InterfaceContainer, NetDeviceContainer, NodeContainer
from tpudes.models.internet.arp import ArpL3Protocol
from tpudes.models.internet.ipv4 import (
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
    Ipv4StaticRouting,
)
from tpudes.models.internet.udp import UdpL4Protocol
from tpudes.network.address import Ipv4Address, Ipv4Mask


class InternetStackHelper:
    def __init__(self):
        self._routing_factory = None
        self._ipv6 = True  # dual stack by default, as upstream

    def SetRoutingHelper(self, routing_helper) -> None:
        self._routing_factory = routing_helper

    def Install(self, nodes) -> None:
        import importlib.util

        if not isinstance(nodes, (NodeContainer, list, tuple)):
            nodes = [nodes]
        have_tcp = importlib.util.find_spec("tpudes.models.internet.tcp") is not None
        for node in nodes:
            if node.GetObject(Ipv4L3Protocol) is not None:
                continue  # already installed
            ipv4 = Ipv4L3Protocol()
            ipv4.SetNode(node)
            node.AggregateObject(ipv4)
            arp = ArpL3Protocol()
            arp.SetNode(node)
            node.AggregateObject(arp)
            if self._routing_factory is not None:
                routing = self._routing_factory.Create(node)
            else:
                routing = Ipv4StaticRouting()
            ipv4.SetRoutingProtocol(routing)
            udp = UdpL4Protocol()
            udp.SetNode(node)
            ipv4.Insert(udp)
            node.AggregateObject(udp)
            from tpudes.models.internet.icmp import IcmpL4Protocol

            icmp = IcmpL4Protocol()
            icmp.SetNode(node)
            ipv4.Insert(icmp)
            node.AggregateObject(icmp)
            # TCP (src/internet/model/tcp-l4-protocol) is installed when
            # available so sockets of both families work out of the box;
            # the spec probe (above) lets a broken tcp.py raise loudly
            if have_tcp:
                from tpudes.models.internet.tcp import TcpL4Protocol

                tcp = TcpL4Protocol()
                tcp.SetNode(node)
                ipv4.Insert(tcp)
                node.AggregateObject(tcp)
            if self._ipv6:
                from tpudes.models.internet.icmpv6 import Icmpv6L4Protocol
                from tpudes.models.internet.ipv6 import (
                    Ipv6L3Protocol,
                    Ipv6StaticRouting,
                )

                ipv6 = Ipv6L3Protocol()
                ipv6.SetNode(node)
                node.AggregateObject(ipv6)
                ipv6.SetRoutingProtocol(Ipv6StaticRouting())
                icmp6 = Icmpv6L4Protocol()
                icmp6.SetNode(node)
                ipv6.Insert(icmp6)
                node.AggregateObject(icmp6)
                # dual stack: the SAME L4 protocol objects serve both
                # families (their demux is per-family)
                ipv6.Insert(udp)

    InstallAll = Install

    def SetIpv6StackInstall(self, enable: bool) -> None:
        """upstream InternetStackHelper::SetIpv6StackInstall."""
        self._ipv6 = bool(enable)


class Ipv4AddressHelper:
    def __init__(self, network: str = "10.0.0.0", mask: str = "255.255.255.0", base: str = "0.0.0.1"):
        self.SetBase(network, mask, base)

    def SetBase(self, network: str, mask: str, base: str = "0.0.0.1") -> None:
        self._network = Ipv4Address(network).addr
        self._mask = Ipv4Mask(mask)
        self._base = Ipv4Address(base).addr
        self._next = self._base

    def NewNetwork(self) -> None:
        # advance network by one subnet
        step = (~self._mask.mask & 0xFFFFFFFF) + 1
        self._network += step
        self._next = self._base

    def NewAddress(self) -> Ipv4Address:
        # exhaustion guard: never hand out the subnet broadcast address or
        # bleed into the next subnet (upstream NS_ABORTs here too)
        host_max = ~self._mask.mask & 0xFFFFFFFF
        if self._next >= host_max:
            raise RuntimeError(
                f"Ipv4AddressHelper: address pool exhausted in "
                f"{Ipv4Address(self._network)}/{self._mask.GetPrefixLength()}"
            )
        addr = Ipv4Address(self._network | self._next)
        self._next += 1
        return addr

    def Assign(self, devices: NetDeviceContainer) -> Ipv4InterfaceContainer:
        container = Ipv4InterfaceContainer()
        for device in devices:
            node = device.GetNode()
            ipv4 = node.GetObject(Ipv4L3Protocol)
            if ipv4 is None:
                raise RuntimeError(
                    f"node {node.GetId()} has no internet stack (InternetStackHelper.Install first)"
                )
            if_index = ipv4.GetInterfaceForDevice(device)
            if if_index < 0:
                if_index = ipv4.AddInterface(device)
            addr = self.NewAddress()
            ipv4.AddAddress(if_index, Ipv4InterfaceAddress(addr, self._mask))
            # connected-subnet route
            routing = ipv4.GetRoutingProtocol()
            if isinstance(routing, Ipv4StaticRouting):
                routing.AddNetworkRouteTo(addr.CombineMask(self._mask), self._mask, if_index)
            else:
                notify = getattr(routing, "NotifyAddAddress", None)
                if notify is not None:
                    notify(if_index, Ipv4InterfaceAddress(addr, self._mask))
            container.Add((ipv4, if_index))
        return container


class Ipv6AddressHelper:
    """src/internet/helper/ipv6-address-helper.{h,cc}: sequential
    interface ids under one /64 (or caller-chosen) prefix; Assign adds
    the connected-prefix route like the v4 helper does."""

    def __init__(self, network: str = "2001:db8::", prefix: int = 64):
        self.SetBase(network, prefix)

    def SetBase(self, network: str, prefix: int = 64) -> None:
        from tpudes.network.address import Ipv6Address, Ipv6Prefix

        self._prefix = Ipv6Prefix(prefix)
        self._network = Ipv6Address(network).addr & self._prefix.mask_int()
        self._next = 1

    def NewNetwork(self) -> None:
        self._network += 1 << (128 - self._prefix.length)
        self._next = 1

    def NewAddress(self):
        from tpudes.network.address import Ipv6Address

        host_max = (1 << (128 - self._prefix.length)) - 1
        if self._next >= host_max:
            raise RuntimeError("Ipv6AddressHelper: pool exhausted")
        addr = Ipv6Address(self._network | self._next)
        self._next += 1
        return addr

    def Assign(self, devices: NetDeviceContainer):
        from tpudes.models.internet.ipv6 import (
            Ipv6InterfaceAddress,
            Ipv6L3Protocol,
            Ipv6StaticRouting,
        )
        from tpudes.network.address import Ipv6Address

        container = []
        for device in devices:
            node = device.GetNode()
            ipv6 = node.GetObject(Ipv6L3Protocol)
            if ipv6 is None:
                raise RuntimeError(
                    f"node {node.GetId()} has no IPv6 stack "
                    "(InternetStackHelper dual-stack Install first)"
                )
            if_index = ipv6.GetInterfaceForDevice(device)
            if if_index < 0:
                if_index = ipv6.AddInterface(device)
            addr = self.NewAddress()
            ipv6.AddAddress(if_index, Ipv6InterfaceAddress(addr, self._prefix))
            routing = ipv6.GetRoutingProtocol()
            if isinstance(routing, Ipv6StaticRouting):
                routing.AddNetworkRouteTo(
                    Ipv6Address(addr.addr & self._prefix.mask_int()),
                    self._prefix, if_index,
                )
            container.append((ipv6, if_index))
        return Ipv6InterfaceContainer(container)


class Ipv6InterfaceContainer:
    def __init__(self, pairs=None):
        self._pairs = list(pairs or [])

    def Add(self, pair) -> None:
        self._pairs.append(pair)

    def GetN(self) -> int:
        return len(self._pairs)

    def Get(self, i: int):
        return self._pairs[i]

    def GetAddress(self, i: int, ad: int = 1):
        """Address ``ad`` of interface i — index 0 is the link-local,
        1 the first global (upstream convention)."""
        ipv6, if_index = self._pairs[i]
        iface = ipv6.GetInterface(if_index)
        globals_ = [a for a in iface.addresses if not a.local.IsLinkLocal()]
        locals_ = [a for a in iface.addresses if a.local.IsLinkLocal()]
        ordered = locals_ + globals_
        return ordered[ad].GetLocal()

    def SetForwarding(self, i: int, enable: bool) -> None:
        ipv6, _ = self._pairs[i]
        ipv6.ip_forward = bool(enable)

    def SetDefaultRouteInAllNodes(self, router_index: int) -> None:
        from tpudes.models.internet.ipv6 import Ipv6StaticRouting

        gw = self.GetAddress(router_index, 1)
        for i, (ipv6, if_index) in enumerate(self._pairs):
            if i == router_index:
                continue
            routing = ipv6.GetRoutingProtocol()
            if isinstance(routing, Ipv6StaticRouting):
                routing.SetDefaultRoute(gw, if_index)
