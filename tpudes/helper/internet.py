"""InternetStackHelper + Ipv4AddressHelper.

Reference parity: src/internet/helper/internet-stack-helper.{h,cc},
ipv4-address-helper.{h,cc}. Address assignment auto-installs the
connected-subnet route on the interface, as upstream's
Ipv4StaticRouting does on NotifyAddAddress.
"""

from __future__ import annotations

from tpudes.helper.containers import Ipv4InterfaceContainer, NetDeviceContainer, NodeContainer
from tpudes.models.internet.arp import ArpL3Protocol
from tpudes.models.internet.ipv4 import (
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
    Ipv4StaticRouting,
)
from tpudes.models.internet.udp import UdpL4Protocol
from tpudes.network.address import Ipv4Address, Ipv4Mask


class InternetStackHelper:
    def __init__(self):
        self._routing_factory = None

    def SetRoutingHelper(self, routing_helper) -> None:
        self._routing_factory = routing_helper

    def Install(self, nodes) -> None:
        import importlib.util

        if not isinstance(nodes, (NodeContainer, list, tuple)):
            nodes = [nodes]
        have_tcp = importlib.util.find_spec("tpudes.models.internet.tcp") is not None
        for node in nodes:
            if node.GetObject(Ipv4L3Protocol) is not None:
                continue  # already installed
            ipv4 = Ipv4L3Protocol()
            ipv4.SetNode(node)
            node.AggregateObject(ipv4)
            arp = ArpL3Protocol()
            arp.SetNode(node)
            node.AggregateObject(arp)
            if self._routing_factory is not None:
                routing = self._routing_factory.Create(node)
            else:
                routing = Ipv4StaticRouting()
            ipv4.SetRoutingProtocol(routing)
            udp = UdpL4Protocol()
            udp.SetNode(node)
            ipv4.Insert(udp)
            node.AggregateObject(udp)
            from tpudes.models.internet.icmp import IcmpL4Protocol

            icmp = IcmpL4Protocol()
            icmp.SetNode(node)
            ipv4.Insert(icmp)
            node.AggregateObject(icmp)
            # TCP (src/internet/model/tcp-l4-protocol) is installed when
            # available so sockets of both families work out of the box;
            # the spec probe (above) lets a broken tcp.py raise loudly
            if have_tcp:
                from tpudes.models.internet.tcp import TcpL4Protocol

                tcp = TcpL4Protocol()
                tcp.SetNode(node)
                ipv4.Insert(tcp)
                node.AggregateObject(tcp)

    InstallAll = Install


class Ipv4AddressHelper:
    def __init__(self, network: str = "10.0.0.0", mask: str = "255.255.255.0", base: str = "0.0.0.1"):
        self.SetBase(network, mask, base)

    def SetBase(self, network: str, mask: str, base: str = "0.0.0.1") -> None:
        self._network = Ipv4Address(network).addr
        self._mask = Ipv4Mask(mask)
        self._base = Ipv4Address(base).addr
        self._next = self._base

    def NewNetwork(self) -> None:
        # advance network by one subnet
        step = (~self._mask.mask & 0xFFFFFFFF) + 1
        self._network += step
        self._next = self._base

    def NewAddress(self) -> Ipv4Address:
        # exhaustion guard: never hand out the subnet broadcast address or
        # bleed into the next subnet (upstream NS_ABORTs here too)
        host_max = ~self._mask.mask & 0xFFFFFFFF
        if self._next >= host_max:
            raise RuntimeError(
                f"Ipv4AddressHelper: address pool exhausted in "
                f"{Ipv4Address(self._network)}/{self._mask.GetPrefixLength()}"
            )
        addr = Ipv4Address(self._network | self._next)
        self._next += 1
        return addr

    def Assign(self, devices: NetDeviceContainer) -> Ipv4InterfaceContainer:
        container = Ipv4InterfaceContainer()
        for device in devices:
            node = device.GetNode()
            ipv4 = node.GetObject(Ipv4L3Protocol)
            if ipv4 is None:
                raise RuntimeError(
                    f"node {node.GetId()} has no internet stack (InternetStackHelper.Install first)"
                )
            if_index = ipv4.GetInterfaceForDevice(device)
            if if_index < 0:
                if_index = ipv4.AddInterface(device)
            addr = self.NewAddress()
            ipv4.AddAddress(if_index, Ipv4InterfaceAddress(addr, self._mask))
            # connected-subnet route
            routing = ipv4.GetRoutingProtocol()
            if isinstance(routing, Ipv4StaticRouting):
                routing.AddNetworkRouteTo(addr.CombineMask(self._mask), self._mask, if_index)
            else:
                notify = getattr(routing, "NotifyAddAddress", None)
                if notify is not None:
                    notify(if_index, Ipv4InterfaceAddress(addr, self._mask))
            container.Add((ipv4, if_index))
        return container
