"""Topology-wiring helpers.

Reference parity: src/*/helper/ (SURVEY.md 1 — the "helper" API layer):
containers, PointToPointHelper, InternetStackHelper, Ipv4AddressHelper,
application helpers.
"""

from tpudes.helper.containers import (
    NodeContainer,
    NetDeviceContainer,
    Ipv4InterfaceContainer,
    ApplicationContainer,
)
from tpudes.helper.point_to_point import PointToPointHelper
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.helper.applications import (
    UdpEchoServerHelper,
    UdpEchoClientHelper,
    UdpServerHelper,
    UdpClientHelper,
    PacketSinkHelper,
    OnOffHelper,
    BulkSendHelper,
)
