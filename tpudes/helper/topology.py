"""Synthetic Internet-scale topology generation (the BRITE analog).

Reference parity: src/brite/helper/brite-topology-helper.{h,cc} wraps
the external BRITE C++ generator (upstream paths; mount empty at survey
— SURVEY.md §0, §2.9, §7 step 9: "reimplement generator, don't bind the
GPL BRITE lib").  BRITE's two flat models are reimplemented here from
their published definitions (Medina et al., BRITE: An Approach to
Universal Topology Generation, MASCOTS 2001):

- **Barabási–Albert** preferential attachment: each new node joins with
  ``m`` links; target chosen w.p. proportional to current degree — the
  AS-level heavy-tail model.
- **Waxman** random geometric: nodes uniform on an L×L plane, edge
  (u,v) w.p. ``alpha * exp(-d(u,v) / (beta * L_max))`` — the
  router-level locality model (connectivity is then ensured by chaining
  each non-first component to its predecessor with one edge).

The generator is pure numpy (vectorized draws, no per-edge Python in
Waxman); ``BuildTopology`` optionally materializes the ns-3 object
graph (Nodes, p2p links, stacks, per-link /30 subnets) for the scalar
engine, while the raw arrays feed the device flow engine directly
(tpudes/parallel/as_flows.py) — constructing 10k Python node objects is
never required just to run on the TPU.
"""

from __future__ import annotations

import math

import numpy as np


def component_labels(n: int, edges) -> np.ndarray:
    """(n,) connected-component root label per vertex (path-halving
    union-find) — the one shared implementation (generator connectivity,
    BRITE component chaining, and lowering guards all use it)."""
    parent = np.arange(n)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for u, v in edges:
        ru, rv = find(int(u)), find(int(v))
        if ru != rv:
            parent[ru] = rv
    return np.asarray([find(i) for i in range(n)])


class BriteGraph:
    """Plain arrays: ``edges`` (E, 2) int32, ``delay_s`` (E,) float64,
    ``rate_bps`` (E,) float64, ``pos`` (N, 2) float64."""

    def __init__(self, n, edges, delay_s, rate_bps, pos):
        self.n = int(n)
        self.edges = np.asarray(edges, np.int32)
        self.delay_s = np.asarray(delay_s, np.float64)
        self.rate_bps = np.asarray(rate_bps, np.float64)
        self.pos = pos

    @property
    def m(self) -> int:
        return int(self.edges.shape[0])

    def is_connected(self) -> bool:
        labels = component_labels(self.n, self.edges)
        return bool((labels == labels[0]).all())


def barabasi_albert(n: int, m: int, rng: np.random.Generator) -> np.ndarray:
    """(E, 2) edge list: BA preferential attachment, ``m`` edges per new
    node, seeded with an (m+1)-clique.  Uses the repeated-endpoint trick
    (a uniform draw from the flat endpoint array lands on a node w.p.
    proportional to its degree) over a preallocated buffer, so each node
    costs O(m) — 10k nodes generate in well under a second."""
    if n <= m:
        raise ValueError(f"need n > m (got n={n}, m={m})")
    seed_edges = [(i, j) for i in range(m + 1) for j in range(i + 1, m + 1)]
    n_edges = len(seed_edges) + m * (n - m - 1)
    edges = np.empty((n_edges, 2), np.int32)
    edges[: len(seed_edges)] = seed_edges
    endpoints = np.empty(2 * n_edges, np.int32)
    endpoints[: 2 * len(seed_edges)] = edges[: len(seed_edges)].ravel()
    e_cnt, ep_cnt = len(seed_edges), 2 * len(seed_edges)
    targets = np.empty(m, np.int32)
    for v in range(m + 1, n):
        seen = 0
        while seen < m:
            # oversample: duplicates are rare for m << degree-sum
            draw = endpoints[rng.integers(0, ep_cnt, size=2 * (m - seen))]
            for t in draw:
                if seen < m and t not in targets[:seen]:
                    targets[seen] = t
                    seen += 1
        edges[e_cnt : e_cnt + m, 0] = v
        edges[e_cnt : e_cnt + m, 1] = targets
        endpoints[ep_cnt : ep_cnt + m] = v
        endpoints[ep_cnt + m : ep_cnt + 2 * m] = targets
        e_cnt += m
        ep_cnt += 2 * m
    return edges


def waxman(
    n: int,
    alpha: float,
    beta: float,
    rng: np.random.Generator,
    plane: float = 1000.0,
):
    """(pos, edges): uniform placement + vectorized Waxman edge draws
    (row-blocked so the n×n distance matrix never materializes — 10k
    nodes peak at ~40 MB); non-first components are chained to keep the
    graph connected."""
    pos = rng.uniform(0.0, plane, size=(n, 2))
    l_max = plane * math.sqrt(2.0)  # plane diagonal bounds every distance
    blocks = []
    block = max(1, min(n, (1 << 22) // max(n, 1)))  # ~32 MB f64 rows
    for lo in range(0, n, block):
        hi = min(lo + block, n)
        d = np.sqrt(
            ((pos[lo:hi, None, :] - pos[None, :, :]) ** 2).sum(-1)
        )
        p = alpha * np.exp(-d / (beta * l_max))
        hit = rng.random((hi - lo, n)) < p
        # upper triangle only: j > i
        rows, cols = np.nonzero(hit)
        rows = rows + lo
        keep = cols > rows
        if keep.any():
            blocks.append(
                np.stack([rows[keep], cols[keep]], axis=1).astype(np.int32)
            )
    edges = (
        np.concatenate(blocks) if blocks else np.empty((0, 2), np.int32)
    )

    # connect components (BRITE post-pass): chain one representative of
    # each component to the previous one
    labels = component_labels(n, edges)
    roots = sorted(set(int(r) for r in labels))
    extra = [(a, b) for a, b in zip(roots, roots[1:])]
    if extra:
        edges = np.concatenate([edges, np.asarray(extra, np.int32)])
    return pos, edges


class BriteTopologyHelper:
    """BriteTopologyHelper analog: generate, then (optionally) build.

    ``model``: "BA" (AS-level) or "Waxman" (router-level locality).
    Link delays: distance/c on the generated plane (BRITE assigns
    geometric delays); link rates: uniform in [bw_min, bw_max].
    """

    def __init__(
        self,
        model: str = "BA",
        n: int = 100,
        m: int = 2,
        alpha: float = 0.15,
        beta: float = 0.2,
        bw_min_bps: float = 10e6,
        bw_max_bps: float = 100e6,
        plane: float = 4000e3,   # 4000 km — continental AS spread
        seed: int = 1,
    ):
        self.model = model
        self.n = int(n)
        self.m_links = int(m)
        self.alpha = alpha
        self.beta = beta
        self.bw_min = bw_min_bps
        self.bw_max = bw_max_bps
        self.plane = plane
        self.seed = seed
        self.graph: BriteGraph | None = None
        self._nodes = None

    # --- generation (pure arrays) ----------------------------------------
    def Generate(self) -> BriteGraph:
        from tpudes.core.rng import seeded_bulk_generator

        # bulk array draws on the seeded-stream contract: the generator
        # is keyed by (RngSeed, RngRun, self.seed), so RngSeedManager
        # run selection re-randomizes the topology like every other
        # stream consumer (was: a bare default_rng(seed) that RngRun
        # could never reach — promoted RNG002 baseline finding)
        rng = seeded_bulk_generator(self.seed)
        if self.model.upper() == "BA":
            edges = barabasi_albert(self.n, self.m_links, rng)
            pos = rng.uniform(0.0, self.plane, size=(self.n, 2))
        elif self.model.lower() == "waxman":
            pos, edges = waxman(self.n, self.alpha, self.beta, rng, self.plane)
        else:
            raise ValueError(f"unknown BRITE model {self.model!r}")
        dist = np.sqrt(
            ((pos[edges[:, 0]] - pos[edges[:, 1]]) ** 2).sum(-1)
        )
        delay_s = dist / 2e8  # propagation at ~2/3 c (fiber)
        rate = rng.uniform(self.bw_min, self.bw_max, size=len(edges))
        self.graph = BriteGraph(self.n, edges, delay_s, rate, pos)
        return self.graph

    def GetNNodesTopology(self) -> int:
        return self.graph.n if self.graph else 0

    def GetNEdgesTopology(self) -> int:
        return self.graph.m if self.graph else 0

    # --- ns-3 object construction (scalar engine path) -------------------
    def BuildTopology(self, stack_helper=None):
        """Materialize Nodes + p2p devices (+ stacks and per-link /30
        addresses when ``stack_helper`` is given).  Returns the
        NodeContainer.  Feasible to ~10k nodes; the device engine does
        not need it."""
        from tpudes.core.nstime import Time
        from tpudes.helper.containers import NodeContainer
        from tpudes.helper.internet import Ipv4AddressHelper
        from tpudes.helper.point_to_point import PointToPointHelper

        if self.graph is None:
            self.Generate()
        g = self.graph
        nodes = NodeContainer()
        nodes.Create(g.n)
        if stack_helper is not None:
            stack_helper.Install(nodes)
        addr = Ipv4AddressHelper("10.0.0.0", "255.255.255.252")
        for e in range(g.m):
            u, v = int(g.edges[e, 0]), int(g.edges[e, 1])
            p2p = PointToPointHelper()
            p2p.SetDeviceAttribute("DataRate", f"{int(g.rate_bps[e])}bps")
            p2p.SetChannelAttribute("Delay", Time(int(g.delay_s[e] * 1e9)))
            devs = p2p.Install(nodes.Get(u), nodes.Get(v))
            if stack_helper is not None:
                addr.Assign(devs)
                addr.NewNetwork()
        self._nodes = nodes
        return nodes
