"""NetDevice & Channel abstractions + the Simple* test fixtures.

Reference parity: src/network/model/net-device.{h,cc}, channel.{h,cc},
src/network/utils/simple-net-device.{h,cc}, simple-channel.{h,cc}
(SURVEY.md 2.2, 4 — SimpleNetDevice is upstream's protocol-test fixture
and serves the same role here).
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId
from tpudes.core.simulator import Simulator
from tpudes.core.nstime import Time
from tpudes.network.address import Mac48Address


class Channel(Object):
    tid = TypeId("tpudes::Channel").AddAttribute("Id", "channel id", 0, field="cid")

    _next_id = 0

    def __init__(self, **attributes):
        super().__init__(**attributes)
        Channel._next_id += 1
        self.cid = Channel._next_id
        self._devices: list = []

    def GetId(self) -> int:
        return self.cid

    def GetNDevices(self) -> int:
        return len(self._devices)

    def GetDevice(self, i: int):
        return self._devices[i]


class NetDevice(Object):
    tid = (
        TypeId("tpudes::NetDevice")
        .AddAttribute("Mtu", "Maximum transmission unit", 1500)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._node = None
        self._if_index = 0
        self._address = Mac48Address.Allocate()
        self._rx_callback = None
        self._promisc_callback = None
        self._link_up = True
        self._link_change_callbacks = []

    # --- identity / wiring ---
    def SetNode(self, node) -> None:
        self._node = node

    def GetNode(self):
        return self._node

    def SetIfIndex(self, index: int) -> None:
        self._if_index = index

    def GetIfIndex(self) -> int:
        return self._if_index

    def SetAddress(self, address) -> None:
        self._address = address

    def GetAddress(self):
        return self._address

    def GetChannel(self):
        return None

    def GetMtu(self) -> int:
        return self.mtu

    def SetMtu(self, mtu: int) -> None:
        self.mtu = mtu

    # --- link state ---
    def IsLinkUp(self) -> bool:
        return self._link_up

    def SetLinkUp(self) -> None:
        if not self._link_up:
            self._link_up = True
            for cb in self._link_change_callbacks:
                cb()

    def SetLinkDown(self) -> None:
        if self._link_up:
            self._link_up = False
            for cb in self._link_change_callbacks:
                cb()

    def AddLinkChangeCallback(self, cb) -> None:
        self._link_change_callbacks.append(cb)

    # --- capabilities (defaults; subclasses override) ---
    def IsBroadcast(self) -> bool:
        return True

    def GetBroadcast(self):
        return Mac48Address.GetBroadcast()

    def IsMulticast(self) -> bool:
        return False

    def IsPointToPoint(self) -> bool:
        return False

    def IsBridge(self) -> bool:
        return False

    def NeedsArp(self) -> bool:
        return False

    # --- tx/rx ---
    def Send(self, packet, dest, protocol: int) -> bool:
        raise NotImplementedError

    def SendFrom(self, packet, source, dest, protocol: int) -> bool:
        return self.Send(packet, dest, protocol)

    def SetReceiveCallback(self, cb) -> None:
        """cb(device, packet, protocol, sender) -> bool"""
        self._rx_callback = cb

    def SetPromiscReceiveCallback(self, cb) -> None:
        self._promisc_callback = cb

    def _deliver_up(self, packet, protocol, sender, receiver, packet_type):
        if self._promisc_callback is not None:
            self._promisc_callback(self, packet.Copy(), protocol, sender, receiver, packet_type)
        if packet_type != 3 and self._rx_callback is not None:  # 3 = OTHERHOST
            return self._rx_callback(self, packet, protocol, sender)
        if self._node is not None:
            return self._node.ReceiveFromDevice(
                self, packet, protocol, sender, receiver, packet_type
            )
        return False


class SimpleChannel(Channel):
    """Broadcast test channel with a fixed delay
    (src/network/utils/simple-channel.{h,cc})."""

    tid = (
        TypeId("tpudes::SimpleChannel")
        .SetParent(Channel.tid)
        .AddConstructor(lambda **kw: SimpleChannel(**kw))
        .AddAttribute("Delay", "Propagation delay", Time(0), field="delay", checker=Time)
    )

    def Add(self, device: "SimpleNetDevice") -> None:
        self._devices.append(device)

    def Send(self, packet, protocol, dest, sender_device) -> None:
        for dev in self._devices:
            if dev is sender_device:
                continue
            Simulator.ScheduleWithContext(
                dev.GetNode().GetId(),
                self.delay,
                dev.Receive,
                packet.Copy(),
                protocol,
                dest,
                sender_device.GetAddress(),
            )


class SimpleNetDevice(NetDevice):
    """Trivial device for protocol tests
    (src/network/utils/simple-net-device.{h,cc})."""

    tid = (
        TypeId("tpudes::SimpleNetDevice")
        .SetParent(NetDevice.tid)
        .AddConstructor(lambda **kw: SimpleNetDevice(**kw))
        .AddTraceSource("PhyRxDrop", "Packet dropped by the error model")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._channel: SimpleChannel | None = None
        self._error_model = None

    def SetChannel(self, channel: SimpleChannel) -> None:
        self._channel = channel
        channel.Add(self)

    def GetChannel(self):
        return self._channel

    def SetReceiveErrorModel(self, em) -> None:
        self._error_model = em

    def Send(self, packet, dest, protocol: int) -> bool:
        if not self._link_up or self._channel is None:
            return False
        self._channel.Send(packet, protocol, dest, self)
        return True

    def Receive(self, packet, protocol, to, from_addr) -> None:
        if self._error_model is not None and self._error_model.IsCorrupt(packet):
            self.phy_rx_drop(packet)
            return
        if to == self._address:
            packet_type = 0  # HOST
        elif getattr(to, "IsBroadcast", lambda: False)():
            packet_type = 1  # BROADCAST
        else:
            packet_type = 3  # OTHERHOST
        self._deliver_up(packet, protocol, from_addr, to, packet_type)
