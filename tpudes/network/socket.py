"""Abstract BSD-like asynchronous Socket API.

Reference parity: src/network/model/socket.{h,cc} (SURVEY.md 2.2):
callback-driven (no blocking), Bind/Connect/Send/Recv, with the same
callback set models rely on (receive, connection succeeded/failed, data
sent, send buffer space).
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId

# ns-3 Socket::SocketErrno
ERROR_NOTERROR = 0
ERROR_ISCONN = 1
ERROR_NOTCONN = 2
ERROR_MSGSIZE = 3
ERROR_AGAIN = 4
ERROR_SHUTDOWN = 5
ERROR_OPNOTSUPP = 6
ERROR_AFNOSUPPORT = 7
ERROR_INVAL = 8
ERROR_BADF = 9
ERROR_NOROUTETOHOST = 10
ERROR_NODEV = 11
ERROR_ADDRNOTAVAIL = 12
ERROR_ADDRINUSE = 13


class Socket(Object):
    tid = TypeId("tpudes::Socket")

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._node = None
        self._errno = ERROR_NOTERROR
        self._ip_tos = 0
        self._recv_callback = None
        self._connect_success_cb = None
        self._connect_fail_cb = None
        self._close_cb = None
        self._close_error_cb = None
        self._accept_request_cb = None
        self._new_connection_cb = None
        self._data_sent_cb = None
        self._send_cb = None

    # --- node wiring ---
    def SetNode(self, node) -> None:
        self._node = node

    def GetNode(self):
        return self._node

    def GetErrno(self) -> int:
        return self._errno

    # --- callbacks ---
    def SetRecvCallback(self, cb) -> None:
        """cb(socket) — data available; call Recv/RecvFrom to drain."""
        self._recv_callback = cb

    def SetConnectCallback(self, success_cb, fail_cb) -> None:
        self._connect_success_cb = success_cb
        self._connect_fail_cb = fail_cb

    def SetCloseCallbacks(self, normal_cb, error_cb) -> None:
        self._close_cb = normal_cb
        self._close_error_cb = error_cb

    def SetAcceptCallback(self, request_cb, new_connection_cb) -> None:
        self._accept_request_cb = request_cb
        self._new_connection_cb = new_connection_cb

    def SetDataSentCallback(self, cb) -> None:
        self._data_sent_cb = cb

    def SetSendCallback(self, cb) -> None:
        """cb(socket, available_bytes) — send buffer space available."""
        self._send_cb = cb

    # --- API (subclasses implement) ---
    def Bind(self, address=None) -> int:
        raise NotImplementedError

    def Connect(self, address) -> int:
        raise NotImplementedError

    def Listen(self) -> int:
        raise NotImplementedError

    def SetIpTos(self, tos: int) -> None:
        """IP TOS/DSCP for outgoing packets (socket.h SetIpTos) — the
        QoS classification input (DSCP -> UP -> EDCA access category)."""
        self._ip_tos = int(tos) & 0xFF

    def GetIpTos(self) -> int:
        return self._ip_tos

    def Send(self, packet, flags: int = 0) -> int:
        raise NotImplementedError

    def SendTo(self, packet, flags: int, to_address) -> int:
        raise NotImplementedError

    def Recv(self, max_size: int = 0xFFFFFFFF, flags: int = 0):
        raise NotImplementedError

    def RecvFrom(self, max_size: int = 0xFFFFFFFF, flags: int = 0):
        """returns (packet, from_address) or (None, None)"""
        raise NotImplementedError

    def Close(self) -> int:
        raise NotImplementedError

    def ShutdownSend(self) -> int:
        return 0

    def ShutdownRecv(self) -> int:
        return 0

    def GetTxAvailable(self) -> int:
        return 0xFFFFFFFF

    def GetRxAvailable(self) -> int:
        return 0

    def BindToNetDevice(self, device) -> None:
        self._bound_device = device

    # --- helpers for subclasses ---
    def NotifyDataRecv(self) -> None:
        if self._recv_callback is not None:
            self._recv_callback(self)

    def NotifyConnectionSucceeded(self) -> None:
        if self._connect_success_cb is not None:
            self._connect_success_cb(self)

    def NotifyConnectionFailed(self) -> None:
        if self._connect_fail_cb is not None:
            self._connect_fail_cb(self)

    def NotifyNormalClose(self) -> None:
        if self._close_cb is not None:
            self._close_cb(self)

    def NotifyErrorClose(self) -> None:
        if self._close_error_cb is not None:
            self._close_error_cb(self)

    def NotifyConnectionRequest(self, from_address) -> bool:
        if self._accept_request_cb is not None:
            return self._accept_request_cb(self, from_address)
        return True

    def NotifyNewConnectionCreated(self, socket, from_address) -> None:
        if self._new_connection_cb is not None:
            self._new_connection_cb(socket, from_address)

    def NotifyDataSent(self, size: int) -> None:
        if self._data_sent_cb is not None:
            self._data_sent_cb(self, size)

    def NotifySend(self, available: int) -> None:
        if self._send_cb is not None:
            self._send_cb(self, available)


class SocketFactory:
    """Per-node socket creation seam (src/network/model/socket-factory.h):
    ``Socket.CreateSocket(node, "tpudes::UdpSocketFactory")``."""

    @staticmethod
    def CreateSocket(node, factory_name: str) -> Socket:
        if "Udp" in factory_name:
            from tpudes.models.internet.udp import UdpL4Protocol

            udp = node.GetObject(UdpL4Protocol)
            if udp is None:
                raise RuntimeError(f"node {node.GetId()} has no UDP stack installed")
            return udp.CreateSocket()
        if "Tcp" in factory_name:
            from tpudes.models.internet.tcp import TcpL4Protocol

            tcp = node.GetObject(TcpL4Protocol)
            if tcp is None:
                raise RuntimeError(f"node {node.GetId()} has no TCP stack installed")
            return tcp.CreateSocket()
        raise ValueError(f"unknown socket factory {factory_name!r}")
