"""Pcap and ascii trace writers (the helper-trace layer).

Reference parity: src/network/helper/trace-helper.{h,cc} — the
``PcapHelperForDevice`` / ``AsciiTraceHelperForDevice`` mixin that gives
every device helper ``EnablePcap(All)`` / ``EnableAscii(All)`` — plus
src/network/utils/pcap-file{,-wrapper}.{h,cc} (upstream paths; mount
empty at survey — SURVEY.md §0, §2.10/§5.1).

The pcap writer emits the classic libpcap format (magic 0xa1b2c3d4,
version 2.4), one file per device, so the output opens in tcpdump /
wireshark / scapy unchanged.  Point-to-point devices use DLT_PPP (9),
matching upstream's PointToPointHelper::EnablePcapInternal; the frame
bytes are the device's on-air serialization (PPP framing included) via
``Packet.ToBytes`` hooked on the device's promiscuous sniffer.

Ascii tracing mirrors upstream's single-file event stream: one line per
queue/rx event — ``+`` enqueue, ``-`` dequeue, ``d`` drop, ``r``
receive — with the simulated timestamp and the config path of the
source.
"""

from __future__ import annotations

import struct

from tpudes.core.nstime import Time
from tpudes.core.simulator import Simulator

DLT_PPP = 9
DLT_IEEE802_11 = 105
DLT_RAW = 101

PCAP_MAGIC = 0xA1B2C3D4
PCAP_VERSION = (2, 4)


class PcapFileWrapper:
    """One .pcap output stream (pcap-file-wrapper.{h,cc})."""

    def __init__(self, filename: str, data_link_type: int, snap_len: int = 65535):
        self._f = open(filename, "wb")
        self.filename = filename
        self.snap_len = snap_len
        self._f.write(
            struct.pack(
                "<IHHiIII",
                PCAP_MAGIC, PCAP_VERSION[0], PCAP_VERSION[1],
                0, 0, snap_len, data_link_type,
            )
        )
        self.n_records = 0

    def Write(self, packet) -> None:
        data = packet.ToBytes()
        ts = Simulator.NowTicks()  # ns ticks
        sec, nsec = divmod(ts, 1_000_000_000)
        usec = nsec // 1000
        cap = min(len(data), self.snap_len)
        self._f.write(
            struct.pack("<IIII", sec, usec, cap, len(data)) + data[:cap]
        )
        self.n_records += 1

    def Close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


class PcapHelper:
    """Owns the open wrappers; files close at Simulator.Destroy."""

    def __init__(self):
        self._wrappers: list[PcapFileWrapper] = []

    def CreateFile(self, filename: str, data_link_type: int) -> PcapFileWrapper:
        w = PcapFileWrapper(filename, data_link_type)
        self._wrappers.append(w)
        Simulator.ScheduleDestroy(w.Close)
        return w

    @staticmethod
    def GetFilenameFromDevice(prefix: str, device) -> str:
        node = device.GetNode()
        return f"{prefix}-{node.GetId()}-{device.GetIfIndex()}.pcap"


class AsciiTraceHelper:
    """Single shared ascii stream (ascii-trace-helper idiom).

    The filename → stream cache is class-level so two EnableAscii calls
    naming the same file append to ONE handle instead of the second
    truncating the first (the upstream single-stream contract)."""

    _streams: dict[str, object] = {}

    def CreateFileStream(self, filename: str):
        f = AsciiTraceHelper._streams.get(filename)
        if f is None or f.closed:
            f = open(filename, "w")
            AsciiTraceHelper._streams[filename] = f

            def close_and_forget():
                if not f.closed:
                    f.close()
                AsciiTraceHelper._streams.pop(filename, None)

            Simulator.ScheduleDestroy(close_and_forget)
        return f

    @staticmethod
    def _line(stream, code: str, path: str, packet) -> None:
        now_s = Time(Simulator.NowTicks()).GetSeconds()
        stream.write(f"{code} {now_s:.9f} {path} {packet!r}\n")

    def HookDevice(self, stream, device) -> None:
        """Wire the standard four event letters for one device."""
        node_id = device.GetNode().GetId()
        dev_id = device.GetIfIndex()
        base = f"/NodeList/{node_id}/DeviceList/{dev_id}"
        queue = getattr(device, "GetQueue", lambda: None)()
        if queue is not None:
            queue.TraceConnectWithoutContext(
                "Enqueue",
                lambda p: self._line(stream, "+", f"{base}/TxQueue/Enqueue", p),
            )
            queue.TraceConnectWithoutContext(
                "Dequeue",
                lambda p: self._line(stream, "-", f"{base}/TxQueue/Dequeue", p),
            )
            queue.TraceConnectWithoutContext(
                "Drop",
                lambda p: self._line(stream, "d", f"{base}/TxQueue/Drop", p),
            )
        device.TraceConnectWithoutContext(
            "MacRx", lambda p: self._line(stream, "r", f"{base}/MacRx", p)
        )


class PcapHelperForDevice:
    """Mixin giving device helpers EnablePcap/EnablePcapAll
    (trace-helper.h).  Subclasses set ``pcap_dlt`` and a device
    type filter via ``_pcap_device_ok``."""

    pcap_dlt = DLT_RAW

    def _pcap_device_ok(self, device) -> bool:
        return True

    def EnablePcap(self, prefix: str, devices, promiscuous: bool = True):
        """``devices``: a NetDeviceContainer, list, or single device."""
        from tpudes.helper.containers import NetDeviceContainer

        if isinstance(devices, NetDeviceContainer):
            devices = list(devices)
        elif not isinstance(devices, (list, tuple)):
            devices = [devices]
        helper = PcapHelper()
        wrappers = []
        for dev in devices:
            if not self._pcap_device_ok(dev):
                continue
            w = helper.CreateFile(
                PcapHelper.GetFilenameFromDevice(prefix, dev), self.pcap_dlt
            )
            source = "PromiscSniffer" if promiscuous else "Sniffer"
            dev.TraceConnectWithoutContext(source, w.Write)
            wrappers.append(w)
        return wrappers

    def EnablePcapAll(self, prefix: str, promiscuous: bool = True):
        from tpudes.network.node import NodeList

        devices = []
        for i in range(NodeList.GetNNodes()):
            node = NodeList.GetNode(i)
            for d in range(node.GetNDevices()):
                devices.append(node.GetDevice(d))
        return self.EnablePcap(prefix, devices, promiscuous)

    def EnableAscii(self, filename: str, devices):
        from tpudes.helper.containers import NetDeviceContainer

        if isinstance(devices, NetDeviceContainer):
            devices = list(devices)
        elif not isinstance(devices, (list, tuple)):
            devices = [devices]
        ascii_helper = AsciiTraceHelper()
        stream = ascii_helper.CreateFileStream(filename)
        for dev in devices:
            if self._pcap_device_ok(dev):
                ascii_helper.HookDevice(stream, dev)
        return stream

    def EnableAsciiAll(self, filename: str):
        from tpudes.network.node import NodeList

        devices = []
        for i in range(NodeList.GetNNodes()):
            node = NodeList.GetNode(i)
            for d in range(node.GetNDevices()):
                devices.append(node.GetDevice(d))
        return self.EnableAscii(filename, devices)
