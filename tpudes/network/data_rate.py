"""DataRate value type with "5Mbps"-style parsing.

Reference parity: src/network/utils/data-rate.{h,cc} (SURVEY.md 2.2).
"""

from __future__ import annotations

import re

from tpudes.core.nstime import Time

_SUFFIXES = {
    "bps": 1,
    "b/s": 1,
    "kbps": 10**3,
    "kb/s": 10**3,
    "kibps": 2**10,
    "mbps": 10**6,
    "mb/s": 10**6,
    "mibps": 2**20,
    "gbps": 10**9,
    "gb/s": 10**9,
    "gibps": 2**30,
    "bs": 1,  # tolerant
}

_RATE_RE = re.compile(r"^\s*([0-9.eE+-]+)\s*([a-zA-Z/]*)\s*$")


class DataRate:
    __slots__ = ("bps",)

    def __init__(self, rate: "str | int | float | DataRate" = 0):
        if isinstance(rate, DataRate):
            self.bps = rate.bps
        elif isinstance(rate, (int, float)):
            self.bps = int(rate)
        else:
            m = _RATE_RE.match(rate)
            if not m:
                raise ValueError(f"cannot parse data rate {rate!r}")
            value = float(m.group(1))
            suffix = m.group(2).lower() or "bps"
            if suffix not in _SUFFIXES:
                raise ValueError(f"unknown data-rate unit {m.group(2)!r}")
            self.bps = int(value * _SUFFIXES[suffix])

    def GetBitRate(self) -> int:
        return self.bps

    def CalculateBytesTxTime(self, nbytes: int) -> Time:
        return self.CalculateBitsTxTime(nbytes * 8)

    def CalculateBitsTxTime(self, nbits: int) -> Time:
        # exact integer tick math: ticks = bits * ticks_per_sec / bps
        ticks_per_sec = 10 ** (-Time._res_exp)
        return Time((nbits * ticks_per_sec) // self.bps)

    def __eq__(self, other):
        return isinstance(other, DataRate) and self.bps == other.bps

    def __lt__(self, other):
        return self.bps < DataRate(other).bps

    def __hash__(self):
        return hash(("rate", self.bps))

    def __repr__(self):
        return f"DataRate({self.bps}bps)"
