"""Error models: stochastic and scripted packet corruption (the link-level
fault-injection surface).

Reference parity: src/network/utils/error-model.{h,cc} (SURVEY.md 2.2,
5.3): RateErrorModel (per-bit/byte/packet Bernoulli), ListErrorModel
(scripted losses by packet uid — the deterministic test fixture),
BurstErrorModel (correlated loss runs).
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId
from tpudes.core.rng import UniformRandomVariable


class ErrorModel(Object):
    tid = (
        TypeId("tpudes::ErrorModel")
        .AddAttribute("IsEnabled", "enable/disable the model", True, field="enabled")
    )

    def IsCorrupt(self, packet) -> bool:
        if not self.enabled:
            return False
        return self.DoCorrupt(packet)

    def DoCorrupt(self, packet) -> bool:
        raise NotImplementedError

    def Enable(self) -> None:
        self.enabled = True

    def Disable(self) -> None:
        self.enabled = False

    def Reset(self) -> None:
        self.DoReset()

    def DoReset(self) -> None:
        pass


class RateErrorModel(ErrorModel):
    ERROR_UNIT_BIT = "ERROR_UNIT_BIT"
    ERROR_UNIT_BYTE = "ERROR_UNIT_BYTE"
    ERROR_UNIT_PACKET = "ERROR_UNIT_PACKET"

    tid = (
        TypeId("tpudes::RateErrorModel")
        .SetParent(ErrorModel.tid)
        .AddConstructor(lambda **kw: RateErrorModel(**kw))
        .AddAttribute("ErrorRate", "error rate per unit", 0.0)
        .AddAttribute("ErrorUnit", "BIT, BYTE or PACKET", "ERROR_UNIT_BYTE")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._ranvar = UniformRandomVariable()

    def SetRandomVariable(self, rv) -> None:
        self._ranvar = rv

    def AssignStreams(self, stream: int) -> int:
        self._ranvar.SetStream(stream)
        return 1

    def DoCorrupt(self, packet) -> bool:
        if self.error_unit == self.ERROR_UNIT_PACKET:
            p_ok = 1.0 - self.error_rate
        elif self.error_unit == self.ERROR_UNIT_BYTE:
            p_ok = (1.0 - self.error_rate) ** packet.GetSize()
        else:
            p_ok = (1.0 - self.error_rate) ** (8 * packet.GetSize())
        return self._ranvar.GetValue() >= p_ok


class ListErrorModel(ErrorModel):
    """Corrupt exactly the listed packet uids — deterministic scripted
    losses for tests."""

    tid = (
        TypeId("tpudes::ListErrorModel")
        .SetParent(ErrorModel.tid)
        .AddConstructor(lambda **kw: ListErrorModel(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._list: set[int] = set()

    def SetList(self, uids) -> None:
        self._list = set(uids)

    def GetList(self):
        return sorted(self._list)

    def DoCorrupt(self, packet) -> bool:
        return packet.GetUid() in self._list


class ReceiveListErrorModel(ErrorModel):
    """Corrupt the Nth received packets (by arrival index)."""

    tid = (
        TypeId("tpudes::ReceiveListErrorModel")
        .SetParent(ErrorModel.tid)
        .AddConstructor(lambda **kw: ReceiveListErrorModel(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._list: set[int] = set()
        self._count = 0

    def SetList(self, indices) -> None:
        self._list = set(indices)

    def DoCorrupt(self, packet) -> bool:
        i = self._count
        self._count += 1
        return i in self._list

    def DoReset(self) -> None:
        self._count = 0


class BurstErrorModel(ErrorModel):
    """Correlated loss: when triggered, corrupts a random-length run of
    consecutive packets."""

    tid = (
        TypeId("tpudes::BurstErrorModel")
        .SetParent(ErrorModel.tid)
        .AddConstructor(lambda **kw: BurstErrorModel(**kw))
        .AddAttribute("ErrorRate", "burst start probability", 0.0, field="burst_rate")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._ranvar = UniformRandomVariable()
        self._burst_size = UniformRandomVariable(Min=1.0, Max=4.0)
        self._remaining = 0

    def SetRandomVariable(self, rv) -> None:
        self._ranvar = rv

    def SetRandomBurstSize(self, rv) -> None:
        self._burst_size = rv

    def AssignStreams(self, stream: int) -> int:
        self._ranvar.SetStream(stream)
        self._burst_size.SetStream(stream + 1)
        return 2

    def DoCorrupt(self, packet) -> bool:
        if self._remaining > 0:
            self._remaining -= 1
            return True
        if self._ranvar.GetValue() < self.burst_rate:
            self._remaining = max(0, int(self._burst_size.GetValue()) - 1)
            return True
        return False

    def DoReset(self) -> None:
        self._remaining = 0
