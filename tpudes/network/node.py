"""Node: the container of devices, protocol handlers, and applications.

Reference parity: src/network/model/node.{h,cc}, node-list.{h,cc}
(SURVEY.md 2.2). ``systemId`` is the partition key for space-parallel
runs (src/mpi partitioning; SURVEY.md 2.3) — nodes owned by another
partition only participate through remote channels.
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId
from tpudes.core.simulator import Simulator


class NodeList:
    """Global node registry; config root ``/NodeList`` (src/network/model/
    node-list.{h,cc})."""

    _nodes: list = []

    @classmethod
    def Add(cls, node) -> int:
        cls._nodes.append(node)
        return len(cls._nodes) - 1

    @classmethod
    def GetNode(cls, nid: int):
        return cls._nodes[nid]

    @classmethod
    def GetNNodes(cls) -> int:
        return len(cls._nodes)

    @classmethod
    def All(cls) -> list:
        return list(cls._nodes)

    @classmethod
    def Reset(cls) -> None:
        cls._nodes = []


# register as a Config root
from tpudes.core.config import Config  # noqa: E402

Config.RegisterRootNamespaceObject("NodeList", lambda: NodeList._nodes)


class ProtocolHandlerEntry:
    __slots__ = ("handler", "protocol", "device", "promiscuous")

    def __init__(self, handler, protocol, device, promiscuous):
        self.handler = handler
        self.protocol = protocol
        self.device = device
        self.promiscuous = promiscuous


class Node(Object):
    tid = (
        TypeId("tpudes::Node")
        .AddConstructor(lambda **kw: Node(**kw))
        .AddAttribute("DeviceList", "The list of devices on this node", None, field="devices")
        .AddAttribute("ApplicationList", "The list of applications", None, field="applications")
        .AddAttribute("Id", "The node id", 0, field="nid")
    )

    # packet types for promiscuous callbacks (ns-3 NetDevice::PacketType)
    PACKET_HOST = 0
    PACKET_BROADCAST = 1
    PACKET_MULTICAST = 2
    PACKET_OTHERHOST = 3

    def __init__(self, system_id: int = 0, **attributes):
        super().__init__(**attributes)
        self.devices = []
        self.applications = []
        self._handlers: list[ProtocolHandlerEntry] = []
        self.system_id = system_id  # MPI-rank analog: mesh-partition key
        self.nid = NodeList.Add(self)

    def GetId(self) -> int:
        return self.nid

    def GetSystemId(self) -> int:
        return self.system_id

    # --- devices ---
    def AddDevice(self, device) -> int:
        index = len(self.devices)
        self.devices.append(device)
        device.SetNode(self)
        device.SetIfIndex(index)
        return index

    def GetDevice(self, index: int):
        return self.devices[index]

    def GetNDevices(self) -> int:
        return len(self.devices)

    # --- applications ---
    def AddApplication(self, app) -> int:
        index = len(self.applications)
        self.applications.append(app)
        app.SetNode(self)
        # ns-3 schedules app initialization at time 0
        Simulator.ScheduleWithContext(self.nid, 0, app.Initialize)
        return index

    def GetApplication(self, index: int):
        return self.applications[index]

    def GetNApplications(self) -> int:
        return len(self.applications)

    # --- protocol dispatch ---
    def RegisterProtocolHandler(self, handler, protocol=0, device=None, promiscuous=False):
        """handler(device, packet, protocol, sender) called for matching
        received packets; protocol 0 = all."""
        self._handlers.append(ProtocolHandlerEntry(handler, protocol, device, promiscuous))

    def UnregisterProtocolHandler(self, handler):
        self._handlers = [e for e in self._handlers if e.handler is not handler]

    def ReceiveFromDevice(self, device, packet, protocol, sender, receiver=None, packet_type=PACKET_HOST):
        """Called by NetDevices on packet arrival; dispatches to handlers
        (ns-3 Node::ReceiveFromDevice / NonPromiscReceiveFromDevice)."""
        found = False
        for entry in self._handlers:
            if entry.device is not None and entry.device is not device:
                continue
            if entry.protocol != 0 and entry.protocol != protocol:
                continue
            if packet_type == self.PACKET_OTHERHOST and not entry.promiscuous:
                continue
            if entry.promiscuous:
                entry.handler(device, packet, protocol, sender, receiver, packet_type)
            else:
                entry.handler(device, packet, protocol, sender)
            found = True
        return found

    def __repr__(self):
        return f"Node({self.nid})"
