"""Packet & node abstractions.

Reference parity: src/network/model/ (SURVEY.md 2.2): Packet (COW buffer +
headers/tags), Node, NetDevice, Channel, Address types, Socket, Queue,
ErrorModel, DataRate.
"""

from tpudes.network.packet import Packet, Header, Trailer, Tag
from tpudes.network.address import (
    Address,
    Mac48Address,
    Ipv4Address,
    Ipv4Mask,
    Ipv6Address,
    InetSocketAddress,
)
from tpudes.network.node import Node, NodeList
from tpudes.network.net_device import NetDevice, Channel, SimpleNetDevice, SimpleChannel
from tpudes.network.queue import Queue, DropTailQueue, QueueSize
from tpudes.network.error_model import (
    ErrorModel,
    RateErrorModel,
    ListErrorModel,
    BurstErrorModel,
)
from tpudes.network.data_rate import DataRate
from tpudes.network.socket import Socket
from tpudes.network.application import Application
