"""Address types: MAC-48, IPv4 (+mask), IPv6-lite, socket addresses.

Reference parity: src/network/utils/mac48-address.{h,cc},
ipv4-address.{h,cc}, ipv6-address.{h,cc}, inet-socket-address.{h,cc}
(SURVEY.md 2.2). All value types, hashable, with the string forms ns-3
scripts use ("10.1.1.0", "255.255.255.0", "00:00:00:00:00:01").
"""

from __future__ import annotations


class Address:
    """Generic opaque address wrapper (src/network/model/address.h)."""

    __slots__ = ("value",)

    def __init__(self, value=None):
        self.value = value

    def __eq__(self, other):
        return isinstance(other, Address) and self.value == other.value

    def __hash__(self):
        return hash(self.value)

    def __repr__(self):
        return f"Address({self.value!r})"


class Mac48Address:
    __slots__ = ("addr",)
    _next = 0

    def __init__(self, addr: "str | int | Mac48Address" = 0):
        if isinstance(addr, Mac48Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr & 0xFFFFFFFFFFFF
        else:
            self.addr = int(addr.replace(":", ""), 16)

    @classmethod
    def Allocate(cls) -> "Mac48Address":
        cls._next += 1
        return cls(cls._next)

    @classmethod
    def GetBroadcast(cls) -> "Mac48Address":
        return cls(0xFFFFFFFFFFFF)

    def IsBroadcast(self) -> bool:
        return self.addr == 0xFFFFFFFFFFFF

    def IsGroup(self) -> bool:
        return bool((self.addr >> 40) & 0x01)

    def to_bytes(self) -> bytes:
        return self.addr.to_bytes(6, "big")

    @classmethod
    def from_bytes(cls, b: bytes) -> "Mac48Address":
        return cls(int.from_bytes(b[:6], "big"))

    def __eq__(self, other):
        return isinstance(other, Mac48Address) and self.addr == other.addr

    def __hash__(self):
        return hash(("mac48", self.addr))

    def __str__(self):
        b = self.to_bytes()
        return ":".join(f"{x:02x}" for x in b)

    __repr__ = __str__


class Ipv4Address:
    __slots__ = ("addr",)

    def __init__(self, addr: "str | int | Ipv4Address" = 0):
        if isinstance(addr, Ipv4Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr & 0xFFFFFFFF
        else:
            parts = addr.split(".")
            self.addr = (
                (int(parts[0]) << 24)
                | (int(parts[1]) << 16)
                | (int(parts[2]) << 8)
                | int(parts[3])
            )

    @classmethod
    def GetAny(cls) -> "Ipv4Address":
        return cls(0)

    @classmethod
    def GetBroadcast(cls) -> "Ipv4Address":
        return cls(0xFFFFFFFF)

    @classmethod
    def GetLoopback(cls) -> "Ipv4Address":
        return cls("127.0.0.1")

    def IsBroadcast(self) -> bool:
        return self.addr == 0xFFFFFFFF

    def IsAny(self) -> bool:
        return self.addr == 0

    def IsLocalhost(self) -> bool:
        return (self.addr >> 24) == 127

    def IsMulticast(self) -> bool:
        return 0xE0000000 <= self.addr <= 0xEFFFFFFF

    def CombineMask(self, mask: "Ipv4Mask") -> "Ipv4Address":
        return Ipv4Address(self.addr & mask.mask)

    def GetSubnetDirectedBroadcast(self, mask: "Ipv4Mask") -> "Ipv4Address":
        return Ipv4Address(self.addr | (~mask.mask & 0xFFFFFFFF))

    def to_bytes(self) -> bytes:
        return self.addr.to_bytes(4, "big")

    @classmethod
    def from_bytes(cls, b: bytes) -> "Ipv4Address":
        return cls(int.from_bytes(b[:4], "big"))

    def __eq__(self, other):
        return isinstance(other, Ipv4Address) and self.addr == other.addr

    def __lt__(self, other):
        return self.addr < other.addr

    def __hash__(self):
        return hash(("ipv4", self.addr))

    def __str__(self):
        a = self.addr
        return f"{a >> 24 & 0xFF}.{a >> 16 & 0xFF}.{a >> 8 & 0xFF}.{a & 0xFF}"

    __repr__ = __str__


class Ipv4Mask:
    __slots__ = ("mask",)

    def __init__(self, mask: "str | int | Ipv4Mask" = 0):
        if isinstance(mask, Ipv4Mask):
            self.mask = mask.mask
        elif isinstance(mask, int):
            self.mask = mask & 0xFFFFFFFF
        elif mask.startswith("/"):
            n = int(mask[1:])
            self.mask = (0xFFFFFFFF << (32 - n)) & 0xFFFFFFFF if n else 0
        else:
            self.mask = Ipv4Address(mask).addr

    def IsMatch(self, a: Ipv4Address, b: Ipv4Address) -> bool:
        return (a.addr & self.mask) == (b.addr & self.mask)

    def GetPrefixLength(self) -> int:
        return bin(self.mask).count("1")

    @classmethod
    def GetOnes(cls) -> "Ipv4Mask":
        return cls(0xFFFFFFFF)

    @classmethod
    def GetZero(cls) -> "Ipv4Mask":
        return cls(0)

    def __eq__(self, other):
        return isinstance(other, Ipv4Mask) and self.mask == other.mask

    def __hash__(self):
        return hash(("mask", self.mask))

    def __str__(self):
        return str(Ipv4Address(self.mask))

    __repr__ = __str__


class Ipv6Address:
    """Minimal IPv6 value type (full v6 stack is out-of-scope this round;
    the type exists so APIs carrying it have the right shape)."""

    __slots__ = ("addr",)

    def __init__(self, addr: "str | int" = 0):
        if isinstance(addr, Ipv6Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr
        else:
            # minimal :: expansion parser
            s = addr
            if "::" in s:
                head, _, tail = s.partition("::")
                h = [p for p in head.split(":") if p]
                t = [p for p in tail.split(":") if p]
                parts = h + ["0"] * (8 - len(h) - len(t)) + t
            else:
                parts = s.split(":")
            self.addr = 0
            for p in parts:
                self.addr = (self.addr << 16) | int(p or "0", 16)

    @classmethod
    def GetAny(cls) -> "Ipv6Address":
        return cls(0)

    def __eq__(self, other):
        return isinstance(other, Ipv6Address) and self.addr == other.addr

    def __hash__(self):
        return hash(("ipv6", self.addr))

    def __str__(self):
        groups = [(self.addr >> (16 * (7 - i))) & 0xFFFF for i in range(8)]
        return ":".join(f"{g:x}" for g in groups)

    __repr__ = __str__


class InetSocketAddress:
    """(Ipv4Address, port) pair (src/network/utils/inet-socket-address.h)."""

    __slots__ = ("ipv4", "port")

    def __init__(self, ipv4: "Ipv4Address | str | int", port: int = 0):
        self.ipv4 = Ipv4Address(ipv4) if not isinstance(ipv4, Ipv4Address) else ipv4
        self.port = port

    def GetIpv4(self) -> Ipv4Address:
        return self.ipv4

    def GetPort(self) -> int:
        return self.port

    def __eq__(self, other):
        return (
            isinstance(other, InetSocketAddress)
            and self.ipv4 == other.ipv4
            and self.port == other.port
        )

    def __hash__(self):
        return hash((self.ipv4, self.port))

    def __repr__(self):
        return f"{self.ipv4}:{self.port}"
