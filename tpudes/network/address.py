"""Address types: MAC-48, IPv4 (+mask), IPv6-lite, socket addresses.

Reference parity: src/network/utils/mac48-address.{h,cc},
ipv4-address.{h,cc}, ipv6-address.{h,cc}, inet-socket-address.{h,cc}
(SURVEY.md 2.2). All value types, hashable, with the string forms ns-3
scripts use ("10.1.1.0", "255.255.255.0", "00:00:00:00:00:01").
"""

from __future__ import annotations


class Address:
    """Generic opaque address wrapper (src/network/model/address.h)."""

    __slots__ = ("value",)

    def __init__(self, value=None):
        self.value = value

    def __eq__(self, other):
        return isinstance(other, Address) and self.value == other.value

    def __hash__(self):
        return hash(self.value)

    def __repr__(self):
        return f"Address({self.value!r})"


class Mac48Address:
    __slots__ = ("addr",)
    _next = 0

    def __init__(self, addr: "str | int | Mac48Address" = 0):
        if isinstance(addr, Mac48Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr & 0xFFFFFFFFFFFF
        else:
            self.addr = int(addr.replace(":", ""), 16)

    @classmethod
    def Allocate(cls) -> "Mac48Address":
        cls._next += 1
        return cls(cls._next)

    @classmethod
    def GetBroadcast(cls) -> "Mac48Address":
        return cls(0xFFFFFFFFFFFF)

    def IsBroadcast(self) -> bool:
        return self.addr == 0xFFFFFFFFFFFF

    def IsGroup(self) -> bool:
        return bool((self.addr >> 40) & 0x01)

    def to_bytes(self) -> bytes:
        return self.addr.to_bytes(6, "big")

    @classmethod
    def from_bytes(cls, b: bytes) -> "Mac48Address":
        return cls(int.from_bytes(b[:6], "big"))

    def __eq__(self, other):
        return isinstance(other, Mac48Address) and self.addr == other.addr

    def __hash__(self):
        return hash(("mac48", self.addr))

    def __str__(self):
        b = self.to_bytes()
        return ":".join(f"{x:02x}" for x in b)

    __repr__ = __str__


class Ipv4Address:
    __slots__ = ("addr",)

    def __init__(self, addr: "str | int | Ipv4Address" = 0):
        if isinstance(addr, Ipv4Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr & 0xFFFFFFFF
        else:
            parts = addr.split(".")
            self.addr = (
                (int(parts[0]) << 24)
                | (int(parts[1]) << 16)
                | (int(parts[2]) << 8)
                | int(parts[3])
            )

    @classmethod
    def GetAny(cls) -> "Ipv4Address":
        return cls(0)

    @classmethod
    def GetBroadcast(cls) -> "Ipv4Address":
        return cls(0xFFFFFFFF)

    @classmethod
    def GetLoopback(cls) -> "Ipv4Address":
        return cls("127.0.0.1")

    def IsBroadcast(self) -> bool:
        return self.addr == 0xFFFFFFFF

    def IsAny(self) -> bool:
        return self.addr == 0

    def IsLocalhost(self) -> bool:
        return (self.addr >> 24) == 127

    def IsMulticast(self) -> bool:
        return 0xE0000000 <= self.addr <= 0xEFFFFFFF

    def CombineMask(self, mask: "Ipv4Mask") -> "Ipv4Address":
        return Ipv4Address(self.addr & mask.mask)

    def GetSubnetDirectedBroadcast(self, mask: "Ipv4Mask") -> "Ipv4Address":
        return Ipv4Address(self.addr | (~mask.mask & 0xFFFFFFFF))

    def to_bytes(self) -> bytes:
        return self.addr.to_bytes(4, "big")

    @classmethod
    def from_bytes(cls, b: bytes) -> "Ipv4Address":
        return cls(int.from_bytes(b[:4], "big"))

    def __eq__(self, other):
        return isinstance(other, Ipv4Address) and self.addr == other.addr

    def __lt__(self, other):
        return self.addr < other.addr

    def __hash__(self):
        return hash(("ipv4", self.addr))

    def __str__(self):
        a = self.addr
        return f"{a >> 24 & 0xFF}.{a >> 16 & 0xFF}.{a >> 8 & 0xFF}.{a & 0xFF}"

    __repr__ = __str__


class Ipv4Mask:
    __slots__ = ("mask",)

    def __init__(self, mask: "str | int | Ipv4Mask" = 0):
        if isinstance(mask, Ipv4Mask):
            self.mask = mask.mask
        elif isinstance(mask, int):
            self.mask = mask & 0xFFFFFFFF
        elif mask.startswith("/"):
            n = int(mask[1:])
            self.mask = (0xFFFFFFFF << (32 - n)) & 0xFFFFFFFF if n else 0
        else:
            self.mask = Ipv4Address(mask).addr

    def IsMatch(self, a: Ipv4Address, b: Ipv4Address) -> bool:
        return (a.addr & self.mask) == (b.addr & self.mask)

    def GetPrefixLength(self) -> int:
        return bin(self.mask).count("1")

    @classmethod
    def GetOnes(cls) -> "Ipv4Mask":
        return cls(0xFFFFFFFF)

    @classmethod
    def GetZero(cls) -> "Ipv4Mask":
        return cls(0)

    def __eq__(self, other):
        return isinstance(other, Ipv4Mask) and self.mask == other.mask

    def __hash__(self):
        return hash(("mask", self.mask))

    def __str__(self):
        return str(Ipv4Address(self.mask))

    __repr__ = __str__


class Ipv6Address:
    """Minimal IPv6 value type (full v6 stack is out-of-scope this round;
    the type exists so APIs carrying it have the right shape)."""

    __slots__ = ("addr",)

    def __init__(self, addr: "str | int" = 0):
        if isinstance(addr, Ipv6Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr
        else:
            # minimal :: expansion parser
            s = addr
            if "::" in s:
                head, _, tail = s.partition("::")
                h = [p for p in head.split(":") if p]
                t = [p for p in tail.split(":") if p]
                parts = h + ["0"] * (8 - len(h) - len(t)) + t
            else:
                parts = s.split(":")
            self.addr = 0
            for p in parts:
                self.addr = (self.addr << 16) | int(p or "0", 16)

    @classmethod
    def GetAny(cls) -> "Ipv6Address":
        return cls(0)

    def __eq__(self, other):
        return isinstance(other, Ipv6Address) and self.addr == other.addr

    def __hash__(self):
        return hash(("ipv6", self.addr))

    def __str__(self):
        groups = [(self.addr >> (16 * (7 - i))) & 0xFFFF for i in range(8)]
        return ":".join(f"{g:x}" for g in groups)

    __repr__ = __str__


class InetSocketAddress:
    """(Ipv4Address, port) pair (src/network/utils/inet-socket-address.h)."""

    __slots__ = ("ipv4", "port")

    def __init__(self, ipv4: "Ipv4Address | str | int", port: int = 0):
        self.ipv4 = Ipv4Address(ipv4) if not isinstance(ipv4, Ipv4Address) else ipv4
        self.port = port

    def GetIpv4(self) -> Ipv4Address:
        return self.ipv4

    def GetPort(self) -> int:
        return self.port

    def __eq__(self, other):
        return (
            isinstance(other, InetSocketAddress)
            and self.ipv4 == other.ipv4
            and self.port == other.port
        )

    def __hash__(self):
        return hash((self.ipv4, self.port))

    def __repr__(self):
        return f"{self.ipv4}:{self.port}"


class Ipv6Address:
    """128-bit IPv6 address (src/network/utils/ipv6-address.{h,cc}).

    Stored as one int; parsing/formatting via the stdlib ``ipaddress``
    module (RFC 4291 text forms incl. '::' compression)."""

    __slots__ = ("addr",)

    def __init__(self, addr: "str | int | bytes | Ipv6Address" = 0):
        if isinstance(addr, Ipv6Address):
            self.addr = addr.addr
        elif isinstance(addr, int):
            self.addr = addr & (1 << 128) - 1
        elif isinstance(addr, bytes):
            self.addr = int.from_bytes(addr[:16], "big")
        else:
            import ipaddress

            self.addr = int(ipaddress.IPv6Address(addr))

    @classmethod
    def GetAny(cls) -> "Ipv6Address":
        return cls(0)

    @classmethod
    def GetLoopback(cls) -> "Ipv6Address":
        return cls(1)

    @classmethod
    def GetAllNodesMulticast(cls) -> "Ipv6Address":
        return cls("ff02::1")

    @classmethod
    def GetAllRoutersMulticast(cls) -> "Ipv6Address":
        return cls("ff02::2")

    @classmethod
    def MakeAutoconfiguredLinkLocalAddress(cls, mac: Mac48Address) -> "Ipv6Address":
        """fe80::/64 + modified EUI-64 from the MAC (RFC 4291 app. A)."""
        return cls((0xFE80 << 112) | cls._eui64(mac))

    @classmethod
    def MakeAutoconfiguredAddress(cls, mac: Mac48Address, prefix: "Ipv6Address") -> "Ipv6Address":
        return cls((Ipv6Address(prefix).addr & ~((1 << 64) - 1)) | cls._eui64(mac))

    @staticmethod
    def _eui64(mac: Mac48Address) -> int:
        b = mac.to_bytes()
        eui = bytes([b[0] ^ 0x02, b[1], b[2], 0xFF, 0xFE, b[3], b[4], b[5]])
        return int.from_bytes(eui, "big")

    @classmethod
    def MakeSolicitedAddress(cls, addr: "Ipv6Address") -> "Ipv6Address":
        """ff02::1:ffXX:XXXX from the target's low 24 bits (RFC 4291)."""
        return cls(int(cls("ff02::1:ff00:0")) | (Ipv6Address(addr).addr & 0xFFFFFF))

    def IsAny(self) -> bool:
        return self.addr == 0

    def IsLoopback(self) -> bool:
        return self.addr == 1

    IsLocalhost = IsLoopback

    def IsBroadcast(self) -> bool:
        return False  # IPv6 has no broadcast

    def IsMulticast(self) -> bool:
        return (self.addr >> 120) == 0xFF

    def IsLinkLocal(self) -> bool:
        return (self.addr >> 118) == (0xFE80 >> 6)

    def IsSolicitedMulticast(self) -> bool:
        return (self.addr >> 24) == (int(Ipv6Address("ff02::1:ff00:0")) >> 24)

    def CombinePrefix(self, prefix: "Ipv6Prefix") -> "Ipv6Address":
        return Ipv6Address(self.addr & prefix.mask_int())

    def to_bytes(self) -> bytes:
        return self.addr.to_bytes(16, "big")

    @classmethod
    def from_bytes(cls, b: bytes) -> "Ipv6Address":
        return cls(int.from_bytes(b[:16], "big"))

    def __int__(self):
        return self.addr

    def __eq__(self, other):
        return isinstance(other, Ipv6Address) and self.addr == other.addr

    def __hash__(self):
        return hash(("ipv6", self.addr))

    def __str__(self):
        import ipaddress

        return str(ipaddress.IPv6Address(self.addr))

    __repr__ = __str__


class Ipv6Prefix:
    """Prefix length (src/network/utils/ipv6-address.h Ipv6Prefix)."""

    __slots__ = ("length",)

    def __init__(self, length: "int | Ipv6Prefix" = 64):
        self.length = length.length if isinstance(length, Ipv6Prefix) else int(length)

    def mask_int(self) -> int:
        if self.length <= 0:
            return 0
        return ((1 << self.length) - 1) << (128 - self.length)

    def GetPrefixLength(self) -> int:
        return self.length

    def IsMatch(self, a: Ipv6Address, b: Ipv6Address) -> bool:
        m = self.mask_int()
        return (Ipv6Address(a).addr & m) == (Ipv6Address(b).addr & m)

    def __eq__(self, other):
        return isinstance(other, Ipv6Prefix) and self.length == other.length

    def __hash__(self):
        return hash(("ipv6prefix", self.length))

    def __repr__(self):
        return f"/{self.length}"


class Inet6SocketAddress:
    """(Ipv6Address, port) pair (src/network/utils/inet6-socket-address.h)."""

    __slots__ = ("ipv6", "port")

    def __init__(self, ipv6: "Ipv6Address | str | int", port: int = 0):
        self.ipv6 = ipv6 if isinstance(ipv6, Ipv6Address) else Ipv6Address(ipv6)
        self.port = port

    def GetIpv6(self) -> Ipv6Address:
        return self.ipv6

    def GetPort(self) -> int:
        return self.port

    def __eq__(self, other):
        return (
            isinstance(other, Inet6SocketAddress)
            and self.ipv6 == other.ipv6
            and self.port == other.port
        )

    def __hash__(self):
        return hash((self.ipv6, self.port))

    def __repr__(self):
        return f"[{self.ipv6}]:{self.port}"
