"""Packet queues.

Reference parity: src/network/model/queue.{h,cc},
src/network/utils/drop-tail-queue.{h,cc}, queue-size.{h,cc}
(SURVEY.md 2.2).
"""

from __future__ import annotations

import re
from collections import deque

from tpudes.core.object import Object, TypeId

_QS_RE = re.compile(r"^\s*([0-9]+)\s*(p|B|kB|MB|Kib|Mib)?\s*$")


class QueueSize:
    """"100p" (packets) or "64kB" (bytes) — src/network/utils/queue-size.h."""

    PACKETS = "p"
    BYTES = "B"

    __slots__ = ("mode", "value")

    def __init__(self, spec: "str | QueueSize" = "100p"):
        if isinstance(spec, QueueSize):
            self.mode, self.value = spec.mode, spec.value
            return
        m = _QS_RE.match(spec)
        if not m:
            raise ValueError(f"cannot parse queue size {spec!r}")
        value, unit = int(m.group(1)), m.group(2) or "p"
        if unit == "p":
            self.mode, self.value = self.PACKETS, value
        else:
            mult = {"B": 1, "kB": 1000, "MB": 10**6, "Kib": 1024, "Mib": 2**20}[unit]
            self.mode, self.value = self.BYTES, value * mult

    def GetValue(self) -> int:
        return self.value

    def __repr__(self):
        return f"QueueSize({self.value}{'p' if self.mode == self.PACKETS else 'B'})"


class Queue(Object):
    tid = (
        TypeId("tpudes::Queue")
        .AddAttribute("MaxSize", "Max queue size", "100p", field="max_size", checker=QueueSize)
        .AddTraceSource("Enqueue", "packet enqueued")
        .AddTraceSource("Dequeue", "packet dequeued")
        .AddTraceSource("Drop", "packet dropped")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._q: deque = deque()
        self._nbytes = 0
        self.total_received_packets = 0
        self.total_dropped_packets = 0

    def GetNPackets(self) -> int:
        return len(self._q)

    def GetNBytes(self) -> int:
        return self._nbytes

    def IsEmpty(self) -> bool:
        return not self._q

    def _would_overflow(self, packet) -> bool:
        if self.max_size.mode == QueueSize.PACKETS:
            return len(self._q) + 1 > self.max_size.value
        return self._nbytes + packet.GetSize() > self.max_size.value

    def Enqueue(self, packet) -> bool:
        self.total_received_packets += 1
        if self._would_overflow(packet):
            self.total_dropped_packets += 1
            self.drop(packet)
            return False
        self._q.append(packet)
        self._nbytes += packet.GetSize()
        self.enqueue(packet)
        return True

    def Dequeue(self):
        if not self._q:
            return None
        packet = self._q.popleft()
        self._nbytes -= packet.GetSize()
        self.dequeue(packet)
        return packet

    def Peek(self):
        return self._q[0] if self._q else None

    def Flush(self) -> None:
        while self._q:
            self.Dequeue()


class DropTailQueue(Queue):
    """FIFO with tail drop (src/network/utils/drop-tail-queue.{h,cc})."""

    tid = (
        TypeId("tpudes::DropTailQueue")
        .SetParent(Queue.tid)
        .AddConstructor(lambda **kw: DropTailQueue(**kw))
    )
