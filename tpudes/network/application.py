"""Application base class with scheduled start/stop.

Reference parity: src/network/model/application.{h,cc} (SURVEY.md 2.2).
"""

from __future__ import annotations

from tpudes.core.event import EventId
from tpudes.core.nstime import Time
from tpudes.core.object import Object, TypeId
from tpudes.core.simulator import Simulator


class Application(Object):
    tid = (
        TypeId("tpudes::Application")
        .AddAttribute("StartTime", "app start time", Time(0), checker=Time)
        .AddAttribute("StopTime", "app stop time (0 = never)", Time(0), checker=Time)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._node = None
        self._started = False
        self._start_event = EventId()
        self._stop_event = EventId()

    def SetNode(self, node) -> None:
        self._node = node

    def GetNode(self):
        return self._node

    def SetStartTime(self, start: Time) -> None:
        self.start_time = Time(start)

    def SetStopTime(self, stop: Time) -> None:
        self.stop_time = Time(stop)

    def DoInitialize(self) -> None:
        # Applications self-schedule their Start/Stop at Initialize (t=0);
        # the EventIds are held so DoDispose can Cancel them (upstream
        # Application::DoDispose cancels m_startEvent/m_stopEvent — a
        # disposed app must never start)
        delay = self.start_time - Simulator.Now()
        self._start_event = Simulator.Schedule(Time(max(0, delay.ticks)), self._start)
        if self.stop_time.ticks > 0:
            delay = self.stop_time - Simulator.Now()
            self._stop_event = Simulator.Schedule(Time(max(0, delay.ticks)), self._stop)

    def DoDispose(self) -> None:
        # upstream Application::DoDispose: cancel the pending start/stop
        # (a disposed app must never start); StopApplication is NOT
        # called here, matching ns-3
        self._start_event.Cancel()
        self._stop_event.Cancel()
        super().DoDispose()

    def _start(self):
        self._started = True
        self.StartApplication()

    def _stop(self):
        if self._started:
            self._started = False
        self.StopApplication()

    def StartApplication(self) -> None:
        pass

    def StopApplication(self) -> None:
        pass
