"""Packet: virtual byte buffer with headers/trailers/tags, value semantics.

Reference parity: src/network/model/packet.{h,cc}, buffer.{h,cc},
header.h, trailer.h, tag.h, packet-metadata.{h,cc} (SURVEY.md 2.2).

Design (idiomatic Python, same capabilities):
- The payload is *virtual* (a size of zero-filled bytes, or real bytes if
  provided) exactly as ns-3's default.
- Headers/trailers are kept *structured* (immutable tuples of Header
  objects) rather than eagerly serialized — the common simulation path
  never needs the wire bytes, and immutability gives ns-3's
  copy-on-write value semantics for free: ``Copy()`` is O(1).
- ``ToBytes``/``FromBytes`` provide the real on-the-wire serialization
  (pcap writing, cross-partition packet transport — the MPI-serialization
  analog in SURVEY.md 2.3).
"""

from __future__ import annotations

import struct


class Header:
    """Base protocol header (src/network/model/header.h). Subclasses
    define fields, GetSerializedSize, Serialize -> bytes and
    classmethod Deserialize(bytes) -> (header, consumed)."""

    def GetSerializedSize(self) -> int:
        return len(self.Serialize())

    def Serialize(self) -> bytes:
        return b""

    @classmethod
    def Deserialize(cls, data: bytes):
        return cls(), 0

    def __repr__(self):
        fields = ", ".join(
            f"{k}={v!r}" for k, v in vars(self).items() if not k.startswith("_")
        )
        return f"{type(self).__name__}({fields})"


class Trailer(Header):
    """Base protocol trailer (src/network/model/trailer.h)."""


class Tag:
    """Base packet/byte tag (src/network/model/tag.h) — small value
    annotations carried alongside the bytes."""


class ByteTag:
    __slots__ = ("tag", "start", "end")

    def __init__(self, tag: Tag, start: int, end: int):
        self.tag = tag
        self.start = start
        self.end = end


_next_uid = [0]


class Packet:
    """A network packet with ns-3 value semantics."""

    __slots__ = ("_headers", "_trailers", "_payload", "_payload_size", "_packet_tags", "_byte_tags", "_uid")

    def __init__(self, payload: "int | bytes" = 0):
        self._headers: tuple = ()
        self._trailers: tuple = ()
        if isinstance(payload, (bytes, bytearray)):
            self._payload = bytes(payload)
            self._payload_size = len(self._payload)
        else:
            self._payload = None  # virtual zero-filled
            self._payload_size = int(payload)
        self._packet_tags: tuple = ()
        self._byte_tags: tuple = ()
        _next_uid[0] += 1
        self._uid = _next_uid[0]

    # --- size ---
    def GetSize(self) -> int:
        return (
            self._payload_size
            + sum(h.GetSerializedSize() for h in self._headers)
            + sum(t.GetSerializedSize() for t in self._trailers)
        )

    def GetUid(self) -> int:
        return self._uid

    # --- headers (front) ---
    def AddHeader(self, header: Header) -> None:
        self._headers = (header,) + self._headers

    def RemoveHeader(self, header_cls=None):
        """Pop the front header. With a class argument, asserts the type
        (ns-3 deserializes into the caller's header object; here the
        header instance is returned)."""
        if not self._headers:
            raise IndexError("packet has no headers")
        h = self._headers[0]
        if header_cls is not None and not isinstance(h, header_cls):
            raise TypeError(f"front header is {type(h).__name__}, expected {header_cls.__name__}")
        self._headers = self._headers[1:]
        return h

    def PeekHeader(self, header_cls=None):
        if not self._headers:
            return None
        h = self._headers[0]
        if header_cls is not None and not isinstance(h, header_cls):
            return None
        return h

    def FindHeader(self, header_cls):
        """Scan all headers for one of the given type (metadata walk)."""
        for h in self._headers:
            if isinstance(h, header_cls):
                return h
        return None

    # --- trailers (back) ---
    def AddTrailer(self, trailer: Trailer) -> None:
        self._trailers = self._trailers + (trailer,)

    def RemoveTrailer(self, trailer_cls=None):
        if not self._trailers:
            raise IndexError("packet has no trailers")
        t = self._trailers[-1]
        if trailer_cls is not None and not isinstance(t, trailer_cls):
            raise TypeError(f"back trailer is {type(t).__name__}")
        self._trailers = self._trailers[:-1]
        return t

    def PeekTrailer(self, trailer_cls=None):
        if not self._trailers:
            return None
        t = self._trailers[-1]
        if trailer_cls is not None and not isinstance(t, trailer_cls):
            return None
        return t

    # --- packet tags (whole-packet annotations) ---
    def AddPacketTag(self, tag: Tag) -> None:
        self._packet_tags = self._packet_tags + (tag,)

    def PeekPacketTag(self, tag_cls):
        for t in self._packet_tags:
            if isinstance(t, tag_cls):
                return t
        return None

    def RemovePacketTag(self, tag_cls):
        for t in self._packet_tags:
            if isinstance(t, tag_cls):
                self._packet_tags = tuple(x for x in self._packet_tags if x is not t)
                return t
        return None

    def RemoveAllPacketTags(self) -> None:
        self._packet_tags = ()

    # --- byte tags (range annotations; ranges kept whole-packet here) ---
    def AddByteTag(self, tag: Tag) -> None:
        self._byte_tags = self._byte_tags + (ByteTag(tag, 0, self.GetSize()),)

    def GetByteTags(self) -> tuple:
        return self._byte_tags

    def FindFirstMatchingByteTag(self, tag_cls):
        for bt in self._byte_tags:
            if isinstance(bt.tag, tag_cls):
                return bt.tag
        return None

    # --- value semantics ---
    def Copy(self) -> "Packet":
        """O(1): all internal state is immutable tuples (the COW analog)."""
        p = Packet.__new__(Packet)
        p._headers = self._headers
        p._trailers = self._trailers
        p._payload = self._payload
        p._payload_size = self._payload_size
        p._packet_tags = self._packet_tags
        p._byte_tags = self._byte_tags
        p._uid = self._uid
        return p

    def CreateFragment(self, start: int, length: int) -> "Packet":
        """Byte-range fragment of the serialized form (used by
        fragmentation); returns a raw-payload packet."""
        data = self.ToBytes()[start : start + length]
        return Packet(data)

    # --- wire serialization ---
    def ToBytes(self) -> bytes:
        parts = [h.Serialize() for h in self._headers]
        if self._payload is not None:
            parts.append(self._payload)
        else:
            parts.append(b"\x00" * self._payload_size)
        parts.extend(t.Serialize() for t in self._trailers)
        return b"".join(parts)

    def GetPayload(self) -> bytes:
        return self._payload if self._payload is not None else b"\x00" * self._payload_size

    def __repr__(self):
        names = [type(h).__name__ for h in self._headers]
        return f"Packet(uid={self._uid}, size={self.GetSize()}, headers={names})"


class LlcSnapHeader(Header):
    """8-byte LLC/SNAP header (src/network/utils/llc-snap-header.{h,cc}),
    used by CSMA/WiFi to carry the EtherType."""

    def __init__(self, ether_type: int = 0x0800):
        self.ether_type = ether_type

    def GetSerializedSize(self) -> int:
        return 8

    def Serialize(self) -> bytes:
        return struct.pack("!BBB3sH", 0xAA, 0xAA, 0x03, b"\x00\x00\x00", self.ether_type)

    @classmethod
    def Deserialize(cls, data: bytes):
        (_, _, _, _, et) = struct.unpack("!BBB3sH", data[:8])
        return cls(et), 8
