"""Config namespace: path-based attribute get/set/connect with wildcards,
plus the Names object-naming registry.

Reference parity: src/core/model/config.{h,cc}, names.{h,cc}
(SURVEY.md 2.1). Paths look like
``/NodeList/3/DeviceList/0/Phy/TxPowerStart`` or with wildcards
``/NodeList/*/DeviceList/*/Phy/PhyRxDrop``; ``$TypeName`` segments cast
through object aggregation, as in ns-3.

Path resolution walks: config roots ("NodeList", Names) -> list indices /
wildcards -> attributes whose values are Objects or lists of Objects ->
leaf attribute (Set/Get) or trace source (Connect).
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId, set_default


class Names:
    """Hierarchical object naming (src/core/model/names.{h,cc})."""

    _by_name: dict[str, object] = {}
    _by_obj: dict[int, str] = {}

    @classmethod
    def Add(cls, name: str, obj) -> None:
        name = name.lstrip("/")
        if name.startswith("Names/"):
            name = name[len("Names/"):]
        cls._by_name[name] = obj
        cls._by_obj[id(obj)] = name

    @classmethod
    def Find(cls, name: str):
        name = name.lstrip("/")
        if name.startswith("Names/"):
            name = name[len("Names/"):]
        return cls._by_name.get(name)

    @classmethod
    def FindName(cls, obj) -> str | None:
        return cls._by_obj.get(id(obj))

    @classmethod
    def Clear(cls) -> None:
        cls._by_name.clear()
        cls._by_obj.clear()


class Config:
    # root name -> zero-arg callable returning a list of objects
    _roots: dict[str, callable] = {}

    @classmethod
    def RegisterRootNamespaceObject(cls, name: str, provider) -> None:
        cls._roots[name] = provider

    # --- resolution ---
    @classmethod
    def _resolve(cls, path: str):
        """Resolve all but the last path segment; return (objects, leaf)."""
        tokens = [t for t in path.split("/") if t]
        if not tokens:
            raise ValueError(f"bad config path {path!r}")
        leaf = tokens[-1]
        steps = tokens[:-1]
        current: list = []
        if not steps:
            raise ValueError(f"config path too short: {path!r}")
        # first token: a root namespace or Names
        first = steps[0]
        if first == "Names":
            obj = Names.Find("/".join(steps[1:] or [leaf]))
            if obj is None:
                return [], leaf
            if steps[1:]:
                current = [obj]
                steps = []
            else:
                return [obj], leaf
        elif first in cls._roots:
            current = [cls._roots[first]()]
            steps = steps[1:]
        else:
            raise ValueError(f"unknown config root {first!r} in {path!r}")
        for tok in steps:
            nxt: list = []
            for obj in current:
                nxt.extend(cls._step(obj, tok))
            current = nxt
        return current, leaf

    @staticmethod
    def _step(obj, tok: str) -> list:
        # list indexing / wildcard
        if isinstance(obj, (list, tuple)):
            if tok == "*":
                return list(obj)
            if tok.isdigit():
                i = int(tok)
                return [obj[i]] if i < len(obj) else []
            # apply the token to each element instead
            out = []
            for el in obj:
                out.extend(Config._step(el, tok))
            return out
        # aggregation cast
        if tok.startswith("$"):
            tid = TypeId.LookupByNameFailSafe(tok[1:])
            if tid is None or not isinstance(obj, Object):
                return []
            found = obj.GetObject(tid)
            return [found] if found is not None else []
        # attribute whose value is an object / list of objects
        tid = type(obj).GetTypeId() if hasattr(type(obj), "GetTypeId") else None
        if tid is not None:
            spec = tid.LookupAttribute(tok)
            if spec is not None:
                val = getattr(obj, spec.field)
                if isinstance(val, (list, tuple)):
                    return [list(val)]
                return [val] if val is not None else []
        # plain python attribute fallback (e.g. helper-exposed children)
        val = getattr(obj, tok, None)
        if val is None:
            return []
        if isinstance(val, (list, tuple)):
            return [list(val)]
        return [val]

    # --- public API ---
    @classmethod
    def Set(cls, path: str, value) -> None:
        objs, leaf = cls._resolve(path)
        if not objs:
            raise ValueError(f"config path matched nothing: {path!r}")
        for obj in objs:
            obj.SetAttribute(leaf, value)

    @classmethod
    def SetFailSafe(cls, path: str, value) -> bool:
        try:
            objs, leaf = cls._resolve(path)
        except ValueError:
            return False
        ok = False
        for obj in objs:
            ok = obj.SetAttributeFailSafe(leaf, value) or ok
        return ok

    @classmethod
    def Get(cls, path: str) -> list:
        objs, leaf = cls._resolve(path)
        return [obj.GetAttribute(leaf) for obj in objs]

    @classmethod
    def Connect(cls, path: str, cb) -> None:
        """Connect with the matched path string prepended as context."""
        objs, leaf = cls._resolve(path)
        if not objs:
            raise ValueError(f"config path matched nothing: {path!r}")
        for obj in objs:
            if not obj.TraceConnect(leaf, path, cb):
                raise ValueError(f"no trace source {leaf!r} at {path!r}")

    @classmethod
    def ConnectWithoutContext(cls, path: str, cb) -> None:
        objs, leaf = cls._resolve(path)
        if not objs:
            raise ValueError(f"config path matched nothing: {path!r}")
        for obj in objs:
            if not obj.TraceConnectWithoutContext(leaf, cb):
                raise ValueError(f"no trace source {leaf!r} at {path!r}")

    @classmethod
    def SetDefault(cls, full_name: str, value) -> None:
        """``Config.SetDefault("tpudes::PointToPointNetDevice::DataRate", v)``
        or the ns-3 two-colon form ``ns3::Class::Attr``."""
        tid_name, _, attr = full_name.rpartition("::")
        set_default(tid_name, attr, value)

    @classmethod
    def LookupMatches(cls, path: str) -> list:
        objs, _ = cls._resolve(path.rstrip("/") + "/_")  # dummy leaf segment
        return objs
