"""Core runtime: time, events, scheduler, simulator engines, object model,
configuration, RNG, logging, tracing.

Reference parity: src/core/model/ (see SURVEY.md section 2.1).
"""

from tpudes.core.nstime import Time, Seconds, MilliSeconds, MicroSeconds, NanoSeconds, PicoSeconds, FemtoSeconds, Minutes, Hours, Days
from tpudes.core.event import EventId
from tpudes.core.simulator import Simulator
from tpudes.core.global_value import GlobalValue
from tpudes.core.object import Object, ObjectBase, ObjectFactory, TypeId
from tpudes.core.command_line import CommandLine
from tpudes.core.config import Config, Names
from tpudes.core.trace import TracedCallback, TracedValue, MakeCallback
from tpudes.core.log import LogComponent, LogComponentEnable, LogComponentDisable
from tpudes.core.rng import (
    RngSeedManager,
    RngStream,
    UniformRandomVariable,
    ConstantRandomVariable,
    ExponentialRandomVariable,
    NormalRandomVariable,
    LogNormalRandomVariable,
    ParetoRandomVariable,
    WeibullRandomVariable,
    GammaRandomVariable,
    ErlangRandomVariable,
    TriangularRandomVariable,
    SequentialRandomVariable,
    DeterministicRandomVariable,
    EmpiricalRandomVariable,
    ZipfRandomVariable,
    ZetaRandomVariable,
    BernoulliRandomVariable,
    BinomialRandomVariable,
)
