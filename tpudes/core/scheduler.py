"""Event schedulers: priority queues of (ts, uid) -> event.

Reference parity: src/core/model/scheduler.{h,cc} plus the five concrete
implementations map-scheduler, list-scheduler, heap-scheduler,
calendar-scheduler, priority-queue-scheduler (SURVEY.md 2.1). The engine
selects one via the ``SchedulerType`` GlobalValue, exactly like ns-3's
TypeId object-factory seam.

Cancellation is lazy everywhere: ``EventId.Cancel`` only flags the event;
schedulers purge flagged events when they reach the head (purge-on-read),
so ``IsEmpty``/``PeekNext``/``RemoveNext`` always reflect live events.
This matches ns-3 semantics (a cancelled event stays queued and is skipped
at invoke time) while keeping the queue state self-consistent.

The default is the binary heap; when the native C event core builds
(native/event_core.c via tpudes.core.native), ``create_scheduler``
transparently upgrades the heap selections to :class:`CppHeapScheduler`
— identical (ts, uid) ordering and lazy-cancel semantics, with the heap
AND the engine dispatch loop in C (DefaultSimulatorImpl.Run detects it
and enters the native loop).  ``TPUDES_NO_NATIVE=1`` or
SchedulerType=tpudes::PyHeapScheduler forces pure Python.
"""

from __future__ import annotations

import heapq
from bisect import insort

from tpudes.core.event import Event


class Scheduler:
    """Abstract priority queue of events, ordered by (ts, uid)."""

    def Insert(self, ev: Event) -> None:
        raise NotImplementedError

    def IsEmpty(self) -> bool:
        raise NotImplementedError

    def PeekNext(self) -> Event:
        """Next live event; caller must ensure not IsEmpty()."""
        raise NotImplementedError

    def RemoveNext(self) -> Event:
        """Pop next live event; caller must ensure not IsEmpty()."""
        raise NotImplementedError

    def Remove(self, ev: Event) -> None:
        """Remove a pending event (ns-3 Scheduler::Remove). Lazy: flag it;
        it is purged when it reaches the head."""
        ev.cancel()

    def __len__(self):
        """Count of live (non-cancelled) events. O(n); test/debug use."""
        raise NotImplementedError


class HeapScheduler(Scheduler):
    """Binary heap (src/core/model/heap-scheduler.{h,cc}) with lazy
    deletion of cancelled events."""

    __slots__ = ("_heap",)

    def __init__(self):
        self._heap: list[Event] = []

    def Insert(self, ev: Event) -> None:
        heapq.heappush(self._heap, ev)

    def _purge(self):
        h = self._heap
        while h and h[0].cancelled:
            heapq.heappop(h)

    def IsEmpty(self) -> bool:
        self._purge()
        return not self._heap

    def PeekNext(self) -> Event:
        self._purge()
        return self._heap[0]

    def RemoveNext(self) -> Event:
        self._purge()
        return heapq.heappop(self._heap)

    def __len__(self):
        return sum(1 for e in self._heap if not e.cancelled)


class ListScheduler(Scheduler):
    """Sorted insertion list (src/core/model/list-scheduler.{h,cc}).

    O(n) insert, O(1) pop. Kept for parity and for tiny event counts.
    """

    __slots__ = ("_list",)

    def __init__(self):
        self._list: list[Event] = []

    def Insert(self, ev: Event) -> None:
        insort(self._list, ev)

    def _purge(self):
        while self._list and self._list[0].cancelled:
            self._list.pop(0)

    def IsEmpty(self) -> bool:
        self._purge()
        return not self._list

    def PeekNext(self) -> Event:
        self._purge()
        return self._list[0]

    def RemoveNext(self) -> Event:
        self._purge()
        return self._list.pop(0)

    def __len__(self):
        return sum(1 for e in self._list if not e.cancelled)


class MapScheduler(Scheduler):
    """Ordered-map scheduler (src/core/model/map-scheduler.{h,cc} —
    std::map in ns-3, the upstream default).

    CPython has no balanced tree in the stdlib; the binary heap provides
    identical (ts, uid) ordering semantics, so this is an alias TypeId kept
    for scheduler-selection parity with ns-3 scripts.
    """

    def __init__(self):
        self._inner = HeapScheduler()

    def Insert(self, ev):
        self._inner.Insert(ev)

    def IsEmpty(self):
        return self._inner.IsEmpty()

    def PeekNext(self):
        return self._inner.PeekNext()

    def RemoveNext(self):
        return self._inner.RemoveNext()

    def __len__(self):
        return len(self._inner)


class PriorityQueueScheduler(MapScheduler):
    """std::priority_queue analogue (src/core/model/
    priority-queue-scheduler.{h,cc}); same heap structure in Python, kept
    as a distinct TypeId for parity."""


class CalendarScheduler(Scheduler):
    """Calendar queue (src/core/model/calendar-scheduler.{h,cc}): hashed
    time buckets of width ``width`` ticks; O(1) amortized insert/pop under
    uniform event-time spread (Brown 1988, the design ns-3 follows).

    This implementation keeps the bucket array but finds the minimum by
    scanning bucket heads (O(nbuckets) per pop) rather than the textbook
    year-scan — simpler, same interface, adequate since the heap is the
    performance path.
    """

    def __init__(self, nbuckets: int = 64, width: int = 1_000_000):
        self._n = nbuckets
        self._w = width
        self._buckets: list[list[Event]] = [[] for _ in range(nbuckets)]
        self._count = 0  # live events (cancelled purged on sight)

    def _bucket(self, ts: int) -> list[Event]:
        return self._buckets[(ts // self._w) % self._n]

    def Insert(self, ev: Event) -> None:
        insort(self._bucket(ev.ts), ev)
        self._count += 1
        if self._count > 4 * self._n:
            self._resize(2 * self._n)

    def _purge_heads(self):
        for b in self._buckets:
            while b and b[0].cancelled:
                b.pop(0)
                self._count -= 1

    def IsEmpty(self) -> bool:
        self._purge_heads()
        return self._count == 0

    def _min_bucket(self) -> list[Event]:
        self._purge_heads()
        best = None
        for b in self._buckets:
            if b and (best is None or b[0] < best[0]):
                best = b
        if best is None:
            raise IndexError("empty calendar queue")
        return best

    def PeekNext(self) -> Event:
        return self._min_bucket()[0]

    def RemoveNext(self) -> Event:
        b = self._min_bucket()
        self._count -= 1
        return b.pop(0)

    def Remove(self, ev: Event) -> None:
        if not ev.cancelled:
            ev.cancel()
        # purged (and counted down) when it reaches a bucket head

    def _resize(self, n: int):
        events = [e for b in self._buckets for e in b if not e.cancelled]
        self._n = n
        self._buckets = [[] for _ in range(n)]
        self._count = len(events)
        for e in events:
            insort(self._bucket(e.ts), e)

    def __len__(self):
        return sum(sum(1 for e in b if not e.cancelled) for b in self._buckets)


class CppHeapScheduler(Scheduler):
    """Native binary heap + C dispatch loop (native/event_core.c).

    Same contract as HeapScheduler; ``run_native(impl)`` executes the
    engine inner loop in C, returning when the queue drains, the stop
    flag rises, or a cross-thread injection needs the Python drain.
    """

    __slots__ = ("_h",)

    def __init__(self):
        from tpudes.core.native import get_native

        native = get_native()
        if native is None:
            raise RuntimeError("native event core unavailable")
        self._h = native.CHeap()

    def Insert(self, ev: Event) -> None:
        self._h.insert(ev.ts, ev.uid, ev)

    def IsEmpty(self) -> bool:
        return self._h.is_empty()

    def PeekNext(self) -> Event:
        return self._h.peek()

    def RemoveNext(self) -> Event:
        return self._h.pop()

    def run_native(self, impl) -> int:
        return self._h.run(impl)

    def __len__(self):
        # live (non-cancelled) count, read-only C scan — matches the
        # Python schedulers' contract without mutating the queue
        return self._h.live_count()


SCHEDULER_TYPES = {
    "tpudes::HeapScheduler": HeapScheduler,
    "tpudes::MapScheduler": MapScheduler,
    "tpudes::ListScheduler": ListScheduler,
    "tpudes::CalendarScheduler": CalendarScheduler,
    "tpudes::PriorityQueueScheduler": PriorityQueueScheduler,
    # ns-3 spellings accepted for drop-in script compatibility
    "ns3::HeapScheduler": HeapScheduler,
    "ns3::MapScheduler": MapScheduler,
    "ns3::ListScheduler": ListScheduler,
    "ns3::CalendarScheduler": CalendarScheduler,
    "ns3::PriorityQueueScheduler": PriorityQueueScheduler,
    # explicit selections bypassing the native upgrade / fallback
    "tpudes::PyHeapScheduler": HeapScheduler,
    "tpudes::CppHeapScheduler": CppHeapScheduler,
}

#: heap-semantics selections that silently upgrade to the native core
_NATIVE_UPGRADABLE = {
    "tpudes::HeapScheduler", "ns3::HeapScheduler",
    "tpudes::MapScheduler", "ns3::MapScheduler",
    "tpudes::PriorityQueueScheduler", "ns3::PriorityQueueScheduler",
}


def create_scheduler(type_name: str) -> Scheduler:
    cls = SCHEDULER_TYPES.get(type_name)
    if cls is None:
        raise ValueError(f"unknown SchedulerType {type_name!r}")
    if type_name in _NATIVE_UPGRADABLE:
        from tpudes.core.native import get_native

        if get_native() is not None:
            return CppHeapScheduler()
    return cls()
