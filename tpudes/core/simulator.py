"""Simulator facade + pluggable engines.

Reference parity: src/core/model/simulator.{h,cc} (static facade),
simulator-impl.{h,cc} (abstract engine), default-simulator-impl.{h,cc}
(sequential engine), realtime-simulator-impl.{h,cc} +
wall-clock-synchronizer.{h,cc} (wall-clock engine). See SURVEY.md 2.1 and
the call stack in SURVEY.md 3.1.

The engine is chosen lazily at first use from the GlobalValue
``SimulatorImplementationType`` — the exact seam BASELINE.json's north star
plugs ``JaxSimulatorImpl`` into (registered via
:func:`register_simulator_impl` on import of ``tpudes.parallel``).

The hot loop works in raw integer ticks; ``Time`` objects only appear at
the API boundary.
"""

from __future__ import annotations

import threading
import time as _wallclock
from collections import deque

from tpudes.core.event import Event, EventId
from tpudes.core.nstime import Time
from tpudes.core.global_value import GlobalValue
from tpudes.core.scheduler import create_scheduler


class SimulatorImpl:
    """Abstract engine: owns current time/context and runs the loop
    (src/core/model/simulator-impl.h)."""

    def __init__(self):
        self.current_ts = 0
        self.current_context = Event.NO_CONTEXT
        self.current_uid = 0
        self._uid = 1  # uid 0 reserved (ns-3 reserves low uids for destroy)
        self._stop = False
        self._destroy_events: list[Event] = []
        scheduler_type = GlobalValue.GetValue("SchedulerType")
        self._events = create_scheduler(scheduler_type)
        self._event_count = 0  # total executed, for ShowProgress/bench
        self._scheduled_stop_ts: int | None = None  # last Stop(delay) target
        # observability (tpudes/obs): with TpudesObs=0 the hot loop runs
        # the pre-obs byte code — no per-event check is added; enabling
        # swaps in the instrumented _invoke and wraps the scheduler (the
        # wrapper hides run_native so every event reaches _invoke_obs)
        self._obs = None
        if GlobalValue.GetValueFailSafe("TpudesObs", 0):
            from tpudes.obs.profiler import HostProfiler, InstrumentedScheduler

            self._obs = HostProfiler()
            self._events = InstrumentedScheduler(self._events, self._obs)
            self._invoke = self._invoke_obs

    # --- scheduling ---
    def Schedule(self, delay_ticks: int, fn, args) -> Event:
        if delay_ticks < 0:
            raise ValueError(f"negative schedule delay: {delay_ticks} ticks")
        ts = self.current_ts + delay_ticks
        ev = Event(ts, self._uid, self.current_context, fn, args)
        self._uid += 1
        self._events.Insert(ev)
        return ev

    def ScheduleWithContext(self, context: int, delay_ticks: int, fn, args) -> Event:
        if delay_ticks < 0:
            raise ValueError(f"negative schedule delay: {delay_ticks} ticks")
        ts = self.current_ts + delay_ticks
        ev = Event(ts, self._uid, context, fn, args)
        self._uid += 1
        self._events.Insert(ev)
        return ev

    def ScheduleAt(self, context: int, ts: int, fn, args) -> Event:
        """Schedule at an absolute timestamp (window engines, thread
        injection, cross-partition receives)."""
        ev = Event(ts, self._uid, context, fn, args)
        self._uid += 1
        self._events.Insert(ev)
        return ev

    def ScheduleDestroy(self, fn, args) -> Event:
        ev = Event(0, self._uid, self.current_context, fn, args)
        self._uid += 1
        self._destroy_events.append(ev)
        return ev

    def Remove(self, ev: Event) -> None:
        self._events.Remove(ev)

    # --- time ---
    def Now(self) -> int:
        return self.current_ts

    def NextTs(self) -> int:
        """Timestamp of next pending event (for window engines)."""
        if self._events.IsEmpty():
            return -1
        return self._events.PeekNext().ts

    def IsFinished(self) -> bool:
        return self._stop or self._events.IsEmpty()

    # --- control ---
    def Run(self) -> None:
        raise NotImplementedError

    def Stop(self, delay_ticks: int | None = None) -> Event | None:
        if delay_ticks is None:
            self._stop = True
            return None
        # the earliest scheduled stop wins (ns-3: the first stop event to
        # fire halts the run) — the lifted replica path reads this as its
        # horizon.  Known limitation: Cancel() of a stop EventId does not
        # retract the recorded horizon.
        ts = self.current_ts + delay_ticks
        if self._scheduled_stop_ts is None or ts < self._scheduled_stop_ts:
            self._scheduled_stop_ts = ts
        return self.Schedule(delay_ticks, self._do_stop, ())

    def _do_stop(self):
        self._stop = True
        # this horizon has been consumed; a later Stop() (segmented runs)
        # records a fresh one
        self._scheduled_stop_ts = None

    def Destroy(self) -> None:
        for ev in self._destroy_events:
            if not ev.cancelled:
                ev.invoke()
        self._destroy_events.clear()

    # --- shared inner step ---
    def _invoke(self, ev: Event) -> None:
        self.current_ts = ev.ts
        self.current_context = ev.context
        self.current_uid = ev.uid
        self._event_count += 1
        ev.invoke()

    def _invoke_obs(self, ev: Event) -> None:
        """Instrumented twin of ``_invoke`` (installed as an instance
        attribute when TpudesObs=1): per-type count + wall time, flight
        recorder, crash dump, and the time-monotonicity invariant."""
        obs = self._obs
        if ev.ts < self.current_ts:
            obs.trip(
                f"event uid={ev.uid} at ts={ev.ts} behind now="
                f"{self.current_ts} (queue ordering violated)"
            )
        self.current_ts = ev.ts
        self.current_context = ev.context
        self.current_uid = ev.uid
        self._event_count += 1
        obs.event_count += 1
        fn = ev.fn
        label = getattr(fn, "__qualname__", None) or type(fn).__name__
        obs.recorder.note(ev.ts, ev.context, ev.uid, label)
        t0 = _wallclock.monotonic()
        try:
            ev.invoke()
        except BaseException as e:
            obs.dump_crash(e)
            raise
        obs.record(label, t0, _wallclock.monotonic() - t0, ev)


class DefaultSimulatorImpl(SimulatorImpl):
    """Sequential engine: pop events in (ts, uid) order and invoke until
    the queue drains or Stop() (src/core/model/default-simulator-impl.cc).

    ``ScheduleWithContextThreadSafe`` + ``_process_events_with_context``
    mirror ns-3's mutex-guarded cross-thread injection channel (used by
    emulation read threads; SURVEY.md 5.2).
    """

    def __init__(self):
        super().__init__()
        self._injected: deque = deque()
        self._injected_lock = threading.Lock()
        self._main_thread = threading.get_ident()

    def ScheduleWithContextThreadSafe(self, context: int, delay_ticks: int, fn, args):
        # capture the timestamp at *injection* time (ns-3 grabs m_currentTs
        # under the mutex here) — sim time may advance before the drain
        with self._injected_lock:
            self._injected.append((context, self.current_ts + delay_ticks, fn, args))

    def _process_events_with_context(self):
        if not self._injected:
            return
        with self._injected_lock:
            pending, self._injected = self._injected, deque()
        for context, ts, fn, args in pending:
            # an injected ts may be in the engine's past by the time it
            # drains; clamp to now (the realtime engine's contract)
            self.ScheduleAt(context, max(ts, self.current_ts), fn, args)

    def Run(self) -> None:
        self._stop = False
        events = self._events
        run_native = getattr(events, "run_native", None)
        if run_native is not None:
            # C dispatch loop; it hands control back whenever a
            # cross-thread injection arrives, the stop flag rises, or
            # the queue drains
            while not self._stop:
                self._process_events_with_context()
                if events.IsEmpty():
                    break
                run_native(self)
            return
        while not self._stop:
            self._process_events_with_context()
            if events.IsEmpty():
                break
            self._invoke(events.RemoveNext())


class RealtimeSimulatorImpl(DefaultSimulatorImpl):
    """Pins simulated time to the wall clock
    (src/core/model/realtime-simulator-impl.cc): before invoking an event
    at sim time t, sleep until wall-clock has reached t since Run() began.
    ``BestEffort`` mode tolerates falling behind; ``HardLimit`` raises if
    the jitter exceeds ``hard_limit`` (default 0.1 s), as in ns-3.
    """

    BEST_EFFORT = 0
    HARD_LIMIT = 1

    def __init__(self, mode: int = 0, hard_limit_s: float = 0.1):
        super().__init__()
        self.mode = mode
        self.hard_limit_s = hard_limit_s

    def Run(self) -> None:
        self._stop = False
        start_wall = _wallclock.monotonic()
        start_sim_s = Time(self.current_ts).GetSeconds()
        events = self._events
        while not self._stop:
            self._process_events_with_context()
            if events.IsEmpty():
                break
            ev = events.PeekNext()
            target_wall = start_wall + (Time(ev.ts).GetSeconds() - start_sim_s)
            now_wall = _wallclock.monotonic()
            if target_wall > now_wall:
                # sleep in slices so injected (emulation) events can preempt
                while True:
                    remaining = target_wall - _wallclock.monotonic()
                    if remaining <= 0:
                        break
                    _wallclock.sleep(min(remaining, 0.001))
                    if self._injected:
                        break
                if self._injected:
                    continue  # re-evaluate next event after injection
            elif self.mode == self.HARD_LIMIT and now_wall - target_wall > self.hard_limit_s:
                raise RuntimeError(
                    f"RealtimeSimulatorImpl(HardLimit): fell "
                    f"{now_wall - target_wall:.3f}s behind wall clock"
                )
            self._invoke(events.RemoveNext())


# --- engine registry (the ObjectFactory seam) ---

SIMULATOR_IMPL_TYPES: dict[str, type] = {}


def register_simulator_impl(name: str, cls: type) -> None:
    SIMULATOR_IMPL_TYPES[name] = cls


register_simulator_impl("tpudes::DefaultSimulatorImpl", DefaultSimulatorImpl)
register_simulator_impl("ns3::DefaultSimulatorImpl", DefaultSimulatorImpl)
register_simulator_impl("tpudes::RealtimeSimulatorImpl", RealtimeSimulatorImpl)
register_simulator_impl("ns3::RealtimeSimulatorImpl", RealtimeSimulatorImpl)


class Simulator:
    """Static facade (src/core/model/simulator.h): Schedule / Run / Stop /
    Now / Destroy. All times are ``Time`` at this boundary."""

    _impl: SimulatorImpl | None = None

    # --- engine seam ---
    @classmethod
    def GetImpl(cls) -> SimulatorImpl:
        if cls._impl is None:
            name = GlobalValue.GetValue("SimulatorImplementationType")
            impl_cls = SIMULATOR_IMPL_TYPES.get(name)
            if impl_cls is None:
                # late registration: the JAX and distributed engines live
                # in tpudes.parallel and register on import
                if "Jax" in name:
                    import tpudes.parallel  # noqa: F401  (registers itself)

                    impl_cls = SIMULATOR_IMPL_TYPES.get(name)
                elif "Distributed" in name or "NullMessage" in name:
                    import tpudes.parallel.distributed  # noqa: F401

                    impl_cls = SIMULATOR_IMPL_TYPES.get(name)
            if impl_cls is None:
                raise ValueError(f"unknown SimulatorImplementationType {name!r}")
            cls._impl = impl_cls()
        return cls._impl

    @classmethod
    def SetImplementation(cls, impl: SimulatorImpl) -> None:
        if cls._impl is not None:
            raise RuntimeError("simulator implementation already created")
        cls._impl = impl

    # --- scheduling API ---
    @classmethod
    def Schedule(cls, delay: Time, fn, *args) -> EventId:
        return EventId(cls.GetImpl().Schedule(Time(delay).ticks, fn, args))

    @classmethod
    def ScheduleNow(cls, fn, *args) -> EventId:
        return EventId(cls.GetImpl().Schedule(0, fn, args))

    @classmethod
    def ScheduleWithContext(cls, context: int, delay: Time, fn, *args) -> EventId:
        return EventId(cls.GetImpl().ScheduleWithContext(context, Time(delay).ticks, fn, args))

    @classmethod
    def ScheduleDestroy(cls, fn, *args) -> EventId:
        return EventId(cls.GetImpl().ScheduleDestroy(fn, args))

    @classmethod
    def Cancel(cls, event_id: EventId) -> None:
        event_id.Cancel()

    @classmethod
    def Remove(cls, event_id: EventId) -> None:
        if event_id._event is not None:
            cls.GetImpl().Remove(event_id._event)

    # --- control ---
    @classmethod
    def Run(cls) -> None:
        cls.GetImpl().Run()

    @classmethod
    def Stop(cls, delay: Time | None = None) -> EventId | None:
        if delay is None:
            cls.GetImpl().Stop(None)
            return None
        return EventId(cls.GetImpl().Stop(Time(delay).ticks))

    @classmethod
    def Destroy(cls) -> None:
        """Invoke destroy events and reset the engine, so a process can run
        several simulations back-to-back (each pytest test does)."""
        if cls._impl is not None:
            cls._impl.Destroy()
            obs = cls._impl._obs
            if obs is not None:
                # TpudesObsTrace names a Chrome-trace output path; the
                # GlobalValue is still bound here (reset_world resets
                # globals only after Destroy returns)
                from tpudes.obs.export import export_on_destroy

                export_on_destroy(obs)
        cls._impl = None

    # --- time / context ---
    @classmethod
    def Now(cls) -> Time:
        return Time(cls.GetImpl().current_ts)

    @classmethod
    def NowTicks(cls) -> int:
        return cls._impl.current_ts if cls._impl is not None else 0

    @classmethod
    def GetContext(cls) -> int:
        return cls.GetImpl().current_context

    @classmethod
    def GetEventCount(cls) -> int:
        return cls.GetImpl()._event_count

    @classmethod
    def IsFinished(cls) -> bool:
        return cls.GetImpl().IsFinished()

    # convenience used by models: delay for next occurrence
    NO_CONTEXT = Event.NO_CONTEXT
