"""Loader/builder for the native C event core (native/event_core.c).

The extension is compiled on first use with the system compiler (plain
``cc -O2 -shared -fPIC`` against the running interpreter's headers — no
pybind11, no setuptools invocation) into ``native/build/`` and cached
there keyed by interpreter version.  Everything degrades gracefully:
if no compiler is present or the build fails, ``get_native()`` returns
None once, warns once, and the pure-Python schedulers carry on — the
native core is an accelerator, never a dependency.
"""

from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess
import sys
import sysconfig

_cached: object = None
_tried = False

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "event_core.c",
)


def _build_dir() -> str:
    return os.path.join(os.path.dirname(_SRC), "build")


def _so_path() -> str:
    tag = f"cpython-{sys.version_info.major}{sys.version_info.minor}"
    return os.path.join(_build_dir(), f"tpudes_event_core.{tag}.so")


def _compile() -> str | None:
    so = _so_path()
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(_SRC):
        return so
    os.makedirs(_build_dir(), exist_ok=True)
    cc = (
        os.environ.get("CC")
        or sysconfig.get_config_var("CC")
        or "cc"
    ).split()[0]
    include = sysconfig.get_paths()["include"]
    # atomic publish: concurrent processes (distributed ranks on a fresh
    # checkout) may race this build — compile to a per-process temp and
    # rename into place so no one ever dlopens a half-written .so
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [
        cc, "-O2", "-shared", "-fPIC", f"-I{include}", _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        os.replace(tmp, so)
    except (OSError, subprocess.SubprocessError) as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        detail = getattr(e, "stderr", b"") or b""
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}): {detail.decode()[:500]}"
        ) from e
    return so


def get_native():
    """The ``tpudes_event_core`` module, or None when unavailable."""
    global _cached, _tried
    if _tried:
        return _cached
    _tried = True
    if os.environ.get("TPUDES_NO_NATIVE"):
        _cached = None
        return None
    try:
        so = _compile()
        loader = importlib.machinery.ExtensionFileLoader(
            "tpudes_event_core", so
        )
        spec = importlib.util.spec_from_file_location(
            "tpudes_event_core", so, loader=loader
        )
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        _cached = mod
    except Exception as e:  # noqa: BLE001 — any failure means fallback
        import warnings

        warnings.warn(
            f"tpudes native event core unavailable ({e}); "
            "using the pure-Python schedulers",
            stacklevel=2,
        )
        _cached = None
    return _cached
