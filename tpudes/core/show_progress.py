"""ShowProgress: the wall-clock/events-per-second run meter.

Reference parity: src/core/model/show-progress.{h,cc} (upstream path;
mount empty at survey — SURVEY.md §0, §5.5).  Construct one before
``Simulator.Run()``; every ``interval`` of *simulated* time it prints
elapsed wall clock, the event execution rate since the last report, and
the speedup (sim seconds per wall second) — upstream's exact trio — and
adapts the reporting interval toward one line per ~second of wall time.

Rate bookkeeping lives in the observability layer
(:class:`tpudes.obs.profiler.RunStats`), not here: with ``TpudesObs=1``
ShowProgress shares the engine profiler's meter (one source of truth
for the trace export and the progress line, plus a live queue-depth
column); with the knob off it owns a standalone ``RunStats``.
"""

from __future__ import annotations

import sys

from tpudes.core.nstime import Seconds, Time
from tpudes.core.simulator import Simulator
from tpudes.obs.profiler import RunStats


class ShowProgress:
    def __init__(self, interval=None, stream=None):
        self._interval = Time(interval) if interval is not None else Seconds(1.0)
        self._stream = stream if stream is not None else sys.stderr
        impl = Simulator.GetImpl()
        self._obs = impl._obs
        self._stats = (
            self._obs.run_stats if self._obs is not None else RunStats()
        )
        # the engine profiler's meter dates from engine construction;
        # prime it here so the first reported interval (and the wall
        # column) measures from ShowProgress creation, as upstream does
        snap0 = self._stats.sample(
            Simulator.GetEventCount(), Simulator.Now().GetSeconds()
        )
        self._wall0 = snap0["wall_s"]
        Simulator.Schedule(self._interval, self._report)

    def _report(self):
        snap = self._stats.sample(
            Simulator.GetEventCount(), Simulator.Now().GetSeconds()
        )
        extra = ""
        if self._obs is not None:
            extra = f" q={self._obs.resync_depth()}"
        self._stream.write(
            f"ShowProgress: sim {snap['sim_s']:.3f}s wall "
            f"{snap['wall_s'] - self._wall0:.1f}s "
            f"[{snap['ev_per_s']:,.0f} ev/s, "
            f"{snap['sim_per_wall']:.3g} sim-s/wall-s]{extra}\n"
        )
        # adapt toward ~1 line per wall second (upstream's behavior)
        if snap["dt_wall"] < 0.5:
            self._interval = Time(self._interval.ticks * 2)
        elif snap["dt_wall"] > 2.0 and self._interval.ticks > 1:
            self._interval = Time(max(self._interval.ticks // 2, 1))
        Simulator.Schedule(self._interval, self._report)
