"""ShowProgress: the wall-clock/events-per-second run meter.

Reference parity: src/core/model/show-progress.{h,cc} (upstream path;
mount empty at survey — SURVEY.md §0, §5.5).  Construct one before
``Simulator.Run()``; every ``interval`` of *simulated* time it prints
elapsed wall clock, the event execution rate since the last report, and
the speedup (sim seconds per wall second) — upstream's exact trio — and
adapts the reporting interval toward one line per ~second of wall time.
"""

from __future__ import annotations

import sys
import time

from tpudes.core.nstime import Seconds, Time
from tpudes.core.simulator import Simulator


class ShowProgress:
    def __init__(self, interval=None, stream=None):
        self._interval = Time(interval) if interval is not None else Seconds(1.0)
        self._stream = stream if stream is not None else sys.stderr
        self._wall_start = time.monotonic()
        self._last_wall = self._wall_start
        self._last_events = 0
        self._last_sim_s = 0.0
        Simulator.Schedule(self._interval, self._report)

    def _report(self):
        now_wall = time.monotonic()
        dt_wall = max(now_wall - self._last_wall, 1e-9)
        events = Simulator.GetEventCount()
        d_events = events - self._last_events
        sim_s = Simulator.Now().GetSeconds()
        d_sim = sim_s - self._last_sim_s
        self._stream.write(
            f"ShowProgress: sim {sim_s:.3f}s wall "
            f"{now_wall - self._wall_start:.1f}s "
            f"[{d_events / dt_wall:,.0f} ev/s, "
            f"{d_sim / dt_wall:.3g} sim-s/wall-s]\n"
        )
        self._last_wall = now_wall
        self._last_events = events
        self._last_sim_s = sim_s
        # adapt toward ~1 line per wall second (upstream's behavior)
        if dt_wall < 0.5:
            self._interval = Time(self._interval.ticks * 2)
        elif dt_wall > 2.0 and self._interval.ticks > 1:
            self._interval = Time(max(self._interval.ticks // 2, 1))
        Simulator.Schedule(self._interval, self._report)
