"""Events: type-erased bound closures with cancel/expire semantics.

Reference parity: src/core/model/event-impl.{h,cc}, event-id.{h,cc},
make-event.h (SURVEY.md 2.1). In ns-3 an event is a heap-allocated
``EventImpl`` (a bound closure) keyed by (timestamp, uid); ``EventId`` is a
value handle supporting ``Cancel``/``IsExpired``/``IsPending``. Here the
closure is a plain Python callable + args; ``Event`` is the queue record.
"""

from __future__ import annotations


class Event:
    """Internal queue record: (ts, uid) orders the queue; context is the
    owning node id (0xffffffff = no context, as in ns-3)."""

    __slots__ = ("ts", "uid", "context", "fn", "args", "cancelled")

    NO_CONTEXT = 0xFFFFFFFF

    def __init__(self, ts: int, uid: int, context: int, fn, args):
        self.ts = ts
        self.uid = uid
        self.context = context
        self.fn = fn
        self.args = args
        self.cancelled = False

    def invoke(self):
        self.fn(*self.args)

    def cancel(self):
        self.cancelled = True

    # ordering used by schedulers: strict (ts, uid) as in ns-3 Scheduler::EventKey
    def __lt__(self, other: "Event"):
        if self.ts != other.ts:
            return self.ts < other.ts
        return self.uid < other.uid

    def __repr__(self):
        return f"Event(ts={self.ts}, uid={self.uid}, ctx={self.context}, fn={getattr(self.fn, '__qualname__', self.fn)})"


class EventId:
    """Value handle to a scheduled event (src/core/model/event-id.h).

    ``Cancel`` marks the closure cancelled without dequeuing (lazy
    deletion); ``Remove`` is done through ``Simulator.Remove``.
    """

    __slots__ = ("_event",)

    def __init__(self, event: Event | None = None):
        self._event = event

    def Cancel(self):
        if self._event is not None:
            self._event.cancel()

    def IsCancelled(self) -> bool:
        return self._event is not None and self._event.cancelled

    def IsExpired(self) -> bool:
        # expired = already run, cancelled, or null
        from tpudes.core.simulator import Simulator

        ev = self._event
        if ev is None or ev.cancelled:
            return True
        now = Simulator.NowTicks()
        if ev.ts < now:
            return True
        if ev.ts == now and Simulator._impl is not None and ev.uid <= Simulator._impl.current_uid:
            return True
        return False

    def IsPending(self) -> bool:
        return not self.IsExpired()

    # ns-3 deprecated alias
    def IsRunning(self) -> bool:
        return self.IsPending()

    def GetTs(self) -> int:
        return self._event.ts if self._event is not None else 0

    def GetUid(self) -> int:
        return self._event.uid if self._event is not None else 0

    def GetContext(self) -> int:
        return self._event.context if self._event is not None else Event.NO_CONTEXT

    def __eq__(self, other):
        return isinstance(other, EventId) and self._event is other._event

    def __hash__(self):
        return id(self._event)

    def __repr__(self):
        return f"EventId({self._event!r})"
