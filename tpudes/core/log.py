"""NS_LOG-style component logging.

Reference parity: src/core/model/log.{h,cc}, log-macros-enabled.h
(SURVEY.md 2.1): named components with per-component levels, enabled at
runtime via ``LogComponentEnable`` or the ``NS_LOG`` environment variable
(``NS_LOG="UdpEchoClient=info|prefix_time:UdpEchoServer=level_all"``).

Disabled components cost one dict lookup + int compare per call — the
Python analogue of ns-3's compiled-out macros.
"""

from __future__ import annotations

import os
import sys

LOG_NONE = 0
LOG_ERROR = 1
LOG_WARN = 2
LOG_DEBUG = 3
LOG_INFO = 4
LOG_FUNCTION = 5
LOG_LOGIC = 6
LOG_ALL = 7

_LEVEL_NAMES = {
    "error": LOG_ERROR,
    "warn": LOG_WARN,
    "debug": LOG_DEBUG,
    "info": LOG_INFO,
    "function": LOG_FUNCTION,
    "logic": LOG_LOGIC,
    "all": LOG_ALL,
    "level_error": LOG_ERROR,
    "level_warn": LOG_WARN,
    "level_debug": LOG_DEBUG,
    "level_info": LOG_INFO,
    "level_function": LOG_FUNCTION,
    "level_logic": LOG_LOGIC,
    "level_all": LOG_ALL,
    "*": LOG_ALL,
}

_components: dict[str, int] = {}
_prefix_time = True
_prefix_node = True


class LogComponent:
    """One named log component (the NS_LOG_COMPONENT_DEFINE analogue)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name
        _components.setdefault(name, _env_level(name))

    @property
    def level(self) -> int:
        return _components[self.name]

    def IsEnabled(self, level: int) -> bool:
        return _components[self.name] >= level

    def _emit(self, tag: str, args) -> None:
        from tpudes.core.simulator import Simulator

        parts = []
        if _prefix_time:
            parts.append(f"+{Simulator.NowTicks()}ns")
        ctx = Simulator._impl.current_context if Simulator._impl else None
        if _prefix_node and ctx is not None and ctx != 0xFFFFFFFF:
            parts.append(str(ctx))
        parts.append(f"{self.name}:{tag}:")
        parts.extend(str(a) for a in args)
        print(" ".join(parts), file=sys.stderr)

    def error(self, *args):
        if _components[self.name] >= LOG_ERROR:
            self._emit("ERROR", args)

    def warn(self, *args):
        if _components[self.name] >= LOG_WARN:
            self._emit("WARN", args)

    def debug(self, *args):
        if _components[self.name] >= LOG_DEBUG:
            self._emit("DEBUG", args)

    def info(self, *args):
        if _components[self.name] >= LOG_INFO:
            self._emit("INFO", args)

    def function(self, *args):
        if _components[self.name] >= LOG_FUNCTION:
            self._emit("FUNC", args)

    def logic(self, *args):
        if _components[self.name] >= LOG_LOGIC:
            self._emit("LOGIC", args)


def _env_level(name: str) -> int:
    env = os.environ.get("NS_LOG", "")
    level = LOG_NONE
    for clause in env.split(":"):
        if not clause:
            continue
        comp, _, spec = clause.partition("=")
        if comp not in (name, "*", "***"):
            continue
        if not spec:
            level = max(level, LOG_DEBUG)
            continue
        for tok in spec.split("|"):
            tok = tok.strip().lower()
            if tok in _LEVEL_NAMES:
                level = max(level, _LEVEL_NAMES[tok])
    return level


def LogComponentEnable(name: str, level: int = LOG_ALL) -> None:
    _components[name] = level


def LogComponentDisable(name: str) -> None:
    _components[name] = LOG_NONE


def LogComponentEnableAll(level: int = LOG_ALL) -> None:
    for name in _components:
        _components[name] = level
