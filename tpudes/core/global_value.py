"""GlobalValue: process-wide named configuration values.

Reference parity: src/core/model/global-value.{h,cc} (SURVEY.md 2.1).
These are the process-level knobs — engine type, scheduler type, RngRun,
ChecksumEnabled — settable programmatically (``Bind``), from the command
line (``--Name=value`` via CommandLine), or from the environment variable
``NS_GLOBAL_VALUE`` (``name=value;name=value``).

This seam is the one-flag opt-in contract from BASELINE.json: scripts
switch to the TPU engine with
``GlobalValue.Bind("SimulatorImplementationType", "tpudes::JaxSimulatorImpl")``.
"""

from __future__ import annotations

import os


class GlobalValue:
    _registry: dict[str, "GlobalValue"] = {}

    def __init__(self, name: str, help: str, initial):
        self.name = name
        self.help = help
        self.initial = initial
        self.value = initial
        GlobalValue._registry[name] = self

    @classmethod
    def Bind(cls, name: str, value) -> None:
        gv = cls._registry.get(name)
        if gv is None:
            raise KeyError(f"no GlobalValue named {name!r}")
        gv.value = value

    @classmethod
    def BindFailSafe(cls, name: str, value) -> bool:
        if name in cls._registry:
            cls._registry[name].value = value
            return True
        return False

    @classmethod
    def GetValue(cls, name: str):
        gv = cls._registry.get(name)
        if gv is None:
            raise KeyError(f"no GlobalValue named {name!r}")
        return gv.value

    @classmethod
    def GetValueFailSafe(cls, name: str, default=None):
        gv = cls._registry.get(name)
        return gv.value if gv is not None else default

    @classmethod
    def Iterate(cls):
        return iter(cls._registry.values())

    @classmethod
    def ResetAll(cls) -> None:
        for gv in cls._registry.values():
            gv.value = gv.initial

    @classmethod
    def ApplyEnvironment(cls) -> None:
        """Apply NS_GLOBAL_VALUE=name=value;name=value overrides, coercing
        the string toward the type of the registered initial value."""
        env = os.environ.get("NS_GLOBAL_VALUE", "")
        for pair in env.split(";"):
            if "=" in pair:
                name, _, value = pair.partition("=")
                name, value = name.strip(), value.strip()
                gv = cls._registry.get(name)
                if gv is None:
                    continue
                if isinstance(gv.initial, bool):
                    gv.value = value.lower() in ("1", "true", "t", "yes", "y")
                elif isinstance(gv.initial, int):
                    gv.value = int(value)
                elif isinstance(gv.initial, float):
                    gv.value = float(value)
                else:
                    gv.value = value


# --- the core globals, mirroring ns-3's (src/core/model/simulator.cc,
# rng-seed-manager.cc, chunk registration sites) ---

SimulatorImplementationType = GlobalValue(
    "SimulatorImplementationType",
    "The type of simulator engine to use (the JaxSimulatorImpl seam).",
    "tpudes::DefaultSimulatorImpl",
)

SchedulerType = GlobalValue(
    "SchedulerType",
    "The event-scheduler (priority queue) implementation to use.",
    "tpudes::HeapScheduler",
)

RngSeed = GlobalValue("RngSeed", "The global RNG seed.", 1)

RngRun = GlobalValue(
    "RngRun",
    "The run number (substream selector) — the Monte-Carlo replica axis.",
    1,
)

ChecksumEnabled = GlobalValue(
    "ChecksumEnabled", "Whether protocol checksums are computed.", False
)

# JaxSimulatorImpl knobs live here (not in tpudes.parallel.engine) so
# CommandLine can bind them before the engine module is ever imported —
# the whole point of the seam is that a stock scenario script flips
# engines from the command line alone.
JaxWindowNs = GlobalValue(
    "JaxWindowNs",
    "conservative window length (ns) for JaxSimulatorImpl",
    1_000_000,
)
JaxBatchMinPhys = GlobalValue(
    "JaxBatchMinPhys",
    "smallest channel (phy count) that engages the batched window cache",
    32,
)
JaxReplicas = GlobalValue(
    "JaxReplicas",
    "Monte-Carlo replica count for the lifted replica-axis path "
    "(0 = windowed scalar engine)",
    0,
)
JaxGeomStride = GlobalValue(
    "JaxGeomStride",
    "geometry refresh stride of the lifted mobile path: recompute the "
    "in-kernel loss tables every K steps/TTIs (1 = every step, "
    "bit-identical to per-step recompute)",
    1,
)
JaxTrafficModel = GlobalValue(
    "JaxTrafficModel",
    "workload model of the lifted BSS path (tpudes/traffic): off = "
    "the scenario's own CBR apps (bit-identical legacy compile), or "
    "cbr | mmpp | onoff | trace — STA arrivals ride the device "
    "traffic stage at the apps' mean rate (beacons stay cbr)",
    "off",
)
JaxTrafficSeed = GlobalValue(
    "JaxTrafficSeed",
    "workload realization seed of the lifted traffic stage (the "
    "fold_in table stream; model/param flips never recompile)",
    0,
)

# Observability knobs (tpudes/obs).  Registered here, like the engine
# knobs, so CommandLine / NS_GLOBAL_VALUE can bind them before any
# engine or device program is constructed.
TpudesObs = GlobalValue(
    "TpudesObs",
    "enable the unified observability layer: host event profiler, "
    "flight recorder, on-device metric accumulators (0 = zero-cost off)",
    0,
)
TpudesObsTrace = GlobalValue(
    "TpudesObsTrace",
    "path to write a Chrome-trace/Perfetto JSON export of the run at "
    "Simulator.Destroy ('' = no export; needs TpudesObs=1)",
    "",
)
TpudesObsRing = GlobalValue(
    "TpudesObsRing",
    "flight-recorder capacity: the last N executed events dumped on an "
    "exception or invariant trip",
    512,
)

GlobalValue.ApplyEnvironment()
