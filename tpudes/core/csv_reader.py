"""CsvReader: typed row-by-row CSV parsing.

Reference parity: src/core/model/csv-reader.{h,cc} (upstream path;
mount empty at survey — SURVEY.md §0, §2.1 misc row).  Same contract:
``FetchNextRow`` advances, ``GetValue(col)`` coerces to the requested
type, comment lines (#) and blank lines are skipped, quoted fields may
contain the delimiter.
"""

from __future__ import annotations

import csv as _csv


class CsvReader:
    def __init__(self, source: str, delimiter: str = ","):
        """``source``: a filename, or a file-like object."""
        if isinstance(source, str):
            self._f = open(source, newline="")
            self._owns = True
        else:
            self._f = source
            self._owns = False
        self._reader = _csv.reader(self._f, delimiter=delimiter)
        self._row: list[str] | None = None
        self.row_number = 0

    def Close(self) -> None:
        if self._owns and not self._f.closed:
            self._f.close()

    def __enter__(self) -> "CsvReader":
        return self

    def __exit__(self, *exc) -> None:
        self.Close()

    def FetchNextRow(self) -> bool:
        for row in self._reader:
            if not row or (row[0].lstrip().startswith("#")):
                continue
            self._row = [c.strip() for c in row]
            self.row_number += 1
            return True
        self._row = None
        self.Close()
        return False

    def ColumnCount(self) -> int:
        return len(self._row) if self._row else 0

    def IsBlankRow(self) -> bool:
        return not self._row or all(not c for c in self._row)

    def GetValue(self, column: int, astype=str):
        """Coerced cell value; raises ValueError on a bad cell, like
        upstream's bool return + NS_ABORT idiom collapsed into raising."""
        if self._row is None or column >= len(self._row):
            raise IndexError(f"no column {column} in row {self.row_number}")
        text = self._row[column]
        if astype is bool:
            return text.lower() in ("1", "true", "t", "yes", "y")
        return astype(text)
