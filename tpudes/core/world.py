"""Process-global world reset.

One simulation per process is the ns-3 contract; tests, benchmarks and
multi-run drivers that build several worlds back-to-back reset ALL
process-global state through this single function (conftest, bench.py
and the parallel tests previously each carried their own copy — any new
global registry must be added HERE only).
"""

from __future__ import annotations

import sys


def reset_world() -> None:
    from tpudes.core.config import Names
    from tpudes.core.global_value import GlobalValue
    from tpudes.core.rng import RngSeedManager
    from tpudes.core.simulator import Simulator

    Simulator.Destroy()
    GlobalValue.ResetAll()
    RngSeedManager.Reset()
    Names.Clear()
    # Config.SetDefault overrides are process-global too — a leaked
    # default (e.g. a test's buffer sizing) silently reshapes every
    # later simulation
    from tpudes.core.object import _DEFAULT_OVERRIDES

    _DEFAULT_OVERRIDES.clear()
    # lazily-imported registries: only touch what the process loaded
    mod = sys.modules.get("tpudes.network.node")
    if mod is not None:
        mod.NodeList.Reset()
    eng = sys.modules.get("tpudes.parallel.engine")
    if eng is not None:
        eng.BatchableRegistry.reset()
    gr = sys.modules.get("tpudes.models.internet.global_routing")
    if gr is not None:
        gr.GlobalRouteManager.Reset()
    bl = sys.modules.get("tpudes.models.buildings")
    if bl is not None:
        bl.BuildingList.Reset()
