"""Random number generation: MRG32k3a streams + the random-variable
distribution library.

Reference parity: src/core/model/rng-stream.{h,cc},
rng-seed-manager.{h,cc}, random-variable-stream.{h,cc} (SURVEY.md 2.1).

The generator is L'Ecuyer's MRG32k3a with the standard stream structure:
each new ``RandomVariableStream`` takes the next *stream* (a 2^127 jump)
and the global run number (``RngRun``) selects the *substream* (a 2^76
jump) — the Monte-Carlo replica axis. Jumps are exact 3x3 matrix powers
mod m, so streams are provably non-overlapping, matching ns-3's
reproducibility contract on the host path.

The TPU path uses counter-based threefry keys derived from
(seed, run, stream-id) instead (tpudes/ops/random.py) — per-backend
deterministic, cross-backend statistically equivalent (documented
deviation; SURVEY.md 7 hard part 4).
"""

from __future__ import annotations

import math

from tpudes.core.global_value import GlobalValue
from tpudes.core.object import Object, TypeId

# --- MRG32k3a constants (L'Ecuyer 1999) ---
_M1 = 4294967087
_M2 = 4294944443
_A12 = 1403580
_A13N = 810728
_A21 = 527612
_A23N = 1370589
_NORM = 1.0 / (_M1 + 1)

# one-step transition matrices
_A1 = ((0, 1, 0), (0, 0, 1), ((_M1 - _A13N) % _M1, _A12, 0))
_A2 = ((0, 1, 0), (0, 0, 1), ((_M2 - _A23N) % _M2, 0, _A21))


def _mat_mul(a, b, m):
    return tuple(
        tuple(sum(a[i][k] * b[k][j] for k in range(3)) % m for j in range(3))
        for i in range(3)
    )


def _mat_pow(a, e, m):
    r = ((1, 0, 0), (0, 1, 0), (0, 0, 1))
    while e > 0:
        if e & 1:
            r = _mat_mul(r, a, m)
        a = _mat_mul(a, a, m)
        e >>= 1
    return r


def _mat_vec(a, v, m):
    return [sum(a[i][k] * v[k] for k in range(3)) % m for i in range(3)]


# jump matrices: stream = 2^127 steps, substream = 2^76 steps (L'Ecuyer)
_A1_P127 = _mat_pow(_A1, 1 << 127, _M1)
_A2_P127 = _mat_pow(_A2, 1 << 127, _M2)
_A1_P76 = _mat_pow(_A1, 1 << 76, _M1)
_A2_P76 = _mat_pow(_A2, 1 << 76, _M2)


class RngStream:
    """One MRG32k3a stream positioned at (seed, stream, substream).

    The per-draw recurrence runs in the native C core when it is built
    (bit-identical to the Python path — pinned by test); stream/
    substream jump math stays in Python (cold path, big-int matrices).
    """

    __slots__ = ("_s1", "_s2", "_native")

    def __init__(self, seed: int, stream: int, substream: int):
        # ns-3 expands the scalar seed into the six-value package seed.
        s = seed % _M1
        if s == 0:
            s = 12345
        base1 = [s, s, s]
        base2 = [s % _M2 or 12345] * 3
        if stream > 0:
            j1 = _mat_pow(_A1_P127, stream, _M1)
            j2 = _mat_pow(_A2_P127, stream, _M2)
            base1 = _mat_vec(j1, base1, _M1)
            base2 = _mat_vec(j2, base2, _M2)
        if substream > 0:
            j1 = _mat_pow(_A1_P76, substream, _M1)
            j2 = _mat_pow(_A2_P76, substream, _M2)
            base1 = _mat_vec(j1, base1, _M1)
            base2 = _mat_vec(j2, base2, _M2)
        self._s1 = base1
        self._s2 = base2
        self._native = None

    def RandU01(self) -> float:
        native = self._native
        if native is None:
            from tpudes.core.native import get_native

            mod = get_native()
            if mod is not None and hasattr(mod, "Mrg32k3a"):
                native = self._native = mod.Mrg32k3a(*self._s1, *self._s2)
            else:
                native = self._native = False
        if native is not False:
            return native.rand_u01()
        s1 = self._s1
        s2 = self._s2
        p1 = (_A12 * s1[1] - _A13N * s1[0]) % _M1
        s1[0], s1[1], s1[2] = s1[1], s1[2], p1
        p2 = (_A21 * s2[2] - _A23N * s2[0]) % _M2
        s2[0], s2[1], s2[2] = s2[1], s2[2], p2
        # L'Ecuyer: p1 <= p2 maps to p1 - p2 + m1, so p1 == p2 yields
        # m1*norm (just below 1), never exactly 0.0 — keeps log(u)/u**-x
        # in downstream distributions safe.
        d = p1 - p2
        if d <= 0:
            d += _M1
        return d * _NORM

    def RandInt(self, low: int, high: int) -> int:
        return low + int(self.RandU01() * (high - low + 1))

    # --- state visibility / pickling with the native path active ---------
    def _sync_from_native(self) -> None:
        if self._native not in (None, False):
            s = self._native.get_state()
            self._s1 = list(s[:3])
            self._s2 = list(s[3:])

    def get_state(self) -> tuple:
        """Current six-value stream position (valid whichever RandU01
        implementation has been advancing it)."""
        self._sync_from_native()
        return tuple(self._s1) + tuple(self._s2)

    def __getstate__(self):
        self._sync_from_native()
        return (list(self._s1), list(self._s2))

    def __setstate__(self, state):
        self._s1, self._s2 = state
        self._native = None


class RngSeedManager:
    """Global (seed, run) state + stream allocation
    (src/core/model/rng-seed-manager.{h,cc})."""

    _next_stream = 0

    @classmethod
    def SetSeed(cls, seed: int) -> None:
        GlobalValue.Bind("RngSeed", int(seed))

    @classmethod
    def GetSeed(cls) -> int:
        return GlobalValue.GetValue("RngSeed")

    @classmethod
    def SetRun(cls, run: int) -> None:
        GlobalValue.Bind("RngRun", int(run))

    @classmethod
    def GetRun(cls) -> int:
        return GlobalValue.GetValue("RngRun")

    @classmethod
    def GetNextStreamIndex(cls) -> int:
        idx = cls._next_stream
        cls._next_stream += 1
        return idx

    @classmethod
    def Reset(cls) -> None:
        cls._next_stream = 0


def seeded_bulk_generator(stream_id: int = 0):
    """A ``numpy.random.Generator`` whose seed material is the global
    ``(RngSeed, RngRun)`` pair plus a caller stream id — the bridge
    between the seeded-stream reproducibility contract and consumers
    that need BULK array draws (topology generation: a 10k-node BA
    graph cannot afford one scalar MRG32k3a call per edge).

    Same ``(RngSeed, RngRun, stream_id)`` → identical draws; changing
    ``RngRun`` re-randomizes every stream, exactly as it does for
    :class:`RngStream` substreams.  This is the ONLY sanctioned
    ``np.random`` entry point outside ops kernels (the analysis gate's
    RNG002 exempts this module)."""
    import numpy as np

    return np.random.default_rng(
        np.random.SeedSequence(
            entropy=(
                int(RngSeedManager.GetSeed()),
                int(RngSeedManager.GetRun()),
                int(stream_id),
            )
        )
    )


class RandomVariableStream(Object):
    """Base of all distributions
    (src/core/model/random-variable-stream.{h,cc}). Each instance owns an
    RngStream; ``SetStream`` pins the stream index for reproducibility
    (the per-model ``AssignStreams`` contract)."""

    tid = (
        TypeId("tpudes::RandomVariableStream")
        .AddAttribute("Stream", "Stream index (-1 = auto-allocate)", -1)
        .AddAttribute("Antithetic", "Use antithetic (1-u) variates", False)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._rng: RngStream | None = None

    def _stream_rng(self) -> RngStream:
        if self._rng is None:
            if self.stream < 0:
                self.stream = RngSeedManager.GetNextStreamIndex()
            self._rng = RngStream(
                RngSeedManager.GetSeed(), self.stream, RngSeedManager.GetRun()
            )
        return self._rng

    def SetStream(self, stream: int) -> None:
        self.stream = stream
        self._rng = None

    def GetStream(self) -> int:
        return self.stream

    def _u01(self) -> float:
        u = self._stream_rng().RandU01()
        return 1.0 - u if self.antithetic else u

    def GetValue(self) -> float:
        raise NotImplementedError

    def GetInteger(self) -> int:
        return int(self.GetValue())


class UniformRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::UniformRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Min", "Lower bound", 0.0)
        .AddAttribute("Max", "Upper bound (exclusive)", 1.0)
    )

    def GetValue(self, min=None, max=None) -> float:
        lo = self.min if min is None else min
        hi = self.max if max is None else max
        return lo + self._u01() * (hi - lo)

    def GetInteger(self, min=None, max=None) -> int:
        lo = int(self.min if min is None else min)
        hi = int(self.max if max is None else max)
        return lo + int(self._u01() * (hi - lo + 1))


class ConstantRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ConstantRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Constant", "The constant value", 0.0)
    )

    def GetValue(self) -> float:
        return self.constant


class SequentialRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::SequentialRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Min", "First value", 0.0)
        .AddAttribute("Max", "Bound (restart below it)", 10.0)
        .AddAttribute("Increment", "Step", 1.0)
        .AddAttribute("Consecutive", "Repeats per value", 1)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._current = None
        self._count = 0

    def GetValue(self) -> float:
        if self._current is None:
            self._current = self.min
        value = self._current
        self._count += 1
        if self._count >= self.consecutive:
            self._count = 0
            inc = self.increment.GetValue() if hasattr(self.increment, "GetValue") else self.increment
            self._current += inc
            if self._current >= self.max:
                self._current = self.min
        return value


class ExponentialRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ExponentialRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Mean", "Mean 1/lambda", 1.0)
        .AddAttribute("Bound", "Upper truncation (0 = none)", 0.0)
    )

    def GetValue(self, mean=None, bound=None) -> float:
        mean = self.mean if mean is None else mean
        bound = self.bound if bound is None else bound
        while True:
            v = -mean * math.log(1.0 - self._u01())
            if bound == 0.0 or v <= bound:
                return v


class ParetoRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ParetoRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Scale", "Scale xm", 1.0)
        .AddAttribute("Shape", "Shape alpha", 2.0)
        .AddAttribute("Bound", "Upper truncation (0 = none)", 0.0)
    )

    def GetValue(self) -> float:
        while True:
            v = self.scale / (1.0 - self._u01()) ** (1.0 / self.shape)
            if self.bound == 0.0 or v <= self.bound:
                return v


class WeibullRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::WeibullRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Scale", "Scale lambda", 1.0)
        .AddAttribute("Shape", "Shape k", 1.0)
        .AddAttribute("Bound", "Upper truncation (0 = none)", 0.0)
    )

    def GetValue(self) -> float:
        while True:
            v = self.scale * (-math.log(1.0 - self._u01())) ** (1.0 / self.shape)
            if self.bound == 0.0 or v <= self.bound:
                return v


class NormalRandomVariable(RandomVariableStream):
    INFINITE_VALUE = 1e307

    tid = (
        TypeId("tpudes::NormalRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Mean", "Mean", 0.0)
        .AddAttribute("Variance", "Variance", 1.0)
        .AddAttribute("Bound", "Symmetric bound around mean", 1e307)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._next: float | None = None

    def GetValue(self, mean=None, variance=None, bound=None) -> float:
        mean = self.mean if mean is None else mean
        variance = self.variance if variance is None else variance
        bound = self.bound if bound is None else bound
        std = math.sqrt(variance)
        while True:
            if self._next is not None:
                z, self._next = self._next, None
            else:
                # Box-Muller (polar), as ns-3 does
                while True:
                    u1 = 2.0 * self._u01() - 1.0
                    u2 = 2.0 * self._u01() - 1.0
                    w = u1 * u1 + u2 * u2
                    if 0.0 < w < 1.0:
                        break
                y = math.sqrt(-2.0 * math.log(w) / w)
                z = u1 * y
                self._next = u2 * y
            v = mean + z * std
            if abs(v - mean) <= bound:
                return v


class LogNormalRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::LogNormalRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Mu", "Location mu (of ln X)", 0.0)
        .AddAttribute("Sigma", "Scale sigma (of ln X)", 1.0)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._normal = None

    def GetValue(self, mu=None, sigma=None) -> float:
        mu = self.mu if mu is None else mu
        sigma = self.sigma if sigma is None else sigma
        if self._normal is None:
            self._normal = NormalRandomVariable(Stream=0)
            self._normal._rng = self._stream_rng()  # share the stream
        z = self._normal.GetValue(0.0, 1.0, NormalRandomVariable.INFINITE_VALUE)
        return math.exp(mu + sigma * z)


class GammaRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::GammaRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Alpha", "Shape alpha", 1.0)
        .AddAttribute("Beta", "Scale beta", 1.0)
    )

    def GetValue(self, alpha=None, beta=None) -> float:
        alpha = self.alpha if alpha is None else alpha
        beta = self.beta if beta is None else beta
        # Marsaglia-Tsang; boost for alpha < 1 via U^(1/alpha) trick
        if alpha < 1.0:
            u = self._u01()
            return self.GetValue(alpha + 1.0, beta) * u ** (1.0 / alpha)
        d = alpha - 1.0 / 3.0
        c = 1.0 / math.sqrt(9.0 * d)
        while True:
            while True:
                # standard normal via Box-Muller polar
                u1 = 2.0 * self._u01() - 1.0
                u2 = 2.0 * self._u01() - 1.0
                w = u1 * u1 + u2 * u2
                if 0.0 < w < 1.0:
                    break
            x = u1 * math.sqrt(-2.0 * math.log(w) / w)
            v = (1.0 + c * x) ** 3
            if v <= 0:
                continue
            u = self._u01()
            if u < 1.0 - 0.0331 * x**4:
                return beta * d * v
            if math.log(u) < 0.5 * x * x + d * (1.0 - v + math.log(v)):
                return beta * d * v


class ErlangRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ErlangRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("K", "Shape k (integer)", 1)
        .AddAttribute("Lambda", "Rate lambda", 1.0, field="lam")
    )

    def GetValue(self, k=None, lam=None) -> float:
        k = self.k if k is None else k
        lam = self.lam if lam is None else lam
        total = 0.0
        for _ in range(int(k)):
            total += -math.log(1.0 - self._u01())
        return total / lam


class TriangularRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::TriangularRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Min", "Lower bound", 0.0)
        .AddAttribute("Max", "Upper bound", 1.0)
        .AddAttribute("Mean", "Mode-determining mean", 0.5)
    )

    def GetValue(self) -> float:
        a, b, mean = self.min, self.max, self.mean
        mode = 3.0 * mean - a - b
        u = self._u01()
        if u <= (mode - a) / (b - a):
            return a + math.sqrt(u * (b - a) * (mode - a))
        return b - math.sqrt((1.0 - u) * (b - a) * (b - mode))


class ZipfRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ZipfRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("N", "Number of outcomes", 1)
        .AddAttribute("Alpha", "Exponent alpha", 0.0)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._c_for = None  # (n, alpha) the cached constant was computed for
        self._c = None

    def GetValue(self) -> float:
        if self._c_for != (self.n, self.alpha):
            self._c = 1.0 / sum(1.0 / i**self.alpha for i in range(1, self.n + 1))
            self._c_for = (self.n, self.alpha)
        u = self._u01()
        acc = 0.0
        for i in range(1, self.n + 1):
            acc += self._c / i**self.alpha
            if u <= acc:
                return float(i)
        return float(self.n)


class ZetaRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::ZetaRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Alpha", "Exponent alpha (> 1)", 3.14)
    )

    def GetValue(self) -> float:
        # Devroye's rejection method, as ns-3 uses
        a = self.alpha
        b = 2.0 ** (a - 1.0)
        while True:
            u = self._u01()
            v = self._u01()
            x = math.floor(u ** (-1.0 / (a - 1.0)))
            t = (1.0 + 1.0 / x) ** (a - 1.0)
            if v * x * (t - 1.0) / (b - 1.0) <= t / b:
                return x


class DeterministicRandomVariable(RandomVariableStream):
    tid = TypeId("tpudes::DeterministicRandomVariable").SetParent(RandomVariableStream.tid)

    def __init__(self, values=(), **attributes):
        super().__init__(**attributes)
        self._values = list(values)
        self._i = 0

    def SetValueArray(self, values) -> None:
        self._values = list(values)
        self._i = 0

    def GetValue(self) -> float:
        v = self._values[self._i % len(self._values)]
        self._i += 1
        return v


class EmpiricalRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::EmpiricalRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Interpolate", "Linear-interpolate between CDF points", False)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._cdf: list[tuple[float, float]] = []  # (value, cumulative prob)

    def CDF(self, value: float, prob: float) -> None:
        self._cdf.append((value, prob))
        self._cdf.sort(key=lambda p: p[1])

    def GetValue(self) -> float:
        u = self._u01()
        prev_v, prev_p = None, 0.0
        for v, p in self._cdf:
            if u <= p:
                if self.interpolate and prev_v is not None and p > prev_p:
                    return prev_v + (v - prev_v) * (u - prev_p) / (p - prev_p)
                return v
            prev_v, prev_p = v, p
        return self._cdf[-1][0] if self._cdf else 0.0


class BernoulliRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::BernoulliRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Probability", "Probability of 1", 0.5)
    )

    def GetValue(self) -> float:
        return 1.0 if self._u01() < self.probability else 0.0


class BinomialRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::BinomialRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Trials", "Number of trials n", 10)
        .AddAttribute("Probability", "Success probability p", 0.5)
    )

    def GetValue(self) -> float:
        return float(sum(1 for _ in range(self.trials) if self._u01() < self.probability))


class LaplacianRandomVariable(RandomVariableStream):
    tid = (
        TypeId("tpudes::LaplacianRandomVariable")
        .SetParent(RandomVariableStream.tid)
        .AddAttribute("Location", "Location mu", 0.0)
        .AddAttribute("Scale", "Scale b", 1.0)
        .AddAttribute("Bound", "Symmetric truncation (0 = none)", 0.0)
    )

    def GetValue(self) -> float:
        while True:
            u = self._u01() - 0.5
            v = self.location - self.scale * math.copysign(1.0, u) * math.log(
                1.0 - 2.0 * abs(u)
            )
            if self.bound == 0.0 or abs(v - self.location) <= self.bound:
                return v
