"""Callback and tracing primitives — the hook mechanism for ALL
observability.

Reference parity: src/core/model/callback.h, traced-callback.h,
traced-value.h (SURVEY.md 2.1, 5.1). In Python any callable is a
``Callback``; ``MakeCallback`` exists for source compatibility.
"""

from __future__ import annotations


def MakeCallback(fn, obj=None):
    if obj is None:
        return fn
    return lambda *args: fn(obj, *args)


def MakeNullCallback(*_):
    """A safely-invokable no-op sentinel, as in ns-3."""

    def _null(*_args, **_kw):
        return None

    _null.is_null = True
    return _null


class TracedCallback:
    """A list of connected sinks invoked on fire
    (src/core/model/traced-callback.h). ``Connect`` attaches a context
    string prepended to the sink's arguments, as Config.Connect does."""

    __slots__ = ("_sinks",)

    def __init__(self):
        self._sinks: list = []

    def ConnectWithoutContext(self, cb) -> None:
        self._sinks.append((None, cb))

    def Connect(self, cb, context: str) -> None:
        self._sinks.append((context, cb))

    def DisconnectWithoutContext(self, cb) -> None:
        self._sinks = [(c, s) for (c, s) in self._sinks if s is not cb]

    def Disconnect(self, cb, context: str) -> None:
        self._sinks = [(c, s) for (c, s) in self._sinks if not (s is cb and c == context)]

    def IsEmpty(self) -> bool:
        return not self._sinks

    def __call__(self, *args) -> None:
        for context, sink in self._sinks:
            if context is None:
                sink(*args)
            else:
                sink(context, *args)


class TracedValue:
    """A value that fires (old, new) callbacks on change
    (src/core/model/traced-value.h)."""

    __slots__ = ("_value", "_trace")

    def __init__(self, initial=None):
        self._value = initial
        self._trace = TracedCallback()

    def Get(self):
        return self._value

    def Set(self, value) -> None:
        if value != self._value:
            old = self._value
            self._value = value
            self._trace(old, value)

    def ConnectWithoutContext(self, cb) -> None:
        self._trace.ConnectWithoutContext(cb)

    def Connect(self, cb, context: str) -> None:
        self._trace.Connect(cb, context)

    def __repr__(self):
        return f"TracedValue({self._value!r})"
