"""Command-line parser binding script args, attributes, and GlobalValues.

Reference parity: src/core/model/command-line.{h,cc} (SURVEY.md 2.1).
Supported forms, as in ns-3:
  --name=value          a script-local value added with AddValue
  --GlobalName=value    any registered GlobalValue (RngRun, engine type...)
  --ns3::Class::Attr=v  a class attribute default (Config.SetDefault);
                        tpudes::Class::Attr equally accepted
  --PrintHelp / --help, --PrintGlobals, --PrintAttributes=<class>
"""

from __future__ import annotations

import sys

from tpudes.core.config import Config
from tpudes.core.global_value import GlobalValue
from tpudes.core.object import TypeId


class CommandLine:
    def __init__(self, usage: str = ""):
        self._usage = usage
        self._values: dict[str, dict] = {}

    def AddValue(self, name: str, help: str, default=None, callback=None):
        self._values[name] = {"help": help, "value": default, "callback": callback}

    def GetValue(self, name: str):
        return self._values[name]["value"]

    def __getattr__(self, name: str):
        # attribute access mirrors C++'s bind-by-reference ergonomics:
        # cmd.AddValue("nStas", ...) → cmd.nStas after Parse()
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]["value"]
        raise AttributeError(name)

    def Parse(self, argv=None) -> None:
        argv = list(sys.argv[1:] if argv is None else argv)
        for arg in argv:
            if arg in ("--PrintHelp", "--help"):
                self.PrintHelp()
                raise SystemExit(0)
            if arg == "--PrintGlobals":
                for gv in GlobalValue.Iterate():
                    print(f"    --{gv.name}=[{gv.value}]  {gv.help}")
                raise SystemExit(0)
            if arg.startswith("--PrintAttributes="):
                tid = TypeId.LookupByName(arg.split("=", 1)[1])
                for name, spec in tid.AllAttributes().items():
                    print(f"    --{tid.name}::{name}=[{spec.initial}]  {spec.help}")
                raise SystemExit(0)
            if not arg.startswith("--") or "=" not in arg:
                raise ValueError(f"unrecognized argument {arg!r}")
            name, _, value = arg[2:].partition("=")
            self._apply(name, value)

    def _apply(self, name: str, value: str) -> None:
        if name in self._values:
            slot = self._values[name]
            if slot["callback"] is not None:
                slot["callback"](value)
            else:
                slot["value"] = _coerce(value, slot["value"])
            return
        if "::" in name:
            Config.SetDefault(name, value)
            return
        if GlobalValue.BindFailSafe(name, _coerce_global(name, value)):
            return
        raise ValueError(f"unknown command-line argument --{name}")

    def PrintHelp(self) -> None:
        print(self._usage)
        if self._values:
            print("Program Options:")
            for name, slot in self._values.items():
                print(f"    --{name}=[{slot['value']}]  {slot['help']}")
        print("General options: --PrintHelp --PrintGlobals --PrintAttributes=<type>")


def _coerce(value: str, template):
    """Parse a CLI string toward the type of the current/default value."""
    if isinstance(template, bool):
        return value.lower() in ("1", "true", "t", "yes", "y")
    if isinstance(template, int):
        return int(value)
    if isinstance(template, float):
        return float(value)
    return value


def _coerce_global(name: str, value: str):
    current = GlobalValue.GetValueFailSafe(name)
    return _coerce(value, current)
