"""Object model: TypeId registry, typed attributes, aggregation, tracing
metadata.

Reference parity: src/core/model/object.{h,cc}, type-id.{h,cc},
object-base.{h,cc}, attribute.{h,cc} and the *-value files, plus
object-factory.{h,cc} (SURVEY.md 2.1).

Differences from ns-3, by design (idiomatic Python, same capability):
- ``Ptr<>`` ref-counting is Python's GC; ``Ptr`` is not reproduced.
- Attribute *values* are plain Python objects; the typed
  ``IntegerValue``/``StringValue`` wrappers collapse into optional
  ``checker`` callables that parse/validate (strings from the command line
  are parsed by the checker, preserving the string-settable contract).
- An attribute binds to a python field on the instance (``field``), so
  model code reads ``self.data_rate`` directly at C speed while
  ``SetAttribute("DataRate", "5Mbps")`` remains the configuration surface.
"""

from __future__ import annotations

import copy


class AttributeSpec:
    __slots__ = ("name", "help", "initial", "field", "checker", "flags")

    def __init__(self, name, help, initial, field, checker=None, flags="rw"):
        self.name = name
        self.help = help
        self.initial = initial
        self.field = field
        self.checker = checker
        self.flags = flags


class TraceSourceSpec:
    __slots__ = ("name", "help", "field")

    def __init__(self, name, help, field):
        self.name = name
        self.help = help
        self.field = field


class TypeId:
    """Run-time type metadata: name, parent, constructor, attributes,
    trace sources (src/core/model/type-id.{h,cc}). Fluent API mirrors
    ns-3's ``GetTypeId`` idiom."""

    _registry: dict[str, "TypeId"] = {}

    def __init__(self, name: str):
        self.name = name
        self.parent: TypeId | None = None
        self.group = ""
        self.ctor = None
        self.attributes: dict[str, AttributeSpec] = {}
        self.trace_sources: dict[str, TraceSourceSpec] = {}
        TypeId._registry[name] = self
        # accept the ns3:: spelling of our own TypeIds for script parity
        if name.startswith("tpudes::"):
            TypeId._registry["ns3::" + name[len("tpudes::"):]] = self

    # --- fluent declaration API ---
    def SetParent(self, parent: "TypeId | None") -> "TypeId":
        self.parent = parent
        return self

    def SetGroupName(self, group: str) -> "TypeId":
        self.group = group
        return self

    def AddConstructor(self, ctor) -> "TypeId":
        self.ctor = ctor
        return self

    def AddAttribute(self, name, help, initial, field=None, checker=None) -> "TypeId":
        field = field or _default_field(name)
        self.attributes[name] = AttributeSpec(name, help, initial, field, checker)
        return self

    def AddTraceSource(self, name, help, field=None) -> "TypeId":
        field = field or _default_field(name)
        self.trace_sources[name] = TraceSourceSpec(name, help, field)
        return self

    # --- lookup ---
    @staticmethod
    def LookupByName(name: str) -> "TypeId":
        tid = TypeId._registry.get(name)
        if tid is None:
            raise KeyError(f"unknown TypeId {name!r}")
        return tid

    @staticmethod
    def LookupByNameFailSafe(name: str) -> "TypeId | None":
        return TypeId._registry.get(name)

    def LookupAttribute(self, name: str) -> AttributeSpec | None:
        tid = self
        while tid is not None:
            spec = tid.attributes.get(name)
            if spec is not None:
                return spec
            tid = tid.parent
        return None

    def LookupTraceSource(self, name: str) -> TraceSourceSpec | None:
        tid = self
        while tid is not None:
            spec = tid.trace_sources.get(name)
            if spec is not None:
                return spec
            tid = tid.parent
        return None

    def AllAttributes(self) -> dict[str, AttributeSpec]:
        out = {}
        chain = []
        tid = self
        while tid is not None:
            chain.append(tid)
            tid = tid.parent
        for tid in reversed(chain):
            out.update(tid.attributes)
        return out

    def IsChildOf(self, other: "TypeId") -> bool:
        tid = self
        while tid is not None:
            if tid is other:
                return True
            tid = tid.parent
        return False

    def GetName(self) -> str:
        return self.name

    def __repr__(self):
        return f"TypeId({self.name})"


def _default_field(name: str) -> str:
    # "DataRate" -> "data_rate"
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i > 0 and (not name[i - 1].isupper()):
            out.append("_")
        out.append(c.lower())
    return "".join(out)


# module-level defaults overridden by Config.SetDefault (config.py)
_DEFAULT_OVERRIDES: dict[tuple[str, str], object] = {}


def set_default(tid_name: str, attr: str, value) -> None:
    tid = TypeId.LookupByName(tid_name)
    spec = tid.LookupAttribute(attr)
    if spec is None:
        raise KeyError(f"{tid_name} has no attribute {attr!r}")
    if isinstance(value, str) and spec.checker is None:
        # coerce CLI strings toward the type of the declared default
        if isinstance(spec.initial, bool):
            value = value.lower() in ("1", "true", "t", "yes", "y")
        elif isinstance(spec.initial, int):
            value = int(float(value))
        elif isinstance(spec.initial, float):
            value = float(value)
    _DEFAULT_OVERRIDES[(tid.name, attr)] = value


class ObjectBase:
    """Attribute plumbing shared by Object and helper-constructed types
    (src/core/model/object-base.{h,cc})."""

    tid: TypeId | None = None  # set by each class

    @classmethod
    def GetTypeId(cls) -> TypeId:
        return cls.tid

    def construct_attributes(self, overrides: dict | None = None):
        """Apply attribute defaults (plus Config.SetDefault overrides and
        per-construct overrides) to this instance's fields, and
        instantiate declared trace sources."""
        from tpudes.core.trace import TracedCallback

        tid = type(self).GetTypeId()
        if tid is None:
            return
        for name, spec in tid.AllAttributes().items():
            value = spec.initial
            # walk override chain: class default overrides first
            t = tid
            while t is not None:
                if (t.name, name) in _DEFAULT_OVERRIDES:
                    value = _DEFAULT_OVERRIDES[(t.name, name)]
                    break
                t = t.parent
            if overrides and name in overrides:
                value = overrides[name]
            if spec.checker is not None:
                value = spec.checker(value)
            elif isinstance(value, (list, dict)):
                value = copy.copy(value)
            setattr(self, spec.field, value)
        # trace sources: instantiate a TracedCallback per declared source
        t = tid
        while t is not None:
            for name, ts in t.trace_sources.items():
                if not hasattr(self, ts.field):
                    setattr(self, ts.field, TracedCallback())
            t = t.parent

    def SetAttribute(self, name: str, value) -> None:
        spec = self._lookup_or_raise(name)
        if spec.checker is not None:
            value = spec.checker(value)
        setattr(self, spec.field, value)

    def SetAttributeFailSafe(self, name: str, value) -> bool:
        tid = type(self).GetTypeId()
        spec = tid.LookupAttribute(name) if tid else None
        if spec is None:
            return False
        if spec.checker is not None:
            try:
                value = spec.checker(value)
            except (ValueError, TypeError):
                return False
        setattr(self, spec.field, value)
        return True

    def GetAttribute(self, name: str):
        spec = self._lookup_or_raise(name)
        return getattr(self, spec.field)

    def _lookup_or_raise(self, name: str) -> AttributeSpec:
        tid = type(self).GetTypeId()
        spec = tid.LookupAttribute(name) if tid is not None else None
        if spec is None:
            raise KeyError(f"{type(self).__name__} has no attribute {name!r}")
        return spec

    def TraceConnectWithoutContext(self, name: str, cb) -> bool:
        tid = type(self).GetTypeId()
        spec = tid.LookupTraceSource(name) if tid is not None else None
        if spec is None:
            return False
        getattr(self, spec.field).ConnectWithoutContext(cb)
        return True

    def TraceConnect(self, name: str, context: str, cb) -> bool:
        tid = type(self).GetTypeId()
        spec = tid.LookupTraceSource(name) if tid is not None else None
        if spec is None:
            return False
        getattr(self, spec.field).Connect(cb, context)
        return True


class Object(ObjectBase):
    """Base for simulation objects: attribute construction + aggregation
    (src/core/model/object.{h,cc}). ``AggregateObject`` links objects into
    one queryable group — e.g. a Node aggregates Ipv4, mobility models."""

    def __init__(self, **attributes):
        self._aggregates: list[Object] = [self]
        self._disposed = False
        self.construct_attributes(attributes or None)

    def AggregateObject(self, other: "Object") -> None:
        # merge the two aggregate rings
        group = self._aggregates
        for o in other._aggregates:
            if o not in group:
                group.append(o)
        for o in group:
            o._aggregates = group

    def GetObject(self, cls_or_tid):
        """Find an aggregated object by class or TypeId."""
        if isinstance(cls_or_tid, TypeId):
            for o in self._aggregates:
                otid = type(o).GetTypeId()
                if otid is not None and otid.IsChildOf(cls_or_tid):
                    return o
            return None
        for o in self._aggregates:
            if isinstance(o, cls_or_tid):
                return o
        return None

    def Dispose(self) -> None:
        if not self._disposed:
            self._disposed = True
            self.DoDispose()

    def DoDispose(self) -> None:
        pass

    def Initialize(self) -> None:
        self.DoInitialize()

    def DoInitialize(self) -> None:
        pass


class ObjectFactory:
    """Creates objects from a TypeId name + attribute overrides
    (src/core/model/object-factory.{h,cc})."""

    def __init__(self, type_name: str | None = None, **attributes):
        self._tid: TypeId | None = None
        self._attributes = dict(attributes)
        if type_name:
            self.SetTypeId(type_name)

    def SetTypeId(self, name: str | TypeId) -> None:
        self._tid = name if isinstance(name, TypeId) else TypeId.LookupByName(name)

    def Set(self, name: str, value) -> "ObjectFactory":
        self._attributes[name] = value
        return self

    def Create(self):
        if self._tid is None or self._tid.ctor is None:
            raise RuntimeError(f"ObjectFactory: no constructor for {self._tid}")
        return self._tid.ctor(**self._attributes)

    def GetTypeId(self) -> TypeId | None:
        return self._tid
