"""ConfigStore: save/load the attribute-default + GlobalValue universe.

Reference parity: src/config-store/model/config-store.{h,cc},
raw-text-config.{h,cc} (upstream paths; mount empty at survey —
SURVEY.md §0, §2.10, §5.6 "ConfigStore missing" row).

RawText format, upstream-shaped::

    default tpudes::PointToPointNetDevice::DataRate "5Mbps"
    global RngRun "7"
    value /NodeList/3/$tpudes::Node/Id "3"        # per-object dump

``Mode=Save`` writes the whole registered attribute universe (every
TypeId attribute's effective default, every GlobalValue) so a run's
parameter set is reproducible; ``Mode=Load`` replays a saved file
through Config.SetDefault / GlobalValue.Bind before the scenario
constructs objects.  Values are stored as strings and coerced toward
the registered initial's type on load, exactly like the
NS_GLOBAL_VALUE environment hook.
"""

from __future__ import annotations

from tpudes.core.global_value import GlobalValue
from tpudes.core.object import TypeId, _DEFAULT_OVERRIDES


def _coerce(initial, text: str):
    if isinstance(initial, bool):
        return text.lower() in ("1", "true", "t", "yes", "y")
    if isinstance(initial, int) and not isinstance(initial, bool):
        try:
            return int(text)
        except ValueError:
            return text
    if isinstance(initial, float):
        try:
            return float(text)
        except ValueError:
            return text
    return text


def _storable(value) -> bool:
    return isinstance(value, (bool, int, float, str))


class ConfigStore:
    tid = (
        TypeId("tpudes::ConfigStore")
        .AddConstructor(lambda **kw: ConfigStore(**kw))
        .AddAttribute("Mode", "Save | Load | None", "None")
        .AddAttribute("Filename", "raw-text file", "config.txt")
        .AddAttribute("FileFormat", "RawText (the only format)", "RawText")
    )

    def __init__(self, **attributes):
        # plain object (not Object) keeps ConfigStore constructible
        # before any simulator state exists, as upstream
        spec = {a.name: a.initial for a in self.tid.attributes.values()}
        for k, v in attributes.items():
            if k not in spec:
                raise ValueError(f"unknown ConfigStore attribute {k!r}")
            spec[k] = v
        self.mode = spec["Mode"]
        self.filename = spec["Filename"]
        if spec["FileFormat"] != "RawText":
            raise ValueError("only the RawText format is implemented")

    # --- the upstream entry point ----------------------------------------
    def ConfigureDefaults(self) -> None:
        if self.mode == "Save":
            self._save()
        elif self.mode == "Load":
            self._load()

    ConfigureAttributes = ConfigureDefaults  # one pass covers both here

    # --- save -------------------------------------------------------------
    def _save(self) -> None:
        seen: set[int] = set()
        with open(self.filename, "w") as f:
            for name, tid in sorted(TypeId._registry.items()):
                if name.startswith("ns3::") or id(tid) in seen:
                    continue  # skip alias spellings, each tid once
                seen.add(id(tid))
                for attr in tid.attributes.values():
                    value = _DEFAULT_OVERRIDES.get(
                        (tid.name, attr.name), attr.initial
                    )
                    if _storable(value):
                        f.write(f'default {name}::{attr.name} "{value}"\n')
            for gv in GlobalValue.Iterate():
                if _storable(gv.value):
                    f.write(f'global {gv.name} "{gv.value}"\n')

    # --- load -------------------------------------------------------------
    def _load(self) -> None:
        from tpudes.core.config import Config

        with open(self.filename) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                kind, _, rest = line.partition(" ")
                path, _, quoted = rest.partition(" ")
                text = quoted.strip().strip('"')
                if kind == "default":
                    tid_name, _, attr = path.rpartition("::")
                    tid = TypeId._registry.get(tid_name)
                    if tid is None or attr not in tid.attributes:
                        continue  # a build without that model
                    Config.SetDefault(
                        path, _coerce(tid.attributes[attr].initial, text)
                    )
                elif kind == "global":
                    gv = GlobalValue._registry.get(path)
                    if gv is not None:
                        GlobalValue.Bind(path, _coerce(gv.initial, text))
                else:
                    raise ValueError(
                        f"{self.filename}:{lineno}: unknown directive {kind!r}"
                    )
