"""Simulation time: 64-bit integer ticks with settable resolution.

Reference parity: src/core/model/nstime.h, time.cc (SURVEY.md 2.1).
ns-3 stores time as int64 ticks at a process-global resolution (default
nanoseconds) and uses int64x64 fixed-point only for multiplication by
non-integers; here Python's arbitrary-precision ints make the fixed-point
type unnecessary — tick arithmetic is exact by construction.

The hot path (the event loop) deals in *raw integer ticks*; ``Time`` is the
user-facing wrapper. Helper constructors (``Seconds`` etc.) mirror the
ns-3 free functions.
"""

from __future__ import annotations

import re

# Unit exponents relative to seconds (powers of ten), ns-3 Time::Unit order.
_UNITS = {
    "Y": None,  # year — handled specially (not power of ten)
    "d": None,
    "h": None,
    "min": None,
    "s": 0,
    "ms": -3,
    "us": -6,
    "ns": -9,
    "ps": -12,
    "fs": -15,
}

# seconds per non-decimal unit
_ODD_UNITS = {"Y": 365 * 86400, "d": 86400, "h": 3600, "min": 60}


class Time:
    """An amount of simulated time, stored as integer ticks.

    Resolution is process-global (default: nanoseconds), mirroring
    ns-3 ``Time::SetResolution``. Changing resolution is only allowed
    while no simulator is running.
    """

    __slots__ = ("ticks",)

    # --- process-global resolution state ---
    _res_exp = -9  # 10^-9 s per tick (nanoseconds), ns-3 default
    _res_name = "ns"

    S = 0
    MS = 1
    US = 2
    NS = 3
    PS = 4
    FS = 5

    _UNIT_TO_NAME = {S: "s", MS: "ms", US: "us", NS: "ns", PS: "ps", FS: "fs"}

    def __init__(self, value: "int | float | str | Time" = 0):
        if isinstance(value, Time):
            self.ticks = value.ticks
        elif isinstance(value, int):
            self.ticks = value
        elif isinstance(value, float):
            # ns-3: a bare number is *seconds* when given as a string, but a
            # raw numeric ctor arg is ticks. Floats as ticks get rounded.
            self.ticks = int(round(value))
        elif isinstance(value, str):
            self.ticks = _parse_time_string(value)
        else:
            raise TypeError(f"cannot construct Time from {type(value)!r}")

    # --- resolution ---
    @classmethod
    def SetResolution(cls, unit: int) -> None:
        # ns-3 forbids changing resolution once Time objects exist; the
        # enforceable analogue here is "before the engine is created" —
        # tick values created under the old resolution would silently
        # rescale otherwise.
        from tpudes.core.simulator import Simulator

        if Simulator._impl is not None:
            raise RuntimeError("Time.SetResolution after simulator creation")
        name = cls._UNIT_TO_NAME[unit]
        cls._res_exp = _UNITS[name]
        cls._res_name = name

    @classmethod
    def GetResolution(cls) -> int:
        return {v: k for k, v in cls._UNIT_TO_NAME.items()}[cls._res_name]

    # --- constructors from units ---
    @classmethod
    def from_seconds(cls, s: float) -> "Time":
        return cls(int(round(s * 10 ** (-cls._res_exp))))

    @classmethod
    def from_unit(cls, value: float, exp: int) -> "Time":
        # value * 10^exp seconds, converted to ticks of 10^_res_exp seconds
        shift = exp - cls._res_exp
        if shift >= 0:
            return cls(int(round(value * 10**shift)))
        return cls(int(round(value / 10**(-shift))))

    # --- accessors ---
    def _in_unit(self, exp: int) -> int:
        shift = self._res_exp - exp
        if shift >= 0:
            return self.ticks * 10**shift
        return self.ticks // 10**(-shift)

    def GetSeconds(self) -> float:
        return self.ticks / 10 ** (-self._res_exp) if self._res_exp < 0 else float(self.ticks * 10**self._res_exp)

    def GetMilliSeconds(self) -> int:
        return self._in_unit(-3)

    def GetMicroSeconds(self) -> int:
        return self._in_unit(-6)

    def GetNanoSeconds(self) -> int:
        return self._in_unit(-9)

    def GetPicoSeconds(self) -> int:
        return self._in_unit(-12)

    def GetFemtoSeconds(self) -> int:
        return self._in_unit(-15)

    def GetTimeStep(self) -> int:
        return self.ticks

    def GetInteger(self) -> int:
        return self.ticks

    def GetDouble(self) -> float:
        return float(self.ticks)

    def IsZero(self) -> bool:
        return self.ticks == 0

    def IsNegative(self) -> bool:
        return self.ticks <= 0

    def IsPositive(self) -> bool:
        return self.ticks >= 0

    def IsStrictlyNegative(self) -> bool:
        return self.ticks < 0

    def IsStrictlyPositive(self) -> bool:
        return self.ticks > 0

    # --- arithmetic ---
    def __add__(self, other):
        return Time(self.ticks + Time(other).ticks)

    __radd__ = __add__

    def __sub__(self, other):
        return Time(self.ticks - Time(other).ticks)

    def __rsub__(self, other):
        return Time(Time(other).ticks - self.ticks)

    def __mul__(self, k):
        if isinstance(k, (int, float)):
            return Time(int(round(self.ticks * k)))
        return NotImplemented

    __rmul__ = __mul__

    def __truediv__(self, other):
        if isinstance(other, Time):
            return self.ticks / other.ticks
        if isinstance(other, (int, float)):
            return Time(int(round(self.ticks / other)))
        return NotImplemented

    def __floordiv__(self, other):
        if isinstance(other, Time):
            return self.ticks // other.ticks
        return NotImplemented

    def __mod__(self, other):
        if isinstance(other, Time):
            return Time(self.ticks % other.ticks)
        return NotImplemented

    def __neg__(self):
        return Time(-self.ticks)

    def __abs__(self):
        return Time(abs(self.ticks))

    # --- comparison / hashing ---
    def __eq__(self, other):
        return isinstance(other, Time) and self.ticks == other.ticks

    def __ne__(self, other):
        return not self.__eq__(other)

    def __lt__(self, other):
        return self.ticks < Time(other).ticks

    def __le__(self, other):
        return self.ticks <= Time(other).ticks

    def __gt__(self, other):
        return self.ticks > Time(other).ticks

    def __ge__(self, other):
        return self.ticks >= Time(other).ticks

    def __hash__(self):
        return hash(self.ticks)

    def __bool__(self):
        return self.ticks != 0

    def __repr__(self):
        return f"Time({self.ticks}{self._res_name})"

    def __str__(self):
        return f"+{self.ticks}{self._res_name}"

    def As(self, unit: int) -> str:
        name = self._UNIT_TO_NAME[unit]
        exp = _UNITS[name]
        val = self.ticks * 10.0 ** (self._res_exp - exp)
        return f"{val:+g}{name}"


_TIME_RE = re.compile(r"^\s*([+-]?[0-9.eE+-]+?)\s*(Y|d|h|min|s|ms|us|ns|ps|fs)?\s*$")


def _parse_time_string(s: str) -> int:
    m = _TIME_RE.match(s)
    if not m:
        raise ValueError(f"cannot parse time string {s!r}")
    num, unit = m.group(1), m.group(2) or "s"
    value = float(num)
    if unit in _ODD_UNITS:
        return Time.from_seconds(value * _ODD_UNITS[unit]).ticks
    return Time.from_unit(value, _UNITS[unit]).ticks


# ns-3 free-function constructors (src/core/model/nstime.h)
def Seconds(v: float) -> Time:
    return Time.from_seconds(v)


def MilliSeconds(v: float) -> Time:
    return Time.from_unit(v, -3)


def MicroSeconds(v: float) -> Time:
    return Time.from_unit(v, -6)


def NanoSeconds(v: float) -> Time:
    return Time.from_unit(v, -9)


def PicoSeconds(v: float) -> Time:
    return Time.from_unit(v, -12)


def FemtoSeconds(v: float) -> Time:
    return Time.from_unit(v, -15)


def Minutes(v: float) -> Time:
    return Time.from_seconds(v * 60)


def Hours(v: float) -> Time:
    return Time.from_seconds(v * 3600)


def Days(v: float) -> Time:
    return Time.from_seconds(v * 86400)


def TimeStep(ticks: int) -> Time:
    return Time(ticks)
