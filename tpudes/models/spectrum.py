"""Frequency-domain channel: SpectrumModel / SpectrumValue / channels.

Reference parity: src/spectrum/model/spectrum-model.{h,cc},
spectrum-value.{h,cc}, spectrum-channel.{h,cc},
single-model-spectrum-channel.{h,cc}, multi-model-spectrum-channel.{h,cc},
spectrum-phy.{h,cc}, spectrum-signal-parameters.{h,cc} (upstream paths;
mount empty at survey — SURVEY.md §0, §2.4).

TPU-first design: a ``SpectrumValue`` *is* an ndarray of PSD samples over
its model's band grid — upstream's "already array math" observation
(SURVEY.md §2.4) taken literally.  Channels keep the object-graph wiring
(Add/StartTx/schedule-rx) host-side; the per-band arithmetic (loss
application, PSD accumulation, integration) is numpy/jnp vector math so a
window engine can batch the full (tx × rx × band) grid in one kernel.
"""

from __future__ import annotations

import numpy as np

from tpudes.core.nstime import Seconds
from tpudes.core.object import Object, TypeId
from tpudes.core.simulator import Simulator


class BandInfo:
    """One frequency band: [fl, fc, fh] (spectrum-model.h BandInfo)."""

    __slots__ = ("fl", "fc", "fh")

    def __init__(self, fl: float, fc: float, fh: float):
        self.fl, self.fc, self.fh = fl, fc, fh

    @property
    def width(self) -> float:
        return self.fh - self.fl


class SpectrumModel:
    """A band grid; identity (uid) keyed so values over the same model
    can be combined without conversion (spectrum-model.cc)."""

    _next_uid = 1

    def __init__(self, bands: list[BandInfo]):
        self.bands = bands
        self.uid = SpectrumModel._next_uid
        SpectrumModel._next_uid += 1
        self.center_frequencies = np.array([b.fc for b in bands])
        self.band_widths = np.array([b.width for b in bands])

    @classmethod
    def FromCenters(cls, centers, width: float) -> "SpectrumModel":
        return cls([BandInfo(fc - width / 2.0, fc, fc + width / 2.0) for fc in centers])

    def GetNumBands(self) -> int:
        return len(self.bands)

    def IsOrthogonal(self, other: "SpectrumModel") -> bool:
        for a in self.bands:
            for b in other.bands:
                if a.fl < b.fh and b.fl < a.fh:
                    return False
        return True


class SpectrumValue:
    """PSD vector (W/Hz per band) over a SpectrumModel — a thin, mutable
    array wrapper with elementwise arithmetic (spectrum-value.cc)."""

    __slots__ = ("model", "values")

    def __init__(self, model: SpectrumModel, values=None):
        self.model = model
        self.values = (
            np.zeros(model.GetNumBands())
            if values is None
            else np.asarray(values, dtype=np.float64).copy()
        )

    def Copy(self) -> "SpectrumValue":
        return SpectrumValue(self.model, self.values)

    def _coerce(self, other):
        if isinstance(other, SpectrumValue):
            if other.model.uid != self.model.uid:
                raise ValueError("SpectrumValue arithmetic across models")
            return other.values
        return other

    def __add__(self, other):
        return SpectrumValue(self.model, self.values + self._coerce(other))

    def __sub__(self, other):
        return SpectrumValue(self.model, self.values - self._coerce(other))

    def __mul__(self, other):
        return SpectrumValue(self.model, self.values * self._coerce(other))

    def __truediv__(self, other):
        return SpectrumValue(self.model, self.values / self._coerce(other))

    __radd__ = __add__
    __rmul__ = __mul__

    def __iadd__(self, other):
        self.values += self._coerce(other)
        return self

    def __getitem__(self, i):
        return self.values[i]

    def __setitem__(self, i, v):
        self.values[i] = v

    def TotalPowerW(self) -> float:
        """∫ PSD df over the band grid (Integral(spectrumValue))."""
        return float(np.sum(self.values * self.model.band_widths))


class SpectrumSignalParameters:
    """Tx descriptor handed to SpectrumChannel::StartTx
    (spectrum-signal-parameters.h): psd + duration + sender."""

    def __init__(self, psd: SpectrumValue, duration_s: float, tx_phy=None):
        self.psd = psd
        self.duration_s = duration_s
        self.tx_phy = tx_phy
        self.payload = None  # packet / transport block rider


class SpectrumPhy(Object):
    """Abstract endpoint on a SpectrumChannel (spectrum-phy.h)."""

    tid = TypeId("tpudes::SpectrumPhy")

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._channel = None
        self._mobility = None
        self._device = None

    def SetChannel(self, channel) -> None:
        self._channel = channel
        channel.AddRx(self)

    def SetMobility(self, mobility) -> None:
        self._mobility = mobility

    def GetMobility(self):
        return self._mobility

    def SetDevice(self, device) -> None:
        self._device = device

    def GetDevice(self):
        return self._device

    def GetRxSpectrumModel(self) -> SpectrumModel | None:
        raise NotImplementedError

    def StartRx(self, params: SpectrumSignalParameters) -> None:
        raise NotImplementedError


class SingleModelSpectrumChannel(Object):
    """All endpoints share one SpectrumModel
    (single-model-spectrum-channel.cc): StartTx applies the loss-model
    chain per receiver and schedules StartRx after the propagation
    delay — the O(N_tx × N_rx) spectrum hot loop (SURVEY.md §3.4)."""

    tid = (
        TypeId("tpudes::SingleModelSpectrumChannel")
        .AddConstructor(lambda **kw: SingleModelSpectrumChannel(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._phys: list[SpectrumPhy] = []
        self._loss = None            # single-frequency PropagationLossModel
        self._spectrum_loss = None   # frequency-selective loss (optional)
        self._delay = None
        self._model: SpectrumModel | None = None

    def AddRx(self, phy: SpectrumPhy) -> None:
        model = phy.GetRxSpectrumModel()
        if model is not None:
            if self._model is None:
                self._model = model
            elif self._model.uid != model.uid:
                raise ValueError(
                    "SingleModelSpectrumChannel: mixed SpectrumModels "
                    "(use MultiModelSpectrumChannel)"
                )
        if phy not in self._phys:
            self._phys.append(phy)

    def AddPropagationLossModel(self, loss) -> None:
        self._loss = loss

    def AddSpectrumPropagationLossModel(self, loss) -> None:
        self._spectrum_loss = loss

    def SetPropagationDelayModel(self, delay) -> None:
        self._delay = delay

    def GetNDevices(self) -> int:
        return len(self._phys)

    def GetDevice(self, i: int):
        return self._phys[i].GetDevice()

    def _adapt_for_rx(self, psd: SpectrumValue, phy: SpectrumPhy):
        """Per-receiver PSD adaptation hook; the single-model channel
        delivers as-is, the multi-model subclass converts grids."""
        return psd

    def StartTx(self, params: SpectrumSignalParameters) -> None:
        sender = params.tx_phy
        sender_mob = sender.GetMobility() if sender is not None else None
        for phy in self._phys:
            if phy is sender:
                continue
            rx_mob = phy.GetMobility()
            psd = params.psd.Copy()
            delay_s = 0.0
            if sender_mob is not None and rx_mob is not None:
                if self._loss is not None:
                    gain_db = self._loss.CalcRxPower(0.0, sender_mob, rx_mob)
                    psd.values *= 10.0 ** (gain_db / 10.0)
                if self._spectrum_loss is not None:
                    psd = self._spectrum_loss.CalcRxPowerSpectralDensity(
                        psd, sender_mob, rx_mob
                    )
                if self._delay is not None:
                    delay_s = self._delay.GetDelay(sender_mob, rx_mob)
            psd = self._adapt_for_rx(psd, phy)
            rx_params = SpectrumSignalParameters(psd, params.duration_s, sender)
            rx_params.payload = params.payload
            node = phy.GetDevice().GetNode() if phy.GetDevice() else None
            Simulator.ScheduleWithContext(
                node.GetId() if node else 0,
                Seconds(delay_s),
                phy.StartRx,
                rx_params,
            )


class SpectrumConverter:
    """PSD conversion between SpectrumModels
    (src/spectrum/model/spectrum-converter.{h,cc}): each target band
    collects the power of every overlapping source band weighted by the
    overlap fraction, preserving total power over the shared range."""

    def __init__(self, from_model: SpectrumModel, to_model: SpectrumModel):
        import numpy as np

        self.from_model = from_model
        self.to_model = to_model
        F, T = from_model.GetNumBands(), to_model.GetNumBands()
        m = np.zeros((T, F))
        for t, tb in enumerate(to_model.bands):
            for f, fb in enumerate(from_model.bands):
                overlap = min(tb.fh, fb.fh) - max(tb.fl, fb.fl)
                if overlap > 0:
                    # power (W) moved = psd_from · overlap; back to PSD
                    # by the target band width
                    m[t, f] = overlap / tb.width
        self._matrix = m

    def Convert(self, value: SpectrumValue) -> SpectrumValue:
        out = SpectrumValue(self.to_model)
        out.values = self._matrix @ value.values
        return out


class MultiModelSpectrumChannel(SingleModelSpectrumChannel):
    """Heterogeneous-model channel
    (src/spectrum/model/multi-model-spectrum-channel.{h,cc}): receivers
    may use different SpectrumModels (LTE RB grid, WiFi band, …); the tx
    PSD is converted per receiver model through converters cached by
    model uid.  Everything else — loss chain, delay, delivery — is the
    single-model channel's loop, specialized only at the per-receiver
    adaptation hook."""

    tid = (
        TypeId("tpudes::MultiModelSpectrumChannel")
        .SetParent(SingleModelSpectrumChannel.tid)
        .AddConstructor(lambda **kw: MultiModelSpectrumChannel(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._converters: dict[tuple[int, int], SpectrumConverter] = {}

    def AddRx(self, phy: SpectrumPhy) -> None:
        # no single-model restriction; direct backref (SetChannel calls
        # AddRx, so calling it back would recurse)
        if phy not in self._phys:
            self._phys.append(phy)
            phy._channel = self

    def _adapt_for_rx(self, psd: SpectrumValue, phy: SpectrumPhy):
        to_model = phy.GetRxSpectrumModel()
        if to_model is None or psd.model.uid == to_model.uid:
            return psd
        key = (psd.model.uid, to_model.uid)
        conv = self._converters.get(key)
        if conv is None:
            conv = SpectrumConverter(psd.model, to_model)
            self._converters[key] = conv
        return conv.Convert(psd)


class ConstantSpectrumPropagationLossModel:
    """Frequency-flat spectrum loss (constant-spectrum-propagation-loss.cc)."""

    def __init__(self, loss_db: float = 0.0):
        self.loss_db = loss_db

    def CalcRxPowerSpectralDensity(self, psd: SpectrumValue, a, b) -> SpectrumValue:
        out = psd.Copy()
        out.values *= 10.0 ** (-self.loss_db / 10.0)
        return out


_UNIFORM_MODEL_CACHE: dict[tuple, SpectrumModel] = {}


def uniform_spectrum_model(
    center_hz: float, n_bands: int, band_hz: float
) -> SpectrumModel:
    """``n_bands`` equal bands around ``center_hz`` — CACHED by the grid
    parameters, so identical PHYs share one model uid (two fresh uids
    for the same grid would force needless conversion and break the
    single-model channel's same-model check)."""
    key = (float(center_hz), int(n_bands), float(band_hz))
    model = _UNIFORM_MODEL_CACHE.get(key)
    if model is None:
        low = center_hz - n_bands * band_hz / 2.0
        centers = [low + (i + 0.5) * band_hz for i in range(n_bands)]
        model = SpectrumModel.FromCenters(centers, band_hz)
        _UNIFORM_MODEL_CACHE[key] = model
    return model


def lte_spectrum_model(n_rb: int, carrier_hz: float) -> SpectrumModel:
    """The LTE RB grid as a SpectrumModel: n_rb bands of 180 kHz around
    the carrier (lte-spectrum-value-helper.cc)."""
    from tpudes.ops.lte import RB_BANDWIDTH_HZ

    return uniform_spectrum_model(carrier_hz, n_rb, RB_BANDWIDTH_HZ)
