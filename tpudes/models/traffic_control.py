"""Traffic control: queue discs between IP and the NetDevice.

Reference parity: src/traffic-control/model/traffic-control-layer.{h,cc},
queue-disc.{h,cc}, red-queue-disc.{h,cc}, codel-queue-disc.{h,cc},
fifo-queue-disc.{h,cc}, helper/traffic-control-helper.{h,cc} (upstream
paths; mount empty at survey — SURVEY.md §0, §2.7 traffic-control row).

Architecture mirrors upstream's intent with one structural difference:
upstream routes every L3 protocol (IPv4 AND ARP) through the
TrafficControlLayer's send callback; here the layer intercepts at the
device boundary — installing a root qdisc wraps ``device.Send`` — so
EVERY sender (IPv4 forwarding, ARP requests and resolved unicasts,
future protocols) goes through the qdisc with zero hot-path cost on
uninstalled nodes.  The layer drains the qdisc into the device under
flow control: "device ready" means its tx path is idle (one frame in
flight), so the backlog lives in the qdisc where RED/CoDel can see it,
not in the device's DropTail.  The drain re-arms off the device's
PhyTxEnd trace (the DeviceQueueInterface wake analog).

RED marks ECT packets CE instead of early-dropping when its ``UseEcn``
attribute is on (RFC 3168; forced-region and hard-cap losses still
drop, the UseHardDrop parity) — the DCTCP test pins the behavior.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from tpudes.core.nstime import Time
from tpudes.core.object import Object, TypeId
from tpudes.core.simulator import Simulator


@dataclass
class QueueDiscItem:
    """queue-disc-item.h: packet + L2 addressing it will need."""

    packet: object
    dest: object
    protocol: int
    enqueue_ts: int = 0

    def GetSize(self) -> int:
        return self.packet.GetSize()


class QueueDisc(Object):
    tid = (
        TypeId("tpudes::QueueDisc")
        .AddAttribute("MaxSize", "queue limit (packets)", 1000, field="max_packets")
        .AddTraceSource("Enqueue", "item queued")
        .AddTraceSource("Dequeue", "item dequeued")
        .AddTraceSource("Drop", "item dropped")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._items: list[QueueDiscItem] = []
        self.stats_enqueued = 0
        self.stats_dequeued = 0
        self.stats_dropped = 0

    def GetNPackets(self) -> int:
        return len(self._items)

    def GetNBytes(self) -> int:
        return sum(i.GetSize() for i in self._items)

    def Enqueue(self, item: QueueDiscItem) -> bool:
        item.enqueue_ts = Simulator.NowTicks()
        if not self.DoEnqueue(item):
            self.stats_dropped += 1
            self.drop(item.packet)
            return False
        self.stats_enqueued += 1
        self.enqueue(item.packet)
        return True

    def Dequeue(self) -> QueueDiscItem | None:
        item = self.DoDequeue()
        if item is not None:
            self.stats_dequeued += 1
            self.dequeue(item.packet)
        return item

    # --- overridables -----------------------------------------------------
    def DoEnqueue(self, item: QueueDiscItem) -> bool:
        raise NotImplementedError

    def DoDequeue(self) -> QueueDiscItem | None:
        raise NotImplementedError


def _mark_ce(packet) -> bool:
    """Set the CE codepoint on an ECT packet's IP header; returns False
    for non-ECT traffic (which must be dropped instead, RFC 3168)."""
    import copy

    from tpudes.models.internet.ipv4 import Ipv4Header

    front = packet.PeekHeader(Ipv4Header)
    if front is None or (front.tos & 0x3) == 0:
        return False  # not ECN-capable transport
    # COW discipline: never mutate a header other holders may share
    packet.RemoveHeader(Ipv4Header)
    marked = copy.copy(front)
    marked.tos = (marked.tos & ~0x3) | 0x3
    packet.AddHeader(marked)
    return True


class FifoQueueDisc(QueueDisc):
    """fifo-queue-disc.{h,cc}: plain tail-drop FIFO."""

    tid = (
        TypeId("tpudes::FifoQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: FifoQueueDisc(**kw))
    )

    def DoEnqueue(self, item) -> bool:
        if len(self._items) >= self.max_packets:
            return False
        self._items.append(item)
        return True

    def DoDequeue(self):
        return self._items.pop(0) if self._items else None


class RedQueueDisc(QueueDisc):
    """RED (Floyd & Jacobson 1993; red-queue-disc.{h,cc}): EWMA average
    queue with probabilistic early drop between MinTh and MaxTh."""

    tid = (
        TypeId("tpudes::RedQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: RedQueueDisc(**kw))
        .AddAttribute("MinTh", "lower threshold (packets)", 5.0, field="min_th")
        .AddAttribute("MaxTh", "upper threshold (packets)", 15.0, field="max_th")
        .AddAttribute("QW", "EWMA weight", 0.002, field="qw")
        .AddAttribute("LInterm", "1/max_p", 50.0, field="l_interm")
        .AddAttribute("Gentle", "gentle RED above MaxTh", True, field="gentle")
        .AddAttribute(
            "LinkBandwidth", "for the idle-time EWMA decay",
            "10Mbps", field="link_bw", checker=None,
        )
        .AddAttribute("MeanPktSize", "for the idle-time decay", 1000,
                      field="mean_pkt_size")
        .AddAttribute(
            "UseEcn",
            "mark ECT packets CE instead of early-dropping (RFC 3168; "
            "forced drops at the hard limit still drop)",
            False, field="use_ecn",
        )
        .AddAttribute(
            "UseHardDrop",
            "drop (even ECT) in the forced region avg >= MaxTh "
            "(red-queue-disc.cc parity; DCTCP setups turn this off so "
            "marking alone governs)",
            True, field="use_hard_drop",
        )
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        from tpudes.core.rng import UniformRandomVariable
        from tpudes.network.data_rate import DataRate

        self._avg = 0.0
        self._count = 0          # packets since last drop
        self._idle_since: int | None = 0
        self._pkt_tx_ticks = max(
            int(8 * self.mean_pkt_size / DataRate(self.link_bw).GetBitRate()
                * 1e9),
            1,
        )
        self._rng = UniformRandomVariable()
        self.stats_early_drops = 0
        self.stats_forced_drops = 0
        self.stats_marked = 0

    def DoEnqueue(self, item) -> bool:
        # Floyd's idle correction: while the queue sat empty the average
        # decays as if m small packets had passed (red-queue-disc.cc)
        if not self._items and self._idle_since is not None:
            m = (Simulator.NowTicks() - self._idle_since) / self._pkt_tx_ticks
            self._avg *= (1.0 - self.qw) ** min(m, 1e6)
        self._idle_since = None
        self._avg = (1 - self.qw) * self._avg + self.qw * len(self._items)
        max_p = 1.0 / self.l_interm
        if len(self._items) >= self.max_packets:
            self.stats_forced_drops += 1
            return False
        drop = False
        hard = False  # forced region: drop even ECT (UseHardDrop parity
        # — marking there would let the standing queue run to the cap)
        if self._avg >= self.max_th:
            if self.gentle and self._avg < 2 * self.max_th:
                p = max_p + (self._avg - self.max_th) / self.max_th * (
                    1.0 - max_p
                )
                drop = self._rng.GetValue(0.0, 1.0) < p
            else:
                drop = True
                hard = bool(self.use_hard_drop)
        elif self._avg > self.min_th:
            p_b = max_p * (self._avg - self.min_th) / (
                self.max_th - self.min_th
            )
            p_a = p_b / max(1.0 - self._count * p_b, 1e-9)
            drop = self._rng.GetValue(0.0, 1.0) < p_a
        else:
            # below MinTh: the since-last-drop counter restarts (Floyd;
            # without this, p_a saturates to 1 on re-entering the band)
            self._count = 0
        if drop:
            self._count = 0
            if not hard and self.use_ecn and _mark_ce(item.packet):
                self.stats_marked += 1
            else:
                self.stats_early_drops += 1
                return False
        else:
            self._count += 1
        self._items.append(item)
        return True

    def DoDequeue(self):
        if not self._items:
            return None
        item = self._items.pop(0)
        if not self._items:
            self._idle_since = Simulator.NowTicks()
        return item


class CoDelQueueDisc(QueueDisc):
    """CoDel (RFC 8289; codel-queue-disc.{h,cc}): sojourn-time keyed
    dropping with the inverse-sqrt control law."""

    tid = (
        TypeId("tpudes::CoDelQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: CoDelQueueDisc(**kw))
        .AddAttribute("Target", "acceptable sojourn", Time(5_000_000), checker=Time)
        .AddAttribute("Interval", "sliding window", Time(100_000_000), checker=Time)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._first_above_ts: int | None = None
        self._dropping = False
        self._drop_next = 0
        self._drop_count = 0
        self.stats_target_drops = 0

    def DoEnqueue(self, item) -> bool:
        if len(self._items) >= self.max_packets:
            return False
        self._items.append(item)
        return True

    def _sojourn_ok(self, item, now) -> bool:
        return now - item.enqueue_ts < self.target.ticks

    def _control_law(self, t: int) -> int:
        return t + int(self.interval.ticks / math.sqrt(self._drop_count))

    def DoDequeue(self):
        now = Simulator.NowTicks()
        item = self._pop_ok(now)
        if item is None:
            return None
        if self._dropping:
            while now >= self._drop_next and self._dropping:
                self.stats_target_drops += 1
                self.stats_dropped += 1
                self.drop(item.packet)
                self._drop_count += 1
                item = self._pop_ok(now)
                if item is None:
                    self._dropping = False
                    return None
                if self._sojourn_ok(item, now):
                    self._dropping = False
                else:
                    self._drop_next = self._control_law(self._drop_next)
        return item

    def _pop_ok(self, now):
        """Pop the head, managing the first-above-time state machine."""
        if not self._items:
            self._first_above_ts = None
            self._dropping = False
            return None
        item = self._items.pop(0)
        if self._sojourn_ok(item, now) or len(self._items) == 0:
            self._first_above_ts = None
        else:
            if self._first_above_ts is None:
                self._first_above_ts = now + self.interval.ticks
            elif now >= self._first_above_ts and not self._dropping:
                self._dropping = True
                self._drop_count = (
                    self._drop_count - 2
                    if self._drop_count > 2
                    and now - self._drop_next < 8 * self.interval.ticks
                    else 1
                )
                self._drop_next = self._control_law(now)
        return item


class TrafficControlLayer(Object):
    """traffic-control-layer.{h,cc}: per-node, maps device → root qdisc
    and drains under tx-idle flow control."""

    tid = (
        TypeId("tpudes::TrafficControlLayer")
        .AddConstructor(lambda **kw: TrafficControlLayer(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._qdiscs: dict[int, QueueDisc] = {}   # id(device) -> qdisc
        self._dev_send: dict[int, object] = {}    # id(device) -> raw Send

    def SetRootQueueDisc(self, device, qdisc: QueueDisc) -> None:
        if id(device) in self._qdiscs:
            raise RuntimeError("device already has a root queue disc")
        self._qdiscs[id(device)] = qdisc
        # shaping discs (TBF) re-trigger the drain when credit returns
        qdisc._wake = lambda _d=device: self._run(_d)
        self._dev_send[id(device)] = device.Send
        # every sender now funnels through the qdisc
        device.Send = (
            lambda packet, dest=None, protocol=0x0800, _d=device:
            self.Send(_d, packet, dest, protocol)
        )
        # wake the drain when the device finishes a frame; deferred one
        # event because PhyTxEnd fires while the device still reports
        # tx-busy (the devices clear the flag after the trace)
        device.TraceConnectWithoutContext(
            "PhyTxEnd",
            lambda _p, d=device: Simulator.ScheduleNow(self._run, d),
        )

    def GetRootQueueDisc(self, device) -> QueueDisc | None:
        return self._qdiscs.get(id(device))

    def Send(self, device, packet, dest, protocol: int) -> bool:
        ok = self._qdiscs[id(device)].Enqueue(
            QueueDiscItem(packet, dest, protocol)
        )
        self._run(device)
        return ok

    def _device_ready(self, device) -> bool:
        busy = getattr(device, "_tx_busy", False)
        return not busy

    def _run(self, device) -> None:
        qdisc = self._qdiscs.get(id(device))
        if qdisc is None:
            return
        raw_send = self._dev_send[id(device)]
        while self._device_ready(device):
            item = qdisc.Dequeue()
            if item is None:
                return
            raw_send(item.packet, item.dest, item.protocol)


class FqCoDelQueueDisc(QueueDisc):
    """FQ-CoDel (RFC 8290; fq-codel-queue-disc.{h,cc}): flows hashed
    into their own CoDel queues, served by deficit round robin with
    new-flow priority — a sparse flow never waits behind a bulk one."""

    tid = (
        TypeId("tpudes::FqCoDelQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: FqCoDelQueueDisc(**kw))
        .AddAttribute("Flows", "hash buckets", 1024, field="n_flows")
        .AddAttribute("Quantum", "DRR quantum (bytes)", 1514, field="quantum")
        .AddAttribute("Target", "per-flow CoDel target", Time(5_000_000),
                      checker=Time)
        .AddAttribute("Interval", "per-flow CoDel interval",
                      Time(100_000_000), checker=Time)
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._flows: dict[int, CoDelQueueDisc] = {}
        self._deficit: dict[int, int] = {}
        self._new: list[int] = []
        self._old: list[int] = []
        self._npackets = 0   # O(1) total (the limit check is hot-path)

    def _classify(self, item) -> int:
        """5-tuple hash (fq-codel-queue-disc.cc's FlowIdHash)."""
        from tpudes.models.internet.ipv4 import Ipv4Header
        from tpudes.models.internet.tcp import TcpHeader
        from tpudes.models.internet.udp import UdpHeader

        ip = item.packet.FindHeader(Ipv4Header)
        sport = dport = proto = 0
        src = dst = 0
        if ip is not None:
            src, dst, proto = ip.source.addr, ip.destination.addr, ip.protocol
            l4 = item.packet.FindHeader(UdpHeader) or item.packet.FindHeader(
                TcpHeader
            )
            if l4 is not None:
                sport, dport = l4.source_port, l4.destination_port
        return hash((src, dst, proto, sport, dport)) % int(self.n_flows)

    def _flow(self, fid: int) -> CoDelQueueDisc:
        q = self._flows.get(fid)
        if q is None:
            q = CoDelQueueDisc(
                MaxSize=self.max_packets, Target=self.target,
                Interval=self.interval,
            )
            self._flows[fid] = q
        return q

    def GetNPackets(self) -> int:
        return self._npackets

    def GetNBytes(self) -> int:
        return sum(q.GetNBytes() for q in self._flows.values())

    def DoEnqueue(self, item) -> bool:
        if self._npackets >= self.max_packets:
            return False
        fid = self._classify(item)
        q = self._flow(fid)
        if fid not in self._new and fid not in self._old:
            self._new.append(fid)
            self._deficit[fid] = int(self.quantum)
        ok = q.DoEnqueue(item)
        if ok:
            self._npackets += 1
        return ok

    def DoDequeue(self):
        while self._new or self._old:
            lst = self._new if self._new else self._old
            fid = lst[0]
            q = self._flows.get(fid)
            if q is None or q.GetNPackets() == 0:
                # drained: a new flow becomes eligible as old next time
                lst.pop(0)
                if lst is self._new:
                    self._old.append(fid)
                continue
            if self._deficit[fid] <= 0:
                self._deficit[fid] += int(self.quantum)
                lst.pop(0)
                self._old.append(fid)
                continue
            before = q.GetNPackets()
            item = q.Dequeue()          # per-flow CoDel law applies
            self._npackets -= before - q.GetNPackets()
            self.stats_dropped += q.stats_dropped
            q.stats_dropped = 0
            if item is None:
                lst.pop(0)
                if lst is self._new:
                    self._old.append(fid)
                continue
            self._deficit[fid] -= item.GetSize()
            return item
        return None


class PieQueueDisc(QueueDisc):
    """PIE (RFC 8033; pie-queue-disc.{h,cc}): proportional-integral
    controller steering the queue DELAY to a reference by random
    enqueue-time drops; probability updated on a fixed timer from the
    departure-rate-estimated delay."""

    tid = (
        TypeId("tpudes::PieQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: PieQueueDisc(**kw))
        .AddAttribute("QueueDelayReference", "target delay",
                      Time(15_000_000), checker=Time, field="target")
        .AddAttribute("Tupdate", "probability update period",
                      Time(15_000_000), checker=Time, field="t_update")
        .AddAttribute("A", "proportional gain", 0.125, field="a")
        .AddAttribute("B", "integral gain", 1.25, field="b")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        from tpudes.core.rng import UniformRandomVariable

        self._rng = UniformRandomVariable()
        self._p = 0.0
        self._qdelay_old = 0.0
        self._depart_rate = 0.0     # bytes/s EWMA
        self._last_dequeue_ts = None
        self._timer_started = False
        self.stats_early_drops = 0

    def _qdelay(self) -> float:
        if self._depart_rate <= 0.0:
            return 0.0
        return self.GetNBytes() / self._depart_rate

    def _update_p(self):
        qdelay = self._qdelay()
        target = self.target.GetSeconds()
        p = self._p + float(self.a) * (qdelay - target) + float(self.b) * (
            qdelay - self._qdelay_old
        )
        # RFC 8033 §4.2 auto-tuning scale-down at small probabilities
        if self._p < 0.000001:
            p = self._p + (p - self._p) / 2048
        elif self._p < 0.00001:
            p = self._p + (p - self._p) / 512
        elif self._p < 0.0001:
            p = self._p + (p - self._p) / 128
        elif self._p < 0.001:
            p = self._p + (p - self._p) / 32
        elif self._p < 0.01:
            p = self._p + (p - self._p) / 8
        elif self._p < 0.1:
            p = self._p + (p - self._p) / 2
        self._p = min(max(p, 0.0), 1.0)
        if qdelay == 0.0 and self._qdelay_old == 0.0:
            self._p *= 0.98          # decay when idle
        self._qdelay_old = qdelay
        if not self._items and self._p < 1e-9:
            # idle and fully decayed: suspend (ns-3 PIE suspends its
            # timer too) — otherwise the recurring event would keep
            # Simulator.Run alive forever on event-queue exhaustion
            self._timer_started = False
            return
        Simulator.Schedule(self.t_update, self._update_p)

    def DoEnqueue(self, item) -> bool:
        if len(self._items) >= self.max_packets:
            return False
        # RFC 8033 §4.1 safeguards: never drop when the queue is tiny
        if (
            self._p > 0.0
            and self.GetNBytes() > 2 * item.GetSize()
            and self._rng.GetValue() < self._p
        ):
            self.stats_early_drops += 1
            return False
        self._items.append(item)
        # arm Tupdate only once the packet is actually queued — a
        # rejected enqueue on an idle disc must not start the recurring
        # probability-update chain
        if not self._timer_started:
            self._timer_started = True
            Simulator.Schedule(self.t_update, self._update_p)
        return True

    def DoDequeue(self):
        if not self._items:
            return None
        item = self._items.pop(0)
        now = Simulator.NowTicks()
        if self._last_dequeue_ts is not None and now > self._last_dequeue_ts:
            inst = item.GetSize() / ((now - self._last_dequeue_ts) / 1e9)
            self._depart_rate = (
                inst if self._depart_rate == 0.0
                else 0.9 * self._depart_rate + 0.1 * inst
            )
        self._last_dequeue_ts = now
        return item


class TbfQueueDisc(QueueDisc):
    """Token bucket filter (tbf-queue-disc.{h,cc}): shapes the dequeue
    rate to Rate with Burst bytes of credit; when tokens run out the
    head waits and the disc wakes the drain when credit accumulates."""

    tid = (
        TypeId("tpudes::TbfQueueDisc")
        .SetParent(QueueDisc.tid)
        .AddConstructor(lambda **kw: TbfQueueDisc(**kw))
        .AddAttribute("Rate", "token rate", "1Mbps", field="rate_str")
        .AddAttribute("Burst", "bucket size (bytes)", 32_000, field="burst")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        from tpudes.network.data_rate import DataRate

        self._rate_bps = float(DataRate(self.rate_str).GetBitRate())
        self._tokens = float(self.burst)
        self._last_refill = 0
        self._wake = None            # set by TrafficControlLayer
        self._wake_pending = False

    def _refill(self):
        now = Simulator.NowTicks()
        self._tokens = min(
            float(self.burst),
            self._tokens + (now - self._last_refill) / 1e9 * self._rate_bps / 8.0,
        )
        self._last_refill = now

    def DoEnqueue(self, item) -> bool:
        if len(self._items) >= self.max_packets:
            return False
        self._items.append(item)
        return True

    def DoDequeue(self):
        if not self._items:
            return None
        self._refill()
        head = self._items[0]
        if head.GetSize() <= self._tokens:
            self._tokens -= head.GetSize()
            return self._items.pop(0)
        # not enough credit: wake the drain when there will be.  The
        # delay CEILs to >= 1 tick — round-to-nearest could leave the
        # refill epsilon short of the head packet and respawn a 0-tick
        # wake forever (livelock at e.g. Rate=3Mbps)
        if not self._wake_pending and self._wake is not None:
            self._wake_pending = True
            deficit = head.GetSize() - self._tokens
            ticks = max(1, int(math.ceil(deficit * 8.0 / self._rate_bps * 1e9)))

            def wake():
                self._wake_pending = False
                self._wake()

            Simulator.Schedule(Time(ticks), wake)
        return None


QUEUE_DISCS = {
    "tpudes::FifoQueueDisc": FifoQueueDisc,
    "tpudes::RedQueueDisc": RedQueueDisc,
    "tpudes::CoDelQueueDisc": CoDelQueueDisc,
    "tpudes::FqCoDelQueueDisc": FqCoDelQueueDisc,
    "tpudes::PieQueueDisc": PieQueueDisc,
    "tpudes::TbfQueueDisc": TbfQueueDisc,
    "ns3::FifoQueueDisc": FifoQueueDisc,
    "ns3::RedQueueDisc": RedQueueDisc,
    "ns3::CoDelQueueDisc": CoDelQueueDisc,
    "ns3::FqCoDelQueueDisc": FqCoDelQueueDisc,
    "ns3::PieQueueDisc": PieQueueDisc,
    "ns3::TbfQueueDisc": TbfQueueDisc,
}


class TrafficControlHelper:
    """helper/traffic-control-helper.{h,cc}."""

    def __init__(self):
        self._type = "tpudes::FifoQueueDisc"
        self._attrs: dict = {}

    def SetRootQueueDisc(self, type_name: str, **attrs) -> None:
        if type_name not in QUEUE_DISCS:
            raise ValueError(f"unknown queue disc {type_name!r}")
        self._type = type_name
        self._attrs = attrs

    def Install(self, devices):
        from tpudes.helper.containers import NetDeviceContainer

        if isinstance(devices, NetDeviceContainer):
            devices = list(devices)
        elif not isinstance(devices, (list, tuple)):
            devices = [devices]
        qdiscs = []
        for dev in devices:
            node = dev.GetNode()
            tc = node.GetObject(TrafficControlLayer)
            if tc is None:
                tc = TrafficControlLayer()
                node.AggregateObject(tc)
            qdisc = QUEUE_DISCS[self._type](**self._attrs)
            tc.SetRootQueueDisc(dev, qdisc)
            qdiscs.append(qdisc)
        return qdiscs
