"""LTE net devices + ideal RRC + radio bearers.

Reference parity: src/lte/model/lte-enb-net-device.{h,cc},
lte-ue-net-device.{h,cc}, lte-enb-rrc.{h,cc}, lte-ue-rrc.{h,cc},
lte-rrc-protocol-ideal.{h,cc}, eps-bearer.{h,cc} (upstream paths; mount
empty at survey — SURVEY.md §0, §2.6 "RRC" row).

RRC here is the *ideal* protocol variant: connection setup, RNTI
assignment and bearer establishment happen by direct state mutation
with no over-the-air RRC messages — exactly the fixture upstream ships
for tests (SURVEY.md §4 "ideal RRC protocol to bypass real message
exchange").  The real-message RRC state machine is an explicit
out-of-scope note for this round.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpudes.core.object import TypeId
from tpudes.models.internet.ipv4 import Ipv4Header
from tpudes.models.lte.phy import LteEnbPhy, LteUePhy
from tpudes.models.lte.rlc import LtePdcp, LteRlc, make_rlc
from tpudes.network.net_device import NetDevice


@dataclass
class RadioBearer:
    """One EPS data radio bearer: RLC+PDCP entities for both directions
    (DL tx lives at the eNB, UL tx at the UE)."""

    lcid: int
    mode: str                      # "sm" | "um" | "tm"
    dl_tx: LteRlc = None
    dl_rx: LteRlc = None
    ul_tx: LteRlc = None
    ul_rx: LteRlc = None
    dl_pdcp: LtePdcp = None
    ul_pdcp: LtePdcp = None

    @classmethod
    def create(cls, lcid: int, mode: str) -> "RadioBearer":
        b = cls(lcid, mode)
        b.dl_tx, b.dl_rx = make_rlc(mode), make_rlc(mode)
        b.ul_tx, b.ul_rx = make_rlc(mode), make_rlc(mode)
        b.dl_pdcp = LtePdcp(b.dl_tx)
        b.ul_pdcp = LtePdcp(b.ul_tx)
        if mode == "am":
            # AM STATUS reports travel the reverse control channel back
            # to the same direction's transmitter
            b.dl_rx.status_callback = b.dl_tx.ReceiveStatus
            b.ul_rx.status_callback = b.ul_tx.ReceiveStatus
        return b


@dataclass
class UeContext:
    """Per-UE state at the eNB (lte-enb-rrc.cc UeManager)."""

    rnti: int
    ue_device: "LteUeNetDevice"
    bearers: dict[int, RadioBearer] = field(default_factory=dict)


class LteEnbRrc:
    """eNB-side ideal RRC: RNTI allocation + bearer setup.

    A UE that detaches WITHOUT the explicit :meth:`remove_ue` path
    (reconnects to another cell — or the SAME cell under a fresh
    RNTI — or releases via :meth:`LteUeRrc.disconnect`) would strand
    its :class:`UeContext` here forever — upstream reclaims these
    through the RRC connection-release/inactivity machinery.  The
    analog here is the PR-6 A3-handover lapse-sweep pattern: the
    departing UE's RRC pings :meth:`note_detach`, which timestamps
    every context its UE no longer claims and arms a sweep that drops
    the ones STILL unclaimed a full lapse window later (per-context
    timestamps, so a detach landing while a sweep is already pending
    keeps its own full grace window; a re-claimed context is simply
    unmarked)."""

    #: grace (ms) between a noted detach and the stranded-context
    #: sweep reclaiming unclaimed contexts — the ideal-RRC analog of
    #: upstream's connection-release timeout
    STRANDED_UE_LAPSE_MS = 100

    def __init__(self, enb_device: "LteEnbNetDevice"):
        self.device = enb_device
        self.ues: dict[int, UeContext] = {}
        self._next_rnti = 1
        self._sweep_ev = None
        #: rnti -> ms timestamp the context was first seen unclaimed
        self._unclaimed_since: dict[int, int] = {}

    def add_ue(self, ue_device: "LteUeNetDevice") -> UeContext:
        rnti = self._next_rnti
        self._next_rnti += 1
        ctx = UeContext(rnti, ue_device)
        self.ues[rnti] = ctx
        return ctx

    def remove_ue(self, rnti: int) -> "UeContext | None":
        """Handover departure: drop the context (the caller carries the
        bearers to the target cell)."""
        self._unclaimed_since.pop(rnti, None)
        return self.ues.pop(rnti, None)

    # --- stranded-context expiry -----------------------------------------

    def _claimed(self, ctx: UeContext) -> bool:
        """Does the UE still claim this context as its serving cell?"""
        rrc = ctx.ue_device.rrc
        return (
            rrc.serving_enb is self.device
            and rrc.rnti == ctx.rnti
            and rrc.state == LteUeRrc.CONNECTED
        )

    def note_detach(self, ue_device=None) -> None:
        """A UE left this cell outside :meth:`remove_ue` (re-attach
        elsewhere or to this same cell under a new RNTI, RRC release):
        timestamp every now-unclaimed context and arm the sweep.  The
        sweep, not this note, does the reclaiming — each context gets
        its own full lapse window from the moment it was first seen
        unclaimed, so an in-flight re-attach has time to land even
        when a sweep armed by an earlier detach is already pending."""
        del ue_device  # the scan below re-checks every context anyway
        from tpudes.core.simulator import Simulator

        now = int(Simulator.Now().GetMilliSeconds())
        for rnti, ctx in self.ues.items():
            if not self._claimed(ctx):
                self._unclaimed_since.setdefault(rnti, now)
        if self._unclaimed_since:
            self._arm_sweep()

    def _arm_sweep(self) -> None:
        from tpudes.core.nstime import MilliSeconds
        from tpudes.core.simulator import Simulator

        if self._sweep_ev is not None and not self._sweep_ev.IsExpired():
            return
        self._sweep_ev = Simulator.Schedule(
            MilliSeconds(self.STRANDED_UE_LAPSE_MS), self._sweep_stranded
        )

    def _sweep_stranded(self) -> None:
        """Drop every marked context still unclaimed a full lapse after
        it was first seen unclaimed; unmark contexts that were
        re-claimed (or already removed) meanwhile, and re-arm while any
        marked context has lapse time left to serve."""
        from tpudes.core.simulator import Simulator

        now = int(Simulator.Now().GetMilliSeconds())
        for rnti in list(self._unclaimed_since):
            ctx = self.ues.get(rnti)
            if ctx is None or self._claimed(ctx):
                del self._unclaimed_since[rnti]
            elif now - self._unclaimed_since[rnti] >= self.STRANDED_UE_LAPSE_MS:
                del self.ues[rnti]
                del self._unclaimed_since[rnti]
        if self._unclaimed_since:
            self._arm_sweep()

    def setup_bearer(self, ctx: UeContext, mode: str) -> RadioBearer:
        lcid = 3 + len(ctx.bearers)  # LCID 1-2 reserved for SRBs
        bearer = RadioBearer.create(lcid, mode)
        ctx.bearers[lcid] = bearer
        ue_rrc = ctx.ue_device.rrc
        ue_rrc.bearers[lcid] = bearer
        # DL SDUs reassembled at the UE surface through its net device
        bearer.dl_rx.rx_sdu_callback = ctx.ue_device.receive_dl_sdu
        # UL SDUs reassembled at the eNB are forwarded to the core
        bearer.ul_rx.rx_sdu_callback = self.device.receive_ul_sdu
        return bearer


class LteUeRrc:
    """UE-side ideal RRC: serving-cell + bearer registry."""

    IDLE, CONNECTED = 0, 1

    def __init__(self, ue_device: "LteUeNetDevice"):
        self.device = ue_device
        self.state = self.IDLE
        self.serving_enb: "LteEnbNetDevice | None" = None
        self.rnti = 0
        self.bearers: dict[int, RadioBearer] = {}

    def connect(self, enb_device: "LteEnbNetDevice", rnti: int) -> None:
        prev = self.serving_enb
        self.serving_enb = enb_device
        self.rnti = rnti
        self.state = self.CONNECTED
        # re-attach without the explicit remove_ue path — to another
        # cell OR to this same cell under a fresh RNTI: let the
        # previous serving cell's RRC reclaim any context this UE no
        # longer claims (the stranded-entry sweep; a same-rnti
        # reconnect stays claimed, so noting it is harmless)
        if prev is not None:
            prev.rrc.note_detach(self.device)

    def disconnect(self) -> None:
        """RRC connection release (UE-initiated / out-of-coverage):
        the eNB-side context is reclaimed by its stranded-context
        sweep after the lapse window."""
        prev = self.serving_enb
        self.state = self.IDLE
        self.serving_enb = None
        self.rnti = 0
        if prev is not None:
            prev.rrc.note_detach(self.device)


class LteEnbNetDevice(NetDevice):
    """eNB device (lte-enb-net-device.cc): cell identity + PHY + RRC;
    the MAC scheduler instance is attached by LteHelper."""

    tid = (
        TypeId("tpudes::LteEnbNetDevice")
        .SetParent(NetDevice.tid)
        .AddAttribute("CellId", "physical cell id", 0, field="cell_id")
    )

    _next_cell_id = 1

    def __init__(self, n_rb: int = 25, **attributes):
        super().__init__(**attributes)
        self.cell_id = LteEnbNetDevice._next_cell_id
        LteEnbNetDevice._next_cell_id += 1
        self.phy = LteEnbPhy(n_rb=n_rb)
        self.rrc = LteEnbRrc(self)
        self.scheduler = None          # FfMacScheduler, set by helper
        self.ul_scheduler = None
        self.controller = None         # LteTtiController, set by helper
        self.ul_sdu_callback = None    # EPC hook: cb(packet) for UL IP SDUs

    def GetCellId(self) -> int:
        return self.cell_id

    def GetPhy(self) -> LteEnbPhy:
        return self.phy

    def IsBroadcast(self) -> bool:
        return False

    def NeedsArp(self) -> bool:
        return False

    def receive_ul_sdu(self, packet) -> None:
        """Reassembled uplink IP SDU: hand to the EPC (or local stack
        when the eNB itself terminates IP, as in test topologies)."""
        if self.ul_sdu_callback is not None:
            self.ul_sdu_callback(packet)
        else:
            self._deliver_up(packet, 0x0800, self._address, self._address, 0)

    def dl_enqueue(self, ue_device: "LteUeNetDevice", packet) -> bool:
        """EPC downlink entry: push an IP packet into the UE's default
        DL bearer at this eNB."""
        ctx = next(
            (c for c in self.rrc.ues.values() if c.ue_device is ue_device), None
        )
        if ctx is None or not ctx.bearers:
            return False
        bearer = ctx.bearers[min(ctx.bearers)]
        bearer.dl_pdcp.TransmitSdu(packet)
        return True

    def Send(self, packet, dest, protocol: int) -> bool:
        """IP-level send from the eNB node itself: route by destination
        UE address (test topologies without an EPC)."""
        header = packet.PeekHeader(Ipv4Header)
        if header is None:
            return False
        for ctx in self.rrc.ues.values():
            ue_ip = getattr(ctx.ue_device, "ue_ipv4", None)
            if ue_ip is not None and ue_ip == header.GetDestination():
                return self.dl_enqueue(ctx.ue_device, packet)
        return False


class LteUeNetDevice(NetDevice):
    """UE device (lte-ue-net-device.cc): IMSI + PHY + RRC; IP packets
    sent through it ride the default UL bearer."""

    tid = (
        TypeId("tpudes::LteUeNetDevice")
        .SetParent(NetDevice.tid)
        .AddAttribute("Imsi", "subscriber id", 0, field="imsi")
    )

    _next_imsi = 1

    def __init__(self, n_rb: int = 25, **attributes):
        super().__init__(**attributes)
        self.imsi = LteUeNetDevice._next_imsi
        LteUeNetDevice._next_imsi += 1
        self.phy = LteUePhy(n_rb=n_rb)
        self.rrc = LteUeRrc(self)
        self.ue_ipv4 = None            # assigned by EpcHelper

    def GetImsi(self) -> int:
        return self.imsi

    def GetPhy(self) -> LteUePhy:
        return self.phy

    def IsBroadcast(self) -> bool:
        return False

    def NeedsArp(self) -> bool:
        return False

    def receive_dl_sdu(self, packet) -> None:
        """Reassembled downlink IP SDU surfaces into the UE's stack."""
        self._deliver_up(packet, 0x0800, self._address, self._address, 0)

    def Send(self, packet, dest, protocol: int) -> bool:
        if self.rrc.state != LteUeRrc.CONNECTED or not self.rrc.bearers:
            return False
        bearer = self.rrc.bearers[min(self.rrc.bearers)]
        bearer.ul_pdcp.TransmitSdu(packet)
        return True
