"""LTE / LENA module (SURVEY.md §2.6): spectrum PHY + MI error model +
FF-MAC schedulers + RLC/PDCP + ideal RRC + EPC stub + helpers.

The per-TTI hot path (SURVEY.md §3.4) runs batched over all cells and
UEs in :mod:`tpudes.models.lte.controller`; the pure kernels live in
:mod:`tpudes.ops.lte`.
"""

from tpudes.models.lte.controller import LteTtiController
from tpudes.models.lte.device import (
    LteEnbNetDevice,
    LteEnbRrc,
    LteUeNetDevice,
    LteUeRrc,
    RadioBearer,
)
from tpudes.models.lte.epc import EpcHelper, PgwNetDevice
from tpudes.models.lte.handover import A3RsrpHandoverAlgorithm
from tpudes.models.lte.helper import LteHelper, RadioEnvironmentMapHelper
from tpudes.models.lte.phy import LteEnbPhy, LteSpectrumPhy, LteUePhy
from tpudes.models.lte.rlc import (
    LtePdcp,
    LteRlcAm,
    LteRlcSm,
    LteRlcTm,
    LteRlcUm,
)
from tpudes.models.lte.scheduler import (
    PfFfMacScheduler,
    RrFfMacScheduler,
)

__all__ = [
    "LteTtiController", "LteEnbNetDevice", "LteEnbRrc", "LteUeNetDevice",
    "LteUeRrc", "RadioBearer", "EpcHelper", "PgwNetDevice", "LteHelper",
    "RadioEnvironmentMapHelper", "LteEnbPhy", "LteSpectrumPhy", "LteUePhy",
    "LtePdcp", "LteRlcAm", "LteRlcSm", "LteRlcTm", "LteRlcUm",
    "A3RsrpHandoverAlgorithm", "PfFfMacScheduler", "RrFfMacScheduler",
]
