"""LteHelper + RadioEnvironmentMapHelper.

Reference parity: src/lte/helper/lte-helper.{h,cc},
radio-environment-map-helper.{h,cc} (upstream paths; mount empty at
survey — SURVEY.md §0, §2.6 "LteHelper" row).

Usage mirrors upstream:

    lte = LteHelper()
    lte.SetSchedulerType("tpudes::PfFfMacScheduler")
    enb_devs = lte.InstallEnbDevice(enb_nodes)
    ue_devs = lte.InstallUeDevice(ue_nodes)
    lte.Attach(ue_devs, enb_devs.Get(0))       # or closest-cell attach
    lte.ActivateDataRadioBearer(ue_devs)       # RLC-SM full buffer

The helper owns the one LteTtiController (the batched TTI engine) and
the network-wide pathloss model (upstream default: Friis at the DL
carrier frequency).
"""

from __future__ import annotations

import numpy as np

from tpudes.helper.containers import NetDeviceContainer
from tpudes.models.lte.controller import LteTtiController
from tpudes.models.lte.device import LteEnbNetDevice, LteUeNetDevice
from tpudes.models.lte.scheduler import SCHEDULERS
from tpudes.models.propagation import FriisPropagationLossModel
from tpudes.ops.lte import RB_BANDWIDTH_HZ


class LteHelper:
    def __init__(self, n_rb: int = 25, pathloss_model=None):
        self.n_rb = n_rb
        self.pathloss = pathloss_model or FriisPropagationLossModel(
            Frequency=2.12e9
        )
        self.controller = LteTtiController(self.pathloss, n_rb)
        self._scheduler_type = "tpudes::PfFfMacScheduler"
        self._ul_scheduler_type = "tpudes::RrFfMacScheduler"

    def SetSchedulerType(self, type_name: str) -> None:
        if type_name not in SCHEDULERS:
            raise ValueError(f"unknown scheduler {type_name!r}")
        self._scheduler_type = type_name

    def SetUlSchedulerType(self, type_name: str) -> None:
        if type_name not in SCHEDULERS:
            raise ValueError(f"unknown scheduler {type_name!r}")
        self._ul_scheduler_type = type_name

    def SetPathlossModel(self, model) -> None:
        self.pathloss = model
        self.controller.pathloss = model

    # --- handover (upstream LteHelper API shape) --------------------------
    def SetHandoverAlgorithmType(self, type_name: str) -> None:
        from tpudes.models.lte.handover import HANDOVER_ALGORITHMS

        if type_name not in HANDOVER_ALGORITHMS:
            raise ValueError(f"unknown handover algorithm {type_name!r}")
        self.controller.handover_algorithm = HANDOVER_ALGORITHMS[type_name]()

    def SetHandoverAlgorithmAttribute(self, name: str, value) -> None:
        if self.controller.handover_algorithm is None:
            raise RuntimeError("SetHandoverAlgorithmType first")
        self.controller.handover_algorithm.SetAttribute(name, value)

    def AddX2Interface(self, _enb_nodes=None) -> None:
        """Arm handover execution (the X2-lite path); without it the
        algorithm never fires, as upstream without X2 links."""
        self.controller.x2_enabled = True

    def SetFfrAlgorithmType(self, type_name: str) -> None:
        from tpudes.models.lte.ffr import FFR_ALGORITHMS

        if type_name not in FFR_ALGORITHMS:
            raise ValueError(f"unknown FFR algorithm {type_name!r}")
        self.controller.ffr_algorithm = FFR_ALGORITHMS[type_name]()
        # the CQI reference PSDs are band-masked at rebuild time
        self.controller._dirty = True

    def SetFfrAlgorithmAttribute(self, name: str, value) -> None:
        if self.controller.ffr_algorithm is None:
            raise RuntimeError("SetFfrAlgorithmType first")
        self.controller.ffr_algorithm.SetAttribute(name, value)

    # --- install ----------------------------------------------------------
    def InstallEnbDevice(self, nodes) -> NetDeviceContainer:
        devices = NetDeviceContainer()
        for node in nodes:
            dev = LteEnbNetDevice(n_rb=self.n_rb)
            dev.SetNode(node)
            node.AddDevice(dev)
            dev.scheduler = SCHEDULERS[self._scheduler_type]()
            dev.ul_scheduler = SCHEDULERS[self._ul_scheduler_type]()
            dev.controller = self.controller
            self.controller.add_enb(dev)
            devices.Add(dev)
        return devices

    def InstallUeDevice(self, nodes) -> NetDeviceContainer:
        devices = NetDeviceContainer()
        for node in nodes:
            dev = LteUeNetDevice(n_rb=self.n_rb)
            dev.SetNode(node)
            node.AddDevice(dev)
            self.controller.add_ue(dev)
            devices.Add(dev)
        return devices

    # --- RRC control ------------------------------------------------------
    def Attach(self, ue_devices, enb_device=None) -> None:
        """Attach UE(s): to the given eNB, or to the strongest cell
        (closest, under a monotone pathloss) when none is given —
        upstream's automatic initial cell selection."""
        if isinstance(ue_devices, LteUeNetDevice):
            ue_devices = [ue_devices]
        for ue in ue_devices:
            enb = enb_device or self._closest_enb(ue)
            self.controller.attach(ue, enb)

    def _closest_enb(self, ue_dev) -> LteEnbNetDevice:
        from tpudes.models.mobility import MobilityModel

        if not self.controller.enbs:
            raise RuntimeError("no eNBs installed")
        up = ue_dev.GetNode().GetObject(MobilityModel).GetPosition()
        best, best_d = None, float("inf")
        for enb in self.controller.enbs:
            ep = enb.GetNode().GetObject(MobilityModel).GetPosition()
            d = (up.x - ep.x) ** 2 + (up.y - ep.y) ** 2 + (up.z - ep.z) ** 2
            if d < best_d:
                best, best_d = enb, d
        return best

    def ActivateDataRadioBearer(self, ue_devices, mode: str = "sm") -> None:
        """Create the default data radio bearer (upstream
        ActivateDataRadioBearer; mode "sm" = saturation/full-buffer)."""
        if isinstance(ue_devices, LteUeNetDevice):
            ue_devices = [ue_devices]
        for ue in ue_devices:
            enb = ue.rrc.serving_enb
            if enb is None:
                raise RuntimeError("attach the UE before activating bearers")
            ctx = enb.rrc.ues[ue.rrc.rnti]
            enb.rrc.setup_bearer(ctx, mode)
        self.controller._dirty = True

    # --- stats ------------------------------------------------------------
    def GetRlcStats(self) -> list[dict]:
        """Per-(UE, bearer) RLC counters — the RadioBearerStats analog."""
        out = []
        for enb in self.controller.enbs:
            for ctx in enb.rrc.ues.values():
                for lcid, b in ctx.bearers.items():
                    out.append(
                        dict(
                            imsi=ctx.ue_device.GetImsi(),
                            cell_id=enb.GetCellId(),
                            lcid=lcid,
                            dl_tx_bytes=b.dl_tx.stats_tx_bytes,
                            dl_rx_bytes=b.dl_rx.stats_rx_bytes,
                            ul_tx_bytes=b.ul_tx.stats_tx_bytes,
                            ul_rx_bytes=b.ul_rx.stats_rx_bytes,
                        )
                    )
        return out


class RadioEnvironmentMapHelper:
    """Downlink SINR over a ground grid in ONE kernel call
    (radio-environment-map-helper.cc — upstream iterates a listener grid
    through the spectrum channel; here the grid IS the batch)."""

    def __init__(self, helper: LteHelper):
        self.helper = helper

    def Compute(self, x0, x1, y0, y1, resolution: int, z: float = 1.5):
        """Returns (sinr_db, serving_cell) arrays of shape
        (resolution, resolution) for the strongest-cell association."""
        import jax.numpy as jnp

        from tpudes.models.mobility import MobilityModel
        from tpudes.ops.lte import tti_sinr

        ctrl = self.helper.controller
        enbs = ctrl.enbs
        if not enbs:
            raise RuntimeError("no eNBs installed")
        xs = np.linspace(x0, x1, resolution)
        ys = np.linspace(y0, y1, resolution)
        gx, gy = np.meshgrid(xs, ys)
        grid = np.stack(
            [gx.ravel(), gy.ravel(), np.full(gx.size, z)], axis=-1
        )  # (G, 3)
        pos_e = np.array(
            [
                (lambda p: (p.x, p.y, p.z))(
                    e.GetNode().GetObject(MobilityModel).GetPosition()
                )
                for e in enbs
            ]
        )
        d = np.sqrt(((pos_e[:, None, :] - grid[None, :, :]) ** 2).sum(-1))
        loss_db = -np.asarray(
            self.helper.pathloss.batch_rx_power(jnp.zeros(()), jnp.asarray(d))
        )
        # the same scene effects the TTI controller applies (shared
        # implementation — tpudes/models/lte/scene.py)
        from tpudes.models.lte.scene import scene_loss_db

        loss_db = loss_db + scene_loss_db(enbs, pos_e, grid)
        gain = 10.0 ** (-loss_db / 10.0)                     # (E, G)
        psd = np.zeros((len(enbs), ctrl.n_rb))
        for i, enb in enumerate(enbs):
            p_w = 10.0 ** ((enb.phy.tx_power_dbm - 30.0) / 10.0)
            psd[i, :] = p_w / (ctrl.n_rb * RB_BANDWIDTH_HZ)
        serving = np.argmax(gain, axis=0)                    # strongest cell
        noise = (
            ctrl.ues[0].phy.noise_psd
            if ctrl.ues
            else 10.0 ** (9.0 / 10.0) * 1.380649e-23 * 290.0
        )
        sinr = np.asarray(
            tti_sinr(
                jnp.asarray(psd),
                jnp.asarray(gain),
                jnp.asarray(serving.astype(np.int32)),
                noise,
            )
        ).mean(axis=1)
        sinr_db = 10.0 * np.log10(np.maximum(sinr, 1e-30))
        shape = (resolution, resolution)
        return sinr_db.reshape(shape), serving.reshape(shape)
