"""RLC (SM / UM / TM) and PDCP entities.

Reference parity: src/lte/model/lte-rlc.{h,cc}, lte-rlc-sm.{h,cc},
lte-rlc-um.{h,cc}, lte-rlc-tm.{h,cc}, lte-pdcp.{h,cc} (upstream paths;
mount empty at survey — SURVEY.md §0, §2.6 "RLC / PDCP" row).

Design notes (TPU-first, zero-copy): an RLC UM PDU carries *segment
descriptors* — (packet, first_byte, last_byte) references into the COW
packets — instead of materialized bytes.  Segmentation, concatenation
and reassembly are pure bookkeeping on sizes; the payload bytes are
never copied, which keeps the per-TTI host work O(segments), not
O(bytes).  The MAC asks the tx entity for one PDU sized to the
transport block via ``NotifyTxOpportunity`` exactly as the FF-MAC
contract does upstream.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

RLC_UM_HEADER_BYTES = 2
RLC_SEGMENT_OVERHEAD_BYTES = 2  # per extension (LI) field


@dataclass
class RlcSegment:
    packet: object          # tpudes Packet (or None for SM filler)
    start: int              # first payload byte carried
    end: int                # one past the last byte carried
    is_first: bool
    is_last: bool

    @property
    def size(self) -> int:
        return self.end - self.start


@dataclass
class RlcPdu:
    sn: int
    segments: list[RlcSegment] = field(default_factory=list)
    size_bytes: int = 0     # on-air size incl. headers
    # AM re-segmentation (TS 36.322 SO/LSF analog): a retransmitted PDU
    # may be split into byte-range parts sharing the SN
    part_start: int = 0     # first payload byte of the original PDU
    sn_total_bytes: int = 0  # payload bytes of the whole original PDU


def _segment_from_queue(queue: deque, room: int, pdu: RlcPdu) -> int:
    """Shared UM/AM segmentation+concatenation loop: fill ``pdu`` with
    up to ``room`` payload bytes from the SDU queue (entries are
    ``[packet, offset]``); returns the unused room.  (r4 review: one
    copy, not two drifting ones.)"""
    while room > 0 and queue:
        entry = queue[0]
        packet, offset = entry
        take = min(room, packet.GetSize() - offset)
        pdu.segments.append(
            RlcSegment(
                packet, offset, offset + take,
                is_first=(offset == 0),
                is_last=(offset + take == packet.GetSize()),
            )
        )
        entry[1] += take
        room -= take
        if entry[1] == packet.GetSize():
            queue.popleft()
        if room > 0 and queue:
            room -= RLC_SEGMENT_OVERHEAD_BYTES  # LI for the next SDU
    return room


def _interval_subtract(iv: tuple, cov: list) -> list:
    """Parts of [iv) not covered by the disjoint sorted interval list."""
    out = []
    a, b = iv
    for ca, cb in cov:
        if cb <= a or ca >= b:
            continue
        if ca > a:
            out.append((a, ca))
        a = max(a, cb)
        if a >= b:
            break
    if a < b:
        out.append((a, b))
    return out


def _interval_insert(cov: list, iv: tuple) -> None:
    """Insert [iv) into the disjoint sorted interval list, merging."""
    cov.append(iv)
    cov.sort()
    merged = [cov[0]]
    for a, b in cov[1:]:
        la, lb = merged[-1]
        if a <= lb:
            merged[-1] = (la, max(lb, b))
        else:
            merged.append((a, b))
    cov[:] = merged


def _reassemble_segment(acc: dict, seg: RlcSegment, deliver) -> None:
    """Shared UM/AM reassembly step: account ``seg`` into the per-SDU
    accumulator and hand complete SDUs to ``deliver``."""
    uid = seg.packet.GetUid()
    if seg.is_first:
        acc[uid] = [seg.packet, 0]
    slot = acc.get(uid)
    if slot is None:
        return  # head segment was lost; discard the tail
    slot[1] += seg.size
    if seg.is_last:
        packet, seen = acc.pop(uid)
        if seen == packet.GetSize() and deliver is not None:
            deliver(packet.Copy())


class LteRlc:
    """Base tx/rx entity pair for one bearer direction."""

    mode = "base"

    def __init__(self):
        self.tx_queue_bytes = 0
        self.stats_tx_pdus = 0
        self.stats_tx_bytes = 0
        self.stats_rx_pdus = 0
        self.stats_rx_bytes = 0
        self.rx_sdu_callback = None   # cb(packet) on reassembled SDU

    # --- tx side (sender) ---
    def TransmitPdcpPdu(self, packet) -> None:
        raise NotImplementedError

    def BufferBytes(self) -> int:
        """Ideal buffer-status report the MAC scheduler reads."""
        return self.tx_queue_bytes

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        raise NotImplementedError

    # --- rx side (receiver) ---
    def ReceivePdu(self, pdu: RlcPdu) -> None:
        raise NotImplementedError


class LteRlcSm(LteRlc):
    """Saturation-mode RLC (lte-rlc-sm.cc): the tx buffer is always
    full, PDUs carry synthetic payload — the full-buffer traffic source
    behind the classic ``lena-simple`` throughput studies."""

    mode = "sm"

    def BufferBytes(self) -> int:
        return 1 << 30

    def TransmitPdcpPdu(self, packet) -> None:  # pragma: no cover - unused
        pass

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        if nbytes <= RLC_UM_HEADER_BYTES:
            return None
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += nbytes
        seg = RlcSegment(None, 0, nbytes - RLC_UM_HEADER_BYTES, True, True)
        return RlcPdu(sn=self.stats_tx_pdus, segments=[seg], size_bytes=nbytes)

    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes


class LteRlcUm(LteRlc):
    """Unacknowledged mode (lte-rlc-um.cc): segmentation + concatenation
    on tx, SN-gap-aware reassembly on rx; lost PDUs drop exactly the
    SDUs they carried bytes of."""

    mode = "um"
    SN_MOD = 1024  # 10-bit UM sequence numbering

    def __init__(self):
        super().__init__()
        self._queue: deque = deque()   # (packet, offset)
        self._vt_us = 0                # next SN to send
        # rx state
        self._vr_ur = 0                # next expected SN
        self._acc: dict[int, list] = {}  # packet uid -> [packet, bytes_seen]

    # --- tx ---
    def TransmitPdcpPdu(self, packet) -> None:
        self._queue.append([packet, 0])
        self.tx_queue_bytes += packet.GetSize()

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        room = nbytes - RLC_UM_HEADER_BYTES
        if room <= 0 or not self._queue:
            return None
        pdu = RlcPdu(sn=self._vt_us)
        before = self.tx_queue_bytes
        room = _segment_from_queue(self._queue, room, pdu)
        taken = sum(s.size for s in pdu.segments)
        self.tx_queue_bytes = before - taken
        if not pdu.segments:
            return None
        self._vt_us = (self._vt_us + 1) % self.SN_MOD
        pdu.size_bytes = nbytes - room if room > 0 else nbytes
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += pdu.size_bytes
        return pdu

    # --- rx ---
    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes
        if pdu.sn != self._vr_ur:
            # SN gap: every SDU with bytes in the lost PDU(s) is torn —
            # drop all partially-assembled SDUs
            self._acc.clear()
        self._vr_ur = (pdu.sn + 1) % self.SN_MOD
        for seg in pdu.segments:
            _reassemble_segment(self._acc, seg, self.rx_sdu_callback)


class LteRlcAm(LteRlc):
    """Acknowledged mode (lte-rlc-am.cc): UM-style segmentation plus a
    retransmission protocol — the receiver reports STATUS (ack + nack
    list) over the reverse control channel and the sender retransmits
    nacked PDUs up to ``MAX_RETX`` times, so SDUs survive PDU loss.

    Protocol machinery mirrored from TS 36.322 (each with its upstream
    analog named):
    - **re-segmentation**: a retransmission that does not fit the MAC
      opportunity is split into byte-range parts sharing the SN (the
      SO/LSF resegmentation), so a shrinking CQI can never stall the
      bearer behind an oversized PDU;
    - **poll-retransmit timer**: ``POLL_RETRANSMIT_MS`` after a
      transmission with data still unacknowledged, the oldest unacked
      SN is retransmitted unprompted (t-PollRetransmit), covering the
      lost-tail-PDU case STATUS alone cannot;
    - **NACK suppression**: NACKs arriving within
      ``NACK_IGNORE_WINDOW_MS`` of that SN's last (re)transmission are
      ignored (the tx-side equivalent of t-StatusProhibit), so the
      per-PDU STATUS cadence cannot flood duplicates to MAX_RETX.

    Documented deviations: STATUS rides an ideal control channel with a
    fixed ``STATUS_DELAY_MS`` latency (upstream multiplexes it into the
    MAC uplink), and sequence numbers are unbounded ints (upstream:
    10-bit with a 512-PDU window) — identical behavior while in-flight
    stays below upstream's window, which the scheduler guarantees.
    """

    mode = "am"
    RLC_AM_HEADER_BYTES = 4
    MAX_RETX = 5
    STATUS_DELAY_MS = 2
    POLL_RETRANSMIT_MS = 40
    NACK_IGNORE_WINDOW_MS = 12  # > STATUS_DELAY + HARQ RTT

    def __init__(self):
        super().__init__()
        self._queue: deque = deque()       # [packet, offset] new SDUs
        self._vt_s = 0                     # next new SN
        #: sn -> list[RlcPdu] parts still unacknowledged (1 part unless
        #: re-segmented)
        self._unacked: dict[int, list] = {}
        #: retx queue entries: [sn, list-of-parts-still-to-send]
        self._retx: deque = deque()
        self._retx_count: dict[int, int] = {}
        self._last_tx_ms: dict[int, float] = {}
        self._poll_gen = 0                 # invalidates stale poll timers
        self.stats_retx_pdus = 0
        self.stats_dropped_pdus = 0
        # rx state: per-SN byte-interval coverage (the SO-based
        # reassembly — overlapping retransmitted parts contribute only
        # their novel byte ranges)
        self._rx_cov: dict[int, list] = {}     # sn -> [(a, b)] disjoint
        self._rx_segs: dict[int, list] = {}    # sn -> [(pdu_off, seg)]
        self._rx_total: dict[int, int] = {}    # sn -> sn_total_bytes
        self._vr_r = 0                     # next in-order SN to deliver
        self._vr_h = 0                     # highest received + 1
        self._acc: dict[int, list] = {}
        self.status_callback = None        # cb(ack_sn, nack_list) -> peer

    # --- tx ---
    def TransmitPdcpPdu(self, packet) -> None:
        self._queue.append([packet, 0])
        self.tx_queue_bytes += packet.GetSize()

    def BufferBytes(self) -> int:
        retx = sum(
            p.size_bytes for _sn, pending in self._retx for p in pending
        )
        return self.tx_queue_bytes + retx

    def _now_ms(self) -> float:
        from tpudes.core.simulator import Simulator

        return Simulator.NowTicks() / 1e6

    def _arm_poll(self) -> None:
        from tpudes.core.nstime import MilliSeconds
        from tpudes.core.simulator import Simulator

        self._poll_gen += 1
        Simulator.Schedule(
            MilliSeconds(self.POLL_RETRANSMIT_MS),
            self._poll_timeout, self._poll_gen,
        )

    def _poll_timeout(self, gen: int) -> None:
        """t-PollRetransmit: nothing acked since the last transmission —
        nudge the oldest unacked SN back onto the retx queue."""
        if gen != self._poll_gen or not self._unacked:
            return
        sn = min(self._unacked)
        if sn not in self._retx:
            self._bump_retx(sn)
        if self._unacked:
            self._arm_poll()

    def _bump_retx(self, sn: int) -> None:
        self._retx_count[sn] = self._retx_count.get(sn, 0) + 1
        if self._retx_count[sn] > self.MAX_RETX:
            self._unacked.pop(sn, None)
            self._retx_count.pop(sn, None)
            self.stats_dropped_pdus += 1
        else:
            self._retx.append([sn, list(self._unacked[sn])])

    @staticmethod
    def _split_pdu(pdu: RlcPdu, fit_bytes: int) -> tuple[RlcPdu, RlcPdu]:
        """Re-segment ``pdu`` at ``fit_bytes`` payload bytes: two parts
        sharing the SN, byte ranges contiguous (SO/LSF analog)."""
        head = RlcPdu(
            sn=pdu.sn, part_start=pdu.part_start,
            sn_total_bytes=pdu.sn_total_bytes,
        )
        tail = RlcPdu(
            sn=pdu.sn, part_start=pdu.part_start + fit_bytes,
            sn_total_bytes=pdu.sn_total_bytes,
        )
        remaining = fit_bytes
        for seg in pdu.segments:
            if remaining >= seg.size:
                head.segments.append(seg)
                remaining -= seg.size
            elif remaining > 0:
                mid = seg.start + remaining
                head.segments.append(
                    RlcSegment(seg.packet, seg.start, mid, seg.is_first, False)
                )
                tail.segments.append(
                    RlcSegment(seg.packet, mid, seg.end, False, seg.is_last)
                )
                remaining = 0
            else:
                tail.segments.append(seg)
        head.size_bytes = fit_bytes + LteRlcAm.RLC_AM_HEADER_BYTES
        tail.size_bytes = (
            sum(s.size for s in tail.segments) + LteRlcAm.RLC_AM_HEADER_BYTES
        )
        return head, tail

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        # retransmissions first (upstream: retx queue outranks new data)
        while self._retx:
            entry = self._retx[0]
            sn, pending = entry
            if sn not in self._unacked:
                self._retx.popleft()       # acked while queued
                continue
            if not pending:
                self._retx.popleft()       # every part sent this round
                continue
            pdu = pending[0]
            if pdu.size_bytes > nbytes:
                fit = nbytes - self.RLC_AM_HEADER_BYTES
                if fit <= 0:
                    return None
                head, tail = self._split_pdu(pdu, fit)
                # refine the stored partition AND the pending list
                stored = self._unacked[sn]
                idx = next(
                    (i for i, p in enumerate(stored)
                     if p.part_start == pdu.part_start), None,
                )
                if idx is not None:
                    stored[idx : idx + 1] = [head, tail]
                pending[0:1] = [head, tail]
                pdu = head
            pending.pop(0)
            self._last_tx_ms[sn] = self._now_ms()
            self.stats_retx_pdus += 1
            self.stats_tx_pdus += 1
            self.stats_tx_bytes += pdu.size_bytes
            self._arm_poll()
            return pdu
        room = nbytes - self.RLC_AM_HEADER_BYTES
        if room <= 0 or not self._queue:
            return None
        pdu = RlcPdu(sn=self._vt_s)
        room = _segment_from_queue(self._queue, room, pdu)
        taken = sum(s.size for s in pdu.segments)
        self.tx_queue_bytes -= taken
        if not pdu.segments:
            return None
        pdu.size_bytes = nbytes - room if room > 0 else nbytes
        pdu.sn_total_bytes = taken
        self._unacked[self._vt_s] = [pdu]
        self._retx_count[self._vt_s] = 0
        self._last_tx_ms[self._vt_s] = self._now_ms()
        self._vt_s += 1
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += pdu.size_bytes
        self._arm_poll()
        return pdu

    def ReceiveStatus(self, ack_sn: int, nacks: list[int]) -> None:
        """STATUS from the peer: everything below ``ack_sn`` arrived
        except the SNs in ``nacks``."""
        nackset = set(nacks)
        for sn in [s for s in self._unacked if s < ack_sn and s not in nackset]:
            self._unacked.pop(sn)
            self._retx_count.pop(sn, None)
            self._last_tx_ms.pop(sn, None)
        now = self._now_ms()
        for sn in nacks:
            if sn not in self._unacked or sn in self._retx:
                continue
            if now - self._last_tx_ms.get(sn, -1e9) < self.NACK_IGNORE_WINDOW_MS:
                continue  # a copy is (likely) still in flight
            self._bump_retx(sn)
        if self._unacked:
            self._arm_poll()
        else:
            self._poll_gen += 1  # all clear: cancel outstanding polls

    # --- rx ---
    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes
        sn = pdu.sn
        if sn >= self._vr_r:
            self._absorb_part(sn, pdu)
            self._vr_h = max(self._vr_h, sn + 1)
        # in-order delivery of every now-complete SN
        while self._sn_complete(self._vr_r):
            for _off, seg in sorted(
                self._rx_segs.pop(self._vr_r), key=lambda t: t[0]
            ):
                _reassemble_segment(self._acc, seg, self.rx_sdu_callback)
            self._rx_cov.pop(self._vr_r, None)
            self._rx_total.pop(self._vr_r, None)
            self._vr_r += 1
        self._send_status()

    def _absorb_part(self, sn: int, pdu: RlcPdu) -> None:
        """Merge a (possibly re-segmented, possibly overlapping) part:
        only byte ranges not yet covered contribute segments — a stale
        duplicate can never double-count (SO-based reassembly)."""
        cov = self._rx_cov.setdefault(sn, [])
        segs = self._rx_segs.setdefault(sn, [])
        if pdu.sn_total_bytes:
            self._rx_total[sn] = pdu.sn_total_bytes
        part_size = sum(s.size for s in pdu.segments)
        novel = _interval_subtract(
            (pdu.part_start, pdu.part_start + part_size), cov
        )
        for a, b in novel:
            # clip this part's segments to pdu-byte range [a, b)
            off = pdu.part_start
            for seg in pdu.segments:
                lo, hi = max(off, a), min(off + seg.size, b)
                if lo < hi:
                    s0 = seg.start + (lo - off)
                    e0 = seg.start + (hi - off)
                    segs.append((
                        lo,
                        RlcSegment(
                            seg.packet, s0, e0,
                            is_first=(s0 == 0),
                            is_last=(e0 == seg.packet.GetSize()),
                        ),
                    ))
                off += seg.size
            _interval_insert(cov, (a, b))

    def _sn_complete(self, sn: int) -> bool:
        total = self._rx_total.get(sn)
        if total is None:
            return False
        cov = self._rx_cov.get(sn, [])
        return len(cov) == 1 and cov[0][0] == 0 and cov[0][1] >= total

    def _send_status(self) -> None:
        if self.status_callback is None:
            return
        from tpudes.core.nstime import MilliSeconds
        from tpudes.core.simulator import Simulator

        ack_sn = self._vr_h
        nacks = [
            sn for sn in range(self._vr_r, self._vr_h)
            if not self._sn_complete(sn)
        ]
        Simulator.Schedule(
            MilliSeconds(self.STATUS_DELAY_MS),
            self.status_callback, ack_sn, nacks,
        )


class LteRlcTm(LteRlc):
    """Transparent mode (lte-rlc-tm.cc): whole SDUs only, no headers,
    no segmentation — an SDU is sent when the opportunity fits it."""

    mode = "tm"

    def __init__(self):
        super().__init__()
        self._queue: deque = deque()
        self._sn = 0

    def TransmitPdcpPdu(self, packet) -> None:
        self._queue.append(packet)
        self.tx_queue_bytes += packet.GetSize()

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        if not self._queue or self._queue[0].GetSize() > nbytes:
            return None
        packet = self._queue.popleft()
        self.tx_queue_bytes -= packet.GetSize()
        self._sn += 1
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += packet.GetSize()
        return RlcPdu(
            sn=self._sn,
            segments=[RlcSegment(packet, 0, packet.GetSize(), True, True)],
            size_bytes=packet.GetSize(),
        )

    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes
        if self.rx_sdu_callback is not None:
            self.rx_sdu_callback(pdu.segments[0].packet.Copy())


class LtePdcp:
    """Sequence-numbering passthrough (lte-pdcp.cc): stamps tx SDUs,
    counts, and forwards; header cost folded into the RLC accounting."""

    def __init__(self, rlc_tx: LteRlc):
        self.rlc_tx = rlc_tx
        self.tx_sn = 0
        self.stats_tx_sdus = 0
        self.stats_rx_sdus = 0
        self.rx_callback = None

    def TransmitSdu(self, packet) -> None:
        self.tx_sn = (self.tx_sn + 1) % 4096
        self.stats_tx_sdus += 1
        self.rlc_tx.TransmitPdcpPdu(packet)

    def ReceiveSdu(self, packet) -> None:
        self.stats_rx_sdus += 1
        if self.rx_callback is not None:
            self.rx_callback(packet)


def make_rlc(mode: str) -> LteRlc:
    return {
        "sm": LteRlcSm, "um": LteRlcUm, "tm": LteRlcTm, "am": LteRlcAm,
    }[mode]()
