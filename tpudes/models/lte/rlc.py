"""RLC (SM / UM / TM) and PDCP entities.

Reference parity: src/lte/model/lte-rlc.{h,cc}, lte-rlc-sm.{h,cc},
lte-rlc-um.{h,cc}, lte-rlc-tm.{h,cc}, lte-pdcp.{h,cc} (upstream paths;
mount empty at survey — SURVEY.md §0, §2.6 "RLC / PDCP" row).

Design notes (TPU-first, zero-copy): an RLC UM PDU carries *segment
descriptors* — (packet, first_byte, last_byte) references into the COW
packets — instead of materialized bytes.  Segmentation, concatenation
and reassembly are pure bookkeeping on sizes; the payload bytes are
never copied, which keeps the per-TTI host work O(segments), not
O(bytes).  The MAC asks the tx entity for one PDU sized to the
transport block via ``NotifyTxOpportunity`` exactly as the FF-MAC
contract does upstream.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

RLC_UM_HEADER_BYTES = 2
RLC_SEGMENT_OVERHEAD_BYTES = 2  # per extension (LI) field


@dataclass
class RlcSegment:
    packet: object          # tpudes Packet (or None for SM filler)
    start: int              # first payload byte carried
    end: int                # one past the last byte carried
    is_first: bool
    is_last: bool

    @property
    def size(self) -> int:
        return self.end - self.start


@dataclass
class RlcPdu:
    sn: int
    segments: list[RlcSegment] = field(default_factory=list)
    size_bytes: int = 0     # on-air size incl. headers


class LteRlc:
    """Base tx/rx entity pair for one bearer direction."""

    mode = "base"

    def __init__(self):
        self.tx_queue_bytes = 0
        self.stats_tx_pdus = 0
        self.stats_tx_bytes = 0
        self.stats_rx_pdus = 0
        self.stats_rx_bytes = 0
        self.rx_sdu_callback = None   # cb(packet) on reassembled SDU

    # --- tx side (sender) ---
    def TransmitPdcpPdu(self, packet) -> None:
        raise NotImplementedError

    def BufferBytes(self) -> int:
        """Ideal buffer-status report the MAC scheduler reads."""
        return self.tx_queue_bytes

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        raise NotImplementedError

    # --- rx side (receiver) ---
    def ReceivePdu(self, pdu: RlcPdu) -> None:
        raise NotImplementedError


class LteRlcSm(LteRlc):
    """Saturation-mode RLC (lte-rlc-sm.cc): the tx buffer is always
    full, PDUs carry synthetic payload — the full-buffer traffic source
    behind the classic ``lena-simple`` throughput studies."""

    mode = "sm"

    def BufferBytes(self) -> int:
        return 1 << 30

    def TransmitPdcpPdu(self, packet) -> None:  # pragma: no cover - unused
        pass

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        if nbytes <= RLC_UM_HEADER_BYTES:
            return None
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += nbytes
        seg = RlcSegment(None, 0, nbytes - RLC_UM_HEADER_BYTES, True, True)
        return RlcPdu(sn=self.stats_tx_pdus, segments=[seg], size_bytes=nbytes)

    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes


class LteRlcUm(LteRlc):
    """Unacknowledged mode (lte-rlc-um.cc): segmentation + concatenation
    on tx, SN-gap-aware reassembly on rx; lost PDUs drop exactly the
    SDUs they carried bytes of."""

    mode = "um"
    SN_MOD = 1024  # 10-bit UM sequence numbering

    def __init__(self):
        super().__init__()
        self._queue: deque = deque()   # (packet, offset)
        self._vt_us = 0                # next SN to send
        # rx state
        self._vr_ur = 0                # next expected SN
        self._acc: dict[int, list] = {}  # packet uid -> [packet, bytes_seen]

    # --- tx ---
    def TransmitPdcpPdu(self, packet) -> None:
        self._queue.append([packet, 0])
        self.tx_queue_bytes += packet.GetSize()

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        room = nbytes - RLC_UM_HEADER_BYTES
        if room <= 0 or not self._queue:
            return None
        pdu = RlcPdu(sn=self._vt_us)
        while room > 0 and self._queue:
            entry = self._queue[0]
            packet, offset = entry
            remaining = packet.GetSize() - offset
            take = min(room, remaining)
            pdu.segments.append(
                RlcSegment(
                    packet,
                    offset,
                    offset + take,
                    is_first=(offset == 0),
                    is_last=(offset + take == packet.GetSize()),
                )
            )
            entry[1] += take
            room -= take
            self.tx_queue_bytes -= take
            if entry[1] == packet.GetSize():
                self._queue.popleft()
            if room > 0 and self._queue:
                room -= RLC_SEGMENT_OVERHEAD_BYTES  # LI for the next SDU
        if not pdu.segments:
            return None
        self._vt_us = (self._vt_us + 1) % self.SN_MOD
        pdu.size_bytes = nbytes - room if room > 0 else nbytes
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += pdu.size_bytes
        return pdu

    # --- rx ---
    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes
        if pdu.sn != self._vr_ur:
            # SN gap: every SDU with bytes in the lost PDU(s) is torn —
            # drop all partially-assembled SDUs
            self._acc.clear()
        self._vr_ur = (pdu.sn + 1) % self.SN_MOD
        for seg in pdu.segments:
            uid = seg.packet.GetUid()
            if seg.is_first:
                self._acc[uid] = [seg.packet, 0]
            slot = self._acc.get(uid)
            if slot is None:
                continue  # first segment was lost; discard the tail
            slot[1] += seg.size
            if seg.is_last:
                packet, seen = self._acc.pop(uid)
                if seen == packet.GetSize() and self.rx_sdu_callback is not None:
                    self.rx_sdu_callback(packet.Copy())


class LteRlcTm(LteRlc):
    """Transparent mode (lte-rlc-tm.cc): whole SDUs only, no headers,
    no segmentation — an SDU is sent when the opportunity fits it."""

    mode = "tm"

    def __init__(self):
        super().__init__()
        self._queue: deque = deque()
        self._sn = 0

    def TransmitPdcpPdu(self, packet) -> None:
        self._queue.append(packet)
        self.tx_queue_bytes += packet.GetSize()

    def NotifyTxOpportunity(self, nbytes: int) -> RlcPdu | None:
        if not self._queue or self._queue[0].GetSize() > nbytes:
            return None
        packet = self._queue.popleft()
        self.tx_queue_bytes -= packet.GetSize()
        self._sn += 1
        self.stats_tx_pdus += 1
        self.stats_tx_bytes += packet.GetSize()
        return RlcPdu(
            sn=self._sn,
            segments=[RlcSegment(packet, 0, packet.GetSize(), True, True)],
            size_bytes=packet.GetSize(),
        )

    def ReceivePdu(self, pdu: RlcPdu) -> None:
        self.stats_rx_pdus += 1
        self.stats_rx_bytes += pdu.size_bytes
        if self.rx_sdu_callback is not None:
            self.rx_sdu_callback(pdu.segments[0].packet.Copy())


class LtePdcp:
    """Sequence-numbering passthrough (lte-pdcp.cc): stamps tx SDUs,
    counts, and forwards; header cost folded into the RLC accounting."""

    def __init__(self, rlc_tx: LteRlc):
        self.rlc_tx = rlc_tx
        self.tx_sn = 0
        self.stats_tx_sdus = 0
        self.stats_rx_sdus = 0
        self.rx_callback = None

    def TransmitSdu(self, packet) -> None:
        self.tx_sn = (self.tx_sn + 1) % 4096
        self.stats_tx_sdus += 1
        self.rlc_tx.TransmitPdcpPdu(packet)

    def ReceiveSdu(self, packet) -> None:
        self.stats_rx_sdus += 1
        if self.rx_callback is not None:
            self.rx_callback(packet)


def make_rlc(mode: str) -> LteRlc:
    return {"sm": LteRlcSm, "um": LteRlcUm, "tm": LteRlcTm}[mode]()
