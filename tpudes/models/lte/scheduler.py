"""FF-MAC schedulers (the full upstream family) + HARQ bookkeeping.

Reference parity: src/lte/model/ff-mac-scheduler.h (the FemtoForum
scheduler API) and the per-algorithm implementations
{pf,rr,tdmt,fdmt,tta,tdbet,fdbet,cqa,pss}-ff-mac-scheduler.{h,cc},
lte-harq-phy.{h,cc} (upstream paths; mount empty at survey — SURVEY.md
§0, §2.6 "MAC + FF-MAC scheduler API" and "HARQ" rows).

The scheduler works on resource-block *groups* (RBGs, TS 36.213 type-0
allocation) and ideal buffer-status reports read straight from the RLC
entities.  With wideband CQI the per-RBG PF metric is flat across
frequency, so allocation is a greedy fill: best metric first, each flow
takes only the RBGs its buffer needs, remainder to the next flow —
which degenerates to winner-takes-all under full-buffer load and to
frequency multiplexing under light load, matching upstream PF behavior
at wideband-CQI fidelity.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from tpudes.ops.lte import mcs_from_cqi_py, tbs_bits_py

HARQ_RTT_TTIS = 8
HARQ_MAX_TX = 4  # 1 first tx + 3 retransmissions


def rbg_size_for(n_rb: int) -> int:
    """TS 36.213 table 7.1.6.1-1 type-0 RBG sizes."""
    if n_rb <= 10:
        return 1
    if n_rb <= 26:
        return 2
    if n_rb <= 63:
        return 3
    return 4


@dataclass
class SchedCandidate:
    """Per-flow scheduler input (the FF-MAC SchedDlTriggerReq view).

    ``hol_delay_ms`` (CQA) and ``tbr_bps`` (PSS) default to 0 — the
    schedulers degrade gracefully when the caller has no QoS state."""

    rnti: int
    cqi: int
    queue_bytes: int
    avg_thr_bps: float = 1.0
    hol_delay_ms: float = 0.0
    tbr_bps: float = 0.0


@dataclass
class Allocation:
    """One scheduled transport block."""

    rnti: int
    rbgs: list[int]
    mcs: int
    tb_bytes: int
    harq: "HarqTb | None" = None  # set for retransmissions


@dataclass
class HarqTb:
    """In-flight transport block awaiting ack (lte-harq-phy soft-buffer
    entry): MI accumulates over retransmissions (IR combining)."""

    rnti: int
    pdu: object            # RlcPdu being carried
    mcs: int
    n_rbg: int
    tb_bytes: int
    mi_acc: float = 0.0
    tx_count: int = 1
    due_tti: int = 0       # next (re)tx TTI
    bearer: object = None  # RadioBearer the PDU belongs to
    rnti_ue_index: int = -1  # controller's global UE index


class FfMacScheduler:
    """Abstract FF-MAC scheduler: allocate free RBGs among candidates."""

    name = "abstract"

    def schedule(
        self, tti: int, candidates: list[SchedCandidate], free_rbgs: list[int],
        rbg_size: int,
    ) -> list[Allocation]:
        raise NotImplementedError

    def update_served(self, rnti: int, bits: int) -> None:
        """Post-TTI feedback hook (PF throughput averaging)."""

    # --- shared helpers ---
    @staticmethod
    def _fill(
        order: list[SchedCandidate], free_rbgs: list[int], rbg_size: int
    ) -> list[Allocation]:
        """Greedy fill in metric order; each flow takes only the RBGs
        its buffer needs (+RLC header slack)."""
        allocs: list[Allocation] = []
        free = list(free_rbgs)
        for cand in order:
            if not free or cand.cqi < 1 or cand.queue_bytes <= 0:
                continue
            mcs = mcs_from_cqi_py(cand.cqi)
            bytes_per_rbg = max(tbs_bits_py(mcs, rbg_size) // 8, 1)
            need = min(
                math.ceil((cand.queue_bytes + 4) / bytes_per_rbg), len(free)
            )
            take, free = free[:need], free[need:]
            tb_bytes = tbs_bits_py(mcs, len(take) * rbg_size) // 8
            allocs.append(Allocation(cand.rnti, take, mcs, tb_bytes))
        return allocs


class RrFfMacScheduler(FfMacScheduler):
    """Round-robin (rr-ff-mac-scheduler.cc): rotate a pointer over the
    active flows; equal opportunity, CQI only picks the MCS."""

    name = "rr"

    def __init__(self):
        self._next = 0

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        if not candidates:
            return []
        order = sorted(candidates, key=lambda c: c.rnti)
        start = self._next % len(order)
        rotated = order[start:] + order[:start]
        allocs = self._fill(rotated, free_rbgs, rbg_size)
        if allocs:
            self._next = (start + 1) % len(order)
        return allocs


class _ThroughputEma:
    """Shared served-throughput EMA (the classic PF average): T ←
    (1−α)T + α·r per TTI, r = 0 for unserved flows — one implementation
    for every scheduler that consumes a past-throughput term."""

    def __init__(self, alpha: float = 0.05):
        self.alpha = alpha
        self._avg: dict[int, float] = {}

    def avg(self, rnti: int) -> float:
        return max(self._avg.get(rnti, 1.0), 1.0)

    def end_tti(self, served_bits: dict[int, int], active_rntis) -> None:
        for rnti in active_rntis:
            old = self._avg.get(rnti, 1.0)
            r = served_bits.get(rnti, 0) * 1000.0  # bits/s at 1 ms TTIs
            self._avg[rnti] = (1.0 - self.alpha) * old + self.alpha * r


class PfFfMacScheduler(_ThroughputEma, FfMacScheduler):
    """Proportional fair (pf-ff-mac-scheduler.cc): metric = achievable
    rate / exponentially-averaged served throughput."""

    name = "pf"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        order = sorted(
            candidates,
            key=lambda c: _rate_bps(c, rbg_size) / self.avg(c.rnti),
            reverse=True,
        )
        return self._fill(order, free_rbgs, rbg_size)




def _rate_bps(c: SchedCandidate, rbg_size: int) -> float:
    """Achievable rate per RBG at the candidate's wideband CQI."""
    return tbs_bits_py(mcs_from_cqi_py(c.cqi), rbg_size) * 1000.0


class TdMtFfMacScheduler(FfMacScheduler):
    """Time-domain max throughput (tdmt-ff-mac-scheduler.cc): ONE UE —
    the one with the highest achievable rate — owns the whole TTI."""

    name = "tdmt"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        live = [c for c in candidates if c.cqi >= 1 and c.queue_bytes > 0]
        if not live:
            return []
        best = max(live, key=lambda c: (_rate_bps(c, rbg_size), -c.rnti))
        return self._fill([best], free_rbgs, rbg_size)


class FdMtFfMacScheduler(FfMacScheduler):
    """Frequency-domain max throughput (fdmt-ff-mac-scheduler.cc): RBGs
    go to the highest-rate UE first; leftovers cascade down the rate
    order (at wideband-CQI fidelity the per-RBG argmax is flat, so the
    cascade IS the per-RBG rule)."""

    name = "fdmt"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        order = sorted(
            candidates, key=lambda c: (_rate_bps(c, rbg_size), -c.rnti),
            reverse=True,
        )
        return self._fill(order, free_rbgs, rbg_size)


class TtaFfMacScheduler(RrFfMacScheduler):
    """Throughput-to-average (tta-ff-mac-scheduler.cc): metric = subband
    rate / wideband rate.  With wideband CQI the ratio is identically 1
    for every UE (documented fidelity limit), so the scheduler reduces
    exactly to RR rotation over live flows — inherited rather than
    re-implemented; subband CQI would give the metric teeth."""

    name = "tta"


class _BetMixin(_ThroughputEma):
    """Blind equal throughput: metric = 1 / past served throughput —
    no channel term, so unequal-CQI UEs converge to equal BITS (where
    RR converges to equal airtime)."""

    def _metric(self, c: SchedCandidate) -> float:
        return 1.0 / self.avg(c.rnti)


class TdBetFfMacScheduler(_BetMixin, FfMacScheduler):
    """Time-domain BET (tdbet-ff-mac-scheduler.cc): the UE with the
    lowest past throughput owns the whole TTI."""

    name = "tdbet"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        live = [c for c in candidates if c.cqi >= 1 and c.queue_bytes > 0]
        if not live:
            return []
        best = max(live, key=lambda c: (self._metric(c), -c.rnti))
        return self._fill([best], free_rbgs, rbg_size)


class FdBetFfMacScheduler(_BetMixin, FfMacScheduler):
    """Frequency-domain BET (fdbet-ff-mac-scheduler.cc): fill in order
    of lowest past throughput."""

    name = "fdbet"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        order = sorted(
            candidates, key=lambda c: (self._metric(c), -c.rnti),
            reverse=True,
        )
        return self._fill(order, free_rbgs, rbg_size)


class CqaFfMacScheduler(_ThroughputEma, FfMacScheduler):
    """Channel-and-QoS-aware (cqa-ff-mac-scheduler.cc, simplified to
    the candidate fields available): flows are grouped by head-of-line
    delay (larger = more urgent) and served PF-style inside a group —
    upstream's d_HOL grouping with its per-group channel metric."""

    name = "cqa"

    def __init__(self, alpha: float = 0.05, group_ms: float = 10.0):
        super().__init__(alpha)
        self.group_ms = group_ms

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        def key(c: SchedCandidate):
            group = int(c.hol_delay_ms // self.group_ms)
            pf = _rate_bps(c, rbg_size) / self.avg(c.rnti)
            return (group, pf)

        order = sorted(candidates, key=key, reverse=True)
        return self._fill(order, free_rbgs, rbg_size)


class PssFfMacScheduler(_ThroughputEma, FfMacScheduler):
    """Priority set scheduler (pss-ff-mac-scheduler.cc): flows whose
    served throughput sits below their target bit rate form the
    priority set (served first, most-starved first); the rest share
    PF-style."""

    name = "pss"

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        prio, rest = [], []
        for c in candidates:  # single pass, no identity games
            (prio if c.tbr_bps > 0 and self.avg(c.rnti) < c.tbr_bps
             else rest).append(c)
        prio.sort(key=lambda c: self.avg(c.rnti) / max(c.tbr_bps, 1.0))
        rest.sort(
            key=lambda c: _rate_bps(c, rbg_size) / self.avg(c.rnti),
            reverse=True,
        )
        return self._fill(prio + rest, free_rbgs, rbg_size)


SCHEDULERS = {
    "tpudes::PfFfMacScheduler": PfFfMacScheduler,
    "ns3::PfFfMacScheduler": PfFfMacScheduler,
    "tpudes::RrFfMacScheduler": RrFfMacScheduler,
    "ns3::RrFfMacScheduler": RrFfMacScheduler,
}
for _cls in (TdMtFfMacScheduler, FdMtFfMacScheduler, TtaFfMacScheduler,
             TdBetFfMacScheduler, FdBetFfMacScheduler, CqaFfMacScheduler,
             PssFfMacScheduler):
    _name = _cls.__name__
    SCHEDULERS[f"tpudes::{_name}"] = _cls
    SCHEDULERS[f"ns3::{_name}"] = _cls


def resolve_scheduler(name: str) -> str:
    """Short name ('pf', 'tdbet', ...) or full TypeId → the canonical
    TypeId string SetSchedulerType accepts; raises with the valid list."""
    if name in SCHEDULERS:
        return name
    by_short = {
        cls.name: f"tpudes::{cls.__name__}" for cls in set(SCHEDULERS.values())
    }
    if name in by_short:
        return by_short[name]
    raise ValueError(
        f"unknown scheduler {name!r}; valid: {sorted(by_short)} "
        "or any full TypeId"
    )
