"""FF-MAC schedulers (PF, RR) + HARQ bookkeeping.

Reference parity: src/lte/model/ff-mac-scheduler.h (the FemtoForum
scheduler API), pf-ff-mac-scheduler.{h,cc}, rr-ff-mac-scheduler.{h,cc},
lte-harq-phy.{h,cc} (upstream paths; mount empty at survey — SURVEY.md
§0, §2.6 "MAC + FF-MAC scheduler API" and "HARQ" rows).

The scheduler works on resource-block *groups* (RBGs, TS 36.213 type-0
allocation) and ideal buffer-status reports read straight from the RLC
entities.  With wideband CQI the per-RBG PF metric is flat across
frequency, so allocation is a greedy fill: best metric first, each flow
takes only the RBGs its buffer needs, remainder to the next flow —
which degenerates to winner-takes-all under full-buffer load and to
frequency multiplexing under light load, matching upstream PF behavior
at wideband-CQI fidelity.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

from tpudes.ops.lte import mcs_from_cqi_py, tbs_bits_py

HARQ_RTT_TTIS = 8
HARQ_MAX_TX = 4  # 1 first tx + 3 retransmissions


def rbg_size_for(n_rb: int) -> int:
    """TS 36.213 table 7.1.6.1-1 type-0 RBG sizes."""
    if n_rb <= 10:
        return 1
    if n_rb <= 26:
        return 2
    if n_rb <= 63:
        return 3
    return 4


@dataclass
class SchedCandidate:
    """Per-flow scheduler input (the FF-MAC SchedDlTriggerReq view)."""

    rnti: int
    cqi: int
    queue_bytes: int
    avg_thr_bps: float = 1.0


@dataclass
class Allocation:
    """One scheduled transport block."""

    rnti: int
    rbgs: list[int]
    mcs: int
    tb_bytes: int
    harq: "HarqTb | None" = None  # set for retransmissions


@dataclass
class HarqTb:
    """In-flight transport block awaiting ack (lte-harq-phy soft-buffer
    entry): MI accumulates over retransmissions (IR combining)."""

    rnti: int
    pdu: object            # RlcPdu being carried
    mcs: int
    n_rbg: int
    tb_bytes: int
    mi_acc: float = 0.0
    tx_count: int = 1
    due_tti: int = 0       # next (re)tx TTI
    bearer: object = None  # RadioBearer the PDU belongs to
    rnti_ue_index: int = -1  # controller's global UE index


class FfMacScheduler:
    """Abstract FF-MAC scheduler: allocate free RBGs among candidates."""

    name = "abstract"

    def schedule(
        self, tti: int, candidates: list[SchedCandidate], free_rbgs: list[int],
        rbg_size: int,
    ) -> list[Allocation]:
        raise NotImplementedError

    def update_served(self, rnti: int, bits: int) -> None:
        """Post-TTI feedback hook (PF throughput averaging)."""

    # --- shared helpers ---
    @staticmethod
    def _fill(
        order: list[SchedCandidate], free_rbgs: list[int], rbg_size: int
    ) -> list[Allocation]:
        """Greedy fill in metric order; each flow takes only the RBGs
        its buffer needs (+RLC header slack)."""
        allocs: list[Allocation] = []
        free = list(free_rbgs)
        for cand in order:
            if not free or cand.cqi < 1 or cand.queue_bytes <= 0:
                continue
            mcs = mcs_from_cqi_py(cand.cqi)
            bytes_per_rbg = max(tbs_bits_py(mcs, rbg_size) // 8, 1)
            need = min(
                math.ceil((cand.queue_bytes + 4) / bytes_per_rbg), len(free)
            )
            take, free = free[:need], free[need:]
            tb_bytes = tbs_bits_py(mcs, len(take) * rbg_size) // 8
            allocs.append(Allocation(cand.rnti, take, mcs, tb_bytes))
        return allocs


class RrFfMacScheduler(FfMacScheduler):
    """Round-robin (rr-ff-mac-scheduler.cc): rotate a pointer over the
    active flows; equal opportunity, CQI only picks the MCS."""

    name = "rr"

    def __init__(self):
        self._next = 0

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        if not candidates:
            return []
        order = sorted(candidates, key=lambda c: c.rnti)
        start = self._next % len(order)
        rotated = order[start:] + order[:start]
        allocs = self._fill(rotated, free_rbgs, rbg_size)
        if allocs:
            self._next = (start + 1) % len(order)
        return allocs


class PfFfMacScheduler(FfMacScheduler):
    """Proportional fair (pf-ff-mac-scheduler.cc): metric = achievable
    rate / exponentially-averaged served throughput."""

    name = "pf"

    def __init__(self, alpha: float = 0.05):
        self.alpha = alpha
        self._avg: dict[int, float] = {}

    def schedule(self, tti, candidates, free_rbgs, rbg_size):
        def metric(c: SchedCandidate) -> float:
            mcs = mcs_from_cqi_py(c.cqi)
            rate = tbs_bits_py(mcs, rbg_size) * 1000.0  # bits/s if served
            return rate / max(self._avg.get(c.rnti, 1.0), 1.0)

        order = sorted(candidates, key=metric, reverse=True)
        return self._fill(order, free_rbgs, rbg_size)

    def end_tti(self, served_bits: dict[int, int], active_rntis) -> None:
        """EMA update for every active flow: T ← (1−α)T + α·r, with r=0
        for flows not served this TTI (the classic PF average)."""
        for rnti in active_rntis:
            old = self._avg.get(rnti, 1.0)
            r = served_bits.get(rnti, 0) * 1000.0  # bits/s at 1 ms TTIs
            self._avg[rnti] = (1.0 - self.alpha) * old + self.alpha * r


SCHEDULERS = {
    "tpudes::PfFfMacScheduler": PfFfMacScheduler,
    "ns3::PfFfMacScheduler": PfFfMacScheduler,
    "tpudes::RrFfMacScheduler": RrFfMacScheduler,
    "ns3::RrFfMacScheduler": RrFfMacScheduler,
}
