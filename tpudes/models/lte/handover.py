"""Handover algorithms + X2-lite execution.

Reference parity: src/lte/model/a3-rsrp-handover-algorithm.{h,cc},
a2-a4-rsrq-handover-algorithm.{h,cc}, lte-enb-rrc.cc handover
preparation/execution and epc-x2.{h,cc} (upstream paths; mount empty at
survey — SURVEY.md §0, §2.6 "Handover & FFR" row).

The A3 event (TS 36.331): a neighbour's RSRP exceeds the serving cell's
by ``Hysteresis`` continuously for ``TimeToTrigger`` → hand the UE
over.  Measurements come from the controller's batched gain matrix
(already rebuilt per TTI under mobility), evaluated every
``MEASUREMENT_PERIOD_TTIS`` — the analog of upstream's filtered
measurement reports.

X2-lite execution: upstream runs an over-the-air RRC reconfiguration +
X2 SN-status transfer + data forwarding.  Here the handover is the
ideal-RRC equivalent (matching the module's ideal RRC everywhere): the
UeContext's bearers move wholesale to the target cell in one event, so
PDCP/RLC state (including AM retransmission buffers) survives — the
"lossless handover" X2 forwarding achieves; in-flight HARQ processes at
the source are flushed, as upstream's MAC reset does.
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId

MEASUREMENT_PERIOD_TTIS = 40  # ≈ upstream's 200 ms layer-3 filter cadence / 5


class LteHandoverAlgorithm(Object):
    tid = TypeId("tpudes::LteHandoverAlgorithm")

    def evaluate(self, tti: int, ue_index: int, serving: int,
                 rsrp_dbm_row) -> int | None:
        """-> target eNB index, or None to stay."""
        raise NotImplementedError


class A3RsrpHandoverAlgorithm(LteHandoverAlgorithm):
    tid = (
        TypeId("tpudes::A3RsrpHandoverAlgorithm")
        .SetParent(LteHandoverAlgorithm.tid)
        .AddConstructor(lambda **kw: A3RsrpHandoverAlgorithm(**kw))
        .AddAttribute("Hysteresis", "A3 offset (dB)", 3.0, field="hysteresis_db")
        .AddAttribute(
            "TimeToTrigger", "sustained-condition time (ms)", 256,
            field="time_to_trigger_ms",
        )
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        #: (ue_index, target) -> (tti the A3 condition first held,
        #:                        tti it was last confirmed)
        self._entered: dict[tuple[int, int], tuple[int, int]] = {}
        self._sweep_ev = None

    def evaluate(self, tti: int, ue_index: int, serving: int, rsrp_dbm_row):
        import numpy as np

        best = int(np.argmax(rsrp_dbm_row))
        # the A3 condition must hold CONTINUOUSLY for one target: any
        # tracked entry for a different target has lapsed — drop it, or
        # a stale start time re-triggers "instantly" on re-entry
        # (r4 review)
        for key in [k for k in self._entered
                    if k[0] == ue_index and k[1] != best]:
            del self._entered[key]
        if best == serving:
            return None
        if rsrp_dbm_row[best] < rsrp_dbm_row[serving] + self.hysteresis_db:
            self._entered.pop((ue_index, best), None)
            return None
        key = (ue_index, best)
        start, _ = self._entered.get(key, (tti, tti))
        self._entered[key] = (start, tti)
        self._arm_sweep()
        if tti - start >= self.time_to_trigger_ms:  # 1 TTI = 1 ms
            del self._entered[key]
            return best
        return None

    # --- stranded-entry expiry -------------------------------------------
    # evaluate() prunes a UE's entries only when it is called FOR that
    # UE; a UE that detaches (or a controller that stops measuring it)
    # would otherwise strand its pending (ue, target) entries forever.
    # A periodic sweep drops any entry not re-confirmed within a lapse
    # window — a live A3 condition is confirmed every measurement
    # period, so only genuinely abandoned entries can age past it.

    def _lapse_ttis(self) -> int:
        return 2 * MEASUREMENT_PERIOD_TTIS + int(self.time_to_trigger_ms)

    def _arm_sweep(self) -> None:
        from tpudes.core.nstime import MilliSeconds
        from tpudes.core.simulator import Simulator

        if self._sweep_ev is not None and not self._sweep_ev.IsExpired():
            return
        self._sweep_ev = Simulator.Schedule(
            MilliSeconds(self._lapse_ttis()), self._sweep_stranded
        )

    def _sweep_stranded(self) -> None:
        from tpudes.core.simulator import Simulator

        now = int(Simulator.Now().GetMilliSeconds())
        lapse = self._lapse_ttis()
        for key in [
            k for k, (_, seen) in self._entered.items()
            if now - seen >= lapse
        ]:
            del self._entered[key]
        if self._entered:
            self._arm_sweep()


HANDOVER_ALGORITHMS = {
    "tpudes::A3RsrpHandoverAlgorithm": A3RsrpHandoverAlgorithm,
    "ns3::A3RsrpHandoverAlgorithm": A3RsrpHandoverAlgorithm,
}
