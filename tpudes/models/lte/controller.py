"""LteTtiController — the batched per-TTI engine for every cell at once.

Reference parity (SURVEY.md §3.4 call stack): upstream clocks each eNB
with per-subframe events (LteEnbPhy::StartSubFrame), each of which runs
the FF-MAC scheduler, transmits over MultiModelSpectrumChannel (an
O(eNB×UE) loop), collects interference chunks, and decodes TBs per UE
(LteSpectrumPhy::StartRxData → LteInterference → LteMiErrorModel).

TPU-first redesign: LTE subframes are *synchronous network-wide*, so
the whole per-TTI PHY — every cell's PSD, every UE's per-RB SINR, MI,
BLER and decode draw, both directions — is ONE jitted kernel call
(ops/lte.py::tti_phy_step) driven by ONE simulator event per TTI.  The
host side keeps what is genuinely sequential/stateful: FF-MAC
scheduling decisions, RLC segmentation, HARQ bookkeeping, RRC state.
This is the 1 ms natural conservative window SURVEY.md §7 hard-part 1
identifies ("LTE is easier: 1 ms TTI is a natural window").

Timing-model notes (deviations, all fixed offsets):
- TB decode outcome is computed in the transmitting TTI's event; HARQ
  retransmissions run at +8 TTIs (the upstream HARQ RTT), CQI feedback
  applies after ``CQI_DELAY_TTIS``.
- Uplink uses the same type-0 RBG allocation as downlink (upstream UL
  is contiguous SC-FDMA allocation).
- UE→eNB and eNB→UE path gains are reciprocal (same loss model, no
  per-direction fading this round).
"""

from __future__ import annotations

import numpy as np

from tpudes.core.nstime import MilliSeconds
from tpudes.core.rng import RngSeedManager
from tpudes.core.simulator import Simulator
from tpudes.models.lte.scheduler import (
    HARQ_MAX_TX,
    HARQ_RTT_TTIS,
    Allocation,
    HarqTb,
    SchedCandidate,
    rbg_size_for,
)
from tpudes.ops.lte import RB_BANDWIDTH_HZ

CQI_DELAY_TTIS = 3


class LteTtiController:
    """One instance per LteHelper: owns the synchronized TTI clock and
    the batched PHY state for all installed cells and UEs."""

    def __init__(self, pathloss_model, n_rb: int = 25):
        self.pathloss = pathloss_model
        self.n_rb = n_rb
        self.rbg_size = rbg_size_for(n_rb)
        self.n_rbg = (n_rb + self.rbg_size - 1) // self.rbg_size
        self.enbs: list = []
        self.ues: list = []
        self.tti = 0
        self._started = False
        self.lifted = False   # set by parallel.lift: device engine owns the run
        self._dirty = True
        self._static_geometry = True
        #: True once a windowed engine has driven refresh_window_cache:
        #: the per-TTI event then trusts the window snapshot instead of
        #: re-evaluating mobile geometry at every event
        self._windowed = False
        # second BatchableRegistry consumer beside YansWifiChannel: the
        # windowed engine refreshes the per-TTI SINR evaluation tables
        # once per window instead of once per TTI event
        from tpudes.parallel.engine import BatchableRegistry

        BatchableRegistry.register(self)
        # device-side constants (built lazily)
        self._gain_dl = None          # (E, U)
        self._gain_ul_eff = None      # (U, U): v's gain at u's serving eNB
        self._serving = None          # (U,)
        self._harq_dl: dict[int, list[HarqTb]] = {}
        self._harq_ul: dict[int, list[HarqTb]] = {}
        self._cqi_dl = None           # (U,) applied CQI at the eNB
        self._cqi_ul = None
        self._cqi_queue: list = []    # (apply_tti, cqi_dl, cqi_ul)
        self._key = None
        self._jit_step = None
        self.handover_algorithm = None   # set via LteHelper
        self.ffr_algorithm = None        # set via LteHelper (RBG masks)
        self.last_alloc: dict = {}       # per-direction (U, n_rb) masks
        self.x2_enabled = False          # AddX2Interface arms execution
        self.handover_log: list = []     # (tti, imsi, from_cell, to_cell)
        self.stats = {
            "dl_tbs": 0, "dl_ok": 0, "dl_harq_retx": 0, "dl_drops": 0,
            "ul_tbs": 0, "ul_ok": 0, "ul_harq_retx": 0, "ul_drops": 0,
            "ttis": 0, "handovers": 0,
        }

    # --- wiring -----------------------------------------------------------
    def add_enb(self, dev) -> None:
        self.enbs.append(dev)
        self._harq_dl[len(self.enbs) - 1] = []
        self._harq_ul[len(self.enbs) - 1] = []
        self._dirty = True

    def add_ue(self, dev) -> None:
        self.ues.append(dev)
        self._dirty = True

    def attach(self, ue_dev, enb_dev) -> None:
        ctx = enb_dev.rrc.add_ue(ue_dev)
        ue_dev.rrc.connect(enb_dev, ctx.rnti)
        self._dirty = True
        self.start()

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        import jax

        self._key = jax.random.PRNGKey(
            (RngSeedManager.GetSeed() * 2654435761 + RngSeedManager.GetRun())
            & 0x7FFFFFFF
        )
        Simulator.Schedule(MilliSeconds(0), self._tti_event)

    # --- geometry / arrays ------------------------------------------------
    def _positions(self, devs) -> np.ndarray:
        from tpudes.models.mobility import MobilityModel

        pos = np.zeros((len(devs), 3), dtype=np.float64)
        for i, d in enumerate(devs):
            mob = d.GetNode().GetObject(MobilityModel)
            if mob is None:
                raise RuntimeError("LTE devices need a mobility model")
            p = mob.GetPosition()
            pos[i] = (p.x, p.y, p.z)
            if "ConstantPosition" not in type(mob).__name__:
                self._static_geometry = False
        return pos

    def _rebuild(self) -> None:
        self._dirty = False
        e, u = len(self.enbs), len(self.ues)
        if e == 0 or u == 0:
            return
        self._static_geometry = True
        self._compute_gain_dl()
        serving = np.full((u,), -1, dtype=np.int64)
        enb_index = {id(dev): i for i, dev in enumerate(self.enbs)}
        for i, ue in enumerate(self.ues):
            s = ue.rrc.serving_enb
            if s is not None:
                serving[i] = enb_index[id(s)]
        self._serving = serving
        self._ue_index = {id(dev): i for i, dev in enumerate(self.ues)}
        # UL CQI is measured SRS-style: intra-cell sounding is orthogonal,
        # so co-served transmitters must NOT appear as interferers in the
        # reference scenario (only inter-cell UEs + noise do).  Without
        # this mask every same-cell UE looks like a full-band interferer
        # and all but one UE per cell report CQI 0 permanently.
        # attachment-aware: an unattached UE (serving -1) is nobody's
        # cell-mate — it stays a real interferer everywhere
        same_cell = (serving[:, None] == serving[None, :]) & (
            serving[:, None] >= 0
        )                                                   # (v, u)
        # kept for the geometry-only refresh (attachment topology: only
        # a handover/attach — which sets _dirty — can change it)
        self._srs_mask = np.where(
            same_cell & ~np.eye(u, dtype=bool), 0.0, 1.0
        )
        self._publish_gain_residents()
        if self._cqi_dl is None or len(self._cqi_dl) != u:
            self._cqi_dl = np.zeros((u,), dtype=np.int64)
            self._cqi_ul = np.zeros((u,), dtype=np.int64)
        # full-power reference PSDs (RS-like) for CQI measurement; under
        # FFR each cell's reference occupies only its allowed subband,
        # so CQI (and hence MCS) sees the reuse pattern's interference
        def _cell_rbs(e_idx: int) -> list[int]:
            if self.ffr_algorithm is None:
                return list(range(self.n_rb))
            return self._rbgs_to_rbs(
                self.ffr_algorithm.allowed_rbgs(e_idx, self.n_rbg)
            )

        self._ref_psd_dl = np.zeros((e, self.n_rb))
        for i, enb in enumerate(self.enbs):
            p_w = 10.0 ** ((enb.phy.tx_power_dbm - 30.0) / 10.0)
            self._ref_psd_dl[i, _cell_rbs(i)] = p_w / (
                self.n_rb * RB_BANDWIDTH_HZ
            )
        self._ref_psd_ul = np.zeros((u, self.n_rb))
        for i, ue in enumerate(self.ues):
            p_w = 10.0 ** ((ue.phy.tx_power_dbm - 30.0) / 10.0)
            rbs = _cell_rbs(int(serving[i])) if serving[i] >= 0 else list(
                range(self.n_rb)
            )
            self._ref_psd_ul[i, rbs] = p_w / (self.n_rb * RB_BANDWIDTH_HZ)
        nf_ue = {float(ue.phy.noise_figure_db) for ue in self.ues}
        nf_enb = {float(enb.phy.noise_figure_db) for enb in self.enbs}
        if len(nf_ue) > 1 or len(nf_enb) > 1:
            raise RuntimeError(
                "batched TTI path assumes uniform noise figures per side"
            )
        self._noise_dl = self.ues[0].phy.noise_psd
        self._noise_ul = self.enbs[0].phy.noise_psd
        if self._jit_step is None:
            import jax

            from tpudes.ops.lte import tti_phy_step

            # both directions fused into ONE device call per TTI: over a
            # remote accelerator (axon tunnel) each host↔device round
            # trip costs ~100 ms, so the TTI event makes exactly one
            # dispatch and one device_get (SURVEY.md §7 hard part 3)
            def both(dl_args, ul_args, ul_ref_gain, noise_dl, noise_ul, k):
                import jax as _jax

                k_dl, k_ul = _jax.random.split(k)
                return (
                    tti_phy_step(*dl_args, k_dl, noise_dl),
                    tti_phy_step(*ul_args, k_ul, noise_ul, ul_ref_gain),
                )

            self._jit_step = jax.jit(both)

    # --- per-window batched refresh (JaxSimulatorImpl contract) -----------
    def refresh_window_cache(self) -> None:
        """Rebuild geometry + the batched per-TTI SINR reference tables
        (gain matrices, reference PSDs) ONCE per conservative window.
        Mobile graphs otherwise pay one full rebuild per TTI *event*;
        under the windowed engine every TTI inside the window reads the
        window-start snapshot — the same granted-time-window geometry
        contract YansWifiChannel's pair-table cache follows.

        This whole path is the FALLBACK behind device-resident mobility
        (``tpudes.parallel.lte_sm`` lifts moving UEs into the scan):
        when it does run, ``TPUDES_DEVICE_GEOM`` selects between the
        geometry-only refresh (recompute exactly the position-dependent
        arrays; the attachment-topology tables were built once) and the
        legacy full rebuild — bit-equal by construction, since the
        geometry-only path runs the same math on the same inputs."""
        from tpudes.obs.geometry import GeomTelemetry
        from tpudes.ops.mobility import device_geom_enabled

        if self._dirty:
            if self.enbs and self.ues:
                self._rebuild()
        elif not self._static_geometry and self.enbs and self.ues:
            if device_geom_enabled():
                self._refresh_geometry()
            else:
                self._rebuild()
            GeomTelemetry.record_host("lte_ctrl")
        self._windowed = True

    def _refresh_geometry(self) -> None:
        """The position-dependent slice of :meth:`_rebuild` — gain
        matrices (+ scene loss) and their device residents, nothing
        else.  Bit-equal to a full rebuild BY CONSTRUCTION: both paths
        call the same two helpers below; this one just skips
        re-deriving the attachment topology (serving maps, SRS mask,
        reference PSDs, noise figures, the jitted step) that only a
        ``_dirty``-setting event can change."""
        self._compute_gain_dl()
        self._publish_gain_residents()

    def _compute_gain_dl(self) -> None:
        """positions → distance → loss chain (+ scene effects) →
        ``_gain_dl`` — the geometry half shared by :meth:`_rebuild`
        and :meth:`_refresh_geometry`."""
        import jax.numpy as jnp

        from tpudes.models.lte.scene import scene_loss_db

        pos_e = self._positions(self.enbs)
        pos_u = self._positions(self.ues)
        d = np.sqrt(
            ((pos_e[:, None, :] - pos_u[None, :, :]) ** 2).sum(-1)
        )  # (E, U)
        # loss chain evaluated as one batched kernel call: gain below
        # unity, reciprocal between directions; buildings (wall
        # penetration) + antennas (directional gain) ride the shared
        # scene implementation (one copy with the REM helper)
        loss_db = -np.asarray(
            self.pathloss.batch_rx_power(jnp.zeros(()), jnp.asarray(d))
        )
        loss_db = loss_db + scene_loss_db(self.enbs, pos_e, pos_u)
        self._gain_dl = 10.0 ** (-loss_db / 10.0)               # (E, U)

    def _publish_gain_residents(self) -> None:
        """``_gain_dl`` + the (attachment-topology) serving map / SRS
        mask → the UL effective gains and the device-resident arrays
        the TTI step consumes — static across TTIs, so device-resident
        once instead of re-shipped per dispatch."""
        import jax.numpy as jnp

        # v transmitting → power at u's serving eNB: (U, U)
        safe = np.maximum(self._serving, 0)
        self._gain_ul_eff = self._gain_dl.T[:, safe].astype(np.float64)
        self._gain_ul_ref = jnp.asarray(self._gain_ul_eff * self._srs_mask)
        self._gain_dl_dev = jnp.asarray(self._gain_dl)
        self._gain_ul_dev = jnp.asarray(self._gain_ul_eff)

    def _rbgs_to_rbs(self, rbgs) -> list[int]:
        """TS 36.213 type-0: expand RBG indices to RB indices (one
        implementation for allocation AND the CQI reference grid)."""
        return [
            r
            for g in rbgs
            for r in range(
                g * self.rbg_size, min((g + 1) * self.rbg_size, self.n_rb)
            )
        ]

    # --- per-TTI scheduling (host side) -----------------------------------
    def _cell_ue_indices(self, e_idx: int) -> list[int]:
        return [i for i in range(len(self.ues)) if self._serving[i] == e_idx]

    def _schedule_direction(self, direction: str):
        """Run HARQ-first + FF-MAC allocation for every cell; returns the
        packed (alloc, mcs, tb_bits, mi_acc, tx_psd, served) arrays."""
        u = len(self.ues)
        e = len(self.enbs)
        alloc = np.zeros((u, self.n_rb), dtype=bool)
        mcs = np.zeros((u,), dtype=np.int64)
        tb_bits = np.zeros((u,), dtype=np.float64)
        mi_acc = np.zeros((u,), dtype=np.float64)
        tx_psd = np.zeros((e, self.n_rb)) if direction == "dl" else np.zeros(
            (u, self.n_rb)
        )
        tb_by_ue: dict[int, HarqTb] = {}
        harq_map = self._harq_dl if direction == "dl" else self._harq_ul
        cqi = self._cqi_dl if direction == "dl" else self._cqi_ul

        for e_idx, enb in enumerate(self.enbs):
            members = self._cell_ue_indices(e_idx)
            if not members:
                continue
            if self.ffr_algorithm is not None:
                free = list(
                    self.ffr_algorithm.allowed_rbgs(e_idx, self.n_rbg)
                )
            else:
                free = list(range(self.n_rbg))
            allocs: list[Allocation] = []
            # 1. HARQ retransmissions due this TTI
            pending = harq_map[e_idx]
            still: list[HarqTb] = []
            for tb in pending:
                ue_i = tb.rnti_ue_index
                if tb.due_tti > self.tti or ue_i in tb_by_ue:
                    still.append(tb)
                    continue
                if len(free) < tb.n_rbg:
                    tb.due_tti = self.tti + 1
                    still.append(tb)
                    continue
                take, free = free[: tb.n_rbg], free[tb.n_rbg:]
                allocs.append(
                    Allocation(tb.rnti, take, tb.mcs, tb.tb_bytes, harq=tb)
                )
                self.stats[f"{direction}_harq_retx"] += 1
            harq_map[e_idx] = still
            # 2. new transmissions
            scheduler = (
                enb.scheduler if direction == "dl" else enb.ul_scheduler
            )
            rnti_to_ue = {
                ctx.rnti: self._ue_index[id(ctx.ue_device)]
                for ctx in enb.rrc.ues.values()
            }
            candidates = []
            for rnti, ctx in enb.rrc.ues.items():
                ue_i = rnti_to_ue[rnti]
                if ue_i in tb_by_ue or any(
                    tb.rnti == rnti for tb in allocs
                ):
                    continue  # one TB per UE per TTI
                queue = sum(
                    (b.dl_tx if direction == "dl" else b.ul_tx).BufferBytes()
                    for b in ctx.bearers.values()
                )
                if queue <= 0 or cqi[ue_i] < 1:
                    continue
                candidates.append(
                    SchedCandidate(rnti, int(cqi[ue_i]), queue)
                )
            allocs.extend(
                scheduler.schedule(self.tti, candidates, free, self.rbg_size)
            )
            # 3. pack allocations into arrays + pull RLC PDUs
            for a in allocs:
                ue_i = rnti_to_ue.get(a.rnti)
                if ue_i is None or ue_i in tb_by_ue:
                    continue
                ctx = enb.rrc.ues[a.rnti]
                if a.harq is None:
                    pdu = None
                    for b in sorted(ctx.bearers):
                        rlc = (
                            ctx.bearers[b].dl_tx
                            if direction == "dl"
                            else ctx.bearers[b].ul_tx
                        )
                        pdu = rlc.NotifyTxOpportunity(a.tb_bytes)
                        if pdu is not None:
                            tb = HarqTb(
                                a.rnti, pdu, a.mcs, len(a.rbgs), a.tb_bytes
                            )
                            tb.bearer = ctx.bearers[b]
                            tb.tx_count = 1
                            break
                    if pdu is None:
                        continue
                    self.stats[f"{direction}_tbs"] += 1
                else:
                    tb = a.harq
                    tb.tx_count += 1
                tb.rnti_ue_index = ue_i
                tb_by_ue[ue_i] = tb
                rbs = self._rbgs_to_rbs(a.rbgs)
                alloc[ue_i, rbs] = True
                mcs[ue_i] = a.mcs
                tb_bits[ue_i] = a.tb_bytes * 8.0
                mi_acc[ue_i] = tb.mi_acc
                if direction == "dl":
                    p_w = 10.0 ** ((enb.phy.tx_power_dbm - 30.0) / 10.0)
                    tx_psd[e_idx, rbs] += p_w / (self.n_rb * RB_BANDWIDTH_HZ)
                else:
                    ue = self.ues[ue_i]
                    p_w = 10.0 ** ((ue.phy.tx_power_dbm - 30.0) / 10.0)
                    # UL concentrates the UE's power in its allocated RBs
                    tx_psd[ue_i, rbs] = p_w / (len(rbs) * RB_BANDWIDTH_HZ)
        return alloc, mcs, tb_bits, mi_acc, tx_psd, tb_by_ue

    # --- handover (A3 measurement + X2-lite execution) --------------------
    def _evaluate_handover(self) -> None:
        from tpudes.models.lte.handover import MEASUREMENT_PERIOD_TTIS

        if (
            self.handover_algorithm is None
            or not self.x2_enabled
            or self.tti % MEASUREMENT_PERIOD_TTIS != 0
            or self._gain_dl is None
            or len(self.enbs) < 2
        ):
            return
        # RSRP per (E, U) from the already-batched gain matrix
        tx_dbm = np.array([e.phy.tx_power_dbm for e in self.enbs])
        rsrp_dbm = tx_dbm[:, None] + 10.0 * np.log10(
            np.maximum(self._gain_dl, 1e-30)
        )
        moves = []
        for u_i, ue in enumerate(self.ues):
            s = int(self._serving[u_i])
            if s < 0:
                continue
            target = self.handover_algorithm.evaluate(
                self.tti, u_i, s, rsrp_dbm[:, u_i]
            )
            if target is not None and target != s:
                moves.append((u_i, s, target))
        for u_i, s, target in moves:
            self._execute_handover(u_i, s, target)

    def _execute_handover(self, ue_index: int, src_idx: int, dst_idx: int):
        """X2-lite: move the UeContext (bearers intact — the lossless
        forwarding analog), flush in-flight HARQ at the source (the MAC
        reset), reconnect the UE, mark geometry dirty."""
        ue = self.ues[ue_index]
        source, target = self.enbs[src_idx], self.enbs[dst_idx]
        ctx = source.rrc.remove_ue(ue.rrc.rnti)
        if ctx is None:
            return
        for harq_map in (self._harq_dl, self._harq_ul):
            harq_map[src_idx] = [
                tb for tb in harq_map[src_idx]
                if tb.rnti_ue_index != ue_index
            ]
        new_ctx = target.rrc.add_ue(ue)
        new_ctx.bearers = ctx.bearers
        for b in new_ctx.bearers.values():
            b.ul_rx.rx_sdu_callback = target.receive_ul_sdu
        ue.rrc.connect(target, new_ctx.rnti)
        self.stats["handovers"] += 1
        self.handover_log.append(
            (self.tti, ue.GetImsi(), source.GetCellId(), target.GetCellId())
        )
        self._dirty = True

    # --- the TTI event ----------------------------------------------------
    def _tti_event(self) -> None:
        import jax
        import jax.numpy as jnp

        if self.lifted:
            return  # the lifted device program runs the scenario instead
        if self._dirty:
            self._rebuild()
        elif not self._static_geometry and not self._windowed:
            # per-event fallback: no windowed engine drives the registry,
            # so mobile geometry must be re-evaluated at every TTI —
            # geometry-only unless the kill switch wants the legacy
            # full rebuild (bit-equal either way; see _refresh_geometry)
            from tpudes.obs.geometry import GeomTelemetry
            from tpudes.ops.mobility import device_geom_enabled

            if device_geom_enabled():
                self._refresh_geometry()
            else:
                self._rebuild()
            GeomTelemetry.record_host("lte_ctrl")
        self._evaluate_handover()
        if self._dirty:
            self._rebuild()  # a handover just moved serving cells
        u, e = len(self.ues), len(self.enbs)
        if u and e:
            self.stats["ttis"] += 1
            key = jax.random.fold_in(self._key, self.tti)
            served_bits_by_cell: dict[str, dict[int, dict[int, int]]] = {}

            # host side: both directions' scheduling first, then ONE
            # fused device call and ONE device_get
            sched = {d: self._schedule_direction(d) for d in ("dl", "ul")}
            #: (U, n_rb) bool allocation masks of the last TTI, per
            #: direction — stats/test visibility (RB-usage traces)
            self.last_alloc = {d: sched[d][0] for d in ("dl", "ul")}

            def pack(direction):
                alloc, mcs, tb_bits, mi_acc, tx_psd, _ = sched[direction]
                if direction == "dl":
                    gain, serving, ref = (
                        self._gain_dl_dev, self._serving, self._ref_psd_dl,
                    )
                else:
                    gain, serving, ref = (
                        self._gain_ul_dev, np.arange(u), self._ref_psd_ul,
                    )
                return (
                    jnp.asarray(tx_psd),
                    jnp.asarray(ref),
                    jnp.asarray(gain),
                    jnp.asarray(np.maximum(serving, 0), dtype=jnp.int32),
                    jnp.asarray(alloc),
                    jnp.asarray(mcs, dtype=jnp.int32),
                    jnp.asarray(tb_bits, dtype=jnp.float32),
                    jnp.asarray(mi_acc, dtype=jnp.float32),
                )

            out_dl, out_ul = jax.device_get(
                self._jit_step(
                    pack("dl"), pack("ul"), self._gain_ul_ref,
                    self._noise_dl, self._noise_ul, key
                )
            )
            for direction, (ok, _bler, cqi_meas, mi_new) in (
                ("dl", out_dl), ("ul", out_ul)
            ):
                tb_by_ue = sched[direction][5]
                served: dict[int, dict[int, int]] = {}
                for ue_i, tb in tb_by_ue.items():
                    e_idx = int(self._serving[ue_i])
                    if ok[ue_i]:
                        rx = (
                            tb.bearer.dl_rx
                            if direction == "dl"
                            else tb.bearer.ul_rx
                        )
                        rx.ReceivePdu(tb.pdu)
                        self.stats[f"{direction}_ok"] += 1
                        served.setdefault(e_idx, {})[tb.rnti] = int(
                            tb.tb_bytes * 8
                        )
                    elif tb.tx_count < HARQ_MAX_TX:
                        tb.mi_acc = float(mi_new[ue_i])
                        tb.due_tti = self.tti + HARQ_RTT_TTIS
                        harq_map = (
                            self._harq_dl if direction == "dl" else self._harq_ul
                        )
                        harq_map[e_idx].append(tb)
                    else:
                        self.stats[f"{direction}_drops"] += 1
                served_bits_by_cell[direction] = served
                if direction == "dl":
                    self._pending_cqi_dl = cqi_meas
                else:
                    self._pending_cqi_ul = cqi_meas

            # CQI feedback delay
            self._cqi_queue.append(
                (self.tti + CQI_DELAY_TTIS, self._pending_cqi_dl,
                 self._pending_cqi_ul)
            )
            while self._cqi_queue and self._cqi_queue[0][0] <= self.tti + 1:
                _, cqi_dl, cqi_ul = self._cqi_queue.pop(0)
                self._cqi_dl = cqi_dl
                self._cqi_ul = cqi_ul
            # PF averages (both directions)
            for e_idx, enb in enumerate(self.enbs):
                rntis = [c.rnti for c in enb.rrc.ues.values()]
                for sched, dirn in ((enb.scheduler, "dl"), (enb.ul_scheduler, "ul")):
                    if hasattr(sched, "end_tti"):
                        sched.end_tti(
                            served_bits_by_cell.get(dirn, {}).get(e_idx, {}),
                            rntis,
                        )
        self.tti += 1
        Simulator.Schedule(MilliSeconds(1), self._tti_event)
