"""EPC core-network stub: PGW node + UE IP addressing + S1-U shortcut.

Reference parity: src/lte/model/epc-{sgw,pgw,mme}-application.{h,cc},
epc-gtpu-header.{h,cc}, helper/point-to-point-epc-helper.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0, §2.6 "EPC core
network" row).

Scope note (explicit stub, per the round-3 plan): upstream tunnels IP
packets through in-sim GTP-U/UDP links between eNB, SGW and PGW.  Here
the PGW is a real Node with a real IP stack and a ``PgwNetDevice``
claiming the UE subnet (7.0.0.0/8), but the S1-U leg PGW↔eNB is an
ideal zero-delay shortcut (direct RLC enqueue) rather than a modeled
GTP-U tunnel.  Remote hosts, routing, sockets and applications work
exactly as with the full EPC; only the backhaul leg's delay/capacity is
idealized.  GTP-U tunnel modeling is future work.
"""

from __future__ import annotations

from tpudes.core.object import TypeId
from tpudes.helper.containers import NodeContainer
from tpudes.helper.internet import InternetStackHelper
from tpudes.models.internet.ipv4 import (
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
    Ipv4StaticRouting,
)
from tpudes.models.internet.ipv4 import Ipv4Header
from tpudes.network.address import Ipv4Address, Ipv4Mask
from tpudes.network.net_device import NetDevice
from tpudes.network.node import Node


class PgwNetDevice(NetDevice):
    """The PGW's tunnel endpoint: IP packets routed to 7.0.0.0/8 exit
    the PGW stack here and are pushed down the serving eNB's DL bearer;
    uplink SDUs from eNBs enter the PGW stack through it."""

    tid = TypeId("tpudes::PgwNetDevice").SetParent(NetDevice.tid)

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._ue_by_ip: dict[int, object] = {}

    def register_ue(self, ip: Ipv4Address, ue_device) -> None:
        self._ue_by_ip[ip.addr] = ue_device

    def NeedsArp(self) -> bool:
        return False

    def IsBroadcast(self) -> bool:
        return False

    def Send(self, packet, dest, protocol: int) -> bool:
        header = packet.PeekHeader(Ipv4Header)
        if header is None:
            return False
        ue = self._ue_by_ip.get(header.GetDestination().addr)
        if ue is None:
            return False
        enb = ue.rrc.serving_enb
        if enb is None:
            return False
        return enb.dl_enqueue(ue, packet)

    def receive_from_enb(self, packet) -> None:
        """Uplink SDU arriving over the (ideal) S1-U leg."""
        self._deliver_up(packet, 0x0800, self._address, self._address, 0)


class EpcHelper:
    """point-to-point-epc-helper.cc analog with the stubbed S1-U leg."""

    UE_NETWORK = "7.0.0.0"
    UE_MASK = "255.0.0.0"

    def __init__(self):
        self.pgw_node = Node()
        InternetStackHelper().Install(self.pgw_node)
        self.pgw_device = PgwNetDevice()
        self.pgw_device.SetNode(self.pgw_node)
        self.pgw_node.AddDevice(self.pgw_device)
        ipv4 = self.pgw_node.GetObject(Ipv4L3Protocol)
        if_index = ipv4.AddInterface(self.pgw_device)
        ipv4.AddAddress(
            if_index,
            Ipv4InterfaceAddress(Ipv4Address("7.0.0.1"), Ipv4Mask(self.UE_MASK)),
        )
        routing = ipv4.GetRoutingProtocol()
        assert isinstance(routing, Ipv4StaticRouting)
        routing.AddNetworkRouteTo(
            Ipv4Address(self.UE_NETWORK), Ipv4Mask(self.UE_MASK), if_index
        )
        self._next_ue_host = 2

    def GetPgwNode(self) -> Node:
        return self.pgw_node

    def GetUeDefaultGatewayAddress(self) -> Ipv4Address:
        return Ipv4Address("7.0.0.1")

    def AssignUeIpv4Address(self, ue_devices) -> list[Ipv4Address]:
        """Give each UE a 7.0.0.0/8 address on its LTE device and a
        default route through it; register the UE at the PGW."""
        addrs = []
        for ue in ue_devices:
            node = ue.GetNode()
            ipv4 = node.GetObject(Ipv4L3Protocol)
            if ipv4 is None:
                raise RuntimeError(
                    "install the internet stack on UE nodes before "
                    "AssignUeIpv4Address"
                )
            n = self._next_ue_host - 2
            self._next_ue_host += 1
            # 253 hosts per /24, spilling across the /8 (avoids .0/.1/.255)
            addr = Ipv4Address(f"7.0.{n // 253}.{2 + n % 253}")
            if_index = ipv4.GetInterfaceForDevice(ue)
            if if_index < 0:
                if_index = ipv4.AddInterface(ue)
            ipv4.AddAddress(
                if_index, Ipv4InterfaceAddress(addr, Ipv4Mask(self.UE_MASK))
            )
            routing = ipv4.GetRoutingProtocol()
            if isinstance(routing, Ipv4StaticRouting):
                routing.SetDefaultRoute(
                    self.GetUeDefaultGatewayAddress(), if_index
                )
            ue.ue_ipv4 = addr
            self.pgw_device.register_ue(addr, ue)
            # uplink: eNB forwards reassembled SDUs to the PGW stack
            enb = ue.rrc.serving_enb
            if enb is not None and enb.ul_sdu_callback is None:
                enb.ul_sdu_callback = self.pgw_device.receive_from_enb
            addrs.append(addr)
        return addrs

    def wire_enbs(self, enb_devices) -> None:
        """Point every eNB's uplink exit at the PGW (ideal S1-U)."""
        for enb in enb_devices:
            enb.ul_sdu_callback = self.pgw_device.receive_from_enb
