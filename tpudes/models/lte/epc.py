"""EPC core network: SGW + PGW nodes, GTP-U over modeled S1-U/S5 links.

Reference parity: src/lte/model/epc-{enb,sgw,pgw}-application.{h,cc},
epc-gtpu-header.{h,cc}, helper/point-to-point-epc-helper.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0, §2.6 "EPC core
network" row).

The data plane is real: every user packet crosses a point-to-point
S1-U link (eNB ↔ SGW) and the S5 link (SGW ↔ PGW) as an in-sim
IPv4/UDP:2152/GTP-U frame, so the backhaul's delay and capacity shape
end-to-end traffic and a pcap on the S1-U wire decodes GTP-U
(tests/test_epc_gtpu.py pins both).  Control plane stays ideal:
S1-AP/S11 signaling and the handover path switch are in-memory (the
SGW resolves a TEID's serving eNB through the live RRC state at
forwarding time), and the MME is not a separate node — the upstream
serialized S1AP/GTPv2-C message surface is out of scope.
"""

from __future__ import annotations

import struct

from tpudes.core.object import TypeId
from tpudes.helper.internet import InternetStackHelper, Ipv4AddressHelper
from tpudes.models.internet.ipv4 import (
    Ipv4Header,
    Ipv4InterfaceAddress,
    Ipv4L3Protocol,
    Ipv4StaticRouting,
)
from tpudes.models.internet.udp import UdpL4Protocol
from tpudes.network.address import InetSocketAddress, Ipv4Address, Ipv4Mask
from tpudes.network.net_device import NetDevice
from tpudes.network.node import Node

GTPU_PORT = 2152


class GtpuHeader:
    """8-byte GTPv1-U header (epc-gtpu-header.cc): version 1, PT=1,
    message type 255 (G-PDU), length, TEID."""

    def __init__(self, teid: int = 0, payload_size: int = 0):
        self.teid = teid
        self.payload_size = payload_size

    def GetSerializedSize(self) -> int:
        return 8

    def Serialize(self) -> bytes:
        return struct.pack("!BBHI", 0x30, 255, self.payload_size, self.teid)

    @classmethod
    def Deserialize(cls, data: bytes):
        _flags, _mtype, length, teid = struct.unpack("!BBHI", data[:8])
        return cls(teid, length), 8


class PgwNetDevice(NetDevice):
    """The PGW's tunnel endpoint (epc-pgw-application.cc TFT side): IP
    packets routed to the UE network exit the PGW stack here and are
    GTP-U-encapsulated toward the SGW; uplink G-PDUs from the SGW are
    decapsulated and re-enter the PGW stack through it."""

    tid = TypeId("tpudes::PgwNetDevice").SetParent(NetDevice.tid)

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self.epc: "EpcHelper | None" = None

    def NeedsArp(self) -> bool:
        return False

    def IsBroadcast(self) -> bool:
        return False

    def Send(self, packet, dest, protocol: int) -> bool:
        header = packet.PeekHeader(Ipv4Header)
        if header is None or self.epc is None:
            return False
        teid = self.epc._teid_by_ueip.get(header.GetDestination().addr)
        if teid is None:
            return False
        return self.epc._pgw_send_dl(packet, teid)

    def inject_uplink(self, packet) -> None:
        """Decapsulated uplink SDU re-enters the PGW's IP stack."""
        self._deliver_up(packet, 0x0800, self._address, self._address, 0)


class EpcHelper:
    """point-to-point-epc-helper.cc analog with a real GTP-U data plane.

    ``s1u_rate``/``s1u_delay`` and ``s5_rate``/``s5_delay`` mirror the
    upstream S1uLinkDataRate/S1uLinkDelay attributes.
    """

    UE_NETWORK = "7.0.0.0"
    UE_MASK = "255.0.0.0"

    def __init__(self, s1u_rate: str = "1Gbps", s1u_delay: str = "0ms",
                 s5_rate: str = "10Gbps", s5_delay: str = "0ms"):
        self._s1u_rate = s1u_rate
        self._s1u_delay = s1u_delay

        self.pgw_node = Node()
        self.sgw_node = Node()
        InternetStackHelper().Install([self.pgw_node, self.sgw_node])

        # tunnel endpoint device claiming the UE network on the PGW
        self.pgw_device = PgwNetDevice()
        self.pgw_device.epc = self
        self.pgw_device.SetNode(self.pgw_node)
        self.pgw_node.AddDevice(self.pgw_device)
        ipv4 = self.pgw_node.GetObject(Ipv4L3Protocol)
        if_index = ipv4.AddInterface(self.pgw_device)
        ipv4.AddAddress(
            if_index,
            Ipv4InterfaceAddress(Ipv4Address("7.0.0.1"), Ipv4Mask(self.UE_MASK)),
        )
        routing = ipv4.GetRoutingProtocol()
        assert isinstance(routing, Ipv4StaticRouting)
        routing.AddNetworkRouteTo(
            Ipv4Address(self.UE_NETWORK), Ipv4Mask(self.UE_MASK), if_index
        )

        # S5 link PGW ↔ SGW
        from tpudes.helper.point_to_point import PointToPointHelper

        p2p = PointToPointHelper()
        p2p.SetDeviceAttribute("DataRate", s5_rate)
        p2p.SetChannelAttribute("Delay", s5_delay)
        s5 = p2p.Install(self.pgw_node, self.sgw_node)
        s5_ifc = Ipv4AddressHelper("13.0.0.0", "255.255.255.252").Assign(s5)
        self._pgw_s5_addr = s5_ifc.GetAddress(0)
        self._sgw_s5_addr = s5_ifc.GetAddress(1)

        # GTP-U sockets (epc-{sgw,pgw}-application.cc)
        self._pgw_sock = self._gtpu_socket(self.pgw_node, self._on_pgw_rx)
        self._sgw_sock = self._gtpu_socket(self.sgw_node, self._on_sgw_rx)

        # S1-U bookkeeping
        self._s1u_addr_helper = Ipv4AddressHelper("10.0.0.0", "255.255.255.252")
        self._enb_socks: dict[int, object] = {}       # id(enb_dev) -> socket
        self._enb_s1u_addr: dict[int, Ipv4Address] = {}   # eNB side
        self._sgw_s1u_addr: dict[int, Ipv4Address] = {}   # SGW side, per eNB
        self.s1u_enb_devices: list = []
        self.s1u_sgw_devices: list = []

        # bearer state
        self._teid_by_ueip: dict[int, int] = {}
        self._ue_by_teid: dict[int, object] = {}
        self._next_teid = 1
        self._next_ue_host = 2

    # --- plumbing -----------------------------------------------------------
    @staticmethod
    def _gtpu_socket(node, rx_cb):
        sock = node.GetObject(UdpL4Protocol).CreateSocket()
        if sock.Bind(InetSocketAddress(Ipv4Address.GetAny(), GTPU_PORT)) != 0:
            raise RuntimeError("GTP-U port 2152 already bound on this node")

        def drain(s):
            while True:
                pkt, src = s.RecvFrom()
                if pkt is None:
                    break
                rx_cb(pkt, src)

        sock.SetRecvCallback(drain)
        return sock

    def _ensure_enb(self, enb_device) -> None:
        """Build the eNB's S1-U link + GTP-U endpoint once
        (epc-enb-application.cc + the helper's AddEnb)."""
        key = id(enb_device)
        if key in self._enb_socks:
            return
        enb_node = enb_device.GetNode()
        if enb_node.GetObject(Ipv4L3Protocol) is None:
            InternetStackHelper().Install(enb_node)
        from tpudes.helper.point_to_point import PointToPointHelper

        p2p = PointToPointHelper()
        p2p.SetDeviceAttribute("DataRate", self._s1u_rate)
        p2p.SetChannelAttribute("Delay", self._s1u_delay)
        link = p2p.Install(enb_node, self.sgw_node)
        ifc = self._s1u_addr_helper.Assign(link)
        self._s1u_addr_helper.NewNetwork()
        self._enb_s1u_addr[key] = ifc.GetAddress(0)
        self._sgw_s1u_addr[key] = ifc.GetAddress(1)
        self.s1u_enb_devices.append(link.Get(0))
        self.s1u_sgw_devices.append(link.Get(1))

        sgw_addr = self._sgw_s1u_addr[key]

        def on_enb_rx(pkt, src, _dev=enb_device):
            gtpu = pkt.RemoveHeader(GtpuHeader)
            ue = self._ue_by_teid.get(gtpu.teid)
            if ue is not None:
                _dev.dl_enqueue(ue, pkt)

        sock = self._gtpu_socket(enb_node, on_enb_rx)
        self._enb_socks[key] = sock

        def on_ul_sdu(packet, _sock=sock, _sgw=sgw_addr):
            header = packet.PeekHeader(Ipv4Header)
            if header is None:
                return
            teid = self._teid_by_ueip.get(header.GetSource().addr)
            if teid is None:
                return
            packet.AddHeader(GtpuHeader(teid, packet.GetSize()))
            _sock.SendTo(packet, 0, InetSocketAddress(_sgw, GTPU_PORT))

        enb_device.ul_sdu_callback = on_ul_sdu

    # --- SGW data plane (epc-sgw-application.cc) ----------------------------
    def _on_sgw_rx(self, pkt, src) -> None:
        gtpu = pkt.PeekHeader(GtpuHeader)  # relay keeps the frame intact
        if src.GetIpv4() == self._pgw_s5_addr:
            # downlink: resolve the TEID's CURRENT serving eNB (the
            # ideal S11/X2 path switch — upstream signals this; we read
            # the live RRC state)
            ue = self._ue_by_teid.get(gtpu.teid)
            enb = ue.rrc.serving_enb if ue is not None else None
            dst = self._enb_s1u_addr.get(id(enb))
            if dst is None:
                return  # serving eNB not wired: drop (loud in tests)
            self._sgw_sock.SendTo(pkt, 0, InetSocketAddress(dst, GTPU_PORT))
        else:
            # uplink: forward over S5 to the PGW
            self._sgw_sock.SendTo(
                pkt, 0, InetSocketAddress(self._pgw_s5_addr, GTPU_PORT)
            )

    # --- PGW data plane (epc-pgw-application.cc) ----------------------------
    def _on_pgw_rx(self, pkt, src) -> None:
        pkt.RemoveHeader(GtpuHeader)
        self.pgw_device.inject_uplink(pkt)

    def _pgw_send_dl(self, packet, teid: int) -> bool:
        packet.AddHeader(GtpuHeader(teid, packet.GetSize()))
        self._pgw_sock.SendTo(
            packet, 0, InetSocketAddress(self._sgw_s5_addr, GTPU_PORT)
        )
        return True

    # --- public API ---------------------------------------------------------
    def GetPgwNode(self) -> Node:
        return self.pgw_node

    def GetSgwNode(self) -> Node:
        return self.sgw_node

    def GetUeDefaultGatewayAddress(self) -> Ipv4Address:
        return Ipv4Address("7.0.0.1")

    def teid_for_ue(self, ue_addr: Ipv4Address) -> int | None:
        return self._teid_by_ueip.get(Ipv4Address(ue_addr).addr)

    def AssignUeIpv4Address(self, ue_devices) -> list[Ipv4Address]:
        """Give each UE a 7.0.0.0/8 address + default route, allocate
        its TEID, and wire its serving eNB's S1-U leg."""
        addrs = []
        for ue in ue_devices:
            node = ue.GetNode()
            ipv4 = node.GetObject(Ipv4L3Protocol)
            if ipv4 is None:
                raise RuntimeError(
                    "install the internet stack on UE nodes before "
                    "AssignUeIpv4Address"
                )
            n = self._next_ue_host - 2
            self._next_ue_host += 1
            # 253 hosts per /24, spilling across the /8 (avoids .0/.1/.255)
            addr = Ipv4Address(f"7.0.{n // 253}.{2 + n % 253}")
            if_index = ipv4.GetInterfaceForDevice(ue)
            if if_index < 0:
                if_index = ipv4.AddInterface(ue)
            ipv4.AddAddress(
                if_index, Ipv4InterfaceAddress(addr, Ipv4Mask(self.UE_MASK))
            )
            routing = ipv4.GetRoutingProtocol()
            if isinstance(routing, Ipv4StaticRouting):
                routing.SetDefaultRoute(
                    self.GetUeDefaultGatewayAddress(), if_index
                )
            ue.ue_ipv4 = addr
            teid = self._next_teid
            self._next_teid += 1
            self._teid_by_ueip[addr.addr] = teid
            self._ue_by_teid[teid] = ue
            enb = ue.rrc.serving_enb
            if enb is not None:
                self._ensure_enb(enb)
            addrs.append(addr)
        return addrs

    def wire_enbs(self, enb_devices) -> None:
        """Build every eNB's S1-U leg (the helper's AddEnb loop) —
        required before handover so the target cell has a tunnel."""
        for enb in enb_devices:
            self._ensure_enb(enb)
