"""Frequency-reuse (FFR) algorithms: per-cell RBG restriction.

Reference parity: src/lte/model/lte-ffr-algorithm.{h,cc},
lte-fr-no-op-algorithm.{h,cc}, lte-fr-hard-algorithm.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0, §2.6
"Handover & FFR algorithms" row).

The seam matches upstream's: the FFR algorithm answers "which RBGs may
this cell schedule" and the FF-MAC scheduler allocates inside that
mask.  Hard reuse-3 trades peak rate (1/3 of the band per cell) for
edge SINR (no first-tier co-channel interference); the no-op passes
the full band through.  Soft/enhanced variants (per-UE edge/center
power masks) keep their upstream names reserved but are not modeled.
"""

from __future__ import annotations

from tpudes.core.object import Object, TypeId


class LteFfrAlgorithm(Object):
    tid = TypeId("tpudes::LteFfrAlgorithm")

    def allowed_rbgs(self, cell_index: int, n_rbg: int) -> list[int]:
        raise NotImplementedError


class LteFrNoOpAlgorithm(LteFfrAlgorithm):
    """lte-fr-no-op-algorithm.cc: the full band, every cell."""

    tid = (
        TypeId("tpudes::LteFrNoOpAlgorithm")
        .SetParent(LteFfrAlgorithm.tid)
        .AddConstructor(lambda **kw: LteFrNoOpAlgorithm(**kw))
    )

    def allowed_rbgs(self, cell_index: int, n_rbg: int) -> list[int]:
        return list(range(n_rbg))


class LteFrHardAlgorithm(LteFfrAlgorithm):
    """lte-fr-hard-algorithm.cc: disjoint 1/N subbands by cell index."""

    tid = (
        TypeId("tpudes::LteFrHardAlgorithm")
        .SetParent(LteFfrAlgorithm.tid)
        .AddConstructor(lambda **kw: LteFrHardAlgorithm(**kw))
        .AddAttribute("ReuseFactor", "number of disjoint subbands", 3,
                      field="reuse_factor")
    )

    def allowed_rbgs(self, cell_index: int, n_rbg: int) -> list[int]:
        k = int(self.reuse_factor)
        if k < 1:
            raise ValueError(f"ReuseFactor must be >= 1 (got {k})")
        band = cell_index % k
        lo = (n_rbg * band) // k
        hi = (n_rbg * (band + 1)) // k
        if lo >= hi:
            # a starved cell is a configuration error, not a quiet one
            raise RuntimeError(
                f"ReuseFactor={k} leaves cell index {cell_index} an empty "
                f"subband ({n_rbg} RBGs available)"
            )
        return list(range(lo, hi))


FFR_ALGORITHMS = {
    "tpudes::LteFrNoOpAlgorithm": LteFrNoOpAlgorithm,
    "tpudes::LteFrHardAlgorithm": LteFrHardAlgorithm,
    "ns3::LteFrNoOpAlgorithm": LteFrNoOpAlgorithm,
    "ns3::LteFrHardAlgorithm": LteFrHardAlgorithm,
}
