"""Shared scene effects: buildings + antenna gain over batched geometry.

One implementation consumed by both the TTI controller's link budget
and the REM grid (r4 review: two hand-synced copies had already
diverged on the inclination sign).
"""

from __future__ import annotations

import sys

import numpy as np


def batch_angles(pos_tx: np.ndarray, pos_rx: np.ndarray):
    """(az, incl) of every rx seen from every tx — the batch companion
    of Angles.FromPositions (same convention: inclination measured from
    +z, so a below-horizon receiver is > π/2)."""
    dx = pos_rx[None, :, 0] - pos_tx[:, None, 0]
    dy = pos_rx[None, :, 1] - pos_tx[:, None, 1]
    dz = pos_rx[None, :, 2] - pos_tx[:, None, 2]
    az = np.arctan2(dy, dx)
    incl = np.arctan2(np.hypot(dx, dy), dz)
    return az, incl


def scene_loss_db(enbs, pos_e: np.ndarray, pos_rx: np.ndarray) -> np.ndarray:
    """(E, R) additional loss: building wall penetration on each
    straight segment plus each eNB's (negative) antenna gain."""
    loss = np.zeros((len(pos_e), len(pos_rx)))
    bmod = sys.modules.get("tpudes.models.buildings")
    if bmod is not None and bmod.BuildingList.GetNBuildings():
        loss = loss + bmod.batch_wall_crossings(pos_e, pos_rx)
    if any(e.phy.antenna is not None for e in enbs):
        az, incl = batch_angles(pos_e, pos_rx)
        for i, e in enumerate(enbs):
            if e.phy.antenna is not None:
                loss[i] -= e.phy.antenna.batch_gain_db(az[i], incl[i])
    return loss
