"""LTE PHY objects: LteSpectrumPhy, LteEnbPhy, LteUePhy.

Reference parity: src/lte/model/lte-spectrum-phy.{h,cc},
lte-enb-phy.{h,cc}, lte-ue-phy.{h,cc}, lte-interference.{h,cc}
(upstream paths; mount empty at survey — SURVEY.md §0, §2.6, §3.4).

TPU-first split: these objects carry per-device PHY *configuration*
(power, noise figure, bandwidth, spectrum model) and the scalar
SpectrumPhy interface; the per-TTI hot math — every cell's PSD × gain →
per-RB SINR → MI → BLER → TB decode for ALL UEs at once — runs in
:mod:`tpudes.models.lte.controller` as one jitted kernel call
(ops/lte.py::tti_phy_step).  That controller is the batched equivalent
of MultiModelSpectrumChannel::StartTx + LteSpectrumPhy::StartRxData +
LteInterference chunk processing per subframe, exploiting that LTE
subframes are synchronous across the network (the same observation
upstream's 1 ms TTI clocking encodes).
"""

from __future__ import annotations

import numpy as np

from tpudes.core.object import Object, TypeId
from tpudes.models.spectrum import (
    SpectrumPhy,
    SpectrumSignalParameters,
    SpectrumValue,
    lte_spectrum_model,
)
from tpudes.ops.lte import RB_BANDWIDTH_HZ, noise_psd_w


class LteSpectrumPhy(SpectrumPhy):
    """Per-device spectrum endpoint (lte-spectrum-phy.cc): builds tx
    PSDs over the RB grid and accepts rx PSDs.  The batched controller
    reads its configuration; the SpectrumPhy interface keeps the scalar
    channel path available for spectrum-layer tests."""

    tid = TypeId("tpudes::LteSpectrumPhy").SetParent(SpectrumPhy.tid)

    def __init__(self, n_rb: int, carrier_hz: float, **attributes):
        super().__init__(**attributes)
        self.n_rb = n_rb
        self.carrier_hz = carrier_hz
        self.spectrum_model = lte_spectrum_model(n_rb, carrier_hz)
        self.rx_psd_callback = None

    def GetRxSpectrumModel(self):
        return self.spectrum_model

    def CreateTxPowerSpectralDensity(
        self, tx_power_dbm: float, used_rbs
    ) -> SpectrumValue:
        """PSD with total power spread uniformly over the full grant
        bandwidth, emitted only on the used RBs
        (lte-spectrum-value-helper.cc semantics)."""
        power_w = 10.0 ** ((tx_power_dbm - 30.0) / 10.0)
        psd_per_hz = power_w / (self.n_rb * RB_BANDWIDTH_HZ)
        values = np.zeros(self.n_rb)
        values[np.asarray(list(used_rbs), dtype=np.int64)] = psd_per_hz
        return SpectrumValue(self.spectrum_model, values)

    def StartRx(self, params: SpectrumSignalParameters) -> None:
        if self.rx_psd_callback is not None:
            self.rx_psd_callback(params)


class LteEnbPhy(Object):
    """eNB PHY configuration (lte-enb-phy.cc defaults: TxPower 30 dBm,
    NoiseFigure 5 dB)."""

    tid = (
        TypeId("tpudes::LteEnbPhy")
        .AddConstructor(lambda **kw: LteEnbPhy(**kw))
        .AddAttribute("TxPower", "dBm", 30.0, field="tx_power_dbm")
        .AddAttribute("NoiseFigure", "dB", 5.0, field="noise_figure_db")
    )

    def __init__(self, n_rb: int = 25, carrier_hz: float = 2.12e9, **attributes):
        super().__init__(**attributes)
        self.n_rb = n_rb
        self.carrier_hz = carrier_hz
        self.spectrum_phy = LteSpectrumPhy(n_rb, carrier_hz)
        #: optional AntennaModel (tpudes.models.antenna); when set, the
        #: controller adds its directional gain into the link budget
        self.antenna = None

    @property
    def noise_psd(self) -> float:
        return noise_psd_w(self.noise_figure_db)


class LteUePhy(Object):
    """UE PHY configuration (lte-ue-phy.cc defaults: TxPower 10 dBm,
    NoiseFigure 9 dB)."""

    tid = (
        TypeId("tpudes::LteUePhy")
        .AddConstructor(lambda **kw: LteUePhy(**kw))
        .AddAttribute("TxPower", "dBm", 10.0, field="tx_power_dbm")
        .AddAttribute("NoiseFigure", "dB", 9.0, field="noise_figure_db")
    )

    def __init__(self, n_rb: int = 25, carrier_hz: float = 1.93e9, **attributes):
        super().__init__(**attributes)
        self.n_rb = n_rb
        self.carrier_hz = carrier_hz
        self.spectrum_phy = LteSpectrumPhy(n_rb, carrier_hz)
        self.wideband_cqi = 0         # latest reported (after feedback delay)
        self.last_dl_sinr_db = float("nan")

    @property
    def noise_psd(self) -> float:
        return noise_psd_w(self.noise_figure_db)
