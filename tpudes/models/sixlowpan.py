"""6LoWPAN: IPv6 over 802.15.4 (RFC 6282 IPHC + RFC 4944 fragments).

Reference parity: src/sixlowpan/model/sixlowpan-net-device.{h,cc},
sixlowpan-header.{h,cc} + helper (upstream paths; mount empty at survey
— SURVEY.md §0, §2.9 "other link modules" row).

SixLowPanNetDevice wraps a link device (LrWpanNetDevice in practice)
and adapts IPv6 to its 110-byte MTU:

- IPHC header compression: when both interface identifiers are
  EUI-64-derivable from the frame's MACs and the traffic class/flow
  label are zero, the 40-byte IPv6 header shrinks to the 7-byte
  compressed form (dispatch+IPHC(2) + hop limit(1) + context/prefix
  nibble handling folded to 4).  Non-compressible headers ride the
  uncompressed IPV6 dispatch (41 bytes).  In-sim the compressed header
  CARRIES the original Ipv6Header object (structured packets cannot be
  bit-sliced — the wire SIZE is what compression changes, and size is
  what drives airtime on the 250 kb/s link).
- FRAG1/FRAGN fragmentation for adapted frames beyond the link MTU,
  with per-(src, tag) reassembly at the receiver and any-loss-kills-
  the-datagram semantics.
"""

from __future__ import annotations

import struct

from tpudes.core.nstime import Seconds
from tpudes.core.object import TypeId
from tpudes.core.simulator import Simulator
from tpudes.network.address import Ipv6Address
from tpudes.network.net_device import NetDevice
from tpudes.network.packet import Header, Packet

#: 6LoWPAN ethertype on the wrapped link (upstream uses raw dispatch
#: bytes; the wrapped device here multiplexes by protocol number)
SIXLOWPAN_PROT = 0xA0ED

IPHC_COMPRESSED_BYTES = 7
IPV6_DISPATCH_BYTES = 41   # 1-byte dispatch + uncompressed header


class SixLowPanIphc(Header):
    """Compressed (or escaped-uncompressed) IPv6 header; carries the
    original header object for reconstruction."""

    def __init__(self, ipv6_header=None, compressed=True):
        self.ipv6_header = ipv6_header
        self.compressed = compressed

    def GetSerializedSize(self) -> int:
        return IPHC_COMPRESSED_BYTES if self.compressed else IPV6_DISPATCH_BYTES

    def Serialize(self) -> bytes:
        if self.compressed:
            h = self.ipv6_header
            return struct.pack(
                "!BBBBBBB", 0x78, 0x33, h.next_header, h.hop_limit & 0xFF,
                (h.payload_size >> 8) & 0xFF, h.payload_size & 0xFF, 0,
            )
        return b"\x41" + self.ipv6_header.Serialize()

    @classmethod
    def Deserialize(cls, data: bytes):
        # in-sim the object rides the header instance; wire decode is
        # exercised for the uncompressed escape only
        if data[:1] == b"\x41":
            from tpudes.models.internet.ipv6 import Ipv6Header

            h, n = Ipv6Header.Deserialize(data[1:])
            return cls(h, compressed=False), 1 + n
        return cls(None, compressed=True), IPHC_COMPRESSED_BYTES


class SixLowPanFrag(Header):
    """FRAG1/FRAGN (RFC 4944 §5.3): datagram size + tag (+offset)."""

    def __init__(self, size=0, tag=0, offset=0, first=True):
        self.size = size
        self.tag = tag
        self.offset = offset   # bytes (8-byte units on the wire)
        self.first = first

    def GetSerializedSize(self) -> int:
        return 4 if self.first else 5

    def Serialize(self) -> bytes:
        disp = (0x18 if self.first else 0x1C) << 3
        head = struct.pack("!HH", (disp << 8) | (self.size & 0x7FF), self.tag)
        if self.first:
            return head
        return head + struct.pack("!B", self.offset >> 3)

    @classmethod
    def Deserialize(cls, data: bytes):
        word, tag = struct.unpack("!HH", data[:4])
        first = not bool(word & 0x2000)
        size = word & 0x7FF
        if first:
            return cls(size, tag, 0, True), 4
        return cls(size, tag, data[4] << 3, False), 5


class SixLowPanNetDevice(NetDevice):
    """The adaptation device: Sends IPv6, speaks compressed frames to
    the wrapped link device underneath."""

    tid = (
        TypeId("tpudes::SixLowPanNetDevice")
        .SetParent(NetDevice.tid)
        .AddConstructor(lambda **kw: SixLowPanNetDevice(**kw))
        .AddTraceSource("Tx", "(packet) adapted and sent")
        .AddTraceSource("Rx", "(packet) reassembled and delivered")
        .AddTraceSource("Drop", "(reason) adaptation drop")
    )

    #: reassembly buffer lifetime (upstream FragmentExpirationTimeout;
    #: mirrors Ipv4L3Protocol.FRAGMENT_EXPIRATION_S — a lost fragment
    #: must not strand the buffer until the 16-bit tag wraps)
    REASSEMBLY_EXPIRATION_S = 60.0

    def __init__(self, inner=None, **attributes):
        super().__init__(**attributes)
        self._inner = inner
        self._tag = 0
        #: (src-mac str, tag) -> {"ranges", "total", "packet"}
        self._frags: dict = {}

    def SetInnerDevice(self, inner) -> None:
        self._inner = inner

    def GetInnerDevice(self):
        return self._inner

    def SetNode(self, node) -> None:
        super().SetNode(node)
        # receive the inner device's 6LoWPAN frames
        node.RegisterProtocolHandler(
            self._receive_from_inner, SIXLOWPAN_PROT, self._inner
        )

    # the wrapper presents the inner link's identity
    def GetAddress(self):
        return self._inner.GetAddress()

    def IsBroadcast(self) -> bool:
        return True

    def GetBroadcast(self):
        return self._inner.GetBroadcast()

    def NeedsArp(self) -> bool:
        return True  # ICMPv6 ND runs over the adaptation layer

    def GetMtu(self) -> int:
        return 1280  # IPv6 minimum MTU: the adaptation layer fragments

    # --- tx ---
    def _compressible(self, h) -> bool:
        if h is None or h.traffic_class != 0:
            return False
        # both IIDs derivable from the on-link MACs (we cannot see the
        # peer's MAC for routed prefixes generally; link-local and
        # EUI-64 global addresses qualify)
        def iid_ok(addr: Ipv6Address) -> bool:
            low = addr.addr & ((1 << 64) - 1)
            return (low >> 24) & 0xFFFF == 0xFFFE or addr.IsMulticast()

        return iid_ok(h.source) and iid_ok(h.destination)

    def Send(self, packet, dest=None, protocol: int = 0x86DD) -> bool:
        from tpudes.models.internet.ipv6 import Ipv6Header

        packet = packet.Copy()
        h = packet.PeekHeader(Ipv6Header)
        if h is not None:
            packet.RemoveHeader(Ipv6Header)
            packet.AddHeader(SixLowPanIphc(h, compressed=self._compressible(h)))
        self.tx(packet)
        mtu = self._inner.GetMtu()
        if packet.GetSize() <= mtu:
            return self._inner.Send(packet, dest, SIXLOWPAN_PROT)
        # RFC 4944 fragmentation of the ADAPTED datagram
        total = packet.GetSize()
        self._tag = (self._tag + 1) & 0xFFFF
        offset = 0
        first = True
        while offset < total:
            fh = SixLowPanFrag(total, self._tag, offset, first)
            chunk = min((mtu - fh.GetSerializedSize()) & ~7, total - offset)
            frag = Packet(chunk)
            if first:
                frag.AddPacketTag(_SixLowPanOriginal(packet.Copy(), total))
            fh.offset = offset
            frag.AddHeader(fh)
            if not self._inner.Send(frag, dest, SIXLOWPAN_PROT):
                self.drop("inner-tx")
                return False
            offset += chunk
            first = False
        return True

    # --- rx ---
    def _receive_from_inner(self, device, packet, protocol, sender):
        packet = packet.Copy()
        front = packet.PeekHeader(SixLowPanFrag)
        if front is not None:
            packet.RemoveHeader(SixLowPanFrag)
            done = self._reassemble(front, packet, sender)
            if done is None:
                return
            packet = done
        self._deliver(packet, sender)

    def _reassemble(self, fh: SixLowPanFrag, packet, sender):
        key = (str(sender), fh.tag)
        buf = self._frags.get(key)
        if buf is None:
            buf = {"ranges": [], "total": fh.size, "packet": None}
            buf["timer"] = Simulator.Schedule(
                Seconds(self.REASSEMBLY_EXPIRATION_S),
                self._expire_reassembly, key,
            )
            self._frags[key] = buf
        tag = packet.PeekPacketTag(_SixLowPanOriginal)
        if tag is not None:
            buf["packet"] = tag.packet
        length = packet.GetSize()
        buf["ranges"].append((fh.offset, fh.offset + length))
        covered = 0
        for s, e in sorted(buf["ranges"]):
            if s > covered:
                return None
            covered = max(covered, e)
        if covered < buf["total"] or buf["packet"] is None:
            return None
        buf["timer"].Cancel()
        del self._frags[key]
        return buf["packet"]

    def _expire_reassembly(self, key):
        if self._frags.pop(key, None) is not None:
            self.drop("reassembly-timeout")

    def _deliver(self, packet, sender):
        from tpudes.models.internet.ipv6 import Ipv6Header

        iphc = packet.PeekHeader(SixLowPanIphc)
        if iphc is not None:
            packet.RemoveHeader(SixLowPanIphc)
            if iphc.ipv6_header is not None:
                packet.AddHeader(iphc.ipv6_header)
        self.rx(packet)
        self._deliver_up(packet, 0x86DD, sender, self.GetAddress(), 0)


class _SixLowPanOriginal:
    __slots__ = ("packet", "total")

    def __init__(self, packet, total):
        self.packet = packet
        self.total = total


class SixLowPanHelper:
    """sixlowpan-helper.cc: wrap each device, add the wrapper to the
    node; assign IPv6 addresses to the WRAPPER devices."""

    def Install(self, devices):
        from tpudes.helper.containers import NetDeviceContainer

        out = NetDeviceContainer()
        for inner in devices:
            node = inner.GetNode()
            wrap = SixLowPanNetDevice(inner=inner)
            node.AddDevice(wrap)
            out.Add(wrap)
        return out
