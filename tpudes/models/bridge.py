"""BridgeNetDevice: a learning L2 switch over member devices.

Reference parity: src/bridge/model/bridge-net-device.{h,cc},
bridge-channel.{h,cc} + helper/bridge-helper.{h,cc} (upstream paths;
mount empty at survey — SURVEY.md §0, §2.9 bridge row).

The bridge aggregates member NetDevices (CSMA ports, typically): frames
received promiscuously on one port are forwarded out the others —
flooded while the destination is unknown, unicast once the source-MAC
learning table has seen the station, with per-entry expiration.  The
bridge device itself can carry the node's IP stack (the switch's
management interface), exactly as upstream.
"""

from __future__ import annotations

from tpudes.core.nstime import Seconds, Time
from tpudes.core.object import TypeId
from tpudes.core.simulator import Simulator
from tpudes.network.net_device import NetDevice


class BridgeNetDevice(NetDevice):
    tid = (
        TypeId("tpudes::BridgeNetDevice")
        .SetParent(NetDevice.tid)
        .AddConstructor(lambda **kw: BridgeNetDevice(**kw))
        .AddAttribute(
            "ExpirationTime", "learning-table entry lifetime",
            Seconds(300.0), checker=Time, field="expiration_time",
        )
        .AddTraceSource("MacTx", "frame sent through the bridge")
        .AddTraceSource("MacRx", "frame delivered to the bridge itself")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        from tpudes.core.event import EventId

        self._ports: list[NetDevice] = []
        #: learned station location: mac addr -> (port, expire_ticks)
        self._learn: dict[int, tuple] = {}
        #: periodic aging sweep over the learning table (armed lazily)
        self._age_event = EventId()

    # --- wiring -----------------------------------------------------------
    def AddBridgePort(self, device: NetDevice) -> None:
        if device is self:
            raise ValueError("a bridge cannot bridge itself")
        if type(device).SendFrom is NetDevice.SendFrom:
            # the base fallback discards the source MAC — forwarding
            # through such a port would silently re-stamp every frame
            # (upstream aborts unless SupportsSendFrom, same contract)
            raise ValueError(
                f"{type(device).__name__} does not support SendFrom; "
                "bridge ports must preserve the source MAC"
            )
        self._ports.append(device)
        device.SetPromiscReceiveCallback(self._receive_from_port)
        # a port belongs to the bridge: its frames must NOT also climb
        # into the node's stack directly (the bridge's own _deliver_up
        # is the management plane)
        device.SetReceiveCallback(lambda *a: True)

    def GetNBridgePorts(self) -> int:
        return len(self._ports)

    def GetBridgePort(self, i: int) -> NetDevice:
        return self._ports[i]

    def IsBridge(self) -> bool:
        return True

    def IsBroadcast(self) -> bool:
        return True

    def NeedsArp(self) -> bool:
        return True

    # --- learning ---------------------------------------------------------
    def _learn_station(self, src, port) -> None:
        self._learn[src.addr] = (
            port, Simulator.NowTicks() + self.expiration_time.ticks
        )
        # aging sweep: _lookup expires lazily, but a station the bridge
        # never hears about again would strand its entry forever — the
        # sweep (upstream's ExpirationTime contract) bounds the table
        if not self._age_event.IsPending():
            self._age_event = Simulator.Schedule(
                self.expiration_time, self._age_learned
            )

    def _age_learned(self) -> None:
        now = Simulator.NowTicks()
        for addr in [
            a for a, (_p, exp) in self._learn.items() if now >= exp
        ]:
            del self._learn[addr]
        if self._learn:
            self._age_event = Simulator.Schedule(
                self.expiration_time, self._age_learned
            )

    def _lookup(self, dst):
        hit = self._learn.get(dst.addr)
        if hit is None:
            return None
        port, expires = hit
        if Simulator.NowTicks() >= expires:
            del self._learn[dst.addr]
            return None
        return port

    # --- forwarding -------------------------------------------------------
    def _receive_from_port(self, in_device, packet, protocol, src, dst,
                           packet_type) -> bool:
        self._learn_station(src, in_device)
        node = self._node
        if packet_type == node.PACKET_HOST or dst == self._address:
            # addressed to the bridge itself (management plane)
            self.mac_rx(packet)
            self._deliver_up(packet.Copy(), protocol, src, dst, node.PACKET_HOST)
            return True
        if packet_type == node.PACKET_BROADCAST or packet_type == node.PACKET_MULTICAST:
            # flood FIRST, and hand the stack a COPY: the node's ARP/IP
            # handlers strip headers in place, and a stripped broadcast
            # must never be what the other segment receives
            self._flood(in_device, packet, src, dst, protocol)
            self._deliver_up(packet.Copy(), protocol, src, dst, packet_type)
            return True
        # other-host unicast: forward learned, else flood
        out = self._lookup(dst)
        if out is not None and out is not in_device:
            out.SendFrom(packet.Copy(), src, dst, protocol)
        elif out is None:
            self._flood(in_device, packet, src, dst, protocol)
        return True

    def _flood(self, in_device, packet, src, dst, protocol) -> None:
        for port in self._ports:
            if port is not in_device:
                port.SendFrom(packet.Copy(), src, dst, protocol)

    # --- the bridge as an interface itself ---------------------------------
    def Send(self, packet, dest=None, protocol: int = 0x0800) -> bool:
        return self.SendFrom(packet, self._address, dest, protocol)

    def SendFrom(self, packet, source, dest, protocol: int) -> bool:
        self.mac_tx(packet)
        out = self._lookup(dest) if dest is not None else None
        if out is not None:
            return out.SendFrom(packet.Copy(), source, dest, protocol)
        self._flood(None, packet, source, dest, protocol)
        return True


class BridgeHelper:
    """helper/bridge-helper.{h,cc}: Install(node, ports)."""

    def __init__(self):
        self._attrs: dict = {}

    def SetDeviceAttribute(self, name: str, value) -> None:
        self._attrs[name] = value

    def Install(self, node, port_devices) -> BridgeNetDevice:
        from tpudes.helper.containers import NetDeviceContainer

        if isinstance(port_devices, NetDeviceContainer):
            port_devices = list(port_devices)
        bridge = BridgeNetDevice(**self._attrs)
        node.AddDevice(bridge)
        for dev in port_devices:
            bridge.AddBridgePort(dev)
        return bridge
