"""LR-WPAN: IEEE 802.15.4 low-rate wireless PAN (channel, PHY+MAC
device, helper).

Reference parity: src/lr-wpan/model/lr-wpan-{phy,mac,net-device,
csmaca,error-model}.{h,cc} + helper (upstream paths; mount empty at
survey — SURVEY.md §0, §2.9 "other link modules" row).

Modeled: the 2.4 GHz O-QPSK PHY at 250 kb/s with a propagation-loss
channel and rx sensitivity; unslotted CSMA/CA (random backoff in unit
periods of 20 symbols, CCA, BE growth, NB limit); acked unicast data
with macMaxFrameRetries; collision corruption when receptions overlap
at a receiver (the SINR error model reduced to capture-less collision
— documented simplification, as is using 48-bit addresses where
upstream has short/extended 802.15.4 addresses).  Beacon-enabled
(slotted) mode, PAN association and GTS are out of scope.
"""

from __future__ import annotations

import struct

from tpudes.core.nstime import MicroSeconds, Seconds
from tpudes.core.object import TypeId
from tpudes.core.rng import UniformRandomVariable
from tpudes.core.simulator import Simulator
from tpudes.network.address import Mac48Address
from tpudes.network.net_device import Channel, NetDevice
from tpudes.network.packet import Header, Packet
from tpudes.network.queue import DropTailQueue

#: 802.15.4 2.4 GHz O-QPSK
BIT_RATE = 250_000           # b/s
SYMBOL_RATE = 62_500         # 4 bits/symbol
UNIT_BACKOFF_US = 320        # aUnitBackoffPeriod = 20 symbols
ACK_WAIT_US = 864            # macAckWaitDuration (54 symbols)
MAC_MIN_BE = 3
MAC_MAX_BE = 5
MAC_MAX_CSMA_BACKOFFS = 4
MAC_MAX_FRAME_RETRIES = 3
ACK_SIZE = 5                 # imm-ack frame bytes
PHY_OVERHEAD = 6             # preamble(4) + SFD(1) + length(1)
A_MAX_PHY_PACKET_SIZE = 127


class LrWpanMacHeader(Header):
    DATA = 1
    ACK = 2

    def __init__(self, frame_type=1, seq=0, dst=None, src=None,
                 protocol=0x86DD):
        self.frame_type = frame_type
        self.seq = seq
        self.dst = dst or Mac48Address.GetBroadcast()
        self.src = src or Mac48Address()
        #: in-sim demux field: 802.15.4 has no ethertype — upstream
        #: distinguishes payloads by 6LoWPAN dispatch bytes; the
        #: structured equivalent rides the header object (not the wire)
        self.protocol = protocol

    def GetSerializedSize(self) -> int:
        # fc(2) + seq(1) + addressing (ack carries none)
        return 3 if self.frame_type == self.ACK else 3 + 12

    def Serialize(self) -> bytes:
        head = struct.pack("!BH", self.frame_type, self.seq & 0xFF)
        if self.frame_type == self.ACK:
            return head
        return head + self.dst.to_bytes() + self.src.to_bytes()

    @classmethod
    def Deserialize(cls, data: bytes):
        t, seq = struct.unpack("!BH", data[:3])
        if t == cls.ACK:
            return cls(t, seq), 3
        return cls(
            t, seq,
            Mac48Address.from_bytes(data[3:9]),
            Mac48Address.from_bytes(data[9:15]),
        ), 15


class LrWpanChannel(Channel):
    """Wireless broadcast medium: every transmission reaches every
    attached device at its rx power (single-model loss chain, like
    YansWifiChannel's)."""

    tid = (
        TypeId("tpudes::LrWpanChannel")
        .SetParent(Channel.tid)
        .AddConstructor(lambda **kw: LrWpanChannel(**kw))
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._loss = None
        self._delay = None

    def SetPropagationLossModel(self, loss) -> None:
        self._loss = loss

    def SetPropagationDelayModel(self, delay) -> None:
        self._delay = delay

    def Attach(self, device) -> None:
        self._devices.append(device)

    def Send(self, sender, packet, duration_s: float, tx_power_dbm: float):
        from tpudes.models.mobility import MobilityModel

        tx_mob = sender.GetNode().GetObject(MobilityModel)
        for dev in self._devices:
            if dev is sender:
                continue
            rx_mob = dev.GetNode().GetObject(MobilityModel)
            rx_dbm = tx_power_dbm
            delay_s = 0.0
            if self._loss is not None and tx_mob and rx_mob:
                rx_dbm = self._loss.CalcRxPower(tx_power_dbm, tx_mob, rx_mob)
                if self._delay is not None:
                    delay_s = self._delay.GetDelay(tx_mob, rx_mob)
            Simulator.ScheduleWithContext(
                dev.GetNode().GetId(), Seconds(delay_s),
                dev.phy_start_rx, packet.Copy(), rx_dbm, duration_s,
            )


class LrWpanNetDevice(NetDevice):
    """PHY + unslotted CSMA/CA MAC in one device (the lr-wpan module's
    phy/mac/csmaca trio folded; the split matters upstream for the
    MLME/MCPS SAP surface, which this build expresses as the plain
    NetDevice API)."""

    tid = (
        TypeId("tpudes::LrWpanNetDevice")
        .SetParent(NetDevice.tid)
        .AddConstructor(lambda **kw: LrWpanNetDevice(**kw))
        .AddAttribute("TxPower", "dBm", 0.0, field="tx_power_dbm")
        .AddAttribute("RxSensitivity", "dBm", -106.58, field="rx_sensitivity")
        .AddTraceSource("MacTx", "frame queued")
        .AddTraceSource("MacTxDrop", "frame dropped (csma/ca or retries)")
        .AddTraceSource("MacTxBackoff", "CCA busy; BE grows")
        .AddTraceSource("MacRx", "frame delivered up")
        .AddTraceSource("PhyTxBegin", "(packet)")
        .AddTraceSource("PhyRxDrop", "(packet, reason)")
    )

    def __init__(self, **attributes):
        super().__init__(**attributes)
        self._channel: LrWpanChannel | None = None
        self._queue = DropTailQueue()
        self._rng = UniformRandomVariable()
        self._seq = 0
        self._tx_busy = False
        self._current = None          # (packet_with_header, header)
        self._nb = 0
        self._be = MAC_MIN_BE
        self._retries = 0
        self._ack_timer = None
        # rx state: overlapping receptions corrupt each other.  Each
        # in-flight reception carries its own corrupted flag — a single
        # shared counter undercounts for >=3 overlapping frames and its
        # residue would drop the NEXT clean frame as a phantom collision
        self._rx_until = 0
        self._rx_inflight: list[dict] = []
        self._dup: dict[str, int] = {}  # src -> last seq delivered

    # --- wiring ---
    def Attach(self, channel: LrWpanChannel) -> None:
        self._channel = channel
        channel.Attach(self)

    def GetChannel(self):
        return self._channel

    def IsBroadcast(self) -> bool:
        return True

    def NeedsArp(self) -> bool:
        return True

    def GetMtu(self) -> int:
        # aMaxPhyPacketSize minus MAC header+FCS: the 6LoWPAN MTU
        return A_MAX_PHY_PACKET_SIZE - 15 - 2

    # --- tx path: unslotted CSMA/CA (lr-wpan-csmaca.cc) ---
    def Send(self, packet, dest=None, protocol: int = 0x86DD) -> bool:
        if not self._link_up:
            self.mac_tx_drop(packet)
            return False
        self.mac_tx(packet)
        self._seq = (self._seq + 1) & 0xFF
        header = LrWpanMacHeader(
            LrWpanMacHeader.DATA, self._seq,
            dst=dest if dest is not None else self.GetBroadcast(),
            src=self._address, protocol=protocol,
        )
        packet = packet.Copy()
        packet.AddHeader(header)
        if not self._queue.Enqueue(packet):
            self.mac_tx_drop(packet)
            return False
        if not self._tx_busy:
            self._next_frame()
        return True

    def _next_frame(self):
        packet = self._queue.Dequeue()
        if packet is None:
            self._tx_busy = False
            return
        self._tx_busy = True
        self._current = packet
        self._nb = 0
        self._be = MAC_MIN_BE
        self._retries = 0
        self._backoff()

    def _backoff(self):
        periods = int(self._rng.GetValue(0, (1 << self._be) - 1 + 1 - 1e-9))
        Simulator.Schedule(
            MicroSeconds(periods * UNIT_BACKOFF_US), self._cca
        )

    def _cca(self):
        now = Simulator.NowTicks()
        if now < self._rx_until:
            # channel busy: grow BE, bounded attempts
            self.mac_tx_backoff(self._current)
            self._nb += 1
            self._be = min(self._be + 1, MAC_MAX_BE)
            if self._nb > MAC_MAX_CSMA_BACKOFFS:
                self.mac_tx_drop(self._current)
                self._current = None
                self._next_frame()
                return
            self._backoff()
            return
        self._transmit()

    def _transmit(self):
        packet = self._current
        self.phy_tx_begin(packet)
        duration_s = (packet.GetSize() + PHY_OVERHEAD) * 8 / BIT_RATE
        self._channel.Send(self, packet, duration_s, self.tx_power_dbm)
        header = packet.PeekHeader(LrWpanMacHeader)
        unicast = header.dst != self.GetBroadcast()
        if unicast:
            self._ack_timer = Simulator.Schedule(
                MicroSeconds(int(duration_s * 1e6) + ACK_WAIT_US),
                self._on_ack_timeout,
            )
        else:
            Simulator.Schedule(Seconds(duration_s), self._tx_done)

    def _tx_done(self):
        self._current = None
        self._next_frame()

    def _on_ack_timeout(self):
        self._ack_timer = None
        self._retries += 1
        if self._retries > MAC_MAX_FRAME_RETRIES:
            self.mac_tx_drop(self._current)
            self._tx_done()
            return
        self._nb = 0
        self._be = MAC_MIN_BE
        self._backoff()

    # --- rx path ---
    def phy_start_rx(self, packet, rx_dbm: float, duration_s: float):
        now = Simulator.NowTicks()
        end = now + Seconds(duration_s).ticks
        if rx_dbm < self.rx_sensitivity:
            self.phy_rx_drop(packet, "below-sensitivity")
            return
        rx = {"corrupt": False}
        if now < self._rx_until:
            rx["corrupt"] = True         # corrupts BOTH frames
            for other in self._rx_inflight:
                other["corrupt"] = True
        self._rx_inflight.append(rx)
        self._rx_until = max(self._rx_until, end)
        Simulator.Schedule(
            Seconds(duration_s), self._phy_end_rx, packet, rx
        )

    def _phy_end_rx(self, packet, rx: dict):
        # remove by identity: equal-valued dicts of concurrent
        # receptions must not be evicted for each other
        self._rx_inflight = [o for o in self._rx_inflight if o is not rx]
        if rx["corrupt"]:
            self.phy_rx_drop(packet, "collision")
            return
        header = packet.RemoveHeader(LrWpanMacHeader)
        if header.frame_type == LrWpanMacHeader.ACK:
            if self._ack_timer is not None:
                self._ack_timer.Cancel()
                self._ack_timer = None
                self._tx_done()
            return
        broadcast = header.dst == self.GetBroadcast()
        if not broadcast and header.dst != self._address:
            return
        if not broadcast:
            # imm-ack rides back after the turnaround time (12 symbols)
            ack = Packet(ACK_SIZE - 3)
            ack.AddHeader(LrWpanMacHeader(LrWpanMacHeader.ACK, header.seq))
            ack_dur = (ack.GetSize() + PHY_OVERHEAD) * 8 / BIT_RATE
            Simulator.Schedule(
                MicroSeconds(192),
                self._channel.Send, self, ack, ack_dur, self.tx_power_dbm,
            )
            last = self._dup.get(str(header.src))
            if last == header.seq:
                return  # retransmission of a frame whose ack was lost
            self._dup[str(header.src)] = header.seq
        self.mac_rx(packet)
        self._deliver_up(packet, header.protocol, header.src, header.dst, 0)


class LrWpanHelper:
    """lr-wpan-helper.cc: shared channel + per-node device."""

    def __init__(self):
        from tpudes.models.propagation import (
            ConstantSpeedPropagationDelayModel,
            LogDistancePropagationLossModel,
        )

        self._channel = LrWpanChannel()
        self._channel.SetPropagationLossModel(
            LogDistancePropagationLossModel()
        )
        self._channel.SetPropagationDelayModel(
            ConstantSpeedPropagationDelayModel()
        )

    def SetChannel(self, channel: LrWpanChannel) -> None:
        self._channel = channel

    def GetChannel(self) -> LrWpanChannel:
        return self._channel

    def Install(self, nodes):
        from tpudes.helper.containers import NetDeviceContainer

        container = NetDeviceContainer()
        try:
            it = list(iter(nodes))
        except TypeError:
            it = [nodes]
        for node in it:
            dev = LrWpanNetDevice()
            dev.SetAddress(Mac48Address.Allocate())
            node.AddDevice(dev)
            dev.Attach(self._channel)
            container.Add(dev)
        return container
